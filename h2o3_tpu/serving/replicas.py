"""Scoring replica pool — slice-leased serving capacity off the training mesh.

ROADMAP item 2's resource-island half (the TensorFlow-serving case,
PAPERS.md: production inference wants its own devices and admission
policy, not best-effort sharing with training): a :class:`ReplicaPool`
holds N :class:`ScoringReplica`\\ s, each a dedicated thread holding one
PR 9 ``MeshScheduler.lease(small=True)`` slice lease for the replica's
lifetime — the elastic-worker pattern (parallel/elastic.py) applied to
serving. Each replica owns its own :class:`ScorerCache` and per-model
:class:`ModelBatcher` seats, so its compiled executables live on its
slice and scoring dispatches never rendezvous with a training build's
collectives on the same devices.

Routing is least-loaded (queued rows + in-flight dispatches). Admission
of a model onto a replica **speculatively pre-compiles the power-of-two
batch buckets** in the background, fed by the persistent XLA compile
cache (``H2O3TPU_COMPILE_CACHE``) — a fresh replica serves warm from its
first request instead of paying a cold trace+compile inside someone's
latency budget.

Scaling (docs/SERVING.md "SLO & replicas"): the pool scales UP when the
queue-wait EMA eats more than a quarter of the SLO budget AND the compute
observatory still shows achieved-FLOP/s headroom on the scoring loop
(PR 10's MFU gauge; unknown backends — this CPU container — read as
headroom), and scales DOWN when queue wait is negligible. Replica count
never exceeds the scheduler's slice count (an extra replica would park
forever waiting for a slice) and never drops below one. Leases release on
``stop()``/``shutdown()`` — the no-leaked-slices test pins it.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time

from h2o3_tpu.serving.scorer import MAX_BUCKET, MIN_BUCKET, ScorerCache
from h2o3_tpu.utils import lockwitness
from h2o3_tpu.utils import telemetry as _tm

#: seconds between scale decisions — the pool must not thrash a lease
#: up/down on one noisy batch
SCALE_COOLDOWN_S = 2.0


def replicas_from_env() -> int:
    """``H2O3TPU_SCORE_REPLICAS`` (resolved at call time — graftlint
    ENV001): 0/unset = no pool, the PR 6 in-process path."""
    try:
        return max(int(os.environ.get("H2O3TPU_SCORE_REPLICAS", "0") or 0), 0)
    except ValueError:
        return 0


def precompile_buckets_from_env() -> tuple[int, ...]:
    """Buckets speculatively compiled when a model lands on a replica
    (``H2O3TPU_SCORE_PRECOMPILE``, comma-separated; empty string disables).
    Default: every power of two from the min bucket to 128."""
    raw = os.environ.get("H2O3TPU_SCORE_PRECOMPILE")
    if raw is not None:
        out = []
        for tok in raw.split(","):
            tok = tok.strip()
            if not tok:
                continue
            try:
                b = int(tok)
            except ValueError:
                continue
            if MIN_BUCKET <= b <= MAX_BUCKET and (b & (b - 1)) == 0:
                out.append(b)
        return tuple(sorted(set(out)))
    out, b = [], MIN_BUCKET
    while b <= min(128, MAX_BUCKET):
        out.append(b)
        b <<= 1
    return tuple(out)


def mfu_ceiling_from_env() -> float:
    """Scoring-loop utilization above which scale-up stops adding
    replicas (``H2O3TPU_SCORE_MFU_CEILING``, default 0.6): past this the
    devices, not the batching, are the bottleneck."""
    try:
        return float(os.environ.get("H2O3TPU_SCORE_MFU_CEILING", "0.6"))
    except ValueError:
        return 0.6


class ScoringReplica:
    """One serving replica: a lifetime slice lease + its own scorer cache
    and per-model batcher seats."""

    def __init__(self, rid: int, scheduler=None, ready_timeout: float = 30.0):
        self.rid = rid
        self.label = f"r{rid}"
        self.scheduler = scheduler
        self.cache = ScorerCache()
        self.mesh = None
        self.devices: tuple = ()
        self.slice_label: str | None = None
        self._batchers: dict[str, object] = {}     # model key -> ModelBatcher
        self._lock = lockwitness.lock("serving.replicas.ScoringReplica._lock")
        self._stop = threading.Event()
        self._ready = threading.Event()
        self._lease_error: BaseException | None = None
        self.busy_seconds = 0.0
        self.dispatches = 0
        self.dispatched_rows = 0
        self.queue_wait_seconds = 0.0
        self.created_at = time.monotonic()
        self._warming = 0            # outstanding precompile threads
        self._thread = threading.Thread(target=self._hold_lease,
                                        name=f"score-replica-{rid}",
                                        daemon=True)
        self._thread.start()
        # bounded readiness wait (WTX001 shape): a lease that cannot be
        # granted inside the ceiling fails the replica instead of parking
        # the admitting caller forever (the holder thread notices _stop the
        # moment a slice finally frees and releases it right back)
        deadline = time.monotonic() + ready_timeout
        while not self._ready.wait(timeout=0.5):
            if time.monotonic() > deadline:
                self._stop.set()
                raise RuntimeError(
                    f"replica {self.label} could not acquire a slice lease "
                    f"within {ready_timeout:.0f}s — is the mesh fully "
                    "leased?")
        if self._lease_error is not None:
            raise RuntimeError(
                f"replica {self.label} lease failed: {self._lease_error!r}")

    def _hold_lease(self) -> None:
        """Dedicated thread: enter the slice lease and hold it for the
        replica's lifetime (the elastic-worker pattern — the lease context
        manager both binds and, on exit, RELEASES the slice)."""
        try:
            cm = (self.scheduler.lease(small=True, algo="scoring")
                  if self.scheduler is not None
                  else contextlib.nullcontext(None))
            with cm as lease:
                if lease is not None:
                    with self._lock:
                        self.mesh = lease.mesh
                        self.devices = tuple(lease.devices)
                        self.slice_label = lease.label
                self._ready.set()
                while not self._stop.wait(timeout=0.5):
                    pass
        except BaseException as e:   # noqa: BLE001 — surfaced to the spawner
            with self._lock:
                self._lease_error = e
        finally:
            self._ready.set()

    # -- seats ---------------------------------------------------------------

    def batcher_for(self, entry):
        """Get-or-create this replica's batcher seat for ``entry``'s
        model; the seat compiles into the REPLICA's cache and dispatches
        under the replica's mesh binding. A STOPPED entry (eviction won
        the race between admit and routing) raises ``Evicted`` — the
        seat must not be resurrected for a model the service just
        dropped (the service re-admits and retries, exactly like the
        non-pool stopped-batcher path)."""
        from h2o3_tpu.serving.batcher import Evicted, ModelBatcher
        with self._lock:
            if getattr(entry, "stopped", False):
                raise Evicted(f"model {entry.key!r} was evicted")
            b = self._batchers.get(entry.key)
            if b is None or b._entry is not entry:
                if b is not None:
                    b.stop()
                b = ModelBatcher(entry, cache=self.cache, replica=self)
                self._batchers[entry.key] = b
            return b

    def drop_model(self, key: str, model) -> None:
        with self._lock:
            b = self._batchers.pop(key, None)
        if b is not None:
            b.stop()
        self.cache.drop_model(model)

    def load(self) -> int:
        """Routing weight: queued rows across seats plus a bucket's worth
        per in-flight dispatch (a replica mid-dispatch is not free even
        with an empty queue)."""
        with self._lock:
            seats = list(self._batchers.values())
        total = 0
        for b in seats:
            with b._cond:
                total += sum(p.n for p in b._queue)
                if b._dispatching:
                    total += MIN_BUCKET
        return total

    def busy(self) -> bool:
        with self._lock:
            seats = list(self._batchers.values())
        return any(b.busy() for b in seats)

    def model_busy(self, key: str) -> bool:
        with self._lock:
            b = self._batchers.get(key)
        return b is not None and b.busy()

    def record_dispatch(self, wall_s: float, rows: int,
                        queue_wait_s: float) -> None:
        with self._lock:
            self.busy_seconds += wall_s
            self.dispatches += 1
            self.dispatched_rows += int(rows)
            self.queue_wait_seconds += max(queue_wait_s, 0.0)

    # -- speculative pre-compile ---------------------------------------------

    def precompile(self, entry, buckets=None) -> threading.Thread:
        """Compile ``entry``'s power-of-two buckets into this replica's
        cache in the background (fed by the persistent compile cache, so
        a previously-seen signature is a fast cache hit): a fresh replica
        serves warm from its first request. Returns the worker thread so
        tests/bench can join it."""
        if buckets is None:
            buckets = precompile_buckets_from_env()
        with self._lock:
            self._warming += 1       # routing de-prefers a cold replica

        def _warm():
            from h2o3_tpu.parallel.mesh import bind_mesh
            try:
                for b in buckets:
                    if self._stop.is_set() or getattr(entry, "stopped",
                                                      False):
                        return
                    try:
                        _tm.SCORE_PRECOMPILE.labels(event="scheduled").inc()
                        if self.mesh is not None:
                            with bind_mesh(self.mesh, rehome_models=False):
                                self.cache.get(entry.model, entry.schema, b)
                        else:
                            self.cache.get(entry.model, entry.schema, b)
                        _tm.SCORE_PRECOMPILE.labels(event="compiled").inc()
                    except Exception:   # noqa: BLE001 — speculative: never fatal
                        _tm.SCORE_PRECOMPILE.labels(event="failed").inc()
            finally:
                if getattr(entry, "stopped", False):
                    # an eviction raced the warm-up: a compile that was
                    # already in flight when the flag flipped must not
                    # survive drop_model (scorer bytes would leak past
                    # the byte-accounted residency)
                    self.cache.drop_model(entry.model)
                with self._lock:
                    self._warming -= 1

        t = threading.Thread(target=_warm, daemon=True,
                             name=f"score-precompile-{self.label}")
        t.start()
        return t

    def warming(self) -> bool:
        """True while speculative pre-compiles are still running — the
        router prefers warm replicas so a freshly scaled-up one doesn't
        win least-loaded (load 0) and serve its first requests cold."""
        with self._lock:
            return self._warming > 0

    # -- lifecycle -----------------------------------------------------------

    def stop(self, timeout: float = 10.0) -> None:
        """Stop seats, release the slice lease (the holder thread exits
        its ``with lease`` block), drop compiled signatures."""
        with self._lock:
            seats = list(self._batchers.values())
            self._batchers.clear()
        for b in seats:
            b.stop()
        self._stop.set()
        self._thread.join(timeout=timeout)
        self.cache.clear()   # graftlint: ok(ScorerCache.clear is internally locked; replica is already stopped here)

    def snapshot(self) -> dict:
        with self._lock:
            models = sorted(self._batchers)
            return {"replica": self.label,
                    "slice": self.slice_label,
                    "devices": list(self.devices),
                    "models": models,
                    "load_rows": None,    # filled by the pool (needs locks)
                    "busy_seconds": round(self.busy_seconds, 6),
                    "dispatches": self.dispatches,
                    "rows": self.dispatched_rows,
                    "queue_wait_seconds": round(self.queue_wait_seconds, 6),
                    "cache": self.cache.stats()}


class ReplicaPool:
    """N slice-leased replicas + least-loaded routing + the scale policy."""

    def __init__(self, n: int, scheduler=None, max_replicas: int | None = None):
        n = max(int(n), 1)
        self.scheduler = scheduler
        cap = max_replicas
        if cap is None:
            cap = n
        if scheduler is not None and getattr(scheduler, "n", 1) > 1:
            # an (n+1)th replica would park forever waiting for a slice
            cap = min(max(cap, n), scheduler.n)
            n = min(n, scheduler.n)
        self.min_replicas = 1
        self.max_replicas = max(cap, 1)
        self._lock = lockwitness.lock("serving.replicas.ReplicaPool._lock")
        self._next_rid = 0
        self._shutdown = False
        self._replicas: list[ScoringReplica] = []
        self._wait_ema_s: float | None = None
        self._last_scale = 0.0
        self.scale_ups = 0
        self.scale_downs = 0
        try:
            with self._lock:       # honor _spawn_locked's contract even
                for _ in range(n):  # though the pool is still unpublished
                    self._spawn_locked()
        except BaseException:
            # a half-built pool must not leak the leases it DID acquire
            for rep in self._replicas:
                rep.stop()
            self._replicas.clear()
            raise
        self._export()

    # -- membership ----------------------------------------------------------

    def _spawn_locked(self, ready_timeout: float = 30.0) -> ScoringReplica:
        rid, self._next_rid = self._next_rid, self._next_rid + 1   # graftlint: ok(caller holds self._lock — _locked suffix contract)
        rep = ScoringReplica(rid, scheduler=self.scheduler,
                             ready_timeout=ready_timeout)
        self._replicas.append(rep)   # graftlint: ok(caller holds self._lock — _locked suffix contract)
        return rep

    @property
    def replicas(self) -> list[ScoringReplica]:
        with self._lock:
            return list(self._replicas)

    def route(self) -> ScoringReplica:
        """Least-loaded replica among the WARM ones (a replica whose
        speculative pre-compiles are still running only serves when every
        replica is warming); ties break to the oldest — caches warmest."""
        reps = self.replicas
        if not reps:
            raise RuntimeError("replica pool is empty (shut down?)")
        return min(reps, key=lambda r: (r.warming(), r.load(), r.rid))

    # -- scale policy --------------------------------------------------------

    def observe_wait(self, wait_s: float) -> None:
        """Fold one request's queue wait (enqueue -> dispatch start) into
        the scale signal's EMA."""
        with self._lock:
            if self._wait_ema_s is None:
                self._wait_ema_s = float(wait_s)
            else:
                self._wait_ema_s += 0.2 * (wait_s - self._wait_ema_s)

    @property
    def wait_ema_s(self) -> float | None:
        with self._lock:
            return self._wait_ema_s

    def mfu_headroom(self) -> bool:
        """True while the compute observatory shows the scoring loop
        under the MFU ceiling — scale-up must track achieved-FLOP/s
        headroom (PR 10), not just QPS. Unknown backends (utilization
        null) read as headroom: there is no roofline to be against."""
        from h2o3_tpu.utils.costs import COSTS
        util = (COSTS.snapshot().get("loops", {})
                .get("scoring", {}).get("utilization"))
        return util is None or util < mfu_ceiling_from_env()

    def maybe_scale(self, slo_ms: float | None,
                    resident_entries=()) -> str | None:
        """One scale decision: up when queue wait eats >25% of the SLO
        budget (and MFU headroom remains), down when it reads <2%.
        Returns "up"/"down"/None; cooldown-limited. The decision runs
        under the pool lock, the ACTION does not: a scale-up's lease wait
        (bounded 5s) and a scale-down's thread join must never block
        ``route()`` — only the one triggering request pays."""
        if slo_ms is None or slo_ms <= 0:
            return None
        budget_s = float(slo_ms) / 1e3
        victim = None
        rid = None
        with self._lock:
            ema = self._wait_ema_s
            now = time.monotonic()
            if ema is None or now - self._last_scale < SCALE_COOLDOWN_S:
                return None
            n = len(self._replicas)
            if ema > 0.25 * budget_s and n < self.max_replicas:
                if not self.mfu_headroom():
                    return None
                # reserve the decision (cooldown + rid) and spawn OUTSIDE
                self._last_scale = now
                self._wait_ema_s = None     # fresh signal for the new shape
                rid, self._next_rid = self._next_rid, self._next_rid + 1
            elif ema < 0.02 * budget_s and n > self.min_replicas:
                # retire the least-loaded idle replica
                victims = sorted(self._replicas,
                                 key=lambda r: (r.load(), -r.rid))
                victim = victims[0]
                if victim.busy():
                    return None
                self._replicas.remove(victim)
                self._last_scale = now
                self.scale_downs += 1
                self._wait_ema_s = None
            else:
                return None
        if rid is not None:
            try:
                # short lease ceiling: a layout contended by another run
                # (the lease state is process-wide per layout) must abort
                # the scale, not stall this request 30s or surface a 500
                rep = ScoringReplica(rid, scheduler=self.scheduler,
                                     ready_timeout=5.0)
            except RuntimeError:
                return None
            for entry in resident_entries:
                rep.precompile(entry)       # route() defers to warm peers
            with self._lock:
                if self._shutdown:
                    dead = True             # reset()/shutdown won the race
                else:
                    dead = False
                    self._replicas.append(rep)
                    self.scale_ups += 1
            if dead:
                # appending to a dead pool would leak the slice lease +
                # thread forever — the no-leaked-slices contract
                rep.stop()
                return None
            _tm.SCORE_SCALE_EVENTS.labels(direction="up").inc()
            self._export()
            return "up"
        victim.stop()
        _tm.SCORE_SCALE_EVENTS.labels(direction="down").inc()
        self._export()
        return "down"

    def _export(self) -> None:
        _tm.SCORE_REPLICAS.set(len(self.replicas))

    # -- fan-out helpers (service eviction paths) ----------------------------

    def drop_model(self, key: str, model) -> None:
        for rep in self.replicas:
            rep.drop_model(key, model)

    def model_busy(self, key: str) -> bool:
        return any(rep.model_busy(key) for rep in self.replicas)

    def any_busy(self) -> bool:
        return any(rep.busy() for rep in self.replicas)

    # -- lifecycle / introspection -------------------------------------------

    def shutdown(self) -> None:
        with self._lock:
            self._shutdown = True       # a racing scale-up stops its replica
            reps, self._replicas = self._replicas, []
        for rep in reps:
            rep.stop()
        self._export()

    def snapshot(self) -> dict:
        reps = self.replicas
        rows = []
        for r in reps:
            snap = r.snapshot()
            snap["load_rows"] = r.load()
            rows.append(snap)
        ema = self.wait_ema_s
        return {"count": len(reps),
                "min": self.min_replicas, "max": self.max_replicas,
                "queue_wait_ema_ms": (round(ema * 1e3, 3)
                                      if ema is not None else None),
                "scale_ups": self.scale_ups,
                "scale_downs": self.scale_downs,
                "mfu_headroom": self.mfu_headroom(),
                "replicas": rows}
