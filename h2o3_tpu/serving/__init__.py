"""Serving tier — compiled, batched, multi-model scoring (docs/SERVING.md).

The production scoring path (ROADMAP item 2): ``/3/Score/{model}`` takes
row payloads (no DKV frame round-trip), the micro-batcher fuses concurrent
requests into one device dispatch, the ScorerCache keeps one compiled
executable per (model, signature, batch-bucket), and multi-model residency
is byte-accounted with LRU eviction under a budget.
"""

from h2o3_tpu.serving.batcher import ModelBatcher
from h2o3_tpu.serving.replicas import ReplicaPool, ScoringReplica
from h2o3_tpu.serving.schema import NotServable, ServingSchema, serving_schema
from h2o3_tpu.serving.scorer import CompiledScorer, ScorerCache, bucket_for
from h2o3_tpu.serving.service import SCORING, ScoringService, ServiceUnavailable
from h2o3_tpu.serving.slo import Shed, SLOController, clamp_priority

__all__ = ["SCORING", "ScoringService", "ServiceUnavailable", "ScorerCache",
           "CompiledScorer", "ModelBatcher", "ServingSchema", "NotServable",
           "serving_schema", "bucket_for", "SLOController", "Shed",
           "clamp_priority", "ReplicaPool", "ScoringReplica"]
