"""ScorerCache — one compiled executable per (model, signature, bucket).

Reference template (PAPERS.md, the TensorFlow-serving design): compile a
model's inference program once per input signature and keep the warm
executable; arbitrary request sizes land in padded power-of-two batch
buckets so the steady state never recompiles. The scorer body is the
model's existing :meth:`Model._score_raw` — the same jitted batch program
training-side scoring uses — traced over a frame REBUILT from raw request
columns (:meth:`ServingSchema.build_frame`), so the serving path cannot
drift from ``model.predict``.

Signatures are ``(model identity, n_num, n_cat, dtype, bucket)``. A hit
returns the warm executable (counted — the bench and tests assert the
second same-shape request compiles nothing); a miss traces + compiles
eagerly via ``jit(...).lower(...).compile()`` so compile cost is paid at
miss time, never mid-batch. Models whose ``_score_raw`` cannot trace
(host-side branches on data) fall back to an eager scorer — still batched,
still correct, just not fused into one executable.
"""

from __future__ import annotations

import os
import time

import jax
import numpy as np

from h2o3_tpu.serving.schema import ServingSchema
from h2o3_tpu.utils import lockwitness
from h2o3_tpu.utils import telemetry as _tm
from h2o3_tpu.utils.costs import COSTS, cost_of

#: requests larger than the max bucket are scored in max-bucket slices
MAX_BUCKET = int(os.environ.get("H2O3TPU_SCORE_MAX_BUCKET", "4096"))

#: smallest bucket — tiny interactive requests share one executable
MIN_BUCKET = 8


def bucket_for(n: int) -> int:
    """Smallest power-of-two bucket holding ``n`` rows (clamped to
    [MIN_BUCKET, MAX_BUCKET])."""
    b = MIN_BUCKET
    while b < n and b < MAX_BUCKET:
        b <<= 1
    return b


class CompiledScorer:
    """One signature's executable: ``score(num, cat)`` over padded host
    arrays returns host predictions ([bucket] or [bucket, K])."""

    __slots__ = ("bucket", "mode", "_fn", "site", "_ncalls", "_flops",
                 "_bytes")

    def __init__(self, model, schema: ServingSchema, bucket: int):
        self.bucket = bucket
        self._ncalls = 0
        self._flops = self._bytes = None

        def raw_fn(num, cat):
            frame = schema.build_frame(num, cat, bucket)
            return model._score_raw(frame)

        num_spec = jax.ShapeDtypeStruct((bucket, len(schema.num_cols)),
                                        np.float32)
        cat_spec = jax.ShapeDtypeStruct((bucket, len(schema.cat_cols)),
                                        np.int32)
        # compile under the cost-observatory site scope: serving compile
        # time / FLOPs / recompile events show in /3/Compute next to the
        # training loops, and compile-cache hits credit the scoring tier.
        # H2O3TPU_COSTS_OFF=1 keeps the full-bypass contract: the scorer
        # still compiles, but nothing is recorded (utils/costs.py).
        from h2o3_tpu.utils.costs import enabled as _costs_on
        site = self.site = f"score:{getattr(model, 'algo', 'model')}"
        try:
            with COSTS.scope(site):
                t0 = time.perf_counter()
                self._fn = jax.jit(raw_fn).lower(num_spec, cat_spec).compile()
                dt = time.perf_counter() - t0
            self.mode = "compiled"
            flops, nbytes = self._flops, self._bytes = cost_of(self._fn)
            if _costs_on():
                COSTS.record_compile(
                    site,
                    {"args": [{"shape": list(num_spec.shape),
                               "dtype": "float32"},
                              {"shape": list(cat_spec.shape),
                               "dtype": "int32"}],
                     "statics": {"model": str(getattr(model, "key", None)),
                                 "bucket": str(bucket)}},
                    dt, flops, nbytes, loop="scoring")
        except Exception:   # noqa: BLE001 — host-side branches in _score_raw
            self._fn = raw_fn
            self.mode = "eager"
            if _costs_on():
                COSTS.record_eager_fallback(site, loop="scoring")

    def score(self, num: np.ndarray, cat: np.ndarray) -> np.ndarray:
        # the device_get below is already a sync, so timing a sampled call
        # costs nothing extra — achieved FLOP/s of the scoring loop rides
        # into /3/Compute next to the training loops
        from h2o3_tpu.utils import costs as _costs
        n, self._ncalls = self._ncalls, self._ncalls + 1
        sampled = (self.mode == "compiled" and _costs.enabled()
                   and n % _costs.sample_every() == 0)
        t0 = time.perf_counter() if sampled else 0.0
        out = np.asarray(jax.device_get(self._fn(num, cat)))
        if sampled:
            # this executable's OWN cost, not the site's latest — several
            # buckets/models share the score:<algo> site
            COSTS.observe(self.site, time.perf_counter() - t0,
                          flops=self._flops, nbytes=self._bytes)
        return out


class ScorerCache:
    """Thread-safe signature → :class:`CompiledScorer` cache with LRU-able
    per-model grouping (evicting a model drops all its signatures)."""

    def __init__(self):
        self._lock = lockwitness.lock("serving.scorer.ScorerCache._lock")
        # (model_token, n_num, n_cat, dtype, bucket) -> CompiledScorer
        self._entries: dict[tuple, CompiledScorer] = {}
        self.hits = 0
        self.misses = 0
        self._pinned_bucket: int | None = None

    # -- bucket pinning (ops-plane recompile-storm remediation) --------------

    def pin_bucket(self, bucket: int) -> int:
        """Pin a floor bucket: requests whose natural bucket is SMALLER
        score in the pinned one instead, collapsing a storm of churning
        small signatures onto one warm executable (padding waste bounded
        by the pin). Returns the clamped pin actually installed."""
        b = MIN_BUCKET
        while b < bucket and b < MAX_BUCKET:
            b <<= 1
        with self._lock:
            self._pinned_bucket = b
        return b

    def unpin_bucket(self) -> None:
        with self._lock:
            self._pinned_bucket = None

    def pinned_bucket(self) -> "int | None":
        with self._lock:
            return self._pinned_bucket

    def bucket_for(self, n: int) -> int:
        """Bucket selection honoring the pin — the batcher's sole seam
        (module-level :func:`bucket_for` stays the pure natural law)."""
        natural = bucket_for(n)
        with self._lock:
            pin = self._pinned_bucket
        return pin if pin is not None and pin > natural else natural

    def compiled_buckets(self) -> "list[int]":
        """Distinct buckets with a compiled signature — what the ops-plane
        recompile-storm action may pin to."""
        with self._lock:
            return sorted({sig[5] for sig in self._entries})

    @staticmethod
    def _signature(model, schema: ServingSchema, bucket: int) -> tuple:
        # id(model) versions the cache: a reloaded model under the same DKV
        # key is a new object and must recompile against its new arrays
        return (getattr(model, "key", None), id(model),
                len(schema.num_cols), len(schema.cat_cols), "f32i32", bucket)

    def get(self, model, schema: ServingSchema, bucket: int) -> CompiledScorer:
        sig = self._signature(model, schema, bucket)
        with self._lock:
            entry = self._entries.get(sig)
            if entry is not None:
                self.hits += 1
                _tm.SCORER_CACHE.labels(event="hit").inc()
                return entry
        # compile OUTSIDE the cache lock: a cold signature must not stall
        # warm-signature scorers for the seconds a trace+compile takes
        entry = CompiledScorer(model, schema, bucket)
        with self._lock:
            won = self._entries.setdefault(sig, entry)
            self.misses += 1
            _tm.SCORER_CACHE.labels(event="miss").inc()
        return won

    def drop_model(self, model) -> int:
        """Evict every signature of ``model``; returns how many dropped."""
        token = (getattr(model, "key", None), id(model))
        with self._lock:
            victims = [s for s in self._entries if s[:2] == token]
            for s in victims:
                del self._entries[s]
            if victims:
                _tm.SCORER_CACHE.labels(event="evict").inc(len(victims))
            return len(victims)

    def stats(self) -> dict:
        with self._lock:
            return {"signatures": len(self._entries),
                    "hits": self.hits, "misses": self.misses,
                    "pinned_bucket": self._pinned_bucket}

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self.hits = self.misses = 0
