"""ScoringService — multi-model residency with byte-accounted admission.

Reference composite (PAPERS.md): TensorFlow-serving's compile-once/serve-
many lifecycle plus Clipper-style multi-model residency — many models
share the device, cold ones are evicted by bytes, and an over-budget
request degrades to a retryable error instead of an OOM.

A model scored through ``/3/Score`` becomes *resident*: its serving schema
is derived once, each of its batcher seats owns a request queue, and its
compiled signatures accumulate in a :class:`ScorerCache`. Residency is
byte-accounted with the same measure ``/3/Memory`` reports per DKV key
(``value_kind_bytes`` — the PR-5 MemoryMeter's artifact-size walk):
admission of a cold model under a configured budget
(``H2O3TPU_SERVE_BUDGET_BYTES``) LRU-evicts idle resident models first,
and when nothing evictable remains the request gets
:class:`ServiceUnavailable` — the REST layer maps it to
``503 + Retry-After`` rather than letting the device OOM. Models with
in-flight batches are never evicted. Eviction drops the scorer-cache
signatures and the worker thread(s); the DKV copy is untouched (that *is*
the cold tier — the next request re-admits it).

SLO layer (docs/SERVING.md "SLO & replicas"): every resident model owns
an :class:`~h2o3_tpu.serving.slo.SLOController` — with a target set
(``H2O3TPU_SCORE_SLO_MS`` or per-request ``slo_ms``) the collect window
adapts and overloaded admissions shed by priority with
``503 + Retry-After`` (``h2o3_score_shed_total{reason,priority}``); with
no target the tier is bit-identical to the PR 6 fixed-window path. With
``H2O3TPU_SCORE_REPLICAS`` > 0 (or :meth:`configure_replicas`) requests
route least-loaded across a :class:`~h2o3_tpu.serving.replicas.
ReplicaPool` of slice-leased replicas instead of one in-process seat.
"""

from __future__ import annotations

import os
import time

from h2o3_tpu.serving.batcher import Evicted, ModelBatcher
from h2o3_tpu.serving.schema import NotServable, serving_schema
from h2o3_tpu.serving.scorer import ScorerCache
from h2o3_tpu.serving.slo import SLOController, Shed, clamp_priority
from h2o3_tpu.utils import lockwitness
from h2o3_tpu.utils import telemetry as _tm
from h2o3_tpu.utils.memory import MEMORY, value_kind_bytes
from h2o3_tpu.utils.registry import DKV

#: requests between opportunistic replica scale checks (cheap, but not
#: free — snapshot reads under locks)
_SCALE_CHECK_EVERY = 32


def _tenancy_mod():
    """The ops-plane tenancy module ONLY if something already imported it
    — the scoring hot path must not be what pulls in multi-tenancy."""
    import sys
    return sys.modules.get("h2o3_tpu.ops_plane.tenancy")


class ServiceUnavailable(RuntimeError):
    """Admission refused under the residency budget (HTTP 503 + retry)."""

    def __init__(self, msg: str, retry_after_ms: int = 1000):
        super().__init__(msg)
        self.retry_after_ms = retry_after_ms


class _Resident:
    """One resident model: schema + SLO controller + batcher seat(s) +
    byte accounting. With a replica pool the seats live on the replicas
    (one per replica that served this model); without one, ``batcher`` is
    the single in-process seat — exactly the PR 6 layout."""

    __slots__ = ("key", "model", "schema", "cache", "batcher", "nbytes",
                 "last_used", "requests", "slo", "pool", "stopped")

    def __init__(self, key: str, model, schema, cache: ScorerCache,
                 nbytes: int, pool=None):
        self.key = key
        self.model = model
        self.schema = schema
        self.cache = cache
        self.nbytes = nbytes     # computed once by the admitting caller
        self.last_used = time.monotonic()
        self.requests = 0
        self.slo = SLOController()
        self.pool = pool
        self.stopped = False     # set by eviction; replica seats check it
        self.batcher = ModelBatcher(self) if pool is None else None

    def submit(self, num, cat, n: int, priority: int):
        """Route to the least-loaded replica seat (pool) or the local
        batcher; returns ``(pending, replica_label)``."""
        pool = self.pool
        if pool is None:
            return self.batcher.submit(num, cat, n, priority=priority), None
        rep = pool.route()
        return rep.batcher_for(self).submit(num, cat, n,
                                            priority=priority), rep.label

    def busy(self) -> bool:
        if self.pool is None:
            return self.batcher.busy()
        return self.pool.model_busy(self.key)

    def stop(self) -> None:
        # the flag FIRST: a score() racing this eviction between _admit
        # and submit must find a dead entry (batcher_for refuses to
        # resurrect a seat for it), not re-create what we just dropped
        self.stopped = True
        if self.batcher is not None:
            self.batcher.stop()
        if self.pool is not None:
            self.pool.drop_model(self.key, self.model)


class ScoringService:
    """Process-wide scoring tier (singleton :data:`SCORING`)."""

    def __init__(self, budget_bytes: int | None = None):
        env = os.environ.get("H2O3TPU_SERVE_BUDGET_BYTES")
        #: residency budget in artifact bytes; None = unlimited (no eviction)
        self.budget_bytes = budget_bytes if budget_bytes is not None else (
            int(env) if env else None)
        self._lock = lockwitness.rlock("serving.service.ScoringService._lock")
        self._resident: dict[str, _Resident] = {}
        self.cache = ScorerCache()
        self.evictions = 0
        #: replica pool — created lazily on first admission (constructing
        #: it eagerly would touch jax devices at module import) or
        #: explicitly via :meth:`configure_replicas`
        self.pool = None
        self._pool_checked = False
        self._shed: dict[tuple, int] = {}      # (reason, priority) -> count
        self._admission_base: dict[str, float] = {}  # key -> original slo_ms

    # -- replica pool ---------------------------------------------------------

    def configure_replicas(self, n: int, scheduler=None) -> None:
        """Install a replica pool of ``n`` slice-leased replicas (``0``
        tears any pool down). ``scheduler`` defaults to a fresh
        ``MeshScheduler(slices=n)`` so each replica leases a disjoint
        slice when the device count allows. Pool CONSTRUCTION (lease
        waits, up to 30s per replica on a contended layout) runs OUTSIDE
        the service lock — warm-path scorers of other models must not
        stall behind it."""
        from h2o3_tpu.serving.replicas import ReplicaPool
        new_pool = None
        if n and int(n) > 0:
            if scheduler is None:
                from h2o3_tpu.orchestration.scheduler import MeshScheduler
                scheduler = MeshScheduler(slices=int(n))
            new_pool = ReplicaPool(int(n), scheduler=scheduler)
        with self._lock:
            old, self.pool = self.pool, new_pool
            self._pool_checked = True
            # existing residents re-point at the NEW pool — or, on
            # teardown (n=0), back at a local seat: an entry left holding
            # the shut-down pool would 500 on every request
            for entry in self._resident.values():
                if new_pool is None:
                    # the local seat must exist BEFORE pool goes None: a
                    # concurrent submit() reads pool first, batcher second
                    # — the reverse order would hand it a None batcher
                    if entry.batcher is None or entry.batcher._stopped:
                        entry.batcher = ModelBatcher(entry)
                    entry.pool = None
                else:
                    entry.pool = new_pool
                    if entry.batcher is not None:
                        entry.batcher.stop()    # seats live on replicas now
                        entry.batcher = None
                    for rep in new_pool.replicas:
                        rep.precompile(entry)
        if old is not None:
            old.shutdown()

    def _ensure_pool(self):
        """Resolve ``H2O3TPU_SCORE_REPLICAS`` once per service lifetime
        (reset() re-arms it) — lazily, so importing the serving package
        never constructs meshes. A construction failure (the slice
        layout contended by other runs past the lease ceiling) re-arms
        the check and surfaces RETRYABLE 503, never a 500."""
        with self._lock:
            if self._pool_checked:
                return self.pool
            self._pool_checked = True
        from h2o3_tpu.serving.replicas import replicas_from_env
        n = replicas_from_env()
        if n > 0:
            try:
                self.configure_replicas(n)
            except RuntimeError as e:
                with self._lock:
                    self._pool_checked = False   # next admission retries
                raise ServiceUnavailable(
                    f"scoring replica pool unavailable: {e}") from None
        return self.pool

    # -- admission widening (ops-plane overload relief) ----------------------

    def widen_admission(self, factor: float = 1.5,
                        cap: float = 4.0) -> "list[dict]":
        """Overload relief without a replica: raise every resident model's
        SLO admission target by ``factor`` so the shed estimator admits a
        deeper queue. Cumulative widening is bounded at ``cap``× each
        model's ORIGINAL target (recorded on first widen). Models with no
        target are untouched. Returns ``[{model, target_ms}]`` for the
        audit record; :meth:`restore_admission` is the rollback."""
        with self._lock:
            entries = list(self._resident.values())
            plan = []
            for e in entries:
                target = e.slo.slo_ms
                if not target:
                    continue
                base = self._admission_base.setdefault(e.key, target)
                new_target = min(target * factor, base * cap)
                if new_target > target:
                    plan.append((e, new_target))
        changed = []
        for e, new_target in plan:
            # set_target outside the service lock (slo has its own lock;
            # keep the order service→slo one-way and brief)
            e.slo.set_target(new_target)
            changed.append({"model": e.key,
                            "target_ms": round(new_target, 3)})
        return changed

    def restore_admission(self) -> "list[dict]":
        """Undo :meth:`widen_admission`: every widened resident returns to
        its recorded original target."""
        with self._lock:
            base = dict(self._admission_base)
            self._admission_base.clear()
            entries = {e.key: e for e in self._resident.values()}
        restored = []
        for key, orig in base.items():
            e = entries.get(key)
            if e is not None:
                e.slo.set_target(orig)
                restored.append({"model": key, "target_ms": orig})
        return restored

    # -- scoring -------------------------------------------------------------

    def score(self, model_key: str, rows, columns=None, priority=None,
              slo_ms=None) -> dict:
        """Score JSON ``rows`` against ``model_key`` through the batched
        path; returns the ``/3/Score`` payload dict. ``priority`` (0-9,
        default 5) orders shedding under overload; ``slo_ms`` overrides
        the model's latency target at admit."""
        t0 = time.perf_counter()
        if not isinstance(rows, (list, tuple)) or not rows:
            # reject before admission: an invalid request must not be able
            # to churn residency (evicting warm models under a budget) for
            # rows that could never score
            raise ValueError("rows must be a non-empty JSON array")
        pr = clamp_priority(priority)
        try:
            entry = self._admit(model_key)
        except Exception:
            # admission failures (404 / unservable / over budget) must move
            # the error counter too, or a failing tier reads healthy; the
            # algo is unknown before admission — one bounded label value
            _tm.SCORE_REQUESTS.labels(algo="unknown", status="error").inc()
            raise
        if slo_ms is not None:
            entry.slo.set_target(slo_ms)
        algo = getattr(entry.model, "algo", "model")
        replica = None
        try:
            # an eviction can race the window between _admit releasing the
            # service lock and submit() enqueueing (budgeted admit of
            # another model, or a key re-put): transient — re-admit once
            # rather than surfacing a server error
            for attempt in (0, 1):
                num, cat = entry.schema.adapt_rows(rows, columns)
                try:
                    pending, replica = entry.submit(num, cat, len(rows), pr)
                    break
                except Shed as e:
                    # the admission estimator turned this request away
                    # before it entered the queue: accounted, retryable
                    self._count_shed(e.reason, pr)
                    raise ServiceUnavailable(
                        str(e), retry_after_ms=e.retry_after_ms) from None
                except TimeoutError as e:
                    # a queue that never drained within the wait ceiling is
                    # a load condition: retryable 503, not a server fault
                    self._count_shed("timeout", pr)
                    raise ServiceUnavailable(str(e)) from None
                except Evicted:
                    if attempt:
                        self._count_shed("evicted", pr)
                        raise ServiceUnavailable(
                            f"{model_key!r} keeps losing residency under "
                            "the budget; retry shortly")
                    with self._lock:
                        # a stopped batcher can never serve again: drop the
                        # entry if it somehow remained resident, so the
                        # re-admit below builds a fresh one
                        if self._resident.get(model_key) is entry:
                            self._evict_locked(entry)
                    entry = self._admit(model_key)
            out = _finalize(entry.model, pending.result, len(rows))
        except Exception:
            _tm.SCORE_REQUESTS.labels(algo=algo, status="error").inc()
            raise
        latency = time.perf_counter() - t0
        entry.slo.record_latency(latency)
        # per MODEL: two resident models of one algo have independent
        # controllers; an algo label would flap between their windows
        # (residency is capped by the serve-budget LRU, so the label set
        # is bounded by max resident models, not by DKV contents)
        _tm.SCORE_WINDOW_MS.labels(model=model_key).set(  # graftlint: ok(label residency bounded by serve-budget LRU)
            entry.slo.current_window_s() * 1e3)
        if pending.queue_wait_s is not None:
            _tm.SCORE_QUEUE_WAIT.observe(pending.queue_wait_s)
            if entry.pool is not None:
                entry.pool.observe_wait(pending.queue_wait_s)
        if entry.pool is not None and entry.requests % _SCALE_CHECK_EVERY == 0:
            with self._lock:
                residents = list(self._resident.values())
            entry.pool.maybe_scale(entry.slo.slo_ms,
                                   resident_entries=residents)
        out.update(model=model_key, rows=len(rows),
                   batch_rows=pending.batch_rows,
                   batch_requests=pending.batch_requests,
                   priority=pr)
        if replica is not None:
            out["replica"] = replica
        _tm.SCORE_REQUESTS.labels(algo=algo, status="ok").inc()
        _tm.SCORE_SECONDS.labels(algo=algo).observe(latency)
        ten = _tenancy_mod()
        if ten is not None:
            # per-tenant device-seconds: this request's pro-rata share of
            # its batch's device wall (queue wait excluded — waiting burns
            # no device). Zero overhead unless ops_plane is loaded.
            busy = latency
            if pending.queue_wait_s is not None:
                busy = max(latency - pending.queue_wait_s, 0.0)
            share = busy * (len(rows) / max(pending.batch_rows or 0, len(rows)))
            ten.QUOTAS.charge_device_seconds(ten.current_tenant(), share)
        return out

    def _count_shed(self, reason: str, priority: int) -> None:
        _tm.SCORE_SHED.labels(reason=reason, priority=str(priority)).inc()
        with self._lock:
            k = (reason, priority)
            self._shed[k] = self._shed.get(k, 0) + 1

    # -- residency / admission ----------------------------------------------

    def _admit(self, model_key: str) -> _Resident:
        self._ensure_pool()
        # DKV.get can fault a spilled model in from disk — a full snapshot
        # load plus device transfer — so it must run BEFORE the service
        # lock, or every warm-path scorer of every other model stalls
        # behind one cold fault-in (DLK002)
        current = DKV.get(model_key)
        with self._lock:
            entry = self._resident.get(model_key)
            if entry is not None and entry.model is current:
                entry.last_used = time.monotonic()
                entry.requests += 1
                return entry
        # cold path: the heavy work — artifact byte walk + schema/level-map
        # derivation — runs OUTSIDE the service lock so warm-path scorers
        # of other models never stall behind an admission (same reason
        # ScorerCache compiles outside its lock); re-checked under the lock
        # below since a concurrent admit may have won
        model = current
        if model is None:
            model = DKV[model_key]     # KeyError → 404 upstream
        if not hasattr(model, "_score_raw"):
            raise NotServable(f"{model_key!r} is not a scorable model")
        incoming = value_kind_bytes(model)[1]
        schema = serving_schema(model)
        with self._lock:
            entry = self._resident.get(model_key)
            if entry is not None and entry.model is model:
                entry.last_used = time.monotonic()
                entry.requests += 1
                return entry           # concurrent admit won the race
            if entry is not None:      # key re-put: stale resident copy
                self._evict_locked(entry)
            self._make_room_locked(incoming, model_key)
            # pool re-read UNDER the lock: an admission that lost the
            # _ensure_pool race must not pin its model to a local seat
            # (global-mesh dispatches, the contention the pool removes)
            # for the resident's whole lifetime
            pool = self.pool
            entry = _Resident(model_key, model, schema, self.cache, incoming,
                              pool=pool)
            self._resident[model_key] = entry
            entry.requests += 1
            self._export_locked()
        if pool is not None:
            # speculative bucket pre-compile at admission: every replica
            # warms the power-of-two buckets in the background (fed by the
            # persistent compile cache), so wherever routing lands this
            # model next, the executable is already there
            for rep in pool.replicas:
                rep.precompile(entry)
        return entry

    def _make_room_locked(self, incoming: int, for_key: str) -> None:
        if self.budget_bytes is None:
            return
        if incoming > self.budget_bytes:
            # no amount of eviction can ever fit it: a terminal client
            # error, not a 503 a well-behaved retrier would loop on forever
            raise NotServable(
                f"{for_key!r} needs {incoming} artifact bytes but the "
                f"residency budget is {self.budget_bytes}; raise "
                "H2O3TPU_SERVE_BUDGET_BYTES to serve this model")
        def resident_bytes():   # noqa: E306
            return sum(e.nbytes for e in self._resident.values())
        if resident_bytes() + incoming <= self.budget_bytes:
            return
        # LRU eviction of IDLE models only: a model with queued requests or
        # a batch on the device is hot by definition. Feasibility first —
        # evicting warm signatures for a request that 503s anyway would
        # make an infeasible admission also destroy working residents.
        victims = [v for v in sorted(self._resident.values(),
                                     key=lambda e: e.last_used)
                   if v.key != for_key and not v.busy()]
        evictable = sum(v.nbytes for v in victims)
        if resident_bytes() - evictable + incoming > self.budget_bytes:
            raise ServiceUnavailable(
                f"scoring tier over budget: {incoming} artifact bytes for "
                f"{for_key!r} do not fit in "
                f"{self.budget_bytes} with {len(self._resident)} resident "
                "model(s) busy; retry shortly")
        for v in victims:
            self._evict_locked(v)
            if resident_bytes() + incoming <= self.budget_bytes:
                return

    def _evict_locked(self, entry: _Resident) -> None:
        self._resident.pop(entry.key, None)       # graftlint: ok(caller holds self._lock — _locked suffix contract)
        entry.stop()
        self.cache.drop_model(entry.model)
        self.evictions += 1                        # graftlint: ok(caller holds self._lock — _locked suffix contract)
        self._export_locked()

    def _export_locked(self) -> None:
        _tm.SCORE_RESIDENT_MODELS.set(len(self._resident))
        _tm.SCORE_RESIDENT_BYTES.set(
            sum(e.nbytes for e in self._resident.values()))

    def evict(self, model_key: str) -> bool:
        """Explicit eviction (REST DELETE + tests)."""
        with self._lock:
            entry = self._resident.get(model_key)
            if entry is None:
                return False
            if entry.busy():
                raise ServiceUnavailable(
                    f"{model_key!r} has in-flight batches; retry")
            self._evict_locked(entry)
            return True

    # -- introspection -------------------------------------------------------

    def stats(self) -> dict:
        """The ``GET /3/Score`` payload: residency + cache counters + the
        SLO/shed/replica view; the device/host watermarks ride along so
        admission decisions can be read against the same numbers
        ``/3/Memory`` serves."""
        with self._lock:
            resident = [{"model": e.key,
                         "algo": getattr(e.model, "algo", "model"),
                         "bytes": e.nbytes, "requests": e.requests,
                         "idle_secs": round(time.monotonic() - e.last_used, 3),
                         "slo": e.slo.snapshot()}
                        for e in sorted(self._resident.values(),
                                        key=lambda e: -e.last_used)]
            budget = self.budget_bytes
            evictions = self.evictions
            shed = [{"reason": r, "priority": p, "count": c}
                    for (r, p), c in sorted(self._shed.items())]
            pool = self.pool
        return {"resident": resident,
                "resident_bytes": sum(r["bytes"] for r in resident),
                "budget_bytes": budget, "evictions": evictions,
                "cache": self.cache.stats(),
                "shed": shed,
                "shed_total": sum(s["count"] for s in shed),
                "replicas": pool.snapshot() if pool is not None else None,
                "watermarks": MEMORY.watermarks}

    def reset(self) -> None:
        """Evict everything and zero counters (tests + shutdown). The
        cache clears wholesale — no per-model drops, which would inflate
        the ``evict`` telemetry counter with non-budget evictions. The
        replica pool shuts down (leases released) and the env knob is
        re-armed for the next admission."""
        with self._lock:
            for entry in list(self._resident.values()):
                entry.stop()
            self._resident.clear()
            self.cache.clear()
            self.evictions = 0
            self._shed.clear()
            self._admission_base.clear()
            pool, self.pool = self.pool, None
            self._pool_checked = False
            self._export_locked()
        if pool is not None:
            pool.shutdown()


def _finalize(model, raw, n: int) -> dict:
    """Raw device predictions → the response payload, mirroring
    :meth:`Model.predict` exactly (labels via the resettable binomial
    threshold / argmax, ``p{level}`` probability columns) so batched REST
    results are bit-identical to the frame path."""
    import numpy as np

    from h2o3_tpu.models.model_base import decision_labels
    raw = np.asarray(raw)[:n]
    nclasses = getattr(model, "nclasses", 0)
    if not nclasses or nclasses < 2 or raw.ndim != 2:
        if raw.ndim == 2:       # multi-output regression (PCA/GLRM shapes)
            return {"predictions": {f"predict_{k}": raw[:, k].tolist()
                                    for k in range(raw.shape[1])}}
        return {"predictions": {"predict": raw.tolist()}}
    labels = np.asarray(decision_labels(model, raw)).astype(np.int64)
    domain = list(getattr(model, "response_domain", None)
                  or [str(k) for k in range(raw.shape[1])])
    preds = {"predict": [domain[int(c)] for c in labels]}
    for k, lvl in enumerate(domain[: raw.shape[1]]):
        preds[f"p{lvl}"] = raw[:, k].tolist()
    return {"predictions": preds}


#: the process-wide scoring tier (reference: the serving sidecar singleton)
SCORING = ScoringService()
