"""Pluggable extensions + lifecycle listeners (the Extensions SPI).

Reference: ``water/ExtensionManager.java`` discovers ``AbstractH2OExtension``
and ``RestApiExtension`` implementations via Java ``ServiceLoader`` on the
classpath and runs their init hooks during node startup;
``water/ListenerService.java`` fans lifecycle events (cloud up, job results)
out to registered listeners.

TPU-native analog: there is no classpath scanning in a Python process, so
discovery is explicit — modules named in ``$H2O3TPU_EXTENSIONS`` (comma-
separated import paths) are imported when a session or server starts, and a
module registers itself at import time via :func:`register`.  Extensions can
contribute node-init hooks, REST routes (the ``RestApiExtension`` analog —
served by ``api/server.py`` after the built-in table), and event listeners.

Events reported by the framework (superset of the reference's
``ListenerService.report`` call sites): ``cloud_up``, ``model_build_start``,
``model_build_end``, ``job_done``.
"""
from __future__ import annotations

import logging
import os
import threading

log = logging.getLogger("h2o3_tpu")

__all__ = ["H2OExtension", "register", "extensions", "init_all",
           "rest_routes", "add_listener", "remove_listener", "report",
           "load_env_extensions", "reset"]


class H2OExtension:
    """Base class (reference ``water/AbstractH2OExtension.java``): subclass,
    set ``name``, optionally override ``init`` / ``routes`` / ``on_event``,
    and pass an instance to :func:`register`."""

    name: str = "extension"
    enabled: bool = True

    def init(self) -> None:
        """Node-startup hook (reference ``onLocalNodeStarted``)."""

    def routes(self):
        """REST contributions: ``[(regex_path, http_method, fn)]`` where
        ``fn(handler, *groups)`` is a bound-style handler taking the live
        request handler (reference ``RestApiExtension.registerEndPoints``)."""
        return []

    def on_event(self, event: str, **info) -> None:
        """Lifecycle callback (reference ``ListenerService.report``)."""


_LOCK = threading.Lock()
_EXTENSIONS: list[H2OExtension] = []
_LISTENERS: list = []          # bare callables: (event, **info) -> None
_INITED: set[int] = set()


def register(ext: H2OExtension) -> H2OExtension:
    with _LOCK:
        if all(e is not ext for e in _EXTENSIONS):
            _EXTENSIONS.append(ext)
    return ext


def extensions() -> list[H2OExtension]:
    return [e for e in _EXTENSIONS if e.enabled]


def init_all() -> None:
    """Run pending init hooks exactly once per extension (the reference
    guards double-init the same way: ``ExtensionManager.registerCoreExtensions``
    is one-shot)."""
    for e in extensions():
        if id(e) not in _INITED:
            _INITED.add(id(e))
            try:
                e.init()
            except Exception:          # noqa: BLE001 — a broken extension
                log.exception("extension %s failed to init", e.name)
                e.enabled = False      # must not take the node down


def rest_routes():
    out = []
    for e in extensions():
        out.extend(e.routes())
    return out


def add_listener(cb) -> None:
    with _LOCK:
        if cb not in _LISTENERS:
            _LISTENERS.append(cb)


def remove_listener(cb) -> None:
    with _LOCK:
        if cb in _LISTENERS:
            _LISTENERS.remove(cb)


def report(event: str, **info) -> None:
    """Fan an event out to listeners and extensions; listener failures are
    logged, never raised into the training/serving path."""
    for cb in list(_LISTENERS):
        try:
            cb(event, **info)
        except Exception:              # noqa: BLE001
            log.exception("listener failed on %s", event)
    for e in extensions():
        try:
            e.on_event(event, **info)
        except Exception:              # noqa: BLE001
            log.exception("extension %s failed on %s", e.name, event)


_ENV_LOADED: set[str] = set()


def load_env_extensions() -> None:
    """Import modules named in $H2O3TPU_EXTENSIONS (they self-register on
    import — the ServiceLoader analog)."""
    import importlib
    for mod in filter(None, os.environ.get("H2O3TPU_EXTENSIONS", "").split(",")):
        mod = mod.strip()
        if mod and mod not in _ENV_LOADED:
            _ENV_LOADED.add(mod)
            try:
                importlib.import_module(mod)
            except Exception:          # noqa: BLE001
                log.exception("failed to load extension module %s", mod)


def reset() -> None:
    """Test hook: drop all registrations."""
    with _LOCK:
        _EXTENSIONS.clear()
        _LISTENERS.clear()
        _INITED.clear()
        _ENV_LOADED.clear()
