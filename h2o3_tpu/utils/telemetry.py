"""Runtime telemetry — metrics registry, OpenMetrics export, structured log ring.

Reference: H2O-3's observability surface — ``water/util/Log.java`` (level-split
log files behind ``water/api/LogsHandler`` → ``/3/Logs``), the ``WaterMeter*``
meters, and the per-request timing Jetty keeps. Here the runtime equivalents
are a process-local :class:`MetricsRegistry` (counters / gauges / fixed-bucket
histograms with optional labels) exported as JSON (``/3/Metrics``) and
Prometheus/OpenMetrics text (``/metrics``), plus a :class:`LogRing` handler —
a fixed-size ring of formatted log lines in H2O's ``MM-dd HH:mm:ss.SSS`` line
format, installed on the ``h2o3_tpu`` logger at session/server startup.

Design constraints:

- **Always-on and off the jit hot path.** Every record site is host-side
  Python around a dispatch (a lock-protected float add, ~µs); nothing is ever
  traced into an XLA program.
- **Thread-safe and exact.** One registry lock guards family creation AND all
  child mutations, so concurrent increments from REST handler threads and
  training jobs never lose counts.
- **Bounded cardinality.** Label values are route patterns / algo names /
  function names — never keys, paths, or user data.
"""

from __future__ import annotations

import bisect
import collections
import logging
import math
import threading

from h2o3_tpu.utils import lockwitness

# Latency buckets (seconds) for request/dispatch histograms: µs-scale
# dispatches up through slow requests.
DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                   1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

# Build-latency buckets: model builds run seconds to an hour — resolution
# must extend past the minute mark or every real build lands in +Inf.
BUILD_BUCKETS = (0.1, 0.5, 1.0, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
                 600.0, 1800.0, 3600.0)


def _fmt(v: float) -> str:
    """OpenMetrics number rendering: integral floats print as integers."""
    if v != v:
        return "NaN"
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _escape_label(v: str) -> str:
    """Label-value escaping per the OpenMetrics exposition format: exactly
    backslash, double-quote, and line feed — in that order (escaping the
    escape character first, or a pre-escaped ``\\n`` would double)."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(v: str) -> str:
    """HELP-text escaping: the format defines only ``\\\\`` and ``\\n``
    here — a ``\\"`` in HELP is an *invalid* escape sequence that makes
    strict OpenMetrics parsers reject the whole exposition, so quotes pass
    through verbatim (unlike label values, HELP is not quote-delimited)."""
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def _label_str(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"' for k, v in labels.items())
    return "{" + inner + "}"


class _Counter:
    """Monotone counter child (one label combination)."""

    __slots__ = ("_lock", "value")

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up; use a gauge")
        with self._lock:
            self.value += amount


class _Gauge:
    """Settable gauge child."""

    __slots__ = ("_lock", "value")

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self.value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self.value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)


class _Histogram:
    """Fixed-bucket histogram child; also tracks min/max so per-dispatch
    duration spreads (straggler visibility) survive aggregation.

    Observations are validated: every histogram here measures a duration,
    a size, or a count — all non-negative and finite by definition. A NaN
    poisons ``_sum`` (and every percentile read downstream) irreversibly,
    a negative or infinite value corrupts it silently; such observations
    are DROPPED and accounted in ``h2o3_telemetry_rejected_total{where}``
    instead (the instrument reports its own bad inputs rather than lying
    with them)."""

    __slots__ = ("_lock", "_reject", "buckets", "counts", "sum", "count",
                 "min", "max")

    def __init__(self, lock: threading.Lock, buckets: tuple, reject=None):
        self._lock = lock
        self._reject = reject               # callable: count a dropped obs
        self.buckets = buckets              # ascending upper bounds, no +Inf
        self.counts = [0] * (len(buckets) + 1)   # last slot = +Inf
        self.sum = 0.0
        self.count = 0
        self.min = math.inf
        self.max = -math.inf

    def observe(self, value: float) -> None:
        v = float(value)
        if not math.isfinite(v) or v < 0.0:
            if self._reject is not None:
                self._reject()
            return
        with self._lock:
            self.counts[bisect.bisect_left(self.buckets, v)] += 1
            self.sum += v
            self.count += 1
            if v < self.min:
                self.min = v
            if v > self.max:
                self.max = v


_KINDS = {"counter": _Counter, "gauge": _Gauge, "histogram": _Histogram}


class _Family:
    """One named metric family: type + help + label schema + children."""

    def __init__(self, registry: "MetricsRegistry", name: str, kind: str,
                 help: str, labelnames: tuple, buckets: tuple | None):
        self.name = name
        self.kind = kind
        self.help = help
        self.labelnames = tuple(labelnames)
        self.buckets = buckets
        self._registry = registry
        self._lock = registry._lock
        self._children: dict[tuple, object] = {}

    def labels(self, **labels):
        if set(labels) != set(self.labelnames):
            raise ValueError(f"{self.name} wants labels {self.labelnames}, "
                             f"got {tuple(labels)}")
        key = tuple(str(labels[n]) for n in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                cls = _KINDS[self.kind]
                child = (cls(self._lock, self.buckets,
                             reject=self._registry._rejecter(self.name))
                         if self.kind == "histogram" else cls(self._lock))
                self._children[key] = child
        return child

    # label-less convenience: the family IS its single child
    def _default(self):
        return self.labels()

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default().dec(amount)

    def set(self, value: float) -> None:
        self._default().set(value)

    def observe(self, value: float) -> None:
        self._default().observe(value)

    def children(self) -> list[tuple[dict, object]]:
        with self._lock:
            items = list(self._children.items())
        return [(dict(zip(self.labelnames, key)), child)
                for key, child in items]


class MetricsRegistry:
    """Thread-safe registry of counter/gauge/histogram families.

    Declaring an existing name returns the same family (idempotent — safe to
    declare at every call site); re-declaring with a different type raises.
    """

    def __init__(self):
        self._lock = lockwitness.rlock("utils.telemetry.MetricsRegistry._lock")
        self._families: dict[str, _Family] = {}

    def _family(self, name: str, kind: str, help: str, labelnames,
                buckets=None) -> _Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != kind:
                    raise ValueError(f"metric {name!r} already registered as "
                                     f"{fam.kind}, not {kind}")
                return fam
            fam = _Family(self, name, kind, help, tuple(labelnames), buckets)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "", labelnames=()) -> _Family:
        return self._family(name, "counter", help, labelnames)

    def gauge(self, name: str, help: str = "", labelnames=()) -> _Family:
        return self._family(name, "gauge", help, labelnames)

    def histogram(self, name: str, help: str = "", labelnames=(),
                  buckets: tuple = DEFAULT_BUCKETS) -> _Family:
        return self._family(name, "histogram", help, labelnames,
                            tuple(sorted(buckets)))

    def reject(self, where: str) -> None:
        """Account one invalid observation (NaN / negative / infinite)
        dropped at ``where`` instead of poisoning an instrument. The ONE
        home of the ``h2o3_telemetry_rejected`` registration — histogram
        children and the serving ``LatencyRing`` both route here, so the
        name/help/labels can never drift apart. ``where`` is a family
        name or a code-defined site, so cardinality stays bounded."""
        self.counter(
            "h2o3_telemetry_rejected",
            "invalid observations (NaN/negative/non-finite) dropped "
            "instead of poisoning a histogram or percentile ring",
            ("where",)).labels(where=where).inc()

    def _rejecter(self, where: str):
        """The per-family drop callback histogram children hold."""
        def count() -> None:
            self.reject(where)
        return count

    def reset(self) -> None:
        """Drop every family (tests only — production metrics are append-only)."""
        with self._lock:
            self._families.clear()

    # -- exporters -----------------------------------------------------------

    def snapshot(self, include_buckets: bool = True) -> list[dict]:
        """Flat sample rows — uniform {name, type, labels, value} dicts, so
        the REST layer can serve them TwoDimTable-style and ``bench.py`` can
        embed them in an artifact."""
        # the registry RLock also guards every child mutation, so holding it
        # across the read pass yields a consistent snapshot (no torn
        # bucket-vs-count reads mid-observe); exports are rare and fast
        with self._lock:
            return self._snapshot_locked(include_buckets)

    def _snapshot_locked(self, include_buckets: bool) -> list[dict]:
        out: list[dict] = []
        for fam in self._families.values():
            for labels, child in fam.children():
                if fam.kind == "histogram":
                    if include_buckets:
                        cum = 0
                        for ub, c in zip(fam.buckets, child.counts):
                            cum += c
                            out.append(dict(name=f"{fam.name}_bucket",
                                            type="histogram",
                                            labels={**labels, "le": _fmt(ub)},
                                            value=cum))
                        out.append(dict(name=f"{fam.name}_bucket",
                                        type="histogram",
                                        labels={**labels, "le": "+Inf"},
                                        value=child.count))
                    out.append(dict(name=f"{fam.name}_count",
                                    type="histogram", labels=labels,
                                    value=child.count))
                    out.append(dict(name=f"{fam.name}_sum",
                                    type="histogram", labels=labels,
                                    value=child.sum))
                    if child.count:
                        out.append(dict(name=f"{fam.name}_min",
                                        type="histogram", labels=labels,
                                        value=child.min))
                        out.append(dict(name=f"{fam.name}_max",
                                        type="histogram", labels=labels,
                                        value=child.max))
                elif fam.kind == "counter":
                    out.append(dict(name=f"{fam.name}_total", type="counter",
                                    labels=labels, value=child.value))
                else:
                    out.append(dict(name=fam.name, type="gauge",
                                    labels=labels, value=child.value))
        return out

    def to_openmetrics(self) -> str:
        """Prometheus/OpenMetrics exposition text (ends with ``# EOF``).
        Rendered under the registry lock for the same consistency guarantee
        as :meth:`snapshot` (monotone cumulative buckets vs ``_count``)."""
        with self._lock:
            return self._openmetrics_locked()

    def _openmetrics_locked(self) -> str:
        lines: list[str] = []
        for fam in self._families.values():
            lines.append(f"# TYPE {fam.name} {fam.kind}")
            if fam.help:
                lines.append(f"# HELP {fam.name} {_escape_help(fam.help)}")
            for labels, child in fam.children():
                ls = _label_str(labels)
                if fam.kind == "counter":
                    lines.append(f"{fam.name}_total{ls} {_fmt(child.value)}")
                elif fam.kind == "gauge":
                    lines.append(f"{fam.name}{ls} {_fmt(child.value)}")
                else:
                    cum = 0
                    for ub, c in zip(fam.buckets, child.counts):
                        cum += c
                        bl = _label_str({**labels, "le": _fmt(ub)})
                        lines.append(f"{fam.name}_bucket{bl} {cum}")
                    bl = _label_str({**labels, "le": "+Inf"})
                    lines.append(f"{fam.name}_bucket{bl} {child.count}")
                    lines.append(f"{fam.name}_count{ls} {child.count}")
                    lines.append(f"{fam.name}_sum{ls} {_fmt(child.sum)}")
        lines.append("# EOF")
        return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# Log ring — the LogsHandler backing store.

#: H2O's log line format: ``MM-dd HH:mm:ss.SSS pid thread LEVEL logger: msg``
#: (reference: ``water/util/Log.java`` ``logHeader``).
LOG_FORMAT = ("%(asctime)s.%(msecs)03d %(process)d %(threadName)s "
              "%(levelname)-5s %(name)s: %(message)s")
LOG_DATEFMT = "%m-%d %H:%M:%S"

LOG_RING_SIZE = 2048


class LogRing(logging.Handler):
    """Fixed-size ring of formatted log records (reference: the in-memory
    tail ``LogsHandler`` serves per level-file). ``deque(maxlen=...)`` gives
    lock-free thread-safe appends under the GIL."""

    def __init__(self, capacity: int = LOG_RING_SIZE):
        super().__init__()
        self.capacity = capacity
        self.buffer: collections.deque = collections.deque(maxlen=capacity)
        self.setFormatter(logging.Formatter(LOG_FORMAT, LOG_DATEFMT))

    def emit(self, record: logging.LogRecord) -> None:
        try:
            text = self.format(record)
            if "\n" in text:
                # one record = one ring line: multi-line payloads
                # (tracebacks, thread dumps) fold onto the header line so
                # every line /3/Logs serves keeps the H2O line format —
                # consumers (h2o-py get_log, the format-parity tests) parse
                # the ``MM-dd HH:mm:ss.SSS pid thread LEVEL`` header per line
                text = " | ".join(
                    ln.rstrip() for ln in text.splitlines() if ln.strip())
            self.buffer.append((record.levelno, text))
        except Exception:   # noqa: BLE001 — logging must never raise
            self.handleError(record)

    def lines(self, min_level: int = 0) -> list[str]:
        return [line for lv, line in list(self.buffer) if lv >= min_level]


LOG_RING: LogRing | None = None

#: reference log *files* → minimum level served (``water/util/Log.java``
#: writes one file per level; ``h2o-py``'s ``get_log`` names one of these)
LOG_FILES = {"trace": 0, "debug": logging.DEBUG, "default": logging.INFO,
             "info": logging.INFO, "httpd": logging.INFO,
             "stdout": logging.INFO, "stderr": logging.WARNING,
             "warn": logging.WARNING, "error": logging.ERROR,
             "fatal": logging.CRITICAL}


def install_log_ring(capacity: int = LOG_RING_SIZE) -> LogRing:
    """Idempotently attach the ring to the ``h2o3_tpu`` logger (called at
    session/server startup; safe to call from any thread, any number of
    times)."""
    global LOG_RING
    logger = logging.getLogger("h2o3_tpu")
    for h in logger.handlers:
        if isinstance(h, LogRing):
            LOG_RING = h
            return h
    ring = LogRing(capacity)
    logger.addHandler(ring)
    if logger.level == logging.NOTSET:
        # the root logger defaults to WARNING; INFO here keeps startup /
        # LogAndEcho lines flowing into the ring without touching root
        logger.setLevel(logging.INFO)
    LOG_RING = ring
    return ring


# ---------------------------------------------------------------------------
# Metric catalog — every always-on instrument in the runtime declares here,
# so the name inventory (docs/OBSERVABILITY.md) has one source of truth.

METRICS = MetricsRegistry()

# REST surface (recorded in api/server.py:_route)
REQUESTS = METRICS.counter(
    "h2o3_requests", "REST requests served, by route pattern/method/status",
    ("route", "method", "status"))
REQUEST_SECONDS = METRICS.histogram(
    "h2o3_request_duration_seconds", "REST request latency",
    ("route", "method"))
SCRAPE_SECONDS = METRICS.histogram(
    "h2o3_metrics_scrape_seconds",
    "wall seconds to render the /metrics OpenMetrics exposition — a "
    "scrape dragging means the registry itself is the bottleneck")

# map_reduce substrate (ops/map_reduce.py)
MR_DISPATCHES = METRICS.counter(
    "h2o3_mapreduce_dispatches", "map_reduce collective dispatches", ("fn",))
MR_PARTITIONS = METRICS.counter(
    "h2o3_mapreduce_partitions",
    "row shards (mesh devices) covered by dispatches")
MR_DISPATCH_SECONDS = METRICS.histogram(
    "h2o3_mapreduce_dispatch_seconds",
    "per-dispatch wall time; min/max spread flags stragglers", ("fn",))

# ingest (frame/parse.py)
PARSE_ROWS = METRICS.counter("h2o3_parse_rows", "rows parsed into frames")
PARSE_BYTES = METRICS.counter("h2o3_parse_bytes", "source bytes parsed")
PARSE_CHUNKS = METRICS.counter(
    "h2o3_parse_chunks", "column chunks (vecs) created by parses")

# streaming ingest pipeline (ingest/pipeline.py — docs/INGEST.md)
INGEST_CHUNKS = METRICS.counter(
    "h2o3_ingest_chunks", "fixed-row-count chunk batches through the "
    "streaming parse pipeline")
INGEST_ROWS = METRICS.counter(
    "h2o3_ingest_rows", "rows parsed by the streaming pipeline")
INGEST_BYTES = METRICS.counter(
    "h2o3_ingest_bytes", "decompressed source bytes consumed by the "
    "streaming pipeline")
INGEST_ENCODED_BYTES = METRICS.counter(
    "h2o3_ingest_encoded_bytes", "compressed host payload bytes produced "
    "by the chunk encoders (vs 4B/value eager columns)")
INGEST_RESTARTS = METRICS.counter(
    "h2o3_ingest_restarts", "promote-and-reparse restarts (a chunk past "
    "the type-inference sample broke a numeric guess)")

# compressed-chunk seam (frame/vec.py lazy decompress-on-access)
CHUNK_DECOMPRESS = METRICS.counter(
    "h2o3_chunk_decompress", "compressed columns materialized to device "
    "arrays on access (Chunk.atd decompress-on-access)")
CHUNK_DECOMPRESS_BYTES = METRICS.counter(
    "h2o3_chunk_decompress_bytes", "decoded bytes materialized on access")
CHUNK_VIEW_DROPS = METRICS.counter(
    "h2o3_chunk_view_drops", "derived device views of compressed columns "
    "dropped by the Cleaner (tier-1 eviction)")
CHUNK_VIEW_DROP_BYTES = METRICS.counter(
    "h2o3_chunk_view_drop_bytes", "device bytes freed by view drops")

# Cleaner spill/fault-in (utils/cleaner.py — docs/INGEST.md "Spill")
SPILLS = METRICS.counter(
    "h2o3_spill", "DKV values spilled to the ice_root", ("kind",))
SPILL_BYTES = METRICS.counter(
    "h2o3_spill_bytes", "resident bytes released by spills", ("kind",))
SPILL_RESTORES = METRICS.counter(
    "h2o3_spill_restore", "spilled values faulted back in on access",
    ("kind",))
SPILL_RESTORE_BYTES = METRICS.counter(
    "h2o3_spill_restore_bytes", "bytes faulted back in on access", ("kind",))

# DKV (utils/registry.py)
DKV_PUTS = METRICS.counter("h2o3_dkv_puts", "DKV puts")
DKV_GETS = METRICS.counter("h2o3_dkv_gets", "DKV gets")
DKV_REMOVES = METRICS.counter("h2o3_dkv_removes", "DKV removes")
DKV_KEYS = METRICS.gauge("h2o3_dkv_keys", "resident DKV keys")

# memory accounting (utils/memory.py MemoryMeter)
DKV_BYTES = METRICS.gauge(
    "h2o3_dkv_bytes", "resident DKV bytes by value kind "
    "(frame/model/raw/job/other; `spilled` carries ON-DISK bytes so the "
    "view reconciles across a Cleaner sweep)", ("kind",))
HOST_RSS_BYTES = METRICS.gauge(
    "h2o3_host_rss_bytes", "process resident set size (/proc/self/status)")
HOST_RSS_PEAK_BYTES = METRICS.gauge(
    "h2o3_host_rss_peak_bytes", "monotonic high-water mark of host RSS")
DEVICE_BYTES = METRICS.gauge(
    "h2o3_device_bytes_in_use",
    "device (HBM) bytes in use, summed over devices; from "
    "device.memory_stats() or live-array accounting on backends without it")
DEVICE_PEAK_BYTES = METRICS.gauge(
    "h2o3_device_peak_bytes",
    "monotonic high-water mark of device bytes in use")

# persist layer (persist/frame_io.py, persist/model_io.py)
PERSIST_READ_BYTES = METRICS.counter(
    "h2o3_persist_read_bytes", "bytes read by the persist layer", ("what",))
PERSIST_WRITE_BYTES = METRICS.counter(
    "h2o3_persist_write_bytes", "bytes written by the persist layer", ("what",))

# model builds (models/model_base.py)
MODEL_BUILDS = METRICS.counter(
    "h2o3_model_builds", "completed model builds", ("algo",))
MODEL_BUILD_SECONDS = METRICS.histogram(
    "h2o3_model_build_seconds", "model build wall time", ("algo",),
    buckets=BUILD_BUCKETS)

# host-driven convergence loops (models/*.py drivers): per-iteration wall
# time — IRLS steps, boosting chunks, DL epochs. The before/after evidence
# for host-sync batching fixes (graftlint TRC003) lives here: fewer
# device→host round-trips per iteration shifts this histogram left.
ITER_SECONDS = METRICS.histogram(
    "h2o3_iteration_seconds",
    "per-iteration wall time of host-driven convergence loops", ("loop",))

# dispatch economy of the same loops: blocking host fetches per logical
# iteration (1.0 = the classic sync-per-step driver; 1/K under K-step
# megasteps). Set by models/model_base.publish_dispatch_audit at the end of
# every fit; bench gates on it so a per-iteration fetch cannot silently
# return to a hot path.
DISPATCHES_PER_ITER = METRICS.gauge(
    "h2o3_dispatches_per_iteration",
    "blocking host syncs per logical iteration of a convergence loop "
    "(1/K under K-step megasteps)", ("loop",))

# mesh-slice scheduler (orchestration/scheduler.py): utilization of the
# disjoint device slices concurrent builds run on (docs/ORCHESTRATION.md).
# Slice labels are indices ("0".."k-1") or "full" for whole-mesh leases.
SLICE_COUNT = METRICS.gauge(
    "h2o3_slice_count",
    "device slices the mesh scheduler currently carves the global mesh into")
SLICE_BUSY = METRICS.counter(
    "h2o3_slice_busy_seconds",
    "cumulative seconds a slice spent running leased builds", ("slice",))
SLICE_BUILDS = METRICS.counter(
    "h2o3_slice_builds", "model builds leased onto a slice", ("slice",))
SLICE_QUEUE_WAIT = METRICS.histogram(
    "h2o3_slice_queue_wait_seconds",
    "time a build waited for a free slice (or for the whole mesh)")

# compute observatory (utils/costs.py CostMeter — docs/OBSERVABILITY.md
# "Compute"). Site labels are code-defined logical compile sites
# (glm:irls_megastep, gbm:grow_batched, map_reduce:<fn>, score:<algo>);
# loop labels match the h2o3_iteration_seconds loops plus "scoring".
COMPILES = METRICS.counter(
    "h2o3_compiles", "XLA compiles observed by the cost observatory",
    ("site",))
COMPILE_SECONDS = METRICS.counter(
    "h2o3_compile_seconds", "compile wall seconds per logical site",
    ("site",))
RECOMPILES = METRICS.counter(
    "h2o3_recompiles",
    "signature changes (a site compiling a 2nd+ distinct signature)",
    ("site",))
ACHIEVED_FLOPS = METRICS.gauge(
    "h2o3_achieved_flops_per_sec",
    "achieved FLOP/s of a loop's compiled program (cost_analysis FLOPs / "
    "sampled synced wall time)", ("loop",))
ACHIEVED_BYTES = METRICS.gauge(
    "h2o3_achieved_bytes_per_sec",
    "achieved bytes/s of a loop's compiled program", ("loop",))
ARITH_INTENSITY = METRICS.gauge(
    "h2o3_arithmetic_intensity",
    "FLOPs per byte accessed of a loop's compiled program", ("loop",))
COMPUTE_UTILIZATION = METRICS.gauge(
    "h2o3_compute_utilization",
    "achieved FLOP/s over the backend's peak (MFU); only published on "
    "backends in the peak table — unknown backends report null via "
    "/3/Compute instead of a bogus 0", ("loop",))

# fault injection (utils/timeline.py FaultInjector)
FAULTS_INJECTED = METRICS.counter(
    "h2o3_faults_injected", "faults injected into dispatches", ("kind",))

# elastic local-SGD membership (parallel/elastic.py — docs/RELIABILITY.md
# "Elastic training"): averaging rounds completed, workers ejected by cause,
# and the live-worker gauge the /3/Cloud workers view mirrors
ELASTIC_ROUNDS = METRICS.counter(
    "h2o3_elastic_rounds", "elastic local-SGD averaging rounds completed")
ELASTIC_EJECTIONS = METRICS.counter(
    "h2o3_elastic_ejections",
    "elastic workers ejected, by cause "
    "(heartbeat/deadline/retry_exhausted/fault)", ("reason",))
ELASTIC_WORKERS = METRICS.gauge(
    "h2o3_elastic_workers",
    "live (ACTIVE) workers in the most recent elastic group")

# dispatch reliability (ops/map_reduce.py retrying): one "retried" per
# backoff-and-reattempt, one "exhausted" when the budget runs out and the
# dispatch surfaces as DispatchFailed (docs/RELIABILITY.md)
DISPATCH_RETRIES = METRICS.counter(
    "h2o3_dispatch_retries",
    "dispatch retry events by call site and outcome (retried/exhausted)",
    ("fn", "outcome"))

# job deadlines (models/job.py): builds that hit max_runtime_secs and were
# cooperatively cancelled between megasteps/tree chunks
JOB_DEADLINE_EXCEEDED = METRICS.counter(
    "h2o3_job_deadline_exceeded",
    "jobs terminated by their max_runtime_secs deadline")

# scoring tier (serving/ — docs/SERVING.md). Batch-size buckets are row
# counts (the micro-batcher's power-of-two buckets), not seconds.
SCORE_BATCH_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0,
                       256.0, 512.0, 1024.0, 2048.0, 4096.0)
SCORE_REQUESTS = METRICS.counter(
    "h2o3_score_requests", "scoring requests served by /3/Score",
    ("algo", "status"))
SCORE_SECONDS = METRICS.histogram(
    "h2o3_score_seconds",
    "end-to-end /3/Score request latency (enqueue -> slice handed back)",
    ("algo",))
SCORE_BATCH_SIZE = METRICS.histogram(
    "h2o3_score_batch_size",
    "rows fused into one scoring dispatch by the micro-batcher",
    buckets=SCORE_BATCH_BUCKETS)
SCORE_BATCH_REQUESTS = METRICS.histogram(
    "h2o3_score_batch_requests",
    "concurrent requests coalesced per scoring dispatch",
    buckets=SCORE_BATCH_BUCKETS)
SCORER_CACHE = METRICS.counter(
    "h2o3_scorer_cache",
    "compiled-scorer signature cache events (hit/miss/evict)", ("event",))
SCORE_RESIDENT_BYTES = METRICS.gauge(
    "h2o3_score_resident_bytes",
    "artifact bytes of models resident in the scoring tier")
SCORE_RESIDENT_MODELS = METRICS.gauge(
    "h2o3_score_resident_models", "models resident in the scoring tier")

# SLO-adaptive serving (serving/slo.py + serving/replicas.py —
# docs/SERVING.md "SLO & replicas"). Shed reasons: overload (admission
# estimator), timeout (in-queue wait ceiling), evicted (persistent
# residency loss); priority is the request's 0-9 class.
SCORE_SHED = METRICS.counter(
    "h2o3_score_shed",
    "scoring requests shed with 503+Retry-After instead of served",
    ("reason", "priority"))
SCORE_QUEUE_WAIT = METRICS.histogram(
    "h2o3_score_queue_wait_seconds",
    "scoring request wait from enqueue to dispatch start (the SLO "
    "controller's scale signal)")
SCORE_WINDOW_MS = METRICS.gauge(
    "h2o3_score_window_ms",
    "current adaptive collect window per model (fixed window when no SLO); "
    "cardinality is bounded by residency, like the per-model /3/Score rows",
    ("model",))
SCORE_REPLICAS = METRICS.gauge(
    "h2o3_score_replicas", "live scoring replicas holding slice leases")
SCORE_SCALE_EVENTS = METRICS.counter(
    "h2o3_score_scale_events",
    "replica pool scale decisions by direction (up/down)", ("direction",))
SCORE_PRECOMPILE = METRICS.counter(
    "h2o3_score_precompile",
    "speculative bucket pre-compiles on replica admission "
    "(scheduled/compiled/failed)", ("event",))
