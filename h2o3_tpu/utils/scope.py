"""Scope — temp-key lifetime tracking.

Reference: ``water/Scope.java`` — ``Scope.enter()``/``Scope.exit(keep...)``
brackets an operation; every key created inside is deleted at exit unless
explicitly kept. The reference threads this through every ModelBuilder so
intermediate frames never leak into the DKV.

Here the DKV is a single registry, so a scope snapshots the key set at entry
and removes the difference at exit (minus ``keep``). Nesting works the
obvious way; ``track`` force-registers keys created through side channels.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager

from h2o3_tpu.utils.registry import DKV

# per-thread like the reference (Scope.java keys its stack by thread):
# concurrent REST handlers must not pop each other's frames
_local = threading.local()


def _stack_of() -> list[dict]:
    if not hasattr(_local, "stack"):
        _local.stack = []
    return _local.stack


def enter() -> None:
    _stack_of().append({"pre": set(DKV.keys()), "tracked": set()})


def track(key: str) -> str:
    """Explicitly mark a key for cleanup at scope exit."""
    stack = _stack_of()
    if stack:
        stack[-1]["tracked"].add(key)
    return key


def untrack(key: str) -> str:
    stack = _stack_of()
    if stack:
        stack[-1]["tracked"].discard(key)
    return key


def exit(*keep: str) -> None:
    """Remove keys created since the matching :func:`enter`, except ``keep``
    (and anything a still-open outer scope already owned)."""
    stack = _stack_of()
    frame = stack.pop()
    keep_set = set(keep)
    new = (set(DKV.keys()) - frame["pre"]) | frame["tracked"]
    for k in new - keep_set:
        if k in DKV:
            DKV.remove(k)
    if stack:    # surviving keys become the outer scope's responsibility
        stack[-1]["tracked"] |= keep_set & set(DKV.keys())


@contextmanager
def scope(*keep: str):
    """``with scope("result_key"): ...`` — the context-manager form."""
    enter()
    try:
        yield
    finally:
        exit(*keep)
