"""User-defined metric / distribution functions uploaded over the wire.

Reference: ``water/udf/`` — h2o-py's ``h2o.upload_custom_metric`` /
``upload_custom_distribution`` (``h2o-py/h2o/h2o.py:2128,2230``) zip generated
Python source, upload it with ``POST /3/PutKey``, and pass the reference
string ``"python:KEY=module.Class"`` as ``custom_metric_func`` /
``custom_distribution_func``.  Server-side the reference loads the source
under Jython against Java interfaces (``water/udf/CMetricFunc.java``,
``CDistributionFunc.java``, loaded by ``CFuncLoader``).  This server IS
Python, so the TPU-native design is simpler and stronger: read the zip from
DKV, exec the module with a tiny shim ``water.udf`` package (the generated
wrapper code does ``import water.udf.CMetricFunc as MetricFunc`` and uses it
as a base class), instantiate the named class, and adapt its row-wise
map/reduce/metric (or link/init/gradient/gamma) contract onto vectorized host
numpy.  Custom distributions enter the jitted boosting scan through
``jax.pure_callback`` so the fused ``lax.scan`` engine stays one compiled
program (the callback runs once per boosting iteration on full columns).

SECURITY: like the reference (which executes uploaded jars/Jython source),
loading a UDF executes user code in-process.  The REST surface is gated by
the server's auth layer; there is no additional sandbox — same trust model
as ``water/udf/``.
"""
from __future__ import annotations

import io
import re
import sys
import types
import zipfile

import numpy as np

__all__ = ["load_cfunc", "parse_ref", "metric_callable", "CustomDistribution",
           "register_custom_dist", "get_custom_dist", "grad_hess_host",
           "LINKS"]


# -- water.udf shim ----------------------------------------------------------

class CMetricFunc:
    """Stand-in for the Java interface ``water.udf.CMetricFunc``: subclasses
    provide ``map(pred, act, w, o, model) -> state``, ``reduce(l, r) ->
    state`` and ``metric(state) -> float``."""


class CDistributionFunc:
    """Stand-in for ``water.udf.CDistributionFunc``: subclasses provide
    ``link() -> str``, ``init(w, o, y) -> [num, den]``, ``gradient(y, f) ->
    float`` and ``gamma(w, y, z, f) -> [num, den]``."""


def _install_shim() -> None:
    """Make ``import water.udf.CMetricFunc as MetricFunc`` (the exact line
    h2o-py's generated wrapper emits) work in CPython: pre-seed sys.modules
    so the import machinery resolves the leaf names to our shim classes."""
    if "water.udf" in sys.modules:
        return
    water = types.ModuleType("water")
    udf = types.ModuleType("water.udf")
    udf.CMetricFunc = CMetricFunc
    udf.CDistributionFunc = CDistributionFunc
    water.udf = udf
    sys.modules["water"] = water
    sys.modules["water.udf"] = udf
    # ``import a.b.c as x`` binds getattr(a.b, 'c') with a sys.modules
    # fallback — seeding the dotted names keeps both resolution paths happy
    sys.modules["water.udf.CMetricFunc"] = CMetricFunc      # type: ignore[assignment]
    sys.modules["water.udf.CDistributionFunc"] = CDistributionFunc  # type: ignore[assignment]


_REF_RE = re.compile(r"^(\w+):([^=]+)=(.+)$")


def parse_ref(ref: str) -> tuple[str, str, str]:
    """Split ``"lang:key=module.Class"`` into (lang, key, qualified class);
    the ONE place the ref grammar lives."""
    m = _REF_RE.match(ref)
    if not m:
        raise ValueError(
            f"malformed UDF reference {ref!r}; expected 'python:key=module.Class'")
    return m.group(1), m.group(2), m.group(3)


def load_cfunc(ref: str):
    """Resolve a ``"python:KEY=module.Class"`` reference to a live instance.

    The KEY names a DKV value holding the zip h2o-py uploaded (a ``func.jar``
    containing ``module.py``); ``module.Class`` names the wrapper class the
    generated source defines."""
    lang, key, qual = parse_ref(ref)
    if lang != "python":
        raise ValueError(f"unsupported UDF language {lang!r} (only 'python')")
    from h2o3_tpu.utils.registry import DKV
    val = DKV.get(key)
    if val is None:
        raise KeyError(f"UDF key {key!r} not found; upload it with /3/PutKey")
    data = getattr(val, "data", val)
    if not isinstance(data, (bytes, bytearray)):
        raise TypeError(f"UDF key {key!r} does not hold raw uploaded bytes")
    module_name, _, cls_name = qual.partition(".")
    if not cls_name:
        raise ValueError(f"UDF reference {ref!r} lacks a class name")
    with zipfile.ZipFile(io.BytesIO(bytes(data))) as zf:
        src = zf.read(module_name + ".py").decode()
    _install_shim()
    ns: dict = {"__name__": module_name}
    exec(compile(src, f"<udf {key}:{module_name}.py>", "exec"), ns)
    if cls_name not in ns:
        raise KeyError(f"class {cls_name!r} not defined by uploaded module "
                       f"{module_name!r}")
    return ns[cls_name]()


# -- custom metric adapter ---------------------------------------------------

def metric_callable(obj, name: str, model=None):
    """Adapt a map/reduce/metric UDF object to the builder's vectorized
    ``(preds, y, w) -> float`` custom-metric contract.

    Row layout matches the reference ``CFuncTask`` (h2o-py docs at
    ``h2o.py:2133``): classifiers get ``[label, p0, p1, ...]``, regression
    gets ``[prediction]``; ``act`` is ``[y]``; offset is 0 (offset-aware
    custom metrics would read it from the model, which we pass as None).

    ``model`` (an object or zero-arg callable yielding one) supplies the
    binomial decision threshold so the label in ``pred[0]`` matches what
    ``predict()`` emits — the reference passes the model's threshold-based
    label, and ``_default_threshold`` is resettable via
    ``model.reset.threshold`` here; falls back to argmax when absent
    (multinomial)."""
    def fn(preds, y, w):
        preds = np.asarray(preds)
        y = np.asarray(y, np.float64)
        w = np.asarray(w, np.float64)
        thr = None
        if preds.ndim == 2 and preds.shape[1] == 2:
            m = model() if callable(model) else model
            t = getattr(m, "_default_threshold", None)
            thr = float(t) if t is not None else None
        acc = None
        for i in np.nonzero(w > 0)[0]:
            if preds.ndim == 2:
                probs = [float(v) for v in preds[i]]
                label = (float(probs[1] >= thr) if thr is not None
                         else float(np.argmax(preds[i])))
                row = [label] + probs
            else:
                row = [float(preds[i])]
            state = obj.map(row, [float(y[i])], float(w[i]), 0.0, None)
            acc = state if acc is None else obj.reduce(acc, state)
        return float(obj.metric(acc)) if acc is not None else float("nan")

    fn.__name__ = name
    return fn


# -- custom distribution -----------------------------------------------------

# forward links only (f0 init); the INVERSE link lives in ONE place —
# ``gbm._linkinv_device`` (device code) — so scoring and init can't drift
LINKS = {
    "identity": lambda x: x,
    "log": lambda x: np.log(np.maximum(x, 1e-30)),
    "logit": lambda x: np.log(np.clip(x, 1e-12, 1 - 1e-12)
                              / (1 - np.clip(x, 1e-12, 1 - 1e-12))),
    "inverse": lambda x: 1.0 / np.where(np.abs(x) < 1e-30, 1e-30, x),
}


class CustomDistribution:
    """Vectorized host adapter over a link/init/gradient/gamma UDF object.

    The engine consumes it as (g, h) pairs with the same Newton-leaf
    convention as the built-in families: leaf = -sum(g)/sum(h).  The UDF's
    ``gamma`` returns per-row leaf-estimate contributions [num, den]
    (reference ``CDistributionFunc.java:49-58``), so g := -num, h := den
    reproduces the reference's custom leaf values exactly while feeding the
    same histogram stats to split finding."""

    def __init__(self, obj, ref: str):
        self.obj = obj
        self.ref = ref
        self.link_name = str(obj.link())
        if self.link_name not in LINKS:
            raise ValueError(f"unsupported custom link {self.link_name!r}; "
                             f"have {sorted(LINKS)}")

    def f0(self, y, w, offset=None) -> float:
        """Initial margin: link(sum num / sum den) over init contributions
        (reference ``DistributionFactory`` custom init)."""
        y = np.asarray(y, np.float64)
        w = np.asarray(w, np.float64)
        o = np.zeros_like(y) if offset is None else np.asarray(offset, np.float64)
        num = den = 0.0
        for i in np.nonzero(w > 0)[0]:
            nd = self.obj.init(float(w[i]), float(o[i]), float(y[i]))
            num += nd[0]
            den += nd[1]
        mu = num / max(den, 1e-30)
        return float(LINKS[self.link_name](mu))

    def grad_hess(self, F, y, w):
        """Per-row (g, h) = (-gamma_num, gamma_den) with z = gradient(y, f).

        Called through ``jax.pure_callback`` from the jitted scan — numpy in,
        numpy out, float32."""
        F = np.asarray(F, np.float64)
        y = np.asarray(y, np.float64)
        w = np.asarray(w, np.float64)
        g = np.zeros_like(F)
        h = np.full_like(F, 1e-10)
        for i in np.nonzero(w > 0)[0]:
            z = float(self.obj.gradient(float(y[i]), float(F[i])))
            nd = self.obj.gamma(float(w[i]), float(y[i]), z, float(F[i]))
            g[i] = -nd[0]
            h[i] = max(nd[1], 1e-10)
        return g.astype(np.float32), h.astype(np.float32)



# process-local registry: jit static args carry the integer id, the callback
# looks the adapter back up (ids are never reused within a process, so cached
# compiled programs always resolve to the distribution they were traced for).
# Allocation is lock-guarded: two concurrent custom-distribution trains
# through the threaded REST server must not collide on a cid — the jitted
# program carries cid as a static arg, so a collision would silently train
# one model with the other upload's gradients.
import itertools as _itertools
import threading as _threading

_CUSTOM_DISTS: dict[int, CustomDistribution] = {}
_DIST_LOCK = _threading.Lock()
_NEXT_CID = _itertools.count(1)


def register_custom_dist(cd: CustomDistribution) -> int:
    with _DIST_LOCK:
        cid = next(_NEXT_CID)
        _CUSTOM_DISTS[cid] = cd
    return cid


_BY_SOURCE: dict[tuple, int] = {}


def resolve_distribution(ref: str) -> tuple[int, "CustomDistribution"]:
    """Load + register a custom distribution, caching the id on the
    (reference, uploaded-bytes) pair: retraining with the same upload reuses
    the jitted boosting program (custom_id is a static arg); re-uploading
    under the same key gets a fresh id so stale compiled traces never fire."""
    import hashlib

    from h2o3_tpu.utils.registry import DKV
    _lang, ref_key, _qual = parse_ref(ref)
    data = getattr(DKV.get(ref_key), "data", b"")
    key = (ref, hashlib.sha1(bytes(data)).hexdigest() if
           isinstance(data, (bytes, bytearray)) else "")
    with _DIST_LOCK:
        if key in _BY_SOURCE:
            cid = _BY_SOURCE[key]
            return cid, _CUSTOM_DISTS[cid]
    cd = CustomDistribution(load_cfunc(ref), ref)
    with _DIST_LOCK:
        if key in _BY_SOURCE:          # lost the load race: reuse winner's id
            cid = _BY_SOURCE[key]
            return cid, _CUSTOM_DISTS[cid]
        cid = next(_NEXT_CID)
        _CUSTOM_DISTS[cid] = cd
        _BY_SOURCE[key] = cid
    return cid, cd


def get_custom_dist(cid: int) -> CustomDistribution:
    return _CUSTOM_DISTS[cid]


def grad_hess_host(cid: int):
    """Top-level callable factory for ``jax.pure_callback`` (must be
    picklable-by-identity across traces so the program cache hits)."""
    cd = _CUSTOM_DISTS[cid]

    def cb(F, y, w):
        return cd.grad_hess(F, y, w)

    return cb
