"""Structured incidents — rule trips with correlated context, in a ring.

Reference: H2O-3 operators diagnose a sick cloud from ``h2o logs download``
— one archive whose value is that every signal was captured *at the same
moment*. This module gives rule trips (:mod:`h2o3_tpu.utils.health`) the
same property live: when a health rule crosses its threshold, an
**incident** opens and auto-captures the correlated context *at trip
time* — the recent trace ids, the last-N log-ring lines, the memory
top-keys, the compute-table loop rows, and the tripping rule's recent
observed-value window — so the operator reads what the system looked like
when it happened, not whatever is left when a human shows up.

Semantics:

- **One open incident per rule.** A rule that keeps tripping sweep after
  sweep updates its open incident (``repeats`` + latest observed) instead
  of flooding the ring; when the rule stops tripping the incident resolves
  (``status: resolved``, ``resolved_ms``/``resolved_at`` stamped; an
  incident the remediation engine acted on names its ``action_id``).
- **Rising edges notify.** A listener registered with
  :meth:`IncidentLog.add_listener` fires once per incident OPEN (never on
  repeats) — the subscription seam the ops-plane remediation engine
  (:mod:`h2o3_tpu.ops_plane.remediate`) hangs off. Listeners run outside
  the ring lock and are fault-isolated.
- **Bounded.** The ring keeps the most recent ``H2O3TPU_INCIDENT_RING``
  records (default 64), oldest evicted first; ``h2o3_incidents_total
  {rule,subsystem}`` counts every OPEN over the process lifetime.
- **Compute-class trips can profile themselves.** With
  ``H2O3TPU_INCIDENT_PROFILE=1``, a compute-subsystem incident fires one
  single-flight PR 10 device-profiler capture in the background (a
  concurrent capture is skipped, never queued — the profiler runtime is
  process-global) and stamps the ``capture_id`` into the incident context.

Everything here is host-side stdlib; capture helpers are individually
fault-isolated — a broken registry can never turn an incident into a
crash of the sweep thread that reported it.
"""

from __future__ import annotations

import os
import threading
import time
import uuid

from h2o3_tpu.utils import lockwitness
from h2o3_tpu.utils import telemetry as _tm

#: every incident OPEN, by rule and subsystem (repeats do not re-count)
INCIDENTS_TOTAL = _tm.METRICS.counter(
    "h2o3_incidents", "health-rule incidents opened", ("rule", "subsystem"))

#: log-ring lines / trace summaries captured into an incident's context
CAPTURE_LOG_LINES = 30
CAPTURE_TRACES = 8


def ring_size_from_env(default: int = 64) -> int:
    try:
        return max(int(os.environ.get("H2O3TPU_INCIDENT_RING", "")
                       or default), 4)
    except ValueError:
        return default


def profile_on_incident() -> bool:
    """Opt-in: compute-class incidents fire a single-flight profiler
    capture (``H2O3TPU_INCIDENT_PROFILE=1``). Off by default — a capture
    costs a bounded ``jax.profiler.trace`` window, which an operator
    should choose, not inherit."""
    return os.environ.get("H2O3TPU_INCIDENT_PROFILE", "") == "1"


# -- context capture (each helper fault-isolated) ----------------------------

def _capture_traces() -> list:
    from h2o3_tpu.utils.tracing import TRACER
    return [{"trace_id": t["trace_id"], "name": t["name"],
             "dur_ms": round(t.get("dur_ns", 0) / 1e6, 3),
             "status": t.get("status")}
            for t in TRACER.list_traces()[:CAPTURE_TRACES]]


def _capture_logs() -> list:
    ring = _tm.install_log_ring()
    return ring.lines()[-CAPTURE_LOG_LINES:]


def _capture_memory() -> dict:
    from h2o3_tpu.utils.memory import MEMORY
    return {"top_keys": MEMORY.top_keys(5),
            "watermarks": MEMORY.watermarks}


def _capture_compute() -> dict:
    from h2o3_tpu.utils.costs import COSTS
    snap = COSTS.snapshot()
    return {"loops": snap["loops"],
            "recompile_events": snap["recompile_events"],
            "signature_count": snap["signature_count"]}


def _capture_flight_window(rule: str,
                           source_series: "str | None") -> "dict | None":
    """The ±window of the tripping series from the flight recorder: the
    rule's declared source series (trend rules) when it holds samples,
    else the rule's own recorded observed series (``health.rule.<rule>``).
    None — cleanly, never a crash — when the recorder is off
    (``H2O3TPU_FLIGHT_OFF=1``), not yet started, or holds no samples for
    either name: the point-sample ``series`` fallback stands alone."""
    from h2o3_tpu.utils.flight import FLIGHT
    win = None
    if source_series:
        win = FLIGHT.window(source_series)
    if win is None:
        win = FLIGHT.window(f"health.rule.{rule}")
    return win


def capture_context(rule: str, subsystem: str,
                    series: "list | None" = None,
                    source_series: "str | None" = None) -> dict:
    """The correlated context stamped into a new incident: what the
    observability pillars showed AT TRIP TIME. Every capture is
    individually fault-isolated (a failed one records its error string).
    ``flight_window`` carries the ±window of the tripping series from the
    flight recorder when one holds samples; incidents opened before the
    recorder starts (or with ``H2O3TPU_FLIGHT_OFF=1``) degrade to the
    point-sample ``series`` list — ``flight_window`` is then None."""
    ctx: dict = {"series": list(series or [])}
    for name, fn in (("traces", _capture_traces), ("logs", _capture_logs),
                     ("memory", _capture_memory),
                     ("compute", _capture_compute)):
        try:
            ctx[name] = fn()
        except Exception as e:   # noqa: BLE001 — capture must never raise
            ctx[name] = {"error": f"{type(e).__name__}: {e}"}
    try:
        ctx["flight_window"] = _capture_flight_window(rule, source_series)
    except Exception as e:   # noqa: BLE001 — capture must never raise
        ctx["flight_window"] = {"error": f"{type(e).__name__}: {e}"}
    return ctx


class IncidentLog:
    """Bounded ring of incident records, one open incident per rule
    (``GET /3/Incidents`` / ``GET /3/Incidents/{id}``)."""

    def __init__(self, capacity: int | None = None):
        self._lock = lockwitness.lock("utils.incidents.IncidentLog._lock")
        self._capacity = capacity if capacity is not None \
            else ring_size_from_env()
        self._ring: "dict[str, dict]" = {}          # id -> record
        self._order: list[str] = []                 # oldest first
        self._open_by_rule: dict[str, str] = {}     # rule -> incident id
        self._opened_total = 0
        self._listeners: list = []                  # rising-edge subscribers

    # -- subscriptions -------------------------------------------------------

    def add_listener(self, fn) -> None:
        """Subscribe ``fn(record_snapshot, log)`` to incident OPENs (rising
        edges only — repeat trips fold into the open record silently).
        Listeners run on the opener's thread, outside the ring lock, after
        the trip-time context is stamped; a raising listener is swallowed
        (remediation must never crash the health sweep)."""
        with self._lock:
            if fn not in self._listeners:
                self._listeners.append(fn)

    def remove_listener(self, fn) -> None:
        with self._lock:
            if fn in self._listeners:
                self._listeners.remove(fn)

    # -- lifecycle -----------------------------------------------------------

    def open(self, rule: str, subsystem: str, severity: str, message: str,
             observed, threshold, series=None,
             source_series: "str | None" = None) -> str:
        """Open (or update) the incident for ``rule``. Returns its id.
        A rule with an incident already open updates it in place —
        ``repeats`` increments, ``observed``/``last_seen_ms`` refresh —
        so a storm of identical trips is one record, not a flood."""
        now_ms = int(time.time() * 1000)
        with self._lock:
            open_id = self._open_by_rule.get(rule)
            if open_id is not None and open_id in self._ring:
                rec = self._ring[open_id]
                rec["repeats"] += 1
                rec["observed"] = observed
                # message/threshold track the LATEST trip too — a record
                # showing observed=50 with a message still claiming the
                # original "observed 3" reads as contradictory numbers
                rec["message"] = message
                rec["threshold"] = threshold
                rec["last_seen_ms"] = now_ms
                return open_id
            iid = f"inc_{uuid.uuid4().hex[:10]}"
            rec = {"id": iid, "rule": rule, "subsystem": subsystem,
                   "severity": severity, "status": "open",
                   "message": message, "observed": observed,
                   "threshold": threshold, "repeats": 1,
                   "opened_ms": now_ms, "last_seen_ms": now_ms,
                   "resolved_ms": None, "resolved_at": None,
                   "action_id": None, "context": None}
            self._ring[iid] = rec
            self._order.append(iid)
            self._open_by_rule[rule] = iid
            self._opened_total += 1
            while len(self._order) > self._capacity:
                # evict the oldest RESOLVED record first: evicting a
                # still-open incident would make its next trip mint a new
                # id and re-count h2o3_incidents_total mid-episode —
                # breaking the one-open-per-rule / repeats-fold-in
                # contract. Only a ring made ENTIRELY of open incidents
                # (more simultaneously-open rules than capacity) falls
                # back to evicting the oldest open one.
                old = next((i for i in self._order
                            if self._ring[i]["status"] != "open"),
                           self._order[0])
                self._order.remove(old)
                dead = self._ring.pop(old, None)
                if dead is not None and \
                        self._open_by_rule.get(dead["rule"]) == old:
                    del self._open_by_rule[dead["rule"]]
        INCIDENTS_TOTAL.labels(rule=rule, subsystem=subsystem).inc()
        # context capture OUTSIDE the lock: the helpers read other
        # registries (their own locks) — holding ours across them invites
        # ordering trouble for zero benefit
        ctx = capture_context(rule, subsystem, series,
                              source_series=source_series)
        with self._lock:
            if iid in self._ring:
                self._ring[iid]["context"] = ctx
            snapshot = dict(self._ring.get(iid) or rec)
            listeners = list(self._listeners)
        # rising-edge notification AFTER context capture, so a remediation
        # listener reads the same trip-time picture an operator would;
        # each listener fault-isolated — acting must never break reporting
        for fn in listeners:
            try:
                fn(snapshot, self)
            except Exception:   # noqa: BLE001 — subscriber bug stays local
                pass
        if subsystem == "compute" and profile_on_incident():
            self._fire_profile(iid)
        return iid

    def resolve(self, rule: str) -> None:
        """The rule stopped tripping: close its open incident (no-op when
        none is open — resolution is edge-triggered by the evaluator)."""
        with self._lock:
            iid = self._open_by_rule.pop(rule, None)
            rec = self._ring.get(iid) if iid else None
            if rec is not None:
                now = time.time()
                rec["status"] = "resolved"
                rec["resolved_ms"] = int(now * 1000)
                rec["resolved_at"] = time.strftime(
                    "%Y-%m-%dT%H:%M:%SZ", time.gmtime(now))

    def annotate_action(self, incident_id: str, action_id: str) -> None:
        """Stamp the remediation ``action_id`` onto its trigger incident —
        a resolved-by-action incident names what touched it (satellite:
        the /3/Incidents record answers "did the machine do this?")."""
        with self._lock:
            rec = self._ring.get(incident_id)
            if rec is not None:
                rec["action_id"] = action_id
                if isinstance(rec.get("context"), dict):
                    rec["context"]["remediation_action"] = action_id

    def _fire_profile(self, incident_id: str) -> None:
        """Single-flight background profiler capture for a compute-class
        incident; a concurrent capture (409-class CaptureBusy) is skipped,
        and the capture id lands in the incident context when done."""
        def run():
            try:
                from h2o3_tpu.utils.profiling import PROFILER, CaptureBusy
                try:
                    rec = PROFILER.capture(duration_ms=200)
                except CaptureBusy:
                    return
            except Exception:   # noqa: BLE001 — best-effort enrichment
                return
            with self._lock:
                inc = self._ring.get(incident_id)
                if inc is not None and isinstance(inc.get("context"), dict):
                    inc["context"]["profiler_capture"] = rec.get("capture_id")

        threading.Thread(target=run, daemon=True,
                         name="h2o3-incident-profile").start()

    # -- views ---------------------------------------------------------------

    def list(self, state: str | None = None) -> list[dict]:
        """Summaries, newest first (context omitted — fetch one by id).
        ``state`` filters to ``"open"`` or ``"resolved"`` records."""
        if state not in (None, "open", "resolved"):
            raise ValueError(f"state must be open|resolved, got {state!r}")
        with self._lock:
            out = []
            for iid in reversed(self._order):
                rec = self._ring[iid]
                if state is not None and rec["status"] != state:
                    continue
                out.append({k: rec.get(k) for k in
                            ("id", "rule", "subsystem", "severity", "status",
                             "message", "observed", "threshold", "repeats",
                             "opened_ms", "last_seen_ms", "resolved_ms",
                             "resolved_at", "action_id")})
            return out

    def get(self, incident_id: str) -> dict:
        with self._lock:
            rec = self._ring.get(incident_id)
            if rec is None:
                raise KeyError(f"no incident {incident_id!r} (ring keeps "
                               f"the last {self._capacity})")
            return {**rec, "context": dict(rec["context"] or {})}

    def export(self) -> list[dict]:
        """Full records (context included), newest first — the bundle's
        ``incidents.json``."""
        with self._lock:
            return [dict(self._ring[iid]) for iid in reversed(self._order)]

    def opened_total(self) -> int:
        """Monotonic count of incidents OPENED this process — the bench
        hollow-watchdog guard windows on its delta."""
        with self._lock:
            return self._opened_total

    def open_rules(self) -> list[str]:
        with self._lock:
            return sorted(self._open_by_rule)

    def reset(self) -> None:
        """Drop everything (tests/bench isolation only)."""
        with self._lock:
            self._ring.clear()
            self._order.clear()
            self._open_by_rule.clear()
            self._opened_total = 0


#: the process-wide incident ring (``GET /3/Incidents``)
INCIDENTS = IncidentLog()
