"""Runtime lock-order witness — the dynamic half of graftlint's DLK.

Opt-in (``H2O3TPU_LOCKWITNESS=1``): the factories below return plain
``threading`` primitives when the witness is unarmed — zero overhead, no
wrapper in the hot path — and instrumented wrappers when armed.  Armed
wrappers record, per thread, the actual acquisition order of every
witnessed lock:

- **dynamic edges** — each (held, newly-acquired) pair actually executed;
- **inversions** — both orientations of a pair observed (the runtime
  shadow of DLK001);
- **held-by-thread** — live held-lock sets, fed to the blackbox thread
  dump so a wedge post-mortem shows who holds what.

The static analyzer (``h2o3_tpu.tools.lockorder``) and this module share
one identity scheme — the literal name passed to a factory is the lock's
identity in both worlds — so a witnessed run can cross-validate the
static graph: any dynamic edge absent from it means the analyzer's call
graph has gone stale (the self-validation gate in tests asserts zero).

Arming is decided at *creation* time: module-level singletons pick it up
from the environment at import, tests arm explicitly before constructing.
The env var is read per call, never cached at import (ENV001).
"""

from __future__ import annotations

import os
import threading
from typing import Any

__all__ = ["lock", "rlock", "condition", "armed", "WITNESS", "LockWitness"]


def armed() -> bool:
    """Whether locks created *now* would be witnessed."""
    return os.environ.get("H2O3TPU_LOCKWITNESS", "") == "1"


class LockWitness:
    """Process-global recorder of witnessed acquisition order."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        # (held, acquired) -> observation count
        self._edge_counts: dict[tuple[str, str], int] = {}
        # per-thread held stacks (ident -> names, reentrant names repeat)
        self._held: dict[int, list[str]] = {}
        self._thread_names: dict[int, str] = {}
        self._acquisitions = 0

    # -- recording (called from wrappers, armed runs only) -------------------

    def record_acquire(self, name: str) -> None:
        ident = threading.get_ident()
        with self._mu:
            stack = self._held.setdefault(ident, [])
            self._thread_names[ident] = threading.current_thread().name
            self._acquisitions += 1
            if name not in stack:  # reentrant re-acquire orders nothing
                for h in dict.fromkeys(stack):
                    e = (h, name)
                    self._edge_counts[e] = self._edge_counts.get(e, 0) + 1
            stack.append(name)

    def record_release(self, name: str) -> None:
        ident = threading.get_ident()
        with self._mu:
            stack = self._held.get(ident)
            if not stack:
                return
            for i in range(len(stack) - 1, -1, -1):
                if stack[i] == name:
                    del stack[i]
                    break
            if not stack:
                del self._held[ident]

    # -- inspection ----------------------------------------------------------

    def edges(self) -> dict[tuple[str, str], int]:
        with self._mu:
            return dict(self._edge_counts)

    def inversions(self) -> list[tuple[str, str]]:
        """Pairs observed in BOTH orders — a live ABBA hazard. Each pair
        once, smaller name first."""
        edges = self.edges()
        return sorted({(min(a, b), max(a, b)) for (a, b) in edges
                       if (b, a) in edges})

    def held_by_thread(self) -> dict[int, list[str]]:
        with self._mu:
            return {i: list(dict.fromkeys(s))
                    for i, s in self._held.items() if s}

    def acquisitions(self) -> int:
        with self._mu:
            return self._acquisitions

    def report(self) -> dict[str, Any]:
        """JSON-ready summary for the self-validation gate."""
        edges = self.edges()
        return {
            "acquisitions": self.acquisitions(),
            "edges": sorted(f"{a}->{b}" for (a, b) in edges),
            "edge_counts": {f"{a}->{b}": n for (a, b) in sorted(edges)
                            for n in [edges[(a, b)]]},
            "inversions": [f"{a}<->{b}" for a, b in self.inversions()],
        }

    def validate(self, static_edges: set[tuple[str, str]],
                 static_locks: set[str]) -> dict[str, list[str]]:
        """Diff the witnessed run against the static graph: dynamic edges
        or lock names the analyzer doesn't know mean its call-graph (or
        the identity contract) has gone stale."""
        edges = self.edges()
        missing = sorted(f"{a}->{b}" for (a, b) in edges
                         if (a, b) not in static_edges)
        unknown = sorted({n for e in edges for n in e} - static_locks)
        return {"missing_from_static": missing, "unknown_locks": unknown}

    def reset(self) -> None:
        with self._mu:
            self._edge_counts.clear()
            self._held.clear()
            self._thread_names.clear()
            self._acquisitions = 0


WITNESS = LockWitness()


class _WitnessedLock:
    """Wrapper over Lock/RLock: records acquire/release order. Matches the
    ``threading`` context-manager protocol (``__enter__`` returns the
    ``acquire`` result, like the C implementation)."""

    __slots__ = ("_inner", "name")

    def __init__(self, inner, name: str) -> None:
        self._inner = inner
        self.name = name

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            WITNESS.record_acquire(self.name)
        return got

    def release(self) -> None:
        self._inner.release()
        WITNESS.record_release(self.name)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<witnessed {self._inner!r} name={self.name!r}>"


class _WitnessedCondition:
    """Wrapper over Condition. ``wait`` keeps the lock in the witnessed
    held set — the waiter still *logically* owns it (matches the static
    model, and a wedge dump should show the waiter as the holder)."""

    __slots__ = ("_inner", "name")

    def __init__(self, inner: threading.Condition, name: str) -> None:
        self._inner = inner
        self.name = name

    def acquire(self, *args) -> bool:
        got = self._inner.acquire(*args)
        if got:
            WITNESS.record_acquire(self.name)
        return got

    def release(self) -> None:
        self._inner.release()
        WITNESS.record_release(self.name)

    def wait(self, timeout: float | None = None) -> bool:
        return self._inner.wait(timeout)

    def wait_for(self, predicate, timeout: float | None = None):
        return self._inner.wait_for(predicate, timeout)

    def notify(self, n: int = 1) -> None:
        self._inner.notify(n)

    def notify_all(self) -> None:
        self._inner.notify_all()

    def __enter__(self):
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<witnessed {self._inner!r} name={self.name!r}>"


# -- factories ---------------------------------------------------------------
#
# The name argument MUST be the lock's static identity:
# ``<module>.<Class>.<attr>`` / ``<module>.<NAME>`` relative to the
# package root (see tools/lockorder.py) — the analyzer trusts the literal.

def lock(name: str):
    """A ``threading.Lock`` — witnessed when the witness is armed."""
    inner = threading.Lock()
    return _WitnessedLock(inner, name) if armed() else inner


def rlock(name: str):
    """A ``threading.RLock`` — witnessed when the witness is armed."""
    inner = threading.RLock()
    return _WitnessedLock(inner, name) if armed() else inner


def condition(name: str, lock: Any = None):
    """A ``threading.Condition`` (optionally over an existing raw lock) —
    witnessed when the witness is armed. Acquisition goes through the
    condition, so the condition's name is the identity."""
    inner = threading.Condition(lock)
    return _WitnessedCondition(inner, name) if armed() else inner
