"""Flight recorder — retained metric time series in fixed-memory rings.

Reference: an aircraft flight recorder answers the question the live
gauges cannot — *what happened before*. The four observability pillars
(metrics PR 2, traces PR 4, memory PR 5, compute PR 10) and the ops
plane (PR 15/16) are all instantaneous: a slow RSS leak, a p99 creeping
toward its SLO, an MFU slide across a training run, or a process that
wedges leaves no record to diagnose. This module retains one: a
background sampler snapshots every registered ``h2o3_*`` metric family
plus a handful of derived series (host RSS straight from ``/proc``, the
last health verdict, open-incident count, p99/SLO ratio, minimum rated
MFU, total sheds) into per-series ring buffers with two downsampling
tiers —

- **tier 0 (raw)**: the last ``H2O3TPU_FLIGHT_RAW_SAMPLES`` (default
  300) ``(t, value)`` samples at the sample interval
  (``H2O3TPU_FLIGHT_INTERVAL_SECS``, default 1s, resolved at
  :meth:`FlightRecorder.start` per the ENV001 lesson);
- **tier 1 (rollup)**: ``H2O3TPU_FLIGHT_ROLLUP_SAMPLES`` (default 480)
  windows of ``H2O3TPU_FLIGHT_ROLLUP_SECS`` (default 30s) each carrying
  ``min`` / ``max`` / ``mean`` / ``last`` / ``count`` — four hours of
  history at the defaults, in bounded memory.

Memory IS bounded: at most ``H2O3TPU_FLIGHT_MAX_SERIES`` (default 512)
distinct series are retained; overflow series are counted and dropped,
never grown. This bound is why metric label values must stay bounded
(graftlint CRD001, docs/STATIC_ANALYSIS.md) — an unbounded label (a DKV
key, a file path, a raw tenant string) would evict real series.

Consumers:

- ``GET /3/TimeSeries?name=&labels=&since=`` (+ Python
  ``client.timeseries()``, R ``h2o.timeseries``) serves the record live;
- trend rules (``utils/health.py``) compute sustained-slope detectors
  over :meth:`FlightRecorder.values`;
- incident context (``utils/incidents.py``) stamps the ±window of the
  tripping series via :meth:`FlightRecorder.window`;
- the black-box post-mortem (``utils/blackbox.py``) and the diagnostics
  bundle ship :meth:`FlightRecorder.export` as ``timeseries.json``.

``H2O3TPU_FLIGHT_OFF=1`` disables everything (sampler, passive ingest);
the bench's overhead comparator. The recorder never imports REST and the
sampler never raises out of its loop — a sick registry is a skipped
sample, not a dead recorder.
"""

from __future__ import annotations

import collections
import logging
import os
import sys
import threading
import time

from h2o3_tpu.utils import lockwitness
from h2o3_tpu.utils import telemetry as _tm

_LOG = logging.getLogger("h2o3_tpu")

#: wall seconds per sampler tick — the observe-the-observers instrument
#: (a slow tick means a registry read is dragging; docs/OBSERVABILITY.md)
FLIGHT_SAMPLE_SECONDS = _tm.METRICS.histogram(
    "h2o3_flight_sample_seconds",
    "wall seconds per flight-recorder sampler tick")


def flight_off() -> bool:
    return os.environ.get("H2O3TPU_FLIGHT_OFF", "") == "1"


def interval_from_env(default: float = 1.0) -> float:
    """Sampler interval seconds (``H2O3TPU_FLIGHT_INTERVAL_SECS``) —
    bounded below so a typo can never busy-spin the sampler."""
    try:
        return max(float(os.environ.get("H2O3TPU_FLIGHT_INTERVAL_SECS", "")
                         or default), 0.05)
    except ValueError:
        return default


def _env_int(name: str, default: int, lo: int) -> int:
    try:
        return max(int(os.environ.get(name, "") or default), lo)
    except ValueError:
        return default


def _env_float(name: str, default: float, lo: float) -> float:
    try:
        return max(float(os.environ.get(name, "") or default), lo)
    except ValueError:
        return default


# -- derived samplers (module-level seams: tests monkeypatch these) ----------

def _derived_rss() -> float:
    """Host RSS straight from ``/proc`` — NOT the ``h2o3_host_rss_bytes``
    gauge, which only moves when the MemoryMeter samples; a leak between
    meter sweeps must still land in the record."""
    from h2o3_tpu.utils.memory import host_stats
    return float(host_stats()["rss_bytes"])


def _derived_health_status() -> "float | None":
    """Rank of the LAST published verdict (0 healthy / 1 degraded /
    2 unhealthy) — never forces an inline evaluation; a recorder tick
    must not become a health sweep."""
    from h2o3_tpu.utils.health import _RANK, HEALTH
    last = HEALTH.last_verdict()
    if last is None:
        return None
    return float(_RANK.get(last.get("status"), 0))


def _derived_open_incidents() -> float:
    from h2o3_tpu.utils.incidents import INCIDENTS
    return float(len(INCIDENTS.open_rules()))


def _derived_p99_ratio() -> "float | None":
    """Worst resident p99/SLO ratio — only when serving is loaded (the
    sampler must not be the thing that imports the stack)."""
    svc = sys.modules.get("h2o3_tpu.serving.service")
    if svc is None:
        return None
    ratios = []
    for row in svc.SCORING.stats().get("resident") or ():
        slo = row.get("slo") or {}
        target, p99 = slo.get("target_ms"), slo.get("p99_ms")
        if target and p99 is not None:
            ratios.append(p99 / target)
    return round(max(ratios), 6) if ratios else None


def _derived_mfu_min() -> "float | None":
    """Minimum utilization across rated loops (≥3 samples) — the MFU
    decline trend rule's input."""
    costs = sys.modules.get("h2o3_tpu.utils.costs")
    if costs is None:
        return None
    utils = [st.get("utilization") for st in costs.COSTS.loops().values()
             if st.get("utilization") is not None
             and st.get("samples", 0) >= 3]
    return round(min(utils), 6) if utils else None


def _derived_shed_total() -> float:
    """All-label shed count — the shed-acceleration trend rule's input."""
    return float(sum(c.value for _, c in _tm.SCORE_SHED.children()))


#: name -> zero-arg sampler; each fault-isolated per tick, None = skip
DERIVED_SERIES = {
    "derived.host_rss_bytes": _derived_rss,
    "derived.health_status": _derived_health_status,
    "derived.open_incidents": _derived_open_incidents,
    "derived.p99_slo_ratio": _derived_p99_ratio,
    "derived.mfu_min": _derived_mfu_min,
    "derived.score_shed_total": _derived_shed_total,
}


class _Series:
    """One retained series: a raw ring of ``(t, value)`` plus the rollup
    ring and its pending accumulation window. Mutated only under the
    owning recorder's lock."""

    __slots__ = ("name", "labels", "raw", "rollup", "pend")

    def __init__(self, name: str, labels: dict, raw_len: int,
                 rollup_len: int):
        self.name = name
        self.labels = dict(labels)
        self.raw = collections.deque(maxlen=raw_len)
        self.rollup = collections.deque(maxlen=rollup_len)
        self.pend: "dict | None" = None

    def append(self, t: float, value: float, rollup_secs: float) -> None:
        self.raw.append((t, value))
        p = self.pend
        if p is not None and t - p["t"] >= rollup_secs:
            self.rollup.append({"t": p["t"], "min": p["min"],
                                "max": p["max"],
                                "mean": p["sum"] / p["count"],
                                "last": p["last"], "count": p["count"]})
            p = None
        if p is None:
            self.pend = {"t": t, "min": value, "max": value, "sum": value,
                         "count": 1, "last": value}
        else:
            p["min"] = min(p["min"], value)
            p["max"] = max(p["max"], value)
            p["sum"] += value
            p["count"] += 1
            p["last"] = value

    def view(self, since: "float | None" = None,
             last_n: "int | None" = None) -> dict:
        samples = [(t, v) for t, v in self.raw
                   if since is None or t >= since]
        if last_n is not None:
            samples = samples[-last_n:]
        rollup = [r for r in self.rollup
                  if since is None or r["t"] >= since]
        return {"name": self.name, "labels": dict(self.labels),
                "samples": [[round(t, 3), v] for t, v in samples],
                "rollup": rollup}


def _series_key(name: str, labels: "dict | None") -> str:
    if not labels:
        return name
    return name + "|" + ",".join(f"{k}={labels[k]}" for k in sorted(labels))


class FlightRecorder:
    """The always-on recorder: a bounded-interval sampler thread feeding
    fixed-memory two-tier rings, plus a passive :meth:`ingest` seam for
    out-of-band series (the health evaluator pushes every rule's observed
    value each sweep). Query with :meth:`query` (REST), :meth:`values`
    (trend rules), :meth:`window` (incident context), :meth:`export`
    (bundle / post-mortem)."""

    def __init__(self, interval_s: "float | None" = None,
                 raw_len: "int | None" = None,
                 rollup_len: "int | None" = None,
                 rollup_secs: "float | None" = None,
                 max_series: "int | None" = None):
        self._interval_explicit = interval_s is not None
        self._lock = lockwitness.lock("utils.flight.FlightRecorder._lock")
        self.interval_s = (interval_s if interval_s is not None
                           else interval_from_env())
        self._raw_len = raw_len if raw_len is not None else \
            _env_int("H2O3TPU_FLIGHT_RAW_SAMPLES", 300, 16)
        self._rollup_len = rollup_len if rollup_len is not None else \
            _env_int("H2O3TPU_FLIGHT_ROLLUP_SAMPLES", 480, 16)
        self.rollup_secs = rollup_secs if rollup_secs is not None else \
            _env_float("H2O3TPU_FLIGHT_ROLLUP_SECS", 30.0, 0.05)
        self._max_series = max_series if max_series is not None else \
            _env_int("H2O3TPU_FLIGHT_MAX_SERIES", 512, 8)
        self._series: "dict[str, _Series]" = {}
        self._dropped_series = 0
        self._ticks = 0
        self._samples_total = 0
        self._stop = threading.Event()
        self._thread: "threading.Thread | None" = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> bool:
        """Start the sampler thread (idempotent; False when already
        running or disabled via ``H2O3TPU_FLIGHT_OFF=1``). Env knobs are
        resolved HERE, not at import (the ENV001 lesson)."""
        if flight_off():
            return False
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return False
            if not self._interval_explicit:
                self.interval_s = interval_from_env()
            self.rollup_secs = _env_float(
                "H2O3TPU_FLIGHT_ROLLUP_SECS", self.rollup_secs, 0.05)
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="h2o3-flight-sample")
            self._thread.start()
            return True

    def stop(self, timeout: float = 5.0) -> None:
        with self._lock:
            thread = self._thread
            self._thread = None
            # set inside the lock: set-after-release races a concurrent
            # start() (the health evaluator's stop() lesson)
            self._stop.set()
        if thread is not None:
            thread.join(timeout=timeout)

    def running(self) -> bool:
        with self._lock:
            return self._thread is not None and self._thread.is_alive()

    def _run(self) -> None:
        # bounded wait (WTX001): stop() wakes it, the interval bounds it
        while not self._stop.wait(self.interval_s):
            with self._lock:
                if self._thread is not threading.current_thread():
                    return      # superseded by a stop()+start() cycle
            try:
                self.sample_once()
            except Exception:   # noqa: BLE001 — the recorder must outlive
                _LOG.exception("flight sample failed")  # what it records

    # -- sampling ------------------------------------------------------------

    def sample_once(self, now: "float | None" = None) -> int:
        """One sampler tick: snapshot every metric family (buckets
        excluded — the rollup tier IS the downsampling story) plus the
        derived series. Returns the number of samples recorded."""
        if flight_off():
            return 0
        t0 = time.perf_counter()
        t = time.time() if now is None else now
        wrote = 0
        try:
            rows = _tm.METRICS.snapshot(include_buckets=False)
        except Exception:   # noqa: BLE001 — a sick registry skips a tick
            rows = []
        with self._lock:
            for row in rows:
                if self._ingest_locked(row["name"], row["value"],
                                       row["labels"], t):
                    wrote += 1
            for name, fn in DERIVED_SERIES.items():
                try:
                    value = fn()
                except Exception:   # noqa: BLE001 — one sick source must
                    continue        # not starve the other series
                if value is None:
                    continue
                if self._ingest_locked(name, float(value), None, t):
                    wrote += 1
            self._ticks += 1
        FLIGHT_SAMPLE_SECONDS.observe(time.perf_counter() - t0)
        return wrote

    def ingest(self, name: str, value, labels: "dict | None" = None,
               now: "float | None" = None) -> bool:
        """Record one out-of-band sample (the health evaluator pushes
        every rule's observed value under ``health.rule.<name>`` each
        sweep). Passive — works whether or not the sampler thread runs;
        a no-op under ``H2O3TPU_FLIGHT_OFF=1`` or for non-numeric
        values."""
        if flight_off() or value is None:
            return False
        try:
            value = float(value)
        except (TypeError, ValueError):
            return False
        t = time.time() if now is None else now
        with self._lock:
            return self._ingest_locked(name, value, labels, t)

    def _ingest_locked(self, name: str, value: float,
                       labels: "dict | None", t: float) -> bool:
        # graftlint: ok(_locked suffix: every caller holds self._lock)
        key = _series_key(name, labels)
        ser = self._series.get(key)
        if ser is None:
            if len(self._series) >= self._max_series:
                # the fixed-memory contract: overflow series are counted
                # and DROPPED, never grown (see CRD001 — unbounded label
                # values are what makes this branch fire)
                self._dropped_series += 1  # graftlint: ok(caller holds self._lock — _locked suffix contract)
                return False
            ser = _Series(name, labels or {}, self._raw_len,
                          self._rollup_len)
            self._series[key] = ser  # graftlint: ok(caller holds self._lock — _locked suffix contract)
        ser.append(t, value, self.rollup_secs)
        self._samples_total += 1  # graftlint: ok(caller holds self._lock — _locked suffix contract)
        return True

    # -- queries -------------------------------------------------------------

    def query(self, name: "str | None" = None,
              labels: "dict | None" = None,
              since: "float | None" = None) -> list[dict]:
        """Matching series views, sorted by (name, labels). ``name``
        matches exactly or as a prefix; ``labels`` must be a subset of a
        series' labels; ``since`` (epoch seconds) filters samples."""
        with self._lock:
            sers = list(self._series.values())
        out = []
        for ser in sers:
            if name is not None and ser.name != name \
                    and not ser.name.startswith(name):
                continue
            if labels and any(ser.labels.get(k) != v
                              for k, v in labels.items()):
                continue
            out.append(ser.view(since=since))
        out.sort(key=lambda s: (s["name"], sorted(s["labels"].items())))
        return out

    def values(self, name: str, labels: "dict | None" = None,
               last_n: "int | None" = None) -> list[float]:
        """The last-N raw values of ONE series (exact name + labels) —
        what trend probes consume. Empty when the series doesn't exist
        (recorder off / not started / never sampled): a trend probe must
        degrade to not-applicable, never crash."""
        with self._lock:
            ser = self._series.get(_series_key(name, labels))
            if ser is None:
                return []
            vals = [v for _, v in ser.raw]
        return vals[-last_n:] if last_n is not None else vals

    def window(self, name: str, labels: "dict | None" = None,
               last_n: "int | None" = None) -> "dict | None":
        """The ±window an incident context captures: the tripping
        series' raw tail plus its rollup history. None when the series
        holds no samples — callers keep their point-sample fallback."""
        with self._lock:
            ser = self._series.get(_series_key(name, labels))
            if ser is None or not ser.raw:
                return None
            view = ser.view(last_n=last_n)
        view["interval_s"] = self.interval_s
        view["rollup_secs"] = self.rollup_secs
        return view

    def series_names(self) -> list[str]:
        with self._lock:
            return sorted({s.name for s in self._series.values()})

    def stats(self) -> dict:
        with self._lock:
            return {"running": (self._thread is not None
                                and self._thread.is_alive()),
                    "off": flight_off(),
                    "interval_s": self.interval_s,
                    "rollup_secs": self.rollup_secs,
                    "raw_samples": self._raw_len,
                    "rollup_samples": self._rollup_len,
                    "max_series": self._max_series,
                    "series": len(self._series),
                    "samples_total": self._samples_total,
                    "dropped_series": self._dropped_series,
                    "ticks": self._ticks}

    def export(self) -> dict:
        """The full record — the bundle's ``timeseries.json`` and the
        black-box post-mortem's ``flight.json``. Bounded by the rings."""
        return {"stats": self.stats(), "series": self.query()}

    def ticks(self) -> int:
        """Sampler ticks taken (the bench's hollow-sampler proof)."""
        with self._lock:
            return self._ticks

    def reset(self) -> None:
        """Drop every series and counter (tests/bench isolation only)."""
        with self._lock:
            self._series.clear()
            self._dropped_series = 0
            self._ticks = 0
            self._samples_total = 0


#: the process-wide recorder (started by ``H2OServer.start``; trend rules
#: and incident context read it wherever it is in its lifecycle)
FLIGHT = FlightRecorder()
