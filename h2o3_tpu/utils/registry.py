"""Keyed object registry — the single-controller stand-in for the reference DKV.

Reference: ``water/DKV.java`` + ``water/Key.java`` — a cluster-wide K/V store
where every key hashes to a home node, non-home nodes cache values, and puts
invalidate replicas over RPC. In the TPU design there is exactly one controller
process per job (JAX's multi-controller SPMD runs the *same* program on every
host, so global metadata like frames/models/jobs needs no replication protocol
— device data is already resident in HBM, addressed by ``jax.Array`` sharding).
What remains of DKV is a process-local name → object registry used by the REST
layer and the Python client to address frames/models/jobs by key.
"""

from __future__ import annotations

import threading
from typing import Any, Iterator


class KeyedStore:
    def __init__(self):
        self._lock = threading.RLock()
        self._store: dict[str, Any] = {}

    def put(self, key: str | None, value: Any) -> str | None:
        if key is None:
            return None
        with self._lock:
            self._store[key] = value
        return key

    def get(self, key: str, default: Any = None) -> Any:
        with self._lock:
            return self._store.get(key, default)

    def __getitem__(self, key: str) -> Any:
        with self._lock:
            return self._store[key]

    def remove(self, key: str) -> Any:
        with self._lock:
            return self._store.pop(key, None)

    def keys(self) -> list[str]:
        with self._lock:
            return list(self._store.keys())

    def __contains__(self, key: str) -> bool:
        with self._lock:
            return key in self._store

    def __iter__(self) -> Iterator[str]:
        return iter(self.keys())

    def clear(self) -> None:
        with self._lock:
            self._store.clear()


# Global registry (reference: the DKV singleton).
DKV = KeyedStore()
