"""Keyed object registry — the single-controller stand-in for the reference DKV.

Reference: ``water/DKV.java`` + ``water/Key.java`` — a cluster-wide K/V store
where every key hashes to a home node, non-home nodes cache values, and puts
invalidate replicas over RPC. In the TPU design there is exactly one controller
process per job (JAX's multi-controller SPMD runs the *same* program on every
host, so global metadata like frames/models/jobs needs no replication protocol
— device data is already resident in HBM, addressed by ``jax.Array`` sharding).
What remains of DKV is a process-local name → object registry used by the REST
layer and the Python client to address frames/models/jobs by key.
"""

from __future__ import annotations

import threading
from typing import Any, Iterator


class KeyedStore:
    def __init__(self):
        self._lock = threading.RLock()
        self._store: dict[str, Any] = {}

    def put(self, key: str | None, value: Any) -> str | None:
        if key is None:
            return None
        with self._lock:
            self._store[key] = value
        if type(value).__name__ == "Frame":
            # Cleaner hook (reference: Cleaner LRU sweep on heap pressure);
            # no-op unless a budget is enabled
            from h2o3_tpu.utils.cleaner import CLEANER
            CLEANER.touch(key)
            CLEANER.sweep(protect=key)
        return key

    def _resolve(self, key: str, value: Any) -> Any:
        if value is None:
            return value
        tname = type(value).__name__
        if tname == "SwappedFrame":
            from h2o3_tpu.utils.cleaner import CLEANER
            return CLEANER.resolve(key, value)
        if tname == "Frame":
            from h2o3_tpu.utils.cleaner import CLEANER
            if CLEANER.budget is not None:
                CLEANER.touch(key)
        return value

    def get(self, key: str, default: Any = None) -> Any:
        with self._lock:
            v = self._store.get(key, default)
        return self._resolve(key, v)

    def __getitem__(self, key: str) -> Any:
        with self._lock:
            v = self._store[key]
        return self._resolve(key, v)

    def remove(self, key: str) -> Any:
        with self._lock:
            v = self._store.pop(key, None)
        if type(v).__name__ == "SwappedFrame":
            import contextlib
            import os
            from h2o3_tpu.utils.cleaner import CLEANER
            with contextlib.suppress(OSError):
                os.remove(v.path)
            CLEANER._touch.pop(key, None)
            return None
        from h2o3_tpu.utils.cleaner import CLEANER
        CLEANER._touch.pop(key, None)
        return v

    def keys(self) -> list[str]:
        with self._lock:
            return list(self._store.keys())

    def raw_items(self) -> list[tuple[str, Any]]:
        """Snapshot WITHOUT resolving spilled stubs — listings must not
        re-inflate swapped frames just to read their metadata."""
        with self._lock:
            return list(self._store.items())

    def __contains__(self, key: str) -> bool:
        with self._lock:
            return key in self._store

    def __iter__(self) -> Iterator[str]:
        return iter(self.keys())

    def clear(self) -> None:
        with self._lock:
            items = list(self._store.items())
            self._store.clear()
        import contextlib
        import os
        for _k, v in items:
            if type(v).__name__ == "SwappedFrame":
                with contextlib.suppress(OSError):
                    os.remove(v.path)
        from h2o3_tpu.utils.cleaner import CLEANER
        CLEANER._touch.clear()


# Global registry (reference: the DKV singleton).
DKV = KeyedStore()
