"""Keyed object registry — the single-controller stand-in for the reference DKV.

Reference: ``water/DKV.java`` + ``water/Key.java`` — a cluster-wide K/V store
where every key hashes to a home node, non-home nodes cache values, and puts
invalidate replicas over RPC. In the TPU design there is exactly one controller
process per job (JAX's multi-controller SPMD runs the *same* program on every
host, so global metadata like frames/models/jobs needs no replication protocol
— device data is already resident in HBM, addressed by ``jax.Array`` sharding).
What remains of DKV is a process-local name → object registry used by the REST
layer and the Python client to address frames/models/jobs by key.
"""

from __future__ import annotations

import contextlib
import threading

from typing import Any, Iterator

from h2o3_tpu.utils import lockwitness
from h2o3_tpu.utils import telemetry as _tm


def _tenancy():
    """The ops-plane tenancy module ONLY if already imported — untagged
    processes must not pay a multi-tenancy import on the DKV hot path."""
    import sys
    return sys.modules.get("h2o3_tpu.ops_plane.tenancy")


class KeyedStore:
    def __init__(self):
        self._lock = lockwitness.rlock("utils.registry.KeyedStore._lock")
        self._store: dict[str, Any] = {}

    def put(self, key: str | None, value: Any) -> str | None:
        if key is None:
            return None
        # per-key byte accounting (reference: MemoryManager metering the
        # K/V store) — registered INSIDE the store lock so a racing
        # remove of the same key cannot leave the meter (and the
        # h2o3_dkv_bytes gauges) counting a key the store no longer holds.
        # Lock order store→meter is acyclic: the meter never touches the
        # store while holding its own lock.
        from h2o3_tpu.utils.memory import MEMORY
        with self._lock:
            old = self._store.get(key)
            self._store[key] = value
            n = len(self._store)
            MEMORY.register(key, value)
        _tm.DKV_PUTS.inc()
        _tm.DKV_KEYS.set(n)
        ten = _tenancy()
        if ten is not None:
            # per-key tenant tagging: the byte ledger attributes this key
            # to whoever the request context says is putting it
            ten.QUOTAS.tag_key(key)
        if old is not None and old is not value \
                and type(old).__name__ in ("Frame", "SwappedFrame"):
            # overwriting a keyed frame (re-put, spill to a stub, restore
            # from one) strands the OLD frame's registered mesh views: the
            # new value's lookup table starts empty, so they would hold
            # full-size device buffers in /3/Memory forever
            self._drop_mesh_views(key)
        if old is not None and old is not value \
                and type(old).__name__ in ("SwappedFrame", "SwappedValue"):
            # a user put over a SPILLED key orphans its snapshot — retire
            # it or the ice_root leaks one artifact per overwrite
            from h2o3_tpu.utils.cleaner import CLEANER, discard_snapshot
            discard_snapshot(old.path)
            CLEANER.forget(key)
        if type(value).__name__ in ("Frame", "RawFile"):
            # Cleaner hook (reference: Cleaner LRU sweep on heap pressure);
            # no-op unless a budget is enabled. Raw upload payloads are
            # spillable values too (per-value spill, docs/INGEST.md)
            from h2o3_tpu.utils.cleaner import CLEANER
            CLEANER.touch(key)
            CLEANER.sweep(protect=key)
        return key

    def replace_if(self, key: str, expected: Any, value: Any) -> bool:
        """Atomic compare-and-swap: install ``value`` only while the store
        still holds ``expected`` (identity). Runs the byte registration but
        NOT the Cleaner put-hook — callers (the Cleaner's spill/fault-in
        paths) touch/sweep themselves OUTSIDE the store lock, because a
        sweep takes the Cleaner IO lock and a concurrent sweep holding that
        lock CASes here: hook-under-store-lock would be an ABBA deadlock."""
        from h2o3_tpu.utils.memory import MEMORY
        with self._lock:
            if self._store.get(key) is not expected:
                return False
            self._store[key] = value
            n = len(self._store)
            MEMORY.register(key, value)
        _tm.DKV_PUTS.inc()
        _tm.DKV_KEYS.set(n)
        if expected is not None and expected is not value \
                and type(expected).__name__ in ("Frame", "SwappedFrame"):
            self._drop_mesh_views(key)
        return True

    def _resolve(self, key: str, value: Any) -> Any:
        if value is None:
            return value
        tname = type(value).__name__
        if tname == "SwappedFrame":
            from h2o3_tpu.utils.cleaner import CLEANER
            return CLEANER.resolve(key, value)
        if tname == "SwappedValue":
            from h2o3_tpu.utils.cleaner import CLEANER
            return CLEANER.resolve_value(key, value)
        if tname in ("Frame", "RawFile"):
            from h2o3_tpu.utils.cleaner import CLEANER
            if CLEANER.budget is not None:
                CLEANER.touch(key)
        return value

    def get(self, key: str, default: Any = None) -> Any:
        with self._lock:
            v = self._store.get(key, default)
        _tm.DKV_GETS.inc()
        if v is not None:
            from h2o3_tpu.utils.memory import MEMORY
            MEMORY.note_access(key)     # resets the leak detector's idle streak
        return self._resolve(key, v)

    def __getitem__(self, key: str) -> Any:
        with self._lock:
            v = self._store[key]
        _tm.DKV_GETS.inc()
        from h2o3_tpu.utils.memory import MEMORY
        MEMORY.note_access(key)
        return self._resolve(key, v)

    def remove(self, key: str, *, only_if: Any = None) -> Any:
        """Remove ``key``; with ``only_if`` the pop happens only while the
        store still holds that exact object (identity CAS, atomic under
        the store lock). Callers that used to spell this as
        ``with DKV._lock: if DKV._store.get(k) is v: DKV.remove(k)``
        held the store lock across the remove cascade — which reaches the
        Cleaner's IO lock, inverting the io->store order every fault-in
        uses (DLK001)."""
        from h2o3_tpu.utils.memory import MEMORY
        with self._lock:
            if only_if is not None and self._store.get(key) is not only_if:
                return None
            v = self._store.pop(key, None)
            n = len(self._store)
            MEMORY.unregister(key)
        _tm.DKV_REMOVES.inc()
        _tm.DKV_KEYS.set(n)
        ten = _tenancy()
        if ten is not None:
            ten.QUOTAS.untag_key(key)
        if type(v).__name__ in ("SwappedFrame", "SwappedValue"):
            # frame snapshots are DIRECTORIES — discard_snapshot handles
            # both shapes (a bare os.remove leaked the ice_root forever)
            from h2o3_tpu.utils.cleaner import CLEANER, discard_snapshot
            discard_snapshot(v.path)
            CLEANER.forget(key)
            # a spilled source's views are just as unreachable as a live
            # one's — the stub carries no view table, so cascade by key
            self._drop_mesh_views(key)
            return None
        from h2o3_tpu.utils.cleaner import CLEANER
        CLEANER.forget(key)
        if type(v).__name__ == "Frame":
            # cascade to the frame's DKV-registered mesh views: after the
            # source is gone they are unreachable (lookups only go through
            # the source's _mesh_views) yet keep full-size device buffers
            # resident and visible in /3/Memory. The key-prefix scan backs
            # up the view table for frames whose table was lost (restored
            # from a spill snapshot) or whose key was reassigned
            for vk in list(getattr(v, "_mesh_views", {}).values()):
                if isinstance(vk, str):
                    self.remove(vk)
            self._drop_mesh_views(key)
        return v

    def _drop_mesh_views(self, key: str) -> None:
        """Remove every DKV-registered mesh view of ``key`` (the
        ``{key}::mesh[...]`` namespace — Frame.on_mesh)."""
        prefix = f"{key}::mesh["
        with self._lock:
            stale = [k for k in self._store if k.startswith(prefix)]
        for k in stale:
            self.remove(k)

    def keys(self) -> list[str]:
        with self._lock:
            return list(self._store.keys())

    def raw_items(self) -> list[tuple[str, Any]]:
        """Snapshot WITHOUT resolving spilled stubs — listings must not
        re-inflate swapped frames just to read their metadata."""
        with self._lock:
            return list(self._store.items())

    def __contains__(self, key: str) -> bool:
        with self._lock:
            return key in self._store

    def __iter__(self) -> Iterator[str]:
        return iter(self.keys())

    def clear(self) -> None:
        from h2o3_tpu.utils.memory import MEMORY
        with self._lock:
            items = list(self._store.items())
            self._store.clear()
            MEMORY.clear()
        _tm.DKV_REMOVES.inc(len(items))
        _tm.DKV_KEYS.set(0)
        ten = _tenancy()
        if ten is not None:
            ten.QUOTAS.untag_all()
        from h2o3_tpu.utils.cleaner import discard_snapshot
        for _k, v in items:
            if type(v).__name__ in ("SwappedFrame", "SwappedValue"):
                discard_snapshot(v.path)
        from h2o3_tpu.utils.cleaner import CLEANER
        CLEANER.forget_all()


class KeyLocks:
    """Key-level read/write locks — the minimal Lockable analog.

    Reference: ``water/Lockable.java:1-299`` — a training job write-locks
    its destination model key and read-locks its input frames; deleting a
    locked key must wait for the lock holder.  The single-controller
    design removes most of the need (builds hold Python references, and
    rapids ops are copy-on-write — they build fresh Frames rather than
    mutating DKV-resident ones), but the threaded REST server
    (api/server.py) + parallel grids mean two clients CAN race on the
    same key: train-into-X vs delete-X, predict vs delete.  These locks
    serialize exactly those pairs; any future genuinely-in-place frame
    op must take ``LOCKS.write`` on its key itself.

    Semantics: readers are shared and never blocked by *waiting* writers
    (read-preference — a thread holding a read lock may take more read
    locks without deadlocking itself); a writer needs exclusivity but is
    reentrant within its own thread.  Unknown keys lock fine (lock state
    is independent of the store, like the reference's key-metadata locks).

    Deadlock freedom: every acquisition — including a mixed write+read
    set — goes through ONE ``locked()`` call that acquires all its keys
    in a single global sort order, so hold-and-wait cycles between
    multi-key users cannot form (two separate ``with`` statements would
    reintroduce ABBA).
    """

    def __init__(self):
        self._mu = threading.Lock()
        self._cond = lockwitness.condition(
            "utils.registry.KeyLocks._cond", lock=self._mu)
        # key -> [readers, writer_thread_ident | None, writer_depth]
        self._state: dict[str, list] = {}

    def _entry(self, key: str) -> list:
        return self._state.setdefault(key, [0, None, 0])

    def _gc(self, key: str) -> None:
        st = self._state.get(key)
        if st is not None and st[0] == 0 and st[1] is None:
            del self._state[key]

    @contextlib.contextmanager
    def locked(self, write=(), read=()):
        """Acquire write locks on ``write`` and read locks on ``read`` —
        all in one globally-sorted pass.  None keys are skipped; a key in
        both sets locks as write."""
        wset = {k for k in write if k}
        rset = {k for k in read if k} - wset
        plan = sorted([(k, True) for k in wset] + [(k, False) for k in rset])
        me = threading.get_ident()
        with self._cond:
            for k, is_write in plan:
                st = self._entry(k)
                # bounded waits + predicate recheck (graftlint WTX001): a
                # notify lost to a dying holder re-polls within a second
                # instead of wedging every later locker process-wide
                if is_write:
                    while (st[1] is not None and st[1] != me) or \
                            (st[1] is None and st[0] > 0):
                        self._cond.wait(timeout=1.0)
                        st = self._entry(k)
                    st[1] = me
                    st[2] += 1
                else:
                    while st[1] is not None and st[1] != me:
                        self._cond.wait(timeout=1.0)
                        st = self._entry(k)
                    st[0] += 1
        try:
            yield
        finally:
            with self._cond:
                for k, is_write in plan:
                    st = self._entry(k)
                    if is_write:
                        st[2] -= 1
                        if st[2] == 0:
                            st[1] = None
                    else:
                        st[0] -= 1
                    self._gc(k)
                self._cond.notify_all()

    def read(self, *keys: str | None):
        return self.locked(read=keys)

    def write(self, *keys: str | None):
        return self.locked(write=keys)


# Global registry (reference: the DKV singleton) + its key locks
# (reference: the Lockable protocol layered on DKV keys).
DKV = KeyedStore()
LOCKS = KeyLocks()
