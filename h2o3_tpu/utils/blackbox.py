"""Black-box post-mortem — the dump that happens when REST cannot.

Reference: ``POST /3/Diagnostics/bundle`` (utils/health.py) answers "what
does the system look like" — but only while the REST server answers. The
two failure classes an operator most needs diagnosed are exactly the ones
it cannot serve through: a **wedged** process (the REST accept loop or
the health sweep stalled past a deadline — every probe then hangs) and a
**fatal exit** (the process dies before anyone asks). This module is the
aircraft black box for both:

- a **watchdog thread** monitors heartbeats stamped by the watched loops
  (:meth:`BlackBox.beat` — the REST accept loop beats from
  ``service_actions`` every poll, the health sweep beats once per sweep).
  A watched heartbeat silent past its deadline
  (``max(H2O3TPU_BLACKBOX_STALL_SECS, 8×period)``) is a wedge: the
  watchdog dumps a post-mortem straight to disk;
- **exit hooks** (``atexit`` + a chained ``SIGTERM`` handler + a chained
  ``sys.excepthook``) dump when the process dies while still **armed** —
  an orderly ``H2OServer.stop()`` disarms first, so a clean shutdown
  never dumps; an exit that skipped shutdown is by definition unplanned.

The dump is a gzip tar written directly to the Cleaner's ``ice_root``
(*no REST involved — the wedge being diagnosed would block it*), exactly
**once per process**, containing the flight record, all thread stacks,
the trace ring, the incident ring, the ActionLog, the log-ring tail, and
the same secrets-redacted config dump as the diagnostics bundle
(``redacted_config`` — the name-pattern redaction contract is shared, not
reimplemented). Every member is individually fault-isolated: a sick
registry records its error string instead of sinking the dump.

``H2O3TPU_BLACKBOX_OFF=1`` disables arming entirely. Knobs (resolved at
:meth:`BlackBox.arm`, per the ENV001 lesson): ``…_STALL_SECS`` (default
30), ``…_CHECK_SECS`` (watchdog cadence, default 1s). docs/OBSERVABILITY
"Flight recorder & post-mortems" carries the trigger matrix.
"""

from __future__ import annotations

import atexit
import io
import json
import logging
import os
import signal
import sys
import tarfile
import threading
import time
import traceback

from h2o3_tpu.utils import lockwitness

_LOG = logging.getLogger("h2o3_tpu")


def blackbox_off() -> bool:
    return os.environ.get("H2O3TPU_BLACKBOX_OFF", "") == "1"


def _env_float(name: str, default: float, lo: float) -> float:
    try:
        return max(float(os.environ.get(name, "") or default), lo)
    except ValueError:
        return default


def _jsonable(obj) -> bytes:
    return json.dumps(obj, indent=1, default=str).encode()


# -- dump members (each fault-isolated by the builder loop) ------------------

def _member_flight() -> bytes:
    from h2o3_tpu.utils.flight import FLIGHT
    return _jsonable(FLIGHT.export())


def _member_threads() -> bytes:
    """Every live thread's stack — the wedge's smoking gun (which frame
    is the stalled loop parked in). When the lock witness is armed, each
    thread also lists the witnessed locks it currently holds, so a wedge
    dump shows who holds what without reading the stacks."""
    names = {t.ident: t.name for t in threading.enumerate()}
    # {} when unarmed: nothing is ever recorded
    held = lockwitness.WITNESS.held_by_thread()
    out = []
    for ident, frame in sys._current_frames().items():
        out.append({"thread_id": ident,
                    "name": names.get(ident, f"thread-{ident}"),
                    "held_locks": held.get(ident, []),
                    "stack": traceback.format_stack(frame)})
    return _jsonable(out)


def _member_traces() -> bytes:
    from h2o3_tpu.utils.tracing import TRACER
    return _jsonable(TRACER.list_traces())


def _member_incidents() -> bytes:
    from h2o3_tpu.utils.incidents import INCIDENTS
    return _jsonable(INCIDENTS.export())


def _member_actions() -> bytes:
    """The ActionLog — only when the ops plane is loaded (the dump path
    must not be the thing that imports it)."""
    acts = sys.modules.get("h2o3_tpu.ops_plane.actions")
    return _jsonable(acts.ACTIONS.list() if acts is not None else [])


def _member_logs() -> bytes:
    from h2o3_tpu.utils import telemetry as _tm
    return "\n".join(_tm.install_log_ring().lines()[-200:]).encode()


def _member_config() -> bytes:
    # the SAME name-pattern redaction as the diagnostics bundle — one
    # contract, two consumers
    from h2o3_tpu.utils.health import redacted_config
    return _jsonable(redacted_config())


#: member name -> builder; the dump loop fault-isolates each one
DUMP_MEMBERS = (
    ("flight.json", _member_flight),
    ("threads.json", _member_threads),
    ("traces.json", _member_traces),
    ("incidents.json", _member_incidents),
    ("actions.json", _member_actions),
    ("logs.txt", _member_logs),
    ("config.json", _member_config),
)


class BlackBox:
    """The watchdog + exit-hook post-mortem dumper. One process-wide
    instance (:data:`BLACKBOX`) is armed by ``H2OServer.start`` and
    disarmed by ``H2OServer.stop``; private instances (tests/bench)
    carry their own once-per-instance fire flag and dump directory."""

    def __init__(self, dump_dir: "str | None" = None):
        self._lock = lockwitness.lock("utils.blackbox.BlackBox._lock")
        self._dump_dir = dump_dir
        self._watch: "dict[str, float]" = {}      # name -> expected period
        self._beats: "dict[str, float]" = {}      # name -> last monotonic
        self._stop = threading.Event()
        self._thread: "threading.Thread | None" = None
        self._armed = False
        self._fired = False
        self._last_dump: "str | None" = None
        self._hooks_installed = False
        self._prev_sigterm = None
        self._prev_excepthook = None
        self.stall_secs = 30.0
        self.check_secs = 1.0

    # -- heartbeats ----------------------------------------------------------

    def watch(self, name: str, period_s: float) -> None:
        """Register a heartbeat to monitor; ``period_s`` is the loop's
        expected cadence (the wedge deadline scales with it, so a slow
        sweep interval doesn't false-positive)."""
        with self._lock:
            self._watch[name] = max(float(period_s), 0.01)
            self._beats[name] = time.monotonic()

    def unwatch(self, name: str) -> None:
        with self._lock:
            self._watch.pop(name, None)
            self._beats.pop(name, None)

    def beat(self, name: str) -> None:
        """Stamp a heartbeat (cheap — one locked dict write; unwatched
        names are ignored so call sites never need to know the arming
        state)."""
        with self._lock:
            if name in self._watch:
                self._beats[name] = time.monotonic()

    def wedged(self) -> "tuple[str, float] | None":
        """The first watched heartbeat silent past its deadline, as
        ``(name, silence_s)`` — None when everything is beating."""
        now = time.monotonic()
        with self._lock:
            for name, period in self._watch.items():
                deadline = max(self.stall_secs, 8.0 * period)
                silence = now - self._beats.get(name, now)
                if silence > deadline:
                    return name, round(silence, 3)
        return None

    # -- lifecycle -----------------------------------------------------------

    def arm(self) -> bool:
        """Start the watchdog and install the exit hooks (idempotent;
        False when already armed or ``H2O3TPU_BLACKBOX_OFF=1``). Env
        knobs resolve here, not at import (ENV001)."""
        if blackbox_off():
            return False
        with self._lock:
            if self._armed:
                return False
            self.stall_secs = _env_float(
                "H2O3TPU_BLACKBOX_STALL_SECS", 30.0, 0.1)
            self.check_secs = _env_float(
                "H2O3TPU_BLACKBOX_CHECK_SECS", 1.0, 0.05)
            self._armed = True
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="h2o3-blackbox")
            self._thread.start()
        self._install_hooks()
        return True

    def disarm(self, timeout: float = 5.0) -> None:
        """Orderly shutdown: stop the watchdog and neutralize the exit
        hooks (they check the armed flag) — a disarmed process never
        dumps at exit."""
        with self._lock:
            thread = self._thread
            self._thread = None
            self._armed = False
            self._stop.set()
        if thread is not None and thread is not threading.current_thread():
            thread.join(timeout=timeout)

    def armed(self) -> bool:
        with self._lock:
            return self._armed

    def fired(self) -> bool:
        with self._lock:
            return self._fired

    def last_dump(self) -> "str | None":
        with self._lock:
            return self._last_dump

    def _run(self) -> None:
        # bounded wait (WTX001): disarm() wakes it, the cadence bounds it
        while not self._stop.wait(self.check_secs):
            with self._lock:
                if self._thread is not threading.current_thread():
                    return
            try:
                wedge = self.wedged()
                if wedge is not None:
                    name, silence = wedge
                    self.dump(f"wedge:{name}",
                              detail={"heartbeat": name,
                                      "silence_s": silence,
                                      "deadline_s": max(
                                          self.stall_secs,
                                          8.0 * self._watch.get(name, 0))})
            except Exception:   # noqa: BLE001 — the watchdog must outlive
                _LOG.exception("blackbox watchdog check failed")

    # -- exit hooks ----------------------------------------------------------

    def _install_hooks(self) -> None:
        """atexit + chained SIGTERM + chained excepthook — once per
        instance; every hook re-checks the armed flag so disarm works
        without uninstalling (uninstalling chained handlers races)."""
        with self._lock:
            if self._hooks_installed:
                return
            self._hooks_installed = True
            atexit.register(self._on_exit)
            self._prev_excepthook = sys.excepthook
            sys.excepthook = self._on_uncaught
            try:
                # only the main thread may set signal handlers; an
                # embedded arm() from a worker thread just skips the
                # signal hook
                self._prev_sigterm = signal.signal(
                    signal.SIGTERM, self._on_sigterm)
            except ValueError:
                self._prev_sigterm = None

    def _on_exit(self) -> None:
        if self.armed():
            # exiting while still armed = shutdown never ran — unplanned
            self.dump("atexit-while-armed")

    def _on_uncaught(self, exc_type, exc, tb) -> None:
        if self.armed():
            try:
                self.dump(f"uncaught:{exc_type.__name__}",
                          detail={"error": f"{exc_type.__name__}: {exc}"})
            except Exception:   # noqa: BLE001 — never mask the real crash
                pass
        (self._prev_excepthook or sys.__excepthook__)(exc_type, exc, tb)

    def _on_sigterm(self, signum, frame) -> None:
        if self.armed():
            try:
                self.dump("SIGTERM")
            except Exception:   # noqa: BLE001 — never block the kill
                pass
        prev = self._prev_sigterm
        if callable(prev):
            prev(signum, frame)
        elif prev == signal.SIG_DFL:
            signal.signal(signal.SIGTERM, signal.SIG_DFL)
            os.kill(os.getpid(), signal.SIGTERM)

    # -- the dump ------------------------------------------------------------

    def dump(self, reason: str, detail: "dict | None" = None
             ) -> "str | None":
        """Write the post-mortem tar.gz to ``ice_root`` — exactly once
        per instance (a persistent wedge must not fill the disk with
        identical dumps). Returns the path, or None when already fired.
        REST is never involved."""
        with self._lock:
            if self._fired:
                return None
            self._fired = True
            watches = {n: {"period_s": p,
                           "silence_s": round(
                               time.monotonic() - self._beats.get(n, 0), 3)}
                       for n, p in self._watch.items()}
        now = int(time.time())
        members: "list[tuple[str, bytes]]" = [
            ("reason.json", _jsonable({
                "reason": reason, "detail": detail or {},
                "pid": os.getpid(), "ts": now,
                "at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                    time.gmtime(now)),
                "watched": watches}))]
        for name, build in DUMP_MEMBERS:
            try:
                members.append((name, build()))
            except Exception as e:   # noqa: BLE001 — a sick member must
                # not sink the post-mortem; its slot records the failure
                members.append((name + ".error",
                                f"{type(e).__name__}: {e}".encode()))
        buf = io.BytesIO()
        with tarfile.open(fileobj=buf, mode="w:gz") as tar:
            for name, data in members:
                info = tarfile.TarInfo(name=f"h2o3_postmortem/{name}")
                info.size = len(data)
                info.mtime = now
                tar.addfile(info, io.BytesIO(data))
        out_dir = self._dump_dir
        if out_dir is None:
            from h2o3_tpu.utils.cleaner import CLEANER
            out_dir = CLEANER.ice_root
        os.makedirs(out_dir, exist_ok=True)
        path = os.path.join(
            out_dir, f"h2o3_postmortem_{os.getpid()}_{now}.tar.gz")
        with open(path, "wb") as f:
            f.write(buf.getvalue())
        with self._lock:
            self._last_dump = path
        _LOG.error("blackbox post-mortem (%s) written to %s", reason, path)
        return path

    def reset(self) -> None:
        """Forget the fired flag and watches (tests/bench only — a real
        process fires at most once)."""
        with self._lock:
            self._fired = False
            self._last_dump = None
            self._watch.clear()
            self._beats.clear()


#: the process-wide black box (armed by ``H2OServer.start``; the health
#: sweep and the REST accept loop beat it unconditionally — beats to an
#: unwatched name are ignored)
BLACKBOX = BlackBox()
