"""Persistent XLA compilation cache — one switch, observable hit/miss counts.

The standard TPU production setup: ``jax_compilation_cache_dir`` persists
compiled executables across processes, so repeated same-shape programs
(an AutoML leaderboard's many model configs, every serving cold start)
stop paying compile time. The r04→r05 ``automl_leaderboard_100k`` wobble
(32.6s→42.2s) is mostly recompiles — ROADMAP item 5's compile-cache down
payment lives here.

Behavior is controlled by ``H2O3TPU_COMPILE_CACHE``:

- unset → caller's default (``enable()`` is opt-in; ``bench.py`` and
  session init pass ``default_on=True``/``False`` respectively);
- ``0``/``off`` → disabled;
- ``1``/``on`` → enabled at the default directory
  (``~/.cache/h2o3_tpu/jax`` or ``$XDG_CACHE_HOME``);
- any other value → enabled at that path.

Hit/miss counts come from JAX's own monitoring events
(``/jax/compilation_cache/cache_hits`` / ``cache_misses``), registered
once at enable time; :func:`stats` snapshots them plus the on-disk entry
count so bench artifacts can carry cache effectiveness per round.
"""

from __future__ import annotations

import os
import threading

_lock = threading.Lock()
_state = {"enabled": False, "dir": None, "hits": 0, "misses": 0,
          "listener": False, "by_site": {}}


def _default_dir() -> str:
    base = os.environ.get("XDG_CACHE_HOME",
                          os.path.join(os.path.expanduser("~"), ".cache"))
    return os.path.join(base, "h2o3_tpu", "jax")


def _on_event(event: str, **_kw) -> None:
    # cache_misses arrives as a duration event on some jax versions and a
    # plain event on others; both funnel here
    if event == "/jax/compilation_cache/cache_hits":
        kind = "hits"
    elif event == "/jax/compilation_cache/cache_misses":
        kind = "misses"
    else:
        return
    # per-site attribution: the CostMeter site scope active at compile time
    # (an AccountedJit AOT compile, a builder's fit scope) names which loop
    # hit/missed the persistent cache — the bench's compile_cache_per_run
    # can then say WHICH loop recompiled, not just that one did
    from h2o3_tpu.utils.costs import COSTS
    site = COSTS.active_site() or "(unattributed)"
    with _lock:
        _state[kind] += 1
        per = _state["by_site"].setdefault(site, {"hits": 0, "misses": 0})
        per[kind] += 1


def enable(cache_dir: str | None = None, *, default_on: bool = False,
           min_compile_secs: float = 1.0) -> bool:
    """Configure the persistent compile cache per the env policy above.
    Returns True when the cache is active. Idempotent; never raises (an
    old jax without the feature simply reports disabled)."""
    env = os.environ.get("H2O3TPU_COMPILE_CACHE", "").strip()
    if env.lower() in ("0", "off", "false"):
        return False
    if not env and not default_on and cache_dir is None:
        return False
    if env and env.lower() not in ("1", "on", "true"):
        cache_dir = env
    cache_dir = cache_dir or _default_dir()
    try:
        import jax
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(min_compile_secs))
    except Exception:   # noqa: BLE001 — older jax: feature absent
        return False
    with _lock:
        _state["enabled"] = True
        _state["dir"] = cache_dir
        if not _state["listener"]:
            try:
                from jax._src import monitoring as _mon
                _mon.register_event_listener(
                    lambda event, **kw: _on_event(event, **kw))
                _mon.register_event_duration_secs_listener(
                    lambda event, _dur, **kw: _on_event(event, **kw))
                _state["listener"] = True
            except Exception:   # noqa: BLE001 — private API may move
                pass
    return True


def stats() -> dict:
    """{enabled, dir, entries, hits, misses, by_site} — ``entries`` counts
    on-disk cache files (an absolute view; hits/misses are this process
    only, ``by_site`` splits them by the CostMeter site active at compile
    time)."""
    with _lock:
        out = {"enabled": _state["enabled"], "dir": _state["dir"],
               "hits": _state["hits"], "misses": _state["misses"],
               "by_site": {k: dict(v)
                           for k, v in _state["by_site"].items()}}
    entries = 0
    if out["dir"]:
        try:
            entries = sum(1 for _ in os.scandir(out["dir"]))
        except OSError:
            pass
    out["entries"] = entries
    return out
