"""Memory observability — device/host byte accounting with attribution.

Reference: H2O-3's substrate is *in-memory*, so the reference meters its heap
everywhere — ``water/H2O.java`` CloudV3 ``free_mem``/``max_mem``/``pojo_mem``
per node, ``water/MemoryManager.java`` budgeting the K/V store, and the
``WaterMeter*`` handlers. An in-memory ML platform dies by OOM, not by crash.
On TPUs the gap is sharper: device HBM is the scarce resource, and JAX exposes
``device.memory_stats()`` precisely so frameworks can meter it.

The :class:`MemoryMeter` accounts bytes at three levels:

1. **Per-DKV-key** — frames report summed chunk ``nbytes`` (``Vec.nbytes`` /
   ``Frame.nbytes``), models report artifact size (the byte total of their
   array tree), raw uploads their payload length. Registered at
   ``DKV.put``/``remove`` so the ``h2o3_dkv_bytes{kind}`` gauges and the
   top-N-keys view are always current.
2. **Per-process/device** — host RSS sampled from ``/proc/self/status``
   plus ``device.memory_stats()`` per JAX device, with a graceful fallback
   to live-array accounting (``jax.live_arrays()``) on backends without
   stats (CPU). Monotonic high-water marks are kept for both.
3. **Per-span** — model builds and ``map_reduce`` dispatches record
   device-byte peaks/deltas as span attrs through the existing
   ``timed_event``/tracing hooks (see :mod:`h2o3_tpu.utils.timeline` and
   :mod:`h2o3_tpu.ops.map_reduce`), so a trace tree shows *which* build ate
   HBM.

On top of the keyed accounting a **leak detector** snapshots keyed bytes
across :class:`~h2o3_tpu.utils.cleaner.Cleaner` sweeps and flags keys that
keep growing, or that stay resident above a size floor with no DKV access,
for N consecutive sweeps. Surfaced via ``GET /3/Memory``, the ``/metrics``
gauges, real numbers in ``/3/Cloud``, and the bench artifact
(``bench.py`` refuses to stamp when the detector fires on a real run).

Everything here is host-side stdlib bookkeeping: byte registration is a
dict write under one lock, and nothing is ever traced into an XLA program.
"""

from __future__ import annotations

import os

import numpy as np

from h2o3_tpu.utils import lockwitness
from h2o3_tpu.utils import telemetry as _tm

#: consecutive sweeps of growth / idleness before a key is flagged
LEAK_SWEEPS = int(os.environ.get("H2O3TPU_LEAK_SWEEPS", "4"))

#: keys below this byte floor are never flagged (jobs, tiny models, stubs)
LEAK_MIN_BYTES = int(os.environ.get("H2O3TPU_LEAK_MIN_BYTES", str(1 << 20)))

_KB = 1024


# ---------------------------------------------------------------------------
# Byte measurement — one definition each for frames, models, raw payloads.


def array_tree_bytes(obj, _depth: int = 0, host_only: bool = False) -> int:
    """Summed ``nbytes`` of every numpy/jax array reachable through dicts,
    lists/tuples, and plain object attributes (depth-limited like the
    persist layer's ``_to_host`` walker). The model-artifact size measure:
    coefficients, tree arrays, DL weights — without pickling anything.
    ``host_only`` counts numpy arrays but skips jax (device) arrays — the
    host-RSS attribution needed by CloudV3's heap arithmetic."""
    if _depth > 8 or obj is None:
        return 0
    nb = getattr(obj, "nbytes", None)
    if nb is not None and getattr(obj, "dtype", None) is not None:
        if host_only and not isinstance(obj, np.ndarray):
            return 0
        try:
            return int(nb)
        except TypeError:
            return 0
    if isinstance(obj, dict):
        return sum(array_tree_bytes(v, _depth + 1, host_only)
                   for v in obj.values())
    if isinstance(obj, (list, tuple)):
        return sum(array_tree_bytes(v, _depth + 1, host_only)
                   for v in obj)
    if isinstance(obj, (str, bytes, int, float, bool)):
        return len(obj) if isinstance(obj, bytes) else 0
    if hasattr(obj, "__dict__") and not isinstance(obj, type):
        return sum(array_tree_bytes(v, _depth + 1, host_only)
                   for v in vars(obj).values())
    return 0


def value_kind_bytes(value) -> tuple[str, int]:
    """(kind, bytes) for a DKV-resident value. Type-name dispatch (not
    isinstance) so the meter never imports frame/model modules at put time;
    models are duck-typed on their ``algo``/``output`` surface so every
    Model subclass lands in the ``model`` kind."""
    tname = type(value).__name__
    if tname == "Frame":
        # Frame.nbytes delegates back to vec_nbytes below — one definition
        # of a frame's resident bytes, so /3/Memory's per-key view can
        # never drift from what the frame reports about itself
        return "frame", int(value.nbytes)
    if tname in ("SwappedFrame", "SwappedValue"):
        # spilled to persist — zero RESIDENT bytes, but the on-disk size is
        # registered under its own kind so the /3/Memory view reconciles
        # across a sweep (bytes move frame→spilled instead of vanishing)
        return "spilled", int(getattr(value, "disk_bytes", 0) or 0)
    if tname == "RawFile":
        return "raw", len(getattr(value, "data", b"") or b"")
    if tname == "Job":
        return "job", 0
    if hasattr(value, "algo") and hasattr(value, "output"):
        # prefer the sizes stamped at build/save time: registration runs on
        # every put AND every refresh/leak sweep, and models are immutable
        # post-build — re-walking each one's object graph per sweep would
        # make frame puts O(sum of model sizes) under an HBM budget
        out = getattr(value, "output", None) or {}
        stamped = out.get("artifact_bytes") \
            or getattr(value, "artifact_file_bytes", None)
        return "model", int(stamped) if stamped else array_tree_bytes(value)
    return "other", 0


def value_host_bytes(value) -> int:
    """The host-RSS-resident portion of a DKV value: frames' host payloads
    (STR/UUID object arrays, exact TIME ms), raw upload bytes, and model
    artifacts. Device (HBM) chunk bytes are EXCLUDED — CloudV3's
    heap-shaped fields must never subtract HBM from host RSS (on the CPU
    backend device arrays do live in RSS, so this understates there, which
    only makes ``pojo_mem`` conservative)."""
    if type(value).__name__ == "Frame":
        total = 0
        for v in getattr(value, "vecs", []):
            host = getattr(v, "host_values", None)
            if host is not None:
                try:
                    total += int(host.nbytes)
                except (TypeError, AttributeError):
                    pass
            comp = getattr(v, "compressed", None)
            if comp is not None:   # compressed column payloads live in RSS
                total += int(comp.nbytes)
        return total
    kind, nbytes = value_kind_bytes(value)
    if kind == "raw":
        return nbytes
    if kind == "model":
        # a freshly-built model's arrays are jax (HBM) buffers; a loaded
        # one's are numpy — count only the numpy side as RSS-resident
        return array_tree_bytes(value, host_only=True)
    return 0


def vec_nbytes(vec) -> int:
    """One column's resident bytes: the padded device chunk (when it is
    materialized — NEVER forced: accounting must not trigger the compressed
    seam's decompress-on-access), any compressed host payload, plus any
    host-side payload (STR/UUID object arrays, exact TIME ms)."""
    total = 0
    # ``_data`` is the raw slot behind the lazily-materializing ``data``
    # property; plain attribute-carriers without it fall back to ``data``
    data = vec._data if hasattr(vec, "_data") else getattr(vec, "data", None)
    if data is not None:
        try:
            total += int(data.nbytes)
        except (TypeError, AttributeError):
            pass
    comp = getattr(vec, "compressed", None)
    if comp is not None:
        total += int(comp.nbytes)
    host = getattr(vec, "host_values", None)
    if host is not None:
        try:
            total += int(host.nbytes)
        except (TypeError, AttributeError):
            pass
    return total


# ---------------------------------------------------------------------------
# Host / device sampling.


def host_stats() -> dict:
    """Process + machine memory from /proc (reference: the per-node heap
    numbers CloudV3 serves). Keys: rss_bytes, rss_peak_bytes (VmHWM),
    total_bytes, available_bytes. Zeros when /proc is unreadable."""
    out = {"rss_bytes": 0, "rss_peak_bytes": 0,
           "total_bytes": 0, "available_bytes": 0}
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    out["rss_bytes"] = int(line.split()[1]) * _KB
                elif line.startswith("VmHWM:"):
                    out["rss_peak_bytes"] = int(line.split()[1]) * _KB
    except (OSError, ValueError, IndexError):
        pass
    # containers on older kernels omit VmHWM; the current RSS is then the
    # best kernel-side floor (the meter's own monotonic watermark covers
    # the rest)
    if out["rss_peak_bytes"] < out["rss_bytes"]:
        out["rss_peak_bytes"] = out["rss_bytes"]
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    out["total_bytes"] = int(line.split()[1]) * _KB
                elif line.startswith("MemAvailable:"):
                    out["available_bytes"] = int(line.split()[1]) * _KB
    except (OSError, ValueError, IndexError):
        pass
    return out


def device_stats() -> dict:
    """Per-device HBM accounting. Primary source: ``device.memory_stats()``
    (TPU/GPU runtimes). Backends without it (CPU) fall back to live-array
    accounting — every ``jax.live_arrays()`` buffer attributed evenly over
    the devices it is sharded across. ``source`` names which path ran."""
    import jax
    devices = []
    total = peak = limit = 0
    have_stats = True
    for d in jax.devices():
        try:
            ms = d.memory_stats()
        except Exception:   # noqa: BLE001 — any backend may refuse
            ms = None
        if not ms:
            have_stats = False
            break
        in_use = int(ms.get("bytes_in_use", 0))
        d_peak = int(ms.get("peak_bytes_in_use", in_use))
        d_limit = int(ms.get("bytes_limit", 0))
        devices.append({"device": str(d), "bytes_in_use": in_use,
                        "peak_bytes_in_use": d_peak, "bytes_limit": d_limit})
        total += in_use
        peak += d_peak
        limit += d_limit
    if have_stats:
        return {"source": "memory_stats", "bytes_in_use": total,
                "peak_bytes_in_use": peak, "bytes_limit": limit,
                "devices": devices}
    per: dict[str, int] = {}
    total = 0
    for a in jax.live_arrays():
        try:
            n = int(a.nbytes)
            ds = [str(d) for d in a.devices()]
        except Exception:   # noqa: BLE001 — deleted/donated buffers race
            continue
        total += n
        if ds:
            share = n // len(ds)
            for dev in ds:
                per[dev] = per.get(dev, 0) + share
    return {"source": "live_arrays", "bytes_in_use": total,
            "peak_bytes_in_use": 0, "bytes_limit": 0,
            "devices": [{"device": k, "bytes_in_use": v,
                         "peak_bytes_in_use": 0, "bytes_limit": 0}
                        for k, v in sorted(per.items())]}


def fast_device_bytes() -> tuple[int, int] | None:
    """(bytes_in_use, peak_bytes_in_use) summed over devices, or None when
    the backend has no ``memory_stats`` — the dispatch-hot-path probe:
    reading runtime counters is ~µs, while the live-array fallback walks
    every resident buffer and has no place inside a per-iteration loop."""
    import jax
    total = peak = 0
    for d in jax.devices():
        try:
            ms = d.memory_stats()
        except Exception:   # noqa: BLE001
            return None
        if not ms:
            return None
        total += int(ms.get("bytes_in_use", 0))
        peak += int(ms.get("peak_bytes_in_use", 0))
    return total, peak


# ---------------------------------------------------------------------------
# Leak detection.


class LeakDetector:
    """Flags keys whose bytes grow, or that sit resident and untouched,
    for N consecutive Cleaner sweeps.

    Semantics (documented in docs/OBSERVABILITY.md): a *sweep* is one
    :meth:`MemoryMeter.leak_sweep` generation — the Cleaner advances it on
    every LRU sweep, and diagnostics (``bench.py``, tests) may advance it
    explicitly. Per key the detector tracks a **growth streak** (consecutive
    sweeps where registered bytes strictly increased) and an **idle streak**
    (consecutive sweeps with no DKV put/get of the key). A key is flagged
    once either streak reaches ``LEAK_SWEEPS``, provided its bytes are at or
    above ``LEAK_MIN_BYTES`` (jobs and tiny models never page anyone)."""

    def __init__(self, sweeps: int = LEAK_SWEEPS,
                 min_bytes: int = LEAK_MIN_BYTES):
        self.sweeps = max(int(sweeps), 1)
        self.min_bytes = int(min_bytes)
        self.generation = 0
        # key -> {"kind", "bytes", "grow", "idle"}
        self._state: dict[str, dict] = {}

    def observe(self, keyed: dict[str, tuple[str, int]],
                accessed: set[str]) -> None:
        self.generation += 1
        gone = set(self._state) - set(keyed)
        for k in gone:
            del self._state[k]
        for key, (kind, nbytes) in keyed.items():
            st = self._state.get(key)
            if st is None:
                self._state[key] = {"kind": kind, "bytes": nbytes,
                                    "grow": 0, "idle": 0}
                continue
            st["grow"] = st["grow"] + 1 if nbytes > st["bytes"] else 0
            st["idle"] = 0 if key in accessed else st["idle"] + 1
            st["bytes"] = nbytes
            st["kind"] = kind

    def report(self) -> list[dict]:
        """Flagged keys, largest first."""
        out = []
        for key, st in self._state.items():
            if st["bytes"] < self.min_bytes:
                continue
            reasons = []
            if st["grow"] >= self.sweeps:
                reasons.append("growing")
            if st["idle"] >= self.sweeps:
                reasons.append("idle")
            if reasons:
                out.append({"key": key, "kind": st["kind"],
                            "bytes": st["bytes"],
                            "growth_sweeps": st["grow"],
                            "idle_sweeps": st["idle"],
                            "reasons": reasons})
        out.sort(key=lambda r: -r["bytes"])
        return out

    def reset(self) -> None:
        self.generation = 0
        self._state.clear()


# ---------------------------------------------------------------------------
# The meter.


class MemoryMeter:
    """Thread-safe byte accountant for the three levels above. One global
    instance (:data:`MEMORY`); the DKV registers keys on put/remove, the
    Cleaner advances leak sweeps, and the REST layer serves summaries."""

    def __init__(self):
        self._lock = lockwitness.lock("utils.memory.MemoryMeter._lock")
        # key -> (kind, bytes, host_bytes)
        self._keyed: dict[str, tuple[str, int, int]] = {}
        self._by_kind: dict[str, int] = {}
        self._host_total = 0                           # RSS-resident K/V bytes
        self._exported_kinds: set[str] = set()         # gauges ever written
        self._accessed: set[str] = set()               # since last sweep
        self._host_peak = 0
        self._device_peak = 0
        self.detector = LeakDetector()

    # -- per-key registration (DKV put/remove/clear) -------------------------

    def register(self, key: str, value) -> None:
        kind, nbytes = value_kind_bytes(value)
        host = value_host_bytes(value)
        with self._lock:
            self._set_locked(key, kind, nbytes, host)
            self._accessed.add(key)
            self._export_locked()

    def unregister(self, key: str) -> None:
        with self._lock:
            self._drop_locked(key)
            self._accessed.discard(key)
            self._export_locked()

    def clear(self) -> None:
        with self._lock:
            self._keyed.clear()
            self._by_kind.clear()
            self._host_total = 0
            self._accessed.clear()
            self.detector.reset()
            self._export_locked()

    def note_access(self, key: str) -> None:
        """A DKV get touched the key — resets its idle streak at the next
        sweep. A set-add under the lock: cheap enough for every get."""
        with self._lock:
            self._accessed.add(key)

    def _set_locked(self, key: str, kind: str, nbytes: int,
                    host: int) -> None:
        self._drop_locked(key)
        self._keyed[key] = (kind, nbytes, host)       # graftlint: ok(caller holds self._lock — _locked suffix contract)
        self._by_kind[kind] = self._by_kind.get(kind, 0) + nbytes   # graftlint: ok(caller holds self._lock — _locked suffix contract)
        self._host_total += host                      # graftlint: ok(caller holds self._lock — _locked suffix contract)

    def _drop_locked(self, key: str) -> None:
        old = self._keyed.pop(key, None)              # graftlint: ok(caller holds self._lock — _locked suffix contract)
        if old is not None:
            self._by_kind[old[0]] -= old[1]           # graftlint: ok(caller holds self._lock — _locked suffix contract)
            self._host_total -= old[2]                # graftlint: ok(caller holds self._lock — _locked suffix contract)

    def _export_locked(self) -> None:
        """Push per-kind totals into the gauges WHILE holding the meter
        lock, so a later snapshot can never be published before an earlier
        one (the telemetry registry's own lock is terminal in the
        store→meter→telemetry order). Kinds exported before but absent now
        are written as 0: after a DKV.clear() the gauge must not keep
        reporting the last resident bytes forever."""
        totals = dict(self._by_kind)
        stale = self._exported_kinds - set(totals)
        self._exported_kinds |= set(totals)           # graftlint: ok(caller holds self._lock — _locked suffix contract)
        for kind, total in totals.items():
            _tm.DKV_BYTES.labels(kind=kind).set(max(total, 0))
        for kind in stale:
            _tm.DKV_BYTES.labels(kind=kind).set(0)

    # -- authoritative refresh ----------------------------------------------

    def refresh(self) -> None:
        """Recompute every key's bytes from the live DKV objects. Puts and
        removes keep the registry current for the common paths; a refresh
        catches in-place mutation (a column added to a resident frame)
        before serving ``/3/Memory``. Runs under the STORE lock so a
        concurrent remove cannot be resurrected by an older snapshot
        (store→meter lock order, same as put/remove registration)."""
        from h2o3_tpu.utils.registry import DKV
        with DKV._lock:   # raw store, consistent with Cleaner.resident_frames
            fresh = {key: (*value_kind_bytes(value),
                           value_host_bytes(value))
                     for key, value in DKV._store.items()}
            with self._lock:
                self._keyed = fresh
                self._by_kind = {}
                self._host_total = 0
                for kind, nbytes, host in fresh.values():
                    self._by_kind[kind] = self._by_kind.get(kind, 0) + nbytes
                    self._host_total += host
                self._export_locked()

    # -- process/device sampling + watermarks --------------------------------

    def sample(self, rss: int | None = None,
               dev: int | None = None) -> tuple[int, int]:
        """(host_rss_bytes, device_bytes_in_use) — updates the monotonic
        high-water marks. The full-fidelity sample: uses the live-array
        fallback when the backend has no stats, so call it at build/section
        granularity, not per dispatch. Pass precomputed values when the
        caller already sampled (``summary`` reads both for its payload)."""
        if rss is None:
            rss = host_stats()["rss_bytes"]
        if dev is None:
            dev = device_stats()["bytes_in_use"]
        # peaks updated AND published under the lock: exporting from an
        # unlocked read could publish an older peak after a newer one,
        # making the "monotonic" gauges visibly decrease
        with self._lock:
            if rss > self._host_peak:
                self._host_peak = rss
            if dev > self._device_peak:
                self._device_peak = dev
            _tm.HOST_RSS_BYTES.set(rss)
            _tm.DEVICE_BYTES.set(dev)
            _tm.HOST_RSS_PEAK_BYTES.set(self._host_peak)
            _tm.DEVICE_PEAK_BYTES.set(self._device_peak)
        return rss, dev

    @property
    def watermarks(self) -> dict:
        with self._lock:
            return {"host_rss_peak_bytes": self._host_peak,
                    "device_peak_bytes": self._device_peak}

    # -- leak sweeps ---------------------------------------------------------

    def leak_sweep(self) -> None:
        """Advance one leak-detector generation over the REGISTERED keyed
        bytes (the Cleaner calls this on every budgeted sweep — i.e. on
        every frame put under an HBM budget — so it must stay O(keys):
        put/remove already keep the registered view current, and growth
        from in-place mutation is caught when the key is re-put or when a
        ``/3/Memory`` read refreshes). ``bench.py`` and tests call it
        directly."""
        with self._lock:
            keyed = {k: (kind, nbytes)
                     for k, (kind, nbytes, _host) in self._keyed.items()}
            accessed = set(self._accessed)
            self._accessed.clear()
            self.detector.observe(keyed, accessed)

    def idle_streaks(self) -> dict[str, int]:
        """Per-key consecutive no-access sweep counts from the leak
        detector — the Cleaner's spill-victim signal (a key idle for many
        sweeps is colder than anything the LRU clock alone can prove)."""
        with self._lock:
            return {k: st["idle"] for k, st in self.detector._state.items()}

    def leak_report(self) -> dict:
        with self._lock:
            return {"sweeps": self.detector.generation,
                    "flag_after_sweeps": self.detector.sweeps,
                    "min_bytes": self.detector.min_bytes,
                    "flagged": self.detector.report()}

    # -- summaries -----------------------------------------------------------

    def dkv_totals(self) -> tuple[int, dict[str, int], int]:
        """(total_bytes, by_kind, key_count) from the registered view."""
        with self._lock:
            by_kind = dict(self._by_kind)
            n = len(self._keyed)
        return sum(by_kind.values()), by_kind, n

    def dkv_host_bytes(self) -> int:
        """Host-RSS-resident K/V bytes (see :func:`value_host_bytes`) —
        what CloudV3's heap arithmetic may legitimately subtract from
        process RSS. A running total maintained at register/unregister:
        ``/3/Cloud`` is polled, so it must not walk the object graph."""
        with self._lock:
            return max(self._host_total, 0)

    def key_bytes(self, key: str) -> int:
        """Registered bytes of one DKV key (0 when unknown) — the tenancy
        byte ledger prices a key by the same measure /3/Memory reports."""
        with self._lock:
            rec = self._keyed.get(key)
            return rec[1] if rec is not None else 0

    def top_keys(self, n: int = 10) -> list[dict]:
        with self._lock:
            rows = [{"key": k, "kind": kind, "bytes": b}
                    for k, (kind, b, _host) in self._keyed.items()]
        rows.sort(key=lambda r: -r["bytes"])
        return rows[:n]

    def summary(self, top_n: int = 10, refresh: bool = True) -> dict:
        """The ``/3/Memory`` payload: host + device stats, keyed totals,
        top-N keys, watermarks, leak report, and the Cleaner's spill view
        (budget, spill/fault-in counters, what sits in the ice_root)."""
        if refresh:
            self.refresh()
        host = host_stats()
        dev = device_stats()
        # watermarks track every summary read too (reusing the samples
        # above — no second /proc read or live-array walk)
        self.sample(rss=host["rss_bytes"], dev=dev["bytes_in_use"])
        total, by_kind, nkeys = self.dkv_totals()
        from h2o3_tpu.utils.cleaner import CLEANER
        return {"host": host, "device": dev,
                "dkv": {"total_bytes": total, "by_kind": by_kind,
                        "keys": nkeys},
                "top_keys": self.top_keys(top_n),
                "watermarks": self.watermarks,
                "leaks": self.leak_report(),
                "spill": CLEANER.stats()}


#: the process-wide meter (reference: the MemoryManager singleton)
MEMORY = MemoryMeter()
