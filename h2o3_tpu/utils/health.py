"""Cluster self-watching — declarative health rules over the live registries.

Reference: H2O-3's cloud is self-monitoring — nodes gossip heartbeats into
a consensus view, ``GET /3/Cloud`` answers "is this cloud healthy and why
not" (``cloud_healthy`` / ``bad_nodes``), and ``h2o logs download`` ships
the whole diagnostic state in one call. This module is the evaluation
layer our four observability pillars (metrics PR 2, traces PR 4, memory
PR 5, compute PR 10) were missing: a **declarative rule set** swept by a
bounded-interval background thread over the live registries —

- heartbeat-lease gaps and SUSPECT dwell from the elastic membership view
  (``parallel/elastic.py``), plus ejection deltas;
- shed-rate and p99-vs-SLO from the serving tier (``serving/service.py``);
- spill/fault-in thrash (``utils/cleaner.py``) and leak-detector growth
  flags (``utils/memory.py``);
- recompile storms and MFU collapse from the compute observatory
  (``utils/costs.py``);
- dispatch-retry exhaustion streaks from the reliability metrics.

Each sweep folds rule results into a subsystem-scored verdict
(``healthy`` / ``degraded`` / ``unhealthy`` per subsystem) served by
``GET /3/Health``; every finding names the tripping **rule**, the
**observed** value, and the **threshold** — never a bare boolean. Rule
trips open structured incidents (:mod:`h2o3_tpu.utils.incidents`) that
auto-capture correlated context at trip time, and
:func:`diagnostic_bundle` is the ``h2o logs download`` analog: one call
tars a gzip archive of every pillar's snapshot plus incidents, logs,
hardware fingerprint, and a secrets-redacted config dump
(``POST /3/Diagnostics/bundle``).

Thresholds are env-tunable per rule (``H2O3TPU_HEALTH_*``, see
docs/OBSERVABILITY.md "Health & incidents"); ``H2O3TPU_HEALTH_OFF=1``
disables the evaluator entirely (the bench's overhead comparator).
Everything is host-side stdlib; a probe that raises is reported and
skipped, never fatal to the sweep.
"""

from __future__ import annotations

import io
import json
import logging
import os
import re
import tarfile
import threading
import time

from h2o3_tpu.utils import flight as _fl
from h2o3_tpu.utils import lockwitness
from h2o3_tpu.utils import telemetry as _tm
from h2o3_tpu.utils.incidents import INCIDENTS

_LOG = logging.getLogger("h2o3_tpu")

#: wall seconds per health evaluation (thread sweeps and inline calls) —
#: the observe-the-observers instrument: a sweep dragging toward its own
#: interval is a probe reading a sick registry (docs/OBSERVABILITY.md)
HEALTH_SWEEP_SECONDS = _tm.METRICS.histogram(
    "h2o3_health_sweep_seconds",
    "wall seconds per health-evaluator sweep")

HEALTHY, DEGRADED, UNHEALTHY = "healthy", "degraded", "unhealthy"
_RANK = {HEALTHY: 0, DEGRADED: 1, UNHEALTHY: 2}

SUBSYSTEMS = ("elastic", "serving", "memory", "compute", "dispatch")

#: observed-value window retained per rule (the incident "metric series")
SERIES_LEN = 32


def health_off() -> bool:
    return os.environ.get("H2O3TPU_HEALTH_OFF", "") == "1"


def interval_from_env(default: float = 5.0) -> float:
    """Sweep interval seconds (``H2O3TPU_HEALTH_INTERVAL_SECS``) — the
    bound on how stale a served verdict can be with the thread running."""
    try:
        return max(float(os.environ.get("H2O3TPU_HEALTH_INTERVAL_SECS", "")
                         or default), 0.05)
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _metric_total(family, **match) -> float:
    """Sum a metric family's child values over label-matching children —
    the window-delta inputs (retry exhaustions, elastic ejections, score
    requests) read the counters the subsystems already publish."""
    total = 0.0
    for labels, child in family.children():
        if all(labels.get(k) == v for k, v in match.items()):
            total += child.value
    return total


# -- registry providers (module-level seams: tests monkeypatch these) --------

def _elastic_rows() -> list:
    """Membership rows of LIVE elastic groups only — ``ELASTIC_STATS``
    retains finished builds for the /3/Cloud view, whose workers stopped
    heartbeating legitimately; health must not page on a completed build."""
    from h2o3_tpu.parallel import elastic
    return elastic.live_rows()


def _serving_stats() -> "dict | None":
    """The scoring tier's stats — only when serving is actually loaded
    (the sweep thread must not be the thing that imports the stack)."""
    import sys
    svc = sys.modules.get("h2o3_tpu.serving.service")
    return svc.SCORING.stats() if svc is not None else None


def _cleaner_stats() -> dict:
    from h2o3_tpu.utils.cleaner import CLEANER
    return CLEANER.stats()


def _leak_growth_flags() -> list:
    """Keys the leak detector flags as GROWING (bytes strictly rising
    across sweeps). Idle-only flags are expected from back-to-back sweeps
    and annotate, not page — same policy as the bench memory gate."""
    from h2o3_tpu.utils.memory import MEMORY
    return [f for f in MEMORY.leak_report()["flagged"]
            if "growing" in f.get("reasons", ())]


def _recompile_total() -> float:
    from h2o3_tpu.utils.costs import COSTS
    return float(COSTS.recompile_count())


def _compute_loops() -> dict:
    from h2o3_tpu.utils.costs import COSTS
    return COSTS.loops()


def _exhausted_total() -> float:
    return _metric_total(_tm.DISPATCH_RETRIES, outcome="exhausted")


def _ejections_total() -> float:
    return _metric_total(_tm.ELASTIC_EJECTIONS)


def _score_requests_total() -> float:
    return _metric_total(_tm.SCORE_REQUESTS)


# -- rules -------------------------------------------------------------------

class Rule:
    """One declarative health rule: a probe over the live registries, a
    threshold (env-overridable), and a severity. ``direction`` is the trip
    comparison: ``above`` pages when observed > threshold, ``below`` when
    observed < threshold (MFU collapse). A probe returning None means
    not-applicable this sweep (no data — never a trip)."""

    def __init__(self, name: str, subsystem: str, severity: str,
                 probe, *, env: str, default, direction: str = "above",
                 unit: str = "", description: str = "",
                 source_series: "str | None" = None):
        self.name = name
        self.subsystem = subsystem
        self.severity = severity
        self.probe = probe
        self.env = env
        self.default = default
        self.direction = direction
        self.unit = unit
        self.description = description
        #: the flight-recorder series this rule trends over (trend rules);
        #: the incident context captures its ±window instead of only the
        #: rule's own point samples
        self.source_series = source_series

    def threshold(self) -> float:
        dflt = self.default() if callable(self.default) else self.default
        return _env_float(self.env, float(dflt))

    def tripped(self, observed, threshold: float) -> bool:
        if observed is None:
            return False
        return (observed > threshold if self.direction == "above"
                else observed < threshold)


# probe implementations take the evaluator (for window deltas / streaks)

def _probe_heartbeat_gap(ev: "HealthEvaluator"):
    gaps = [r["last_heartbeat_ago_ms"] / 1e3 for r in _elastic_rows()
            if r.get("state") in ("ACTIVE", "SUSPECT", "JOINING")]
    return round(max(gaps), 3) if gaps else None


def _probe_suspect_dwell(ev: "HealthEvaluator"):
    suspects = sum(1 for r in _elastic_rows() if r.get("state") == "SUSPECT")
    return float(ev._streak("elastic_suspect", suspects > 0))


def _probe_ejections(ev: "HealthEvaluator"):
    return ev._delta("elastic_ejections", _ejections_total())


def _probe_shed_rate(ev: "HealthEvaluator"):
    stats = _serving_stats()
    if stats is None:
        return None
    shed = ev._delta("score_shed", float(stats.get("shed_total") or 0))
    total = ev._delta("score_requests", _score_requests_total())
    if shed <= 0 and total <= 0:
        return None          # no traffic this window — nothing to rate
    # every shed ALSO lands in the request counter (service.score counts
    # the ServiceUnavailable as status=error on its way out), so the
    # all-status request delta already IS the full admission count —
    # dividing by shed+total would double-count sheds and saturate the
    # rate at 0.5. A shed recorded astride a window edge can still leave
    # shed > total; clamp so the rate stays in [0, 1].
    total = max(total, shed)
    return round(shed / total, 4)


def _probe_p99_vs_slo(ev: "HealthEvaluator"):
    stats = _serving_stats()
    if stats is None:
        return None
    ratios = []
    for row in stats.get("resident") or ():
        slo = row.get("slo") or {}
        target, p99 = slo.get("target_ms"), slo.get("p99_ms")
        if target and p99 is not None:
            ratios.append(p99 / target)
    return round(max(ratios), 4) if ratios else None


def _probe_spill_thrash(ev: "HealthEvaluator"):
    st = _cleaner_stats()
    spills = ev._delta("spills", float(st.get("spill_count") or 0))
    restores = ev._delta("restores", float(st.get("restore_count") or 0))
    return min(spills, restores)


def _probe_leak_growth(ev: "HealthEvaluator"):
    return float(len(_leak_growth_flags()))


def _probe_recompile_storm(ev: "HealthEvaluator"):
    return ev._delta("recompiles", _recompile_total())


def _probe_mfu_collapse(ev: "HealthEvaluator"):
    utils = [st.get("utilization") for st in _compute_loops().values()
             if st.get("utilization") is not None
             and st.get("samples", 0) >= 3]
    return round(min(utils), 6) if utils else None


def _probe_retry_exhaustion(ev: "HealthEvaluator"):
    delta = ev._delta("dispatch_exhausted", _exhausted_total())
    return float(ev._streak("dispatch_exhausted", delta > 0))


# -- trend probes (sustained-slope detectors over the flight recorder) -------
#
# Point rules answer "is it bad NOW"; trend rules answer "is it HEADING
# bad" — a slow leak, a creeping p99, an MFU slide. Each reads a retained
# series from the flight recorder (utils/flight.py) and compares the tail
# of the window against its head, so a single noisy sample never pages.
# With the recorder off (H2O3TPU_FLIGHT_OFF=1), not started, or not yet
# holding a full window, every probe returns None (not-applicable) — the
# clean-degrade contract incidents rely on.

def trend_window() -> int:
    """Raw samples a trend probe needs before it speaks
    (``H2O3TPU_FLIGHT_TREND_SAMPLES``, default 12, min 4)."""
    try:
        return max(int(os.environ.get("H2O3TPU_FLIGHT_TREND_SAMPLES", "")
                       or 12), 4)
    except ValueError:
        return 12


def _trend_vals(name: str) -> "list | None":
    """The last trend-window values of a flight series, or None when the
    window isn't full yet (an under-filled window must not fabricate a
    slope from two samples)."""
    n = trend_window()
    vals = _fl.FLIGHT.values(name, last_n=n)
    return vals if len(vals) >= n else None


def _edge_means(vals: list) -> "tuple[float, float]":
    """(head_mean, tail_mean) over the window's first and last quartiles
    — a noise-damped two-point slope."""
    q = max(len(vals) // 4, 1)
    head = sum(vals[:q]) / q
    tail = sum(vals[-q:]) / q
    return head, tail


def _probe_trend_rss(ev: "HealthEvaluator"):
    """Relative RSS growth across the trend window (0.05 = +5%)."""
    vals = _trend_vals("derived.host_rss_bytes")
    if vals is None:
        return None
    head, tail = _edge_means(vals)
    if head <= 0 or tail <= head:
        return 0.0
    return round((tail - head) / head, 4)


def _probe_trend_p99(ev: "HealthEvaluator"):
    """p99/SLO-ratio rise across the window — only while the tail is
    already near the SLO (a creep from 0.1 to 0.2 is headroom, not
    danger)."""
    vals = _trend_vals("derived.p99_slo_ratio")
    if vals is None:
        return None
    head, tail = _edge_means(vals)
    if tail < 0.8 or tail <= head:
        return 0.0
    return round(tail - head, 4)


def _probe_trend_mfu(ev: "HealthEvaluator"):
    """MFU lost across the window (positive = declining utilization)."""
    vals = _trend_vals("derived.mfu_min")
    if vals is None:
        return None
    head, tail = _edge_means(vals)
    return round(max(head - tail, 0.0), 6)


def _probe_trend_shed(ev: "HealthEvaluator"):
    """Shed-rate acceleration: sheds in the window's second half minus
    sheds in its first (the cumulative counter's second difference) — a
    steady overload pages the point rule; this one pages when shedding
    is getting WORSE."""
    vals = _trend_vals("derived.score_shed_total")
    if vals is None:
        return None
    mid = len(vals) // 2
    first = vals[mid] - vals[0]
    second = vals[-1] - vals[mid]
    return round(max(second - first, 0.0), 4)


def default_rules() -> list[Rule]:
    """The rule catalog (docs/OBSERVABILITY.md "Health & incidents" is the
    operator-facing table; keep both in step)."""
    from h2o3_tpu.parallel.elastic import lease_secs_from_env
    return [
        Rule("elastic_heartbeat_gap", "elastic", UNHEALTHY,
             _probe_heartbeat_gap,
             env="H2O3TPU_HEALTH_HEARTBEAT_GAP_SECS",
             default=lease_secs_from_env, unit="s",
             description="max heartbeat silence of a live elastic worker "
                         "exceeds the lease — a worker is dead or wedged"),
        Rule("elastic_suspect_dwell", "elastic", DEGRADED,
             _probe_suspect_dwell,
             env="H2O3TPU_HEALTH_SUSPECT_SWEEPS", default=1, unit="sweeps",
             description="SUSPECT workers present for consecutive sweeps — "
                         "a straggler is dwelling instead of recovering"),
        Rule("elastic_ejections", "elastic", DEGRADED,
             _probe_ejections,
             env="H2O3TPU_HEALTH_EJECTIONS", default=0, unit="ejections",
             description="workers ejected from elastic groups this window "
                         "(membership decayed; training throughput lost)"),
        Rule("serving_shed_rate", "serving", DEGRADED,
             _probe_shed_rate,
             env="H2O3TPU_HEALTH_SHED_RATE", default=0.05, unit="fraction",
             description="fraction of scoring admissions shed with 503 "
                         "this window — the tier is overloaded"),
        Rule("serving_p99_slo", "serving", UNHEALTHY,
             _probe_p99_vs_slo,
             env="H2O3TPU_HEALTH_P99_RATIO", default=1.0, unit="ratio",
             description="a resident model's p99 latency exceeds its SLO "
                         "target (ratio of p99 to target)"),
        Rule("memory_spill_thrash", "memory", DEGRADED,
             _probe_spill_thrash,
             env="H2O3TPU_HEALTH_THRASH_CYCLES", default=2, unit="cycles",
             description="spill/fault-in cycles this window — the working "
                         "set no longer fits the Cleaner budget"),
        Rule("memory_leak_growth", "memory", DEGRADED,
             _probe_leak_growth,
             env="H2O3TPU_HEALTH_LEAK_KEYS", default=0, unit="keys",
             description="DKV keys the leak detector flags as GROWING "
                         "across sweeps"),
        Rule("compute_recompile_storm", "compute", DEGRADED,
             _probe_recompile_storm,
             env="H2O3TPU_HEALTH_RECOMPILES", default=2, unit="recompiles",
             description="recompile events this window — signatures are "
                         "churning (shape/dtype instability)"),
        Rule("compute_mfu_collapse", "compute", DEGRADED,
             _probe_mfu_collapse, direction="below",
             env="H2O3TPU_HEALTH_MFU_FLOOR", default=0.02, unit="MFU",
             description="a rated loop's utilization fell below the floor "
                         "(only on backends in the peak table)"),
        Rule("dispatch_retry_exhaustion", "dispatch", UNHEALTHY,
             _probe_retry_exhaustion,
             env="H2O3TPU_HEALTH_EXHAUSTION_SWEEPS", default=0,
             unit="sweeps",
             description="consecutive sweeps with dispatch-retry budgets "
                         "exhausted — dispatches are failing through their "
                         "whole retry budget"),
        # trend rules: sustained-slope detectors over the flight recorder
        # (not-applicable — never a trip — while the recorder is off or
        # its window unfilled; docs/OBSERVABILITY.md "Flight recorder")
        Rule("trend_rss_growth", "memory", DEGRADED,
             _probe_trend_rss,
             env="H2O3TPU_HEALTH_TREND_RSS_GROWTH", default=0.05,
             unit="fraction", source_series="derived.host_rss_bytes",
             description="host RSS grew steadily across the trend window "
                         "— a slow leak the point rules cannot see"),
        Rule("trend_p99_creep", "serving", DEGRADED,
             _probe_trend_p99,
             env="H2O3TPU_HEALTH_TREND_P99_CREEP", default=0.1,
             unit="ratio", source_series="derived.p99_slo_ratio",
             description="a resident model's p99/SLO ratio is rising while "
                         "already near the target — creeping toward an SLO "
                         "breach"),
        Rule("trend_mfu_decline", "compute", DEGRADED,
             _probe_trend_mfu,
             env="H2O3TPU_HEALTH_TREND_MFU_DECLINE", default=0.05,
             unit="MFU", source_series="derived.mfu_min",
             description="a rated loop's utilization slid across the trend "
                         "window — throughput is decaying, not collapsed"),
        Rule("trend_shed_accel", "serving", DEGRADED,
             _probe_trend_shed,
             env="H2O3TPU_HEALTH_TREND_SHED_ACCEL", default=5,
             unit="sheds", source_series="derived.score_shed_total",
             description="scoring sheds accelerated window-over-window — "
                         "overload is compounding, not steady"),
    ]


# -- the evaluator -----------------------------------------------------------

class HealthEvaluator:
    """Background health sweep: a bounded-interval thread running the rule
    set over the live registries, folding results into the subsystem
    verdict ``GET /3/Health`` serves and opening/resolving incidents on
    rule edges. Usable inline too — :meth:`evaluate` is what the REST
    handler calls when no thread is running."""

    def __init__(self, interval_s: float | None = None,
                 rules: list[Rule] | None = None,
                 incidents=None):
        self._interval_explicit = interval_s is not None
        self.interval_s = (interval_s if interval_s is not None
                           else interval_from_env())
        self.rules = list(rules) if rules is not None else default_rules()
        self.incidents = incidents if incidents is not None else INCIDENTS
        # verdict + lifecycle state
        self._lock = lockwitness.lock("utils.health.HealthEvaluator._lock")
        # one evaluation at a time
        self._eval_lock = lockwitness.lock(
            "utils.health.HealthEvaluator._eval_lock")
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._last: dict | None = None
        self._prev: dict[str, float] = {}     # window-delta baselines
        self._streaks: dict[str, int] = {}
        self._series: dict[str, list] = {}
        self._active: set[str] = set()        # rules currently tripped
        self._sweeps = 0
        self._thread_sweeps = 0               # sweeps the THREAD ran

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> bool:
        """Start the sweep thread (idempotent; False when already running
        or disabled via ``H2O3TPU_HEALTH_OFF=1``)."""
        if health_off():
            return False
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return False
            if not self._interval_explicit:
                # the ENV001 lesson: the process-wide evaluator is built at
                # import, but the knob must land when exported before
                # launch — resolve the cadence at start, not import
                self.interval_s = interval_from_env()
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="h2o3-health-sweep")
            self._thread.start()
            return True

    def stop(self, timeout: float = 5.0) -> None:
        with self._lock:
            thread = self._thread
            self._thread = None
            # set INSIDE the lock: set-after-release races a concurrent
            # start() — it could clear a new thread's event (killing the
            # sweep it just launched) or miss the old one entirely
            self._stop.set()
        if thread is not None:
            thread.join(timeout=timeout)

    def running(self) -> bool:
        with self._lock:
            return self._thread is not None and self._thread.is_alive()

    def _run(self) -> None:
        # bounded wait (WTX001): stop() wakes it immediately, the interval
        # bounds it otherwise; the sweep itself never raises out
        from h2o3_tpu.utils import blackbox as _bb
        from h2o3_tpu.utils import timeline as _tl
        while not self._stop.wait(self.interval_s):
            with self._lock:
                if self._thread is not threading.current_thread():
                    # a stop() whose join timed out on a wedged probe left
                    # this thread alive; a later start() must not revive
                    # it — two sweeps would split every window delta
                    return
            # heartbeat BEFORE the sweep: the black-box watchdog pages on
            # silence, and the sweep body is exactly what can wedge (the
            # chaos seam below is the injectable stall the bench drives;
            # BLACKBOX looked up per sweep so tests can swap the instance)
            _bb.BLACKBOX.beat("health_sweep")
            if _tl.FAULTS is not None:
                _tl.FAULTS.maybe_fault("health.sweep")
            try:
                # a stop() landing while this sweep is in flight drains it:
                # the abort seam is checked between rules AND between a
                # probe and its incident open, so the final sweep can never
                # open an incident after shutdown
                verdict = self.evaluate(abort=self._stop.is_set)
                if verdict is None:
                    return
                with self._eval_lock:
                    # thread-driven sweeps counted apart from inline
                    # evaluate() calls: the bench's hollow-watchdog guard
                    # must prove the WATCHDOG ran, not its own probes
                    self._thread_sweeps += 1
            except Exception:   # noqa: BLE001 — the watcher must outlive
                _LOG.exception("health sweep failed")   # what it watches

    # -- window helpers (probes call back into these) ------------------------

    def _delta(self, key: str, total: float) -> float:
        """Counter movement since the previous sweep; the FIRST sweep
        baselines (returns 0) so pre-existing totals never page."""
        prev = self._prev.get(key)
        # graftlint: ok(probes only run inside evaluate() under _eval_lock)
        self._prev[key] = total
        return 0.0 if prev is None else max(total - prev, 0.0)

    def _streak(self, key: str, condition: bool) -> int:
        # graftlint: ok(probes only run inside evaluate() under _eval_lock)
        self._streaks[key] = self._streaks.get(key, 0) + 1 if condition else 0
        return self._streaks[key]

    # -- evaluation ----------------------------------------------------------

    def evaluate(self, abort=None) -> "dict | None":
        """Run every rule once; fold into the verdict; open/resolve
        incidents on rule edges. Thread-safe and re-entrant-free (one
        evaluation at a time — window deltas must not interleave).
        ``abort`` (a zero-arg truth callable — the sweep thread passes its
        stop flag) drains the sweep: checked between rules and between a
        probe and its incident open, an aborted sweep returns ``None``
        without opening incidents or publishing a verdict."""
        t0 = time.perf_counter()
        with self._eval_lock:
            verdict = self._evaluate_locked(abort)
        if verdict is not None:
            # aborted (drained) sweeps don't observe: a shutdown-time
            # partial sweep would poison the duration distribution low
            HEALTH_SWEEP_SECONDS.observe(time.perf_counter() - t0)
        return verdict

    def _evaluate_locked(self, abort=None) -> "dict | None":
        # graftlint: ok(_locked suffix: serialized by _eval_lock above)
        self._sweeps += 1
        findings: list[dict] = []
        statuses = {s: HEALTHY for s in SUBSYSTEMS}
        tripped_rules: set[str] = set()
        failed_rules: set[str] = set()
        for rule in self.rules:
            if abort is not None and abort():
                return None
            try:
                observed = rule.probe(self)
            except Exception as e:   # noqa: BLE001 — a sick registry is a
                # finding, not a sweep crash
                failed_rules.add(rule.name)
                findings.append({"rule": rule.name,
                                 "subsystem": rule.subsystem,
                                 "severity": DEGRADED, "observed": None,
                                 "threshold": None,
                                 "message": f"probe failed: "
                                            f"{type(e).__name__}: {e}"})
                statuses[rule.subsystem] = max(
                    statuses[rule.subsystem], DEGRADED, key=_RANK.get)
                continue
            # graftlint: ok(_locked suffix: caller holds _eval_lock)
            series = self._series.setdefault(rule.name, [])
            if observed is not None:
                series.append(observed)
                del series[:-SERIES_LEN]
                # every rule's observed value is ALSO a retained flight
                # series (health.rule.<name>) — the incident ±window and
                # /3/TimeSeries read it; a no-op when the recorder is off
                try:
                    _fl.FLIGHT.ingest(f"health.rule.{rule.name}", observed)
                except Exception:   # noqa: BLE001 — recording must never
                    pass            # break evaluating
            threshold = rule.threshold()
            if not rule.tripped(observed, threshold):
                continue
            tripped_rules.add(rule.name)
            cmp = ">" if rule.direction == "above" else "<"
            message = (f"{rule.name}: observed {observed} {cmp} threshold "
                       f"{threshold}{' ' + rule.unit if rule.unit else ''} "
                       f"— {rule.description}")
            findings.append({"rule": rule.name, "subsystem": rule.subsystem,
                             "severity": rule.severity, "observed": observed,
                             "threshold": threshold, "message": message})
            statuses[rule.subsystem] = max(
                statuses[rule.subsystem], rule.severity, key=_RANK.get)
            if abort is not None and abort():
                # the stop flag rose while this rule's probe ran — the
                # drained sweep must not open an incident after shutdown
                return None
            self.incidents.open(rule.name, rule.subsystem, rule.severity,
                                message, observed, threshold,
                                series=series,
                                source_series=rule.source_series)
        # falling edges resolve their incidents — but a FAILED probe is
        # blindness, not recovery: a rule whose probe raised stays in
        # whatever state it was (an open incident must not read "resolved"
        # because the registry it watches got sick)
        for name in self._active - tripped_rules - failed_rules:
            self.incidents.resolve(name)
        # graftlint: ok(_locked suffix: caller holds _eval_lock)
        self._active = tripped_rules | (self._active & failed_rules)
        overall = max(statuses.values(), key=_RANK.get)
        verdict = {
            "status": overall,
            "healthy": overall == HEALTHY,
            "subsystems": {
                s: {"status": statuses[s],
                    "findings": [f for f in findings
                                 if f["subsystem"] == s]}
                for s in SUBSYSTEMS},
            "findings": findings,
            "sweep": self._sweeps,
            "interval_s": self.interval_s,
            "evaluated_ms": int(time.time() * 1000),
            "open_incidents": self.incidents.open_rules(),
            "rules": [{"rule": r.name, "subsystem": r.subsystem,
                       "severity": r.severity, "threshold": r.threshold(),
                       "direction": r.direction, "unit": r.unit,
                       "env": r.env}
                      for r in self.rules],
        }
        with self._lock:
            self._last = verdict
        return verdict

    def verdict(self) -> dict:
        """What ``GET /3/Health`` serves: the sweep thread's latest verdict
        when one is running (staleness bounded by the interval), else an
        inline evaluation. Disabled (``H2O3TPU_HEALTH_OFF=1``) reports so
        instead of pretending health was checked."""
        if health_off():
            return {"status": "disabled", "healthy": None,
                    "subsystems": {}, "findings": [], "sweep": 0,
                    "open_incidents": [],
                    "message": "H2O3TPU_HEALTH_OFF=1 — evaluator disabled"}
        if self.running():
            with self._lock:
                if self._last is not None:
                    return self._last
        return self.evaluate()

    def last_verdict(self) -> "dict | None":
        """The most recently PUBLISHED verdict, never evaluating inline —
        the flight recorder's health-status series reads this each tick
        (a recorder tick must not become a health sweep)."""
        with self._lock:
            return self._last

    def sweeps(self) -> int:
        with self._eval_lock:
            return self._sweeps

    def thread_sweeps(self) -> int:
        """Sweeps the background THREAD ran (inline :meth:`evaluate`
        calls excluded) — the hollow-watchdog proof."""
        with self._eval_lock:
            return self._thread_sweeps

    def reset(self) -> None:
        """Forget window baselines/streaks/verdict (tests/bench)."""
        with self._eval_lock:
            self._prev.clear()
            self._streaks.clear()
            self._series.clear()
            self._active = set()
            self._sweeps = 0
            self._thread_sweeps = 0
            with self._lock:
                self._last = None


#: the process-wide evaluator (started by ``H2OServer.start``)
HEALTH = HealthEvaluator()


# -- the diagnostic bundle (`h2o logs download` analog) ----------------------

#: env names whose values never leave the process in a bundle
_SECRET_RE = re.compile(
    r"(SECRET|TOKEN|PASSWORD|PASSWD|CREDENTIAL|API_?KEY|ACCESS_?KEY"
    r"|PRIVATE|AUTH|COOKIE|CERT)", re.IGNORECASE)

#: env prefixes worth shipping — the runtime's own knobs plus the JAX/XLA
#: flags that change compiled behavior
_CONFIG_PREFIXES = ("H2O3TPU_", "JAX_", "XLA_", "LIBTPU_", "TPU_")


def redacted_config() -> dict:
    """The config/env knob dump: every tunable that shapes this process,
    with secret-looking names redacted BY NAME (a secret accidentally
    exported under a knob-looking name still ships — redaction is a
    name-pattern contract, documented in docs/OBSERVABILITY.md)."""
    out = {}
    for name in sorted(os.environ):
        if not name.startswith(_CONFIG_PREFIXES):
            continue
        out[name] = ("[redacted]" if _SECRET_RE.search(name)
                     else os.environ[name])
    return out


def hardware_fingerprint() -> dict:
    """Backend identity for the bundle — which hardware produced these
    numbers (the bench artifact's `extra.hardware` sibling)."""
    import platform
    out: dict = {"python": platform.python_version(),
                 "platform": platform.platform()}
    try:
        import jax
        import jaxlib
        devs = jax.devices()
        out.update(backend=jax.default_backend(),
                   device_kind=devs[0].device_kind if devs else None,
                   devices=len(devs), jax=jax.__version__,
                   jaxlib=jaxlib.__version__)
    except Exception as e:   # noqa: BLE001 — a sick backend still bundles
        out["backend_error"] = f"{type(e).__name__}: {e}"
    return out


def _trace_export() -> dict:
    from h2o3_tpu.utils.tracing import TRACER
    summaries = TRACER.list_traces()
    spans = {}
    for t in summaries[:8]:
        try:
            spans[t["trace_id"]] = TRACER.get_trace(t["trace_id"])
        except KeyError:     # evicted between list and get — ring churn
            continue
    return {"traces": summaries, "spans": spans}


def _jsonable(obj) -> bytes:
    return json.dumps(obj, indent=1, default=str).encode()


def diagnostic_bundle(evaluator: HealthEvaluator | None = None
                      ) -> "tuple[bytes, str]":
    """One call, everything an operator needs: a gzip tar of all four
    pillar snapshots (metrics, traces, memory, compute), the health
    verdict, the incident ring (contexts included), the ActionLog, the
    flight-recorder time series, the log ring, the hardware fingerprint,
    and the redacted config dump. Returns
    ``(bytes, filename)`` — the ``POST /3/Diagnostics/bundle`` payload
    and what both clients save to disk."""
    ev = evaluator if evaluator is not None else HEALTH
    members: list[tuple[str, bytes]] = []

    def add(name: str, build) -> None:
        try:
            members.append((name, build()))
        except Exception as e:   # noqa: BLE001 — a sick pillar must not
            # sink the whole bundle; its slot records the failure
            members.append((name + ".error",
                            f"{type(e).__name__}: {e}".encode()))

    add("metrics.json", lambda: _jsonable(_tm.METRICS.snapshot()))
    add("metrics.prom", lambda: _tm.METRICS.to_openmetrics().encode())
    add("traces.json", lambda: _jsonable(_trace_export()))
    add("memory.json", lambda: _memory_summary_bytes())
    add("compute.json", lambda: _compute_snapshot_bytes())
    add("health.json", lambda: _jsonable(ev.verdict()))
    add("incidents.json", lambda: _jsonable(ev.incidents.export()))
    add("actions.json", lambda: _jsonable(_actions_export()))
    add("timeseries.json", lambda: _jsonable(_fl.FLIGHT.export()))
    add("logs.txt",
        lambda: "\n".join(_tm.install_log_ring().lines()).encode())
    add("hardware.json", lambda: _jsonable(hardware_fingerprint()))
    add("config.json", lambda: _jsonable(redacted_config()))

    buf = io.BytesIO()
    now = int(time.time())
    with tarfile.open(fileobj=buf, mode="w:gz") as tar:
        for name, data in members:
            info = tarfile.TarInfo(name=f"h2o3_diagnostics/{name}")
            info.size = len(data)
            info.mtime = now
            tar.addfile(info, io.BytesIO(data))
    return buf.getvalue(), f"h2o3_diagnostics_{now}.tar.gz"


def _actions_export() -> list:
    """The ActionLog, newest first — only when the ops plane is loaded
    (the bundle must not be the thing that imports it)."""
    import sys
    acts = sys.modules.get("h2o3_tpu.ops_plane.actions")
    return acts.ACTIONS.list() if acts is not None else []


def _memory_summary_bytes() -> bytes:
    from h2o3_tpu.utils.memory import MEMORY
    return _jsonable(MEMORY.summary())


def _compute_snapshot_bytes() -> bytes:
    from h2o3_tpu.utils.costs import COSTS
    return _jsonable(COSTS.snapshot())
