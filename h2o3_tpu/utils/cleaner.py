"""Cleaner — LRU/idle-streak spill of cold DKV values under memory pressure.

Reference: ``water/Cleaner.java:10-12`` — a background sweeper that writes
the least-recently-used DKV byte[] values to the ice_root when the heap
crosses a watermark, transparently reloading them on next access
(``water/Value.java`` spill state); ``water/MemoryManager.java`` tracks the
budget.

TPU-native, three eviction tiers (cheapest first):

1. **Derived-view drop** — a compressed Vec's materialized device array is
   a VIEW of its host payload (``ingest/encode``): dropping it frees device
   bytes at zero I/O cost, and the next access decompresses it back (the
   PR 9 ``{key}::mesh[...]`` view-cascade template applied to chunks).
2. **Mesh-view removal** — DKV-registered resharded views rebuild from
   their source columns; spilling one would snapshot data nobody reloads.
3. **Per-value spill** — cold DKV values (frames AND raw upload payloads)
   go to the ice_root; the key holds a :class:`SwappedFrame` /
   :class:`SwappedValue` stub whose on-disk bytes stay registered under
   the ``spilled`` kind so ``/3/Memory`` reconciles across a sweep.
   ``DKV.get`` resolves stubs by reloading (fault-in) and sweeps again.

Victims are chosen by the PR 5 accounting: per-key registered bytes order
what's worth spilling, and the leak detector's **idle streaks** (sweeps
with no DKV access) rank coldness ahead of the LRU clock — a key idle for
four sweeps is colder than anything last-touch ordering alone can prove.

Enable with ``enable_cleaner(budget_bytes)`` or ``H2O3TPU_HBM_BUDGET``
(bytes; off by default — a single-chip v5e holds 16GB and most jobs never
need spill).
"""

from __future__ import annotations

import contextlib
import os
import shutil
import tempfile
import time
import uuid

from h2o3_tpu.utils import lockwitness
from h2o3_tpu.utils import telemetry as _tm
from h2o3_tpu.utils.registry import DKV


def discard_snapshot(path: str) -> None:
    """Delete a spill artifact: frame snapshots are DIRECTORIES
    (columns.npz + frame.json), raw spills are files — a bare os.remove
    on the former raises and silently leaks the ice_root forever. Taken
    under the Cleaner IO lock so a discard can never tear a snapshot out
    from under a concurrent fault-in load (reentrant from sweep/resolve)."""
    with CLEANER._io_lock:
        with contextlib.suppress(OSError):
            if os.path.isdir(path):
                shutil.rmtree(path, ignore_errors=True)
            else:
                os.remove(path)


class SwappedFrame:
    """DKV stub for a spilled frame (reference: Value on-disk state)."""

    def __init__(self, key: str, path: str, nrows: int, ncols: int,
                 disk_bytes: int = 0):
        self.key = key
        self.path = path
        self.nrows = nrows
        self.ncols = ncols
        self.disk_bytes = int(disk_bytes)

    def __repr__(self) -> str:
        return f"SwappedFrame({self.key} @ {self.path})"


class SwappedValue:
    """DKV stub for a spilled non-frame value (today: RawFile payloads)."""

    def __init__(self, key: str, path: str, value_kind: str,
                 disk_bytes: int, meta: dict | None = None):
        self.key = key
        self.path = path
        self.value_kind = value_kind
        self.disk_bytes = int(disk_bytes)
        self.meta = meta or {}

    def __repr__(self) -> str:
        return f"SwappedValue({self.key} [{self.value_kind}] @ {self.path})"


class Cleaner:
    def __init__(self, budget_bytes: int | None = None,
                 ice_root: str | None = None):
        env = os.environ.get("H2O3TPU_HBM_BUDGET")
        self.budget = budget_bytes if budget_bytes is not None else (
            int(env) if env else None)
        self.ice_root = ice_root or os.path.join(
            tempfile.gettempdir(), "h2o3_tpu_ice")
        # LRU bookkeeping is mutated from every DKV.put/get/remove caller
        # thread; the lock keeps it owned HERE — callers must use touch/
        # forget, never reach into ``_touch`` (graftlint LCK003)
        self._lock = lockwitness.lock("utils.cleaner.Cleaner._lock")
        self._touch: dict[str, float] = {}
        # serializes spill-side disk I/O against fault-in: a sweep rewriting
        # a key's snapshot while a concurrent ``resolve`` reads it is a torn
        # read (half-written frame.json). Reentrant because a fault-in's own
        # DKV.put re-enters sweep on the same thread.
        self._io_lock = lockwitness.rlock("utils.cleaner.Cleaner._io_lock")
        # spill/restore accounting (served in /3/Memory's ``spill`` view)
        self._spills = 0
        self._spill_bytes = 0
        self._restores = 0
        self._restore_bytes = 0
        self._view_drops = 0
        self._view_drop_bytes = 0

    # -- bookkeeping ---------------------------------------------------------

    @staticmethod
    def _frame_bytes(fr) -> int:
        """Resident bytes of a frame WITHOUT forcing lazy materialization
        (``Frame.nbytes`` → ``vec_nbytes`` reads the raw device slot)."""
        return int(getattr(fr, "nbytes", 0) or 0)

    @staticmethod
    def _value_bytes(v) -> int:
        tname = type(v).__name__
        if tname == "Frame":
            return Cleaner._frame_bytes(v)
        if tname == "RawFile":
            return len(getattr(v, "data", b"") or b"")
        return 0

    def touch(self, key: str) -> None:
        with self._lock:
            self._touch[key] = time.monotonic()

    def forget(self, key: str) -> None:
        """Drop LRU state for a removed key (DKV.remove calls this)."""
        with self._lock:
            self._touch.pop(key, None)

    def forget_all(self) -> None:
        """Drop all LRU state (DKV.clear calls this)."""
        with self._lock:
            self._touch.clear()

    def last_touched(self, key: str) -> float:
        with self._lock:
            return self._touch.get(key, 0.0)

    def resident_frames(self):
        from h2o3_tpu.frame.frame import Frame
        out = []
        for k, v in DKV.raw_items():   # raw: get would re-inflate stubs
            if isinstance(v, Frame):
                out.append((k, v))
        return out

    def _spillable_values(self):
        """(key, value) for every DKV value the sweeper may evict: frames
        and raw upload payloads, never jobs/models/stubs."""
        from h2o3_tpu.frame.frame import Frame
        from h2o3_tpu.frame.parse import RawFile
        out = []
        for k, v in DKV.raw_items():
            if isinstance(v, (Frame, RawFile)):
                out.append((k, v))
        return out

    def stats(self) -> dict:
        """The ``/3/Memory`` spill view: budget, live counters, and what is
        currently sitting on disk."""
        spilled = []
        for k, v in DKV.raw_items():
            if isinstance(v, (SwappedFrame, SwappedValue)):
                spilled.append({"key": k, "disk_bytes": v.disk_bytes,
                                "kind": getattr(v, "value_kind", "frame")})
        with self._lock:
            return {"budget_bytes": self.budget, "ice_root": self.ice_root,
                    "spill_count": self._spills,
                    "spill_bytes": self._spill_bytes,
                    "restore_count": self._restores,
                    "restore_bytes": self._restore_bytes,
                    "view_drops": self._view_drops,
                    "view_drop_bytes": self._view_drop_bytes,
                    "spilled_keys": sorted(spilled,
                                           key=lambda r: -r["disk_bytes"]),
                    "spilled_disk_bytes": sum(r["disk_bytes"]
                                              for r in spilled)}

    # -- sweep ---------------------------------------------------------------

    def _cold_order(self, items):
        """Victim order: longest idle streak first (per-key accounting +
        idle-streak detector, utils/memory.py), LRU clock as tiebreak."""
        from h2o3_tpu.utils.memory import MEMORY
        idle = MEMORY.idle_streaks()
        return sorted(items,
                      key=lambda kv: (-idle.get(kv[0], 0),
                                      self.last_touched(kv[0])))

    def sweep(self, protect: str | None = None) -> list[str]:
        """Evict cold values until under budget; returns the spilled keys.

        Tier 1 drops derived device views of compressed columns (free);
        tier 2 removes rebuildable mesh views; tier 3 spills whole values
        to the ice_root behind stubs."""
        if self.budget is None:
            return []
        # every budgeted sweep advances one leak-detector generation: the
        # detector snapshots keyed bytes across sweeps and flags keys that
        # grow or sit untouched for N of them (utils/memory.py)
        from h2o3_tpu.utils.memory import MEMORY
        MEMORY.leak_sweep()
        values = self._spillable_values()
        total = sum(self._value_bytes(v) for _, v in values)
        if total <= self.budget:
            return []
        # -- tier 1: drop decompress-on-access device views ------------------
        for k, v in self._cold_order(values):
            if total <= self.budget or k == protect:
                continue
            drop = getattr(v, "drop_device_views", None)
            if drop is None:
                continue
            freed = drop()
            if freed:
                total -= freed
                with self._lock:
                    self._view_drops += 1
                    self._view_drop_bytes += freed
                _tm.CHUNK_VIEW_DROPS.inc()
                _tm.CHUNK_VIEW_DROP_BYTES.inc(freed)
                MEMORY.register(k, v)   # re-account the slimmer frame
        if total <= self.budget:
            return []
        # -- tiers 2+3: remove mesh views / spill whole values ---------------
        os.makedirs(self.ice_root, exist_ok=True)
        spilled = []
        from h2o3_tpu.persist.frame_io import save_frame, snapshot_bytes
        with self._io_lock:    # never rewrite a snapshot a fault-in is reading
            for k, v in self._cold_order(values):
                if total <= self.budget:
                    break
                if k == protect:
                    continue
                with DKV._lock:    # raw read: is this value still current?
                    if DKV._store.get(k) is not v:
                        continue   # re-put/removed/restored since snapshot
                nbytes = self._value_bytes(v)
                if getattr(v, "_is_mesh_view", False):
                    # resharded mesh views (Frame.on_mesh) rebuild from
                    # their source columns on next use — spilling one would
                    # write a snapshot nobody ever reloads and leave a stub
                    # posing as a user frame; just drop it. Identity-checked
                    # INSIDE remove (only_if): holding DKV._lock around the
                    # remove here would invert the io->store lock order the
                    # fault-in path relies on (DLK001)
                    DKV.remove(k, only_if=v)
                elif type(v).__name__ == "RawFile":
                    # unique path per spill: a restored key's snapshot is
                    # discarded AFTER install, and a re-spill racing that
                    # discard must never share the deleted path
                    path = os.path.join(
                        self.ice_root, f"{k}.{uuid.uuid4().hex[:8]}.raw")
                    with open(path, "wb") as fh:
                        fh.write(v.data)
                    stub = SwappedValue(k, path, "raw", len(v.data),
                                        meta={"name": v.name})
                    if not self._cas_stub(k, v, stub):
                        continue     # key changed during the write: no spill
                    self._note_spill("raw", len(v.data))
                else:
                    path = os.path.join(
                        self.ice_root, f"{k}.{uuid.uuid4().hex[:8]}")
                    save_frame(v, path)
                    stub = SwappedFrame(k, path, v.nrows, v.ncols,
                                        disk_bytes=snapshot_bytes(path))
                    if not self._cas_stub(k, v, stub):
                        continue
                    self._note_spill("frame", nbytes)
                total -= nbytes
                spilled.append(k)
        return spilled

    def force_spill(self, keys, limit: int = 2) -> list[str]:
        """Targeted tier-3 spill of named DKV keys regardless of budget
        headroom — the ops-plane's coldest-tenant relief (data is parked
        on disk behind a stub, NEVER deleted; the next get faults it
        back). Only frames and raw payloads spill (mesh views and
        models/jobs are skipped); bounded by ``limit``. Returns the keys
        actually spilled."""
        from h2o3_tpu.frame.frame import Frame
        from h2o3_tpu.frame.parse import RawFile
        from h2o3_tpu.persist.frame_io import save_frame, snapshot_bytes
        os.makedirs(self.ice_root, exist_ok=True)
        spilled: list[str] = []
        with self._io_lock:
            for k in keys:
                if len(spilled) >= limit:
                    break
                with DKV._lock:
                    v = DKV._store.get(k)
                if isinstance(v, RawFile):
                    path = os.path.join(
                        self.ice_root, f"{k}.{uuid.uuid4().hex[:8]}.raw")
                    with open(path, "wb") as fh:
                        fh.write(v.data)
                    stub = SwappedValue(k, path, "raw", len(v.data),
                                        meta={"name": v.name})
                    if self._cas_stub(k, v, stub):
                        self._note_spill("raw", len(v.data))
                        spilled.append(k)
                elif isinstance(v, Frame) \
                        and not getattr(v, "_is_mesh_view", False):
                    nbytes = self._value_bytes(v)
                    path = os.path.join(
                        self.ice_root, f"{k}.{uuid.uuid4().hex[:8]}")
                    save_frame(v, path)
                    stub = SwappedFrame(k, path, v.nrows, v.ncols,
                                        disk_bytes=snapshot_bytes(path))
                    if self._cas_stub(k, v, stub):
                        self._note_spill("frame", nbytes)
                        spilled.append(k)
        return spilled

    def _cas_stub(self, key: str, expected, stub) -> bool:
        """Install a spill stub ONLY while the store still holds the value
        the snapshot was taken from. The snapshot write happens outside the
        store lock (it's slow), so a concurrent put of a NEW value under
        the same key must win — otherwise the stub would resurrect stale
        data on the next fault-in (lost update)."""
        if not DKV.replace_if(key, expected, stub):
            discard_snapshot(stub.path)
            return False
        return True

    def _note_spill(self, kind: str, nbytes: int) -> None:
        with self._lock:
            self._spills += 1
            self._spill_bytes += nbytes
        _tm.SPILLS.labels(kind=kind).inc()
        _tm.SPILL_BYTES.labels(kind=kind).inc(nbytes)

    def _note_restore(self, kind: str, nbytes: int) -> None:
        with self._lock:
            self._restores += 1
            self._restore_bytes += nbytes
        _tm.SPILL_RESTORES.labels(kind=kind).inc()
        _tm.SPILL_RESTORE_BYTES.labels(kind=kind).inc(nbytes)

    def _resolve_loop(self, key: str, stub, live_type, load, kind: str):
        """Shared fault-in driver: load the snapshot under the IO lock,
        CAS the restored value in, and on a lost race ADOPT whatever
        superseded our stub — a live value wins outright, a NEWER stub
        (the key was re-put and re-spilled mid-restore) is resolved in its
        place (never hand back the stale load), and a concurrent remove is
        honored (the load is returned but never resurrected). Bounded —
        under pathological thrash the latest load is still correct data
        for the caller."""
        value = None
        for _ in range(8):
            with self._io_lock:
                with DKV._lock:
                    cur = DKV._store.get(key)
                if isinstance(cur, live_type):
                    return cur            # racing restore/user-put won
                if type(cur).__name__ in ("SwappedFrame", "SwappedValue") \
                        and cur is not stub:
                    stub = cur            # newer spill superseded ours
                elif cur is None and value is not None:
                    return value          # removed mid-restore: honor it
                try:
                    value = load(stub)
                except OSError:
                    # the key was removed AND its snapshot discarded before
                    # we got the IO lock — the key is simply gone
                    return value
            if DKV.replace_if(key, stub, value):
                self._note_restore(kind, self._value_bytes(value)
                                   or getattr(stub, "disk_bytes", 0))
                discard_snapshot(stub.path)   # store owns the data again
                self.touch(key)
                self.sweep(protect=key)
                return value
        return value

    def resolve(self, key: str, stub: SwappedFrame):
        """Fault a spilled frame back in (sweeping others to stay under
        budget). Serialized against sweeps via the IO lock, and installed
        by compare-and-swap: a racing restore/user-put wins — never hand
        back a torn load, never resurrect stale data."""
        from h2o3_tpu.frame.frame import Frame
        from h2o3_tpu.persist.frame_io import load_frame

        def load(st):
            fr = load_frame(st.path)
            fr.key = key
            return fr

        return self._resolve_loop(key, stub, Frame, load, "frame")

    def resolve_value(self, key: str, stub: SwappedValue):
        """Fault a spilled non-frame value back in."""
        if stub.value_kind != "raw":
            raise ValueError(f"unknown spilled value kind {stub.value_kind!r}")
        from h2o3_tpu.frame.parse import RawFile

        def load(st):
            with open(st.path, "rb") as fh:
                return RawFile(fh.read(), name=st.meta.get("name", "upload"))

        return self._resolve_loop(key, stub, RawFile, load, "raw")


CLEANER = Cleaner()


def enable_cleaner(budget_bytes: int, ice_root: str | None = None) -> Cleaner:
    """Turn on automatic spill with the given resident-byte budget."""
    CLEANER.budget = int(budget_bytes)
    if ice_root:
        CLEANER.ice_root = ice_root
    return CLEANER


def disable_cleaner() -> None:
    CLEANER.budget = None
