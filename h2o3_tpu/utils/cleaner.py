"""Cleaner — automatic LRU spill of cold frames under HBM pressure.

Reference: ``water/Cleaner.java:10-12`` — a background sweeper that writes
the least-recently-used DKV byte[] values to the ice_root when the heap
crosses a watermark, transparently reloading them on next access
(``water/Value.java`` spill state); ``water/MemoryManager.java`` tracks the
budget.

TPU-native: HBM is the scarce memory. The Cleaner tracks the device bytes
of every DKV-resident Frame, and past a configurable budget swaps the
least-recently-USED frames to the spill directory via the frame persist
format. A swapped key holds a :class:`SwappedFrame` stub; ``DKV.get``
resolves stubs by reloading (and sweeps again, possibly evicting something
else). Enable with ``enable_cleaner(budget_bytes)`` or the
``H2O3TPU_HBM_BUDGET`` env var (bytes; off by default — a single-chip v5e
holds 16GB and most jobs never need spill).
"""

from __future__ import annotations

import os
import tempfile
import threading
import time

from h2o3_tpu.utils.registry import DKV


class SwappedFrame:
    """DKV stub for a spilled frame (reference: Value on-disk state)."""

    def __init__(self, key: str, path: str, nrows: int, ncols: int):
        self.key = key
        self.path = path
        self.nrows = nrows
        self.ncols = ncols

    def __repr__(self) -> str:
        return f"SwappedFrame({self.key} @ {self.path})"


class Cleaner:
    def __init__(self, budget_bytes: int | None = None,
                 ice_root: str | None = None):
        env = os.environ.get("H2O3TPU_HBM_BUDGET")
        self.budget = budget_bytes if budget_bytes is not None else (
            int(env) if env else None)
        self.ice_root = ice_root or os.path.join(
            tempfile.gettempdir(), "h2o3_tpu_ice")
        # LRU bookkeeping is mutated from every DKV.put/get/remove caller
        # thread; the lock keeps it owned HERE — callers must use touch/
        # forget, never reach into ``_touch`` (graftlint LCK003)
        self._lock = threading.Lock()
        self._touch: dict[str, float] = {}

    # -- bookkeeping ---------------------------------------------------------

    @staticmethod
    def _frame_bytes(fr) -> int:
        total = 0
        for v in getattr(fr, "vecs", []):
            if v.data is not None:
                total += v.data.size * v.data.dtype.itemsize
        return total

    def touch(self, key: str) -> None:
        with self._lock:
            self._touch[key] = time.monotonic()

    def forget(self, key: str) -> None:
        """Drop LRU state for a removed key (DKV.remove calls this)."""
        with self._lock:
            self._touch.pop(key, None)

    def forget_all(self) -> None:
        """Drop all LRU state (DKV.clear calls this)."""
        with self._lock:
            self._touch.clear()

    def last_touched(self, key: str) -> float:
        with self._lock:
            return self._touch.get(key, 0.0)

    def resident_frames(self):
        from h2o3_tpu.frame.frame import Frame
        out = []
        with DKV._lock:   # RAW store: DKV.get would re-inflate swapped stubs
            items = list(DKV._store.items())
        for k, v in items:
            if isinstance(v, Frame):
                out.append((k, v))
        return out

    # -- sweep ---------------------------------------------------------------

    def sweep(self, protect: str | None = None) -> list[str]:
        """Spill LRU frames until under budget; returns spilled keys."""
        if self.budget is None:
            return []
        # every budgeted sweep advances one leak-detector generation: the
        # detector snapshots keyed bytes across sweeps and flags keys that
        # grow or sit untouched for N of them (utils/memory.py)
        from h2o3_tpu.utils.memory import MEMORY
        MEMORY.leak_sweep()
        frames = self.resident_frames()
        total = sum(self._frame_bytes(f) for _, f in frames)
        if total <= self.budget:
            return []
        os.makedirs(self.ice_root, exist_ok=True)
        order = sorted(frames, key=lambda kv: self.last_touched(kv[0]))
        spilled = []
        from h2o3_tpu.persist.frame_io import save_frame
        for k, fr in order:
            if total <= self.budget:
                break
            if k == protect:
                continue
            if getattr(fr, "_is_mesh_view", False):
                # resharded mesh views (Frame.on_mesh) rebuild from their
                # source columns on next use — spilling one would write a
                # snapshot nobody ever reloads and leave a SwappedFrame
                # stub posing as a user frame; just drop it
                DKV.remove(k)
            else:
                path = os.path.join(self.ice_root, k)
                save_frame(fr, path)
                DKV.put(k, SwappedFrame(k, path, fr.nrows, fr.ncols))
            total -= self._frame_bytes(fr)
            spilled.append(k)
        return spilled

    def resolve(self, key: str, stub: SwappedFrame):
        """Reload a spilled frame (sweeping others to stay under budget)."""
        from h2o3_tpu.persist.frame_io import load_frame
        fr = load_frame(stub.path, key=key)
        DKV.put(key, fr)
        self.touch(key)
        self.sweep(protect=key)
        return fr


CLEANER = Cleaner()


def enable_cleaner(budget_bytes: int, ice_root: str | None = None) -> Cleaner:
    """Turn on automatic spill with the given HBM budget (bytes)."""
    CLEANER.budget = int(budget_bytes)
    if ice_root:
        CLEANER.ice_root = ice_root
    return CLEANER


def disable_cleaner() -> None:
    CLEANER.budget = None
