"""Timeline + profiling — observability for the TPU runtime.

Reference: ``water/TimeLine.java:12-42`` — per-node lock-free ring buffer of
the last 2048 network events (every UDP/TCP send/recv, nanotime, drop bits),
snapshotted cluster-wide via ``water/api/TimelineHandler``; sampling profiler
``water/util/ProfileCollectorTask`` + ``JStackCollectorTask`` behind
``/3/Profiler`` and ``/3/JStack``; per-process CPU/IO meters
(``WaterMeterCpuTicks``, ``WaterMeterIo``).

TPU-native mapping: the "network events" of this runtime are **device
dispatches and collectives** (jit calls, host↔device transfers) — recorded
into the same fixed-size ring buffer; thread stacks come from
``sys._current_frames`` (the JStack analog); deep kernel-level profiles
delegate to ``jax.profiler`` traces (the XLA-native tool); CPU/IO meters read
``/proc``.
"""

from __future__ import annotations

import contextlib
import contextvars
import sys
import threading
import time
import traceback

from h2o3_tpu.utils import lockwitness
from h2o3_tpu.utils import tracing as _tracing

RING_SIZE = 2048   # reference: TimeLine.MAX_EVENTS=2048


class TimeLine:
    """Fixed-size event ring (reference: water/TimeLine ring buffer).

    Events carry a monotonic **epoch**: :meth:`clear` bumps it instead of
    swapping the buffer out, so a reader that raced a clear can never be
    served stale-index events from the previous generation — snapshot
    filters on the epoch it captured under the lock."""

    def __init__(self, size: int = RING_SIZE):
        self._size = size
        # (ns, kind, what, dur_ns, epoch)
        self._events: list[tuple] = [None] * size
        self._idx = 0
        self._epoch = 0
        self._lock = lockwitness.lock("utils.timeline.TimeLine._lock")

    def record(self, kind: str, what: str, dur_ns: int = 0) -> None:
        with self._lock:
            self._events[self._idx % self._size] = (
                time.time_ns(), kind, what, dur_ns, self._epoch)
            self._idx += 1

    def snapshot(self) -> list[dict]:
        """Events oldest→newest (reference: TimelineHandler snapshot)."""
        with self._lock:
            epoch = self._epoch
            n = min(self._idx, self._size)
            start = self._idx - n
            evs = [self._events[(start + i) % self._size] for i in range(n)]
        return [dict(ns=e[0], kind=e[1], what=e[2], dur_ns=e[3])
                for e in evs if e is not None and e[4] == epoch]

    def clear(self) -> None:
        # epoch bump retires every live event without reallocating the
        # buffer or letting a concurrent snapshot mix generations
        with self._lock:
            self._epoch += 1
            self._idx = 0


TIMELINE = TimeLine()


class timed_event:
    """Context manager recording a timed event into the global timeline.

    ``observe`` optionally takes a telemetry histogram child (anything
    with an ``observe(seconds)`` method) so convergence-loop call sites
    feed the ``h2o3_iteration_seconds`` histogram and the timeline ring
    from one wrapper. The same wrapper also opens a child **span** under
    the active trace (:mod:`h2o3_tpu.utils.tracing`) — IRLS iterations,
    DL epochs, and GBM chunks become span-tree nodes with zero extra
    instrumentation at the call sites (and zero cost when no trace is
    active: the span hook is a contextvar read returning None)."""

    def __init__(self, kind: str, what: str, observe=None):
        self.kind, self.what = kind, what
        self._observe = observe
        self._mem0 = None

    def __enter__(self):
        self._scope = _tracing.TRACER.span(self.what, kind=self.kind)
        self._span = self._scope.__enter__()
        if self.kind == "model":
            # device-byte attribution at build granularity (two full samples
            # per fit — never per iteration, where the live-array fallback
            # walk would cost); also advances the host/device watermarks
            from h2o3_tpu.utils.memory import MEMORY
            try:
                self._mem0 = MEMORY.sample()
            except Exception:   # noqa: BLE001 — metering must never break a fit
                self._mem0 = None
        self._t0 = time.time_ns()
        return self

    def __exit__(self, *exc):
        dur_ns = time.time_ns() - self._t0
        TIMELINE.record(self.kind, self.what, dur_ns)
        if self._observe is not None:
            self._observe.observe(dur_ns / 1e9)
        if self._mem0 is not None:
            from h2o3_tpu.utils.memory import MEMORY
            try:
                rss1, dev1 = MEMORY.sample()
                peak = max(self._mem0[1], dev1)
                if self._span is not None:
                    # the fit span carries its own peak/delta; the trace
                    # ROOT max-merges the peak so "which build ate HBM" is
                    # one attr lookup on the root (docs/OBSERVABILITY.md)
                    self._span.set_attrs(
                        peak_device_bytes=peak,
                        device_bytes_delta=dev1 - self._mem0[1],
                        host_rss_bytes=rss1)
                    _tracing.TRACER.annotate_root(
                        self._span.trace_id, peak_device_bytes=peak)
            except Exception:   # noqa: BLE001
                pass
        self._scope.__exit__(*exc)
        return False


def jstack(exclude: "set[int] | None" = None) -> list[dict]:
    """All Python thread stacks (reference: JStackCollectorTask → /3/JStack).

    ``exclude`` drops the given thread idents — the sampling profiler passes
    its own ident so profiles show real work, not the sampler itself
    (reference: ProfileCollectorTask skips the collector thread)."""
    frames = sys._current_frames()
    out = []
    for th in threading.enumerate():
        if exclude and th.ident in exclude:
            continue
        fr = frames.get(th.ident)
        stack = traceback.format_stack(fr) if fr is not None else []
        out.append(dict(name=th.name, daemon=th.daemon, alive=th.is_alive(),
                        stack="".join(stack)))
    return out


def cpu_ticks() -> dict:
    """Per-CPU tick counters (reference: WaterMeterCpuTicks reads /proc/stat)."""
    out = {}
    try:
        with open("/proc/stat") as f:
            for line in f:
                if line.startswith("cpu"):
                    parts = line.split()
                    out[parts[0]] = [int(v) for v in parts[1:8]]
    except OSError:
        pass
    return out


def io_stats() -> dict:
    """Process IO counters (reference: WaterMeterIo reads /proc/self/io)."""
    out = {}
    try:
        with open("/proc/self/io") as f:
            for line in f:
                k, _, v = line.partition(":")
                out[k.strip()] = int(v)
    except OSError:
        pass
    return out


#: elastic-worker identity for fault scoping: the elastic group's worker
#: threads run their round work under :func:`worker_scope`, and the injector
#: consults it so a chaos scenario can make EXACTLY ONE worker straggle or
#: die (``FaultInjector(worker_rates={1: {...}})``) while its peers run clean
_WORKER_ID: contextvars.ContextVar["int | str | None"] = \
    contextvars.ContextVar("h2o3_fault_worker_id", default=None)


def current_worker_id() -> "int | str | None":
    """The elastic worker id bound to this context, or None outside one."""
    return _WORKER_ID.get()


@contextlib.contextmanager
def worker_scope(worker_id: "int | str"):
    """Bind an elastic worker id to this thread/task for fault scoping and
    membership attribution (parallel/elastic.py worker threads)."""
    token = _WORKER_ID.set(worker_id)
    try:
        yield
    finally:
        _WORKER_ID.reset(token)


class FaultInjector:
    """Random fault injection for the communication substrate (reference:
    the ``-random_udp_drop`` flag ``water/H2O.java:446`` drops UDP packets to
    exercise the RPC retry path; here faults hit dispatch call sites
    (``map_reduce``, the builders' megastep/chunk dispatches) — a random
    delay models a straggler shard, a raised ``FaultInjected`` models a lost
    reduction (absorbed by the dispatch retry loop, docs/RELIABILITY.md),
    a ``stall`` is a BOUNDED hold on a gate that :meth:`release_stalls` (or
    the bound) releases — a hung worker, as distinct from ``delay``'s fixed
    sleep — and a ``crash`` is process-fatal (``os._exit``) so auto-recovery
    resume paths can be exercised end to end.

    ``site_rates`` overrides rates per call site::

        FaultInjector(site_rates={"gbm_chunk": {"drop_rate": 1.0,
                                                "after": 1}})

    ``after`` skips the first N calls at that site — deterministic
    "fail the second chunk" scenarios for checkpoint-resume tests.

    ``worker_rates`` scopes overrides to ONE elastic worker (keyed by the
    :func:`worker_scope` id the elastic group binds around its round work)::

        FaultInjector(worker_rates={1: {"stall_rate": 1.0,
                                        "stall_ms": 30_000, "after": 2}})

    Worker overrides take precedence over site overrides, which take
    precedence over the global rates; the per-worker ``after``/
    ``crash_after`` thresholds count that worker's own faultable calls.

    Thread-safe: chaos runs under ``windowed_parallel`` hit this from
    concurrent dispatch threads, so the RNG draw and the fault counters
    mutate under one lock (unlocked, concurrent ``random.Random`` calls can
    return duplicate draws and drop increments)."""

    def __init__(self, drop_rate: float = 0.0, delay_ms: float = 0.0,
                 delay_rate: float = 0.0, seed: int = 17,
                 crash_rate: float = 0.0, crash_after: int = 0,
                 stall_ms: float = 0.0, stall_rate: float = 0.0,
                 site_rates: "dict[str, dict] | None" = None,
                 worker_rates: "dict | None" = None):
        import random
        self.drop_rate = drop_rate
        self.delay_ms = delay_ms
        self.delay_rate = delay_rate
        self.crash_rate = crash_rate
        self.stall_ms = stall_ms
        self.stall_rate = stall_rate
        # crash on the Nth faultable call overall (0 = disabled) — the
        # deterministic kill for resume tests
        self.crash_after = int(crash_after)
        self.site_rates = dict(site_rates or {})
        self.worker_rates = dict(worker_rates or {})
        self._rng = random.Random(seed)
        self._lock = lockwitness.lock("utils.timeline.FaultInjector._lock")
        # stall gate: held stalls block on this event up to their bound;
        # release_stalls() wakes every held worker early (bounded hold that
        # RELEASES — a stall can never wedge a test past its bound)
        self._stall_gate = threading.Event()
        self._calls = 0
        self._site_calls: dict[str, int] = {}
        self._worker_calls: dict = {}
        self.dropped = 0
        self.delayed = 0
        self.crashed = 0
        self.stalled = 0

    def _site(self, what: str, key: str, default):
        # precedence: worker override > site override > global rate. A
        # worker block only overrides the keys it names — scoping a fault
        # to one worker means giving ONLY that worker a nonzero rate (the
        # globals stay 0, so its peers run clean).
        wid = current_worker_id()
        if wid is not None and wid in self.worker_rates:
            w = self.worker_rates[wid]
            if key in w:
                return w[key]
        return self.site_rates.get(what, {}).get(key, default)

    def release_stalls(self) -> None:
        """Release every held ``stall`` fault immediately (tests/teardown)."""
        self._stall_gate.set()

    def maybe_fault(self, what: str) -> None:
        # injected faults surface as metrics too, so fault-injection runs are
        # observable through /metrics alongside the timeline events; the
        # active span (if a trace is open) is marked so fault-injection runs
        # are visible in trace trees
        from h2o3_tpu.utils.telemetry import FAULTS_INJECTED
        wid = current_worker_id()
        with self._lock:
            self._calls += 1
            calls = self._calls
            site_calls = self._site_calls[what] = \
                self._site_calls.get(what, 0) + 1
            # a worker-scoped `after`/`crash_after` counts THAT worker's own
            # faultable calls, not the site's (its peers advance the site
            # counter too, which would make "fail my 2nd call" racy)
            armed_calls = site_calls
            if wid is not None and wid in self.worker_rates:
                armed_calls = self._worker_calls[wid] = \
                    self._worker_calls.get(wid, 0) + 1
            armed = armed_calls > int(self._site(what, "after", 0))
            drop_rate = self._site(what, "drop_rate", self.drop_rate)
            delay_rate = self._site(what, "delay_rate", self.delay_rate)
            delay_ms = self._site(what, "delay_ms", self.delay_ms)
            crash_rate = self._site(what, "crash_rate", self.crash_rate)
            stall_rate = self._site(what, "stall_rate", self.stall_rate)
            stall_ms = self._site(what, "stall_ms", self.stall_ms)
            # deterministic kills: Nth faultable call overall (crash_after)
            # or Nth call at THIS site (site_rates[what]["crash_after"])
            site_crash_after = int(self._site(what, "crash_after", 0))
            r = self._rng.random()
            r2 = self._rng.random()
            crash = (
                bool(self.crash_after and calls >= self.crash_after)
                or bool(site_crash_after
                        and armed_calls >= site_crash_after)
                or (armed and crash_rate > 0 and r < crash_rate))
            drop = (not crash) and armed and drop_rate > 0 and r < drop_rate
            stall = (not crash and not drop) and armed \
                and stall_rate > 0 and r < stall_rate
            delay = (not crash and not drop and not stall) and armed \
                and delay_rate > 0 and r2 < delay_rate
            if crash:
                self.crashed += 1
            elif drop:
                self.dropped += 1
            elif stall:
                self.stalled += 1
        if crash:
            # process-fatal (reference: a kill -9 mid-build, the scenario
            # hex/faulttolerance/Recovery.java exists for). Recorded first so
            # an inherited log/timeline snapshot shows the cause of death;
            # os._exit skips atexit — nothing may "clean up" a crash test.
            TIMELINE.record("fault", f"crash:{what}")
            FAULTS_INJECTED.labels(kind="crash").inc()
            import os as _os
            _os._exit(86)
        if drop:
            TIMELINE.record("fault", f"drop:{what}")
            FAULTS_INJECTED.labels(kind="drop").inc()
            _tracing.TRACER.mark_active(status="error",
                                        fault=f"drop:{what}")
            raise FaultInjected(what)
        if stall:
            # bounded hold: the caller hangs on the gate until
            # release_stalls() fires or the bound elapses — a hung worker
            # the elastic membership layer must eject, not a fixed sleep
            # (the gate makes the hold interruptible; the bound makes it
            # impossible to wedge a run forever)
            t0 = time.time_ns()
            self._stall_gate.wait(timeout=stall_ms / 1000.0)
            dur_ns = time.time_ns() - t0
            TIMELINE.record("fault", f"stall:{what}", dur_ns)
            FAULTS_INJECTED.labels(kind="stall").inc()
            _tracing.TRACER.mark_active(status="stalled",
                                        fault=f"stall:{what}",
                                        stall_ns=dur_ns)
            return
        if delay:
            t0 = time.time_ns()
            time.sleep(delay_ms / 1000.0)
            dur_ns = time.time_ns() - t0
            with self._lock:
                self.delayed += 1
            # the event carries the TRUE injected stall, not 0 — delay
            # faults are stragglers and must read as such in the timeline
            TIMELINE.record("fault", f"delay:{what}", dur_ns)
            FAULTS_INJECTED.labels(kind="delay").inc()
            _tracing.TRACER.mark_active(status="delayed",
                                        fault=f"delay:{what}",
                                        delay_ns=dur_ns)


class FaultInjected(RuntimeError):
    pass


FAULTS: FaultInjector | None = None


class inject_faults:
    """Context manager enabling fault injection (tests only)."""

    def __init__(self, **kw):
        self.injector = FaultInjector(**kw)

    def __enter__(self):
        global FAULTS
        FAULTS = self.injector
        return self.injector

    def __exit__(self, *exc):
        global FAULTS
        FAULTS = None
        # unstick any worker still held on the stall gate — a finished
        # chaos scenario must never leave a thread parked on its injector
        self.injector.release_stalls()
        return False


def start_profiler(log_dir: str) -> None:
    """Start an XLA-level trace (reference analog: /3/Profiler; here the
    profile is a TensorBoard-compatible jax.profiler trace, the native tool
    for TPU kernels)."""
    import jax
    jax.profiler.start_trace(log_dir)


def stop_profiler() -> None:
    import jax
    jax.profiler.stop_trace()


def device_memory_profile() -> bytes:
    import jax
    return jax.profiler.device_memory_profile()
