"""Compute observatory — XLA cost accounting, recompile attribution, MFU.

Every timing elsewhere in the stack is host wall-clock; this module is the
fourth observability pillar (after metrics, traces, and memory): it answers
*what fraction of the hardware are we using, and where did the compile time
go* — automatically, instead of by redoing ROOFLINE.md's FLOP arithmetic by
hand.

Three pieces:

- :class:`AccountedJit` (via :func:`accounted_jit`) — a drop-in for
  ``jax.jit`` at the host-dispatched compile sites (serving scorers, GLM/DL
  megasteps, the GBM tree program, ``map_reduce`` collectives). It compiles
  ahead-of-time (``jit().lower().compile()``), so every compile is observed:
  the wrapper holds one executable per **signature** (static values + dynamic
  shapes/dtypes/shardings) and records, per logical *site*, the signature,
  the compile wall time, and the executable's ``cost_analysis()`` FLOPs /
  bytes. When a site compiles a *second* signature the :class:`CostMeter`
  records a **recompile event** with the signature diff (which dim / dtype /
  device set / static changed) — recompile attribution becomes a live table
  instead of forensic bench archaeology.
- :class:`CostMeter` (``COSTS``) — the process-wide registry behind
  ``GET /3/Compute``. Sampled execution probes (the wrapper's own, or the
  ``map_reduce`` dispatch probe feeding :meth:`CostMeter.observe`) combine
  the recorded FLOPs with measured wall time into achieved FLOP/s and
  bytes/s per loop, rated against :data:`PEAK_TABLE` —
  ``h2o3_compute_utilization{loop}`` plus arithmetic-intensity / roofline
  gauges. Unknown backends (this CPU-only container) report utilization as
  ``None``, never 0 and never an exception.
- the **site scope** (:meth:`CostMeter.scope`) — a contextvar naming the
  logical site active at compile time, consulted by the persistent
  compile-cache listeners (``utils/compile_cache.py``) so cache hits/misses
  credit the loop that caused them.

Always-on and host-side: the per-call overhead is a pytree flatten + dict
lookup (~µs); the only device syncs are on SAMPLED calls (every
``H2O3TPU_COSTS_SAMPLE``-th, first always), exactly like the ``map_reduce``
dispatch probe. ``H2O3TPU_COSTS_OFF=1`` bypasses the wrapper entirely
(plain ``jax.jit`` dispatch, nothing recorded).
"""

from __future__ import annotations

import contextlib
import contextvars
import inspect
import itertools
import os
import sys
import threading
import time
import weakref
from collections import OrderedDict

#: logical site active for compile attribution (innermost scope wins)
_SITE: contextvars.ContextVar["str | None"] = \
    contextvars.ContextVar("h2o3_cost_site", default=None)


def enabled() -> bool:
    """Cost accounting on? (``H2O3TPU_COSTS_OFF=1`` disables; read per call
    so tests and the bench overhead probe can flip it at runtime.)"""
    return os.environ.get("H2O3TPU_COSTS_OFF", "") != "1"


def sample_every() -> int:
    """Execution-probe sampling period (``H2O3TPU_COSTS_SAMPLE``, default
    16; the first call per wrapper always samples so short sessions still
    measure something — same contract as the map_reduce dispatch probe)."""
    try:
        return max(int(os.environ.get("H2O3TPU_COSTS_SAMPLE", "") or 16), 1)
    except ValueError:
        return 16


# ---------------------------------------------------------------------------
# Per-backend peak table. Provenance: the v5e numbers are ROOFLINE.md's
# (~819 GB/s HBM measured there; 197 TFLOP/s bf16 is the published chip
# peak the MFU in that document rates against); other generations are the
# published per-chip peaks. Keyed by substring of `device.device_kind`
# (lowercased) — "TPU v5 lite" and "TPU v5e" both resolve to the v5e row.
# An unmatched kind (CPU, GPU, future chips) yields None: utilization is
# then reported as null, NEVER 0 and never an exception.

PEAK_TABLE = (
    ("v5 lite", {"name": "TPU v5e", "flops_per_sec": 197e12,
                 "hbm_bytes_per_sec": 819e9}),
    ("v5e", {"name": "TPU v5e", "flops_per_sec": 197e12,
             "hbm_bytes_per_sec": 819e9}),
    ("v5p", {"name": "TPU v5p", "flops_per_sec": 459e12,
             "hbm_bytes_per_sec": 2765e9}),
    ("v6", {"name": "TPU v6e", "flops_per_sec": 918e12,
            "hbm_bytes_per_sec": 1640e9}),
    ("v4", {"name": "TPU v4", "flops_per_sec": 275e12,
            "hbm_bytes_per_sec": 1228e9}),
    ("v3", {"name": "TPU v3", "flops_per_sec": 123e12,
            "hbm_bytes_per_sec": 900e9}),
    ("v2", {"name": "TPU v2", "flops_per_sec": 46e12,
            "hbm_bytes_per_sec": 700e9}),
)

_peak_cache: "dict[str, dict | None]" = {}


def backend_peak(device_kind: str | None = None) -> dict | None:
    """Peak {name, flops_per_sec, hbm_bytes_per_sec} for the (default)
    backend's device kind, or None when the kind is not in the table (a
    CPU container, an unknown accelerator). Peaks are bf16 MXU peaks —
    utilization is MFU against the bf16 peak, the convention ROOFLINE.md's
    hand accounting used."""
    if device_kind is None:
        try:
            import jax
            device_kind = jax.devices()[0].device_kind
        except Exception:   # noqa: BLE001 — no backend → no peak
            return None
    kind = str(device_kind).lower()
    if kind not in _peak_cache:
        _peak_cache[kind] = next(
            (row for sub, row in PEAK_TABLE if sub in kind), None)
    return _peak_cache[kind]


# ---------------------------------------------------------------------------
# Signatures: canonical hashable keys + human-readable descriptors + diffs.


def _leaf_key(x):
    """Hashable signature component for one dynamic pytree leaf: shape /
    dtype / sharding for arrays, value-independent type name for Python
    scalars (they trace as weak-typed scalars — the value never forces a
    recompile, so it must not split the signature)."""
    if hasattr(x, "shape") and hasattr(x, "dtype"):
        return (tuple(x.shape), str(x.dtype), getattr(x, "sharding", None))
    return (type(x).__name__,)


def _leaf_descr(x) -> dict:
    if hasattr(x, "shape") and hasattr(x, "dtype"):
        d = {"shape": list(x.shape), "dtype": str(x.dtype)}
        sh = getattr(x, "sharding", None)
        devs = getattr(sh, "device_set", None) if sh is not None else None
        if devs:
            d["devices"] = sorted(getattr(dv, "id", -1) for dv in devs)
        return d
    return {"scalar": type(x).__name__}


def signature_diff(old: dict, new: dict) -> list[str]:
    """Human-readable per-component diff between two recorded signatures —
    the payload of a recompile event: WHICH dimension / dtype / device set /
    static argument changed. ``old``/``new`` are the ``signature`` dicts
    :meth:`CostMeter.record_compile` stores ({"args": [...], "statics": {}}).
    """
    out: list[str] = []
    oa, na = old.get("args", []), new.get("args", [])
    if len(oa) != len(na):
        out.append(f"arg count: {len(oa)} -> {len(na)}")
    for i, (a, b) in enumerate(zip(oa, na)):
        if a == b:
            continue
        if "shape" in a and "shape" in b:
            sa, sb = a["shape"], b["shape"]
            if len(sa) != len(sb):
                out.append(f"arg{i}.rank: {len(sa)} -> {len(sb)}")
            else:
                for d, (x, y) in enumerate(zip(sa, sb)):
                    if x != y:
                        out.append(f"arg{i}.shape[{d}]: {x} -> {y}")
            if a.get("dtype") != b.get("dtype"):
                out.append(f"arg{i}.dtype: {a.get('dtype')} -> "
                           f"{b.get('dtype')}")
            if a.get("devices") != b.get("devices"):
                out.append(f"arg{i}.devices: {a.get('devices')} -> "
                           f"{b.get('devices')}")
        else:
            out.append(f"arg{i}: {a} -> {b}")
    os_, ns = old.get("statics", {}), new.get("statics", {})
    for k in sorted(set(os_) | set(ns)):
        if os_.get(k) != ns.get(k):
            out.append(f"static {k}: {os_.get(k)} -> {ns.get(k)}")
    return out or ["signature structure changed"]


def cost_of(compiled) -> tuple[float | None, float | None]:
    """(flops, bytes accessed) from an executable's ``cost_analysis()``;
    (None, None) when the backend doesn't provide it. jax returns a dict on
    some versions and a one-element list of dicts on others."""
    try:
        ca = compiled.cost_analysis()
    except Exception:   # noqa: BLE001 — optional on some backends
        return None, None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return None, None
    flops = ca.get("flops")
    nbytes = ca.get("bytes accessed")
    return (float(flops) if flops is not None else None,
            float(nbytes) if nbytes is not None else None)


# ---------------------------------------------------------------------------
# The registry.

#: recompile events kept per site / process-wide cap on stored signatures
MAX_SIGNATURES_PER_SITE = 32
MAX_RECOMPILE_EVENTS = 64


class CostMeter:
    """Process-wide per-site compile/cost registry (``GET /3/Compute``)."""

    def __init__(self):
        self._lock = threading.Lock()
        # site -> {"loop": str|None, "signatures": OrderedDict[key, rec],
        #          "recompiles": [event], "compiles": int,
        #          "compile_seconds": float, "eager_fallbacks": int}
        self._sites: "OrderedDict[str, dict]" = OrderedDict()
        # loop -> {"samples": int, "achieved_flops_per_sec": float, ...}
        self._loops: dict[str, dict] = {}
        self._wrappers: "weakref.WeakSet[AccountedJit]" = weakref.WeakSet()

    # -- site scope (compile-cache attribution) ------------------------------

    @contextlib.contextmanager
    def scope(self, site: str):
        """Name the logical site active for compile attribution in this
        context (innermost wins). The persistent compile-cache listeners
        read it at event time (``utils/compile_cache.py``)."""
        token = _SITE.set(site)
        try:
            yield
        finally:
            _SITE.reset(token)

    @staticmethod
    def active_site() -> str | None:
        return _SITE.get()

    # -- recording -----------------------------------------------------------

    def _site_locked(self, site: str, loop: str | None) -> dict:
        rec = self._sites.get(site)
        if rec is None:
            # graftlint: ok(_locked suffix: every caller holds self._lock)
            rec = self._sites[site] = {
                "loop": loop, "signatures": OrderedDict(), "recompiles": [],
                "compiles": 0, "compile_seconds": 0.0, "eager_fallbacks": 0}
        elif loop is not None and rec["loop"] is None:
            rec["loop"] = loop
        return rec

    def record_compile(self, site: str, signature: dict, seconds: float,
                       flops: float | None, nbytes: float | None,
                       loop: str | None = None, key=None) -> None:
        """One observed compile at ``site``. ``signature`` is the
        {"args": [...], "statics": {...}} descriptor; ``key`` its canonical
        hashable form (a repr of the descriptor when omitted). A compile of
        an already-recorded signature (fresh-lambda churn, an executable
        cache cleared between test modules) increments counts but is NOT a
        recompile event; a genuinely new second+ signature is."""
        from h2o3_tpu.utils import telemetry as _tm
        key = key if key is not None else repr(signature)
        with self._lock:
            rec = self._site_locked(site, loop)
            rec["compiles"] += 1
            rec["compile_seconds"] = round(
                rec["compile_seconds"] + float(seconds), 6)
            known = key in rec["signatures"]
            if not known:
                prev = next(reversed(rec["signatures"].values()), None)
                rec["signatures"][key] = {
                    "signature": signature, "compile_seconds": round(
                        float(seconds), 6),
                    "flops": flops, "bytes": nbytes,
                    "compiles": 1}
                while len(rec["signatures"]) > MAX_SIGNATURES_PER_SITE:
                    rec["signatures"].popitem(last=False)
                if prev is not None:
                    rec["recompiles"].append({
                        "site": site,
                        "from": prev["signature"], "to": signature,
                        "diff": signature_diff(prev["signature"], signature),
                        "compile_seconds": round(float(seconds), 6)})
                    del rec["recompiles"][:-MAX_RECOMPILE_EVENTS]
            else:
                rec["signatures"][key]["compiles"] += 1
                rec["signatures"].move_to_end(key)
            recompiled = (not known) and len(rec["signatures"]) > 1
        _tm.COMPILES.labels(site=site).inc()
        _tm.COMPILE_SECONDS.labels(site=site).inc(float(seconds))
        if recompiled:
            _tm.RECOMPILES.labels(site=site).inc()

    def record_eager_fallback(self, site: str, loop: str | None = None
                              ) -> None:
        """A site whose program would not AOT-compile (host-side branches):
        it runs eagerly/jit-path, unaccounted — counted so the table says so
        instead of silently missing."""
        with self._lock:
            self._site_locked(site, loop)["eager_fallbacks"] += 1

    def latest_cost(self, site: str) -> tuple[float | None, float | None]:
        """(flops, bytes) of the site's most recently compiled signature —
        the fallback when the caller cannot name which signature ran."""
        with self._lock:
            rec = self._sites.get(site)
            if rec is None:
                return None, None
            sig = next(reversed(rec["signatures"].values()), None)
            if sig is None:
                return None, None
            return sig["flops"], sig["bytes"]

    def cost_for(self, site: str, key) -> tuple[float | None, float | None]:
        """(flops, bytes) of one SPECIFIC recorded signature, so a sampled
        probe attributes the cost of the program that actually ran — a site
        holding several live signatures (full GBM chunk + remainder chunk,
        wide + narrow IRLS) must not rate one signature's wall time against
        another's FLOPs. (None, None) when evicted/unknown."""
        with self._lock:
            rec = self._sites.get(site)
            sig = rec["signatures"].get(key) if rec is not None else None
            if sig is None:
                return None, None
            return sig["flops"], sig["bytes"]

    # -- execution probes → achieved FLOP/s / roofline gauges ----------------

    def observe(self, site: str, seconds: float,
                flops: float | None = None,
                nbytes: float | None = None) -> None:
        """Fold one SAMPLED, synced execution of ``site``'s program (wall
        ``seconds``) into the per-loop achieved-throughput view. Cost
        defaults to the site's most recent signature (the ``map_reduce``
        dispatch probe calls this with its own measured duration). Unknown
        backends publish achieved FLOP/s but no utilization gauge — the
        REST view reports utilization null there."""
        if seconds <= 0:
            return
        from h2o3_tpu.utils import telemetry as _tm
        if flops is None:
            flops, nbytes = self.latest_cost(site)
        if flops is None or flops <= 0:
            return
        achieved = flops / seconds
        achieved_b = (nbytes / seconds) if nbytes else None
        intensity = (flops / nbytes) if nbytes else None
        peak = backend_peak()
        util = (achieved / peak["flops_per_sec"]) if peak else None
        with self._lock:
            loop = (self._sites.get(site) or {}).get("loop") or site
            st = self._loops.setdefault(loop, {"samples": 0})
            st["samples"] += 1
            st["achieved_flops_per_sec"] = round(achieved, 1)
            st["achieved_bytes_per_sec"] = (round(achieved_b, 1)
                                            if achieved_b else None)
            st["arithmetic_intensity"] = (round(intensity, 3)
                                          if intensity else None)
            st["utilization"] = round(util, 6) if util is not None else None
            if peak and intensity is not None:
                ridge = peak["flops_per_sec"] / peak["hbm_bytes_per_sec"]
                st["roofline"] = ("compute-bound" if intensity >= ridge
                                  else "memory-bound")
            else:
                st["roofline"] = None
        _tm.ACHIEVED_FLOPS.labels(loop=loop).set(achieved)
        if achieved_b is not None:
            _tm.ACHIEVED_BYTES.labels(loop=loop).set(achieved_b)
        if intensity is not None:
            _tm.ARITH_INTENSITY.labels(loop=loop).set(intensity)
        if util is not None:
            _tm.COMPUTE_UTILIZATION.labels(loop=loop).set(util)
        # per-slice achieved-FLOPs fold into the PR 9 mesh_slices view —
        # only when the scheduler is actually loaded (no import cost here)
        sched = sys.modules.get("h2o3_tpu.orchestration.scheduler")
        if sched is not None:
            label = sched.active_slice_label()
            if label is not None:
                sched.SLICE_STATS.add_flops(label, flops)

    # -- views ---------------------------------------------------------------

    def snapshot(self) -> dict:
        """The ``GET /3/Compute`` payload: per-site compiles / signatures /
        costs / recompile events, per-loop achieved throughput + roofline
        position, and the backend peak row (null on unknown backends)."""
        peak = backend_peak()
        try:
            import jax
            kind = jax.devices()[0].device_kind
            backend = jax.default_backend()
        except Exception:   # noqa: BLE001
            kind = backend = None
        with self._lock:
            sites = []
            for name, rec in self._sites.items():
                sigs = list(rec["signatures"].values())
                sites.append({
                    "site": name, "loop": rec["loop"],
                    "compiles": rec["compiles"],
                    "compile_seconds": rec["compile_seconds"],
                    "eager_fallbacks": rec["eager_fallbacks"],
                    "flops": next((s["flops"] for s in reversed(sigs)
                                   if s["flops"] is not None), None),
                    "bytes": next((s["bytes"] for s in reversed(sigs)
                                   if s["bytes"] is not None), None),
                    "signatures": [dict(s) for s in sigs],
                    "recompile_events": [dict(e) for e in rec["recompiles"]],
                })
            loops = {k: dict(v) for k, v in self._loops.items()}
        return {"backend": backend, "device_kind": kind,
                "peak": dict(peak) if peak else None,
                "sites": sites, "loops": loops,
                "signature_count": sum(len(s["signatures"]) for s in sites),
                "recompile_events": sum(len(s["recompile_events"])
                                        for s in sites)}

    def loops(self) -> dict:
        """Per-loop achieved-throughput rows only (utilization / samples /
        roofline) — the health evaluator's MFU-collapse probe reads this
        every sweep, so it must not pay :meth:`snapshot`'s full per-site
        signature copy."""
        with self._lock:
            return {k: dict(v) for k, v in self._loops.items()}

    def signature_count(self) -> int:
        """Total distinct signatures across sites — the bench's
        steady-state recompile probe: a warm scenario re-run must not grow
        this."""
        with self._lock:
            return sum(len(r["signatures"]) for r in self._sites.values())

    def recompile_count(self) -> int:
        with self._lock:
            return sum(len(r["recompiles"]) for r in self._sites.values())

    # -- lifecycle -----------------------------------------------------------

    def _register_wrapper(self, w: "AccountedJit") -> None:
        self._wrappers.add(w)

    def clear_executables(self) -> None:
        """Drop every wrapper's held executables (recorded costs stay).
        Called alongside ``jax.clear_caches()`` between test modules — the
        AOT handles the wrappers hold are live XLA executables the global
        cache clear cannot see."""
        for w in list(self._wrappers):
            w.clear_executables()

    def clear(self) -> None:
        """Tests only: drop every record AND held executable (so a
        rebuilt-same-shape scenario re-records from a clean slate)."""
        self.clear_executables()
        with self._lock:
            self._sites.clear()
            self._loops.clear()


COSTS = CostMeter()


# ---------------------------------------------------------------------------
# The accounted jit wrapper.

#: sentinel for signatures whose AOT compile failed — the call falls back
#: to the plain jit path permanently (host-side branches, unhashables)
_AOT_FAILED = object()

_MAX_EXECUTABLES = 64


class AccountedJit:
    """``jax.jit`` with per-signature AOT compilation and cost accounting.

    One executable per (static values, dynamic tree structure, per-leaf
    shape/dtype/sharding); compiles happen through
    ``jit().lower().compile()`` under the site scope so compile time, FLOPs
    and bytes are recorded per site. Calls whose leaves are tracers (the
    site nested inside another jit trace) and calls under
    ``H2O3TPU_COSTS_OFF=1`` fall through to the plain jit path unchanged.
    """

    def __init__(self, site: str, fun, *, static_argnames=(),
                 donate_argnums=(), loop: str | None = None,
                 sample: bool = True):
        import jax
        self.site = site
        self.loop = loop
        self._fun = fun
        self._jit = jax.jit(fun, static_argnames=tuple(static_argnames),
                            donate_argnums=tuple(donate_argnums))
        self._static = frozenset(static_argnames)
        self._param_names: "list[str] | None" = None
        if self._static:
            try:
                self._param_names = [
                    p.name for p in
                    inspect.signature(fun).parameters.values()]
            except (ValueError, TypeError):   # C callables, odd wrappers
                self._param_names = None
        self._sample = sample
        self._calls = itertools.count()
        self._lock = threading.Lock()
        self._compiled: "OrderedDict[tuple, object]" = OrderedDict()
        self._last_key = None
        COSTS._register_wrapper(self)

    # functools.wraps-ish surface so callers can introspect
    @property
    def __name__(self):
        return getattr(self._fun, "__name__", self.site)

    def clear_executables(self) -> None:
        with self._lock:
            self._compiled.clear()

    def lower(self, *args, **kwargs):
        """AOT escape hatch — delegate to the underlying ``jax.jit``'s
        ``lower`` for diagnostic compiles (the entry point's comm-volume
        audit inspects the HLO this way). Compiles made through it bypass
        the wrapper's executable cache and are not accounted."""
        return self._jit.lower(*args, **kwargs)

    def last_cost(self) -> tuple[float | None, float | None]:
        """(flops, bytes) of the most recently dispatched signature — the
        ``map_reduce`` dispatch probe reads this so its sampled duration is
        rated against the program that actually ran, not the site's most
        recent compile."""
        key = self._last_key
        if key is None:
            return None, None
        return COSTS.cost_for(self.site, key)

    # -- call path -----------------------------------------------------------

    def _split(self, args, kwargs):
        """(statics, dyn_args, dyn_kwargs) or None when the statics cannot
        be mapped to positions (vararg functions with statics — none of the
        instrumented sites, but fail safe to the jit path)."""
        if not self._static:
            return (), args, kwargs
        names = self._param_names
        if names is None or len(args) > len(names):
            return None
        statics, dyn_args = [], []
        for i, a in enumerate(args):
            if names[i] in self._static:
                statics.append((names[i], a))
            else:
                dyn_args.append(a)
        dyn_kwargs = {}
        for k, v in kwargs.items():
            if k in self._static:
                statics.append((k, v))
            else:
                dyn_kwargs[k] = v
        return (tuple(sorted(statics)), tuple(dyn_args), dyn_kwargs)

    def __call__(self, *args, **kwargs):
        import jax
        if not enabled():
            return self._jit(*args, **kwargs)
        split = self._split(args, kwargs)
        if split is None:
            return self._jit(*args, **kwargs)
        statics, dyn_args, dyn_kwargs = split
        leaves, treedef = jax.tree.flatten((dyn_args, dyn_kwargs))
        if any(isinstance(leaf, jax.core.Tracer) for leaf in leaves):
            # nested inside another trace: the outer program owns the
            # compile; calling an executable with tracers would throw
            return self._jit(*args, **kwargs)
        try:
            key = (statics, treedef, tuple(_leaf_key(x) for x in leaves))
            hash(key)
        except TypeError:        # unhashable static/sharding: unaccountable
            return self._jit(*args, **kwargs)
        with self._lock:
            entry = self._compiled.get(key)
            if entry is not None:
                self._compiled.move_to_end(key)
        if entry is None:
            entry = self._compile(key, statics, leaves, args, kwargs)
        if entry is _AOT_FAILED:
            return self._jit(*args, **kwargs)
        self._last_key = key      # unsynchronized: observability-only hint
        n = next(self._calls)
        if self._sample and (n == 0 or n % sample_every() == 0):
            t0 = time.perf_counter()
            out = entry(*dyn_args, **dyn_kwargs)
            out = jax.block_until_ready(out)  # graftlint: ok(sampled achieved-FLOPs probe — the sync is the measurement)
            dt = time.perf_counter() - t0
            # the EXECUTED signature's cost, not the site's latest compile
            flops, nbytes = COSTS.cost_for(self.site, key)
            COSTS.observe(self.site, dt, flops=flops, nbytes=nbytes)
            return out
        return entry(*dyn_args, **dyn_kwargs)

    def _compile(self, key, statics, leaves, args, kwargs):
        import jax
        try:
            with COSTS.scope(self.site):
                t0 = time.perf_counter()
                compiled = self._jit.lower(*args, **kwargs).compile()
                dt = time.perf_counter() - t0
        except Exception:   # noqa: BLE001 — host-side branches etc.
            COSTS.record_eager_fallback(self.site, self.loop)
            compiled = _AOT_FAILED
        else:
            flops, nbytes = cost_of(compiled)
            signature = {"args": [_leaf_descr(x) for x in leaves],
                         "statics": {k: repr(v) for k, v in statics}}
            COSTS.record_compile(self.site, signature, dt, flops, nbytes,
                                 loop=self.loop, key=key)
        with self._lock:
            won = self._compiled.setdefault(key, compiled)
            while len(self._compiled) > _MAX_EXECUTABLES:
                self._compiled.popitem(last=False)
        return won


def accounted_jit(site: str, fun=None, *, static_argnames=(),
                  donate_argnums=(), loop: str | None = None,
                  sample: bool = True):
    """``jax.jit`` replacement that registers the executable with the
    compute observatory under ``site`` (decorator or direct form)::

        @accounted_jit("glm:irls_megastep", static_argnames=("k",),
                       loop="glm_irls")
        def _irls_megastep(...): ...
    """
    if fun is None:
        return lambda f: AccountedJit(site, f,
                                      static_argnames=static_argnames,
                                      donate_argnums=donate_argnums,
                                      loop=loop, sample=sample)
    return AccountedJit(site, fun, static_argnames=static_argnames,
                        donate_argnums=donate_argnums, loop=loop,
                        sample=sample)
