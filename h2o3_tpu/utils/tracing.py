"""Distributed request tracing — span trees from REST to partition dispatches.

Reference: ``water/TimeLine`` + ``water/api/TimelineHandler`` snapshot
causally-ordered send/recv events cluster-wide so a slow request can be
walked back to the node and packet that stalled it. The flat event ring
(:mod:`h2o3_tpu.utils.timeline`) keeps that role for aggregate history; this
module adds the **per-request causality** the ring cannot express: a GLM
build's 40 IRLS iterations each fanning out to 8 partitions, one shard
straggling — as one tree of spans under the originating REST request.

Model:

- A **span** is ``(trace_id, span_id, parent_id, name, kind, attrs,
  start/end ns, status)``. Spans nest via a :mod:`contextvars` context so
  the active span propagates through plain function calls with no plumbing.
- A **trace** is the set of spans sharing a ``trace_id``; it is *completed*
  once every span (and every retained hand-off, see :meth:`Tracer.capture`)
  has ended, then moves into a bounded ring of the last N completed traces.
- **W3C propagation**: incoming ``traceparent`` headers join the caller's
  trace; responses carry the root span's ``traceparent`` back.

Everything here is host-side stdlib — nothing is ever traced into an XLA
program, and a span begin/end is a lock-protected dict update (~µs).
``H2O3TPU_TRACE_OFF=1`` disables root-span creation entirely (child spans
never start without an active trace, so the whole stack quiesces).
"""

from __future__ import annotations

import collections
import contextvars
import os
import re
import threading
import time
import uuid

#: completed-trace ring capacity (the TimeLine ring analog, per trace)
TRACE_RING_SIZE = int(os.environ.get("H2O3TPU_TRACE_RING", "128"))

#: open (in-flight) traces beyond this are force-finalized oldest-first —
#: a Job that never ran must not pin its trace in memory forever
MAX_OPEN_TRACES = 64

#: spans beyond this per trace are counted, not stored (an AutoML run with
#: CV folds can emit thousands of iteration spans; the tree stays bounded)
MAX_SPANS_PER_TRACE = 4096

_TRACEPARENT = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$")

#: the active span's context for the current thread/task
_CURRENT: contextvars.ContextVar["SpanContext | None"] = \
    contextvars.ContextVar("h2o3_span", default=None)

#: set by utils/profiling.py while a device-profiler capture is open: every
#: span entered during the window additionally opens a
#: ``jax.profiler.TraceAnnotation`` named after the span, so the Perfetto
#: capture carries span-derived names. None (the default) costs one
#: is-not-None check per span — the always-on tracer budget is untouched.
SPAN_HOOK = None


def enabled() -> bool:
    return os.environ.get("H2O3TPU_TRACE_OFF", "") != "1"


def trace_partitions_enabled() -> bool:
    """Full-fidelity partition tracing: when ``H2O3TPU_TRACE_PARTITIONS=1``,
    EVERY traced ``map_reduce`` dispatch syncs and stamps per-partition
    readiness sub-spans + straggler attrs. Off by default because the
    per-shard sequential blocking serializes the data plane — the dispatch
    path then keeps straggler attribution only on its SAMPLED dispatches
    (see ``ops/map_reduce._SAMPLE_EVERY``). Read per call so tests and
    operators can flip it at runtime without re-importing."""
    return os.environ.get("H2O3TPU_TRACE_PARTITIONS", "") == "1"


class SpanContext:
    """Immutable (trace_id, span_id) pair — what propagates."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id

    def __repr__(self) -> str:
        return f"SpanContext({self.trace_id}, {self.span_id})"


def parse_traceparent(header: str | None) -> SpanContext | None:
    """W3C ``traceparent`` → :class:`SpanContext` (None on absent/invalid)."""
    if not header:
        return None
    m = _TRACEPARENT.match(header.strip().lower())
    if not m or m.group(1) == "ff":
        return None
    trace_id, span_id = m.group(2), m.group(3)
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return SpanContext(trace_id, span_id)


def format_traceparent(ctx: SpanContext) -> str:
    return f"00-{ctx.trace_id}-{ctx.span_id}-01"


class Span:
    """One timed operation; mutable until :meth:`Tracer.end` seals it."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "kind", "attrs",
                 "start_ns", "end_ns", "status", "tid")

    def __init__(self, trace_id: str, span_id: str, parent_id: str | None,
                 name: str, kind: str, attrs: dict | None, tid: str):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.kind = kind
        self.attrs = dict(attrs or {})
        self.start_ns = time.time_ns()
        self.end_ns = 0
        self.status = "ok"
        self.tid = tid

    @property
    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    def set_attrs(self, **attrs) -> None:
        self.attrs.update(attrs)

    def set_status(self, status: str) -> None:
        self.status = status

    def to_dict(self) -> dict:
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "parent_id": self.parent_id, "name": self.name,
                "kind": self.kind, "start_ns": self.start_ns,
                "end_ns": self.end_ns,
                "dur_ns": max(self.end_ns - self.start_ns, 0),
                "status": self.status, "tid": self.tid, "attrs": self.attrs}


class _SpanScope:
    """Context manager activating a span (or a no-op when tracing yields
    no span — off, or no active trace to parent under)."""

    __slots__ = ("_tracer", "_span", "_token", "_ann")

    def __init__(self, tracer: "Tracer", span: Span | None):
        self._tracer = tracer
        self._span = span
        self._token = None
        self._ann = None

    def __enter__(self) -> Span | None:
        if self._span is not None:
            self._token = _CURRENT.set(self._span.context)
            if SPAN_HOOK is not None:    # device-profiler capture open
                self._ann = SPAN_HOOK(self._span.name)
        return self._span

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._span is not None:
            if self._ann is not None:
                try:
                    self._ann.__exit__(None, None, None)
                except Exception:   # noqa: BLE001 — annotation best-effort
                    pass
                self._ann = None
            if self._token is not None:
                _CURRENT.reset(self._token)
            if exc_type is not None and self._span.status == "ok":
                self._span.status = "error"
                self._span.attrs.setdefault(
                    "exception", f"{exc_type.__name__}: {exc}")
            self._tracer.end(self._span, self._span.status)
        return False


class _AdoptScope:
    """Context manager for a captured (retained) context: activates it in
    the adopting thread, opens a child span, releases the retention."""

    __slots__ = ("_tracer", "_ctx", "_name", "_kind", "_attrs", "_scope")

    def __init__(self, tracer: "Tracer", ctx: SpanContext | None,
                 name: str, kind: str, attrs: dict | None):
        self._tracer = tracer
        self._ctx = ctx
        self._name = name
        self._kind = kind
        self._attrs = attrs
        self._scope: _SpanScope | None = None

    def __enter__(self) -> Span | None:
        if self._ctx is None:
            return None
        span = self._tracer.begin(self._name, kind=self._kind,
                                  parent=self._ctx, attrs=self._attrs)
        self._scope = _SpanScope(self._tracer, span)
        return self._scope.__enter__()

    def __exit__(self, exc_type, exc, tb) -> bool:
        try:
            if self._scope is not None:
                self._scope.__exit__(exc_type, exc, tb)
        finally:
            if self._ctx is not None:
                self._tracer.release(self._ctx)
        return False


class Tracer:
    """Thread-safe span recorder with a bounded completed-trace ring."""

    def __init__(self, capacity: int = TRACE_RING_SIZE,
                 max_open: int = MAX_OPEN_TRACES):
        self._lock = threading.Lock()
        self._max_open = max_open
        # trace_id → {"spans": [dict], "open": {span_id: Span},
        #             "pending": int, "dropped": int, "root": Span|None}
        self._active: "collections.OrderedDict[str, dict]" = \
            collections.OrderedDict()
        self._done: collections.deque = collections.deque(maxlen=capacity)

    # -- span lifecycle ------------------------------------------------------

    def current(self) -> SpanContext | None:
        """The active span context in this thread/task (no retention)."""
        return _CURRENT.get()

    def begin(self, name: str, kind: str = "internal",
              parent: SpanContext | None = None, attrs: dict | None = None,
              root: bool = False, ephemeral: bool = False) -> Span | None:
        """Start a span. Without ``root``, a span only starts under an
        active trace (explicit ``parent`` or the contextvar) — library-level
        instrumentation stays silent until something opens a trace.
        ``ephemeral`` roots propagate normally (context, traceparent) but
        their finished trace is DISCARDED instead of entering the completed
        ring — for high-frequency polling/scrape endpoints whose one-span
        traces would otherwise churn out the traces worth keeping."""
        ctx = parent if parent is not None else _CURRENT.get()
        if root:
            if not enabled():
                return None
            trace_id = ctx.trace_id if ctx is not None else uuid.uuid4().hex
            parent_id = ctx.span_id if ctx is not None else None
        else:
            if ctx is None:
                return None
            trace_id, parent_id = ctx.trace_id, ctx.span_id
        span = Span(trace_id, uuid.uuid4().hex[:16], parent_id, name, kind,
                    attrs, tid=str(threading.get_ident()))
        with self._lock:
            tr = self._active.get(trace_id)
            if tr is None:
                tr = {"spans": [], "open": {}, "pending": 0, "dropped": 0,
                      "root": None, "ephemeral": bool(root and ephemeral)}
                self._active[trace_id] = tr
                self._evict_open_locked()
            if tr["root"] is None and span.parent_id is None or root:
                tr["root"] = tr["root"] or span
            tr["open"][span.span_id] = span
        return span

    def end(self, span: Span | None, status: str | None = None) -> None:
        if span is None:
            return
        span.end_ns = time.time_ns()
        if status is not None:
            span.status = status
        with self._lock:
            tr = self._active.get(span.trace_id)
            if tr is None:
                return
            tr["open"].pop(span.span_id, None)
            if len(tr["spans"]) < MAX_SPANS_PER_TRACE:
                tr["spans"].append(span.to_dict())
            else:
                tr["dropped"] += 1
            self._maybe_finalize_locked(span.trace_id)

    def span(self, name: str, kind: str = "internal",
             attrs: dict | None = None, parent: SpanContext | None = None,
             root: bool = False, ephemeral: bool = False) -> _SpanScope:
        """``with TRACER.span("glm:fit", kind="model") as s:`` — begins,
        activates, and ends a span around the block (no-op off-trace)."""
        return _SpanScope(self, self.begin(name, kind=kind, parent=parent,
                                           attrs=attrs, root=root,
                                           ephemeral=ephemeral))

    def add_span(self, name: str, kind: str, parent: Span,
                 start_ns: int, end_ns: int, attrs: dict | None = None,
                 tid: str | None = None, status: str = "ok") -> None:
        """Record an already-timed child span (e.g. per-partition readiness
        measured after a dispatch) without touching the contextvar."""
        span = Span(parent.trace_id, uuid.uuid4().hex[:16], parent.span_id,
                    name, kind, attrs, tid=tid or str(threading.get_ident()))
        span.start_ns, span.end_ns, span.status = start_ns, end_ns, status
        with self._lock:
            tr = self._active.get(parent.trace_id)
            if tr is None:
                return
            if len(tr["spans"]) < MAX_SPANS_PER_TRACE:
                tr["spans"].append(span.to_dict())
            else:
                tr["dropped"] += 1

    # -- cross-thread hand-off ----------------------------------------------

    def capture(self) -> SpanContext | None:
        """Capture the active context for another thread, RETAINING its
        trace: the trace will not finalize until :meth:`release` (a Job's
        worker span may begin after the creating request's root span ends —
        the retention bridges that gap)."""
        ctx = _CURRENT.get()
        if ctx is None:
            return None
        with self._lock:
            tr = self._active.get(ctx.trace_id)
            if tr is None:
                return None
            tr["pending"] += 1
        return ctx

    def release(self, ctx: SpanContext | None) -> None:
        if ctx is None:
            return
        with self._lock:
            tr = self._active.get(ctx.trace_id)
            if tr is None:
                return
            tr["pending"] = max(tr["pending"] - 1, 0)
            self._maybe_finalize_locked(ctx.trace_id)

    def adopt(self, ctx: SpanContext | None, name: str, kind: str = "job",
              attrs: dict | None = None) -> _AdoptScope:
        """``with TRACER.adopt(captured_ctx, "job:GLM") as s:`` in the
        worker thread — child span under the captured context, retention
        released at exit."""
        return _AdoptScope(self, ctx, name, kind, attrs)

    def make_ephemeral(self, trace_id: str) -> None:
        """Flag an in-flight trace for discard at finalize — for requests
        that turn out to be noise only after routing (404s, auth failures:
        a scanner must not be able to churn the completed ring)."""
        with self._lock:
            tr = self._active.get(trace_id)
            if tr is not None:
                tr["ephemeral"] = True

    def annotate_root(self, trace_id: str, **attrs) -> None:
        """Roll an attribute up to an in-flight trace's ROOT span — numeric
        values max-merge so many child samples yield the trace-wide peak
        (memory attribution: every model fit under a request reports its
        device-byte peak, and the root carries the request's maximum).
        Works on a SEALED root too, as long as the trace is still open: a
        REST build's root closes when the response is sent, before the
        background Job even starts the fit — the retained trace's stored
        root record is updated in place."""
        def merge(target: dict) -> None:
            for k, v in attrs.items():
                old = target.get(k)
                if isinstance(old, (int, float)) and \
                        isinstance(v, (int, float)):
                    target[k] = max(old, v)
                else:
                    target[k] = v

        with self._lock:
            tr = self._active.get(trace_id)
            root = tr.get("root") if tr is not None else None
            if root is None:
                return                       # trace unknown or rootless
            if root.span_id in tr["open"]:
                merge(root.attrs)            # still open: seals with attrs
                return
            for rec in tr["spans"]:          # sealed: patch the stored dict
                if rec["span_id"] == root.span_id:
                    merge(rec["attrs"])
                    return

    def mark_active(self, status: str | None = None, force: bool = False,
                    **attrs) -> None:
        """Annotate the innermost active span (fault injection hooks).

        By default a status only lands on a still-"ok" span (the FIRST
        fault wins); ``force=True`` overrides — the dispatch retry layer
        uses it to flip an injected drop's "error" into "retried" once the
        re-attempt succeeds (the fault was absorbed, not fatal)."""
        ctx = _CURRENT.get()
        if ctx is None:
            return
        with self._lock:
            tr = self._active.get(ctx.trace_id)
            span = tr["open"].get(ctx.span_id) if tr else None
        if span is not None:
            if status is not None and (force or span.status == "ok"):
                span.status = status
            span.attrs.update(attrs)

    # -- store ---------------------------------------------------------------

    def _maybe_finalize_locked(self, trace_id: str) -> None:
        tr = self._active.get(trace_id)
        if tr is None or tr["open"] or tr["pending"]:
            return
        del self._active[trace_id]                    # graftlint: ok(caller holds self._lock — _locked suffix contract)
        if tr.get("ephemeral"):
            return            # polling/scrape noise: never enters the ring
        self._done.append(self._summarize(trace_id, tr))  # graftlint: ok(caller holds self._lock)

    def _evict_open_locked(self) -> None:
        while len(self._active) > self._max_open:
            # prefer victims nobody retains: evicting a pending trace would
            # let its Job's later adopt() recreate the entry and emit a
            # duplicate record for the same trace_id
            tid = next((k for k, t in self._active.items()
                        if not t["pending"]), None)
            if tid is None:
                tid = next(iter(self._active))    # all retained: oldest goes
            tr = self._active.pop(tid)            # graftlint: ok(caller holds self._lock — _locked suffix contract)
            if tr.get("ephemeral"):
                continue
            for s in tr["open"].values():
                s.end_ns = s.end_ns or time.time_ns()
                tr["spans"].append(s.to_dict())
            rec = self._summarize(tid, tr)
            rec["status"] = "truncated"
            self._done.append(rec)                    # graftlint: ok(caller holds self._lock)

    @staticmethod
    def _summarize(trace_id: str, tr: dict) -> dict:
        spans = tr["spans"]
        start = min((s["start_ns"] for s in spans), default=0)
        end = max((s["end_ns"] for s in spans), default=0)
        root = tr.get("root")
        status = "ok"
        if any(s["status"] == "error" for s in spans):
            status = "error"
        elif any(s["status"] == "delayed" for s in spans):
            status = "delayed"
        return {"trace_id": trace_id,
                "name": root.name if root is not None else
                (spans[0]["name"] if spans else ""),
                "start_ns": start, "dur_ns": max(end - start, 0),
                "nspans": len(spans), "dropped": tr["dropped"],
                "status": status, "spans": spans}

    def list_traces(self) -> list[dict]:
        """Completed-trace summaries, newest first (span lists omitted)."""
        with self._lock:
            done = list(self._done)
        return [{k: v for k, v in t.items() if k != "spans"}
                for t in reversed(done)]

    def get_trace(self, trace_id: str) -> dict:
        """Full completed trace; an in-flight trace returns its partial
        span list with ``in_progress: true``. Raises ``KeyError`` if the
        id is unknown (evicted or never seen)."""
        with self._lock:
            # newest record wins: same-traceparent callers produce several
            # completed records per trace_id; the latest is the one with
            # the substantive spans
            for t in reversed(self._done):
                if t["trace_id"] == trace_id:
                    return dict(t)
            tr = self._active.get(trace_id)
            if tr is not None:
                partial = {"spans": list(tr["spans"]),
                           "dropped": tr["dropped"], "root": tr.get("root")}
        if tr is not None:
            rec = self._summarize(trace_id, partial)
            rec["in_progress"] = True
            return rec
        raise KeyError(f"no trace {trace_id!r}")

    def clear(self) -> None:
        """Drop every trace (tests only)."""
        with self._lock:
            self._active.clear()
            self._done.clear()


TRACER = Tracer()


def run_in_context(ctx: SpanContext | None, fn, *args, **kwargs):
    """Run ``fn`` with ``ctx`` as the active span context — the hand-off
    for worker-pool threads whose submitter remains blocked (no retention
    needed; the submitting span outlives the call)."""
    if ctx is None:
        return fn(*args, **kwargs)
    token = _CURRENT.set(ctx)
    try:
        return fn(*args, **kwargs)
    finally:
        _CURRENT.reset(token)


# ---------------------------------------------------------------------------
# Trace analysis + export


def span_tree(trace: dict) -> list[dict]:
    """Nested ``{**span, "children": [...]}`` forest from a trace's flat
    span list (roots = spans whose parent is absent from the trace)."""
    spans = trace.get("spans", [])
    nodes = {s["span_id"]: {**s, "children": []} for s in spans}
    roots = []
    for s in spans:
        node = nodes[s["span_id"]]
        parent = nodes.get(s["parent_id"]) if s["parent_id"] else None
        if parent is None:
            roots.append(node)
        else:
            parent["children"].append(node)
    for n in nodes.values():
        n["children"].sort(key=lambda c: c["start_ns"])
    roots.sort(key=lambda c: c["start_ns"])
    return roots


def critical_path(trace: dict) -> list[dict]:
    """The chain of spans that determined the trace's wall time: from the
    root, repeatedly descend into the child that finished last. Each entry
    reports its span and ``self_ns`` — time not accounted to the next span
    on the path (host work between dispatches)."""
    roots = span_tree(trace)
    if not roots:
        return []
    cur = max(roots, key=lambda n: n["end_ns"])
    path = []
    while True:
        nxt = max(cur["children"], key=lambda n: n["end_ns"], default=None)
        path.append({"span_id": cur["span_id"], "name": cur["name"],
                     "kind": cur["kind"], "dur_ns": cur["dur_ns"],
                     "self_ns": max(cur["dur_ns"] - (nxt["dur_ns"] if nxt
                                                     else 0), 0)})
        if nxt is None:
            return path
        cur = nxt


def to_chrome_trace(trace: dict) -> dict:
    """Chrome trace-event JSON (``ph``/``ts``/``dur``/``pid``/``tid``) —
    loadable in Perfetto / chrome://tracing. Spans become complete ("X")
    events; per-thread (and per-partition) lanes get thread_name metadata.
    Timestamps are µs relative to the trace start."""
    spans = trace.get("spans", [])
    t0 = trace.get("start_ns") or min(
        (s["start_ns"] for s in spans), default=0)
    pid = os.getpid()
    tids = {}
    events = [{"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
               "args": {"name": f"h2o3_tpu trace {trace.get('trace_id')}"}}]
    for s in spans:
        lane = s.get("tid") or "0"
        if lane not in tids:
            tids[lane] = len(tids) + 1
            events.append({"ph": "M", "name": "thread_name", "pid": pid,
                           "tid": tids[lane],
                           "args": {"name": lane if not lane.isdigit()
                                    else f"thread-{lane}"}})
        events.append({
            "ph": "X", "name": s["name"], "cat": s["kind"],
            "ts": (s["start_ns"] - t0) / 1e3,
            "dur": max(s["dur_ns"] / 1e3, 0.001),
            "pid": pid, "tid": tids[lane],
            "args": {"span_id": s["span_id"], "parent_id": s["parent_id"],
                     "status": s["status"], **s["attrs"]}})
    return {"displayTimeUnit": "ms", "traceEvents": events}
