"""Utilities: keyed registry (DKV equivalent), logging, tables."""

from h2o3_tpu.utils.registry import DKV, KeyedStore

__all__ = ["DKV", "KeyedStore"]
