"""On-demand device profiler — bounded ``jax.profiler.trace`` captures.

The span tracer (``utils/tracing.py``) answers *which request/iteration was
slow*; this module answers *what the device and the XLA runtime were doing
while it was slow*: ``POST /3/Profiler/capture`` wraps
``jax.profiler.trace`` around a bounded window and keeps the resulting
Perfetto-loadable artifact (the ``*.trace.json.gz`` Chrome-trace file the
profiler writes) for listing and download.

While a capture is open, every span the tracer starts additionally enters a
``jax.profiler.TraceAnnotation`` named after the span (via
``tracing.SPAN_HOOK``), so the profiler timeline carries the SAME names the
span tree uses — host spans, device ops, and XLA runtime events line up in
one Perfetto view.

One capture at a time: the profiler runtime is process-global state, so a
second concurrent ``capture()`` raises :class:`CaptureBusy` (the REST layer
maps it to a structured 409). Artifacts live under ``H2O3TPU_PROFILE_DIR``
(default: a per-process dir under the system tempdir) and the registry
keeps the last :data:`MAX_CAPTURES` — older artifact directories are
deleted.
"""

from __future__ import annotations

import glob
import os
import shutil
import tempfile
import threading
import time
import uuid

#: captures are bounded: 10 ms .. 30 s
MIN_CAPTURE_MS = 10
MAX_CAPTURE_MS = 30_000

MAX_CAPTURES = 8


class CaptureBusy(RuntimeError):
    """A capture is already open — the profiler runtime is process-global,
    so concurrent captures would interleave into one corrupt artifact."""


def _base_dir() -> str:
    d = os.environ.get("H2O3TPU_PROFILE_DIR", "").strip()
    if not d:
        d = os.path.join(tempfile.gettempdir(),
                         f"h2o3_tpu_profiles_{os.getpid()}")
    os.makedirs(d, exist_ok=True)
    return d


class DeviceProfiler:
    """Single-flight ``jax.profiler.trace`` capture manager."""

    def __init__(self):
        self._busy = threading.Lock()
        self._reg_lock = threading.Lock()
        self._captures: list[dict] = []

    def capture(self, duration_ms: int = 500, exercise: bool = True) -> dict:
        """Open a profiler trace for ``duration_ms`` (clamped to
        [10 ms, 30 s]), annotate spans for the window, and register the
        artifact. ``exercise`` runs one tiny traced dispatch under a
        ``profiler:exercise`` span so an otherwise-idle server still yields
        a non-empty, annotation-carrying capture. Raises
        :class:`CaptureBusy` when a capture is already open."""
        duration_ms = max(MIN_CAPTURE_MS, min(int(duration_ms),
                                              MAX_CAPTURE_MS))
        if not self._busy.acquire(blocking=False):
            raise CaptureBusy(
                "a profiler capture is already in progress (the profiler "
                "runtime is process-global; retry when it completes)")
        try:
            import jax
            from h2o3_tpu.utils import tracing as _tr
            cap_id = f"cap_{uuid.uuid4().hex[:12]}"
            out_dir = os.path.join(_base_dir(), cap_id)
            os.makedirs(out_dir, exist_ok=True)
            t0 = time.time()
            jax.profiler.start_trace(out_dir)
            _tr.SPAN_HOOK = _annotation_hook
            try:
                deadline = time.perf_counter() + duration_ms / 1e3
                if exercise:
                    self._exercise()
                while time.perf_counter() < deadline:
                    time.sleep(min(0.01, max(
                        deadline - time.perf_counter(), 0.0)))
            finally:
                _tr.SPAN_HOOK = None
                jax.profiler.stop_trace()
            rec = self._register(cap_id, out_dir, duration_ms, t0)
            return rec
        finally:
            self._busy.release()

    @staticmethod
    def _exercise() -> None:
        """One tiny traced dispatch under a span, so the capture provably
        carries span-derived annotations even on an idle server."""
        import jax
        import jax.numpy as jnp
        from h2o3_tpu.utils import tracing as _tr
        with _tr.TRACER.span("profiler:exercise", kind="profile", root=True,
                             ephemeral=True):
            x = jnp.ones((128, 128), jnp.float32)
            jax.block_until_ready(jax.jit(jnp.matmul)(x, x))  # graftlint: ok(profiler exercise — the capture needs a synced dispatch inside the window)

    def _register(self, cap_id: str, out_dir: str, duration_ms: int,
                  t0: float) -> dict:
        trace_files = sorted(glob.glob(os.path.join(
            out_dir, "plugins", "profile", "*", "*.trace.json.gz")))
        artifact = trace_files[-1] if trace_files else None
        rec = {"capture_id": cap_id, "duration_ms": duration_ms,
               "started_at_ms": int(t0 * 1000),
               "artifact": os.path.basename(artifact) if artifact else None,
               "bytes": os.path.getsize(artifact) if artifact else 0,
               "path": artifact}
        with self._reg_lock:
            self._captures.append(rec)
            while len(self._captures) > MAX_CAPTURES:
                old = self._captures.pop(0)
                shutil.rmtree(os.path.join(_base_dir(), old["capture_id"]),
                              ignore_errors=True)
        return {k: v for k, v in rec.items() if k != "path"}

    def list_captures(self) -> list[dict]:
        with self._reg_lock:
            return [{k: v for k, v in rec.items() if k != "path"}
                    for rec in self._captures]

    def artifact_bytes(self, capture_id: str) -> tuple[bytes, str]:
        """(gzip bytes, filename) of a capture's Perfetto trace artifact.
        Raises ``KeyError`` for unknown/evicted ids or artifact-less
        captures."""
        with self._reg_lock:
            rec = next((r for r in self._captures
                        if r["capture_id"] == capture_id), None)
        if rec is None or not rec.get("path"):
            raise KeyError(f"no profiler capture {capture_id!r} "
                           "(the registry keeps the last "
                           f"{MAX_CAPTURES})")
        with open(rec["path"], "rb") as f:
            return f.read(), rec["artifact"]

    def clear(self) -> None:
        """Tests only: drop the registry and its artifact dirs."""
        with self._reg_lock:
            for rec in self._captures:
                shutil.rmtree(os.path.join(_base_dir(), rec["capture_id"]),
                              ignore_errors=True)
            self._captures.clear()


def _annotation_hook(name: str):
    """``tracing.SPAN_HOOK`` payload: enter a ``TraceAnnotation`` carrying
    the span's name (shows as the event's ``long_name`` in the Chrome
    trace). Returns the live context manager, or None when jax is absent —
    tracing must never break on a profiler problem."""
    try:
        import jax
        ann = jax.profiler.TraceAnnotation(name)
        ann.__enter__()
        return ann
    except Exception:   # noqa: BLE001 — annotation is best-effort
        return None


PROFILER = DeviceProfiler()
