"""Frame utilities: CreateFrame, interactions, TF-IDF, rebalance.

Reference:

- ``hex/createframe/`` + ``water/api/schemas3/CreateFrameV3.java`` — random
  frame generator (column-type fractions, factor cardinality, missing
  fraction, optional response).
- ``water/fvec/CreateInteractions.java`` / h2o-py ``h2o.interaction`` —
  categorical interaction columns: combined levels of factor tuples,
  truncated to the ``max_factors`` most frequent (rest → ``"other"``),
  ``min_occurrence`` filter.
- ``hex/tfidf/`` (TermFrequencyTask, InverseDocumentFrequencyTask:
  ``idf = log((N+1)/(df+1))``) / h2o-py ``tf_idf`` — output rows
  (document id, word, tf, idf, tf-idf).
- ``water/fvec/RebalanceDataSet.java`` — re-chunk for parallelism. Here
  sharding is always even over the device mesh, so rebalance re-materializes
  the frame (fresh upload → fresh padding/sharding); its main use is
  compacting a frame whose logical ``nrows`` shrank (filters).

Generation and text processing are host-side (like the reference's
in-memory chunk builders); the results upload to device-sharded Vecs.
"""

from __future__ import annotations

import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.types import VecType
from h2o3_tpu.frame.vec import Vec


def split_frame(frame: Frame, ratios=(0.75,), destination_frames=None,
                seed: int = -1) -> list[Frame]:
    """Probabilistic row split (h2o-py ``frame.split_frame``; reference
    ``h2o-py/h2o/frame.py:2543`` — per-row uniform draw against cumulative
    ratio boundaries, so splits have the ratios in expectation, exact-ish at
    scale). Returns ``len(ratios)+1`` frames; registers them in DKV when
    ``destination_frames`` names are given."""
    ratios = list(ratios)
    if not ratios:
        raise ValueError("ratios may not be empty")
    if any(r <= 0 for r in ratios):
        raise ValueError("ratios must be > 0")
    if sum(ratios) >= 1.0:
        raise ValueError("ratios must add up to less than 1.0")
    if destination_frames is not None and len(destination_frames) != len(ratios) + 1:
        raise ValueError("need len(ratios)+1 destination_frames")
    rng = np.random.default_rng(None if seed in (-1, None) else int(seed))
    u = rng.random(frame.nrows)
    bounds = np.cumsum([0.0] + ratios + [1.0])
    out = []
    for i in range(len(ratios) + 1):
        mask = np.zeros(frame.plen, np.float32)
        mask[:frame.nrows] = ((u > bounds[i]) if i else (u >= 0)) & (u <= bounds[i + 1])
        part = frame.filter(mask)
        if destination_frames is not None:
            from h2o3_tpu.utils.registry import DKV
            part.key = destination_frames[i]
            DKV.put(part.key, part)
        out.append(part)
    return out


def create_frame(rows: int = 10000, cols: int = 10, randomize: bool = True,
                 value: float = 0.0, real_range: float = 100.0,
                 categorical_fraction: float = 0.2, factors: int = 100,
                 integer_fraction: float = 0.2, integer_range: int = 100,
                 binary_fraction: float = 0.1, binary_ones_fraction: float = 0.02,
                 time_fraction: float = 0.0, string_fraction: float = 0.0,
                 missing_fraction: float = 0.01, has_response: bool = False,
                 response_factors: int = 2, positive_response: bool = False,
                 seed: int | None = None, key: str | None = None) -> Frame:
    """h2o-py ``h2o.create_frame`` (reference: CreateFrameV3 fields)."""
    fracs = (categorical_fraction + integer_fraction + binary_fraction
             + time_fraction + string_fraction)
    if fracs > 1.0 + 1e-9:
        raise ValueError("column type fractions sum to > 1")
    rng = np.random.default_rng(seed)
    counts = {
        "cat": int(round(cols * categorical_fraction)),
        "int": int(round(cols * integer_fraction)),
        "bin": int(round(cols * binary_fraction)),
        "time": int(round(cols * time_fraction)),
        "str": int(round(cols * string_fraction)),
    }
    counts["real"] = max(0, cols - sum(counts.values()))

    names, vecs = [], []
    if has_response:
        names.append("response")
        if response_factors == 1:
            r = rng.uniform(0, real_range, rows) if positive_response \
                else rng.uniform(-real_range, real_range, rows)
            vecs.append(Vec.from_numpy(r.astype(np.float32)))
        else:
            dom = tuple(f"resp_{i}" for i in range(response_factors))
            codes = rng.integers(0, response_factors, rows)
            vecs.append(Vec.from_numpy(codes.astype(np.int32), VecType.CAT, domain=dom))

    def miss(arr):
        if missing_fraction > 0 and randomize:
            m = rng.random(rows) < missing_fraction
            arr = arr.astype(np.float64)
            arr[m] = np.nan
        return arr

    idx = 0
    for kind, n in counts.items():
        for _ in range(n):
            name = f"C{idx + 1}"
            idx += 1
            names.append(name)
            if not randomize:
                vecs.append(Vec.from_numpy(np.full(rows, value, np.float32)))
                continue
            if kind == "real":
                vecs.append(Vec.from_numpy(
                    miss(rng.uniform(-real_range, real_range, rows)).astype(np.float32)))
            elif kind == "int":
                vecs.append(Vec.from_numpy(
                    miss(rng.integers(-integer_range, integer_range + 1, rows)
                         .astype(np.float64)).astype(np.float32)))
            elif kind == "bin":
                vecs.append(Vec.from_numpy(
                    miss((rng.random(rows) < binary_ones_fraction)
                         .astype(np.float64)).astype(np.float32)))
            elif kind == "cat":
                dom = tuple(f"c{idx}.l{i}" for i in range(factors))
                codes = rng.integers(0, factors, rows).astype(np.int32)
                if missing_fraction > 0:
                    codes[rng.random(rows) < missing_fraction] = -1
                vecs.append(Vec.from_numpy(codes, VecType.CAT, domain=dom))
            elif kind == "time":
                t = rng.integers(0, 2_000_000_000_000, rows).astype(np.float64)
                vecs.append(Vec.from_numpy(miss(t), VecType.TIME))
            else:  # str
                strs = np.array([f"s{v:06d}" for v in rng.integers(0, 10**6, rows)],
                                dtype=object)
                vecs.append(Vec.from_numpy(strs, VecType.STR))
    return Frame(names, vecs, key=key)


def interaction(frame: Frame, factors: list, pairwise: bool = False,
                max_factors: int = 100, min_occurrence: int = 1,
                destination_frame: str | None = None) -> Frame:
    """h2o-py ``h2o.interaction`` (reference: CreateInteractions.java).

    ``factors``: column names (or a list of lists for several interactions).
    ``pairwise``: all 2-way combos instead of one N-way interaction.
    """
    if factors and isinstance(factors[0], (list, tuple)):
        groups = [list(g) for g in factors]
    elif pairwise:
        groups = [[a, b] for i, a in enumerate(factors)
                  for b in factors[i + 1:]]
    else:
        groups = [list(factors)]

    names, vecs = [], []
    for group in groups:
        if len(group) < 2:
            raise ValueError(f"interaction needs >= 2 columns, got {group}")
        labels = None
        na = None
        for c in group:
            v = frame.vec(c)
            if not v.is_categorical:
                raise ValueError(f"interaction column {c!r} must be categorical")
            part = v.labels().astype(object)
            pna = np.array([l is None for l in part])
            part = np.where(pna, "", part).astype(object)
            labels = part if labels is None else labels + "_" + part
            na = pna if na is None else (na | pna)
        labels[na] = None
        # frequency-ranked domain, truncated to max_factors (rest → "other")
        vals, cnts = np.unique(labels[labels != None], return_counts=True)  # noqa: E711
        keep = vals[cnts >= min_occurrence]
        kc = cnts[cnts >= min_occurrence]
        order = np.argsort(-kc, kind="stable")
        kept = list(keep[order][:max_factors])
        overflow = (len(keep) > max_factors) or (len(vals) > len(keep))
        dom = tuple(kept + (["other"] if overflow else []))
        lut = {lvl: i for i, lvl in enumerate(dom)}
        other = lut.get("other", -1)
        codes = np.array([lut.get(l, other) if l is not None else -1
                          for l in labels], np.int32)
        names.append("_".join(group))
        vecs.append(Vec.from_numpy(codes, VecType.CAT, domain=dom))
    return Frame(names, vecs, key=destination_frame)


def tf_idf(frame: Frame, document_id_col: str, text_col: str,
           preprocess: bool = True, case_sensitive: bool = True) -> Frame:
    """h2o-py ``tf_idf`` (reference: hex/tfidf). Returns a frame with rows
    (document id, word, tf, idf, tf-idf); ``idf = log((N+1)/(df+1))``."""
    doc_v = frame.vec(document_id_col)
    txt_v = frame.vec(text_col)
    docs = doc_v.labels() if doc_v.domain is not None else doc_v.to_numpy()
    texts = txt_v.labels() if txt_v.domain is not None else txt_v.to_numpy()

    pairs: dict[tuple, int] = {}
    doc_words: dict[str, set] = {}
    for d, t in zip(docs, texts):
        if t is None or (isinstance(t, float) and np.isnan(t)):
            continue
        words = str(t).split() if preprocess else [str(t)]
        for w in words:
            if not case_sensitive:
                w = w.lower()
            pairs[(d, w)] = pairs.get((d, w), 0) + 1
            doc_words.setdefault(w, set()).add(d)
    n_docs = len(set(np.asarray(docs, dtype=object)[~_isnan_obj(docs)]))
    rows = sorted(pairs.items(), key=lambda kv: (str(kv[0][0]), kv[0][1]))
    doc_numeric = doc_v.is_numeric
    if doc_numeric:
        out_doc_arr = np.array([float(d) for (d, _), _ in rows], np.float32)
    else:
        out_doc_arr = np.array([str(d) for (d, _), _ in rows], dtype=object)
    out_word = np.array([w for (_, w), _ in rows], dtype=object)
    tf = np.array([c for _, c in rows], np.float32)
    idf = np.array([np.log((n_docs + 1) / (len(doc_words[w]) + 1))
                    for (_, w), _ in rows], np.float32)
    doc_vec = (Vec.from_numpy(out_doc_arr) if doc_numeric
               else Vec.from_numpy(out_doc_arr, VecType.STR))
    return Frame(
        [document_id_col, text_col, "TF", "IDF", "TF_IDF"],
        [doc_vec,
         Vec.from_numpy(out_word, VecType.STR),
         Vec.from_numpy(tf),
         Vec.from_numpy(idf),
         Vec.from_numpy(tf * idf)])


def _isnan_obj(a):
    return np.array([isinstance(v, (float, np.floating)) and np.isnan(v)
                     for v in a])


def rebalance(frame: Frame, key: str | None = None) -> Frame:
    """Re-materialize a frame with fresh even sharding/padding (reference:
    RebalanceDataSet re-chunks; here shards are always even over the mesh, so
    this compacts logical rows and re-uploads)."""
    names, vecs = [], []
    for name in frame.names:
        v = frame.vec(name)
        host = v.to_numpy()
        if v.is_categorical:
            codes = np.asarray(v.to_numpy())
            vecs.append(Vec.from_numpy(codes.astype(np.int32), VecType.CAT,
                                       domain=v.domain))
        else:
            vecs.append(Vec.from_numpy(host, v.type))
        names.append(name)
    return Frame(names, vecs, key=key)
