"""Frame — a named list of equal-length distributed columns.

Reference: ``water/fvec/Frame.java`` (2,005 LoC) — ordered name→Vec mapping with
column add/remove/slice; all Vecs share one ESPC row layout. Here all Vecs of a
Frame share one padded length and one row sharding, so any subset of columns can
be stacked into a [rows, k] matrix for MXU-friendly compute without relayout.
"""

from __future__ import annotations

from typing import Iterable, Mapping, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.types import VecType
from h2o3_tpu.frame.vec import Vec, padded_len


class Frame:
    """Distributed columnar table (reference: ``water.fvec.Frame``)."""

    def __init__(self, names: Sequence[str], vecs: Sequence[Vec], key: str | None = None):
        if len(names) != len(vecs):
            raise ValueError("names/vecs length mismatch")
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate column names: {names}")
        nrows = {v.nrows for v in vecs}
        if len(nrows) > 1:
            raise ValueError(f"vecs disagree on nrows: {nrows}")
        self.names: list[str] = list(names)
        self.vecs: list[Vec] = list(vecs)
        self.key = key
        # mesh-view bookkeeping (see on_mesh): structural mutations bump the
        # epoch, which invalidates every cached resharded view of this frame
        self._view_epoch: int = 0
        self._mesh_views: dict[tuple, "Frame | str"] = {}
        self._is_mesh_view: bool = False

    # -- construction -------------------------------------------------------

    @staticmethod
    def from_arrays(cols: Mapping[str, np.ndarray], types: Mapping[str, VecType] | None = None,
                    key: str | None = None) -> "Frame":
        """Build a frame with BATCHED device upload: all float columns go up
        as one transfer and all categorical code columns as another (a
        per-column ``device_put`` costs a tunnel round-trip each)."""
        from h2o3_tpu.frame.vec import CAT_NA, _factorize, _guess_type, upload_columns
        types = types or {}
        names = list(cols.keys())
        plans: dict[str, Vec] = {}
        float_cols: list[tuple[str, np.ndarray, VecType]] = []
        cat_cols: list[tuple[str, np.ndarray, tuple]] = []
        for k in names:
            v = np.asarray(cols[k])
            t = types.get(k) or _guess_type(v)
            if t is VecType.CAT and v.dtype.kind not in "iu":
                codes, dom = _factorize(v)
                cat_cols.append((k, codes.astype(np.int32), tuple(dom)))
            elif t is VecType.CAT:
                # caller passed codes + (domain unknown) — per-column path
                plans[k] = Vec.from_numpy(v, type=t)
            elif t in (VecType.NUM, VecType.INT) and v.dtype.kind in "fiub":
                float_cols.append((k, np.asarray(v, np.float32), t))
            else:
                plans[k] = Vec.from_numpy(v, type=t)
        nrows = len(next(iter(cols.values()))) if cols else 0
        fdev = upload_columns([h for _, h, _ in float_cols], nrows, np.nan, np.float32)
        cdev = upload_columns([c for _, c, _ in cat_cols], nrows, CAT_NA, np.int32)
        for (k, _, t), d in zip(float_cols, fdev):
            plans[k] = Vec.from_device(d, nrows, t)
        for (k, _, dom), d in zip(cat_cols, cdev):
            plans[k] = Vec.from_device(d, nrows, VecType.CAT, domain=dom)
        return Frame(names, [plans[k] for k in names], key=key)

    @staticmethod
    def from_pandas(df, key: str | None = None) -> "Frame":
        """Convert a pandas DataFrame (type guessing per parser semantics);
        numeric/categorical columns ride the batched upload of
        :meth:`from_arrays`."""
        cols: dict[str, np.ndarray] = {}
        types: dict[str, VecType] = {}
        time_cols: dict[str, np.ndarray] = {}
        for col in df.columns:
            s = df[col]
            name = str(col)
            if s.dtype.kind in "OUS" or str(s.dtype) in ("category", "str"):
                if str(s.dtype) == "category":
                    # re-factorize so the domain is sorted (parser contract)
                    cols[name] = s.astype(object).to_numpy()
                else:
                    cols[name] = s.to_numpy(dtype=object)
            elif s.dtype.kind == "M":
                # pandas >=3.0 defaults to datetime64[us]; Vec normalizes to ns
                time_cols[name] = s.to_numpy()
            elif s.dtype.kind == "b":
                cols[name] = s.to_numpy().astype(np.float32)
                types[name] = VecType.INT
            else:
                cols[name] = s.to_numpy(dtype=np.float32, na_value=np.nan)
        fr = Frame.from_arrays(cols, types=types)
        names, vecs = [], []
        for col in df.columns:
            name = str(col)
            if name in time_cols:
                names.append(name)
                vecs.append(Vec.from_numpy(time_cols[name], type=VecType.TIME))
            else:
                names.append(name)
                vecs.append(fr.vec(name))
        return Frame(names, vecs, key=key)

    # -- shape --------------------------------------------------------------

    @property
    def nrows(self) -> int:
        return self.vecs[0].nrows if self.vecs else 0

    @property
    def ncols(self) -> int:
        return len(self.vecs)

    @property
    def plen(self) -> int:
        """Padded device length shared by all on-device columns."""
        return self.vecs[0].plen if self.vecs else padded_len(0)

    @property
    def shape(self) -> tuple[int, int]:
        return (self.nrows, self.ncols)

    @property
    def nbytes(self) -> int:
        """Summed resident bytes of every column (device chunks + host
        payloads) — what `/3/Memory` reports for this frame's key."""
        return sum(v.nbytes for v in self.vecs)

    @property
    def types(self) -> dict[str, str]:
        return {n: str(v.type) for n, v in zip(self.names, self.vecs)}

    def drop_device_views(self) -> int:
        """Release every column's derived (decompress-on-access) device
        array — the Cleaner's cheapest eviction tier for frames built by
        the streaming ingest path. Returns freed device bytes; columns
        without a compressed host payload are untouched."""
        return sum(v.drop_device() for v in self.vecs)

    # -- column access ------------------------------------------------------

    def vec(self, col: int | str) -> Vec:
        return self.vecs[self._index(col)]

    def _index(self, col: int | str) -> int:
        if isinstance(col, (int, np.integer)):
            return int(col)
        try:
            return self.names.index(col)
        except ValueError:
            raise KeyError(f"no such column: {col!r} (have {self.names})") from None

    def __getitem__(self, sel):
        if isinstance(sel, (str, int, np.integer)):
            i = self._index(sel)
            return Frame([self.names[i]], [self.vecs[i]])
        if isinstance(sel, (list, tuple)):
            idxs = [self._index(c) for c in sel]
            return Frame([self.names[i] for i in idxs], [self.vecs[i] for i in idxs])
        if isinstance(sel, Vec):           # boolean row filter (rapids AstRowSlice)
            return self.filter(sel)
        raise TypeError(f"unsupported selector {sel!r}")

    def __contains__(self, name: str) -> bool:
        return name in self.names

    def add(self, name: str, vec: Vec) -> "Frame":
        if vec.nrows != self.nrows and self.vecs:
            raise ValueError("row count mismatch")
        if name in self.names:
            raise ValueError(f"duplicate column name: {name!r}")
        self.names.append(name)
        self.vecs.append(vec)
        self.invalidate_views()
        return self

    def remove(self, col: int | str) -> Vec:
        i = self._index(col)
        self.names.pop(i)
        self.invalidate_views()
        return self.vecs.pop(i)

    def replace_vec(self, col: int | str, vec: Vec) -> "Frame":
        """Replace a column's Vec IN PLACE (impute, pipeline transforms).
        Goes through here — not ``frame.vecs[i] = ...`` — so cached mesh
        views are invalidated: a slice-bound build resharding this frame
        must see the replacement, never the pre-mutation column."""
        if vec.nrows != self.nrows and self.vecs:
            raise ValueError("row count mismatch")
        self.vecs[self._index(col)] = vec
        self.invalidate_views()
        return self

    def subframe(self, cols: Iterable[str]) -> "Frame":
        return self[list(cols)]

    # -- mesh views (slice-bound builds; orchestration/scheduler.py) ---------

    def invalidate_views(self) -> None:
        """Drop every cached resharded view (called on structural mutation —
        add/remove — so a slice-bound build can never train on a stale
        column set). DKV-registered view keys are removed so their bytes
        leave ``/3/Memory`` with them."""
        self._view_epoch += 1
        stale, self._mesh_views = self._mesh_views, {}
        if any(isinstance(v, str) for v in stale.values()):
            from h2o3_tpu.utils.registry import DKV
            for v in stale.values():
                if isinstance(v, str):
                    DKV.remove(v)

    def on_mesh(self, mesh) -> "Frame":
        """This frame resharded onto ``mesh`` — ONE batched ``device_put``
        of the stacked column matrix per dtype (the ``upload_columns``
        pattern: per-column transfers cost a tunnel round-trip each).

        Returns ``self`` when the frame is already laid out on ``mesh``'s
        device set. Views are cached per (device set, mutation epoch) and
        byte-accounted: a keyed frame's views register in the DKV under
        ``{key}::mesh[...]`` so ``/3/Memory`` shows resharded bytes and the
        Cleaner can evict them (an evicted view is simply rebuilt from the
        source columns on next use)."""
        from h2o3_tpu.parallel.mesh import mesh_device_ids
        dev_idx = [i for i, v in enumerate(self.vecs) if v.data is not None]
        if not dev_idx:
            return self
        target = mesh_device_ids(mesh)
        cur = getattr(self.vecs[dev_idx[0]].data, "sharding", None)
        cur_devs = tuple(sorted(d.id for d in getattr(cur, "device_set", ())
                                )) if cur is not None else ()
        if cur_devs == target:
            return self
        ck = (target, self._view_epoch)
        cached = self._mesh_views.get(ck)
        if cached is not None:
            if isinstance(cached, Frame):
                return cached
            # DKV-registered view: rebuild if it was evicted/removed
            from h2o3_tpu.utils.cleaner import CLEANER
            from h2o3_tpu.utils.registry import DKV
            with DKV._lock:
                live = DKV._store.get(cached)
            if type(live).__name__ == "Frame":
                # keep hot views off the LRU chopping block (on_mesh reads
                # the raw store, so DKV.get's access accounting never fires)
                CLEANER.touch(cached)
                return live
        view = self._reshard(mesh)
        view._is_mesh_view = True
        if self.key:
            from h2o3_tpu.utils.registry import DKV
            vkey = f"{self.key}::mesh[{'-'.join(map(str, target))}]" \
                   f"@{self._view_epoch}"
            view.key = vkey
            DKV.put(vkey, view)
            self._mesh_views[ck] = vkey
        else:
            self._mesh_views[ck] = view
        return view

    def _reshard(self, mesh) -> "Frame":
        """Copy every device column onto ``mesh`` in (at most) two batched
        transfers — one [k, plen] float stack, one int stack for CAT codes —
        then slice rows back out (each slice inherits the target row
        sharding, exactly like ``upload_columns``)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from h2o3_tpu.parallel.mesh import ROWS
        sharding = NamedSharding(mesh, P(None, ROWS))
        groups: dict[str, list[int]] = {}
        for i, v in enumerate(self.vecs):
            if v.data is not None:
                groups.setdefault(str(v.data.dtype), []).append(i)
        moved: dict[int, jax.Array] = {}
        for idxs in groups.values():
            stacked = jnp.stack([self.vecs[i].data for i in idxs], axis=0)
            dev = jax.device_put(stacked, sharding)
            for j, i in enumerate(idxs):
                moved[i] = dev[j]
        vecs = []
        for i, v in enumerate(self.vecs):
            if i not in moved:
                vecs.append(v)          # host-only columns share the payload
                continue
            nv = Vec(moved[i], v.type, v.nrows, domain=v.domain,
                     host_values=v.host_values, time_offset=v.time_offset)
            nv._rollups = v._rollups    # rollups are layout-independent
            vecs.append(nv)
        return Frame(list(self.names), vecs)

    # -- device views -------------------------------------------------------

    def row_mask(self) -> jax.Array:
        """Boolean [plen] device array marking logical (non-padding) rows."""
        return _row_mask(self.plen, jnp.int32(self.nrows))

    def matrix(self, cols: Sequence[str] | None = None) -> jax.Array:
        """Stack on-device columns into a [plen, k] float32 matrix.

        Categorical columns contribute their raw codes as floats (NaN for NA);
        model-ready expansion (one-hot etc.) lives in DataInfo, mirroring the
        reference split between ``Frame`` and ``hex/DataInfo.java``.
        """
        cols = list(cols) if cols is not None else [n for n, v in zip(self.names, self.vecs)
                                                    if v.type.on_device]
        arrs = []
        for c in cols:
            v = self.vec(c)
            if not v.type.on_device:
                raise TypeError(f"column {c!r} of type {v.type} has no device data")
            arrs.append(v.as_float())
        return jnp.stack(arrs, axis=1)

    # -- host round-trip ----------------------------------------------------

    def to_pandas(self):
        import pandas as pd
        out = {}
        for n, v in zip(self.names, self.vecs):
            if v.type is VecType.CAT:
                codes = v.to_numpy()
                if len(v.domain) == 0:  # all-missing column: no levels to index
                    out[n] = pd.Series([None] * v.nrows, dtype=object)
                    continue
                dom = np.asarray(v.domain, dtype=object)
                vals = np.where(codes >= 0, dom[np.clip(codes, 0, None)], None)
                out[n] = pd.Series(vals, dtype=object)
            elif v.type is VecType.TIME:
                out[n] = pd.to_datetime(pd.Series(v.to_numpy()), unit="ms")
            elif v.type.on_device:
                out[n] = v.to_numpy()
            else:
                out[n] = pd.Series(v.host_values, dtype=object)
        return pd.DataFrame(out)

    def head(self, n: int = 10):
        return self.to_pandas().head(n)

    # -- munging surface (rapids layer; mirrors h2o-py H2OFrame methods) -----

    def sort(self, by, ascending=True) -> "Frame":
        from h2o3_tpu.rapids import munge
        return munge.sort(self, by, ascending)

    def group_by(self, by):
        from h2o3_tpu.rapids import GroupBy
        return GroupBy(self, by)

    def merge(self, other: "Frame", by=None, all_x: bool = False,
              all_y: bool = False) -> "Frame":
        from h2o3_tpu.rapids import munge
        return munge.merge(self, other, by=by, all_x=all_x, all_y=all_y)

    def filter(self, mask) -> "Frame":
        from h2o3_tpu.rapids import munge
        return munge.filter_rows(self, mask)

    def rbind(self, *others: "Frame") -> "Frame":
        from h2o3_tpu.rapids import munge
        return munge.rbind(self, *others)

    def cbind(self, *others: "Frame") -> "Frame":
        from h2o3_tpu.rapids import munge
        return munge.cbind(self, *others)

    def split_frame(self, ratios=(0.75,), destination_frames=None,
                    seed: int = -1) -> list["Frame"]:
        from h2o3_tpu.frame.utils import split_frame
        return split_frame(self, ratios, destination_frames, seed)

    def unique(self, cols=None) -> "Frame":
        from h2o3_tpu.rapids import munge
        return munge.unique(self, cols)

    def pivot(self, index: str, column: str, value: str, agg: str = "mean") -> "Frame":
        from h2o3_tpu.rapids import munge
        return munge.pivot(self, index, column, value, agg)

    def melt(self, id_vars, value_vars=None, **kw) -> "Frame":
        from h2o3_tpu.rapids import munge
        return munge.melt(self, id_vars, value_vars, **kw)

    def quantile(self, probs=(0.001, 0.01, 0.1, 0.25, 0.333, 0.5, 0.667,
                              0.75, 0.9, 0.99, 0.999)) -> "Frame":
        from h2o3_tpu.rapids import ops
        return ops.quantile(self, probs)

    def impute(self, column: str, method: str = "mean", by=None) -> "Frame":
        from h2o3_tpu.rapids import ops
        return ops.impute(self, column, method, by)

    def scale(self, center: bool = True, scale: bool = True) -> "Frame":
        from h2o3_tpu.rapids import ops
        return ops.scale(self, center, scale)

    def __len__(self) -> int:
        return self.nrows

    def __repr__(self) -> str:
        return f"Frame({self.nrows} rows x {self.ncols} cols: {self.names[:8]}{'...' if self.ncols > 8 else ''})"


from functools import partial


@partial(jax.jit, static_argnames=("plen",))
def _row_mask(plen: int, nrows: jax.Array) -> jax.Array:
    return jnp.arange(plen) < nrows
