"""SQL ingest — the JDBC import equivalent.

Reference: ``water/jdbc/SQLManager.java`` (h2o-py ``import_sql_table`` /
``import_sql_select``): connect via JDBC, partition the table into SELECT
ranges fetched in parallel by the cluster, build a frame.

TPU-native: ingestion is a host-side concern (SURVEY.md §7 stage 2 — parse
on host, upload device-sharded). Python DB-API replaces JDBC: ``sqlite3``
ships in-tree; any other installed DB-API driver works through
``connection_factory``. Range-partitioned fetches mirror the reference's
SELECT splitting (over ``rowid`` for sqlite).
"""

from __future__ import annotations

import numpy as np

from h2o3_tpu.frame.frame import Frame


def _connect(connection_url: str, connection_factory=None):
    if connection_factory is not None:
        return connection_factory(connection_url)
    if connection_url.startswith(("sqlite:", "sqlite3:")):
        import sqlite3
        path = connection_url.split(":", 1)[1].lstrip("/")
        # keep absolute paths absolute (sqlite:///tmp/x.db)
        if connection_url.count("/") >= 3 or connection_url.startswith("sqlite:/"):
            path = "/" + path if not path.startswith("/") else path
        return sqlite3.connect(path)
    raise ValueError(
        f"unsupported connection url {connection_url!r}: built-in support is "
        "sqlite:<path>; pass connection_factory=<callable> for other DB-API "
        "drivers (the reference's JDBC drivers are likewise user-supplied)")


def _rows_to_frame(cols, rows, key=None) -> Frame:
    """Build + DKV-register (imports are addressable by key, like the
    reference's destination_frame)."""
    n = len(rows)
    arrays = {}
    for i, name in enumerate(cols):
        vals = [r[i] for r in rows]
        numeric = all(v is None or isinstance(v, (int, float)) for v in vals)
        if numeric:
            arr = np.array([np.nan if v is None else float(v) for v in vals],
                           np.float32)
        else:
            arr = np.array(["" if v is None else str(v) for v in vals],
                           dtype=object)
        arrays[name] = arr
    if n == 0:
        raise ValueError("query returned no rows")
    frame = Frame.from_arrays(arrays, key=key)
    if frame.key:
        from h2o3_tpu.utils.registry import DKV
        DKV.put(frame.key, frame)
    return frame


def import_sql_select(connection_url: str, select_query: str,
                      username: str | None = None, password: str | None = None,
                      connection_factory=None, key: str | None = None) -> Frame:
    """h2o-py ``import_sql_select``: run a SELECT, build a frame."""
    conn = _connect(connection_url, connection_factory)
    try:
        cur = conn.cursor()
        cur.execute(select_query)
        cols = [d[0] for d in cur.description]
        rows = cur.fetchall()
    finally:
        conn.close()
    return _rows_to_frame(cols, rows, key=key)


def import_sql_table(connection_url: str, table: str,
                     columns: list[str] | None = None,
                     username: str | None = None, password: str | None = None,
                     fetch_mode: str = "SINGLE", num_chunks: int = 4,
                     connection_factory=None, key: str | None = None) -> Frame:
    """h2o-py ``import_sql_table``: fetch a whole table.

    ``fetch_mode="DISTRIBUTED"`` splits the scan into ``num_chunks`` rowid
    ranges (the reference's parallel SELECT ranges, SQLManager.java)."""
    if not table.replace("_", "").replace(".", "").isalnum():
        raise ValueError(f"suspicious table name {table!r}")
    key = key or table          # default destination key = table name
    collist = ", ".join(columns) if columns else "*"
    conn = _connect(connection_url, connection_factory)
    try:
        cur = conn.cursor()
        # The reference's DISTRIBUTED mode partitions by KEYED ranges
        # (SQLManager.java: WHERE id > a AND id <= b per node) — never
        # LIMIT/OFFSET, whose unspecified order can overlap/skip rows.
        # Keyed ranges need a key: sqlite exposes `rowid`, so we range over
        # it there; for other DB-API drivers (and sqlite views/WITHOUT-ROWID
        # tables, which have no rowid) a single-controller ingest gains
        # nothing from chunked scans, so they take the one-SELECT path.
        ranges = None
        if fetch_mode.upper() == "DISTRIBUTED" and connection_factory is None:
            try:
                cur.execute(f"SELECT MIN(rowid), MAX(rowid) FROM {table}")  # noqa: S608
                lo, hi = cur.fetchone()
            except Exception:
                lo = hi = None      # view / WITHOUT ROWID: fall through
            if lo is not None:
                per = max(1, (hi - lo + 1 + num_chunks - 1) // num_chunks)
                ranges = [(lo - 1 + c * per, min(lo - 1 + (c + 1) * per, hi))
                          for c in range(num_chunks)
                          if lo - 1 + c * per < hi]
        if ranges is not None:
            rows, cols = [], None
            for a, b in ranges:
                cur.execute(f"SELECT {collist} FROM {table} "   # noqa: S608
                            f"WHERE rowid > {a} AND rowid <= {b} "
                            "ORDER BY rowid")
                if cols is None:
                    cols = [d[0] for d in cur.description]
                rows.extend(cur.fetchall())
        else:
            cur.execute(f"SELECT {collist} FROM {table}")   # noqa: S608
            cols = [d[0] for d in cur.description]
            rows = cur.fetchall()
    finally:
        conn.close()
    return _rows_to_frame(cols, rows, key=key)
