"""Data plane: distributed columnar frames (reference: ``water/fvec``)."""

from h2o3_tpu.frame.types import VecType, CAT_NA
from h2o3_tpu.frame.vec import Vec, padded_len
from h2o3_tpu.frame.frame import Frame

__all__ = ["Frame", "Vec", "VecType", "CAT_NA", "padded_len"]
