"""Lazy rollup statistics over a sharded column.

Reference: ``water/fvec/RollupStats.java:19-30,40-146`` — every Vec lazily
computes min/max/mean/sigma/NA-count/zero-count (plus a histogram) exactly once
per mutation epoch, via an MRTask whose per-chunk partials reduce with a
commutative-associative merge.

TPU-native expression: the whole column is a single row-sharded ``jax.Array``,
so the "MRTask" is one jitted reduction — XLA's SPMD partitioner computes
per-shard partials on each chip and all-reduces them over ICI. Results are
cached on the Vec and invalidated on mutation, mirroring the reference's
rollup epoch.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class Rollups:
    """Column summary statistics (reference: ``RollupStats``)."""

    nrows: int
    na_cnt: int
    min: float
    max: float
    mean: float
    sigma: float       # sample standard deviation (H2O semantics, n-1)
    nzero: int         # count of exact zeros among non-missing values
    is_int: bool       # every non-missing value is integral
    pinfs: int
    ninfs: int


@partial(jax.jit, static_argnames=("padded",))
def _numeric_rollups(data: jax.Array, nrows: jax.Array, padded: int):
    """One pass over a padded, row-sharded float column.

    Rows at index >= nrows are padding (NaN); NaN in-range means missing.
    """
    idx = jnp.arange(padded)
    in_range = idx < nrows
    finite = jnp.isfinite(data)
    valid = in_range & finite
    pinf = in_range & jnp.isposinf(data)
    ninf = in_range & jnp.isneginf(data)
    na = in_range & jnp.isnan(data)

    x = jnp.where(valid, data, 0.0)
    cnt = valid.sum()
    s = x.sum()
    mean = jnp.where(cnt > 0, s / cnt, jnp.nan)
    # Centered second pass avoids float32 catastrophic cancellation of the
    # naive sum-of-squares formula (large-mean columns); still one fused kernel.
    d = jnp.where(valid, data - mean, 0.0)
    var = jnp.where(cnt > 1, (d * d).sum() / (cnt - 1), 0.0)
    sigma = jnp.sqrt(jnp.maximum(var, 0.0))
    mn = jnp.where(valid, data, jnp.inf).min()
    mx = jnp.where(valid, data, -jnp.inf).max()
    # include infs in min/max like the reference (Double.POSITIVE_INFINITY sorts)
    mn = jnp.where(ninf.any(), -jnp.inf, mn)
    mx = jnp.where(pinf.any(), jnp.inf, mx)
    nzero = (valid & (data == 0.0)).sum()
    is_int = jnp.where(cnt > 0, (jnp.where(valid, data - jnp.round(data), 0.0) == 0.0).all(), False)
    return dict(
        na_cnt=na.sum(),  # NaN only; infs tracked separately
        min=mn, max=mx, mean=mean, sigma=sigma, nzero=nzero,
        is_int=is_int, pinfs=pinf.sum(), ninfs=ninf.sum(), cnt=cnt,
    )


def numeric_rollups(data: jax.Array, nrows: int) -> Rollups:
    r = jax.device_get(_numeric_rollups(data, jnp.int32(nrows), data.shape[0]))
    return Rollups(
        nrows=nrows,
        na_cnt=int(r["na_cnt"]),
        min=float(r["min"]) if r["cnt"] > 0 else float("nan"),
        max=float(r["max"]) if r["cnt"] > 0 else float("nan"),
        mean=float(r["mean"]),
        sigma=float(r["sigma"]),
        nzero=int(r["nzero"]),
        is_int=bool(r["is_int"]),
        pinfs=int(r["pinfs"]),
        ninfs=int(r["ninfs"]),
    )


@partial(jax.jit, static_argnames=("padded",))
def _cat_rollups(codes: jax.Array, nrows: jax.Array, padded: int):
    idx = jnp.arange(padded)
    in_range = idx < nrows
    valid = in_range & (codes >= 0)
    cnt = valid.sum()
    c = jnp.where(valid, codes, 0)
    s = c.sum()
    mean = jnp.where(cnt > 0, s / cnt, jnp.nan)
    mn = jnp.where(valid, codes, jnp.iinfo(jnp.int32).max).min()
    mx = jnp.where(valid, codes, -1).max()
    return dict(na_cnt=in_range.sum() - cnt, min=mn, max=mx, mean=mean, cnt=cnt,
                nzero=(valid & (codes == 0)).sum())


def cat_rollups(codes: jax.Array, nrows: int) -> Rollups:
    r = jax.device_get(_cat_rollups(codes, jnp.int32(nrows), codes.shape[0]))
    cnt = int(r["cnt"])
    return Rollups(
        nrows=nrows,
        na_cnt=int(r["na_cnt"]),
        min=float(r["min"]) if cnt else float("nan"),
        max=float(r["max"]) if cnt else float("nan"),
        mean=float(r["mean"]),
        sigma=float("nan"),
        nzero=int(r["nzero"]),
        is_int=True,
        pinfs=0,
        ninfs=0,
    )
