"""Vec — one distributed column.

Reference: ``water/fvec/Vec.java`` — a Vec is a collection of ~64KB compressed
chunks distributed over the cloud by an ESPC (element-start-per-chunk) layout
shared per VectorGroup (``Vec.java:152,264``), with lazily computed rollup
statistics (``RollupStats.java``).

TPU-native redesign: a Vec is ONE row-sharded ``jax.Array`` in HBM, padded to a
multiple of the mesh's row-axis size. The ESPC layout becomes the (uniform)
``NamedSharding(mesh, P("rows"))`` partition; chunk compression becomes dtype
choice (see :mod:`h2o3_tpu.frame.types`); decompress-on-access (``Chunk.atd``)
is unnecessary. String/UUID columns stay host-resident (numpy object arrays) —
they feed munging and parsing, never device compute, matching how the reference
excludes them from model training.
"""

from __future__ import annotations

import math
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.types import CAT_NA, VecType
from h2o3_tpu.frame.rollups import Rollups, cat_rollups, numeric_rollups
from h2o3_tpu.parallel.mesh import (ROWS, bound_mesh, num_global_devices,
                                    row_sharding)

# Pad row counts to a multiple of (devices * _ROW_ALIGN) so every shard is
# sublane-aligned for float32 tiles (8 x 128 min tile).
_ROW_ALIGN = 8


def padded_len(nrows: int, ndev: int | None = None) -> int:
    # against the GLOBAL device count, never just a bound slice: a frame's
    # padded length is a process-wide invariant, and scheduler slices divide
    # it (slice_meshes carves equal divisors), so arrays pad identically no
    # matter which lease creates them. A bound mesh whose size does NOT
    # divide the global unit (public mesh_context with an arbitrary submesh)
    # widens the unit to the lcm so the same array shards cleanly on both
    # the bound and the global mesh.
    if ndev is None:
        ndev = num_global_devices()
        b = bound_mesh()
        if b is not None and ROWS in b.shape:
            ndev = math.lcm(ndev, b.shape[ROWS])
    unit = ndev * _ROW_ALIGN
    return max(unit, ((nrows + unit - 1) // unit) * unit)


def _put(host: np.ndarray, sharding) -> jax.Array:
    """Host→device under the given sharding. Multi-process: the sharding may
    span devices this process cannot address — materialize only the local
    shards from the (replicated) host array (every process holds the full
    ingest, the cross-host Frame layout comes from the mesh)."""
    if jax.process_count() > 1:
        return jax.make_array_from_callback(
            host.shape, sharding, lambda idx: host[idx])
    return jax.device_put(host, sharding)


def _upload(host: np.ndarray, nrows: int, fill) -> jax.Array:
    plen = padded_len(nrows)
    padded = np.full(plen, fill, dtype=host.dtype)
    padded[:nrows] = host
    return _put(padded, row_sharding(1))


def upload_columns(hosts: list[np.ndarray], nrows: int, fill, dtype) -> list[jax.Array]:
    """Upload many same-length columns as ONE [ncols, plen] transfer, then
    slice rows on device. Per-column ``device_put`` over a tunneled TPU costs
    a full round-trip each (~seconds for a wide frame); one batched transfer
    amortizes it. The matrix is sharded (replicated, rows) so each row slice
    comes out row-sharded exactly like a per-column upload."""
    if not hosts:
        return []
    from jax.sharding import NamedSharding, PartitionSpec as P

    from h2o3_tpu.parallel.mesh import ROWS, get_mesh
    plen = padded_len(nrows)
    mat = np.full((len(hosts), plen), fill, dtype=dtype)
    for i, h in enumerate(hosts):
        mat[i, :nrows] = h
    dev = _put(mat, NamedSharding(get_mesh(), P(None, ROWS)))
    return [dev[i] for i in range(len(hosts))]


class Vec:
    """One named, typed, distributed column of a Frame."""

    def __init__(
        self,
        data: jax.Array | None,
        type: VecType,
        nrows: int,
        domain: tuple[str, ...] | None = None,
        host_values: np.ndarray | None = None,
        time_offset: float = 0.0,
        compressed=None,
    ):
        self._data = data                 # padded, row-sharded device array (or None for STR/UUID)
        # compressed host payload (ingest/encode.CompressedChunk): when set,
        # the device array is a DERIVED view — ``data`` materializes it on
        # first access and the Cleaner may drop it again (drop_device)
        self._compressed = compressed
        self.type = type
        self.nrows = nrows
        self.domain = domain              # categorical level names, sorted (parser semantics)
        self.host_values = host_values    # host-only payload (STR/UUID; exact f64 ms for TIME)
        # TIME device data is float32 *relative* ms (value - time_offset): epoch
        # millis (~1.8e12) overflow a float32 mantissa, so absolute times live
        # host-side in float64 and device compute uses the shifted column.
        self.time_offset = time_offset
        self._rollups: Rollups | None = None
        # per-vec histogram cache (filled by api/schemas._histogram_cached;
        # lives here so invalidate_rollups clears BOTH derived summaries)
        self._hist_cache: dict | None = None

    # -- construction -------------------------------------------------------

    @staticmethod
    def from_numpy(values: np.ndarray, type: VecType | None = None,
                   domain: Sequence[str] | None = None) -> "Vec":
        """Build a Vec from a host array, guessing the type if not given."""
        nrows = len(values)
        if type is None:
            type = _guess_type(values)
        if type is VecType.TIME and np.asarray(values).dtype.kind == "M":
            ns = np.asarray(values).astype("datetime64[ns]")
            ms = ns.astype(np.int64).astype(np.float64) / 1e6
            ms = np.where(np.isnat(ns), np.nan, ms)
            offset = float(np.nanmin(ms)) if np.isfinite(ms).any() else 0.0
            data = _upload((ms - offset).astype(np.float32), nrows, np.nan)
            return Vec(data, VecType.TIME, nrows, host_values=ms, time_offset=offset)
        if type in (VecType.STR, VecType.UUID):
            return Vec(None, type, nrows, host_values=np.asarray(values, dtype=object))
        if type is VecType.CAT:
            if domain is None:
                codes, domain = _factorize(values)
            else:
                codes = np.asarray(values, dtype=np.int32)
            data = _upload(codes.astype(np.int32), nrows, CAT_NA)
            return Vec(data, type, nrows, domain=tuple(domain))
        host = np.asarray(values, dtype=np.float32)
        data = _upload(host, nrows, np.nan)
        return Vec(data, type, nrows)

    @staticmethod
    def from_device(data: jax.Array, nrows: int, type: VecType = VecType.NUM,
                    domain: tuple[str, ...] | None = None) -> "Vec":
        """Wrap an existing padded, row-sharded device array."""
        return Vec(data, type, nrows, domain=domain)

    @staticmethod
    def from_compressed(chunk, type: VecType, nrows: int,
                        domain: tuple[str, ...] | None = None) -> "Vec":
        """Wrap a compressed host payload (ingest/encode.CompressedChunk);
        the device array materializes lazily on first ``data`` access."""
        return Vec(None, type, nrows, domain=domain, compressed=chunk)

    # -- properties ---------------------------------------------------------

    @property
    def data(self) -> jax.Array | None:
        """The padded, row-sharded device column. For compressed vecs this
        is a DERIVED view: first access decodes the host payload and
        uploads (``Chunk.atd`` decompress-on-access, amortized per column);
        :meth:`drop_device` releases it again."""
        arr = self._data    # local: a concurrent Cleaner drop_device between
        # materialization and return must not turn this access into None
        if arr is None and self._compressed is not None:
            from h2o3_tpu.utils import telemetry as _tm2
            decoded = self._compressed.decode()
            fill = CAT_NA if self.type is VecType.CAT else np.nan
            arr = _upload(decoded, self.nrows, fill)
            self._data = arr
            _tm2.CHUNK_DECOMPRESS.inc()
            _tm2.CHUNK_DECOMPRESS_BYTES.inc(int(decoded.nbytes))
        return arr

    @data.setter
    def data(self, value) -> None:
        self._data = value

    @property
    def compressed(self):
        """The compressed host payload, if this Vec carries one."""
        return self._compressed

    @property
    def device_resident(self) -> bool:
        """True when a device array is materialized RIGHT NOW — the
        accounting view (never triggers decompress, unlike ``data``)."""
        return self._data is not None

    def drop_device(self) -> int:
        """Release the derived device array of a compressed Vec (the
        Cleaner's cheapest eviction: the host payload rebuilds it on next
        access). Returns the freed device bytes; 0 when there is nothing
        safely droppable."""
        if self._compressed is None or self._data is None:
            return 0
        freed = int(self._data.nbytes)
        self._data = None
        return freed

    @property
    def plen(self) -> int:
        return self._data.shape[0] if self._data is not None \
            else padded_len(self.nrows)

    @property
    def nbytes(self) -> int:
        """Resident bytes of this column: the padded device chunk plus any
        host-side payload (reference: summed ``Chunk`` byte sizes — the
        per-key accounting ``utils/memory.py`` registers with the DKV)."""
        from h2o3_tpu.utils.memory import vec_nbytes
        return vec_nbytes(self)

    @property
    def is_categorical(self) -> bool:
        return self.type is VecType.CAT

    @property
    def is_numeric(self) -> bool:
        return self.type.is_numeric

    def cardinality(self) -> int:
        """Number of categorical levels (reference: ``Vec.cardinality()``)."""
        return len(self.domain) if self.domain is not None else -1

    # -- rollups (lazy, cached; reference RollupStats semantics) ------------

    def rollups(self) -> Rollups:
        if self._rollups is None:
            if self.type is VecType.CAT:
                self._rollups = cat_rollups(self.data, self.nrows)
            elif self.type.on_device:
                self._rollups = numeric_rollups(self.data, self.nrows)
            else:
                na = int(sum(v is None for v in self.host_values))
                self._rollups = Rollups(self.nrows, na, float("nan"), float("nan"),
                                        float("nan"), float("nan"), 0, False, 0, 0)
        return self._rollups

    def invalidate_rollups(self) -> None:
        """Call after mutating ``data`` (reference: rollup epoch bump)."""
        self._rollups = None
        self._hist_cache = None

    def min(self) -> float: return self.rollups().min
    def max(self) -> float: return self.rollups().max
    def mean(self) -> float: return self.rollups().mean
    def sigma(self) -> float: return self.rollups().sigma
    def na_cnt(self) -> int: return self.rollups().na_cnt
    def is_int(self) -> bool: return self.rollups().is_int

    # -- access -------------------------------------------------------------

    def to_numpy(self) -> np.ndarray:
        """Gather the logical (unpadded) column to host (TIME: exact f64 ms)."""
        if not self.type.on_device:
            return self.host_values
        if self.type is VecType.TIME and self.host_values is not None:
            return self.host_values[: self.nrows]
        if self._data is None and self._compressed is not None:
            # host read of an unmaterialized compressed column: decode
            # directly — no reason to round-trip through the device. COPY:
            # the identity codec decodes to the payload itself, and the
            # eager path's fetch() always returns a fresh array callers
            # may mutate — never alias the host source of truth
            return self._compressed.decode()[: self.nrows].copy()
        from h2o3_tpu.parallel.distributed import fetch
        return fetch(self.data)[: self.nrows]

    def labels(self) -> np.ndarray:
        """Categorical column as its level strings (NA → None); the view the
        h2o-py client renders for CAT columns (``as_data_frame``)."""
        if not self.is_categorical:
            raise ValueError("labels() requires a categorical Vec")
        codes = self.to_numpy()
        dom = np.array(self.domain, dtype=object)
        out = np.full(len(codes), None, dtype=object)
        ok = codes >= 0
        out[ok] = dom[codes[ok]]
        return out

    def as_float(self) -> jax.Array:
        """Device column as float32 with NaN for missing (cats → code floats)."""
        if self.type is VecType.CAT:
            return jnp.where(self.data < 0, jnp.nan, self.data.astype(jnp.float32))
        return self.data

    def __len__(self) -> int:
        return self.nrows

    def __repr__(self) -> str:
        dom = f", card={self.cardinality()}" if self.is_categorical else ""
        return f"Vec({self.type}, nrows={self.nrows}{dom})"

    # -- elementwise operators (reference: water/rapids/ast/prims/operators/) --
    #
    # Results are NUM Vecs; NA propagates through NaN arithmetic for free
    # (padding is NaN too, so padded slots stay invalid). Comparisons yield
    # 0.0/1.0 with NaN for NA operands, matching the reference's binary ops.

    def _operand(self, other):
        if isinstance(other, Vec):
            if other.nrows != self.nrows:
                raise ValueError("Vec length mismatch")
            o = other.as_float()
            # TIME device data is offset-relative (see __init__); align the
            # operand into THIS column's frame so differences/compares are
            # exact regardless of each column's own offset
            if self.type is VecType.TIME or other.type is VecType.TIME:
                o = o + (other.time_offset - self.time_offset)
            return o
        if isinstance(other, str):
            if not self.is_categorical:
                raise TypeError("string comparand requires a categorical Vec")
            try:
                return float(self.domain.index(other))
            except ValueError:
                return float("nan")   # unknown level: matches nothing
        o = float(other)
        if self.type is VecType.TIME:
            o = o - self.time_offset   # scalars are absolute epoch ms
        return o

    def _time_pair_host(self, other):
        """Both-TIME operand pair as exact float64 host ms, or None. A 25-year
        offset difference overflows the f32 relative representation, so
        TIME⋅TIME arithmetic runs on the exact host payload."""
        if (isinstance(other, Vec) and self.type is VecType.TIME
                and other.type is VecType.TIME
                and self.host_values is not None
                and other.host_values is not None):
            return (self.host_values[: self.nrows].astype(np.float64),
                    other.host_values[: other.nrows].astype(np.float64))
        return None

    def _ew(self, other, fn, swap: bool = False):
        pair = self._time_pair_host(other)
        if pair is not None:
            a, o = pair
            # numpy twin of the jnp ufunc: jnp would downcast the exact f64
            # epoch values to f32 (x64 is disabled)
            fn = getattr(np, getattr(fn, "__name__", ""), fn)
            out = np.asarray(fn(o, a) if swap else fn(a, o), np.float32)
            return Vec.from_numpy(out, type=VecType.NUM)
        o = self._operand(other)
        a = self.as_float()
        out = fn(o, a) if swap else fn(a, o)
        return Vec(out.astype(jnp.float32), VecType.NUM, self.nrows)

    def __add__(self, o): return self._ew(o, jnp.add)
    def __radd__(self, o): return self._ew(o, jnp.add)
    def __sub__(self, o): return self._ew(o, jnp.subtract)
    def __rsub__(self, o): return self._ew(o, jnp.subtract, swap=True)
    def __mul__(self, o): return self._ew(o, jnp.multiply)
    def __rmul__(self, o): return self._ew(o, jnp.multiply)
    def __truediv__(self, o): return self._ew(o, jnp.divide)
    def __rtruediv__(self, o): return self._ew(o, jnp.divide, swap=True)
    def __pow__(self, o): return self._ew(o, jnp.power)
    def __rpow__(self, o): return self._ew(o, jnp.power, swap=True)
    def __mod__(self, o): return self._ew(o, jnp.mod)
    def __rmod__(self, o): return self._ew(o, jnp.mod, swap=True)
    def __floordiv__(self, o): return self._ew(o, jnp.floor_divide)
    def __rfloordiv__(self, o): return self._ew(o, jnp.floor_divide, swap=True)
    def __neg__(self): return self._ew(-1.0, jnp.multiply)

    def _cmp(self, other, fn):
        pair = self._time_pair_host(other)
        if pair is not None:
            a, o = pair
            fn = getattr(np, getattr(fn, "__name__", ""), fn)   # keep f64 exact
            out = np.where(np.isnan(a) | np.isnan(o), np.nan,
                           np.asarray(fn(a, o), np.float32))
            return Vec.from_numpy(out.astype(np.float32), type=VecType.NUM)
        o = self._operand(other)
        a = self.as_float()
        valid = ~jnp.isnan(a)
        if isinstance(o, jax.Array):
            valid = valid & ~jnp.isnan(o)
        out = jnp.where(valid, fn(a, o).astype(jnp.float32), jnp.nan)
        return Vec(out, VecType.NUM, self.nrows)

    def __lt__(self, o): return self._cmp(o, jnp.less)
    def __le__(self, o): return self._cmp(o, jnp.less_equal)
    def __gt__(self, o): return self._cmp(o, jnp.greater)
    def __ge__(self, o): return self._cmp(o, jnp.greater_equal)
    def __eq__(self, o): return self._cmp(o, lambda a, b: a == b)
    def __ne__(self, o): return self._cmp(o, lambda a, b: a != b)
    __hash__ = object.__hash__   # __eq__ returns a Vec, not a bool

    def __and__(self, o): return self._cmp(o, lambda a, b: (a != 0) & (b != 0))
    def __or__(self, o): return self._cmp(o, lambda a, b: (a != 0) | (b != 0))
    def __invert__(self): return self._cmp(0.0, lambda a, b: a == b)

    def isna(self) -> "Vec":
        """1.0 where the value is missing (works on padded slots too — they
        read as NA but are excluded by the frame row mask downstream)."""
        return Vec(jnp.isnan(self.as_float()).astype(jnp.float32),
                   VecType.NUM, self.nrows)


def _guess_type(values: np.ndarray) -> VecType:
    values = np.asarray(values)
    if values.dtype.kind in "fc":
        finite = values[np.isfinite(values)]
        return VecType.INT if finite.size and np.all(finite == np.round(finite)) else VecType.NUM
    if values.dtype.kind in "iu":
        return VecType.INT
    if values.dtype.kind == "b":
        return VecType.INT
    if values.dtype.kind == "M":
        return VecType.TIME
    return VecType.CAT


def _factorize(values: np.ndarray) -> tuple[np.ndarray, list[str]]:
    """Categorical encoding with a lexicographically sorted domain.

    Reference: the parser sorts categorical domains (``water/parser`` packed
    domain merge), so codes are stable across chunk orderings.
    """
    arr = np.asarray(values, dtype=object)
    mask = np.array([v is None or (isinstance(v, (float, np.floating)) and np.isnan(v)) for v in arr],
                    dtype=bool)
    strs = np.array([str(v) for v in arr[~mask]])
    domain = sorted(set(strs.tolist()))
    lut = {s: i for i, s in enumerate(domain)}
    codes = np.full(len(arr), CAT_NA, dtype=np.int32)
    codes[~mask] = np.array([lut[s] for s in strs], dtype=np.int32)
    return codes, domain
