"""Avro + Excel ingestion — the reference's binary-parser extensions.

Reference: ``h2o-parsers/h2o-avro-parser/`` (Avro object-container files →
frames; primitive types + nullable unions, ``AvroParser.java``) and
``water/parser/XlsParser.java`` (Excel). This image vendors no avro/xlsx
library, so both are implemented directly:

- Avro: a compact object-container reader — JSON schema, zigzag varints,
  null/deflate codecs, records of primitives with ``["null", T]`` unions
  (the shapes tabular Avro actually uses). Complex nests raise clearly.
- Excel: ``.xlsx`` (OOXML = zip of XML) via zipfile + ElementTree — shared
  strings, inline strings, numbers, header row. Legacy BIFF ``.xls`` files
  are rejected with guidance (the reference's XlsParser covers BIFF; OOXML
  is what current Excel writes).
"""

from __future__ import annotations

import json
import struct
import zlib

import numpy as np

#: RFC 1952 gzip member header magic
_GZIP_MAGIC = b"\x1f\x8b"


def is_gzipped(path: str) -> bool:
    """Magic-byte gzip sniff (reference: the parser's ``ZipUtil`` codec
    detection reads bytes, never trusts extensions) — the streaming ingest
    router and pipeline share this so a gzipped file without a ``.gz``
    suffix still decompresses incrementally."""
    try:
        with open(path, "rb") as fh:
            return fh.read(2) == _GZIP_MAGIC
    except OSError:
        return False


# ---------------------------------------------------------------------------
# Avro object container

_MAGIC = b"Obj\x01"


class _Reader:
    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def read(self, n: int) -> bytes:
        out = self.buf[self.pos:self.pos + n]
        self.pos += n
        return out

    def long(self) -> int:
        shift, acc = 0, 0
        while True:
            b = self.buf[self.pos]
            self.pos += 1
            acc |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        return (acc >> 1) ^ -(acc & 1)       # zigzag

    def bytes_(self) -> bytes:
        return self.read(self.long())

    def string(self) -> str:
        return self.bytes_().decode("utf-8")

    def value(self, schema):
        if isinstance(schema, str):
            t = schema
        elif isinstance(schema, dict):
            t = schema["type"]
        else:                                 # union
            idx = self.long()
            return self.value(schema[idx])
        if t == "null":
            return None
        if t == "boolean":
            return self.read(1) == b"\x01"
        if t in ("int", "long"):
            return self.long()
        if t == "float":
            return struct.unpack("<f", self.read(4))[0]
        if t == "double":
            return struct.unpack("<d", self.read(8))[0]
        if t == "string":
            return self.string()
        if t == "bytes":
            return self.bytes_()
        if t == "enum":
            return schema["symbols"][self.long()]
        if t == "record":
            return {f["name"]: self.value(f["type"])
                    for f in schema["fields"]}
        raise ValueError(f"unsupported Avro type {t!r} (tabular subset only)")


def read_avro(path: str) -> tuple[list[str], list[dict]]:
    """(column names, row dicts) from an Avro object-container file."""
    with open(path, "rb") as f:
        buf = f.read()
    r = _Reader(buf)
    if r.read(4) != _MAGIC:
        raise ValueError(f"{path!r} is not an Avro object-container file")
    meta = {}
    while True:
        n = r.long()
        if n == 0:
            break
        if n < 0:       # block with byte size
            r.long()
            n = -n
        for _ in range(n):
            k = r.string()
            meta[k] = r.bytes_()
    r.read(16)          # sync marker
    schema = json.loads(meta["avro.schema"].decode())
    codec = meta.get("avro.codec", b"null").decode()
    if schema.get("type") != "record":
        raise ValueError("Avro ingestion expects a record schema")
    names = [f["name"] for f in schema["fields"]]

    rows: list[dict] = []
    while r.pos < len(r.buf):
        count = r.long()
        size = r.long()
        block = r.read(size)
        if codec == "deflate":
            block = zlib.decompress(block, -15)
        elif codec != "null":
            raise ValueError(f"unsupported Avro codec {codec!r}")
        br = _Reader(block)
        for _ in range(count):
            rows.append(br.value(schema))
        r.read(16)      # sync
    return names, rows


def parse_avro(path: str, key: str | None = None):
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.utils.registry import DKV
    names, rows = read_avro(path)
    cols: dict[str, np.ndarray] = {}
    for n in names:
        vals = [row.get(n) for row in rows]
        if all(v is None or isinstance(v, (int, float, bool)) for v in vals):
            cols[n] = np.array([np.nan if v is None else float(v)
                                for v in vals], np.float32)
        else:
            cols[n] = np.array([None if v is None else str(v) for v in vals],
                               dtype=object)
    fr = Frame.from_arrays(cols, key=key)
    if fr.key:
        DKV.put(fr.key, fr)
    return fr


# ---------------------------------------------------------------------------
# Excel (.xlsx)

def _col_to_idx(ref: str) -> int:
    """'BC12' → zero-based column index of 'BC'."""
    idx = 0
    for ch in ref:
        if ch.isalpha():
            idx = idx * 26 + (ord(ch.upper()) - 64)
        else:
            break
    return idx - 1


def read_xlsx(path: str, sheet: int = 0) -> list[list]:
    """Cell grid of one worksheet (numbers as float, text as str)."""
    import xml.etree.ElementTree as ET
    import zipfile

    ns = "{http://schemas.openxmlformats.org/spreadsheetml/2006/main}"
    with zipfile.ZipFile(path) as z:
        sheets = sorted(n for n in z.namelist()
                        if n.startswith("xl/worksheets/sheet"))
        if not sheets:
            raise ValueError(f"{path!r} has no worksheets")
        shared: list[str] = []
        if "xl/sharedStrings.xml" in z.namelist():
            root = ET.fromstring(z.read("xl/sharedStrings.xml"))
            for si in root.iter(f"{ns}si"):
                shared.append("".join(t.text or "" for t in si.iter(f"{ns}t")))
        root = ET.fromstring(z.read(sheets[sheet]))
        grid: list[list] = []
        for row in root.iter(f"{ns}row"):
            cells: dict[int, object] = {}
            for c in row.iter(f"{ns}c"):
                ref = c.get("r", "A1")
                j = _col_to_idx(ref)
                ctype = c.get("t", "n")
                vel = c.find(f"{ns}v")
                if ctype == "inlineStr":
                    cells[j] = "".join(t.text or ""
                                       for t in c.iter(f"{ns}t"))
                elif vel is None:
                    continue
                elif ctype == "s":
                    cells[j] = shared[int(vel.text)]
                elif ctype == "b":
                    cells[j] = float(vel.text)
                elif ctype == "str":
                    cells[j] = vel.text
                else:
                    try:
                        cells[j] = float(vel.text)
                    except (TypeError, ValueError):
                        cells[j] = vel.text
            width = max(cells) + 1 if cells else 0
            grid.append([cells.get(j) for j in range(width)])
    width = max((len(r) for r in grid), default=0)
    return [r + [None] * (width - len(r)) for r in grid]


def parse_xlsx(path: str, key: str | None = None):
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.utils.registry import DKV
    if path.lower().endswith(".xls"):
        raise ValueError(
            "legacy BIFF .xls is not supported — save as .xlsx (OOXML); "
            "the reference's XlsParser covers the pre-2007 format only")
    grid = read_xlsx(path)
    if not grid:
        raise ValueError(f"{path!r} is empty")
    header = [str(h) if h is not None else f"C{j + 1}"
              for j, h in enumerate(grid[0])]
    body = grid[1:]
    cols: dict[str, np.ndarray] = {}
    for j, name in enumerate(header):
        vals = [row[j] if j < len(row) else None for row in body]
        if all(v is None or isinstance(v, float) for v in vals):
            cols[name] = np.array([np.nan if v is None else v for v in vals],
                                  np.float32)
        else:
            cols[name] = np.array([None if v is None else str(v)
                                   for v in vals], dtype=object)
    fr = Frame.from_arrays(cols, key=key)
    if fr.key:
        DKV.put(fr.key, fr)
    return fr
