"""Ingest — file parsing to distributed Frames.

Reference: the 2-phase distributed parse (``water/parser/ParseDataset.java:623``:
type/header guessing via ``ParseSetup``, then an MRTask over raw file chunks with
per-chunk CSV state machines and categorical domain merging). On TPU the parse
itself is host-side work: we delegate tokenization/type-guessing to
pandas/pyarrow C++ readers (the moral equivalent of the reference's vendored
parser codecs), then upload columns as row-sharded device arrays. The
"distributed" part — laying rows out across chips — happens at upload via
``NamedSharding``, replacing the reference's CHK-key home-node writes
(``water/TaskPutKey.java``).

Formats: CSV (+gzip/zip via pandas), Parquet/ORC/Avro-ish via pyarrow, SVMLight.
"""

from __future__ import annotations

import io
import os

import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.utils import telemetry as _tm
from h2o3_tpu.utils.registry import DKV


def _note_parse(frame, path: str | None = None, nbytes: int | None = None):
    """Record parse throughput (rows/bytes/chunks) for a finished parse;
    returns the frame so terminal sites can ``return _note_parse(...)``."""
    if nbytes is None:
        nbytes = 0
        if path and "://" not in path:
            try:
                nbytes = os.path.getsize(path)
            except OSError:
                pass
    _tm.PARSE_ROWS.inc(getattr(frame, "nrows", 0) or 0)
    _tm.PARSE_BYTES.inc(nbytes or 0)
    _tm.PARSE_CHUNKS.inc(len(getattr(frame, "vecs", None) or ()))
    return frame


#: extensions the streaming pipeline can parse (CSV-shaped text, plus gzip)
_STREAMABLE_EXTS = ("csv", "txt", "data", "gz")


def _stream_mode(path: str, ext: str) -> bool:
    """Should this import ride the streaming chunked pipeline
    (``ingest/pipeline.py``)? Gated by ``H2O3TPU_INGEST_STREAMING``:
    ``0``/unset = never (the eager path is the parity-proven default),
    ``1`` = every streamable file, ``auto`` = gzip-compressed files and
    files over the ``H2O3TPU_INGEST_STREAM_MIN_BYTES`` floor (64MB) —
    the ones whose eager parse would materialize O(file) host columns."""
    mode = os.environ.get("H2O3TPU_INGEST_STREAMING", "0").strip().lower()
    if mode in ("", "0", "off", "false") or ext not in _STREAMABLE_EXTS:
        return False
    if mode in ("1", "on", "true", "force"):
        return True
    if mode != "auto":
        return False
    from h2o3_tpu.frame.binfmt import is_gzipped
    if ext == "gz" or is_gzipped(path):
        return True
    floor = int(os.environ.get("H2O3TPU_INGEST_STREAM_MIN_BYTES",
                               str(64 << 20)))
    try:
        return os.path.getsize(path) >= floor
    except OSError:
        return False


def _check_readable(path: str) -> None:
    """Surface bad paths as the structured errors the REST layer maps to a
    400 (reference: ImportFiles ``fails`` entries) — never a 500 traceback
    from deep inside a reader."""
    if not os.path.exists(path):
        raise FileNotFoundError(f"import_file: no such file or directory: "
                                f"{path!r}")
    if os.path.isdir(path):
        raise IsADirectoryError(f"import_file: {path!r} is a directory, "
                                "not a data file")
    if not os.access(path, os.R_OK):
        raise PermissionError(f"import_file: {path!r} is not readable")


def import_file(path: str, key: str | None = None, header: int | None = 0,
                col_types: dict | None = None, na_strings: list[str] | None = None,
                sep: str | None = None) -> Frame:
    """Parse a file into a Frame (reference: ``h2o.import_file`` → ``POST /3/Parse``)."""
    import pandas as pd

    # URI routing (reference: water/persist/PersistManager scheme dispatch)
    if "://" in path:
        scheme = path.split("://", 1)[0].lower()
        if scheme in ("s3", "s3a", "s3n", "gs", "gcs", "hdfs"):
            # cloud persist backends (stdlib-HTTP S3 SigV4 / GCS JSON /
            # WebHDFS — persist/cloud.py); fetch then parse as local
            from h2o3_tpu.persist.cloud import MANAGER
            tmp = MANAGER.fetch_to_temp(path)
            try:
                return import_file(tmp, key=key or _key_from_path(path),
                                   header=header, col_types=col_types,
                                   na_strings=na_strings, sep=sep)
            finally:
                os.unlink(tmp)
        if scheme not in ("http", "https", "file"):
            raise ValueError(f"unknown URI scheme {scheme!r}")
        if scheme == "file":
            path = path.split("://", 1)[1]

    _check_readable(path)
    ext = os.path.splitext(path)[1].lower().lstrip(".")
    if _stream_mode(path, ext):
        # streaming chunked parse: overlapped read→decompress→tokenize→
        # device stages, compressed host columns, O(chunk) peak transient
        # memory, and a real Job with row/byte progress (docs/INGEST.md)
        from h2o3_tpu.ingest.pipeline import stream_import
        from h2o3_tpu.models.job import Job
        job = Job(f"Parse {os.path.basename(path)}")

        def _run(j):
            return stream_import(path, key=key or _key_from_path(path),
                                 header=header, col_types=col_types,
                                 na_strings=na_strings, sep=sep, job=j)
        job.run(_run, background=False)
        if job.exception is not None:
            raise job.exception
        if job.result is None:
            # cancelled mid-parse (Job swallows JobCancelled into status):
            # surface a structured client error, never return None into
            # handlers that dereference .key (→ 500)
            raise ValueError(f"parse of {path!r} was cancelled "
                             f"(job {job.key})")
        return _note_parse(job.result, path)
    if ext in ("parquet", "pq"):
        df = pd.read_parquet(path)
    elif ext == "orc":
        import pyarrow.orc as orc
        df = orc.ORCFile(path).read().to_pandas()
    elif ext == "svmlight" or ext == "svm":
        return _note_parse(_parse_svmlight(path, key), path)
    elif ext == "arff":
        return _note_parse(_parse_arff(path, key), path)
    elif ext == "avro":
        from h2o3_tpu.frame.binfmt import parse_avro
        return _note_parse(parse_avro(path, key or _key_from_path(path)), path)
    elif ext in ("xlsx", "xls"):
        from h2o3_tpu.frame.binfmt import parse_xlsx
        return _note_parse(parse_xlsx(path, key or _key_from_path(path)), path)
    else:
        if ext in ("csv", "txt", "data") and na_strings is None and header == 0 \
                and (sep is None or len(sep) == 1):
            frame = _parse_csv_native(path, sep or ",", key)
            if frame is not None:
                DKV.put(frame.key, frame)
                return _note_parse(frame, path)
        kw = dict(header=header, na_values=na_strings, compression="infer")
        if sep is not None:
            kw["sep"] = sep
        df = pd.read_csv(path, engine="c", **kw)
    frame = Frame.from_pandas(df, key=key or _key_from_path(path))
    DKV.put(frame.key, frame)
    return _note_parse(frame, path)


def _parse_csv_native(path: str, sep: str, key: str | None) -> Frame | None:
    """Fast path: the chunk-parallel C++ tokenizer (reference:
    ``MultiFileParseTask`` + ``CsvParser``); None → caller falls back to
    pandas."""
    from h2o3_tpu.frame.types import VecType
    from h2o3_tpu.frame.vec import Vec
    from h2o3_tpu.native import parse_csv_native

    try:
        with open(path, "rb") as fh:
            data = fh.read()
        out = parse_csv_native(data, has_header=True, sep=sep)
    except Exception:
        return None
    if out is None:
        return None
    names, cols = out
    vecs = []
    for col in cols:
        if col[0] == "num":
            vecs.append(Vec.from_numpy(col[1].astype(np.float32)))
        else:
            vecs.append(Vec.from_numpy(col[1], type=VecType.CAT, domain=col[2]))
    return Frame(names, vecs, key=key or _key_from_path(path))


def upload_file(path: str, key: str | None = None, **kw) -> Frame:
    """Alias of import_file — no client/server split here, one process owns ingest."""
    return import_file(path, key=key, **kw)


class RawFile:
    """Unparsed uploaded bytes (reference: ``water/fvec/UploadFileVec`` — the
    raw key ``POST /3/PostFile`` creates, later consumed by ParseSetup/Parse).
    Parsing is lazy and cached: ParseSetup triggers it for the type guess and
    Parse re-keys the same Frame."""

    nrows = 0
    ncols = 0

    def __init__(self, data: bytes, name: str = "upload"):
        self.data = data
        self.name = name
        self._frame: Frame | None = None

    def frame(self) -> Frame:
        if self._frame is None:
            import tempfile
            import uuid
            suffix = os.path.splitext(self.name)[1] or ".csv"
            fd, tmp = tempfile.mkstemp(suffix=suffix)
            # a transient unique key: import_file registers its result, and
            # parsing under the upload's basename would clobber any existing
            # frame a user keyed by that name; only Parse's destination key
            # should ever be visible
            tkey = f"_upload_{uuid.uuid4().hex[:12]}"
            try:
                with os.fdopen(fd, "wb") as f:
                    f.write(self.data)
                self._frame = import_file(tmp, key=tkey)
            finally:
                os.unlink(tmp)
            if tkey in DKV:
                DKV.remove(tkey)
        return self._frame


def parse_raw(text: str, key: str | None = None, **kw) -> Frame:
    """Parse CSV text from memory (test fixture convenience)."""
    import pandas as pd
    df = pd.read_csv(io.StringIO(text), **kw)
    frame = Frame.from_pandas(df, key=key)
    if key:
        DKV.put(key, frame)
    return _note_parse(frame, nbytes=len(text))


def _parse_arff(path: str, key: str | None) -> Frame:
    """ARFF (reference: ``water/parser/ARFFParser.java``): @attribute header
    declares name + type (numeric / {nominal,...} / string / date), @data is
    CSV. Declared nominals become categorical domains even when unobserved."""
    import io

    import pandas as pd

    names: list[str] = []
    kinds: list[tuple[str, tuple[str, ...] | None]] = []
    data_lines: list[str] = []
    in_data = False
    with open(path) as f:
        for line in f:
            s = line.strip()
            if not s or s.startswith("%"):
                continue
            low = s.lower()
            if in_data:
                data_lines.append(s)
            elif low.startswith("@attribute"):
                rest = s.split(None, 2)[1:]
                name = rest[0].strip("'\"")
                typ = rest[1] if len(rest) > 1 else "numeric"
                if typ.startswith("{"):
                    dom = tuple(v.strip().strip("'\"")
                                for v in typ.strip("{}").split(","))
                    kinds.append(("nominal", dom))
                elif typ.lower() in ("numeric", "real", "integer"):
                    kinds.append(("numeric", None))
                else:
                    kinds.append(("string", None))
                names.append(name)
            elif low.startswith("@data"):
                in_data = True
    df = pd.read_csv(io.StringIO("\n".join(data_lines)), header=None,
                     names=names, na_values=["?"], skipinitialspace=True)
    from h2o3_tpu.frame.types import VecType
    from h2o3_tpu.frame.vec import Vec
    vecs = []
    for name, (kind, dom) in zip(names, kinds):
        col = df[name]
        if kind == "nominal":
            vals = col.astype("object")
            lut = {lvl: i for i, lvl in enumerate(dom)}
            codes = np.array([lut.get(str(v).strip("'\""), -1)
                              if not pd.isna(v) else -1 for v in vals], np.int32)
            vecs.append(Vec.from_numpy(codes, VecType.CAT, domain=dom))
        elif kind == "numeric":
            vecs.append(Vec.from_numpy(col.to_numpy(np.float32)))
        else:
            vecs.append(Vec.from_numpy(col.astype(str).to_numpy(), VecType.STR))
    frame = Frame(names, vecs, key=key or _key_from_path(path))
    DKV.put(frame.key, frame)
    return frame


#: widths beyond this stay sparse end-to-end (densifying a 10k-wide text
#: one-hot would not fit HBM — reference keeps CXI chunks sparse throughout)
_SVMLIGHT_DENSE_MAX_COLS = 1000


def _parse_svmlight(path: str, key: str | None):
    """SVMLight sparse format (reference: ``water/parser/SVMLightParser.java``).

    Narrow files densify at ingest (TPU compute is dense-friendly and every
    munger applies); wide files return a :class:`SparseFrame` (COO in HBM +
    matrix-free models — SURVEY.md §7 hard part (c))."""
    from sklearn.datasets import load_svmlight_file
    X, y = load_svmlight_file(path)
    if X.shape[1] > _SVMLIGHT_DENSE_MAX_COLS:
        from h2o3_tpu.frame.sparse import parse_svmlight_sparse
        return parse_svmlight_sparse(path, key=key or _key_from_path(path))
    X = np.asarray(X.todense(), dtype=np.float32)
    cols = {"C0": y.astype(np.float32)}
    for j in range(X.shape[1]):
        cols[f"C{j + 1}"] = X[:, j]
    frame = Frame.from_arrays(cols, key=key or _key_from_path(path))
    DKV.put(frame.key, frame)
    return frame


def import_svmlight(path: str, key: str | None = None, sparse: bool = True):
    """Explicit SVMLight entry: ``sparse=True`` always yields a SparseFrame."""
    if sparse:
        from h2o3_tpu.frame.sparse import parse_svmlight_sparse
        return parse_svmlight_sparse(path, key=key or _key_from_path(path))
    return _parse_svmlight(path, key)


def _key_from_path(path: str) -> str:
    base = os.path.basename(path)
    return base.replace(".", "_") + ".hex"
