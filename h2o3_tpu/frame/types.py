"""Column types — mirrors the reference Vec type system.

Reference: ``water/fvec/Vec.java:207-212`` defines T_BAD, T_UUID, T_STR, T_NUM,
T_CAT, T_TIME. On TPU, the 20+ chunk compression codecs of the reference
(``water/fvec/NewChunk.java:993-997`` picks the cheapest of ``C0DChunk``,
``C1Chunk``, ``C2SChunk``, ... per ~64KB fragment) collapse into dtype choice:
numeric data is float32 in HBM (NaN = missing, replacing the reference's NA
sentinel scheme), categoricals are int32 codes (-1 = missing) with a host-side
string domain, and the compressed-int bias/scale codecs are unnecessary because
XLA operates on dense typed arrays.
"""

from __future__ import annotations

import enum


class VecType(enum.Enum):
    BAD = "bad"        # all-missing column
    NUM = "real"       # numeric (float32 on device)
    INT = "int"        # integer-valued numeric (still float32 on device)
    CAT = "enum"       # categorical: int32 codes + host domain
    TIME = "time"      # epoch millis (float64 host / float32 device)
    STR = "string"     # host-resident string column (not uploaded)
    UUID = "uuid"      # host-resident uuid column

    @property
    def is_numeric(self) -> bool:
        return self in (VecType.NUM, VecType.INT, VecType.TIME)

    @property
    def on_device(self) -> bool:
        return self in (VecType.NUM, VecType.INT, VecType.TIME, VecType.CAT)

    def __str__(self) -> str:  # matches h2o-py frame "types" display names
        return self.value


# Missing-value sentinel for categorical codes (reference uses per-chunk NA
# codes; a single negative sentinel suffices for int32 codes).
CAT_NA = -1
