"""Sparse frames — CSR/COO storage for wide-sparse data in HBM.

Reference: sparse chunk codecs ``water/fvec/CXIChunk.java``/``CXFChunk.java``
store (row-offset, value) pairs so a 10k-wide one-hot/text frame does not
materialize its zeros; SVMLight ingest (``water/parser/SVMLightParser.java``)
produces them directly.

TPU-native redesign (SURVEY.md §7 hard part (c)): a padded COO triplet
(``data``/``row``/``col``, padded with zero-weight entries to a static nnz)
— every sparse kernel is then a dense gather + ``segment_sum``, the shapes
XLA compiles well. The two products every linear model needs:

    X @ v      = segment_sum(data * v[col], row)         (rows segments)
    X.T @ u    = segment_sum(data * u[row], col)         (cols segments)

ride one segment-sum each; a sparse GLM never forms the dense design.
Dense auxiliary columns (response, weights, offset) stay regular
:class:`Vec` columns.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.vec import Vec


@dataclasses.dataclass
class SparseMatrix:
    """Padded COO on device. Padding entries carry data==0 at (0, 0)."""
    data: jax.Array      # f32 [nnz_pad]
    row: jax.Array       # int32 [nnz_pad]
    col: jax.Array       # int32 [nnz_pad]
    nrows: int
    ncols: int
    nnz: int

    @staticmethod
    def from_scipy_like(rows: np.ndarray, cols: np.ndarray, vals: np.ndarray,
                        nrows: int, ncols: int, pad_to: int | None = None
                        ) -> "SparseMatrix":
        nnz = len(vals)
        pad = pad_to or max(8, ((nnz + 127) // 128) * 128)
        d = np.zeros(pad, np.float32)
        r = np.zeros(pad, np.int32)
        c = np.zeros(pad, np.int32)
        d[:nnz] = vals
        r[:nnz] = rows
        c[:nnz] = cols
        return SparseMatrix(jnp.asarray(d), jnp.asarray(r), jnp.asarray(c),
                            nrows, ncols, nnz)

    def matvec(self, v: jax.Array) -> jax.Array:
        """X @ v → [nrows] (one gather + one segment_sum)."""
        return _matvec(self.data, self.row, self.col, v, self.nrows)

    def rmatvec(self, u: jax.Array) -> jax.Array:
        """X.T @ u → [ncols]."""
        return _matvec(self.data, self.col, self.row, u, self.ncols)

    def col_sq_weighted(self, w_rows: jax.Array) -> jax.Array:
        """Σ_r w_r x_{rj}² per column — the diagonal of X'WX (Jacobi
        preconditioner for the CG solve)."""
        return jax.ops.segment_sum(self.data * self.data * w_rows[self.row],
                                   self.col, num_segments=self.ncols)

    def to_dense(self) -> jax.Array:
        out = jnp.zeros((self.nrows, self.ncols), jnp.float32)
        return out.at[self.row, self.col].add(self.data)


from functools import partial


@partial(jax.jit, static_argnames=("n_out",))
def _matvec(data, seg, idx, v, n_out: int):
    return jax.ops.segment_sum(data * v[idx], seg, num_segments=n_out)


class SparseFrame:
    """A wide-sparse design + dense side columns (response/weights/offset).

    Mirrors just enough of :class:`Frame` for the sparse model paths; the
    full munging surface intentionally stays on dense frames (reference
    sparse chunks are likewise compute-only)."""

    def __init__(self, X: SparseMatrix, dense_cols: dict[str, Vec] | None = None,
                 key: str | None = None):
        self.X = X
        self.dense: dict[str, Vec] = dense_cols or {}
        self.key = key

    @property
    def nrows(self) -> int:
        return self.X.nrows

    @property
    def ncols(self) -> int:
        return self.X.ncols

    def vec(self, name: str) -> Vec:
        return self.dense[name]

    def row_mask(self):
        """All rows are logical (COO carries no shard padding)."""
        return jnp.ones(self.nrows, bool)

    def __contains__(self, name: str) -> bool:
        return name in self.dense

    def density(self) -> float:
        return self.X.nnz / max(self.X.nrows * self.X.ncols, 1)

    def __repr__(self) -> str:
        return (f"SparseFrame({self.nrows} x {self.ncols}, nnz={self.X.nnz}"
                f" [{100 * self.density():.3f}%], dense={list(self.dense)})")


def parse_svmlight_sparse(path: str, key: str | None = None) -> SparseFrame:
    """SVMLight → SparseFrame, sparse END-TO-END (reference: SVMLightParser
    fills CXI chunks; round-1 densified here, which OOMed wide data).

    Parsed by sklearn's C loader (qid annotations, comments, auto one-based
    shift — identical index conventions to the dense route) and converted
    CSR→COO without ever densifying. The response is named ``C0`` like the
    dense SVMLight frame, so the width threshold never changes the schema.
    """
    from sklearn.datasets import load_svmlight_file
    Xs, y = load_svmlight_file(path)
    coo = Xs.tocoo()
    X = SparseMatrix.from_scipy_like(
        coo.row.astype(np.int64), coo.col.astype(np.int64),
        coo.data.astype(np.float64), Xs.shape[0], Xs.shape[1])
    yv = Vec.from_numpy(np.asarray(y, np.float32))
    sf = SparseFrame(X, {"C0": yv}, key=key)
    if key:
        from h2o3_tpu.utils.registry import DKV
        DKV.put(key, sf)
    return sf
