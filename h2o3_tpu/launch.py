"""Multi-process launcher — ``java -jar h2o.jar`` / ``multiNodeUtils.sh`` equivalent.

Reference: a multi-node H2O cluster is N JVMs started with the same cloud
name (``/root/reference/multiNodeUtils.sh:21-26``); each calls
``H2O.main`` → ``waitForCloudSize``. Here:

    # one process per host, same script everywhere (multi-controller SPMD)
    python -m h2o3_tpu.launch --coordinator host0:7337 \
        --num-processes 2 --process-id $I train.py [script args...]

    # or spawn an N-process cloud on THIS host (the multiNodeUtils.sh mode;
    # CPU devices are split across the processes)
    python -m h2o3_tpu.launch --fork 2 --devices-per-process 4 train.py

Each process joins the cloud via ``jax.distributed.initialize`` (blocking
until all processes connect — the reference's ``waitForCloudSize``), installs
the spanning mesh, then executes the script. All processes must run the same
script: jitted steps are one SPMD program over the global mesh.
"""

from __future__ import annotations

import argparse
import os
import runpy
import subprocess
import sys


def _run_script(script: str, argv: list[str]) -> None:
    sys.argv = [script] + argv
    runpy.run_path(script, run_name="__main__")


def main(args=None) -> int:
    ap = argparse.ArgumentParser(prog="h2o3_tpu.launch", description=__doc__)
    ap.add_argument("--coordinator", default=None,
                    help="coordinator address host:port (process 0's host)")
    ap.add_argument("--num-processes", type=int, default=None)
    ap.add_argument("--process-id", type=int, default=None)
    ap.add_argument("--fork", type=int, default=None, metavar="N",
                    help="spawn an N-process cloud on this host (test mode)")
    ap.add_argument("--devices-per-process", type=int, default=4,
                    help="with --fork: virtual CPU devices per process")
    ap.add_argument("--port", type=int, default=7337,
                    help="with --fork: coordinator port")
    ap.add_argument("--serve", action="store_true",
                    help="serve the REST API after clouding (instead of, or "
                         "in addition to, running a script) — the k8s pod-0 "
                         "/ driver-node mode")
    ap.add_argument("--rest-port", type=int, default=54321)
    ap.add_argument("--ldap-login", default=None, metavar="URL",
                    help="gate the REST API behind an LDAP simple bind "
                         "(ldap://host:port; reference water/H2O.java "
                         "-ldap_login)")
    ap.add_argument("--ldap-user-template", default=None, metavar="DN",
                    help="bind-DN template with one {} for the login name, "
                         "e.g. 'uid={},ou=people,dc=example,dc=org'")
    ap.add_argument("script", nargs="?", default=None)
    ap.add_argument("script_args", nargs=argparse.REMAINDER)
    ns = ap.parse_args(args)
    if not ns.serve and ns.script is None:
        ap.error("a script is required unless --serve is given")

    if ns.fork:
        procs = []
        for pid in range(ns.fork):
            env = dict(os.environ)
            env["JAX_PLATFORMS"] = "cpu"
            flags = " ".join(
                f for f in env.get("XLA_FLAGS", "").split()
                if not f.startswith("--xla_force_host_platform_device_count"))
            env["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count="
                                f"{ns.devices_per_process}").strip()
            cmd = [sys.executable, "-m", "h2o3_tpu.launch",
                   "--coordinator", f"localhost:{ns.port}",
                   "--num-processes", str(ns.fork), "--process-id", str(pid)]
            if ns.serve:
                cmd += ["--serve", "--rest-port", str(ns.rest_port)]
            if ns.script is not None:
                cmd += [ns.script] + ns.script_args
            procs.append(subprocess.Popen(cmd, env=env))
        # reap in any order; one failure tears down the rest (a dead
        # coordinator would leave workers blocked in initialize forever)
        import time
        rc, pending = 0, set(procs)
        while pending:
            for p in list(pending):
                code = p.poll()
                if code is None:
                    continue
                pending.discard(p)
                rc = code or rc
                if code != 0:
                    for q in pending:
                        q.terminate()
            time.sleep(0.05)
        return rc

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # must run BEFORE the first jax backend touch — the environment's
        # sitecustomize force-registers the TPU plugin, and the serve-only
        # path's jax.process_index() would otherwise initialize it even
        # when the operator asked for CPU (and hang on a sick chip)
        import jax
        jax.config.update("jax_platforms", "cpu")
    if ns.coordinator is not None:
        from h2o3_tpu.parallel.distributed import init_distributed
        init_distributed(ns.coordinator, ns.num_processes, ns.process_id)
    # persistent XLA compile cache (H2O3TPU_COMPILE_CACHE=1|path): every
    # process in the cloud shares recompile savings across launches
    from h2o3_tpu.utils import compile_cache
    compile_cache.enable()
    if ns.serve:
        import jax
        from h2o3_tpu.api import H2OServer
        # only the controller process serves (reference: the driver node's
        # REST API); workers just participate in the SPMD cloud
        if getattr(jax, "process_index", lambda: 0)() == 0:
            authenticator = None
            if ns.ldap_login:
                if not ns.ldap_user_template:
                    ap.error("--ldap-login needs --ldap-user-template")
                from h2o3_tpu.api.ldap_auth import ldap_authenticator
                authenticator = ldap_authenticator(ns.ldap_login,
                                                   ns.ldap_user_template)
            server = H2OServer(port=ns.rest_port, host="0.0.0.0",
                               authenticator=authenticator).start()
            print(f"h2o3_tpu REST serving on {server.url}", flush=True)
    if ns.script is not None:
        _run_script(ns.script, ns.script_args)
    if ns.serve:
        # keep serving after the (optional) setup script: the REST server
        # runs on a daemon thread, so returning would tear it down. Workers
        # block as cloud members; REST-driven TRAINING is single-controller
        # (multi-host training uses script mode, where every process runs
        # the same SPMD program).
        import threading
        # graftlint: ok(serve forever — blocking IS this process's job)
        threading.Event().wait()
    return 0


if __name__ == "__main__":
    sys.exit(main())
