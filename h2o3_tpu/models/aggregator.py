"""Aggregator — exemplar-based dataset reduction.

Reference: ``hex/aggregator/Aggregator.java`` — single pass over chunks
collecting exemplars (a row becomes an exemplar when no existing exemplar is
within ``radius_scale``-scaled distance), then per-chunk exemplar sets merge;
output is the exemplar frame with a ``counts`` column.

TPU-native: the sequential per-row scan is hostile to SPMD, so the exemplar
set is selected with the same farthest-point/k-means|| style device sweep
KMeans init uses (distance matrices on the MXU), which preserves the
contract — a reduced frame whose exemplars cover the data within a radius,
with member counts — while staying batched.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.types import VecType
from h2o3_tpu.frame.vec import Vec
from h2o3_tpu.models.data_info import DataInfo
from h2o3_tpu.models.job import Job
from h2o3_tpu.models.model_base import Model, ModelBuilder, make_model_key
from h2o3_tpu.rapids.munge import gather_rows


class AggregatorModel(Model):
    algo = "aggregator"

    def _score_raw(self, frame: Frame):
        raise NotImplementedError("Aggregator produces an output frame; use "
                                  "aggregated_frame")

    def model_performance(self, frame: Frame):
        return None

    @property
    def aggregated_frame(self) -> Frame:
        return self.output["output_frame"]


class Aggregator(ModelBuilder):
    """h2o-py surface: ``H2OAggregatorEstimator``."""

    algo = "aggregator"
    unsupervised = True

    @classmethod
    def defaults(cls) -> dict:
        return dict(
            super().defaults(),
            target_num_exemplars=100,
            rel_tol_num_exemplars=0.5,
            transform="NORMALIZE",
        )

    def _fit(self, job: Job, frame: Frame, x, y, weights) -> AggregatorModel:
        p = self.params
        di = DataInfo.make(frame, x, standardize=p["transform"] != "NONE",
                           use_all_factor_levels=True)
        X = di.expand(frame)
        mask = (weights > 0)
        n = frame.nrows
        target = min(int(p["target_num_exemplars"]), n)

        # farthest-point sweep: greedily add the row farthest from the current
        # exemplar set (batched distance updates; k-means|| flavored)
        key = jax.random.PRNGKey(int(p.get("seed") or 0) or 11)
        # seed from the included (weight>0) rows only
        r = jax.random.uniform(key, (X.shape[0],))
        first = int(jax.device_get(jnp.argmax(jnp.where(mask, r, -1.0))))
        idx = [first]
        d2 = jnp.where(mask, ((X - X[first][None, :]) ** 2).sum(1), -jnp.inf)
        for i in range(1, target):
            nxt = int(jax.device_get(jnp.argmax(d2)))
            if float(jax.device_get(d2[nxt])) <= 0:
                break
            idx.append(nxt)
            d2 = jnp.minimum(d2, jnp.where(mask, ((X - X[nxt][None, :]) ** 2).sum(1),
                                           -jnp.inf))
            if i % 32 == 0:
                job.update(0.8 * i / target, f"{i} exemplars")
        exemplars = np.array(idx, np.int64)

        # assign every row to its nearest exemplar → member counts; the
        # ||x||²+||e||²−2x·e form keeps the [rows,k] distance on the MXU
        # without a [rows,k,dims] broadcast intermediate
        E = X[jnp.asarray(exemplars)]
        d = ((X * X).sum(1, keepdims=True) + (E * E).sum(1)[None, :]
             - 2.0 * X @ E.T)
        assign = jnp.argmin(d, axis=1)
        counts = jax.ops.segment_sum(mask.astype(jnp.float32), assign,
                                     len(exemplars))

        out = gather_rows(frame, exemplars)
        out.add("counts", Vec.from_numpy(
            np.asarray(jax.device_get(counts), np.float64)))
        job.update(1.0, f"{len(exemplars)} exemplars")

        return AggregatorModel(
            key=make_model_key(self.algo, self.model_id),
            params=self.params, data_info=di, response_column=None,
            response_domain=None,
            output=dict(output_frame=out, exemplar_rows=exemplars,
                        exemplar_assignment=assign),
        )
