"""GBM and DRF — gradient boosting and random forest on the shared tree engine.

Reference: ``hex/tree/gbm/GBM.java`` (driver loop ``scoreAndBuildTrees``,
``SharedTree.java:481,519``), ``hex/tree/drf/DRF.java``. GBM grows one tree per
iteration on the gradient of the loss at the current prediction; DRF grows
independent trees on bootstrap resamples with per-tree feature subsampling and
averages. Distribution semantics follow ``hex/DistributionFactory`` (bernoulli
log-odds F, gaussian residuals, poisson log-link).

TPU-native notes: bootstrap resampling is Poisson(1) *weighting* (identical in
expectation, static shapes — no row gather); per-split column sampling of the
reference becomes per-tree feature masks; binning is global-quantile
(XGBoost-hist style) rather than the reference's per-node adaptive histograms
— same family of estimator, better fit for fixed-shape compilation.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.data_info import _remap_codes
from h2o3_tpu.models.job import Job, JobCancelled
from h2o3_tpu.models.model_base import (Model, ModelBuilder, make_model_key,
                                        publish_dispatch_audit)
from h2o3_tpu.utils import telemetry as _tm
from h2o3_tpu.utils.costs import accounted_jit
from h2o3_tpu.utils.timeline import timed_event
from jax import lax

from h2o3_tpu.models.tree import (Tree, _grow_tree_device, fold_binned,
                                  predict_binned, predict_raw)
from h2o3_tpu.ops.quantile import bin_features, compute_bin_edges


def tree_matrix(frame: Frame, cols: list[str], domains: dict[str, tuple]) -> jax.Array:
    """[plen, F] raw feature matrix with train-domain-adapted cat codes."""
    arrs = []
    for c in cols:
        v = frame.vec(c)
        if v.is_categorical and domains.get(c) and v.domain != domains[c]:
            codes = _remap_codes(v.data, v.domain or (), domains[c])
            arrs.append(jnp.where(codes < 0, jnp.nan, codes.astype(jnp.float32)))
        else:
            arrs.append(v.as_float())
    return jnp.stack(arrs, axis=1)


def _weighted_quantile_host(y, w, prob: float) -> float:
    """Weighted quantile of y over rows with w>0 (host-side, init only)."""
    yh = np.asarray(jax.device_get(y), np.float64)
    wh = np.asarray(jax.device_get(w), np.float64)
    ok = wh > 0
    if not ok.any():
        return 0.0
    order = np.argsort(yh[ok])
    ys, ws = yh[ok][order], wh[ok][order]
    cw = np.cumsum(ws)
    idx = int(np.searchsorted(cw, prob * cw[-1]))
    return float(ys[min(idx, len(ys) - 1)])


@partial(jax.jit, static_argnames=("dist", "custom_id"))
def _grad_hess(dist: str, F, y, w, quantile_alpha: float = 0.5,
               huber_alpha: float = 0.9, tweedie_power: float = 1.5,
               custom_id: int = -1):
    """Per-distribution (g, h) pairs (reference: hex/Distribution.java loss
    families; non-smooth losses use the standard GBM pseudo-residual with
    unit hessian, leaf value = weighted mean pseudo-residual)."""
    if dist == "custom":
        # user-uploaded CDistributionFunc (water/udf/CDistributionFunc.java):
        # host callback once per boosting iteration on full columns; the
        # scan stays one compiled program around it
        from h2o3_tpu.utils import udf
        shp = jax.ShapeDtypeStruct(F.shape, jnp.float32)
        return jax.pure_callback(udf.grad_hess_host(custom_id), (shp, shp),
                                 F, y, w)
    if dist == "bernoulli":
        p = jax.nn.sigmoid(F)
        return w * (p - y), w * jnp.maximum(p * (1 - p), 1e-10)
    if dist == "poisson":
        mu = jnp.exp(jnp.clip(F, -30, 30))
        return w * (mu - y), w * mu
    if dist == "gamma":
        # log link; deviance grad: 1 - y*exp(-F)
        ey = y * jnp.exp(jnp.clip(-F, -30, 30))
        return w * (1.0 - ey), w * ey
    if dist == "tweedie":
        p_ = tweedie_power
        e1 = jnp.exp(jnp.clip((1.0 - p_) * F, -30, 30))
        e2 = jnp.exp(jnp.clip((2.0 - p_) * F, -30, 30))
        g = w * (-y * e1 + e2)
        h = w * (-(1.0 - p_) * y * e1 + (2.0 - p_) * e2)
        return g, jnp.maximum(h, 1e-10)
    if dist == "laplace":
        return w * jnp.sign(F - y), w
    if dist == "quantile":
        a = quantile_alpha
        return w * jnp.where(y > F, -a, 1.0 - a), w
    if dist == "huber":
        # reference: delta = huber_alpha quantile of |residual|, refreshed
        # every iteration (DistributionFactory huber). Weighted quantile so
        # zero-weight rows (shard padding, excluded rows) cannot bias delta.
        r = F - y
        ar = jnp.abs(r)
        order = jnp.argsort(ar)
        cw = jnp.cumsum(w[order])
        tgt = huber_alpha * jnp.maximum(cw[-1], 1e-30)
        idx = jnp.clip(jnp.searchsorted(cw, tgt), 0, ar.shape[0] - 1)
        delta = ar[order][idx]
        return w * jnp.clip(r, -delta, delta), w
    return w * (F - y), w  # gaussian


def _linkinv_device(link: str, f):
    """Inverse link on device for custom distributions (reference
    ``LinkFunction*.java`` families; names per CDistributionFunc.link())."""
    if link == "log":
        return jnp.exp(jnp.clip(f, -30, 30))
    if link == "logit":
        return jax.nn.sigmoid(f)
    if link == "inverse":
        return 1.0 / jnp.where(jnp.abs(f) < 1e-30, 1e-30, f)
    return f


def _metric_device(metric: str, dist: str, F, y, w, nclass: int,
                   custom_link: str | None = None):
    """Stopping/score metric as traced device code (less-is-better; AUC is
    negated), so the fused scan can emit one scalar per tree with zero host
    round-trips (reference: ``ScoreKeeper`` scores between driver
    iterations). ``dist="drf_prob"`` means F already IS the prediction
    (probability / mean), the DRF averaging semantics."""
    n = jnp.maximum(w.sum(), 1e-30)
    if nclass > 1:
        prob = F if dist == "drf_prob" else jax.nn.softmax(F, axis=1)
        prob = jnp.clip(prob, 1e-15, 1.0)
        if metric in ("AUTO", "deviance", "logloss"):
            picked = jnp.take_along_axis(
                jnp.log(prob), y.astype(jnp.int32)[:, None], 1)[:, 0]
            return -(w * picked).sum() / n
        if metric in ("MSE", "RMSE"):
            ptrue = jnp.take_along_axis(prob, y.astype(jnp.int32)[:, None],
                                        1)[:, 0]
            mse = (w * (1.0 - ptrue) ** 2).sum() / n
            return jnp.sqrt(mse) if metric == "RMSE" else mse
        if metric == "misclassification":
            pred = jnp.argmax(prob, axis=1).astype(jnp.float32)
            return (w * (pred != y)).sum() / n
        raise ValueError(f"unsupported multinomial stopping_metric {metric!r}")
    if dist == "bernoulli":
        prob = jax.nn.sigmoid(F)
    elif dist == "drf_prob":
        prob = jnp.clip(F, 0.0, 1.0)
    elif dist in ("poisson", "gamma", "tweedie"):
        prob = None
        mu = jnp.exp(jnp.clip(F, -30, 30))
    elif dist == "custom":
        # score in RESPONSE space, not link space (review r3 finding)
        prob = None
        mu = _linkinv_device(custom_link or "identity", F)
    else:
        prob = None
        mu = F
    if metric in ("AUTO", "deviance", "logloss"):
        if prob is not None:         # bernoulli margins or DRF probabilities
            pc = jnp.clip(prob, 1e-7, 1 - 1e-7)
            return -(w * (y * jnp.log(pc) +
                          (1 - y) * jnp.log1p(-pc))).sum() / n
        if dist in ("poisson", "gamma", "tweedie"):
            return (w * (mu - y * jnp.clip(F, -30, 30))).sum() / n
        return (w * (mu - y) ** 2).sum() / n
    if metric in ("MSE", "RMSE"):
        err = ((prob - y) ** 2 if prob is not None else (mu - y) ** 2)
        mse = (w * err).sum() / n
        return jnp.sqrt(mse) if metric == "RMSE" else mse
    if metric == "misclassification":
        pred = (prob > 0.5).astype(jnp.float32)
        return (w * (pred != y)).sum() / n
    if metric == "AUC":
        # weighted Mann-Whitney with EXACT tie handling (reference
        # ScoreKeeper scores tied predictions at half credit): positives in
        # a tie group earn cumneg-before-group + half the group's negative
        # weight. Negated so the stopping comparison stays less-is-better.
        order = jnp.argsort(prob)
        s = prob[order]
        ys, ws = y[order], w[order]
        negw = ws * (1.0 - ys)
        cumneg = jnp.cumsum(negw)
        lo = jnp.searchsorted(s, s, side="left")
        hi = jnp.searchsorted(s, s, side="right") - 1
        before = jnp.where(lo > 0, cumneg[jnp.maximum(lo - 1, 0)], 0.0)
        credit = before + 0.5 * (cumneg[hi] - before)
        posw = ws * ys
        tot = jnp.maximum(posw.sum() * negw.sum(), 1e-30)
        return -(posw * credit).sum() / tot
    raise ValueError(f"unsupported stopping_metric {metric!r}")


def _traverse_heap_device(binned_v, heap, n_bins: int, has_mask: bool):
    """Leaf values of ONE freshly grown tree for held-out rows, straight from
    the device heap channels (feat, thresh_bin, thresh_val, na_left,
    is_split, leaf, gain, cover[, left_mask]) — lets the fused scan carry
    validation margins without leaving the device."""
    feat, tbin, na_l, is_sp, leaf = heap[0], heap[1], heap[3], heap[4], heap[5]
    mask = heap[8] if has_mask else None
    rows = binned_v.shape[0]
    depth = int(np.log2(feat.shape[0] + 1)) - 1
    idx = jnp.zeros(rows, jnp.int32)
    for _ in range(depth):
        f = jnp.maximum(feat[idx], 0)
        b = jnp.take_along_axis(binned_v, f[:, None], axis=1)[:, 0]
        if mask is None:
            left = jnp.where(b >= n_bins, na_l[idx], b < tbin[idx])
        else:
            left = jnp.where(b >= n_bins, na_l[idx],
                             mask[idx, jnp.minimum(b, n_bins - 1)])
        nxt = idx * 2 + jnp.where(left, 1, 2)
        idx = jnp.where(is_sp[idx], nxt, idx)
    return leaf[idx]


@jax.jit
def _grad_hess_multinomial(F, y, w):
    """Softmax gradients for all K classes at once (reference: GBM.java
    multinomial pseudo-residuals). F: [rows, K]; y: int class ids."""
    p = jax.nn.softmax(F, axis=1)
    yoh = jax.nn.one_hot(y.astype(jnp.int32), F.shape[1], dtype=F.dtype)
    return w[:, None] * (p - yoh), w[:, None] * jnp.maximum(p * (1 - p), 1e-10)


def _pack_hp(col_rate, sample_rate, col_tree_rate, min_rows, reg_lambda,
             reg_alpha, gamma, min_split_improvement, lr,
             quantile_alpha=0.5, huber_alpha=0.9, tweedie_power=1.5):
    """The ``_boost_scan_jit`` hp-vector layout — the ONE place the slot
    order lives (the dryrun audit in ``__graft_entry__`` packs with this
    too, so it can never silently audit a differently-wired program)."""
    return jnp.asarray([col_rate, sample_rate, col_tree_rate, min_rows,
                        reg_lambda, reg_alpha, gamma, min_split_improvement,
                        lr, quantile_alpha, huber_alpha, tweedie_power],
                       jnp.float32)


def _boost_scan(binned, edges, yc, w, fmask_base, Fcur0, keys, *,
                dist: str, depth: int, n_bins: int, col_rate: float,
                sample_rate: float, col_tree_rate: float, min_rows: float,
                reg_lambda: float, reg_alpha: float, gamma: float,
                min_split_improvement: float, lr: float,
                bootstrap: bool, drf: bool, nclass: int,
                quantile_alpha: float = 0.5, huber_alpha: float = 0.9,
                tweedie_power: float = 1.5, mono=None, reach=None,
                cat_feats=None, track: str | None = None, val=None,
                ntrees_prior: int = 0, custom_id: int = -1,
                custom_link: str | None = None):
    """The WHOLE boosting/bagging run in one compiled program.

    Reference: ``SharedTree.scoreAndBuildTrees`` loops trees on the driver
    node, publishing to DKV per iteration. Here the loop is a ``lax.scan``
    whose body is gradient refresh + row/feature sampling + one fused tree
    growth, so the ensemble trains in ONE device dispatch — on a tunneled
    TPU every host-visible op between trees costs a ~30-40ms round-trip,
    which at 20 trees would double the total train time.

    Hyperparameter floats (lr, rates, regularization) are packed into ONE
    traced f32 vector, NOT static jit args: AutoML's random grids vary them
    per model, and as compile-time constants every config would pay a fresh
    XLA compile (the round-2 564s leaderboard was mostly compiles). One
    packed vector costs one ~40ms host→device upload per *model* (amortized
    over the whole train); sharing the compiled program saves tens of
    seconds per config. Only shape/control-flow params (dist, depth, bins,
    sampling on/off) remain static.

    ``keys``: [M, 3, 2] per-remaining-tree PRNG keys (precomputed from the
    base seed so checkpoint resume replays the same per-tree randomness).
    ``nclass`` > 1 grows one tree per class per round (multinomial), vmapped.
    Returns stacked heap arrays [M(, K), heap] + final margins Fcur.
    """
    hp = _pack_hp(col_rate, sample_rate, col_tree_rate, min_rows,
                  reg_lambda, reg_alpha, gamma, min_split_improvement,
                  lr, quantile_alpha, huber_alpha, tweedie_power)
    from h2o3_tpu.models.tree import hist_mesh
    return _boost_scan_jit(
        binned, edges, yc, w, fmask_base, Fcur0, keys, hp,
        dist=dist, depth=depth, n_bins=n_bins, bootstrap=bootstrap, drf=drf,
        nclass=nclass,
        do_row_sample=bool(sample_rate < 1.0),
        do_tree_col_sample=bool(col_tree_rate < 1.0),
        do_col_sample=bool(col_rate < 1.0),
        mono=mono, reach=reach, cat_feats=cat_feats, track=track, val=val,
        ntrees_prior=ntrees_prior, custom_id=custom_id,
        custom_link=custom_link, mesh=hist_mesh(binned))


# the boosting chunk's host-dispatched program — registered with the
# compute observatory (utils/costs.py): each (rows, K, depth, mesh)
# signature's compile wall time and cost_analysis FLOPs/bytes show in
# /3/Compute, and a shape-changed rebuild records a recompile event
@accounted_jit("gbm:boost_scan", loop="gbm_chunk",
               static_argnames=("dist", "depth", "n_bins", "bootstrap",
                                "drf", "nclass", "do_row_sample",
                                "do_tree_col_sample", "do_col_sample",
                                "track", "ntrees_prior", "custom_id",
                                "custom_link", "mesh"))
def _boost_scan_jit(binned, edges, yc, w, fmask_base, Fcur0, keys, hp, *,
                    dist: str, depth: int, n_bins: int, bootstrap: bool,
                    drf: bool, nclass: int, do_row_sample: bool,
                    do_tree_col_sample: bool, do_col_sample: bool,
                    mono=None, reach=None, cat_feats=None,
                    track: str | None = None, val=None,
                    ntrees_prior: int = 0, custom_id: int = -1,
                    custom_link: str | None = None, mesh=None):
    (col_rate, sample_rate, col_tree_rate, min_rows, reg_lambda, reg_alpha,
     gamma, min_split_improvement, lr, quantile_alpha, huber_alpha,
     tweedie_power) = hp
    F = binned.shape[1]
    binned_T = binned.T   # hoisted once by XLA; the Pallas kernel wants [F, R]

    def sample_w(k1):
        if bootstrap:
            return w * jax.random.poisson(k1, sample_rate, w.shape).astype(jnp.float32)
        if do_row_sample:
            return w * (jax.random.uniform(k1, w.shape) < sample_rate)
        return w

    def sample_fmask(k2):
        if not do_tree_col_sample:
            return fmask_base
        ku, kf = jax.random.split(k2)
        # force a guaranteed feature BEFORE intersecting with the base mask
        # so the sample can never re-enable a feature the base mask bans
        sub = jax.random.uniform(ku, (F,)) < col_tree_rate
        sub = sub.at[jax.random.randint(kf, (), 0, F)].set(True)
        m = fmask_base & sub
        return jnp.where(m.any(), m, fmask_base)

    def grow(g, h, wt, fmask, k3):
        return _grow_tree_device(
            binned, binned_T, edges, g, h, wt, fmask, k3, depth, n_bins,
            min_rows, reg_lambda, reg_alpha, gamma, min_split_improvement,
            col_rate, do_col_sample=do_col_sample,
            mono=mono, reach=reach, cat_feats=cat_feats, mesh=mesh)

    # -- optional per-tree metric tracking (fused ScoreKeeper) ---------------
    # `track` emits one train-metric scalar per tree from the carried
    # margins; `val` additionally carries held-out margins, traversing each
    # fresh tree on the validation bins inside the scan — scoring history and
    # early stopping then cost ZERO extra dispatches. DRF carries the running
    # SUM of tree predictions; its metric divides by the tree count.
    track_dist = "drf_prob" if drf else dist
    has_mask = cat_feats is not None
    M_prior = float(ntrees_prior)

    def scores(i, Ft, Fv):
        outs = []
        if drf:
            denom = jnp.maximum(i + 1.0 + M_prior, 1.0)
            Ft = Ft / denom
            Fv = None if Fv is None else Fv / denom
        if track is not None:
            outs.append(_metric_device(track, track_dist, Ft, yc, w, nclass,
                                       custom_link))
        if Fv is not None:
            vb, yv, wv, _ = val
            outs.append(_metric_device(track or "AUTO", track_dist, Fv, yv,
                                       wv, nclass, custom_link))
        return tuple(outs)

    def update_val(Fval, heap):
        if val is None:
            return None
        vb = val[0]
        if nclass <= 1:
            step = _traverse_heap_device(vb, heap, n_bins, has_mask)
            return Fval + (step if drf else lr * step)
        step = jnp.stack(
            [_traverse_heap_device(vb, [h[k] for h in heap], n_bins, has_mask)
             for k in range(nclass)], axis=1)
        return Fval + (step if drf else lr * step)

    if nclass <= 1:
        def body(carry, xs):
            ks, i = xs
            Fcur, Fval = carry
            wt = sample_w(ks[0])
            if drf:
                g, h = -yc * wt, wt      # leaf = weighted in-node mean
            else:
                g, h = _grad_hess(dist, Fcur, yc, wt, quantile_alpha,
                                  huber_alpha, tweedie_power, custom_id)
            out = grow(g, h, wt, sample_fmask(ks[1]), ks[2])
            heap, row_leaf = out[:-1], out[-1]
            Fnew = Fcur + (row_leaf if drf else lr * row_leaf)
            Fval = update_val(Fval, heap)
            return (Fnew, Fval), (heap, *scores(i, Fnew, Fval))
    else:
        yoh = jax.nn.one_hot(yc.astype(jnp.int32), nclass)

        def body(carry, xs):
            ks, i = xs
            Fcur, Fval = carry
            wt = sample_w(ks[0])
            if drf:
                G = -(yoh * wt[:, None])
                H = jnp.broadcast_to(wt[:, None], G.shape)
            else:
                G, H = _grad_hess_multinomial(Fcur, yc, wt)
            fmask = sample_fmask(ks[1])
            kk = jax.random.split(ks[2], nclass)
            outs = jax.vmap(lambda gk, hk, k: grow(gk, hk, wt, fmask, k))(
                G.T, H.T, kk)
            heap, row_leaf = outs[:-1], outs[-1]       # row_leaf: [K, R]
            Fnew = Fcur + (row_leaf.T if drf else lr * row_leaf.T)
            Fval = update_val(Fval, heap)
            return (Fnew, Fval), (heap, *scores(i, Fnew, Fval))

    Fval0 = val[3] if val is not None else None
    idx = jnp.arange(keys.shape[0], dtype=jnp.float32)
    (Fend, Fvend), ys = lax.scan(body, (Fcur0, Fval0), (keys, idx))
    heap = ys[0]
    extras = ys[1:]      # (tscore[, vscore]) per-tree metric arrays
    return Fend, heap, extras, Fvend


def _trees_from_stacked(heap, m: int, k: int | None = None) -> Tree:
    """Tree m (class k) from _boost_scan's stacked heap arrays.

    ``heap`` should be host-side (see ``_heap_to_host``): slicing device
    arrays per tree would cost a dispatch each — hundreds of tunnel
    round-trips per model."""
    pick = (lambda a: a[m] if k is None else a[m][k])
    vals = [pick(a) for a in heap]
    hf, ht, htv, hna, hsp, hlf, hg, hc = vals[:8]
    hm = vals[8] if len(vals) > 8 else None   # group-split membership masks
    return Tree(feat=hf, thresh_bin=ht, thresh_val=htv, na_left=hna,
                is_split=hsp, leaf=hlf, gain=hg, cover=hc, left_mask=hm)


def _heap_to_host(heap):
    """ONE batched transfer for the whole stacked ensemble (the heap arrays
    are tiny: ntrees x 2^(depth+1) nodes; per-leaf device_get would pay one
    ~40ms tunnel round-trip PER CHANNEL)."""
    return jax.tree.map(np.asarray, jax.device_get(heap))


class SharedTreeModel(Model):
    def _tree_raw_sum(self, frame: Frame) -> jax.Array:
        if not self.output["trees"]:
            # a deadline-cancelled build may legitimately hold zero trees;
            # it scores as the null model (f0 margin only)
            return jnp.zeros(frame.plen, jnp.float32)
        X = tree_matrix(frame, self.output["x_cols"], self.output["feat_domains"])
        return predict_raw(X, self.output["trees"],
                           cat_card=self.output.get("cat_card"),
                           n_bins=int(self.output.get("cat_bins") or 0))

    def predict(self, frame: Frame) -> Frame:
        """Score; a calibrated binomial model appends ``cal_p0``/``cal_p1``
        (reference: ``CalibrationHelper.postProcessPredictions``)."""
        out = super().predict(frame)
        cal = self.output.get("calibration")
        if cal is not None:
            p1 = np.clip(out.vec(2).to_numpy(), 1e-15, 1 - 1e-15)
            if cal["method"] == "PlattScaling":
                z = cal["a"] * np.log(p1 / (1 - p1)) + cal["b"]
                cp1 = 1.0 / (1.0 + np.exp(-z))
            else:                     # IsotonicRegression: PAV step interp
                cp1 = np.interp(p1, cal["xs"], cal["ys"])
            from h2o3_tpu.frame.types import VecType
            from h2o3_tpu.frame.vec import Vec
            out.add("cal_p0", Vec.from_numpy((1 - cp1).astype(np.float32),
                                             type=VecType.NUM))
            out.add("cal_p1", Vec.from_numpy(cp1.astype(np.float32),
                                             type=VecType.NUM))
        return out

    def varimp(self, use_pandas: bool = False):
        """Per-feature split-gain importance (reference: ``SharedTree``
        relative importance = accumulated squared-error reduction; h2o-py
        ``model.varimp()`` rows = (variable, relative, scaled, percentage))."""
        cols = self.output["x_cols"]
        rel = np.zeros(len(cols))
        all_trees = self.output.get("trees") or [
            t for ts in self.output.get("trees_multi", []) for t in ts]
        # getattr: artifacts pickled before the gain/cover channels restore
        # __dict__ directly, bypassing the dataclass defaults
        with_gain = [t for t in all_trees
                     if getattr(t, "gain", None) is not None]
        # ONE batched transfer for the whole ensemble — per-tree device_gets
        # paid 2 host round-trips per tree (graftlint TRC003)
        fetched = jax.device_get([(t.feat, t.gain) for t in with_gain])
        for feat, gain in fetched:
            feat, gain = np.asarray(feat), np.asarray(gain)
            ok = feat >= 0
            np.add.at(rel, feat[ok], np.maximum(gain[ok], 0.0))
        mx = rel.max() if rel.max() > 0 else 1.0
        tot = rel.sum() if rel.sum() > 0 else 1.0
        rows = sorted(zip(cols, rel, rel / mx, rel / tot),
                      key=lambda r: -r[1])
        if use_pandas:
            import pandas as pd
            return pd.DataFrame(rows, columns=["variable", "relative_importance",
                                               "scaled_importance", "percentage"])
        return rows

    def _contrib_scale_bias(self) -> tuple[float, float]:
        """(scale, extra_bias) mapping summed tree-leaf SHAP onto this model's
        raw margin: margin = scale * tree_sum + extra_bias."""
        return 1.0, 0.0

    def predict_contributions(self, frame: Frame) -> Frame:
        """Per-row SHAP contributions + BiasTerm (reference:
        ``Model.scoreContributions`` → genmodel TreeSHAP; h2o-py
        ``model.predict_contributions``). Row sums equal the model's raw
        margin (logit for bernoulli, mean prediction for DRF/regression)."""
        from h2o3_tpu.frame.types import VecType
        from h2o3_tpu.frame.vec import Vec
        from h2o3_tpu.genmodel.treeshap import ensemble_contributions
        if "trees" not in self.output:
            raise ValueError("contributions need a single-tree-set model")
        X = np.asarray(jax.device_get(
            tree_matrix(frame, self.output["x_cols"],
                        self.output["feat_domains"])))[: frame.nrows]
        phi = ensemble_contributions(
            self.output["trees"], X,
            cat_card=self.output.get("cat_card"),
            n_bins=int(self.output.get("cat_bins") or 0))
        scale, bias = self._contrib_scale_bias()
        phi *= scale
        phi[:, -1] += bias
        names = list(self.output["x_cols"]) + ["BiasTerm"]
        return Frame(names, [Vec.from_numpy(phi[:, i].astype(np.float32),
                                            type=VecType.NUM)
                             for i in range(phi.shape[1])])

    def _tree_raw_sum_per_class(self, frame: Frame) -> jax.Array:
        """[rows, K] per-class sums for multinomial (trees_multi[k] = class k)."""
        if not any(self.output["trees_multi"]):
            # zero-round deadline-cancelled partial: null model (f0 only),
            # same contract as the single-class guard in _tree_raw_sum
            return jnp.zeros((frame.plen, len(self.output["trees_multi"])),
                             jnp.float32)
        X = tree_matrix(frame, self.output["x_cols"], self.output["feat_domains"])
        cc = self.output.get("cat_card")
        nb = int(self.output.get("cat_bins") or 0)
        return jnp.stack([predict_raw(X, ts, cat_card=cc, n_bins=nb)
                          for ts in self.output["trees_multi"]], axis=1)


class GBMModel(SharedTreeModel):
    algo = "gbm"

    def _contrib_scale_bias(self):
        return float(self.output["learn_rate"]), float(self.output["f0"])

    def _score_raw(self, frame: Frame) -> jax.Array:
        if self.output["distribution"] == "multinomial":
            f = jnp.asarray(self.output["f0_multi"])[None, :] \
                + self.output["learn_rate"] * self._tree_raw_sum_per_class(frame)
            return jax.nn.softmax(f, axis=1)
        f = self.output["f0"] + self.output["learn_rate"] * self._tree_raw_sum(frame)
        oc = self.params.get("offset_column")
        if oc:
            if oc not in frame:
                raise ValueError(f"scoring frame lacks offset column {oc!r}")
            f = f + jnp.nan_to_num(frame.vec(oc).as_float(), nan=0.0)
        if self.output["distribution"] == "bernoulli":
            p = jax.nn.sigmoid(f)
            return jnp.stack([1 - p, p], axis=1)
        if self.output["distribution"] in ("poisson", "gamma", "tweedie"):
            return jnp.exp(jnp.clip(f, -30, 30))   # log link
        if self.output["distribution"] == "custom":
            return _linkinv_device(self.output["custom_link"], f)
        return f


class SharedTreeBuilder(ModelBuilder):
    """Common driver for boosting/bagging (reference: hex/tree/SharedTree.java)."""

    @classmethod
    def defaults(cls) -> dict:
        return dict(
            super().defaults(),
            ntrees=50,
            max_depth=5,
            min_rows=10.0,
            nbins=64,
            sample_rate=1.0,
            col_sample_rate_per_tree=1.0,
            min_split_improvement=1e-5,
            stopping_rounds=0,
            stopping_metric="AUTO",      # deviance (logloss/MSE) like reference
            stopping_tolerance=1e-3,
            score_tree_interval=0,   # history row cadence; the fused tracker
            score_each_iteration=False,  # scores EVERY tree at no cost, so
                                         # these only thin the reported table
            monotone_constraints=None,       # {col: ±1} (Constraints.java)
            interaction_constraints=None,    # [[cols...], ...] (BranchInteractionConstraints)
            calibrate_model=False,           # CalibrationHelper.java:18
            calibration_frame=None,
            calibration_method="PlattScaling",   # or IsotonicRegression
            nbins_cats=1024,                 # DHistogram enum bins (capped at nbins here)
            categorical_encoding="AUTO",     # AUTO/enum = group splits; ordinal = thresholds
            offset_column=None,              # per-row margin offset (Model.Parameters._offset)
        )

    # Dense-heap trees cap depth at 16 (2^17 nodes); the reference's default 20
    # assumes sparse node storage.
    MAX_TREE_DEPTH = 16

    #: scoring-history column name per stopping metric (AUC is tracked
    #: negated for less-is-better stopping; the table shows the true value)
    _HIST_NAMES = {"AUTO": "deviance", "deviance": "deviance",
                   "logloss": "logloss", "MSE": "mse", "RMSE": "rmse",
                   "AUC": "auc", "misclassification": "classification_error"}

    def _scoring_history(self, model):
        """Per-tree metric rows from the fused scan's tracked series
        (reference: ``SharedTree.doScoringAndSaveModel`` →
        ``createScoringHistoryTable``)."""
        series = getattr(self, "_score_series", None)
        if not series:
            return None
        metric, tser, vser = series
        name = self._HIST_NAMES.get(metric, "deviance")
        sign = -1.0 if metric == "AUC" else 1.0   # tracked negated
        cols = [("number_of_trees", "long", "%d"),
                (f"training_{name}", "double", "%.5f")]
        if vser is not None:
            cols.append((f"validation_{name}", "double", "%.5f"))
        # score_tree_interval thins the REPORTED table (reference scores on
        # that cadence; the fused tracker gets every tree anyway) — the last
        # tree always reports, matching doScoringAndSaveModel(finalScoring)
        sti = int(self.params.get("score_tree_interval") or 0)
        if self.params.get("score_each_iteration"):
            sti = 1
        values = [[i + 1, sign * float(tv)] +
                  ([sign * float(vser[i])] if vser is not None else [])
                  for i, tv in enumerate(tser)
                  if sti <= 1 or (i + 1) % sti == 0 or i == len(tser) - 1]
        return self._history_table(model, cols, values)

    def _prepare(self, frame: Frame, x: list[str], y: str, weights=None):
        depth = int(self.params["max_depth"])
        if depth > self.MAX_TREE_DEPTH:
            raise ValueError(f"max_depth={depth} exceeds the dense-heap limit "
                             f"{self.MAX_TREE_DEPTH}")
        yvec = frame.vec(y)
        # edges from a strided host sample assembled per COLUMN — stacking a
        # full [rows, F] float matrix on TPU pads F to 128 lanes (4.6x HBM;
        # 5.6GB at HIGGS-11M), so the raw design matrix is never materialized
        nrows = frame.nrows
        stride = max(1, nrows // 100_000)
        idx = jnp.arange(0, nrows, stride)
        sample_dev = jnp.stack([frame.vec(c).as_float()[idx] for c in x],
                               axis=1)
        sample = np.asarray(jax.device_get(sample_dev))
        w_sample = None
        if weights is not None:
            # weighted edges keep the weights-as-replication contract
            # (compute_bin_edges docstring); same strided sample of rows
            w_sample = np.asarray(jax.device_get(weights[idx])).astype(np.float64)
        edges = jnp.asarray(compute_bin_edges(sample, int(self.params["nbins"]),
                                              w_sample))
        self._setup_cat_info(frame, x)
        binned = self._bin_frame(frame, x, edges)
        from h2o3_tpu.models.data_info import response_as_float
        yy, valid = response_as_float(yvec)
        domains = {c: frame.vec(c).domain for c in x if frame.vec(c).is_categorical}
        return None, edges, binned, yy, valid, yvec, domains

    def _bin_frame(self, frame: Frame, x: list[str], edges) -> jax.Array:
        """Per-column binning → [rows, F] int8/int16 (the only row-major
        matrix training keeps). The dtype is the narrowest that holds
        every bin id PLUS the Pallas pad sentinel (n_bins_tot + 1): int8
        up to 125 bins halves HBM reads of the histogram kernel's dominant
        input vs int16 (the default 64-bin config packs; the 256-bin
        XGBoost config stays int16) — VERDICT r4 next #2."""
        from h2o3_tpu.models.tree import cat_bins_for_codes
        nbins = int(self.params["nbins"])
        from h2o3_tpu.ops.quantile import bin_dtype
        dtype = bin_dtype(nbins)
        cc, cat_bins = (self._cat_info if self._cat_info is not None
                        else (None, 0))
        cols = []
        for j, c in enumerate(x):
            v = frame.vec(c).as_float()
            if cc is not None and int(cc[j]) > 0:
                b = cat_bins_for_codes(v[:, None], cc[j:j + 1], cat_bins)[:, 0]
                b = jnp.where(jnp.isnan(v), nbins, b)
            else:
                b = jnp.searchsorted(edges[j], v, side="right")
                b = jnp.where(jnp.isnan(v), nbins, b)
            cols.append(b.astype(dtype))
        return jnp.stack(cols, axis=1)

    def _setup_cat_info(self, frame: Frame, x: list[str]) -> None:
        """Categorical group-split binning state (reference: DHistogram gives
        enums one bin per level up to ``nbins_cats``, then range-groups;
        ``categorical_encoding="ordinal"`` opts back into threshold splits)."""
        enc = str(self.params.get("categorical_encoding") or "AUTO").lower()
        cat_card = np.zeros(len(x), np.int32)
        if enc in ("auto", "enum"):
            for j, c in enumerate(x):
                if frame.vec(c).is_categorical:
                    cat_card[j] = frame.vec(c).cardinality()
        elif enc not in ("ordinal", "label_encoder", "labelencoder"):
            raise ValueError(f"unsupported categorical_encoding {enc!r}; "
                             "have AUTO, enum, ordinal/label_encoder")
        if cat_card.any():
            nbins = int(self.params["nbins"])
            cat_bins = min(nbins, int(self.params.get("nbins_cats") or nbins))
            self._cat_info = (jnp.asarray(cat_card), cat_bins)
        else:
            self._cat_info = None

    def _apply_cat_bins(self, X, binned):
        """Re-bin categorical columns: bin = (possibly range-grouped) level
        code, missing stays the overflow bin."""
        if self._cat_info is None:
            return binned
        cc, cat_bins = self._cat_info
        from h2o3_tpu.models.tree import cat_bins_for_codes
        nbins = int(self.params["nbins"])
        cb = cat_bins_for_codes(X, cc, cat_bins)
        is_cat = cc[None, :] > 0
        nan = jnp.isnan(X)
        out = jnp.where(is_cat & ~nan, cb, binned)
        return jnp.where(is_cat & nan, nbins, out).astype(binned.dtype)

    @property
    def _cat_feats(self):
        return None if self._cat_info is None else self._cat_info[0] > 0

    def _cat_output(self) -> dict:
        """Extra model-output entries for group-split models."""
        if self._cat_info is None:
            return {}
        cc, cat_bins = self._cat_info
        return dict(cat_card=cc, cat_bins=cat_bins)

    def _maybe_calibrate(self, model) -> None:
        """Fit probability calibration on a held-out frame (reference:
        ``hex/tree/CalibrationHelper.java:18`` — Platt scaling or isotonic
        regression on the model's predicted p1 vs the actual class)."""
        if not self.params.get("calibrate_model"):
            return
        if model.nclasses != 2:
            raise ValueError("calibrate_model requires a binomial model "
                             "(reference: CalibrationHelper)")
        cf = self.params.get("calibration_frame")
        if cf is None:
            raise ValueError("calibrate_model requires calibration_frame")
        if isinstance(cf, str):
            from h2o3_tpu.utils.registry import DKV
            cf = DKV[cf]
        method = str(self.params.get("calibration_method") or "PlattScaling")
        if method not in ("PlattScaling", "IsotonicRegression"):
            raise ValueError(f"unknown calibration_method {method!r}")
        from h2o3_tpu.models.data_info import response_adapted
        from h2o3_tpu.parallel.distributed import fetch
        raw = model._score_raw(cf)
        yv, valid = response_adapted(cf.vec(model.response_column),
                                     model.response_domain)
        mask = fetch(cf.row_mask() & valid)[:cf.nrows]
        p1 = np.clip(fetch(raw)[:cf.nrows, 1][mask], 1e-15, 1 - 1e-15)
        y = fetch(yv)[:cf.nrows][mask]
        if method == "PlattScaling":
            f = np.log(p1 / (1 - p1))
            # Platt's target smoothing: t+=(N++1)/(N++2), t-=1/(N-+2)
            npos, nneg = float(y.sum()), float((1 - y).sum())
            t = np.where(y > 0, (npos + 1) / (npos + 2), 1 / (nneg + 2))
            a, b = 1.0, 0.0
            for _ in range(50):
                p = 1 / (1 + np.exp(-(a * f + b)))
                g = np.array([np.sum((p - t) * f), np.sum(p - t)])
                W = np.maximum(p * (1 - p), 1e-10)
                Hm = np.array([[np.sum(W * f * f) + 1e-9, np.sum(W * f)],
                               [np.sum(W * f), np.sum(W) + 1e-9]])
                step = np.linalg.solve(Hm, g)
                a, b = a - step[0], b - step[1]
                if np.abs(step).max() < 1e-10:
                    break
            model.output["calibration"] = dict(method=method, a=float(a),
                                               b=float(b))
        else:
            order = np.argsort(p1)
            xs, ys = p1[order], y[order].astype(np.float64)
            # pool-adjacent-violators (reference hex/isotonic)
            vals, wts, cnt = list(ys), [1.0] * len(ys), list(xs)
            i = 0
            merged_v, merged_w, merged_x = [], [], []
            for v, wt, xx in zip(vals, wts, cnt):
                merged_v.append(v); merged_w.append(wt); merged_x.append(xx)
                while len(merged_v) > 1 and merged_v[-2] > merged_v[-1]:
                    v2, w2 = merged_v.pop(), merged_w.pop()
                    merged_x.pop()
                    merged_v[-1] = (merged_v[-1] * merged_w[-1] + v2 * w2) / (merged_w[-1] + w2)
                    merged_w[-1] += w2
            model.output["calibration"] = dict(
                method=method,
                xs=[float(v) for v in merged_x],
                ys=[float(v) for v in merged_v])

    def _constraint_arrays(self, x: list[str], frame: Frame):
        """(mono[F], reach[F,F]) device arrays from the constraint params.

        Reference: ``hex/tree/Constraints.java:7`` (monotone directions) and
        ``BranchInteractionConstraints.java`` (allowed-feature propagation).
        Unlisted features form singleton interaction sets (XGBoost
        semantics: they may split anywhere but nothing else may follow)."""
        mc = self.params.get("monotone_constraints") or {}
        ic = self.params.get("interaction_constraints")
        mono = reach = None
        if mc:
            bad = set(mc) - set(x)
            if bad:
                raise ValueError(f"monotone_constraints name non-feature "
                                 f"columns: {sorted(bad)}")
            for c in mc:
                if frame.vec(c).is_categorical:
                    raise ValueError(f"monotone constraint on categorical "
                                     f"column {c!r} (reference: numeric only)")
                if int(mc[c]) not in (-1, 0, 1):
                    raise ValueError(f"monotone_constraints[{c!r}] must be "
                                     "-1, 0 or 1")
            mono = jnp.asarray([int(mc.get(c, 0)) for c in x], jnp.int32)
        if ic:
            F = len(x)
            reach_np = np.zeros((F, F), bool)
            listed: set[int] = set()
            for group in ic:
                bad = set(group) - set(x)
                if bad:
                    raise ValueError(f"interaction_constraints name "
                                     f"non-feature columns: {sorted(bad)}")
                idxs = [x.index(c) for c in group]
                for i in idxs:
                    reach_np[i, idxs] = True
                listed.update(idxs)
            for f in range(F):
                if f not in listed:
                    reach_np[f, f] = True
            reach = jnp.asarray(reach_np)
        return mono, reach

    def _effective_col_rate(self) -> float:
        """Per-level feature-sampling rate (XGBoost overrides to fold
        colsample_bynode in without mutating the stored params)."""
        return float(self.params["col_sample_rate"])

    def _feat_mask(self, key, F: int, rate: float) -> jax.Array:
        if rate >= 1.0:
            return jnp.ones(F, bool)
        ku, kf = jax.random.split(key)
        m = jax.random.uniform(ku, (F,)) < rate
        # guarantee at least one feature
        return m.at[jax.random.randint(kf, (), 0, F)].set(True)

    def _check_checkpoint(self, cp, x, dist: str | None):
        """Validate checkpoint compatibility (reference: SharedTree.java:241
        checks immutable params against the prior model)."""
        if cp is None:
            return
        if list(cp.output["x_cols"]) != list(x):
            raise ValueError("checkpoint feature columns differ from this train")
        if dist is not None and cp.output["distribution"] != dist:
            raise ValueError(f"checkpoint distribution {cp.output['distribution']!r}"
                             f" != {dist!r}")
        for immut in ("max_depth", "nbins"):
            if int(cp.params.get(immut, self.params[immut])) != int(self.params[immut]):
                raise ValueError(f"checkpoint {immut} differs; tree structure "
                                 "params are immutable across resume")
        # group-split state must match: mixing masked and threshold trees in
        # one ensemble would mis-route every categorical (the traversal mode
        # is chosen per ensemble)
        cp_grouped = cp.output.get("cat_card") is not None
        if cp_grouped != (getattr(self, "_cat_info", None) is not None):
            raise ValueError(
                "checkpoint categorical encoding differs (group splits vs "
                "ordinal); set categorical_encoding to match the checkpoint")
        if cp_grouped and int(cp.output.get("cat_bins") or 0) != \
                int(self._cat_info[1]):
            raise ValueError("checkpoint nbins_cats differs; immutable "
                             "across resume")
        # learn_rate scales EVERY tree at scoring time — changing it across a
        # resume would silently rescale the checkpoint's trees too
        if "learn_rate" in self.params and "learn_rate" in cp.params:
            if float(cp.params["learn_rate"]) != float(self.params["learn_rate"]):
                raise ValueError("checkpoint learn_rate differs; it is immutable "
                                 "across resume (it rescales prior trees)")
        prior = int(cp.output["ntrees"])
        if int(self.params["ntrees"]) <= prior:
            raise ValueError(f"ntrees must exceed the checkpoint's {prior} "
                             "to continue training")

    def _row_weights(self, key, w, rate: float, bootstrap: bool):
        if bootstrap:
            # Poisson(rate) ≈ bootstrap of a `rate` fraction (sample_rate honored)
            return w * jax.random.poisson(key, rate, w.shape).astype(jnp.float32)
        if rate >= 1.0:
            return w
        return w * (jax.random.uniform(key, w.shape) < rate)


class GBM(SharedTreeBuilder):
    """h2o-py surface: ``H2OGradientBoostingEstimator``."""

    algo = "gbm"

    def supports_auto_recovery(self) -> bool:
        return True     # chunk-boundary snapshots in _grow_with_stopping

    def _retag_model(self, m: GBMModel) -> GBMModel:
        """Partial-model snapshots must carry the builder's model class so
        a resume passes the checkpoint algo check (XGBoost re-classes its
        models the same way at the end of ``_fit``)."""
        if self.algo == "xgboost":
            from h2o3_tpu.models.xgboost import XGBoostModel
            m.__class__ = XGBoostModel
        return m

    @classmethod
    def defaults(cls) -> dict:
        return dict(
            super().defaults(),
            learn_rate=0.1,
            distribution="AUTO",
            reg_lambda=0.0,
            col_sample_rate=1.0,   # per-level feature sampling inside grow_tree
            quantile_alpha=0.5,    # quantile distribution target
            huber_alpha=0.9,       # huber delta = this quantile of |residual|
            tweedie_power=1.5,
            custom_distribution_func=None,  # "python:key=module.Class" UDF
            # boosting rounds per compiled device program (0 = auto-size to
            # the watchdog budget); each dispatch pays ONE host sync for the
            # early-stopping decision. GBM/XGBoost only: DRF and the other
            # bagging builders grow their whole forest in one dispatch, so
            # the knob would be inert there
            trees_per_dispatch=0,
        )

    def _fit(self, job: Job, frame: Frame, x, y, weights) -> GBMModel:
        p = self.params
        X, edges, binned, yy, valid, yvec, domains = self._prepare(
            frame, x, y, weights)
        cp = self._resolve_checkpoint()
        if cp is not None:
            # validate BEFORE re-binning: a feature-list mismatch must raise
            # the intended error, not a shape error from bin_features
            self._check_checkpoint(cp, x, None)
            # binning must match the prior model's edges exactly, else tree
            # thresholds silently shift (reference keeps the checkpoint's
            # DHistogram bins)
            edges = cp.output["edges"]
            binned = self._bin_frame(frame, x, edges)
        dist = str(p["distribution"])
        if dist.lower() == "auto":   # h2o-py sends lowercase enum names
            dist = "AUTO"
        if yvec.is_categorical:
            if dist not in ("AUTO", "bernoulli", "multinomial"):
                raise ValueError(f"distribution {dist!r} requires a numeric response")
            if dist == "bernoulli" and yvec.cardinality() != 2:
                raise ValueError("Binomial requires the response to be a 2-class "
                                 "categorical")
            dist = "bernoulli" if yvec.cardinality() == 2 else "multinomial"
        else:
            if dist == "AUTO":
                dist = "gaussian"
            if dist == "bernoulli":
                raise ValueError("bernoulli distribution requires a categorical (2-level) response")
            if dist not in ("gaussian", "poisson", "gamma", "tweedie",
                            "laplace", "quantile", "huber", "custom"):
                raise ValueError(f"unsupported distribution {dist!r}; "
                                 "have gaussian, bernoulli, poisson, gamma, "
                                 "tweedie, laplace, quantile, huber, custom, "
                                 "AUTO")
        custom_id, custom_dist = -1, None
        if dist == "custom":
            ref = p.get("custom_distribution_func")
            if not ref:
                raise ValueError("distribution='custom' requires "
                                 "custom_distribution_func "
                                 "(h2o.upload_custom_distribution reference)")
            from h2o3_tpu.utils import udf as _udf
            custom_id, custom_dist = _udf.resolve_distribution(ref)
        w = weights * valid
        yc = jnp.where(w > 0, yy, 0.0)

        if dist == "multinomial":
            if p.get("offset_column"):
                raise ValueError("offset_column is not supported for "
                                 "multinomial distributions")
            return self._fit_multinomial(job, frame, x, y, w, yc, yvec,
                                         X, edges, binned, domains, cp)
        self._check_checkpoint(cp, x, dist)

        if cp is not None:
            f0 = float(cp.output["f0"])
        else:
            ybar = float(jax.device_get((w * yc).sum() / jnp.maximum(w.sum(), 1e-30)))
            if dist == "bernoulli":
                ybar = min(max(ybar, 1e-6), 1 - 1e-6)
                f0 = float(np.log(ybar / (1 - ybar)))
            elif dist in ("poisson", "gamma", "tweedie"):
                f0 = float(np.log(max(ybar, 1e-10)))   # log link
            elif dist in ("laplace", "huber"):
                f0 = _weighted_quantile_host(yy, w, 0.5)
            elif dist == "quantile":
                f0 = _weighted_quantile_host(yy, w, float(p["quantile_alpha"]))
            elif dist == "custom":
                import numpy as _np
                oc_ = p.get("offset_column")
                off = (_np.nan_to_num(np.asarray(frame.vec(oc_).as_float()))
                       if oc_ else None)
                f0 = custom_dist.f0(np.asarray(jax.device_get(yy)),
                                    np.asarray(jax.device_get(w)), off)
            else:
                f0 = ybar

        lr = float(p["learn_rate"])
        seed = int(p["seed"]) if int(p["seed"]) >= 0 else 42
        key = jax.random.PRNGKey(seed)
        Fcur = jnp.full(binned.shape[0], f0, jnp.float32)
        oc = p.get("offset_column")
        if oc:
            # per-row margin offset (reference: offset_column adds to F on
            # both train and score; score0 re-reads it from the scored frame)
            Fcur = Fcur + jnp.nan_to_num(frame.vec(oc).as_float(), nan=0.0)
        trees: list[Tree] = []
        if cp is not None:
            trees = list(cp.output["trees"])
            # fold (not sum-then-scale): the resumed margins must match the
            # uninterrupted scan's accumulation order bit-for-bit, so the
            # remaining trees come out identical (exact-resume contract)
            Fcur = fold_binned(binned, trees, int(p["nbins"]), lr, Fcur)
        ntrees = int(p["ntrees"])
        done = len(trees)
        keys = jax.random.split(key, ntrees * 3).reshape(ntrees, 3, 2)[done:]
        job.update(0.1, f"growing {ntrees - done} trees (one fused program)")
        kwargs = dict(
            dist=dist, depth=int(p["max_depth"]), n_bins=int(p["nbins"]),
            col_rate=self._effective_col_rate(),
            sample_rate=float(p["sample_rate"]),
            col_tree_rate=float(p["col_sample_rate_per_tree"]),
            min_rows=float(p["min_rows"]), reg_lambda=float(p["reg_lambda"]),
            reg_alpha=float(p.get("reg_alpha", 0.0)),
            gamma=float(p.get("gamma", 0.0)),
            min_split_improvement=float(p["min_split_improvement"]), lr=lr,
            bootstrap=False, drf=False, nclass=0,
            quantile_alpha=float(p["quantile_alpha"]),
            huber_alpha=float(p["huber_alpha"]),
            tweedie_power=float(p["tweedie_power"]), custom_id=custom_id,
            custom_link=custom_dist.link_name if custom_dist else None)
        mono, reach = self._constraint_arrays(x, frame)
        kwargs.update(mono=mono, reach=reach, cat_feats=self._cat_feats)
        fmask_base = jnp.ones(binned.shape[1], bool)
        valid = None
        if getattr(self, "_validation_frame", None) is not None or \
                int(p.get("stopping_rounds") or 0) > 0:
            # also tracked without early stopping: the validation series
            # feeds scoring_history (reference scores valid per event)
            valid = self._valid_stop_data(
                edges, 0, f0, lr, domains,
                yvec.domain if yvec.is_categorical else None,
                prior_trees=trees or None)

        # auto-checkpoint constructor: a resumable partial ensemble in the
        # exact shape checkpoint= resume consumes (distinct key — the final
        # model must never be clobbered by its own snapshot)
        self._partial_model_fn = None
        if getattr(self, "_build_recovery", None) is not None:
            def _partial(grown: list) -> GBMModel:
                pm = GBMModel(
                    key=f"{self.model_id or self.algo}_autockpt",
                    params=self.params, data_info=None, response_column=y,
                    response_domain=(yvec.domain if yvec.is_categorical
                                     else None),
                    output=dict(trees=trees + grown, edges=edges, f0=f0,
                                learn_rate=lr, distribution=dist,
                                x_cols=list(x), feat_domains=domains,
                                ntrees=len(trees) + len(grown),
                                **({"custom_link": custom_dist.link_name}
                                   if custom_dist is not None else {}),
                                **self._cat_output()))
                return self._retag_model(pm)
            self._partial_model_fn = _partial
        grown, Fend = self._grow_with_stopping(job, binned, edges, yc, w,
                                               fmask_base, Fcur, keys, dist,
                                               0, kwargs, p, valid=valid)
        self._partial_model_fn = None
        trees += grown
        job.update(0.9, f"{len(trees)} trees grown")
        # final margins double as training predictions (skips the re-score);
        # cached on the transient builder so models never pickle them
        if dist == "bernoulli":
            pe = jax.nn.sigmoid(Fend)
            self._last_train_raw = jnp.stack([1 - pe, pe], axis=1)
        elif dist in ("poisson", "gamma", "tweedie"):
            self._last_train_raw = jnp.exp(jnp.clip(Fend, -30, 30))
        elif dist == "custom":
            self._last_train_raw = _linkinv_device(custom_dist.link_name, Fend)
        else:
            self._last_train_raw = Fend

        model = GBMModel(
            key=make_model_key(self.algo, self.model_id),
            params=self.params, data_info=None, response_column=y,
            response_domain=yvec.domain if yvec.is_categorical else None,
            output=dict(trees=trees, edges=edges, f0=f0, learn_rate=lr,
                        distribution=dist, x_cols=list(x), feat_domains=domains,
                        ntrees=len(trees),
                        **({"custom_link": custom_dist.link_name}
                           if custom_dist is not None else {}),
                        **self._cat_output()),
        )
        self._maybe_calibrate(model)
        return model

    #: early-stopping metrics honored (reference: ScoreKeeper.StoppingMetric)
    STOPPING_METRICS = ("AUTO", "deviance", "logloss", "MSE", "RMSE", "AUC",
                        "misclassification")

    def _stop_score(self, metric: str, dist: str, F, y, w, nclass: int,
                    custom_link: str | None = None) -> float:
        """Less-is-better score for ``stopping_metric`` in host loops (the
        DART driver); same math as the fused scan's :func:`_metric_device`
        — one implementation keeps the two paths from drifting."""
        sdist = "multinomial" if nclass > 1 else dist
        if metric in ("logloss", "misclassification", "AUC") and sdist not in (
                "bernoulli", "multinomial"):
            raise ValueError(f"stopping_metric={metric!r} requires a "
                             "classification distribution")
        if metric == "AUC" and sdist != "bernoulli":
            raise ValueError("stopping_metric='AUC' requires a binomial "
                             "response")
        if metric not in self.STOPPING_METRICS:
            raise ValueError(f"unsupported stopping_metric {metric!r}; have "
                             f"{self.STOPPING_METRICS}")
        return float(jax.device_get(
            _metric_device(metric, sdist, F, y, w, nclass, custom_link)))

    def _valid_stop_data(self, edges, nclass: int, f0, lr: float,
                         domains, y_domain, prior_trees=None):
        """Bin the validation frame with the training edges and seed its
        margins — early stopping then scores the held-out frame per tree
        chunk (reference: ScoreKeeper scores the validation frame when one
        is given). Categorical features and response are remapped to the
        train domains (``Model.adaptTestForTrain`` semantics)."""
        vf = getattr(self, "_validation_frame", None)
        if vf is None:
            return None
        x = self._x_cols
        Xv = tree_matrix(vf, x, domains)
        binned_v = self._apply_cat_bins(Xv, bin_features(Xv, edges))
        from h2o3_tpu.models.data_info import response_adapted
        yvec = vf.vec(self._y_col)
        yv, validv = response_adapted(yvec, y_domain)
        wv = vf.row_mask().astype(jnp.float32) * validv
        wcol = self.params.get("weights_column")
        if wcol and wcol in vf:
            wv = wv * vf.vec(wcol).data
        yv = jnp.where(wv > 0, yv, 0.0)
        nbins = int(self.params["nbins"])
        if nclass > 1:
            Fval = jnp.broadcast_to(
                jnp.asarray(f0, jnp.float32)[None, :],
                (Xv.shape[0], nclass)).astype(jnp.float32)
            if prior_trees:  # checkpoint: [K][ntrees] lists
                Fval = Fval + lr * jnp.stack(
                    [predict_binned(binned_v, ts, nbins) for ts in prior_trees],
                    axis=1)
        else:
            Fval = jnp.full(Xv.shape[0], float(f0), jnp.float32)
            if prior_trees:
                Fval = Fval + lr * predict_binned(binned_v, prior_trees, nbins)
        return binned_v, yv, wv, Fval

    def _grow_with_stopping(self, job, binned, edges, yc, w, fmask_base,
                            Fcur, keys, dist: str, nclass: int, kwargs: dict,
                            p, valid=None) -> list:
        """Run the fused scan in watchdog-sized chunks, with per-tree metric
        series computed INSIDE the scan (train always; validation when a
        frame was given) — scoring history and ``stopping_rounds`` early
        stopping cost zero extra dispatches (reference: ``ScoreKeeper``
        between driver iterations; ``SharedTree.doScoringAndSaveModel``).
        On a stop the surplus chunk tail is discarded and the margins are
        replayed to the kept prefix, so the result is tree-for-tree
        identical to per-tree scoring."""
        M = keys.shape[0]
        sr = int(p.get("stopping_rounds") or 0)
        metric = str(p.get("stopping_metric") or "AUTO")
        # h2o-py sends enum values lowercase
        metric = {m.lower(): m for m in self.STOPPING_METRICS}.get(
            metric.lower(), metric)
        if metric not in self.STOPPING_METRICS:
            raise ValueError(f"unsupported stopping_metric {metric!r}; have "
                             f"{self.STOPPING_METRICS}")
        # validate metric/distribution compatibility up front (the device
        # tracker assumes a classification margin for AUC/logloss/misclass)
        sdist = "multinomial" if nclass > 1 else dist
        if metric in ("logloss", "misclassification", "AUC") and sdist not in (
                "bernoulli", "multinomial"):
            raise ValueError(f"stopping_metric={metric!r} requires a "
                             "classification distribution")
        if metric == "AUC" and sdist == "multinomial":
            raise ValueError("stopping_metric='AUC' requires a binomial "
                             "response")
        out_trees: list = []
        tser: list[float] = []
        vser: list[float] = []

        def collect(heap_h, count):
            if nclass > 1:
                return [[_trees_from_stacked(heap_h, m, k)
                         for k in range(nclass)] for m in range(count)]
            return [_trees_from_stacked(heap_h, m) for m in range(count)]

        # cap rows*trees per dispatch: a single fused program running
        # >~90s trips the device/tunnel watchdog (observed at HIGGS-11M
        # x 20 trees); ~1.5e8 rows*trees ≈ 60s on v5e at 64 bins, and
        # histogram cost scales with bins. The inter-chunk host hop
        # costs ~40ms — noise against a multi-second chunk. The 25-tree
        # ceiling decouples the program shape from large ntrees: the common
        # AutoML values (50, 100, 200 trees) all balance to 25-tree chunks
        # and share one compile per (depth, bins) config; other ntrees get
        # waste-free balanced chunks (per = ceil(M/k)) at the cost of their
        # own shape. `trees_per_dispatch` overrides the auto sizing (an
        # upper bound per compiled program — balanced chunking below may
        # round it down to avoid padded surplus trees).
        tpd = int(p.get("trees_per_dispatch") or 0)
        if tpd < 0:
            raise ValueError("trees_per_dispatch must be >= 0 (0 = auto)")
        if tpd > 0:
            per = max(1, min(tpd, max(M, 1)))
        else:
            cost = max(binned.shape[0], 1) * max(int(kwargs["n_bins"]), 64) // 64
            per = max(1, min(int(1.5e8 // cost), 25))
            if sr > 0:
                # bound the discarded overshoot past the stopping point; ≥16
                # trees per chunk keeps the dispatch count low (each chunk
                # pays a host round-trip for the stopping decision)
                per = min(per, max(4 * sr, 16))
        # balanced chunks: ceil(M/k) for k = chunk count. Padding then wastes
        # at most k-1 trees per train instead of up to per-1 (a 20-tree run
        # with per=13 must grow 2x10, not 13 + a padded 7->13)
        k_chunks = max(1, -(-M // per))
        per = -(-M // k_chunks)
        tol = float(p.get("stopping_tolerance") or 1e-3)
        lr = float(kwargs["lr"])
        nbins = int(kwargs["n_bins"])
        best, since = np.inf, 0
        chunks = 0
        # auto-checkpoint plumbing (docs/RELIABILITY.md): the fit installed
        # a partial-model constructor when auto_recovery_dir is set; every
        # ckpt_every grown trees the partial ensemble lands on disk through
        # the SAME artifact format checkpoint= resume consumes
        recovery = getattr(self, "_build_recovery", None)
        partial_fn = getattr(self, "_partial_model_fn", None)
        from h2o3_tpu.persist.recovery import checkpoint_every
        ckpt_every = checkpoint_every()
        last_snap = 0
        deadline_stop = False
        from h2o3_tpu.ops.map_reduce import retrying
        for s0 in range(0, M, per):
            if job.should_stop:
                # cooperative deadline/cancel between chunks: built trees
                # are KEPT — the model returns partial, the job CANCELLED
                deadline_stop = True
                job.keep_partial()
                break
            kchunk = keys[s0:s0 + per]
            take = kchunk.shape[0]
            if take < per and per <= M:
                # pad the final partial chunk to the compiled chunk shape:
                # the surplus trees are grown then discarded (keep cap below)
                # — one margin replay is far cheaper than a second ~30-40s
                # XLA compile of an odd-shaped program
                reps = np.concatenate([np.arange(take),
                                       np.full(per - take, take - 1)])
                kchunk = kchunk[reps]
            F_prev = Fcur

            def _chunk():
                Fc, heap, extras, Fv = _boost_scan(
                    binned, edges, yc, w, fmask_base, F_prev, kchunk,
                    track=metric, val=valid, **kwargs)
                # ONE batched host transfer per chunk (tunnel round-trips
                # are ~40ms each; per-leaf gets would pay a dozen of them);
                # the fetch feeds the host-side early-stopping decision —
                # and surfaces any async dispatch error INSIDE the retry
                # scope
                hh, eh = jax.device_get(  # graftlint: ok(batched chunk fetch)
                    (heap, extras))
                return Fc, hh, eh, Fv

            with timed_event("tree", f"{self.algo}:chunk",
                             observe=_tm.ITER_SECONDS.labels(
                                 loop=f"{self.algo}_chunk")):
                # transient dispatch failures (injected drops, transient
                # runtime errors) retry with backoff instead of killing the
                # build; the chunk is functional over F_prev so a re-run is
                # exact
                Fcur, heap_h, extras_h, Fvend = retrying(
                    f"{self.algo}_chunk", _chunk)
            chunks += 1
            heap_h = jax.tree.map(np.asarray, heap_h)
            new_trees = collect(heap_h, take)
            ts = np.asarray(extras_h[0], np.float64)[:take]
            vs = (np.asarray(extras_h[1], np.float64)[:take]
                  if len(extras_h) > 1 else None)
            if valid is not None:
                valid = (valid[0], valid[1], valid[2], Fvend)
            series = vs if vs is not None else ts
            stop_at = None
            if sr > 0:
                for j, dev in enumerate(series):
                    # sign-safe relative improvement: deviances can be < 0
                    if dev < best - tol * abs(best) or not np.isfinite(best):
                        best, since = dev, 0
                    else:
                        since += 1
                        if since >= sr:
                            stop_at = j
                            break
            keep = take if stop_at is None else stop_at + 1
            out_trees.extend(new_trees[:keep])
            tser.extend(ts[:keep])
            if vs is not None:
                vser.extend(vs[:keep])
            shown = -series[keep - 1] if metric == "AUC" else series[keep - 1]
            try:
                job.update(0.1 + 0.8 * min(s0 + keep, M) / M,
                           f"{len(out_trees)}/{M} trees, {metric} {shown:.5f}")
            except JobCancelled:
                # deadline/cancel tripped inside update: this algorithm
                # keeps partial results, so swallow the cooperative raise
                # and stop growing — the job still terminates CANCELLED
                deadline_stop = True
                job.keep_partial()
            if recovery is not None and partial_fn is not None and \
                    len(out_trees) - last_snap >= ckpt_every:
                pm = partial_fn(list(out_trees))
                # progress counts TOTAL ensemble trees (prior checkpoint
                # included) against the params target, so a resume-of-a-
                # resume keeps its arithmetic straight
                recovery.snapshot(pm, progress=int(pm.output["ntrees"]),
                                  target=int(p["ntrees"]))
                last_snap = len(out_trees)
            if keep < kchunk.shape[0] and not kwargs.get("drf"):
                # the scan's margins include discarded trees (mid-chunk stop
                # or chunk padding) — replay to the kept prefix; one cheap
                # dispatch
                kept = new_trees[:keep]
                if nclass > 1:
                    Fcur = F_prev + lr * jnp.stack(
                        [predict_binned(binned, [t[k] for t in kept], nbins)
                         for k in range(nclass)], axis=1)
                else:
                    Fcur = F_prev + lr * predict_binned(binned, kept, nbins)
            if stop_at is not None or deadline_stop:
                break
        if deadline_stop and recovery is not None and partial_fn is not None \
                and len(out_trees) > last_snap:
            # deadline-cancelled builds stay resumable from exactly where
            # they stopped (train() keeps the snapshot on CANCELLED)
            pm = partial_fn(list(out_trees))
            recovery.snapshot(pm, progress=int(pm.output["ntrees"]),
                              target=int(p["ntrees"]))
        self._score_series = (metric, tser, vser if vser else None)
        # dispatch economy: ONE host sync (the stopping/heap fetch) per
        # `trees_per_dispatch`-sized chunk, not per boosting round
        publish_dispatch_audit(self, f"{self.algo}_round",
                               iterations=max(len(out_trees), 1),
                               host_syncs=chunks, device_dispatches=chunks)
        return out_trees, Fcur

    def _fit_multinomial(self, job: Job, frame, x, y, w, yc, yvec,
                         X, edges, binned, domains, cp=None) -> GBMModel:
        """K one-vs-rest trees per round on softmax gradients (reference:
        GBM.java multinomial — one DTree per class per iteration)."""
        p = self.params
        self._check_checkpoint(cp, x, "multinomial")
        K = yvec.cardinality()
        if cp is not None:
            f0 = np.asarray(cp.output["f0_multi"], np.float32)
        else:
            yoh = jax.nn.one_hot(yc.astype(jnp.int32), K) * w[:, None]
            prior = np.asarray(jax.device_get(yoh.sum(axis=0)), np.float64)
            prior = np.maximum(prior / max(prior.sum(), 1e-30), 1e-10)
            f0 = np.log(prior).astype(np.float32)

        lr = float(p["learn_rate"])
        seed = int(p["seed"]) if int(p["seed"]) >= 0 else 42
        key = jax.random.PRNGKey(seed)
        Fcur = jnp.broadcast_to(jnp.asarray(f0)[None, :],
                                (binned.shape[0], K)).astype(jnp.float32)
        trees_multi: list[list[Tree]] = [[] for _ in range(K)]
        done = 0
        if cp is not None:
            trees_multi = [list(ts) for ts in cp.output["trees_multi"]]
            done = len(trees_multi[0])
            # per-class sequential fold matches the scan's per-round
            # accumulation order exactly (see the single-class path)
            Fcur = jnp.stack(
                [fold_binned(binned, ts, int(p["nbins"]), lr, Fcur[:, ki])
                 for ki, ts in enumerate(trees_multi)], axis=1)
        ntrees = int(p["ntrees"])
        keys = jax.random.split(key, ntrees * 3).reshape(ntrees, 3, 2)[done:]
        job.update(0.1, f"growing {(ntrees - done) * K} trees (one fused program)")
        kwargs = dict(
            dist="multinomial", depth=int(p["max_depth"]),
            n_bins=int(p["nbins"]), col_rate=self._effective_col_rate(),
            sample_rate=float(p["sample_rate"]),
            col_tree_rate=float(p["col_sample_rate_per_tree"]),
            min_rows=float(p["min_rows"]), reg_lambda=float(p["reg_lambda"]),
            reg_alpha=float(p.get("reg_alpha", 0.0)),
            gamma=float(p.get("gamma", 0.0)),
            min_split_improvement=float(p["min_split_improvement"]), lr=lr,
            bootstrap=False, drf=False, nclass=K)
        if self.params.get("monotone_constraints"):
            raise ValueError("monotone_constraints are not supported for "
                             "multinomial distributions (reference: GBM.java)")
        _, reach = self._constraint_arrays(x, frame)
        kwargs.update(mono=None, reach=reach, cat_feats=self._cat_feats)
        valid = None
        if getattr(self, "_validation_frame", None) is not None or \
                int(p.get("stopping_rounds") or 0) > 0:
            valid = self._valid_stop_data(
                edges, K, f0, lr, domains, yvec.domain,
                prior_trees=trees_multi if done else None)
        self._partial_model_fn = None
        if getattr(self, "_build_recovery", None) is not None:
            def _partial(rounds_grown: list) -> GBMModel:
                tm = [list(ts) for ts in trees_multi]
                for per_class in rounds_grown:
                    for k in range(K):
                        tm[k].append(per_class[k])
                pm = GBMModel(
                    key=f"{self.model_id or self.algo}_autockpt",
                    params=self.params, data_info=None, response_column=y,
                    response_domain=yvec.domain,
                    output=dict(trees_multi=tm, edges=edges, f0_multi=f0,
                                learn_rate=lr, distribution="multinomial",
                                x_cols=list(x), feat_domains=domains,
                                ntrees=len(tm[0]), **self._cat_output()))
                return self._retag_model(pm)
            self._partial_model_fn = _partial
        rounds, Fend = self._grow_with_stopping(job, binned, edges, yc, w,
                                                jnp.ones(binned.shape[1], bool),
                                                Fcur, keys, "multinomial", K,
                                                kwargs, p, valid=valid)
        self._partial_model_fn = None
        for per_class in rounds:
            for k in range(K):
                trees_multi[k].append(per_class[k])
        job.update(0.9, f"{len(rounds) * K} trees grown")
        self._last_train_raw = jax.nn.softmax(Fend, axis=1)

        return GBMModel(
            key=make_model_key(self.algo, self.model_id),
            params=self.params, data_info=None, response_column=y,
            response_domain=yvec.domain,
            output=dict(trees_multi=trees_multi, edges=edges, f0_multi=f0,
                        learn_rate=lr, distribution="multinomial",
                        x_cols=list(x), feat_domains=domains, ntrees=ntrees,
                        **self._cat_output()),
        )


class DRFModel(SharedTreeModel):
    algo = "drf"

    def _contrib_scale_bias(self):
        return 1.0 / max(self.output["ntrees"], 1), 0.0

    def _score_raw(self, frame: Frame) -> jax.Array:
        if self.output.get("trees_multi") is not None:
            probs = jnp.clip(self._tree_raw_sum_per_class(frame)
                             / max(self.output["ntrees"], 1), 0.0, 1.0)
            return probs / jnp.maximum(probs.sum(axis=1, keepdims=True), 1e-30)
        mean = self._tree_raw_sum(frame) / max(self.output["ntrees"], 1)
        if self.output["binomial"]:
            pmean = jnp.clip(mean, 0.0, 1.0)
            return jnp.stack([1 - pmean, pmean], axis=1)
        return mean


class DRF(SharedTreeBuilder):
    """h2o-py surface: ``H2ORandomForestEstimator``.

    Reference: ``hex/tree/drf/DRF.java`` — bagged trees, mtries feature
    sampling, predictions averaged. Each tree fits the response directly
    (g=-y, h=1 → leaf = in-node mean)."""

    algo = "drf"

    @classmethod
    def defaults(cls) -> dict:
        d = dict(super().defaults(), mtries=-1)
        d["max_depth"] = 14
        d["min_rows"] = 1.0
        d["sample_rate"] = 0.632
        # reference DRF.java: binomial normally trains ONE tree per round
        # (complement trick); this opts into a tree per class like
        # multinomial (ktrees=2), normalized by vote sum
        d["binomial_double_trees"] = False
        return d

    def _fit(self, job: Job, frame: Frame, x, y, weights) -> DRFModel:
        p = self.params
        X, edges, binned, yy, valid, yvec, domains = self._prepare(
            frame, x, y, weights)
        cp = self._resolve_checkpoint()
        if cp is not None:
            self._check_checkpoint(cp, x, None)   # before the edges swap
            edges = cp.output["edges"]
            binned = self._bin_frame(frame, x, edges)
        classifier = yvec.is_categorical
        nclass = yvec.cardinality() if classifier else 0
        w = weights * valid
        yc = jnp.where(w > 0, yy, 0.0)

        X = None    # training reads only `binned`
        F = binned.shape[1]
        mtries = int(p["mtries"])
        if mtries <= 0:
            mtries = max(1, int(np.sqrt(F)) if classifier else max(F // 3, 1))
        seed = int(p["seed"]) if int(p["seed"]) >= 0 else 42
        key = jax.random.PRNGKey(seed)
        ntrees = int(p["ntrees"])
        fmask = jnp.ones(F, bool)

        if nclass > 2 or (nclass == 2 and p.get("binomial_double_trees")):
            # one class-indicator tree per class per round; leaf = in-node
            # class fraction (reference: DRF.java multinomial ktrees —
            # binomial_double_trees routes 2-class fits here too)
            trees_multi: list[list[Tree]] = [[] for _ in range(nclass)]
            done = 0
            if cp is not None:
                if cp.output.get("trees_multi") is None:
                    raise ValueError(
                        "checkpoint was trained without binomial_double_"
                        "trees; the tree layouts are incompatible")
                trees_multi = [list(ts) for ts in cp.output["trees_multi"]]
                done = len(trees_multi[0])
            keys = jax.random.split(key, ntrees * 3).reshape(ntrees, 3, 2)[done:]
            # deadline checkpoint: DRF grows the whole forest in ONE fused
            # program, so the budget is only observable at dispatch
            # boundaries — a deadline that already tripped cancels here,
            # before the program launches (docs/RELIABILITY.md)
            job.update(0.1, f"growing {(ntrees - done) * nclass} trees "
                            "(one fused program)")
            _, heap, _, _ = _boost_scan(
                binned, edges, yc, w, fmask,
                jnp.zeros((binned.shape[0], nclass), jnp.float32), keys,
                dist="multinomial", depth=int(p["max_depth"]),
                n_bins=int(p["nbins"]), col_rate=mtries / F,
                sample_rate=float(p["sample_rate"]), col_tree_rate=1.0,
                min_rows=float(p["min_rows"]), reg_lambda=0.0, reg_alpha=0.0,
                gamma=0.0,
                min_split_improvement=float(p["min_split_improvement"]),
                lr=1.0, bootstrap=True, drf=True, nclass=nclass,
                cat_feats=self._cat_feats)
            heap = _heap_to_host(heap)
            for m in range(ntrees - done):
                for k in range(nclass):
                    trees_multi[k].append(_trees_from_stacked(heap, m, k))
            try:
                job.update(0.9, f"{ntrees * nclass} trees grown")
            except JobCancelled:
                # deadline tripped while the program ran: the forest is
                # already complete — keep it (job still reads CANCELLED)
                job.keep_partial()
            return DRFModel(
                key=make_model_key(self.algo, self.model_id),
                params=self.params, data_info=None, response_column=y,
                response_domain=yvec.domain,
                output=dict(trees_multi=trees_multi, edges=edges, ntrees=ntrees,
                            binomial=False, x_cols=list(x), feat_domains=domains,
                            f0=0.0, learn_rate=1.0, distribution="multinomial",
                            **self._cat_output()),
            )

        trees: list[Tree] = []
        if cp is not None:
            if cp.output.get("trees") is None:
                # the reverse of the guard above: a double-trees (or
                # multinomial-layout) checkpoint cannot continue as a
                # single-tree forest — refusing beats silently dropping
                # every checkpointed tree
                raise ValueError(
                    "checkpoint was trained with binomial_double_trees; "
                    "the tree layouts are incompatible")
            trees = list(cp.output["trees"])
        done = len(trees)
        keys = jax.random.split(key, ntrees * 3).reshape(ntrees, 3, 2)[done:]
        # deadline checkpoint at the dispatch boundary (see the multinomial
        # branch above): cancel BEFORE the fused forest program launches
        job.update(0.1, f"growing {ntrees - done} trees (one fused program)")
        _, heap, _, _ = _boost_scan(
            binned, edges, yc, w, fmask,
            jnp.zeros(binned.shape[0], jnp.float32), keys,
            dist="gaussian", depth=int(p["max_depth"]), n_bins=int(p["nbins"]),
            col_rate=mtries / F, sample_rate=float(p["sample_rate"]),
            col_tree_rate=1.0, min_rows=float(p["min_rows"]), reg_lambda=0.0,
            reg_alpha=0.0, gamma=0.0,
            min_split_improvement=float(p["min_split_improvement"]),
            lr=1.0, bootstrap=True, drf=True, nclass=0,
            cat_feats=self._cat_feats)
        heap = _heap_to_host(heap)
        trees += [_trees_from_stacked(heap, m) for m in range(ntrees - done)]
        try:
            job.update(0.9, f"{len(trees)} trees grown")
        except JobCancelled:
            # forest is complete by the time the deadline is observable —
            # keep it; the job still terminates CANCELLED
            job.keep_partial()

        model = DRFModel(
            key=make_model_key(self.algo, self.model_id),
            params=self.params, data_info=None, response_column=y,
            response_domain=yvec.domain if classifier else None,
            output=dict(trees=trees, edges=edges, ntrees=len(trees),
                        binomial=classifier, x_cols=list(x), feat_domains=domains,
                        f0=0.0, learn_rate=1.0, distribution="gaussian",
                        **self._cat_output()),
        )
        self._maybe_calibrate(model)
        return model
