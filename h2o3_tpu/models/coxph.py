"""Cox proportional hazards.

Reference: ``hex/coxph/CoxPH.java`` (~2 kLoC): per-iteration MRTask
(``CoxPHTask``) accumulates risk-set sums, gradient and Hessian of the partial
log-likelihood across the cloud; Newton updates with step-halving on the
leader; Efron or Breslow handling of tied event times.

TPU-native redesign: rows are sorted by stop time once, so every risk set is a
suffix — risk-set accumulation is a single reversed ``cumsum`` over the sorted
exp(Xβ) column, and tie groups are ``segment_sum``s keyed by unique event
time. The partial log-likelihood is therefore one closed-form jitted program
of β, and the gradient/Hessian the reference hand-accumulates come from
``jax.grad``/``jax.hessian`` of that program (exact, XLA-fused). The whole
Newton solve stays on device except the tiny [P,P] solve.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.types import VecType
from h2o3_tpu.frame.vec import Vec
from h2o3_tpu.models.data_info import DataInfo, response_as_float
from h2o3_tpu.models.job import Job
from h2o3_tpu.models.model_base import Model, ModelBuilder, make_model_key


@partial(jax.jit, static_argnames=("n_groups", "efron"))
def _cox_loglik(beta, X, event, w, group, tie_rank, tie_tot, n_groups: int,
                efron: bool):
    """Partial log-likelihood; rows pre-sorted by stop time DESCENDING so the
    risk set of any time is a prefix — risk sums are plain cumsums.

    group: tie-group id per row (0 = latest time); tie_rank/tie_tot: this
    event's 0-based rank among its group's events and the group's event count
    (for the Efron correction).
    """
    xb = X @ beta
    exb = w * jnp.exp(xb)
    risk = jnp.cumsum(exb)                                   # suffix sums in time
    # risk sum at each group's time = cumsum value at the group's LAST row
    grp_risk = jax.ops.segment_max(risk, group, num_segments=n_groups)
    de = w * event
    tied_exb = jax.ops.segment_sum(exb * event, group, num_segments=n_groups)
    if efron:
        denom = grp_risk[group] - (tie_rank / jnp.maximum(tie_tot, 1.0)) \
            * tied_exb[group]
    else:
        denom = grp_risk[group]
    return (de * (xb - jnp.log(jnp.maximum(denom, 1e-300)))).sum()


class CoxPHModel(Model):
    algo = "coxph"

    def _score_raw(self, frame: Frame) -> jax.Array:
        """Linear predictor lp = (x - x̄)·β (reference: CoxPH scoring emits lp)."""
        X = self.data_info.expand(frame)
        mu = jnp.asarray(self.output["x_mean"], jnp.float32)
        return (X - mu[None, :]) @ self.output["coef"]

    def predict(self, frame: Frame) -> Frame:
        lp = self._score_raw(frame)
        return Frame(["lp"], [Vec.from_device(lp, frame.nrows, VecType.NUM)])

    def model_performance(self, frame: Frame):
        return None

    def concordance(self, frame: Frame | None = None) -> float:
        """Harrell's concordance index (reference: ``hex/coxph/
        CoxPH.java:737`` — the fraction of comparable pairs where the higher
        linear predictor has the shorter survival; ties in lp count 0.5).
        Comparable pair: (i, j) with t_i < t_j and event_i = 1. Computed in
        O(n log n) with a Fenwick tree over lp ranks."""
        if frame is not None:
            lp = np.asarray(jax.device_get(self._score_raw(frame)),
                            np.float64)[: frame.nrows]
            t = np.asarray(jax.device_get(
                frame.vec(self.params["stop_column"]).as_float()),
                np.float64)[: frame.nrows]
            from h2o3_tpu.models.data_info import response_as_float
            ev, okv = response_as_float(frame.vec(self.response_column))
            e = np.asarray(jax.device_get(ev), np.float64)[: frame.nrows]
            ok = (np.asarray(jax.device_get(okv), bool)[: frame.nrows]
                  & np.isfinite(t) & np.isfinite(lp))
            lp, t, e = lp[ok], t[ok], e[ok]
        else:
            lp = np.asarray(self.output["train_lp"], np.float64)
            t = np.asarray(self.output["train_time"], np.float64)
            e = np.asarray(self.output["train_event"], np.float64)
        n = len(t)
        if n < 2:
            return float("nan")
        # process rows in time order; for each EVENT row, every later-time
        # row is comparable: count how its lp ranks against them
        ranks = np.searchsorted(np.sort(np.unique(lp)), lp)
        R = ranks.max() + 1
        order = np.argsort(t, kind="stable")
        conc = disc = tied = 0.0
        bit = np.zeros(R + 1)          # Fenwick counts of lp-ranks seen

        def bit_add(i):
            i += 1
            while i <= R:
                bit[i] += 1
                i += i & (-i)

        def bit_sum(i):                # count of ranks <= i
            i += 1
            s = 0.0
            while i > 0:
                s += bit[i]
                i -= i & (-i)
            return s

        # iterate times DESCENDING, inserting rows into the tree; an event
        # at time t is compared against all strictly-later rows (already
        # inserted). Tied times are flushed in blocks so same-time pairs
        # are never compared.
        i = n - 1
        total = 0
        while i >= 0:
            j = i
            while j >= 0 and t[order[j]] == t[order[i]]:
                j -= 1
            for k in range(i, j, -1):      # the tied-time block
                r = order[k]
                if e[r] > 0:
                    later = total
                    if later:
                        lower = bit_sum(ranks[r] - 1) if ranks[r] > 0 else 0.0
                        at = bit_sum(ranks[r]) - lower
                        conc += lower            # later row with LOWER lp
                        tied += at
                        disc += later - lower - at
            for k in range(i, j, -1):
                bit_add(ranks[order[k]])
                total += 1
            i = j
        pairs = conc + disc + tied
        return float((conc + 0.5 * tied) / pairs) if pairs else float("nan")

    def coefficients(self) -> dict[str, float]:
        names = self.output["coef_names"]
        return dict(zip(names, np.asarray(self.output["coef"]).tolist()))

    def hazard_ratios(self) -> dict[str, float]:
        return {k: float(np.exp(v)) for k, v in self.coefficients().items()}

    def baseline_hazard(self) -> Frame:
        """Breslow cumulative baseline hazard H0(t) at the covariate mean
        (reference: CoxPHModel baseline hazard table / R ``survfit``)."""
        t = self.output["baseline_times"]
        h = self.output["baseline_cumhaz"]
        return Frame(["t", "cumhaz"],
                     [Vec.from_numpy(np.asarray(t, np.float32)),
                      Vec.from_numpy(np.asarray(h, np.float32))])

    def predict_survival(self, frame: Frame, times) -> Frame:
        """S(t | x) = exp(-H0(t) · exp(lp)) per row for each requested time
        (the survfit curve evaluated on new data)."""
        lp = np.asarray(jax.device_get(self._score_raw(frame)))[: frame.nrows]
        bt = np.asarray(self.output["baseline_times"])
        bh = np.asarray(self.output["baseline_cumhaz"])
        names, vecs = [], []
        for t in np.atleast_1d(times):
            idx = np.searchsorted(bt, float(t), side="right") - 1
            h0 = bh[idx] if idx >= 0 else 0.0
            s = np.exp(-h0 * np.exp(lp))
            names.append(f"S_{t:g}")
            vecs.append(Vec.from_numpy(s.astype(np.float32)))
        return Frame(names, vecs)


class CoxPH(ModelBuilder):
    """h2o-py surface: ``H2OCoxProportionalHazardsEstimator``."""

    algo = "coxph"
    supports_classification = False

    @classmethod
    def defaults(cls) -> dict:
        return dict(
            super().defaults(),
            stop_column=None,      # event-time column (required)
            ties="efron",          # efron | breslow
            max_iterations=20,
            lre=9.0,               # log-relative-error convergence (reference)
        )

    def train(self, x=None, y=None, training_frame=None, **kw):
        # y is the event (0/1) column; stop_column carries the time
        if self.params.get("stop_column") is None:
            raise ValueError("stop_column (event time) is required")
        saved = self.params.get("ignored_columns")
        self.params["ignored_columns"] = list(saved or []) + [self.params["stop_column"]]
        try:
            return super().train(x=x, y=y, training_frame=training_frame, **kw)
        finally:
            self.params["ignored_columns"] = saved

    def _fit(self, job: Job, frame: Frame, x, y, weights) -> CoxPHModel:
        p = self.params
        t_vec = frame.vec(p["stop_column"])
        times = np.asarray(jax.device_get(t_vec.as_float()))
        evt, evt_valid = response_as_float(frame.vec(y))
        di = DataInfo.make(frame, x, standardize=False)
        X = di.expand(frame)
        P = X.shape[1]

        w = weights * evt_valid * ~jnp.isnan(jnp.asarray(times))
        wh = np.asarray(jax.device_get(w))
        keep = np.nonzero(wh > 0)[0]
        if keep.size == 0:
            raise ValueError("no usable rows")
        # sort kept rows by time DESCENDING (risk sets become prefixes)
        order = keep[np.argsort(-times[keep], kind="stable")]
        ts = times[order]
        Xs = jnp.asarray(np.asarray(jax.device_get(X))[order])
        es = jnp.asarray(np.asarray(jax.device_get(jnp.where(w > 0, evt, 0.0)))[order])
        ws = jnp.asarray(wh[order])

        # tie groups over unique times (descending); Efron rank among events
        _, group = np.unique(-ts, return_inverse=True)
        eh = np.asarray(jax.device_get(es))
        tie_rank = np.zeros(len(ts), np.float32)
        tie_tot = np.zeros(len(ts), np.float32)
        for g in range(group.max() + 1):
            sel = (group == g) & (eh > 0)
            d = int(sel.sum())
            if d:
                tie_rank[sel] = np.arange(d, dtype=np.float32)
                tie_tot[sel] = float(d)
        n_groups = int(group.max()) + 1
        group_j = jnp.asarray(group.astype(np.int32))
        tie_rank_j, tie_tot_j = jnp.asarray(tie_rank), jnp.asarray(tie_tot)
        efron = str(p["ties"]).lower() == "efron"

        ll = lambda b: _cox_loglik(b, Xs, es, ws, group_j, tie_rank_j, tie_tot_j,
                                   n_groups, efron)

        # named defs so the executables are attributable in profiler
        # captures and the cost registry (graftlint PRF001)
        @jax.jit
        def coxph_grad(b):
            return jax.grad(ll)(b)

        @jax.jit
        def coxph_hessian(b):
            return jax.hessian(ll)(b)

        grad_f, hess_f = coxph_grad, coxph_hessian

        beta = jnp.zeros(P, jnp.float32)
        ll_prev = float(jax.device_get(ll(beta)))
        iters = 0
        for it in range(max(int(p["max_iterations"]), 1)):
            g = np.asarray(jax.device_get(grad_f(beta)), np.float64)
            H = np.asarray(jax.device_get(hess_f(beta)), np.float64)
            try:
                step = np.linalg.solve(H - 1e-9 * np.eye(P), g)
            except np.linalg.LinAlgError:
                step = np.linalg.lstsq(H, g, rcond=None)[0]
            # Newton with step-halving (reference: CoxPH.java step halving loop)
            for _ in range(10):
                cand = beta - jnp.asarray(step, jnp.float32)
                ll_new = float(jax.device_get(ll(cand)))
                if np.isfinite(ll_new) and ll_new >= ll_prev - 1e-12:
                    break
                step = step * 0.5
            beta = cand
            iters = it + 1
            job.update(iters / max(int(p["max_iterations"]), 1),
                       f"iter {iters} loglik {ll_new:.6f}")
            if abs(ll_new - ll_prev) <= 10.0 ** (-float(p["lre"])) * max(abs(ll_prev), 1.0):
                ll_prev = ll_new
                break
            ll_prev = ll_new

        H = np.asarray(jax.device_get(hess_f(beta)), np.float64)
        try:
            cov = np.linalg.inv(-H)
            se = np.sqrt(np.maximum(np.diag(cov), 0.0))
        except np.linalg.LinAlgError:
            se = np.full(P, np.nan)
        x_mean = np.asarray(jax.device_get(
            (ws[:, None] * Xs).sum(axis=0) / jnp.maximum(ws.sum(), 1e-30)))

        # Breslow cumulative baseline hazard at the (centered) covariate mean
        # (reference: CoxPH.java baseline hazard output / R survfit):
        # dH0(t) = sum(w_i : event at t) / sum(w_j exp((x_j - xbar)β) : t_j >= t)
        rs = np.asarray(jax.device_get(
            jnp.exp((Xs - jnp.asarray(x_mean)[None, :]) @ beta))) * np.asarray(
            jax.device_get(ws))
        wh_events = np.asarray(jax.device_get(es * ws))
        # ts is DESCENDING → risk set at time t is the prefix through t's group
        risk_prefix = np.cumsum(rs)
        # `group` (tie groups, 0 = largest time) is non-decreasing because ts
        # is sorted descending, so group boundaries come straight from unique
        _, first = np.unique(-ts, return_index=True)
        last = np.append(first[1:] - 1, len(ts) - 1)
        d = np.bincount(group, weights=wh_events, minlength=n_groups)
        denom = risk_prefix[last]
        inc = np.where((d > 0) & (denom > 0), d / np.maximum(denom, 1e-30), 0.0)
        bh_t = ts[first][::-1]                         # ascending time
        bh_h = np.cumsum(inc[::-1])

        train_lp = np.asarray(jax.device_get(
            (Xs - jnp.asarray(x_mean)[None, :]) @ beta), np.float64)
        return CoxPHModel(
            key=make_model_key(self.algo, self.model_id),
            params=self.params, data_info=di, response_column=y,
            response_domain=None,
            output=dict(coef=beta, se_coef=se, loglik=ll_prev, iterations=iters,
                        coef_names=di.coef_names, x_mean=x_mean,
                        baseline_times=np.asarray(bh_t, np.float64),
                        baseline_cumhaz=np.asarray(bh_h, np.float64),
                        n=int(keep.size), n_events=int(eh.sum()),
                        # training triplet for the concordance statistic
                        # (CoxPH.java:737); sorted by descending time
                        train_lp=train_lp,
                        train_time=np.asarray(ts, np.float64),
                        train_event=np.asarray(jax.device_get(es), np.float64)),
        )
