"""Word2Vec — word embeddings from tokenized text columns.

Reference: ``hex/word2vec/Word2Vec.java`` (SkipGram + hierarchical softmax,
``WordVectorTrainer.java:114-168`` distributed SGD over chunk-local windows;
vocab build ``WordCountTask``), plus the h2o-py surface
(``H2OWord2vecEstimator``: train on a string column, ``find_synonyms``,
``transform(aggregate_method="AVERAGE")``).

TPU-native redesign: the default objective is **skip-gram with negative
sampling** — every step is a [batch] gather of center/context/negative
embedding rows, a batched dot product, and a scatter-add update, fused by XLA
into MXU-friendly programs (Mikolov et al. report SGNS quality ≥ HS at lower
cost). The reference's **hierarchical softmax** is also available
(``objective="hsm"``): the per-word variable-length Huffman walk is made
fixed-shape by padding every path to the tree depth with a mask, so the HSM
update compiles to the same single fused ``lax.scan``. Window-pair
generation is a one-time host pass over the (host-resident) string column;
the SGD epochs run entirely on device over shuffled minibatches.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.types import VecType
from h2o3_tpu.frame.vec import Vec
from h2o3_tpu.models.job import Job
from h2o3_tpu.models.model_base import Model, ModelBuilder, make_model_key


@partial(jax.jit, static_argnames=("n_neg",), donate_argnums=(0, 1))
def _sgns_epoch(Wc, Wx, centers, contexts, noise_cdf, key, lr, n_neg: int):
    """One epoch of skip-gram negative-sampling SGD over minibatches.

    Wc: [V, D] center embeddings; Wx: [V, D] context embeddings.
    centers/contexts: [nb, B] int32 pair minibatches; noise_cdf: [V] cumulative
    unigram^0.75 noise distribution (Mikolov et al. SGNS).
    """

    def step(carry, batch):
        Wc, Wx, key = carry
        c, x = batch
        key, nk = jax.random.split(key)
        u = jax.random.uniform(nk, (c.shape[0], n_neg))
        neg = jnp.searchsorted(noise_cdf, u).astype(jnp.int32)
        vc = Wc[c]                                  # [B, D]
        ux = Wx[x]                                  # [B, D]
        un = Wx[neg]                                # [B, n_neg, D]
        s_pos = jax.nn.sigmoid(jnp.einsum("bd,bd->b", vc, ux))
        s_neg = jax.nn.sigmoid(jnp.einsum("bd,bnd->bn", vc, un))
        g_pos = s_pos - 1.0                          # d/ds of -log sigmoid
        d_vc = g_pos[:, None] * ux + jnp.einsum("bn,bnd->bd", s_neg, un)
        d_ux = g_pos[:, None] * vc
        d_un = s_neg[..., None] * vc[:, None, :]
        Wc = Wc.at[c].add(-lr * d_vc)
        Wx = Wx.at[x].add(-lr * d_ux)
        Wx = Wx.at[neg.reshape(-1)].add(-lr * d_un.reshape(-1, Wc.shape[1]))
        return (Wc, Wx, key), None

    (Wc, Wx, _), _ = jax.lax.scan(step, (Wc, Wx, key), (centers, contexts))
    return Wc, Wx


def _huffman_paths(freqs: np.ndarray):
    """Huffman tree over the vocab (reference ``buildHuffmanBinaryTree``):
    per word, the inner-node index path and the binary code, padded to the
    tree's max depth so the HSM update has a fixed shape.

    Returns (nodes [V, L] int32, codes [V, L] f32, mask [V, L] f32)."""
    import heapq

    V = len(freqs)
    heap = [(float(f), i) for i, f in enumerate(freqs)]
    heapq.heapify(heap)
    parent = np.full(2 * V - 1, -1, np.int64)
    bit = np.zeros(2 * V - 1, np.int8)
    nxt = V
    while len(heap) > 1:
        f1, a = heapq.heappop(heap)
        f2, b = heapq.heappop(heap)
        parent[a], parent[b] = nxt, nxt
        bit[b] = 1
        heapq.heappush(heap, (f1 + f2, nxt))
        nxt += 1
    paths, codes = [], []
    for w in range(V):
        p, c, n = [], [], w
        while parent[n] >= 0:
            p.append(parent[n] - V)       # inner-node id in [0, V-1)
            c.append(float(bit[n]))
            n = parent[n]
        paths.append(p[::-1])
        codes.append(c[::-1])
    L = max(len(p) for p in paths)
    nodes = np.zeros((V, L), np.int32)
    code = np.zeros((V, L), np.float32)
    mask = np.zeros((V, L), np.float32)
    for w in range(V):
        k = len(paths[w])
        nodes[w, :k] = paths[w]
        code[w, :k] = codes[w]
        mask[w, :k] = 1.0
    return nodes, code, mask


@partial(jax.jit, donate_argnums=(0, 1))
def _hsm_epoch(Wc, Wn, centers, contexts, nodes, codes, mask, lr):
    """One epoch of skip-gram + hierarchical softmax SGD — the reference's
    objective (``WordVectorTrainer.java:114-168``), reshaped for XLA: each
    (center, context) pair updates the context word's Huffman path (padded
    to fixed length L, masked), so the whole epoch is one fused lax.scan.

    Wc: [V, D] word embeddings; Wn: [V-1, D] inner-node vectors."""

    def step(carry, batch):
        Wc, Wn = carry
        c, x = batch
        pn = nodes[x]                               # [B, L]
        pc = codes[x]                               # [B, L]
        pm = mask[x]                                # [B, L]
        vc = Wc[c]                                  # [B, D]
        un = Wn[pn]                                 # [B, L, D]
        s = jax.nn.sigmoid(jnp.einsum("bd,bld->bl", vc, un))
        g = (s - pc) * pm                           # d/dθ of -log p(code)
        d_vc = jnp.einsum("bl,bld->bd", g, un)
        d_un = g[..., None] * vc[:, None, :]
        Wc = Wc.at[c].add(-lr * d_vc)
        Wn = Wn.at[pn.reshape(-1)].add(-lr * d_un.reshape(-1, Wc.shape[1]))
        return (Wc, Wn), None

    (Wc, Wn), _ = jax.lax.scan(step, (Wc, Wn), (centers, contexts))
    return Wc, Wn


class Word2VecModel(Model):
    algo = "word2vec"

    def find_synonyms(self, word: str, count: int = 20) -> dict[str, float]:
        """Nearest words by cosine similarity (reference: /3/Word2VecSynonyms)."""
        vocab = self.output["vocab"]
        if word not in self.output["word_index"]:
            return {}
        W = self.output["vectors"]
        i = self.output["word_index"][word]
        v = W[i]
        sims = np.asarray(jax.device_get(
            (W @ v) / (jnp.linalg.norm(W, axis=1) * jnp.linalg.norm(v) + 1e-12)))
        order = np.argsort(-sims)
        out = {}
        for j in order:
            if j == i:
                continue
            out[vocab[j]] = float(sims[j])
            if len(out) >= count:
                break
        return out

    def transform(self, frame: Frame, aggregate_method: str = "NONE") -> Frame:
        """Map a words column to vectors; AVERAGE aggregates per NA-delimited
        sequence (reference: Word2VecModel.transform AggregateMethod)."""
        col = frame.names[0]
        words = frame.vec(col).host_values
        idx = self.output["word_index"]
        W = np.asarray(jax.device_get(self.output["vectors"]))
        D = W.shape[1]
        if str(aggregate_method).upper() == "AVERAGE":
            # one row per NA-delimited sequence; a trailing NA closes the last
            # sequence (no spurious extra row — reference AggregateMethod)
            docs, acc, cnt, pending = [], np.zeros(D), 0, False
            for t in words:
                if t is None or (isinstance(t, float) and np.isnan(t)):
                    if pending:
                        docs.append(acc / cnt if cnt else np.full(D, np.nan))
                    acc, cnt, pending = np.zeros(D), 0, False
                else:
                    pending = True
                    if str(t) in idx:
                        acc = acc + W[idx[str(t)]]
                        cnt += 1
            if pending:
                docs.append(acc / cnt if cnt else np.full(D, np.nan))
            M = np.stack(docs)
        else:
            M = np.stack([W[idx[str(t)]] if (t is not None and str(t) in idx)
                          else np.full(D, np.nan) for t in words])
        return Frame([f"C{i+1}" for i in range(D)],
                     [Vec.from_numpy(M[:, i], VecType.NUM) for i in range(D)])

    def to_frame(self) -> Frame:
        """Word ↔ vector table (reference: Word2VecModel.toFrame)."""
        W = np.asarray(jax.device_get(self.output["vectors"]))
        cols = {"Word": np.array(self.output["vocab"], dtype=object)}
        for i in range(W.shape[1]):
            cols[f"V{i+1}"] = W[:, i]
        return Frame.from_arrays(cols)

    def _score_raw(self, frame: Frame):
        raise NotImplementedError("use transform()/find_synonyms()")

    def model_performance(self, frame: Frame):
        return None


class Word2Vec(ModelBuilder):
    """h2o-py surface: ``H2OWord2vecEstimator`` (train on one string column)."""

    algo = "word2vec"
    unsupervised = True

    @classmethod
    def defaults(cls) -> dict:
        return dict(
            super().defaults(),
            vec_size=100,
            window_size=5,
            min_word_freq=5,
            init_learning_rate=0.025,
            sent_sample_rate=1e-3,
            epochs=5,
            negative_samples=5,
            mini_batch_size=1024,
            word_model="SkipGram",
            # objective: "sgns" (default; see module docstring) or "hsm"
            # (the reference's hierarchical softmax, Huffman paths padded to
            # fixed length so the update still compiles to one fused scan)
            objective="sgns",
            # frame (or DKV key) holding an external word->vector table:
            # col 0 = STR words, cols 1..D = numeric components (reference
            # Word2Vec.fromPretrainedModel, Word2Vec.java:123-145)
            pre_trained=None,
        )

    def train(self, x=None, y=None, training_frame=None, **kw):
        pre = self.params.get("pre_trained")
        if pre is not None:
            self.job = Job("word2vec-import")
            self.model = self.job.run(
                lambda job: self._from_pretrained(pre))
            if self.job.status == Job.FAILED:
                raise self.job.exception
            return self.job.result
        frame = training_frame
        str_cols = [c for c in frame.names if frame.vec(c).type is VecType.STR]
        if not str_cols:
            raise ValueError("Word2Vec requires a string column of tokens")
        self._word_col = str_cols[0]
        # bypass ModelBuilder.train: features are host strings, not device cols
        self.job = Job("word2vec")
        self.model = self.job.run(lambda job: self._fit_words(job, frame))
        if self.job.status == Job.FAILED:
            raise self.job.exception
        return self.job.result

    def _from_pretrained(self, pre) -> Word2VecModel:
        """Wrap an external embedding table as a full Word2VecModel
        (reference ``convertToModel``/``fromPretrainedModel``,
        ``Word2Vec.java:112-145``): col 0 STR words, cols 1.. numeric."""
        from h2o3_tpu.utils.registry import DKV
        fr = pre if isinstance(pre, Frame) else DKV.get(str(pre))
        if fr is None or fr.ncols < 2:
            raise ValueError("pre_trained frame needs >= 2 columns "
                             "(words + vector components)")
        wv = fr.vecs[0]
        if wv.type is not VecType.STR and not wv.is_categorical:
            # reference demands T_STR; a parsed word table may legitimately
            # arrive categorical — accept its labels as the words
            raise ValueError("pre_trained column 0 must be the STR words "
                             f"column, got {wv.type}")
        bad = [n for n, v in zip(fr.names[1:], fr.vecs[1:])
               if not v.is_numeric]
        if bad:
            raise ValueError(f"non-numeric vector components: {bad}")
        # reference sets vec_size from the frame (fromPretrainedModel); an
        # explicit mismatching vec_size is the driver's IllegalState. The
        # builder default (100) is indistinguishable from a user-passed 100,
        # so 100 is accepted and vec_size is overwritten from the frame.
        want = int(self.params.get("vec_size") or 0)
        if want not in (0, 100, fr.ncols - 1):
            raise ValueError(
                f"pre-trained frame has {fr.ncols - 1} components, "
                f"vec_size={want} specified")
        self.params["vec_size"] = fr.ncols - 1
        vocab = [str(w) for w in
                 (wv.labels() if wv.is_categorical else
                  wv.host_values[: fr.nrows])]
        W = np.stack([np.asarray(v.to_numpy(), np.float32)[: fr.nrows]
                      for v in fr.vecs[1:]], 1)
        model = Word2VecModel(
            key=make_model_key(self.algo, self.model_id),
            params=self.params, data_info=None, response_column=None,
            response_domain=None,
            output=dict(vectors=jnp.asarray(W), vocab=vocab,
                        word_index={w: i for i, w in enumerate(vocab)},
                        vec_size=W.shape[1], epochs_run=0, n_pairs=0,
                        pre_trained=True))
        DKV.put(model.key, model)
        return model

    def _fit(self, job, frame, x, y, weights):
        return self._fit_words(job, frame)

    def _fit_words(self, job: Job, frame: Frame) -> Word2VecModel:
        p = self.params
        tokens = frame.vec(self._word_col).host_values
        # vocab build (reference WordCountTask) — NA rows delimit sentences
        sents: list[list[str]] = [[]]
        for t in tokens:
            if t is None or (isinstance(t, float) and np.isnan(t)):
                if sents[-1]:
                    sents.append([])
            else:
                sents[-1].append(str(t))
        if not sents[-1]:
            sents.pop()
        from collections import Counter
        counts = Counter(w for s in sents for w in s)
        vocab = sorted(w for w, c in counts.items() if c >= int(p["min_word_freq"]))
        if not vocab:
            raise ValueError(f"no words reach min_word_freq={p['min_word_freq']}")
        index = {w: i for i, w in enumerate(vocab)}
        V, D = len(vocab), int(p["vec_size"])

        seed = int(p.get("seed") or -1)
        rng = np.random.default_rng(seed if seed >= 0 else 7919)
        # frequent-word subsampling (reference sent_sample_rate semantics)
        total = sum(counts[w] for w in vocab)
        samp = float(p["sent_sample_rate"])
        keep_p = {w: min(1.0, (np.sqrt(counts[w] / (samp * total)) + 1)
                         * (samp * total) / counts[w]) if samp > 0 else 1.0
                  for w in vocab}

        win = int(p["window_size"])
        centers, contexts = [], []
        for s in sents:
            ids = [index[w] for w in s if w in index and rng.random() < keep_p[w]]
            for i, c in enumerate(ids):
                lo = max(0, i - win)
                hi = min(len(ids), i + win + 1)
                for j in range(lo, hi):
                    if j != i:
                        centers.append(c)
                        contexts.append(ids[j])
        if not centers:
            raise ValueError("no training pairs (corpus too small for the window)")
        centers = np.asarray(centers, np.int32)
        contexts = np.asarray(contexts, np.int32)

        B = min(int(p["mini_batch_size"]), len(centers))
        key = jax.random.PRNGKey(int(rng.integers(0, 2**31)))
        Wc = (jax.random.uniform(key, (V, D), jnp.float32) - 0.5) / D
        Wx = jnp.zeros((V, D), jnp.float32)
        objective = str(p.get("objective", "sgns")).lower()
        if objective == "hsm":
            # reference objective: Huffman-coded hierarchical softmax
            word_freq = np.array([counts[w] for w in vocab], np.float64)
            hn, hc, hm = _huffman_paths(word_freq)
            hn_d, hc_d, hm_d = (jnp.asarray(hn), jnp.asarray(hc),
                                jnp.asarray(hm))
            Wn = jnp.zeros((max(V - 1, 1), D), jnp.float32)
        else:
            # unigram^0.75 noise distribution for negative sampling
            freq = np.array([counts[w] for w in vocab], np.float64) ** 0.75
            noise_cdf = jnp.asarray(np.cumsum(freq / freq.sum()), jnp.float32)
        lr = float(p["init_learning_rate"])
        n_epochs = max(int(p["epochs"]), 1)
        for ep in range(n_epochs):
            perm = rng.permutation(len(centers))
            nb = len(centers) // B
            cb = jnp.asarray(centers[perm][: nb * B].reshape(nb, B))
            xb = jnp.asarray(contexts[perm][: nb * B].reshape(nb, B))
            key, ek = jax.random.split(key)
            # linear LR decay per epoch (reference: alpha annealing)
            lr_e = lr * max(1.0 - ep / n_epochs, 1e-4 / lr if lr > 0 else 0.0)
            if objective == "hsm":
                Wc, Wn = _hsm_epoch(Wc, Wn, cb, xb, hn_d, hc_d, hm_d,
                                    jnp.float32(lr_e))
            else:
                Wc, Wx = _sgns_epoch(Wc, Wx, cb, xb, noise_cdf, ek,
                                     jnp.float32(lr_e),
                                     int(p["negative_samples"]))
            job.update((ep + 1) / n_epochs, f"epoch {ep + 1}/{n_epochs}")

        model = Word2VecModel(
            key=make_model_key(self.algo, self.model_id),
            params=self.params, data_info=None, response_column=None,
            response_domain=None,
            output=dict(vectors=Wc, vocab=vocab, word_index=index,
                        vec_size=D, epochs_run=n_epochs,
                        n_pairs=len(centers)))
        from h2o3_tpu.utils.registry import DKV
        DKV.put(model.key, model)
        return model
