"""Model framework + algorithms (reference: ``hex/`` in h2o-core and h2o-algos).

Estimators follow the h2o-py naming so users of the reference find the same
surface: ``H2OGeneralizedLinearEstimator``-like classes live here as ``GLM``,
``GBM``, ``DeepLearning``, ``KMeans``, etc., each a ``ModelBuilder`` subclass
producing a ``Model`` with metrics, prediction, and export.
"""

from h2o3_tpu.models.model_base import Model, ModelBuilder, ModelParameters
from h2o3_tpu.models.job import Job
from h2o3_tpu.models.glm import GLM, GLMModel
from h2o3_tpu.models.hglm import HGLM, HGLMModel
from h2o3_tpu.models.gbm import GBM, GBMModel, DRF, DRFModel
from h2o3_tpu.models.xgboost import XGBoost, XGBoostModel
from h2o3_tpu.models.deeplearning import AutoEncoder, DeepLearning, DeepLearningModel
from h2o3_tpu.models.kmeans import KMeans, KMeansModel
from h2o3_tpu.models.decomposition import GLRM, GLRMModel, PCA, PCAModel, SVD, SVDModel
from h2o3_tpu.models.naive_bayes import NaiveBayes, NaiveBayesModel
from h2o3_tpu.models.isofor import (
    ExtendedIsolationForest, ExtendedIsolationForestModel,
    IsolationForest, IsolationForestModel)
from h2o3_tpu.models.isotonic import IsotonicRegression, IsotonicRegressionModel
from h2o3_tpu.models.coxph import CoxPH, CoxPHModel
from h2o3_tpu.models.word2vec import Word2Vec, Word2VecModel
from h2o3_tpu.models.target_encoder import TargetEncoder, TargetEncoderModel
from h2o3_tpu.models.rulefit import RuleFit, RuleFitModel
from h2o3_tpu.models.decision_tree import DecisionTree, DecisionTreeModel
from h2o3_tpu.models.aggregator import Aggregator, AggregatorModel
from h2o3_tpu.models.grep_algo import Grep, GrepModel
from h2o3_tpu.models.gam import GAM, GAMModel
from h2o3_tpu.models.model_selection import (ANOVAGLM, ANOVAGLMModel,
                                             ModelSelection, ModelSelectionModel)
from h2o3_tpu.models.uplift import UpliftDRF, UpliftDRFModel
from h2o3_tpu.models.psvm import PSVM, PSVMModel
from h2o3_tpu.models.infogram import Infogram, InfogramModel

__all__ = ["Model", "ModelBuilder", "ModelParameters", "Job",
           "GLM", "HGLM", "HGLMModel", "GLMModel", "GBM", "GBMModel", "DRF", "DRFModel",
           "XGBoost", "XGBoostModel",
           "DeepLearning", "DeepLearningModel", "AutoEncoder",
           "KMeans", "KMeansModel", "PCA", "PCAModel", "SVD", "SVDModel",
           "GLRM", "GLRMModel", "NaiveBayes", "NaiveBayesModel",
           "IsolationForest", "IsolationForestModel",
           "ExtendedIsolationForest", "ExtendedIsolationForestModel",
           "IsotonicRegression", "IsotonicRegressionModel",
           "CoxPH", "CoxPHModel", "Word2Vec", "Word2VecModel",
           "TargetEncoder", "TargetEncoderModel", "RuleFit", "RuleFitModel",
           "DecisionTree", "DecisionTreeModel",
           "Aggregator", "AggregatorModel", "Grep", "GrepModel",
           "GAM", "GAMModel", "ModelSelection", "ModelSelectionModel",
           "ANOVAGLM", "ANOVAGLMModel", "UpliftDRF", "UpliftDRFModel",
           "PSVM", "PSVMModel", "Infogram", "InfogramModel"]
