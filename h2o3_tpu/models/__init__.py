"""Model framework + algorithms (reference: ``hex/`` in h2o-core and h2o-algos).

Estimators follow the h2o-py naming so users of the reference find the same
surface: ``H2OGeneralizedLinearEstimator``-like classes live here as ``GLM``,
``GBM``, ``DeepLearning``, ``KMeans``, etc., each a ``ModelBuilder`` subclass
producing a ``Model`` with metrics, prediction, and export.
"""

from h2o3_tpu.models.model_base import Model, ModelBuilder, ModelParameters
from h2o3_tpu.models.job import Job
from h2o3_tpu.models.glm import GLM, GLMModel
from h2o3_tpu.models.gbm import GBM, GBMModel, DRF, DRFModel
from h2o3_tpu.models.xgboost import XGBoost, XGBoostModel

__all__ = ["Model", "ModelBuilder", "ModelParameters", "Job",
           "GLM", "GLMModel", "GBM", "GBMModel", "DRF", "DRFModel",
           "XGBoost", "XGBoostModel"]
