"""Isolation Forest + Extended Isolation Forest — anomaly detection.

Reference: ``hex/tree/isofor/IsolationForest.java`` (random-split trees on
per-tree subsamples, anomaly score normalized by the min/max path length seen
in training) and ``hex/tree/isoforextended/ExtendedIsolationForest.java``
(non-axis-parallel hyperplane splits, Liu et al. anomaly score
``2^(-E[h]/c(psi))``).

TPU-native redesign: unlike GBM there are no histograms — splits are *random*,
so each level of every tree is a tiny vectorized program over the subsample
(per-node ``segment_min``/``segment_max`` for the split range, uniform draws,
gather-routing). Axis-parallel trees reuse the dense-heap ``Tree`` layout of
``tree.py`` with leaf values = path length (depth + c(n) tail correction), so
scoring a full frame is the same stacked-gather traversal as GBM — one fused
XLA program, no per-row recursion. Extended trees store per-node hyperplane
normals ``[heap, F]`` and traverse by masked dot products.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.types import VecType
from h2o3_tpu.frame.vec import Vec
from h2o3_tpu.models.gbm import tree_matrix
from h2o3_tpu.models.job import Job
from h2o3_tpu.models.model_base import Model, ModelBuilder, make_model_key
from h2o3_tpu.models.tree import Tree, predict_raw

EULER_GAMMA = 0.5772156649015329


def _avg_path_norm(n):
    """c(n): expected unsuccessful-search path length in a BST of n points."""
    n = np.asarray(n, np.float64)
    c = 2.0 * (np.log(np.maximum(n - 1, 1)) + EULER_GAMMA) - 2.0 * (n - 1) / np.maximum(n, 1)
    return np.where(n > 2, c, np.where(n == 2, 1.0, 0.0))


class IsolationForestModel(Model):
    algo = "isolationforest"

    def _mean_length(self, frame: Frame) -> jax.Array:
        X = tree_matrix(frame, self.output["x_cols"], self.output["feat_domains"])
        total = predict_raw(X, self.output["trees"])
        return total / max(self.output["ntrees"], 1)

    def _score_raw(self, frame: Frame) -> jax.Array:
        return self._mean_length(frame)

    def predict(self, frame: Frame) -> Frame:
        """Columns ``predict`` (normalized anomaly score) and ``mean_length``
        (reference: IsolationForestModel score0 normalizes by the train-time
        min/max path length)."""
        mean_len = self._mean_length(frame)
        lo, hi = self.output["min_path_length"], self.output["max_path_length"]
        score = jnp.clip((hi - mean_len) / max(hi - lo, 1e-12), 0.0, 1.0)
        n = frame.nrows
        return Frame(["predict", "mean_length"],
                     [Vec.from_device(score, n, VecType.NUM),
                      Vec.from_device(mean_len, n, VecType.NUM)])

    def model_performance(self, frame: Frame):
        return None


class _IsoForBase(ModelBuilder):
    unsupervised = True
    supports_classification = False

    @classmethod
    def defaults(cls) -> dict:
        return dict(super().defaults(), ntrees=50, sample_size=256, max_depth=8)

    def _matrix(self, frame: Frame, x: list[str], weights):
        X = tree_matrix(frame, x, {})
        valid = np.asarray(jax.device_get(weights > 0)).nonzero()[0]
        if len(valid) == 0:
            raise ValueError("no rows with positive weight")
        domains = {c: frame.vec(c).domain for c in x if frame.vec(c).is_categorical}
        return X, valid, domains


def _grow_iso_tree(Xs: np.ndarray, max_depth: int, rng: np.random.Generator) -> Tree:
    """One random-split tree over the subsample, level-synchronous on host.

    The subsample is tiny (default 256 rows), so growth runs in numpy; the
    expensive part — scoring millions of rows — stays on device via
    ``predict_raw``. NaNs route to a per-node random side."""
    n, F = Xs.shape
    heap = 2 ** (max_depth + 1) - 1
    hf = np.full(heap, -1, np.int32)
    htv = np.zeros(heap, np.float32)
    hna = np.zeros(heap, bool)
    hsp = np.zeros(heap, bool)
    hlf = np.zeros(heap, np.float32)

    node = np.zeros(n, np.int64)  # heap position per row; -1 = frozen
    for d in range(max_depth + 1):
        off = 2 ** d - 1
        N = 2 ** d
        live = node >= 0
        if not live.any():
            break
        ids = np.where(live, node - off, 0)
        counts = np.bincount(ids[live], minlength=N)
        if d == max_depth:
            hlf[off:off + N] = d + _avg_path_norm(counts)
            break
        feats = rng.integers(0, F, N)
        fv = Xs[np.arange(n), feats[ids]]
        fv_ok = live & ~np.isnan(fv)
        big = np.where(fv_ok, fv, np.inf)
        small = np.where(fv_ok, fv, -np.inf)
        mins = np.full(N, np.inf)
        maxs = np.full(N, -np.inf)
        np.minimum.at(mins, ids[live], big[live])
        np.maximum.at(maxs, ids[live], small[live])
        can = (counts > 1) & np.isfinite(mins) & np.isfinite(maxs) & (maxs > mins)
        lo = np.where(can, mins, 0.0)
        hi = np.where(can, maxs, 0.0)
        thr = (rng.uniform(0, 1, N) * (hi - lo) + lo).astype(np.float32)
        na_left = rng.integers(0, 2, N).astype(bool)
        hf[off:off + N] = np.where(can, feats, -1)
        htv[off:off + N] = thr
        hna[off:off + N] = na_left
        hsp[off:off + N] = can
        hlf[off:off + N] = np.where(can, 0.0, d + _avg_path_norm(counts))
        # route rows of splitting nodes to children
        go = live & can[ids]
        left = np.where(np.isnan(fv), na_left[ids], fv < thr[ids])
        child = (off + ids) * 2 + np.where(left, 1, 2)
        node = np.where(go, child, -1)

    return Tree(feat=jnp.asarray(hf), thresh_bin=jnp.zeros(heap, jnp.int32),
                thresh_val=jnp.asarray(htv), na_left=jnp.asarray(hna),
                is_split=jnp.asarray(hsp), leaf=jnp.asarray(hlf))


class IsolationForest(_IsoForBase):
    """h2o-py surface: ``H2OIsolationForestEstimator``."""

    algo = "isolationforest"

    def _fit(self, job: Job, frame: Frame, x, y, weights) -> IsolationForestModel:
        p = self.params
        X, valid, domains = self._matrix(frame, x, weights)
        Xh = np.asarray(jax.device_get(X))
        seed = int(p["seed"]) if int(p["seed"]) >= 0 else 0xC0FFEE
        rng = np.random.default_rng(seed)
        ntrees = int(p["ntrees"])
        trees: list[Tree] = []
        for m in range(ntrees):
            sub = rng.choice(valid, size=min(int(p["sample_size"]), len(valid)),
                             replace=False)
            trees.append(_grow_iso_tree(Xh[sub], int(p["max_depth"]), rng))
            job.update((m + 1) / ntrees, f"tree {m + 1}/{ntrees}")

        model = IsolationForestModel(
            key=make_model_key(self.algo, self.model_id),
            params=self.params, data_info=None, response_column=None,
            response_domain=None,
            output=dict(trees=trees, ntrees=len(trees), x_cols=list(x),
                        feat_domains=domains, min_path_length=0.0,
                        max_path_length=1.0))
        # train-time path-length range for score normalization (reference:
        # IsolationForest driver records _min/_max path length over training rows)
        mean_len = np.asarray(jax.device_get(model._mean_length(frame)))[valid]
        model.output["min_path_length"] = float(mean_len.min())
        model.output["max_path_length"] = float(mean_len.max())
        return model


# ---------------------------------------------------------------------------
# Extended Isolation Forest
# ---------------------------------------------------------------------------

class ExtendedIsolationForestModel(Model):
    algo = "extendedisolationforest"

    def _mean_length(self, frame: Frame) -> jax.Array:
        X = jnp.nan_to_num(
            tree_matrix(frame, self.output["x_cols"], self.output["feat_domains"]))
        o = self.output
        return _eif_path_lengths(X, o["normals"], o["offsets"], o["is_split"],
                                 o["leaf"]) / max(o["ntrees"], 1)

    def _score_raw(self, frame: Frame) -> jax.Array:
        return self._mean_length(frame)

    def predict(self, frame: Frame) -> Frame:
        """Columns ``anomaly_score`` (2^(-E[h]/c(psi))) and ``mean_length``
        (reference: ExtendedIsolationForestModel.score0)."""
        mean_len = self._mean_length(frame)
        score = jnp.exp2(-mean_len / max(self.output["cn"], 1e-12))
        n = frame.nrows
        return Frame(["anomaly_score", "mean_length"],
                     [Vec.from_device(score, n, VecType.NUM),
                      Vec.from_device(mean_len, n, VecType.NUM)])

    def model_performance(self, frame: Frame):
        return None


@jax.jit
def _eif_path_lengths(X, normals, offsets, is_split, leaf):
    """Sum of per-tree path lengths. normals: [T, heap, F]; X: [rows, F]."""
    rows = X.shape[0]
    depth = int(np.log2(normals.shape[1] + 1)) - 1

    def one_tree(acc, tr):
        nv, off, sp, lf = tr
        idx = jnp.zeros(rows, jnp.int32)
        for _ in range(depth):
            proj = jnp.einsum("rf,rf->r", X, nv[idx]) - off[idx]
            nxt = idx * 2 + jnp.where(proj <= 0, 1, 2)
            idx = jnp.where(sp[idx], nxt, idx)
        return acc + lf[idx], None

    acc, _ = jax.lax.scan(one_tree, jnp.zeros(rows, jnp.float32),
                          (normals, offsets, is_split, leaf))
    return acc


def _grow_eif_tree(Xs: np.ndarray, max_depth: int, ext_level: int,
                   rng: np.random.Generator):
    """One extended tree: per-node random hyperplane (normal with
    ``ext_level+1`` non-zero coords, intercept uniform in the node's bounding
    box). Reference: ExtendedIsolationForestSplitter semantics."""
    n, F = Xs.shape
    heap = 2 ** (max_depth + 1) - 1
    normals = np.zeros((heap, F), np.float32)
    offsets = np.zeros(heap, np.float32)
    hsp = np.zeros(heap, bool)
    hlf = np.zeros(heap, np.float32)

    node = np.zeros(n, np.int64)
    for d in range(max_depth + 1):
        off = 2 ** d - 1
        N = 2 ** d
        live = node >= 0
        if not live.any():
            break
        ids = np.where(live, node - off, 0)
        counts = np.bincount(ids[live], minlength=N)
        if d == max_depth:
            hlf[off:off + N] = d + _avg_path_norm(counts)
            break
        # bounding box per node
        mins = np.full((N, F), np.inf)
        maxs = np.full((N, F), -np.inf)
        np.minimum.at(mins, ids[live], Xs[live])
        np.maximum.at(maxs, ids[live], Xs[live])
        can = counts > 1
        # normal vectors: N(0,1) with F-1-ext_level coords zeroed
        nv = rng.normal(size=(N, F)).astype(np.float32)
        keep = np.argsort(rng.uniform(size=(N, F)), axis=1) <= ext_level
        nv = nv * keep
        box = np.where(np.isfinite(mins) & np.isfinite(maxs), maxs - mins, 0.0)
        p = np.where(np.isfinite(mins), mins, 0.0) + rng.uniform(size=(N, F)) * box
        ofs = np.einsum("nf,nf->n", nv, p).astype(np.float32)
        normals[off:off + N] = np.where(can[:, None], nv, 0.0)
        offsets[off:off + N] = np.where(can, ofs, 0.0)
        hsp[off:off + N] = can
        hlf[off:off + N] = np.where(can, 0.0, d + _avg_path_norm(counts))
        proj = np.einsum("rf,rf->r", Xs, nv[ids]) - ofs[ids]
        go = live & can[ids]
        child = (off + ids) * 2 + np.where(proj <= 0, 1, 2)
        node = np.where(go, child, -1)

    return normals, offsets, hsp, hlf


class ExtendedIsolationForest(_IsoForBase):
    """h2o-py surface: ``H2OExtendedIsolationForestEstimator``."""

    algo = "extendedisolationforest"

    @classmethod
    def defaults(cls) -> dict:
        d = dict(super().defaults(), extension_level=0)
        d["ntrees"] = 100
        # reference EIF has no max_depth param: depth is ceil(log2(sample_size))
        del d["max_depth"]
        return d

    def _fit(self, job: Job, frame: Frame, x, y, weights) -> ExtendedIsolationForestModel:
        p = self.params
        X, valid, domains = self._matrix(frame, x, weights)
        Xh = np.nan_to_num(np.asarray(jax.device_get(X)))
        F = Xh.shape[1]
        ext = int(p["extension_level"])
        if not 0 <= ext <= F - 1:
            raise ValueError(f"extension_level must be in [0, {F - 1}]")
        sample_size = min(int(p["sample_size"]), len(valid))
        max_depth = int(np.ceil(np.log2(max(sample_size, 2))))
        seed = int(p["seed"]) if int(p["seed"]) >= 0 else 0xC0FFEE
        rng = np.random.default_rng(seed)
        ntrees = int(p["ntrees"])
        parts = []
        for m in range(ntrees):
            sub = rng.choice(valid, size=sample_size, replace=False)
            parts.append(_grow_eif_tree(Xh[sub], max_depth, ext, rng))
            job.update((m + 1) / ntrees, f"tree {m + 1}/{ntrees}")

        return ExtendedIsolationForestModel(
            key=make_model_key(self.algo, self.model_id),
            params=self.params, data_info=None, response_column=None,
            response_domain=None,
            output=dict(
                normals=jnp.asarray(np.stack([t[0] for t in parts])),
                offsets=jnp.asarray(np.stack([t[1] for t in parts])),
                is_split=jnp.asarray(np.stack([t[2] for t in parts])),
                leaf=jnp.asarray(np.stack([t[3] for t in parts])),
                ntrees=ntrees, x_cols=list(x), feat_domains=domains,
                cn=float(_avg_path_norm(sample_size))))
