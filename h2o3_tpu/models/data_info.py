"""DataInfo — adapts a Frame into a model-ready design matrix.

Reference: ``h2o-algos/.../hex/DataInfo.java`` (~1.3 kLoC): shared by
GLM/DL/GAM/PCA etc.; lays out categorical one-hot blocks first then numeric
columns, handles ``use_all_factor_levels`` (``DataInfo.java:112``),
standardization (``_normMul`` ``:120``), and missing-value imputation
(``:149``). Test-time frames are adapted to the train layout
(``hex/Model.adaptTestForTrain``): categorical levels are matched by name,
unseen levels become missing.

TPU-native: expansion is a jitted gather/compare producing a dense f32
[rows, K] matrix straight into HBM — dense one-hot blocks feed the MXU
(a Gram of one-hot blocks is exactly a matmul), so there is no sparse row
format like the reference's ``DataInfo.Row``.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.types import VecType


@dataclasses.dataclass
class DataInfo:
    cat_cols: list[str]
    num_cols: list[str]
    cat_domains: list[tuple[str, ...]]     # train-time domains, layout order
    cat_offsets: np.ndarray                # start of each cat block in X
    num_means: np.ndarray                  # imputation values
    num_mul: np.ndarray                    # 1/sigma (or 1) per numeric col
    num_sub: np.ndarray                    # mean (or 0) per numeric col
    use_all_factor_levels: bool
    standardize: bool
    ncats_expanded: int

    @property
    def ncols_expanded(self) -> int:
        return self.ncats_expanded + len(self.num_cols)

    @property
    def coef_names(self) -> list[str]:
        names = []
        for col, dom in zip(self.cat_cols, self.cat_domains):
            lo = 0 if self.use_all_factor_levels else 1
            names += [f"{col}.{lvl}" for lvl in dom[lo:]]
        return names + list(self.num_cols)

    # -- construction (train side) ------------------------------------------

    @staticmethod
    def make(frame: Frame, x: list[str], standardize: bool = True,
             use_all_factor_levels: bool = False) -> "DataInfo":
        cat_cols = [c for c in x if frame.vec(c).is_categorical]
        num_cols = [c for c in x if not frame.vec(c).is_categorical]
        for c in num_cols:
            if not frame.vec(c).type.on_device:
                raise TypeError(f"column {c!r} has type {frame.vec(c).type}; not trainable")
        cat_domains = [frame.vec(c).domain for c in cat_cols]
        offs, k = [], 0
        for dom in cat_domains:
            offs.append(k)
            k += len(dom) if use_all_factor_levels else max(len(dom) - 1, 0)
        means = np.array([frame.vec(c).mean() for c in num_cols], np.float32)
        sigmas = np.array([frame.vec(c).sigma() for c in num_cols], np.float32)
        means = np.nan_to_num(means)
        mul = np.where((sigmas > 0) & np.isfinite(sigmas), 1.0 / np.maximum(sigmas, 1e-30), 1.0).astype(np.float32) \
            if standardize else np.ones_like(means)
        sub = means if standardize else np.zeros_like(means)
        return DataInfo(cat_cols, num_cols, cat_domains, np.array(offs, np.int32),
                        means, mul, sub, use_all_factor_levels, standardize, k)

    # -- expansion (train or adapted test) ----------------------------------

    def expand(self, frame: Frame) -> jax.Array:
        """Build the [plen, K] design matrix; test domains adapted by name."""
        cats = []
        for col, train_dom in zip(self.cat_cols, self.cat_domains):
            v = frame.vec(col)
            codes = v.data
            if v.type is not VecType.CAT:
                raise TypeError(f"column {col!r} must be categorical at scoring time")
            if v.domain != train_dom:
                codes = _remap_codes(codes, v.domain, train_dom)
            cats.append(codes)
        nums = [frame.vec(c).data for c in self.num_cols] if self.num_cols else []
        cat_stack = jnp.stack(cats, axis=1) if cats else jnp.zeros((frame.plen, 0), jnp.int32)
        num_stack = jnp.stack(nums, axis=1) if nums else jnp.zeros((frame.plen, 0), jnp.float32)
        cards = tuple(len(d) for d in self.cat_domains)
        return _expand(cat_stack, num_stack, cards, self.use_all_factor_levels,
                       jnp.asarray(self.num_sub), jnp.asarray(self.num_mul),
                       jnp.asarray(self.num_means))

    def response(self, frame: Frame, y: str) -> tuple[jax.Array, int]:
        """Response column as f32 (codes for cat) + number of classes (0=regression)."""
        v = frame.vec(y)
        if v.is_categorical:
            return v.data.astype(jnp.float32), v.cardinality()
        return v.data, 0


def response_as_float(vec) -> tuple[jax.Array, jax.Array]:
    """Response as f32 + per-row validity mask — THE canonical NA semantics for
    supervised training/metrics (cat code -1 and numeric NaN are missing).
    Single home so trainers, holdout metrics, and CV masks cannot diverge."""
    yy = vec.data.astype(jnp.float32) if vec.is_categorical else vec.data
    valid = (vec.data >= 0) if vec.is_categorical else ~jnp.isnan(vec.data)
    return yy, valid


def expand_interactions(frame, interactions: list[str], domains=None):
    """Pairwise interaction columns among ``interactions`` (reference:
    ``hex/DataInfo.java`` interactions / ``CreateInteractions``):

    - num × num → elementwise product column ``a_b``
    - cat × num → one numeric column per level: ``cat.lvl_num`` (indicator
      times the numeric value)
    - cat × cat → combined factor ``a_b`` (level cross)

    Returns an EXTENDED frame (originals untouched); both train and score
    paths route through here so the expansion cannot drift. ``domains``
    (``{col: train_domain}``, captured at train) pins the cat×num column
    set: a scoring batch missing some training level still produces every
    design column (its indicator is simply all-zero)."""
    import itertools

    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.frame.types import VecType
    from h2o3_tpu.frame.vec import Vec

    domains = domains or {}
    out = Frame(list(frame.names), list(frame.vecs), key=frame.key)
    for a, b in itertools.combinations(interactions, 2):
        va, vb = frame.vec(a), frame.vec(b)
        name = f"{a}_{b}"
        if va.is_categorical and vb.is_categorical:
            from h2o3_tpu.frame.utils import interaction as cat_cross
            crossed = cat_cross(frame, [[a, b]], pairwise=True)
            out.add(name, crossed.vec(0))
        elif not va.is_categorical and not vb.is_categorical:
            out.add(name, Vec(va.as_float() * vb.as_float(), VecType.NUM,
                              frame.nrows))
        else:
            cat, num = (va, vb) if va.is_categorical else (vb, va)
            cname = a if va.is_categorical else b
            dom = domains.get(cname, cat.domain or ())
            codes = cat.data
            if cat.domain != tuple(dom):
                codes = _remap_codes(codes, cat.domain or (), tuple(dom))
            for li, lvl in enumerate(dom):
                ind = (codes == li).astype(jnp.float32)
                out.add(f"{cname}.{lvl}_{name}",
                        Vec(ind * jnp.nan_to_num(num.as_float(), nan=0.0),
                            VecType.NUM, frame.nrows))
    return out


def response_adapted(vec, train_domain) -> tuple[jax.Array, jax.Array]:
    """Response as f32 + validity, remapped to the TRAIN domain when the
    frame's categorical levels differ (``Model.adaptTestForTrain`` semantics;
    unseen levels → invalid). The single home for held-out response adaptation
    — model_performance and mid-train validation scoring both route here."""
    if train_domain and vec.is_categorical and vec.domain != train_domain:
        codes = _remap_codes(vec.data, vec.domain or (), train_domain)
        return codes.astype(jnp.float32), codes >= 0
    return response_as_float(vec)


def _remap_codes(codes: jax.Array, src_dom: tuple[str, ...], dst_dom: tuple[str, ...]) -> jax.Array:
    """Align test categorical codes to the train domain (unseen → NA).

    Reference: ``Model.adaptTestForTrain`` domain mapping."""
    lut_host = np.full(max(len(src_dom), 1), -1, np.int32)
    dst = {s: i for i, s in enumerate(dst_dom)}
    for i, s in enumerate(src_dom):
        lut_host[i] = dst.get(s, -1)
    lut = jnp.asarray(lut_host)
    return jnp.where(codes >= 0, lut[jnp.clip(codes, 0, len(lut_host) - 1)], -1)


@partial(jax.jit, static_argnames=("cards", "use_all"))
def _expand(cat_codes, nums, cards: tuple[int, ...], use_all: bool, sub, mul, impute):
    """Dense one-hot + standardized-numeric expansion, fully fused.

    Missing values: cat NA (-1) → all-zero block; numeric NaN → imputed to the
    mean, i.e. 0 after standardization (reference MeanImputation semantics).
    """
    blocks = []
    for j, card in enumerate(cards):
        c = cat_codes[:, j]
        lo = 0 if use_all else 1
        width = card - lo
        if width <= 0:
            continue
        oh = (c[:, None] == jnp.arange(lo, card)[None, :]).astype(jnp.float32)
        blocks.append(oh)
    if nums.shape[1]:
        # mean imputation always (reference MeanImputation), independent of
        # whether standardization is on (sub is 0 when standardize=False)
        imputed = jnp.where(jnp.isnan(nums), impute[None, :], nums)
        blocks.append((imputed - sub) * mul)
    if not blocks:
        return jnp.zeros((cat_codes.shape[0], 0), jnp.float32)
    return jnp.concatenate(blocks, axis=1)
