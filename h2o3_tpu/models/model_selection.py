"""ModelSelection + ANOVAGLM — GLM wrapper algorithms.

Reference: ``hex/modelselection/ModelSelection.java`` (2.7 kLoC): best-subset
GLM search with modes maxr / maxrsweep / forward / backward, ranking subsets
by R² (gaussian) or deviance; ``hex/anovaglm/ANOVAGLM.java`` (1.1 kLoC):
trains GLMs on all predictor-subset combinations to produce a type-III-style
ANOVA significance table.

Each candidate subset is an independent small IRLS fit — host-level task
parallelism over device-resident data, like the reference's parallel model
builds (``hex/ModelBuilder.java:884``).
"""

from __future__ import annotations

import itertools

import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.job import Job
from h2o3_tpu.models.model_base import Model, ModelBuilder, make_model_key


def _fit_glm(frame, xs, y, weights, params):
    from h2o3_tpu.models.glm import GLM
    return GLM(family=params.get("family", "AUTO"),
               lambda_=float(params.get("lambda_", 0.0)),
               alpha=float(params.get("alpha", 0.0)),
               standardize=bool(params.get("standardize", True))) \
        .train(x=list(xs), y=y, training_frame=frame, weights=weights)


def _score(m) -> float:
    mm = m.training_metrics
    r2 = getattr(mm, "r2", None)
    if r2 is not None and np.isfinite(r2):
        return float(r2)
    return -float(m.output.get("residual_deviance", np.inf))


class ModelSelectionModel(Model):
    algo = "modelselection"

    def _score_raw(self, frame: Frame):
        return self.output["best_model"]._score_raw(frame)

    def result(self) -> list[dict]:
        """Per-size best subsets (h2o-py: ``result()`` frame)."""
        return self.output["results"]

    def coef(self):
        return self.output["best_model"].coef()


class ModelSelection(ModelBuilder):
    """h2o-py surface: ``H2OModelSelectionEstimator`` (mode=maxr|forward|backward)."""

    algo = "modelselection"

    @classmethod
    def defaults(cls) -> dict:
        return dict(
            super().defaults(),
            mode="maxr",
            max_predictor_number=3,
            min_predictor_number=1,
            family="AUTO",
            lambda_=0.0,
            alpha=0.0,
            standardize=True,
        )

    def _fit(self, job: Job, frame: Frame, x, y, weights) -> ModelSelectionModel:
        p = self.params
        mode = str(p["mode"]).lower()
        results = []
        best_per_size = {}

        if mode in ("maxr", "maxrsweep", "allsubsets"):
            maxk = min(int(p["max_predictor_number"]), len(x))
            for k in range(int(p["min_predictor_number"]), maxk + 1):
                best = None
                for subset in itertools.combinations(x, k):
                    m = _fit_glm(frame, subset, y, weights, p)
                    if best is None or _score(m) > _score(best):
                        best = m
                best_per_size[k] = best
                results.append(dict(n_predictors=k,
                                    predictors=[c for c in x if c in
                                                best.output["coef_names"] or
                                                any(n.startswith(c + ".") for n in
                                                    best.output["coef_names"])],
                                    r2=_score(best), model_key=best.key))
                job.update(k / maxk, f"best of size {k}: r2={_score(best):.4f}")
        elif mode == "forward":
            chosen: list[str] = []
            maxk = min(int(p["max_predictor_number"]), len(x))
            while len(chosen) < maxk:
                cand = [(c, _fit_glm(frame, chosen + [c], y, weights, p))
                        for c in x if c not in chosen]
                c, m = max(cand, key=lambda t: _score(t[1]))
                chosen.append(c)
                best_per_size[len(chosen)] = m
                results.append(dict(n_predictors=len(chosen),
                                    predictors=list(chosen),
                                    r2=_score(m), model_key=m.key))
                job.update(len(chosen) / maxk, f"+{c}")
        elif mode == "backward":
            chosen = list(x)
            m = _fit_glm(frame, chosen, y, weights, p)
            best_per_size[len(chosen)] = m
            results.append(dict(n_predictors=len(chosen), predictors=list(chosen),
                                r2=_score(m), model_key=m.key))
            while len(chosen) > int(p["min_predictor_number"]):
                cand = [(c, _fit_glm(frame, [d for d in chosen if d != c],
                                     y, weights, p)) for c in chosen]
                c, m = max(cand, key=lambda t: _score(t[1]))
                chosen.remove(c)
                best_per_size[len(chosen)] = m
                results.append(dict(n_predictors=len(chosen),
                                    predictors=list(chosen),
                                    r2=_score(m), model_key=m.key))
                job.update(1 - len(chosen) / len(x), f"-{c}")
        else:
            raise ValueError(f"unknown mode {p['mode']!r}")

        best = max(best_per_size.values(), key=_score)
        yvec = frame.vec(y)
        return ModelSelectionModel(
            key=make_model_key(self.algo, self.model_id),
            params=self.params, data_info=None, response_column=y,
            response_domain=yvec.domain if yvec.is_categorical else None,
            output=dict(results=results, best_model=best,
                        best_per_size={k: m.key for k, m in best_per_size.items()}),
        )


class ANOVAGLMModel(Model):
    algo = "anovaglm"

    def _score_raw(self, frame: Frame):
        return self.output["full_model"]._score_raw(frame)

    def anova_table(self) -> list[dict]:
        return self.output["table"]


class ANOVAGLM(ModelBuilder):
    """h2o-py surface: ``H2OANOVAGLMEstimator`` — deviance-decomposition
    significance of each predictor (and pairwise interactions)."""

    algo = "anovaglm"

    @classmethod
    def defaults(cls) -> dict:
        return dict(
            super().defaults(),
            family="AUTO",
            lambda_=0.0,
            alpha=0.0,
            standardize=True,
            highest_interaction_term=2,
        )

    def _fit(self, job: Job, frame: Frame, x, y, weights) -> ANOVAGLMModel:
        p = self.params
        full = _fit_glm(frame, x, y, weights, p)
        dev_full = float(full.output.get("residual_deviance", np.nan))
        n = frame.nrows

        table = []
        for i, c in enumerate(x):
            reduced = [d for d in x if d != c]
            if not reduced:
                continue
            m = _fit_glm(frame, reduced, y, weights, p)
            dev_r = float(m.output.get("residual_deviance", np.nan))
            df = len(full.output["coef_names"]) - len(m.output["coef_names"])
            ss = max(dev_r - dev_full, 0.0)
            denom = max(dev_full, 1e-12) / max(n - len(full.output["coef_names"]) - 1, 1)
            fstat = (ss / max(df, 1)) / denom
            from scipy.stats import f as f_dist
            pval = float(f_dist.sf(fstat, max(df, 1),
                                   max(n - len(full.output["coef_names"]) - 1, 1)))
            table.append(dict(predictor=c, df=df, deviance=ss,
                              f_value=fstat, p_value=pval))
            job.update((i + 1) / len(x), f"dropped {c}: p={pval:.4g}")

        yvec = frame.vec(y)
        return ANOVAGLMModel(
            key=make_model_key(self.algo, self.model_id),
            params=self.params, data_info=None, response_column=y,
            response_domain=yvec.domain if yvec.is_categorical else None,
            output=dict(full_model=full, table=table),
        )
