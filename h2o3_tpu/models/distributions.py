"""Distribution families and link functions.

Reference: ``hex/Distribution.java``, ``DistributionFactory.java``,
``LinkFunction*.java`` (bernoulli, quasibinomial, multinomial, gaussian,
poisson, gamma, tweedie, laplace, quantile, huber) and the GLM family/link
tables in ``hex/glm/GLMModel.java`` (GLMParameters.Family/Link).

All functions are pure jnp and jit-safe; IRLS needs (link, inverse link,
d mu/d eta, variance function), boosting needs (deviance, gradient, hessian).
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax.numpy as jnp

_EPS = 1e-10


def _clip01(p):
    return jnp.clip(p, _EPS, 1.0 - _EPS)


@dataclasses.dataclass(frozen=True)
class Family:
    """GLM family: link pair + variance + deviance (reference: GLMParameters.Family)."""

    name: str
    link: Callable          # eta = g(mu)
    linkinv: Callable       # mu = g^-1(eta)
    dmu_deta: Callable      # mu'(eta)
    variance: Callable      # Var(y|mu) up to dispersion
    deviance: Callable      # per-row deviance d(y, mu)

    def initialize_mu(self, y):
        """Starting mu for IRLS (reference: GLM.java initialization)."""
        if self.name == "binomial":
            return (y + 0.5) / 2.0
        if self.name in ("poisson", "gamma", "tweedie"):
            return jnp.maximum(y, 0.1)
        return y


def _gaussian():
    return Family(
        "gaussian",
        link=lambda mu: mu,
        linkinv=lambda eta: eta,
        dmu_deta=lambda eta: jnp.ones_like(eta),
        variance=lambda mu: jnp.ones_like(mu),
        deviance=lambda y, mu: (y - mu) ** 2,
    )


def _binomial():
    def linkinv(eta):
        return _clip01(jnp.where(eta >= 0, 1.0 / (1.0 + jnp.exp(-eta)),
                                 jnp.exp(eta) / (1.0 + jnp.exp(eta))))

    return Family(
        "binomial",
        link=lambda mu: jnp.log(_clip01(mu) / (1.0 - _clip01(mu))),
        linkinv=linkinv,
        dmu_deta=lambda eta: _clip01(linkinv(eta)) * (1.0 - _clip01(linkinv(eta))),
        variance=lambda mu: _clip01(mu) * (1.0 - _clip01(mu)),
        deviance=lambda y, mu: -2.0 * (y * jnp.log(_clip01(mu)) + (1 - y) * jnp.log(1 - _clip01(mu))),
    )


def _poisson():
    return Family(
        "poisson",
        link=lambda mu: jnp.log(jnp.maximum(mu, _EPS)),
        linkinv=lambda eta: jnp.exp(jnp.clip(eta, -30, 30)),
        dmu_deta=lambda eta: jnp.exp(jnp.clip(eta, -30, 30)),
        variance=lambda mu: jnp.maximum(mu, _EPS),
        deviance=lambda y, mu: 2.0 * (jnp.where(y > 0, y * jnp.log(jnp.maximum(y, _EPS) / jnp.maximum(mu, _EPS)), 0.0) - (y - mu)),
    )


def _gamma():
    return Family(
        "gamma",
        link=lambda mu: jnp.log(jnp.maximum(mu, _EPS)),   # log link default (H2O allows inverse)
        linkinv=lambda eta: jnp.exp(jnp.clip(eta, -30, 30)),
        dmu_deta=lambda eta: jnp.exp(jnp.clip(eta, -30, 30)),
        variance=lambda mu: jnp.maximum(mu, _EPS) ** 2,
        deviance=lambda y, mu: -2.0 * (jnp.log(jnp.maximum(y, _EPS) / jnp.maximum(mu, _EPS)) - (y - mu) / jnp.maximum(mu, _EPS)),
    )


def _tweedie(p: float = 1.5):
    def deviance(y, mu):
        mu = jnp.maximum(mu, _EPS)
        y1 = jnp.maximum(y, _EPS)
        return 2.0 * (y1 ** (2 - p) / ((1 - p) * (2 - p))
                      - y * mu ** (1 - p) / (1 - p) + mu ** (2 - p) / (2 - p))

    return Family(
        "tweedie",
        link=lambda mu: jnp.log(jnp.maximum(mu, _EPS)),
        linkinv=lambda eta: jnp.exp(jnp.clip(eta, -30, 30)),
        dmu_deta=lambda eta: jnp.exp(jnp.clip(eta, -30, 30)),
        variance=lambda mu: jnp.maximum(mu, _EPS) ** p,
        deviance=deviance,
    )


def _negativebinomial(theta: float = 1.0):
    """Log link; Var = mu + theta*mu^2 (reference: GLM negativebinomial
    family, ``hex/glm`` NB deviance with dispersion theta)."""
    def deviance(y, mu):
        mu = jnp.maximum(mu, _EPS)
        y1 = jnp.maximum(y, _EPS)
        t1 = jnp.where(y > 0, y * jnp.log(y1 / mu), 0.0)
        t2 = (y + 1.0 / theta) * jnp.log((1.0 + theta * y) / (1.0 + theta * mu))
        return 2.0 * (t1 - t2)

    return Family(
        "negativebinomial",
        link=lambda mu: jnp.log(jnp.maximum(mu, _EPS)),
        linkinv=lambda eta: jnp.exp(jnp.clip(eta, -30, 30)),
        dmu_deta=lambda eta: jnp.exp(jnp.clip(eta, -30, 30)),
        variance=lambda mu: jnp.maximum(mu, _EPS) * (1.0 + theta * jnp.maximum(mu, _EPS)),
        deviance=deviance,
    )


def _quasibinomial():
    """Binomial machinery on a CONTINUOUS y in [0,1] (reference:
    quasibinomial / fractionalbinomial families — same link/variance, y not
    required to be 0/1)."""
    b = _binomial()
    return Family(
        "quasibinomial",
        link=b.link, linkinv=b.linkinv, dmu_deta=b.dmu_deta,
        variance=b.variance,
        deviance=lambda y, mu: -2.0 * (
            jnp.where(y > 0, y * jnp.log(_clip01(mu) / jnp.maximum(y, _EPS)), 0.0)
            + jnp.where(y < 1, (1 - y) * jnp.log((1 - _clip01(mu))
                                                 / jnp.maximum(1 - y, _EPS)), 0.0)),
    )


_FAMILIES: dict[str, Callable[[], Family]] = {
    "gaussian": _gaussian,
    "binomial": _binomial,
    "bernoulli": _binomial,
    "poisson": _poisson,
    "gamma": _gamma,
    "tweedie": _tweedie,
    "negativebinomial": _negativebinomial,
    "quasibinomial": _quasibinomial,
    "fractionalbinomial": _quasibinomial,
}


def get_family(name: str, **kw) -> Family:
    try:
        f = _FAMILIES[name]
    except KeyError:
        raise ValueError(f"unknown family {name!r}; have {sorted(_FAMILIES)}") from None
    return f(**kw) if kw else f()
