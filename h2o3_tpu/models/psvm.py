"""PSVM — kernel Support Vector Machine via ICF + primal-dual interior point.

Reference: ``hex/psvm/PSVM.java`` (driver: gamma default 1/fullN
``PSVM.java:128-130``, ICF rank default sqrt(n) ``:230``), the Google-PSVM
algorithm ``hex/psvm/psvm/IncompleteCholeskyFactorization.java`` (pivoted ICF
of the label-signed kernel matrix) and ``hex/psvm/psvm/PrimalDualIPM.java``
(primal-dual IPM on the SVM dual with box constraints [0, C±] and the
equality y'x = 0; Newton system solved through Sherman-Morrison-Woodbury on
the rank-p ICF factor: ``icfA = H'DH + I`` then a p×p Cholesky,
``PrimalDualIPM.java:85-99``). Support vectors thresholded at
``sv_threshold`` (``RegulateAlphaTask``, ``PSVM.java:399-438``), bias rho
from free SVs (``CalculateRhoTask``).

TPU-native redesign: the reference streams the n×p ICF factor through MRTask
chunk passes with host-side p-vectors. Here the factor lives as one
row-sharded [n, p] array in HBM; every IPM iteration is a handful of
matmuls/reductions (MXU work: ``H'(d*v)``, rank-p Cholesky solve, [n,p]×[p]
matvec) in a single jitted step — XLA all-reduces the per-shard partials over
ICI where the reference's MRTask reduce crossed the cloud. The ICF pivot loop
is a ``lax.fori_loop`` with dynamic-slice pivot selection (static shapes,
kernel columns computed on the fly — never materializing the n×n kernel).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.data_info import DataInfo
from h2o3_tpu.models.job import Job
from h2o3_tpu.models.model_base import Model, ModelBuilder, make_model_key


def _kernel_col(X, norms, q: jax.Array, gamma: float):
    """Gaussian kernel column K(:, q) = exp(-gamma * ||x_i - x_q||^2)."""
    xq = lax_dynamic_row(X, q)
    nq = norms[q]
    d2 = jnp.maximum(norms + nq - 2.0 * (X @ xq), 0.0)
    return jnp.exp(-gamma * d2)


def lax_dynamic_row(X, q):
    return jax.lax.dynamic_slice_in_dim(X, q, 1, axis=0)[0]


@partial(jax.jit, static_argnames=("rank", "gamma"))
def _icf(X, y, rank: int, gamma: float, keep=None):
    """Pivoted incomplete Cholesky of Q = diag(y) K diag(y), rank columns.

    Reference: ``IncompleteCholeskyFactorization.java`` — greedy pivot on the
    largest diagonal residual; RBF diagonal starts at 1. ``keep`` masks rows
    excluded from training (zero weight / shard padding): they never pivot.
    """
    n = X.shape[0]
    norms = jnp.sum(X * X, axis=1)
    H0 = jnp.zeros((n, rank), jnp.float32)
    diag0 = jnp.ones(n, jnp.float32)        # K(x,x) = 1 for RBF
    dead0 = jnp.zeros(n, bool) if keep is None else ~keep

    def body(j, carry):
        H, diag, dead = carry
        # exhausted (residual ~0) or excluded rows must not pivot: a duplicate
        # re-pick would divide float32 round-off by ~1e-6 and fill H with noise
        cand = jnp.where(dead | (diag < 1e-8), -jnp.inf, diag)
        q = jnp.argmax(cand).astype(jnp.int32)
        usable = jnp.isfinite(cand[q])
        pivot = jnp.sqrt(jnp.maximum(diag[q], 1e-12))
        kcol = _kernel_col(X, norms, q, gamma) * y * y[q]   # label-signed Q col
        hq = lax_dynamic_row(H, q)                           # H[q, :]
        proj = H @ hq                                        # sum_k H[i,k] H[q,k]
        col = (kcol - proj) / pivot
        col = col.at[q].set(pivot)
        col = jnp.where(usable, col, 0.0)    # rank exhausted → zero column
        H = H.at[:, j].set(col)
        diag = jnp.maximum(diag - col * col, 0.0)
        dead = dead.at[q].set(True)
        return H, diag, dead

    H, _, _ = jax.lax.fori_loop(0, rank, body, (H0, diag0, dead0))
    return H


@jax.jit
def _smw_partial(H, d, b):
    """Solve the p×p system of SMW: returns vz = (I + H'DH)^{-1} H'(d*b)."""
    p = H.shape[1]
    db = d * b
    A = H.T @ (d[:, None] * H) + jnp.eye(p, dtype=H.dtype)
    L = jnp.linalg.cholesky(A)
    rhs = H.T @ db
    z1 = jax.scipy.linalg.solve_triangular(L, rhs, lower=True)
    return jax.scipy.linalg.solve_triangular(L.T, z1, lower=False)


@jax.jit
def _smw_solve(H, d, b):
    """(Sigma + HH')^{-1} b  via SMW with D = 1/Sigma = d (elementwise)."""
    vz = _smw_partial(H, d, b)
    return d * b - d * (H @ vz)


@jax.jit
def _ipm_step(H, y, c_vec, x, xi, la, nu, t_mu_num):
    """One primal-dual IPM Newton iteration (PrimalDualIPM.java:66-99)."""
    eps = 1e-9
    # surrogate gap (SurrogateGapTask): la'c + x'(xi - la)
    eta = jnp.sum(la * c_vec) + jnp.sum(x * (xi - la))
    t = t_mu_num / jnp.maximum(eta, 1e-30)

    # z = Qx + nu*y - 1 (computePartialZ + CheckConvergenceTask)
    z_q = H @ (H.T @ x)
    z = z_q + nu * y - 1.0
    resd = jnp.sqrt(jnp.sum((la - xi + z) ** 2))
    resp = jnp.abs(jnp.sum(y * x))

    # UpdateVarsTask
    m_lx = jnp.maximum(x, eps)
    m_ux = jnp.maximum(c_vec - x, eps)
    tlx = 1.0 / (t * m_lx)
    tux = 1.0 / (t * m_ux)
    xilx = jnp.maximum(xi / m_lx, eps)
    laux = jnp.maximum(la / m_ux, eps)
    d = 1.0 / (xilx + laux)
    zr = tlx - tux - z

    # delta nu (DeltaNuTask): dnu = sum1/sum2 over SMW partial solves
    vz = _smw_partial(H, d, zr)
    vl = _smw_partial(H, d, y)
    tw = zr - H @ vz
    tl = y - H @ vl
    sum1 = jnp.sum(y * (tw * d + x))
    sum2 = jnp.sum(y * tl * d)
    dnu = sum1 / sum2

    # delta x: (Sigma + Q)^{-1} (zr - dnu*y)
    dx = _smw_solve(H, d, zr - dnu * y)

    # dxi/dla (LineSearchTask)
    dxi = tlx - xilx * dx - xi
    dla = tux + laux * dx - la

    # step sizes: largest feasible, capped at 1, damped 0.99
    big = jnp.float32(3.4e38)
    ap = jnp.min(jnp.where(dx > 0, (c_vec - x) / dx,
                 jnp.where(dx < 0, -x / dx, big)))
    ad = jnp.min(jnp.minimum(jnp.where(dxi < 0, -xi / dxi, big),
                             jnp.where(dla < 0, -la / dla, big)))
    ap = jnp.minimum(ap, 1.0) * 0.99
    ad = jnp.minimum(ad, 1.0) * 0.99

    return (x + ap * dx, xi + ad * dxi, la + ad * dla, nu + ad * dnu,
            eta, resp, resd)


@jax.jit
def _sv_decision(X, norms_sv, Xsv, coef, gamma, rho):
    """f(x) = sum_j coef_j K(sv_j, x) + rho  (coef = alpha_j * y_j)."""
    nx = jnp.sum(X * X, axis=1)
    d2 = jnp.maximum(nx[:, None] + norms_sv[None, :] - 2.0 * (X @ Xsv.T), 0.0)
    K = jnp.exp(-gamma * d2)
    return K @ coef + rho


class PSVMModel(Model):
    algo = "psvm"

    def _score_raw(self, frame: Frame) -> jax.Array:
        o = self.output
        X = self.data_info.expand(frame)
        f = _sv_decision(X, o["sv_norms"], o["sv_x"], o["sv_coef"],
                         o["gamma"], o["rho"])
        p1 = jax.nn.sigmoid(f)   # pseudo-probability for the metrics stack
        return jnp.stack([1.0 - p1, p1], axis=1)

    def decision_function(self, frame: Frame) -> jax.Array:
        o = self.output
        X = self.data_info.expand(frame)
        return _sv_decision(X, o["sv_norms"], o["sv_x"], o["sv_coef"],
                            o["gamma"], o["rho"])


class PSVM(ModelBuilder):
    """Kernel SVM (binomial only, like the reference ``PSVM.can_build``)."""

    algo = "psvm"
    supports_regression = False

    @classmethod
    def defaults(cls) -> dict:
        return dict(
            ModelBuilder.defaults(),
            hyper_param=1.0,          # C  (PSVMModel.java:115)
            positive_weight=1.0,
            negative_weight=1.0,
            kernel_type="gaussian",
            gamma=-1.0,               # -1 → 1/fullN
            rank_ratio=-1.0,          # -1 → sqrt(n)
            sv_threshold=1e-4,
            max_iterations=200,
            mu_factor=10.0,
            feasible_threshold=1e-3,
            surrogate_gap_threshold=1e-3,
        )

    def _fit(self, job: Job, frame: Frame, x, y, weights) -> PSVMModel:
        p = self.params
        di = DataInfo.make(frame, x, standardize=True)
        X = di.expand(frame)
        yvec = frame.vec(y)
        if not yvec.is_categorical or len(yvec.domain) != 2:
            raise ValueError("PSVM supports only binomial classification")
        ycode = yvec.data.astype(jnp.float32)
        ypm = jnp.where(ycode > 0, 1.0, -1.0)       # {-1, +1}
        keep = (weights > 0) & (ycode >= 0)
        # zero-weight rows contribute nothing: zero their feature rows and pin
        # their box to C=0 so alpha stays 0 (static shapes; no sub-frame carve)
        X = jnp.where(keep[:, None], X, 0.0)
        n = X.shape[0]

        gamma = float(p["gamma"])
        if gamma <= 0:
            gamma = 1.0 / max(di.ncols_expanded, 1)
        rr = float(p["rank_ratio"])
        rank = int(np.sqrt(n)) if rr <= 0 else int(rr * n)
        rank = max(1, min(rank, n))

        H = _icf(X, ypm, rank, gamma, keep)
        H = jnp.where(keep[:, None], H, 0.0)

        c_pos = float(p["hyper_param"]) * float(p["positive_weight"])
        c_neg = float(p["hyper_param"]) * float(p["negative_weight"])
        c_vec = jnp.where(ypm > 0, c_pos, c_neg) * keep.astype(jnp.float32)
        c_vec = jnp.maximum(c_vec, 1e-12)

        # InitTask: la = xi = c/10, x = 0, nu = 0
        xv = jnp.zeros(n, jnp.float32)
        xi = c_vec / 10.0
        la = c_vec / 10.0
        nu = jnp.float32(0.0)
        t_mu_num = jnp.float32(float(p["mu_factor"]) * 2.0 * n)

        feas = float(p["feasible_threshold"])
        sgap = float(p["surrogate_gap_threshold"])
        for it in range(int(p["max_iterations"])):
            # the step returns eta/resp/resd measured on the INCOMING iterate
            # (reference checks convergence before stepping,
            # PrimalDualIPM.java:66-80) — so on convergence keep the pre-step
            # state: the extra Newton step past convergence is numerically
            # degenerate (t → inf) in float32.
            prev = (xv, xi, la, nu)
            xv, xi, la, nu, eta, resp, resd = _ipm_step(
                H, ypm, c_vec, xv, xi, la, nu, t_mu_num)
            job.update(min(0.9, it / max(int(p["max_iterations"]), 1)),
                       f"IPM iter {it}: sgap={float(eta):.3e}")
            converged = (float(resp) <= feas and float(resd) <= feas
                         and float(eta) <= sgap)
            if converged or not bool(jnp.isfinite(xv).all()):
                xv, xi, la, nu = prev
                break

        # RegulateAlphaTask: clamp, zero below sv_threshold, sign with label
        alpha = np.asarray(jax.device_get(xv))
        cv = np.asarray(jax.device_get(c_vec))
        alpha = np.clip(alpha, 0.0, cv)
        alpha[alpha < float(p["sv_threshold"])] = 0.0
        sv_idx = np.nonzero(alpha > 0)[0]
        ypm_h = np.asarray(jax.device_get(ypm))
        coef = alpha[sv_idx] * ypm_h[sv_idx]

        Xh = np.asarray(jax.device_get(X))
        Xsv = jnp.asarray(Xh[sv_idx]) if len(sv_idx) else jnp.zeros((1, X.shape[1]), jnp.float32)
        svcoef = jnp.asarray(coef.astype(np.float32)) if len(sv_idx) else jnp.zeros(1, jnp.float32)
        sv_norms = jnp.sum(Xsv * Xsv, axis=1)

        # rho from free SVs: mean(y_i - f0(x_i)) over 0 < alpha_i < C
        # (reference CalculateRhoTask on a sample of SVs)
        if len(sv_idx):
            free = sv_idx[(alpha[sv_idx] < cv[sv_idx] - 1e-8)]
            ref = free if len(free) else sv_idx
            ref = ref[:1000]
            f0 = jax.device_get(_sv_decision(jnp.asarray(Xh[ref]),
                                             sv_norms, Xsv, svcoef,
                                             gamma, jnp.float32(0.0)))
            rho = float(np.mean(ypm_h[ref] - np.asarray(f0)))
        else:
            rho = 0.0

        model = PSVMModel(
            make_model_key(self.algo, self.model_id), self.params, di, y,
            yvec.domain,
            output=dict(sv_x=Xsv, sv_coef=svcoef, sv_norms=sv_norms,
                        gamma=jnp.float32(gamma), rho=jnp.float32(rho),
                        svs_count=int(len(sv_idx)), rank=rank,
                        alpha=alpha))
        return model
