"""Job — async work units with progress/cancel.

Reference: ``water/Job.java`` (556 LoC): a keyed DKV object with start/update/
stop, progress fraction, status polling via REST ``/3/Jobs``. Here a Job wraps a
Python callable run either synchronously (library use) or on a worker thread
(REST use); device work is already async under JAX dispatch, so the Job's role
is bookkeeping: status, progress, timing, cancellation flag, exception capture.
"""

from __future__ import annotations

import contextvars
import threading
import time
import traceback
import uuid
from typing import Any, Callable

from h2o3_tpu.utils import lockwitness
from h2o3_tpu.utils import tracing as _tracing
from h2o3_tpu.utils.registry import DKV


class JobCancelled(Exception):
    pass


#: innermost-first stack of Jobs executing on this context — the REST path
#: nests a library Job inside the REST Job, and dispatch-retry accounting
#: must land on BOTH so /3/Jobs pollers see the retries the build absorbed
_JOB_STACK: contextvars.ContextVar[tuple] = contextvars.ContextVar(
    "h2o3_job_stack", default=())


def current_job() -> "Job | None":
    """The innermost Job executing on this context, if any."""
    stack = _JOB_STACK.get()
    return stack[0] if stack else None


def note_dispatch_retry(n: int = 1) -> None:
    """Credit ``n`` dispatch retries to every Job on this context's stack
    (called by :func:`h2o3_tpu.ops.map_reduce.retrying`)."""
    for j in _JOB_STACK.get():
        with j._lock:
            j.retries += int(n)


class Job:
    CREATED, RUNNING, DONE, FAILED, CANCELLED = "CREATED", "RUNNING", "DONE", "FAILED", "CANCELLED"

    def __init__(self, description: str, key: str | None = None,
                 max_runtime_secs: float = 0.0):
        self.key = key or f"job_{uuid.uuid4().hex[:12]}"
        self.description = description
        self.status = Job.CREATED
        self.progress = 0.0
        self.progress_msg = ""
        self.start_time: float | None = None
        self.end_time: float | None = None
        self.exception: BaseException | None = None
        self.traceback: str | None = None
        self.result: Any = None
        # reliability surface (docs/RELIABILITY.md): a deadline enforced
        # cooperatively in update()/should_stop, absorbed dispatch-retry
        # counts, and the attempt history of an exhausted retry budget
        self.max_runtime_secs = float(max_runtime_secs or 0.0)
        self.deadline_exceeded = False
        self.retries = 0
        self.retry_history: list | None = None
        self.auto_recovery_dir: str | None = None
        # elastic local-SGD membership decay (parallel/elastic.py): workers
        # ejected from the build's elastic group, served by JobV3 so
        # pollers watch throughput degrade instead of the job stalling
        self.workers_ejected = 0
        # guards every post-construction field mutation: the worker thread
        # writes status/progress/result while REST handler threads serialize
        # the job (schemas.job_v3 polls) — unlocked multi-field transitions
        # let a poller observe DONE with a stale result/progress
        self._lock = lockwitness.lock("models.job.Job._lock")
        self._cancel_requested = threading.Event()
        self._partial_accepted = False
        self._done = threading.Event()
        # the creating request's span context rides into the worker thread
        # (contextvars do not cross threads) so REST polling and execution
        # correlate under one trace; capture() RETAINS the trace until the
        # job span ends — the root span may close (response sent) before
        # the background thread even starts
        self._span_ctx = _tracing.TRACER.capture()
        self.trace_id = (self._span_ctx.trace_id
                         if self._span_ctx is not None else None)
        DKV.put(self.key, self)

    # -- driver side ---------------------------------------------------------

    def run(self, fn: Callable[["Job"], Any], background: bool = False) -> "Job":
        """Execute ``fn(job)``; fn should call ``job.update`` and check
        ``job.cancelled`` periodically (reference: ``Job.update``)."""
        if background:
            threading.Thread(target=self._exec, args=(fn,), daemon=True).start()
        else:
            self._exec(fn)
        return self

    def _exec(self, fn):
        # adopt the creating request's span context: the job's work appears
        # as a child span in that trace, and the retention taken at
        # construction is released when the job span (tree) ends
        token = _JOB_STACK.set((self,) + _JOB_STACK.get())
        with _tracing.TRACER.adopt(self._span_ctx,
                                   f"job:{self.description}", kind="job",
                                   attrs={"job": self.key}) as jspan:
            with self._lock:
                self.status = Job.RUNNING
                self.start_time = time.time()
            try:
                result = fn(self)      # the lock is NOT held across the work
                with self._lock:
                    # status is written LAST: pollers read fields lock-free
                    # in (status, progress, result) order, so once they
                    # observe a terminal status the other fields are final
                    self.result = result
                    self.progress = 1.0
                    self.status = (Job.CANCELLED
                                   if self._cancel_requested.is_set()
                                   else Job.DONE)
            except JobCancelled:
                with self._lock:
                    self.status = Job.CANCELLED
                if jspan is not None:
                    jspan.set_status("cancelled")
            except BaseException as e:
                # Job is the error carrier (REST/background polls read it);
                # the synchronous caller re-raises from job.exception. An
                # exhausted dispatch-retry budget (DispatchFailed) lands its
                # per-attempt history here so pollers see what was tried.
                with self._lock:
                    self.status = Job.FAILED
                    self.exception = e
                    self.traceback = traceback.format_exc()
                    self.retry_history = getattr(e, "history", None)
                if jspan is not None:
                    jspan.set_status("error")
                    jspan.set_attrs(exception=f"{type(e).__name__}: {e}")
            finally:
                _JOB_STACK.reset(token)
                with self._lock:
                    self.end_time = time.time()
                self._done.set()

    def _check_deadline(self) -> bool:
        """True once the job has outlived ``max_runtime_secs`` (reference:
        ``Job.update`` throws when the work budget is spent). Trips the
        cancellation flag so the normal-return path lands on CANCELLED —
        builders that keep partial results (GBM's built trees) return them
        and the job still reads as deadline-terminated."""
        if self.max_runtime_secs <= 0 or self.start_time is None \
                or self.deadline_exceeded:
            return self.deadline_exceeded
        if time.time() - self.start_time > self.max_runtime_secs:
            from h2o3_tpu.utils.telemetry import JOB_DEADLINE_EXCEEDED
            with self._lock:
                self.deadline_exceeded = True
                self.progress_msg = (f"max_runtime_secs="
                                     f"{self.max_runtime_secs:g} exceeded")
            self._cancel_requested.set()
            JOB_DEADLINE_EXCEEDED.inc()
        return self.deadline_exceeded

    def keep_partial(self) -> None:
        """A partial-result builder ACCEPTED the stop signal: it stopped
        its loop and is finalizing what it built. Later ``update`` calls
        must not re-raise, or finalization itself would be cancelled —
        the job still terminates CANCELLED."""
        with self._lock:
            self._partial_accepted = True

    def update(self, progress: float, msg: str = "") -> None:
        self._check_deadline()
        with self._lock:
            self.progress = float(progress)
            if not self.deadline_exceeded:
                self.progress_msg = msg
        if self._cancel_requested.is_set() and not self._partial_accepted:
            raise JobCancelled(self.key)

    # -- client side ---------------------------------------------------------

    @property
    def cancelled(self) -> bool:
        return self._cancel_requested.is_set()

    @property
    def should_stop(self) -> bool:
        """Cooperative stop signal — explicit cancel OR deadline. Builders
        that can keep partial results check this between megasteps/chunks
        and break instead of letting ``update`` raise."""
        return self._cancel_requested.is_set() or self._check_deadline()

    def cancel(self) -> None:
        self._cancel_requested.set()

    def join(self, timeout: float | None = None) -> "Job":
        self._done.wait(timeout)
        return self

    @property
    def run_time(self) -> float:
        end = self.end_time or time.time()
        return (end - self.start_time) if self.start_time else 0.0

    def __repr__(self) -> str:
        return f"Job({self.key}, {self.status}, {self.progress:.0%}, {self.description!r})"
