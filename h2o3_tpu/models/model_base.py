"""ModelBuilder / Model — the algorithm framework.

Reference: ``hex/ModelBuilder.java`` (2,171 LoC: param validation, train/valid
adaptation, Driver lifecycle, n-fold CV orchestration ``computeCrossValidation``
``:608``) and ``hex/Model.java`` (3,482 LoC: ``adaptTestForTrain``,
``score(Frame)`` → BigScore MRTask ``:1866-1959``, metrics hookup).

TPU-first redesign decisions:

- **CV and holdout masking via weights, not sub-frames.** The reference carves
  physical train/holdout frames per fold. Here every algorithm trains against a
  per-row weight vector (0 = excluded), so all folds share one device-resident
  design matrix and every fold's program has identical static shapes — XLA
  compiles once, folds differ only in an input array. (The reference itself
  routes user weights through ``DataInfo._weights``; we promote that to the
  universal mechanism.)
- **Scoring is a jitted batch program**, not a per-row ``score0`` virtual call:
  ``Model._score_raw`` maps the design matrix to predictions on-device.
"""

from __future__ import annotations

import os
import time
import uuid
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.types import VecType
from h2o3_tpu.frame.vec import Vec
from h2o3_tpu.models.data_info import DataInfo
from h2o3_tpu.models.job import Job
from h2o3_tpu.models.metrics import (
    binomial_metrics,
    multinomial_metrics,
    regression_metrics,
)
from h2o3_tpu.ops.map_reduce import map_reduce
from h2o3_tpu.utils import telemetry as _tm
from h2o3_tpu.utils.costs import COSTS
from h2o3_tpu.utils.registry import DKV, LOCKS
from h2o3_tpu.utils.timeline import timed_event


def megastep_k(default: int = 4) -> int:
    """K-step megastep width for device-resident convergence loops
    (``H2O3TPU_MEGASTEP_K``, default 4). The host fetches convergence
    scalars ONCE per K-step megastep instead of once per iteration — with
    JAX async dispatch the K compiled steps pipeline on device and the
    per-step host round-trip disappears from the critical path. Iteration
    counts and results stay exact: the megastep freezes its carry once the
    on-device convergence predicate fires, and the single fetch reconciles
    how many steps actually ran."""
    try:
        k = int(os.environ.get("H2O3TPU_MEGASTEP_K", "") or default)
    except ValueError:
        k = default
    return max(k, 1)


def publish_dispatch_audit(builder, loop: str, iterations: int,
                           host_syncs: int, device_dispatches: int) -> None:
    """Record a convergence loop's host-sync economy: how many blocking
    device→host fetches and compiled dispatches the loop paid for how many
    logical iterations. Feeds ``h2o3_dispatches_per_iteration{loop}`` and
    the builder's ``_dispatch_audit`` (bench embeds it as
    ``extra.dispatch_audit`` and refuses to stamp on a regression)."""
    iters = max(int(iterations), 1)
    audit = getattr(builder, "_dispatch_audit", None)
    if audit is None:
        audit = builder._dispatch_audit = {}
    audit[loop] = dict(iterations=int(iterations),
                       host_syncs=int(host_syncs),
                       device_dispatches=int(device_dispatches),
                       syncs_per_iteration=round(host_syncs / iters, 4))
    _tm.DISPATCHES_PER_ITER.labels(loop=loop).set(host_syncs / iters)


def _weight_rollup(w):
    """Per-shard (rows-with-weight, weight-sum) partial — the classic
    MRTask row count specialized to the padded-weight representation
    (padding and Skip rows carry weight 0, so they never count)."""
    return jnp.sum((w > 0).astype(jnp.float32)), jnp.sum(w)


class ModelParameters(dict):
    """Parameter bag with attribute access and declared defaults.

    Reference: per-algo ``Model.Parameters`` Iced classes with ``@API`` fields;
    here a dict so the REST schema layer can serialize uniformly.
    """

    def __getattr__(self, k):
        try:
            return self[k]
        except KeyError:
            raise AttributeError(k) from None

    def __setattr__(self, k, v):
        self[k] = v


class Model:
    """A trained model: artifacts + scoring + metrics (reference: ``hex.Model``)."""

    algo = "model"

    def __init__(self, key: str, params: ModelParameters, data_info: DataInfo | None,
                 response_column: str | None, response_domain: tuple[str, ...] | None,
                 output: dict[str, Any]):
        self.key = key
        # snapshot: the builder's live params dict must not alias into the
        # trained model (builder stays reusable / mutable after train)
        self.params = ModelParameters(params)
        self.data_info = data_info
        self.response_column = response_column
        self.response_domain = response_domain  # None for regression
        self.output = output                    # algo artifacts (device arrays ok)
        self.training_metrics = None
        self.validation_metrics = None
        self.cross_validation_metrics = None
        self.cv_holdout_predictions = None   # [plen] or [plen, K] OOF preds
        self.cv_holdout_mask = None
        # (metric_names, nfolds, rows) for the per-fold summary table
        self.cv_metrics_summary = None
        self.run_time_ms: int = 0
        # per-scoring-event table (reference: Model.Output._scoring_history
        # TwoDimTable, surfaced as h2o-py model.scoring_history()):
        # (columns, rows) where columns = [(name, type, format), ...]
        self.scoring_history: tuple[list, list] | None = None
        # transformers applied to every scoring frame (reference: AutoML
        # bundles the TargetEncoder into the model's scoring pipeline)
        self.preprocessors: list = []

    # -- problem type --------------------------------------------------------

    @property
    def nclasses(self) -> int:
        return len(self.response_domain) if self.response_domain else 0

    @property
    def is_classifier(self) -> bool:
        return self.nclasses >= 2

    # -- scoring -------------------------------------------------------------

    def _score_raw(self, frame: Frame) -> jax.Array:
        """Device predictions: [plen] for regression, [plen, nclasses] probs
        for classification. Implemented per algorithm."""
        raise NotImplementedError

    def _preprocess(self, frame: Frame) -> Frame:
        for p in self.preprocessors:
            if hasattr(p, "is_applied") and p.is_applied(frame):
                continue
            frame = p.transform(frame)
        return frame

    def predict(self, frame: Frame) -> Frame:
        """Score a frame (reference: ``Model.score`` → prediction frame)."""
        frame = self._preprocess(frame)
        raw = self._score_raw(frame)
        n = frame.nrows
        if not self.is_classifier:
            return Frame(["predict"], [Vec.from_device(raw, n, VecType.NUM)])
        labels = decision_labels(self, raw).astype(jnp.int32)
        names = ["predict"] + [f"p{d}" for d in self.response_domain]
        vecs = [Vec.from_device(labels, n, VecType.CAT, domain=self.response_domain)]
        for k in range(self.nclasses):
            vecs.append(Vec.from_device(raw[:, k], n, VecType.NUM))
        return Frame(names, vecs)

    def model_performance(self, frame: Frame):
        """Compute metrics on a (possibly new) frame (reference:
        ``ModelMetrics`` builders run inside BigScore)."""
        if self.response_column not in frame:
            raise ValueError(f"frame lacks response column {self.response_column!r}")
        frame = self._preprocess(frame)
        raw = self._score_raw(frame)
        yvec = frame.vec(self.response_column)
        mask = frame.row_mask()
        from h2o3_tpu.models.data_info import response_adapted
        y, valid = response_adapted(
            yvec, self.response_domain if self.is_classifier else None)
        return compute_metrics(raw, y, mask & valid, self.nclasses)

    # -- persistence hooks ---------------------------------------------------

    def download_mojo(self, path: str) -> str:
        """Export a portable scoring artifact (h2o-py: ``download_mojo``)."""
        from h2o3_tpu.genmodel.mojo import write_mojo
        return write_mojo(self, path)

    def download_pojo(self, path: str) -> str:
        """Export standalone scoring source (h2o-py: ``download_pojo``; here
        a numpy-only Python module instead of a Java class)."""
        from h2o3_tpu.genmodel.codegen import download_pojo
        return download_pojo(self, path)

    def save(self, path: str) -> str:
        """Binary model save (h2o-py: ``h2o.save_model``)."""
        from h2o3_tpu.persist.model_io import save_model
        return save_model(self, path)

    def __repr__(self) -> str:
        lines = [f"{type(self).__name__}(key={self.key!r})"]
        if self.training_metrics:
            lines.append(f"  train: {self.training_metrics!r}")
        if self.validation_metrics:
            lines.append(f"  valid: {self.validation_metrics!r}")
        if self.cross_validation_metrics:
            lines.append(f"  cv:    {self.cross_validation_metrics!r}")
        return "\n".join(lines)


def decision_labels(model, raw):
    """Class labels from raw ``[n, K]`` probabilities — THE one home of the
    reset-able binomial decision threshold (reference:
    ``AstModelResetThreshold`` / ``defaultThreshold``; argmax == 0.5) vs
    argmax choice. Array-agnostic (numpy or jax input, same-kind output):
    ``Model.predict`` and the serving tier's batched finalizer both call
    here, so the two paths cannot drift."""
    thr = getattr(model, "_default_threshold", None)
    if thr is not None and getattr(model, "nclasses", 0) == 2:
        return raw[:, 1] >= float(thr)
    return raw.argmax(axis=1)


def compute_metrics(raw: jax.Array, y: jax.Array, mask: jax.Array, nclasses: int):
    if nclasses == 0:
        return regression_metrics(raw, y, mask)
    if nclasses == 2:
        return binomial_metrics(raw[:, 1], y, mask)
    return multinomial_metrics(raw, y, mask, nclasses)


class ModelBuilder:
    """Algorithm driver base (reference: ``hex.ModelBuilder`` lifecycle:
    validate params → Driver → CV → metrics)."""

    algo = "base"
    supports_classification = True
    supports_regression = True

    def __init__(self, **params):
        self.params = ModelParameters(self.defaults())
        unknown = set(params) - set(self.params) - {"model_id"}
        if unknown:
            raise ValueError(f"{type(self).__name__}: unknown parameters {sorted(unknown)}; "
                             f"valid: {sorted(self.params)}")
        self.params.update(params)
        self.model_id = params.get("model_id")
        self.job: Job | None = None
        self.model: Model | None = None

    # -- subclass contract ---------------------------------------------------

    @classmethod
    def defaults(cls) -> dict:
        return dict(
            seed=-1,
            nfolds=0,
            # Modulo | Random | Stratified (reference hex/FoldAssignment.java)
            fold_assignment="Modulo",
            fold_column=None,           # explicit per-row fold ids
            weights_column=None,
            ignored_columns=None,
            max_runtime_secs=0.0,   # job deadline, enforced in Job.update()
            keep_cross_validation_predictions=False,
            checkpoint=None,     # prior model (key or Model) to resume from
            # auto-checkpoint dir for long builds (reference:
            # -auto_recovery_dir): GBM/XGBoost/DL snapshot a partial model
            # every H2O3TPU_CHECKPOINT_EVERY trees/epochs; a restarted
            # train() with the same dir+params resumes from the snapshot
            # through the checkpoint machinery (docs/RELIABILITY.md)
            auto_recovery_dir=None,
            custom_metric_func=None,   # python callable (preds, y, w) -> value
        )

    def validate_request(self) -> None:
        """Fail-fast validation the REST layer runs BEFORE starting the
        background job: raise ``ValueError`` for a request no build could
        ever satisfy (the server maps it to a structured 400 instead of a
        FAILED job the poller unwraps later). Subclasses extend."""

    def supports_auto_recovery(self) -> bool:
        """True when this builder actually WRITES auto-checkpoint snapshots
        under ``auto_recovery_dir`` (GBM/XGBoost-gbtree chunk snapshots, DL
        epoch snapshots). Base builders don't — advertising
        ``auto_recoverable`` for them would promise a resume that silently
        restarts from scratch."""
        return False

    def _resolve_checkpoint(self) -> "Model | None":
        """Resolve the ``checkpoint`` param to a prior Model (reference:
        ``Model.Parameters._checkpoint``, trees ``SharedTree.java:144,241``,
        DL ``DeepLearning.java:348``)."""
        cp = self.params.get("checkpoint")
        if cp is None:
            return None
        if isinstance(cp, Model):
            self.params["checkpoint"] = cp.key   # don't drag the model object
            return cp                            # into param snapshots/pickles
        model = DKV.get(cp)
        if model is None:
            raise ValueError(f"checkpoint model {cp!r} not found in DKV")
        if model.algo != self.algo:
            raise ValueError(f"checkpoint is a {model.algo!r} model; "
                             f"this builder is {self.algo!r}")
        return model

    def _fit(self, job: Job, frame: Frame, x: list[str], y: str | None,
             weights: jax.Array) -> Model:
        """Train on rows where weights>0; must honor job.update/cancel."""
        raise NotImplementedError

    def _apply_custom_metric(self, model: Model, frame: Frame, y: str,
                             weights, fn, mm=None) -> None:
        """Evaluate a user metric callable on predictions over ``frame`` and
        attach it to ``mm`` (default: training metrics). Reference:
        custom_metric_func via water/udf — computed for every scored frame
        (CMetricScoringTask), so validation metrics carry it too."""
        import numpy as np

        from h2o3_tpu.models.data_info import response_adapted
        from h2o3_tpu.parallel.distributed import fetch
        raw = fetch(model._score_raw(frame))[: frame.nrows]
        yv, valid = response_adapted(
            frame.vec(y),
            model.response_domain if model.is_classifier else None)
        ok = fetch(frame.row_mask() & valid)[: frame.nrows]
        w = fetch(weights)[: frame.nrows] * ok if weights is not None else ok
        value = fn(np.asarray(raw), fetch(yv)[: frame.nrows], np.asarray(w))
        mm = model.training_metrics if mm is None else mm
        try:
            mm.custom_metric_name = getattr(fn, "__name__", "custom")
            mm.custom_metric_value = float(value)
        except AttributeError:   # frozen dataclass
            object.__setattr__(mm, "custom_metric_name",
                               getattr(fn, "__name__", "custom"))
            object.__setattr__(mm, "custom_metric_value", float(value))

    # -- public train API (mirrors h2o-py estimator.train) -------------------

    def train(self, x: Sequence[str] | None = None, y: str | None = None,
              training_frame: Frame | None = None, validation_frame: Frame | None = None,
              weights: jax.Array | None = None) -> Model:
        frame = training_frame
        if frame is None:
            raise ValueError("training_frame is required")
        if y is None and not getattr(self, "unsupervised", False):
            raise ValueError(f"{self.algo} is supervised: y is required")
        # slice-bound build (orchestration/scheduler.py lease): reshard the
        # inputs onto the bound mesh ONCE, up front — every downstream mesh
        # (row_sharding, map_reduce, tree.hist_mesh from input shardings)
        # then resolves inside the slice, so a build compiled on slice 0
        # never embeds slice 1's devices and concurrent builds never share
        # a collective rendezvous
        from h2o3_tpu.parallel import mesh as _pmesh
        bound = _pmesh.bound_mesh()
        # user-facing name for Job/extension surfaces: the reshard below
        # swaps in an internal `{key}::mesh[...]` view key that means
        # nothing to the user (and may be evicted before they look)
        user_frame_key = frame.key
        if bound is not None:
            frame = frame.on_mesh(bound)
            if validation_frame is not None:
                validation_frame = validation_frame.on_mesh(bound)
            if weights is not None and isinstance(weights, jax.Array):
                from jax.sharding import NamedSharding, PartitionSpec as _P
                weights = jax.device_put(
                    weights, NamedSharding(bound, _P(_pmesh.ROWS)))
            from h2o3_tpu.utils.tracing import TRACER as _trc
            _trc.mark_active(mesh_devices=",".join(
                str(i) for i in _pmesh.mesh_device_ids(bound)))
        ignored = set(self.params.get("ignored_columns") or [])
        if self.params.get("weights_column"):
            ignored.add(self.params["weights_column"])
        if self.params.get("offset_column"):
            ignored.add(self.params["offset_column"])
        if self.params.get("fold_column"):
            ignored.add(self.params["fold_column"])
        x = [c for c in (x if x is not None else frame.names)
             if c != y and c not in ignored and frame.vec(c).type.on_device]
        if not x:
            raise ValueError("no usable feature columns")
        self._validate(frame, x, y)

        base_w = frame.row_mask().astype(jnp.float32)
        if self.params.get("weights_column"):
            base_w = base_w * frame.vec(self.params["weights_column"]).data
        if weights is not None:
            base_w = base_w * weights

        # stashed for trainers that score held-out data mid-train (GBM/DRF
        # early stopping on the validation frame, ScoreKeeper semantics)
        self._validation_frame = validation_frame
        self._x_cols = x
        self._y_col = y

        # auto-recovery: when a prior run with this dir+params left a
        # partial-model snapshot, resume through the ordinary checkpoint
        # machinery (seed-derived per-tree keys make the resumed GBM
        # bit-identical to an uninterrupted run); an explicit checkpoint=
        # from the caller wins over the snapshot
        self._build_recovery = None
        self._resume_snap_key = None
        rdir = self.params.get("auto_recovery_dir")
        if rdir and not self.supports_auto_recovery():
            # no snapshot will ever be written: keep the job's
            # auto_recoverable contract honest rather than advertising a
            # resume that would restart from scratch
            rdir = None
        if rdir:
            from h2o3_tpu.persist.recovery import BuildRecovery
            self._build_recovery = BuildRecovery(str(rdir))
            if not self.params.get("checkpoint"):
                snap = self._build_recovery.load_snapshot(self.params)
                if snap is not None:
                    # load_model already re-registered it in the DKV (so
                    # every checkpoint consumer — CV refits resolve by key —
                    # can see it); remember the key to remove after the run
                    self._resume_snap_key = snap.key
                    self.params["checkpoint"] = snap

        self.job = Job(f"{self.algo} on {user_frame_key or 'frame'}",
                       max_runtime_secs=float(
                           self.params.get("max_runtime_secs") or 0.0))
        self.job.auto_recovery_dir = rdir
        if getattr(self, "_cancel_requested_early", False):
            # a REST cancel raced job creation (see server._run_build_job):
            # honor it now, before the build starts
            self.job.cancel()
        t0 = time.time()

        self._score_series = None   # per-train metric series (tree builders)

        def driver(job: Job) -> Model:
            from h2o3_tpu.utils import extensions as _ext
            # Lockable protocol (water/Lockable.java): the build holds the
            # write lock on its (named) destination key from first fit to
            # final DKV.put, so a concurrent DELETE waits and a mid-build
            # delete cannot be resurrected by the final put.  Anonymous
            # (auto-generated) keys are unguessable, so a None model_id
            # needs no lock.  Covers every build path: direct, REST, grid,
            # AutoML (reentrant for the REST path, which already holds it).
            with LOCKS.write(self.model_id):
                return locked_driver(job, _ext)

        def locked_driver(job: Job, _ext) -> Model:
            _ext.report("model_build_start", algo=self.algo, job=job.key,
                        frame=user_frame_key)
            # build wall-time lands in the timeline ring (kind "model") and
            # in the metrics registry; scoring history carries it through
            # run_time_ms (reference: TwoDimTable duration column)
            # the fit runs under a CostMeter site scope so persistent
            # compile-cache hits/misses during the build credit this algo
            # (utils/compile_cache.py by_site — docs/OBSERVABILITY.md
            # "Compute")
            with timed_event("model", f"{self.algo}:fit"), \
                    COSTS.scope(f"fit:{self.algo}"):
                model = self._fit(job, frame, x, y, base_w)
                # effective-rows rollup through the EXPLICIT MRTask path
                # (reference: every build's GLMIterationTask-style row
                # count): one tiny psum per build keeps partition dispatch —
                # and its per-shard straggler attribution — in every model's
                # trace subtree, and nobs/weight-sum land in the output.
                # Runs AFTER fit over the weights the fit actually used
                # (GLM Skip zeroes NA-row weights into _metrics_weights)
                w_eff = getattr(self, "_metrics_weights", None)
                if w_eff is None:
                    w_eff = base_w
                nobs_d, wsum_d = map_reduce(_weight_rollup, w_eff)
                nobs, wsum = (float(v) for v in
                              jax.device_get((nobs_d, wsum_d)))
                model.output.setdefault("effective_nobs", int(nobs))
                model.output.setdefault("weight_sum", wsum)
            # a builder may shrink the effective row set during fit (GLM
            # missing_values_handling=Skip zeroes NA-row weights); metrics
            # and CV must see the same rows the fit saw (reference: Skip
            # rows carry weight 0 everywhere)
            w_metrics = getattr(self, "_metrics_weights", None)
            if w_metrics is None:
                w_metrics = base_w
            model.run_time_ms = int((time.time() - t0) * 1000)
            _tm.MODEL_BUILDS.labels(algo=self.algo).inc()
            _tm.MODEL_BUILD_SECONDS.labels(algo=self.algo).observe(
                model.run_time_ms / 1000.0)
            # user UDF metric: either an in-process python callable
            # (preds, y, w) -> value, or the reference's wire form
            # "python:key=module.Class" naming a /3/PutKey upload
            # (water/udf CFuncRef; h2o.upload_custom_metric). Resolved
            # up-front so validation scoring sees the callable even when
            # training metrics are absent (CMetricScoringTask computes the
            # custom metric on EVERY scored frame).
            cmf = self.params.get("custom_metric_func")
            if isinstance(cmf, str) and y is not None:
                from h2o3_tpu.utils import udf as _udf
                _, key_name, _qual = _udf.parse_ref(cmf)
                cmf = _udf.metric_callable(_udf.load_cfunc(cmf), key_name,
                                           model=model)
            if y is not None:
                model.training_metrics = self._holdout_metrics(model, frame,
                                                               y, w_metrics)
                if cmf is not None and model.training_metrics is not None:
                    self._apply_custom_metric(model, frame, y, w_metrics, cmf)
            if validation_frame is not None and y is not None:
                model.validation_metrics = model.model_performance(validation_frame)
                if cmf is not None and model.validation_metrics is not None:
                    # weights apply on every scored frame, validation included
                    vw = None
                    wc = self.params.get("weights_column")
                    if wc and wc in validation_frame.names:
                        vw = (validation_frame.row_mask().astype(jnp.float32)
                              * validation_frame.vec(wc).data)
                    self._apply_custom_metric(model, validation_frame, y,
                                              vw, cmf,
                                              mm=model.validation_metrics)
            # snapshot BEFORE the CV refits below clobber the per-iteration
            # series on this (shared) builder instance
            model.scoring_history = self._scoring_history(model)
            nfolds = int(self.params.get("nfolds") or 0)
            if self.params.get("fold_column"):
                # an explicit fold column defines the folds outright
                # (reference: ModelBuilder.init rejects combining it with
                # nfolds and requires >= 2 distinct fold values)
                if nfolds:
                    raise ValueError(
                        "specify either fold_column or nfolds, not both")
                nfolds = self._fold_column_cardinality(frame)
                if nfolds < 2:
                    raise ValueError(
                        f"fold_column {self.params['fold_column']!r} must "
                        "hold at least 2 distinct folds")
            if nfolds >= 2 and y is not None:
                model.cross_validation_metrics = self._cross_validate(
                    job, frame, x, y, w_metrics, nfolds, model)
            # artifact size (summed bytes of the model's array tree —
            # coefficients / tree arrays / DL weights) rides in the output
            # and is what /3/Memory reports for the model's DKV key
            from h2o3_tpu.utils.memory import array_tree_bytes
            model.output.setdefault("artifact_bytes",
                                    array_tree_bytes(model))
            DKV.put(model.key, model)
            _ext.report("model_build_end", algo=self.algo, model=model.key,
                        job=job.key)
            return model

        self.model = self.job.run(driver)
        if bound is not None and _pmesh.rehome_requested() \
                and self.job.result is not None:
            # the model's artifacts (coefficients, tree heaps, OOF
            # predictions) are committed to the slice's devices; re-home
            # them onto the scheduler's base mesh so downstream consumers
            # (predict on base-mesh frames, stacked-ensemble level-one
            # assembly across models built on DIFFERENT slices) never mix
            # device sets in one program — XLA raises on incompatible
            # devices
            _pmesh.rehome(self.job.result, _pmesh.rehome_target())
        if self._resume_snap_key:
            # the transient resume-source model has served its purpose
            DKV.remove(self._resume_snap_key)
        if self.job.status == Job.FAILED:
            raise self.job.exception
        if self.job.status == Job.CANCELLED and self.job.result is None:
            # the build stopped (explicit cancel or max_runtime_secs) before
            # it could produce even a partial model — surfacing None would
            # read as success; builders that keep partial results (GBM's
            # built trees) return them with the job still marked CANCELLED
            from h2o3_tpu.models.job import JobCancelled
            raise JobCancelled(
                f"{self.algo} build cancelled"
                + (" (max_runtime_secs exceeded)"
                   if self.job.deadline_exceeded else ""))
        if self.job.status == Job.DONE and self._build_recovery is not None:
            # only a COMPLETED build retires its snapshot: a deadline-
            # cancelled partial keeps it so a rerun resumes where it stopped
            self._build_recovery.complete()
        return self.job.result

    def train_segments(self, segments: list[str], y: str,
                       training_frame: Frame, x: list[str] | None = None,
                       segment_models_id: str | None = None):
        """Train one model per unique segment combo (h2o-py
        ``estimator.train_segments``; reference hex/segments)."""
        from h2o3_tpu.orchestration.segments import train_segments
        return train_segments(self, segments, training_frame, y, x=x,
                              segment_models_id=segment_models_id)

    # -- helpers -------------------------------------------------------------

    def _scoring_history(self, model: Model):
        """Per-scoring-event table hook (reference: ``SharedTree.java:798``
        ``doScoringAndSaveModel`` fills a TwoDimTable per iteration).
        Iterative builders override; returns (columns, rows) or None."""
        return None

    def _history_table(self, model: Model, value_cols, values):
        """Shared timestamp/duration scaffold for scoring-history rows:
        ``value_cols`` = [(name, type, format), ...], ``values`` = one value
        list per scoring event (duration is interpolated from the total
        train wall-clock — the events happened inside one fused program)."""
        if not values:
            return None
        stamp = time.strftime("%Y-%m-%d %H:%M:%S")
        total_s = model.run_time_ms / 1000.0
        n = len(values)
        cols = [("timestamp", "string", "%s"),
                ("duration", "string", "%s")] + list(value_cols)
        rows = [[stamp, f"{total_s * (i + 1) / n:.3f} sec", *vals]
                for i, vals in enumerate(values)]
        return cols, rows

    def _validate(self, frame: Frame, x: list[str], y: str | None) -> None:
        if y is not None:
            yv = frame.vec(y)
            if yv.is_categorical and not self.supports_classification:
                raise ValueError(f"{self.algo} does not support a categorical response")
            if not yv.is_categorical and not self.supports_regression:
                raise ValueError(f"{self.algo} requires a categorical response")

    def _holdout_metrics(self, model: Model, frame: Frame, y: str, w: jax.Array):
        from h2o3_tpu.models.data_info import response_as_float
        # a fit that already produced training-row predictions (e.g. the
        # boosting scan's final margins) caches them on the transient builder
        # — skip the full re-score of the training frame
        raw = getattr(self, "_last_train_raw", None)
        self._last_train_raw = None
        if raw is None:
            raw = model._score_raw(frame)
        yy, valid = response_as_float(frame.vec(y))
        return compute_metrics(raw, yy, (w > 0) & valid, model.nclasses)

    def _fold_column_values(self, frame: Frame) -> np.ndarray:
        """Per-row fold codes from the explicit fold column: distinct
        values map to 0..K-1 in sorted order (reference:
        ``FoldAssignment.fromUserFoldSpecification``).  NA fold values are
        rejected like the reference does — a silent default would leak
        those rows into every fold's training set.  Cached per frame:
        train() needs it for the cardinality and _cross_validate for the
        ids — one host pass, not two."""
        cache = getattr(self, "_fold_values_cache", None)
        if cache is not None and cache[0] is frame:
            return cache[1]
        v = frame.vec(self.params["fold_column"])
        vals = np.asarray(v.data)[: frame.plen].astype(np.float64)
        body = vals[: frame.nrows]
        na = (body < 0) if v.type is VecType.CAT else np.isnan(body)
        if na.any():
            raise ValueError(
                f"fold_column {self.params['fold_column']!r} has "
                f"{int(na.sum())} missing values; every row needs a fold")
        uniq = np.unique(body)
        # padding rows map to fold 0; they carry weight 0 everywhere
        safe = np.where(np.isnan(vals) | (vals < uniq[0]), uniq[0], vals)
        out = np.searchsorted(uniq, safe).clip(0, len(uniq) - 1) \
            .astype(np.int32)
        self._fold_values_cache = (frame, out)
        return out

    def _fold_column_cardinality(self, frame: Frame) -> int:
        return int(self._fold_column_values(frame).max()) + 1

    def _fold_ids(self, frame: Frame, nfolds: int, yvec=None) -> jax.Array:
        """Fold assignment vector (reference: ``hex/FoldAssignment.java``):
        Modulo (default), Random, Stratified (per-class round-robin so
        every fold sees every response class), or an explicit fold
        column."""
        plen = frame.plen
        if self.params.get("fold_column"):
            return jnp.asarray(self._fold_column_values(frame))
        assignment = self.params.get("fold_assignment", "Modulo")
        if assignment == "Random":
            seed = int(self.params.get("seed") or -1)
            key = jax.random.PRNGKey(seed if seed >= 0 else 907)
            return jax.random.randint(key, (plen,), 0, nfolds)
        if assignment == "Stratified":
            if yvec is None or not yvec.is_categorical:
                # reference FoldAssignment: stratification needs a
                # categorical response — refuse rather than silently
                # degrade to Modulo
                raise ValueError("fold_assignment='Stratified' requires a "
                                 "categorical response")
            codes = np.asarray(yvec.data)[:plen]
            ids = np.arange(plen, dtype=np.int32) % nfolds
            for c in np.unique(codes[codes >= 0]):
                rows = np.where(codes == c)[0]
                ids[rows] = np.arange(len(rows)) % nfolds
            return jnp.asarray(ids)
        return jnp.arange(plen) % nfolds

    def _cross_validate(self, job: Job, frame: Frame, x: list[str], y: str,
                        base_w: jax.Array, nfolds: int, model: Model | None = None):
        """K-fold CV: same compiled program per fold, weights differ
        (reference: ``ModelBuilder.computeCrossValidation`` builds physical
        sub-frames; see module docstring for why masking replaces that)."""
        from h2o3_tpu.models.data_info import response_as_float
        yvec = frame.vec(y)
        folds = self._fold_ids(frame, nfolds, yvec)
        yy, valid = response_as_float(yvec)
        raws, masks = [], []
        for k in range(nfolds):
            w_train = base_w * (folds != k)
            cv_builder = type(self)(**{**self.params, "nfolds": 0})
            cv_model = cv_builder._fit(job, frame, x, y, w_train)
            raw_k = cv_model._score_raw(frame)
            hold = (base_w > 0) & (folds == k) & valid
            raws.append(raw_k)
            masks.append(hold)
        # pool holdout predictions into one metrics pass (reference: CV main
        # metrics are computed on merged holdout predictions)
        nclass = len(yvec.domain) if yvec.is_categorical else 0
        pooled = sum(jnp.where((m[:, None] if r.ndim == 2 else m), r, 0.0)
                     for r, m in zip(raws, masks))
        any_mask = jnp.stack(masks).any(axis=0)
        if model is not None and self.params.get("keep_cross_validation_predictions"):
            # out-of-fold predictions feed the StackedEnsemble metalearner
            # (reference: keep_cross_validation_predictions + holdout frames)
            model.cv_holdout_predictions = pooled
            model.cv_holdout_mask = any_mask
        if model is not None:
            # per-fold metric table (reference: ModelBuilder
            # cross_validation_metrics_summary TwoDimTable — mean/sd +
            # one column per fold; h2o-py's
            # model.cross_validation_metrics_summary() reads it)
            per_fold = [compute_metrics(r, yy, m, nclass)
                        for r, m in zip(raws, masks)]
            names = [f for f in ("mse", "rmse", "logloss", "auc", "pr_auc",
                                 "mae", "r2", "mean_per_class_error")
                     if getattr(per_fold[0], f, None) is not None]
            rows = []
            for f in names:
                vals = np.array([float(getattr(pf, f)) for pf in per_fold])
                # an empty-holdout fold (all rows zero-weight / NA
                # response) yields NaN metrics; mean/sd summarize the
                # FINITE folds so one bad fold can't blank the table
                fin = vals[np.isfinite(vals)]
                mean = float(fin.mean()) if fin.size else float("nan")
                sd = float(fin.std(ddof=1)) if fin.size > 1 else 0.0
                rows.append([f, mean, sd] + [float(v) for v in vals])
            model.cv_metrics_summary = (names, nfolds, rows)
        return compute_metrics(pooled, yy, any_mask, nclass)


def make_model_key(algo: str, model_id: str | None) -> str:
    return model_id or f"{algo}_{uuid.uuid4().hex[:10]}"
