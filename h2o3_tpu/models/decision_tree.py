"""Single Decision Tree (SDT).

Reference: ``hex/tree/dt/DT.java`` — one CART grown level-wise with
entropy-based binary splits, binomial or regression response. Here the shared
level-synchronous histogram engine grows the tree in one shot: with zero prior
score the second-order leaf objective reduces to the weighted node mean, so a
single "boosting" step with identity gradients IS the CART fit (leaf = mean
response; for a 0/1 response that mean is the class-1 probability).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.gbm import SharedTreeBuilder, SharedTreeModel, tree_matrix
from h2o3_tpu.models.job import Job
from h2o3_tpu.models.model_base import make_model_key
from h2o3_tpu.models.tree import TreeParams, grow_trees_batched
from h2o3_tpu.models.data_info import response_as_float


class DecisionTreeModel(SharedTreeModel):
    algo = "decisiontree"

    def _score_raw(self, frame: Frame):
        raw = self._tree_raw_sum(frame)
        if self.nclasses == 2:
            p = jnp.clip(raw, 0.0, 1.0)
            return jnp.stack([1 - p, p], axis=1)
        return raw


class DecisionTree(SharedTreeBuilder):
    """h2o-py surface: ``H2ODecisionTreeEstimator`` (algo ``dt``)."""

    algo = "decisiontree"

    @classmethod
    def defaults(cls) -> dict:
        d = super().defaults()
        d.update(max_depth=10, min_rows=10.0, nbins=64, ntrees=1)
        return d

    def _fit(self, job: Job, frame: Frame, x, y, weights) -> DecisionTreeModel:
        p = self.params
        yvec = frame.vec(y)
        if yvec.is_categorical and yvec.cardinality() != 2:
            raise ValueError("DecisionTree supports binary or numeric responses")
        X, edges, binned, yy, valid, yvec, domains = self._prepare(frame, x, y, weights)
        w = weights * valid
        yy = jnp.where(w > 0, yy, 0.0)

        tp = TreeParams(max_depth=int(p["max_depth"]), nbins=int(p["nbins"]),
                        min_rows=float(p["min_rows"]), reg_lambda=0.0,
                        min_split_improvement=float(p["min_split_improvement"]))
        # identity-gradient trick: g = -w*y, h = w ⇒ leaf = Σwy/Σw (node mean)
        g = -w * yy
        h = w
        key = jax.random.PRNGKey(int(p.get("seed") or 0) or 5)
        trees, _ = grow_trees_batched(binned, edges, g[None], h[None], w[None],
                                      tp, jnp.ones(binned.shape[1], bool),
                                      key=key, cat_feats=self._cat_feats)
        job.update(1.0, "tree grown")

        return DecisionTreeModel(
            key=make_model_key(self.algo, self.model_id),
            params=self.params, data_info=None, response_column=y,
            response_domain=yvec.domain if yvec.is_categorical else None,
            output=dict(trees=trees, x_cols=list(x), feat_domains=domains,
                        **self._cat_output()),
        )
