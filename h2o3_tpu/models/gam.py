"""GAM — generalized additive models via spline basis expansion + GLM.

Reference: ``hex/gam/`` (4.7 kLoC): selected numeric predictors are expanded
into penalized cubic-regression-spline bases on quantile knots
(``GamSplines/``), the expanded frame is handed to GLM with a per-spline-group
ridge penalty, and the model scores by re-expanding at predict time
(``GAMModel.java``).

TPU-native: the natural cubic spline basis is one closed-form elementwise map
per (row, knot) pair — computed as a [rows, k] broadcast on device — and the
fit IS the existing distributed IRLS (the basis columns just join the design
matrix), so everything downstream (families, regularization, metrics) is
inherited.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.types import VecType
from h2o3_tpu.frame.vec import Vec
from h2o3_tpu.models.job import Job
from h2o3_tpu.models.model_base import Model, ModelBuilder, make_model_key


def _ncs_basis(x: jax.Array, knots: jax.Array) -> jax.Array:
    """Natural cubic spline basis [rows, k] on ``k`` interior knots
    (truncated-power construction with natural boundary constraints;
    Hastie/Tibshirani ESL eq. 5.4-5.5 — the reference's CR splines span the
    same function space)."""
    k = knots.shape[0]
    last = knots[-1]

    def d(j):
        num = jnp.maximum(x - knots[j], 0.0) ** 3 \
            - jnp.maximum(x - last, 0.0) ** 3
        return num / jnp.maximum(last - knots[j], 1e-12)

    cols = [x, ]
    dlast = d(k - 2)
    for j in range(k - 2):
        cols.append(d(j) - dlast)
    return jnp.stack(cols, axis=1)   # [rows, k-1]: linear + k-2 curvature terms


class GAMModel(Model):
    algo = "gam"

    def _expand(self, frame: Frame):
        o = self.output
        cols, names = [], []
        for c in o["gam_columns"]:
            x = frame.vec(c).as_float()
            x = jnp.where(jnp.isnan(x), jnp.asarray(o["col_means"][c]), x)
            B = _ncs_basis(x, jnp.asarray(o["knots"][c]))
            for i in range(B.shape[1]):
                cols.append(B[:, i])
                names.append(f"{c}_gam_{i}")
        out = Frame(list(frame.names), list(frame.vecs))
        for n, c in zip(names, cols):
            out.add(n, Vec(c.astype(jnp.float32), VecType.NUM, frame.nrows))
        return out, names

    def _score_raw(self, frame: Frame):
        expanded, _ = self._expand(frame)
        return self.output["glm"]._score_raw(expanded)

    def coef(self):
        return self.output["glm"].coef()


class GAM(ModelBuilder):
    """h2o-py surface: ``H2OGeneralizedAdditiveEstimator``."""

    algo = "gam"

    @classmethod
    def defaults(cls) -> dict:
        return dict(
            super().defaults(),
            gam_columns=None,            # required: columns to spline-expand
            num_knots=5,
            family="AUTO",
            lambda_=0.0,
            alpha=0.0,
            scale=1e-4,                  # spline smoothness ridge (reference: scale;
            #                              applied as uniform L2 — see _fit note)
            standardize=True,
            max_iterations=50,
        )

    def _fit(self, job: Job, frame: Frame, x, y, weights) -> GAMModel:
        p = self.params
        gam_cols = p["gam_columns"]
        if not gam_cols:
            raise ValueError("gam_columns is required")
        for c in gam_cols:
            if frame.vec(c).is_categorical:
                raise ValueError(f"gam column {c!r} must be numeric")

        knots, col_means = {}, {}
        k = int(p["num_knots"])
        if k < 3:
            raise ValueError("num_knots must be >= 3")
        for c in gam_cols:
            v = frame.vec(c).as_float()
            qs = jnp.nanquantile(v, jnp.linspace(0.02, 0.98, k))
            kn = np.asarray(jax.device_get(qs), np.float64)
            kn = np.unique(kn)
            if len(kn) < 3:
                raise ValueError(f"gam column {c!r} has too few distinct values")
            knots[c] = kn.astype(np.float32)
            col_means[c] = float(jax.device_get(jnp.nanmean(v)))

        # expanded training frame: linear+spline terms replace the raw column
        model_stub = GAMModel(key="_tmp", params=self.params, data_info=None,
                              response_column=y, response_domain=None,
                              output=dict(gam_columns=gam_cols, knots=knots,
                                          col_means=col_means))
        expanded, gam_names = model_stub._expand(frame)

        from h2o3_tpu.models.glm import GLM
        keep_x = [c for c in x if c not in gam_cols]
        lam = float(p["lambda_"]) + float(p["scale"])   # smoothness as ridge
        glm = GLM(family=p["family"], lambda_=lam, alpha=float(p["alpha"]),
                  standardize=bool(p["standardize"]),
                  max_iterations=int(p["max_iterations"])) \
            .train(x=keep_x + gam_names, y=y, training_frame=expanded,
                   weights=weights)
        job.update(1.0, "glm on spline basis done")

        yvec = frame.vec(y)
        return GAMModel(
            key=make_model_key(self.algo, self.model_id),
            params=self.params, data_info=None, response_column=y,
            response_domain=yvec.domain if yvec.is_categorical else None,
            output=dict(gam_columns=gam_cols, knots=knots, col_means=col_means,
                        glm=glm, gam_names=gam_names),
        )
