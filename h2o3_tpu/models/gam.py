"""GAM — generalized additive models via spline basis expansion + GLM.

Reference: ``hex/gam/`` (4.7 kLoC): selected predictors are expanded into
penalized spline bases on quantile knots (``GamSplines/``), the expanded
frame is handed to GLM with a smoothness penalty, and the model scores by
re-expanding at predict time (``GAMModel.java``). Basis families:

- ``bs=0`` cubic regression splines (``CubicRegressionSplines.java``) —
  natural cubic basis on quantile knots;
- ``bs=1`` thin-plate regression splines (``ThinPlateRegressionUtils.java``)
  — radial basis |r|³ (1-D) / r²·log r (2-D) on knot centers plus the
  polynomial null space; supports MULTI-predictor smooths
  (``gam_columns=[["x1","x2"], ...]``);
- ``bs=2`` monotone I-splines (``NBSplinesTypeII``/ISplines) — integrated
  M-spline basis with non-negative coefficients (enforced through GLM
  ``beta_constraints``), giving monotone-increasing smooths
  (``splines_non_negative``).

TPU-native: every basis is a closed-form elementwise map computed as a
[rows, k] broadcast on device, and the fit IS the existing distributed IRLS
(basis columns join the design matrix), so families, regularization and
metrics are inherited. Knot selection is quantile-based like the reference
(``knot_ids`` may override with user knots).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.types import VecType
from h2o3_tpu.frame.vec import Vec
from h2o3_tpu.models.job import Job
from h2o3_tpu.models.model_base import Model, ModelBuilder, make_model_key


def _ncs_basis(x: jax.Array, knots: jax.Array) -> jax.Array:
    """Natural cubic spline basis [rows, k-1] on ``k`` knots
    (truncated-power construction with natural boundary constraints;
    Hastie/Tibshirani ESL eq. 5.4-5.5 — the reference's CR splines span the
    same function space)."""
    k = knots.shape[0]
    last = knots[-1]

    def d(j):
        num = jnp.maximum(x - knots[j], 0.0) ** 3 \
            - jnp.maximum(x - last, 0.0) ** 3
        return num / jnp.maximum(last - knots[j], 1e-12)

    cols = [x, ]
    dlast = d(k - 2)
    for j in range(k - 2):
        cols.append(d(j) - dlast)
    return jnp.stack(cols, axis=1)   # [rows, k-1]: linear + k-2 curvature terms


def _tp_basis_1d(x: jax.Array, knots: jax.Array) -> jax.Array:
    """1-D thin-plate basis: η(r)=|r|³ radial terms + the linear null space
    (reference ThinPlate* distance measure for d=1, m=2)."""
    r = jnp.abs(x[:, None] - knots[None, :])
    return jnp.concatenate([x[:, None], r ** 3], axis=1)


def _tp_basis_2d(x1: jax.Array, x2: jax.Array, kx: np.ndarray) -> jax.Array:
    """2-D thin-plate basis: η(r)=r²·log r on knot centers + linear null
    space (reference thin-plate for d=2, m=2)."""
    dx = x1[:, None] - kx[None, :, 0]
    dy = x2[:, None] - kx[None, :, 1]
    r2 = dx * dx + dy * dy
    rad = jnp.where(r2 > 1e-24, 0.5 * r2 * jnp.log(jnp.maximum(r2, 1e-24)),
                    0.0)
    return jnp.concatenate([x1[:, None], x2[:, None], rad], axis=1)


def _bspline_basis(x: jax.Array, knots: np.ndarray, degree: int = 3):
    """Cox–de Boor B-spline basis [rows, n_basis] on an open knot vector."""
    t = np.concatenate([[knots[0]] * degree, knots, [knots[-1]] * degree])
    n = len(t) - degree - 1
    # the right-open intervals exclude the last knot; clip to the largest
    # f32 BELOW it (a 1e-9 offset rounds back to the knot in float32)
    hi = np.nextafter(np.float32(knots[-1]), np.float32(knots[0]))
    xs = jnp.clip(x, knots[0], hi)
    B = [jnp.where((xs >= t[i]) & (xs < t[i + 1]), 1.0, 0.0)
         for i in range(len(t) - 1)]
    for d in range(1, degree + 1):
        Bn = []
        for i in range(len(t) - d - 1):
            den1, den2 = t[i + d] - t[i], t[i + d + 1] - t[i + 1]
            a = (xs - t[i]) / den1 * B[i] if den1 > 0 else 0.0
            b = (t[i + d + 1] - xs) / den2 * B[i + 1] if den2 > 0 else 0.0
            Bn.append(a + b)
        B = Bn
    return jnp.stack(B[:n], axis=1)


def _ispline_basis(x: jax.Array, knots: np.ndarray, degree: int = 3):
    """I-spline (monotone) basis: I_i(x) = Σ_{j>=i} B_j(x) of one-degree-
    higher B-splines (Ramsay 1988; reference ISplines). Each I_i rises
    monotonically 0→1, so non-negative coefficients give a monotone smooth."""
    Bhi = _bspline_basis(x, knots, degree)
    # cumulative from the right, dropping the constant first function
    rev = jnp.cumsum(Bhi[:, ::-1], axis=1)[:, ::-1]
    return rev[:, 1:]


def _entry_name(entry) -> str:
    return "_".join(entry) if isinstance(entry, (list, tuple)) else entry


class GAMModel(Model):
    algo = "gam"

    def _expand(self, frame: Frame):
        o = self.output
        cols, names = [], []
        for entry, bs in zip(o["gam_columns"], o["bs"]):
            nm = _entry_name(entry)
            if isinstance(entry, (list, tuple)):     # multi-dim thin plate
                xs = []
                for c in entry:
                    v = frame.vec(c).as_float()
                    xs.append(jnp.where(jnp.isnan(v),
                                        jnp.asarray(o["col_means"][c]), v))
                B = _tp_basis_2d(xs[0], xs[1], np.asarray(o["knots"][nm]))
            else:
                v = frame.vec(entry).as_float()
                x = jnp.where(jnp.isnan(v), jnp.asarray(o["col_means"][entry]), v)
                kn = o["knots"][nm]
                if bs == 1:
                    B = _tp_basis_1d(x, jnp.asarray(kn))
                elif bs == 2:
                    B = _ispline_basis(x, np.asarray(kn))
                else:
                    B = _ncs_basis(x, jnp.asarray(kn))
            for i in range(B.shape[1]):
                cols.append(B[:, i])
                names.append(f"{nm}_gam_{i}")
        out = Frame(list(frame.names), list(frame.vecs))
        for n, c in zip(names, cols):
            out.add(n, Vec(c.astype(jnp.float32), VecType.NUM, frame.nrows))
        return out, names

    def _score_raw(self, frame: Frame):
        expanded, _ = self._expand(frame)
        return self.output["glm"]._score_raw(expanded)

    def coef(self):
        return self.output["glm"].coef()


class GAM(ModelBuilder):
    """h2o-py surface: ``H2OGeneralizedAdditiveEstimator``."""

    algo = "gam"

    @classmethod
    def defaults(cls) -> dict:
        return dict(
            super().defaults(),
            gam_columns=None,            # str entries, or [c1,c2] lists (tp)
            bs=None,                     # per-entry basis: 0=cr, 1=tp, 2=is
            num_knots=5,
            knot_ids=None,               # {col: [user knots]} overrides
            splines_non_negative=True,   # bs=2: monotone INCREASING
            family="AUTO",
            lambda_=0.0,
            alpha=0.0,
            scale=1e-4,                  # spline smoothness ridge (reference: scale;
            #                              applied as uniform L2 — see _fit note)
            standardize=True,
            max_iterations=50,
        )

    def _select_knots(self, frame, entry, k: int, user_knots):
        """Quantile knot selection per the reference's default placement
        (``GamUtils.generateKnotsFromKeys``); user ``knot_ids`` override."""
        nm = _entry_name(entry)
        if user_knots and nm in user_knots:
            kn = np.asarray(user_knots[nm], np.float64)
            if kn.ndim == 1 and isinstance(entry, (list, tuple)):
                raise ValueError(f"thin-plate entry {nm} needs 2-D knots")
            return kn.astype(np.float32)
        if isinstance(entry, (list, tuple)):
            # knots = strided DATA points (reference thin-plate picks knot
            # rows from the frame; a per-axis quantile zip would put every
            # knot on one diagonal). NaN rows are excluded — one NaN knot
            # would poison the whole radial basis.
            cols = [frame.vec(c).to_numpy().astype(np.float64)
                    for c in entry]
            pts = np.stack(cols, axis=1)
            pts = pts[~np.isnan(pts).any(axis=1)]
            if len(pts) < k:
                raise ValueError(f"thin-plate entry {nm}: only {len(pts)} "
                                 f"complete rows for {k} knots")
            idx = np.linspace(0, len(pts) - 1, k).astype(np.int64)
            return pts[idx].astype(np.float32)
        v = frame.vec(entry).as_float()
        qs = jnp.nanquantile(v, jnp.linspace(0.02, 0.98, k))
        kn = np.unique(np.asarray(jax.device_get(qs), np.float64))
        if len(kn) < 3:
            raise ValueError(f"gam column {entry!r} has too few distinct values")
        return kn.astype(np.float32)

    def _fit(self, job: Job, frame: Frame, x, y, weights) -> GAMModel:
        p = self.params
        gam_cols = p["gam_columns"]
        if not gam_cols:
            raise ValueError("gam_columns is required")
        bs = list(p["bs"]) if p.get("bs") else [0] * len(gam_cols)
        if len(bs) != len(gam_cols):
            raise ValueError("bs must have one entry per gam column")
        for entry, b in zip(gam_cols, bs):
            names = entry if isinstance(entry, (list, tuple)) else [entry]
            if isinstance(entry, (list, tuple)):
                if int(b) != 1:
                    raise ValueError("multi-column gam entries require "
                                     "bs=1 (thin plate)")
                if len(entry) != 2:
                    raise ValueError("thin-plate smooths support 1 or 2 "
                                     "predictors here")
            for c in names:
                if frame.vec(c).is_categorical:
                    raise ValueError(f"gam column {c!r} must be numeric")
            if int(b) not in (0, 1, 2):
                raise ValueError(f"bs={b} unknown (0=cr, 1=tp, 2=is)")

        k = int(p["num_knots"])
        if k < 3:
            raise ValueError("num_knots must be >= 3")
        knots, col_means = {}, {}
        flat_cols = []
        for entry in gam_cols:
            names = entry if isinstance(entry, (list, tuple)) else [entry]
            flat_cols.extend(names)
            knots[_entry_name(entry)] = self._select_knots(
                frame, entry, k, p.get("knot_ids"))
            for c in names:
                col_means[c] = float(jax.device_get(
                    jnp.nanmean(frame.vec(c).as_float())))

        model_stub = GAMModel(key="_tmp", params=self.params, data_info=None,
                              response_column=y, response_domain=None,
                              output=dict(gam_columns=gam_cols, bs=bs,
                                          knots=knots, col_means=col_means))
        expanded, gam_names = model_stub._expand(frame)

        # bs=2 monotonicity: non-negative I-spline coefficients via GLM's
        # box constraints (reference: splines_non_negative)
        constraints = None
        if any(int(b) == 2 for b in bs) and bool(p["splines_non_negative"]):
            constraints = {}
            for entry, b in zip(gam_cols, bs):
                if int(b) != 2:
                    continue
                nm = _entry_name(entry)
                for gname in gam_names:
                    if gname.startswith(f"{nm}_gam_"):
                        constraints[gname] = (0.0, None)

        from h2o3_tpu.models.glm import GLM
        keep_x = [c for c in x if c not in flat_cols]
        lam = float(p["lambda_"]) + float(p["scale"])   # smoothness as ridge
        glm = GLM(family=p["family"], lambda_=lam, alpha=float(p["alpha"]),
                  standardize=bool(p["standardize"]),
                  beta_constraints=constraints,
                  max_iterations=int(p["max_iterations"])) \
            .train(x=keep_x + gam_names, y=y, training_frame=expanded,
                   weights=weights)
        job.update(1.0, "glm on spline basis done")

        yvec = frame.vec(y)
        return GAMModel(
            key=make_model_key(self.algo, self.model_id),
            params=self.params, data_info=None, response_column=y,
            response_domain=yvec.domain if yvec.is_categorical else None,
            output=dict(gam_columns=gam_cols, bs=bs, knots=knots,
                        col_means=col_means, glm=glm, gam_names=gam_names),
        )
