"""Shared histogram-tree grower — the engine under GBM/DRF/IsolationForest.

Reference: ``hex/tree/`` — per level, ``ScoreBuildHistogram2``
(``ScoreBuildHistogram2.java:62,119-236``) accumulates per-bin (w, wY, wYY)
into ``DHistogram._vals`` (``DHistogram.java:48-94``) with a two-stage
node-local pass, histograms reduce across the cloud, and
``DTree.findBestSplitPoint`` (``DTree.java:984``) scans bins for the best
split. The XGBoost extension does the same with (grad, hess) stats and
gain = 0.5*(GL²/(HL+λ)+GR²/(HR+λ)−G²/(H+λ))−γ.

TPU-native redesign (the "hard part #1" of SURVEY.md §7): growth is
**level-synchronous with static shapes** — every level is one compiled
program: a feature-scanned ``segment_sum`` builds all node histograms at once
(XLA reduces per-chip partials over ICI), split finding is a vectorized
cumsum+argmax over [F, nodes, bins, dir], and row routing is a gather. No
per-node recursion, no dynamic shapes; leaves freeze rows by setting their
node id to -1 (dropped by the masked segment_sum). Trees are stored as dense
heaps (arrays indexed 2i+1/2i+2), so prediction is D gather steps.

Uses (g, h) gradient-pair stats — the XGBoost formulation — for GBM too;
with h = w this reduces exactly to H2O GBM's (w, wY) mean-leaf semantics.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


@dataclasses.dataclass
class TreeParams:
    max_depth: int = 5
    nbins: int = 64              # regular bins; bin index nbins = missing
    min_rows: float = 10.0       # min sum of instance weights per child
    reg_lambda: float = 1.0      # L2 on leaf values (XGBoost lambda; H2O GBM ~0)
    reg_alpha: float = 0.0       # L1 on leaf values (XGBoost alpha)
    gamma: float = 0.0           # min split gain (XGBoost gamma)
    min_split_improvement: float = 1e-8


@dataclasses.dataclass
class Tree:
    """Dense heap arrays, length 2^(max_depth+1)-1."""
    feat: jax.Array         # int32, split feature (or -1)
    thresh_bin: jax.Array   # int32, go left if bin < thresh_bin
    thresh_val: jax.Array   # f32, go left if x < thresh_val (raw traversal)
    na_left: jax.Array      # bool, direction for missing values
    is_split: jax.Array     # bool
    leaf: jax.Array         # f32 leaf values (valid where !is_split)


@partial(jax.jit, static_argnames=("n_nodes", "n_bins_tot"))
def _level_histograms(binned, node_local, g, h, w, n_nodes: int, n_bins_tot: int):
    """All node histograms for one level: [F, n_nodes*n_bins_tot, 3] of (G,H,W).

    The MRTask analog: per-shard masked segment-sums, psum-reduced by XLA.
    """
    ghw = jnp.stack([g, h, w], axis=1)
    active = node_local >= 0
    base = jnp.where(active, node_local * n_bins_tot, 0)
    vals = jnp.where(active[:, None], ghw, 0.0)

    def per_feature(_, binf):
        ids = base + jnp.minimum(binf, n_bins_tot - 1)
        return None, jax.ops.segment_sum(vals, ids, num_segments=n_nodes * n_bins_tot)

    _, hists = lax.scan(per_feature, None, binned.T)
    return hists


@partial(jax.jit, static_argnames=("n_bins",))
def _find_splits(hists, n_bins: int, min_rows, reg_lambda, reg_alpha, gamma, feat_mask):
    """Vectorized split search (reference: DTree.findBestSplitPoint).

    hists: [F, N*(n_bins+1), 3]. Returns per-node best (gain, feat, t, na_left)
    and node totals (G, H, W). Candidate split t in [1, n_bins-1]: bins < t go
    left; the missing bin (index n_bins) is assigned to the better direction.
    """
    F = hists.shape[0]
    Bt = n_bins + 1
    N = hists.shape[1] // Bt
    hist4 = hists.reshape(F, N, Bt, 3)
    reg = hist4[:, :, :n_bins, :]                 # [F,N,B,3]
    na = hist4[:, :, n_bins, :]                   # [F,N,3]
    cum = jnp.cumsum(reg, axis=2)                 # [F,N,B,3]
    tot = cum[:, :, -1, :] + na                   # [F,N,3] (same for all f)
    G, H, W = tot[0, :, 0], tot[0, :, 1], tot[0, :, 2]

    GL = cum[:, :, : n_bins - 1, :]               # split t=b+1 → left = bins<=b
    # direction choice for missing values: [2, F, N, B-1, 3]
    GLd = jnp.stack([GL + na[:, :, None, :], GL], axis=0)
    gl, hl, wl = GLd[..., 0], GLd[..., 1], GLd[..., 2]
    gr = G[None, None, :, None] - gl
    hr = H[None, None, :, None] - hl
    wr = W[None, None, :, None] - wl

    def half(gs, hs):
        # XGBoost leaf objective with L1: soft-threshold G by alpha
        gt = jnp.sign(gs) * jnp.maximum(jnp.abs(gs) - reg_alpha, 0.0)
        return gt * gt / (hs + reg_lambda)

    parent = half(G, H)[None, None, :, None]
    gain = 0.5 * (half(gl, hl) + half(gr, hr) - parent) - gamma
    ok = (wl >= min_rows) & (wr >= min_rows) & feat_mask[None, :, None, None]
    gain = jnp.where(ok, gain, -jnp.inf)

    flat = gain.transpose(2, 0, 1, 3).reshape(N, -1)   # [N, 2*F*(B-1)]
    best = jnp.argmax(flat, axis=1)
    best_gain = jnp.take_along_axis(flat, best[:, None], axis=1)[:, 0]
    na_left = best < F * (n_bins - 1)
    rem = best % (F * (n_bins - 1))
    best_feat = (rem // (n_bins - 1)).astype(jnp.int32)
    best_t = (rem % (n_bins - 1) + 1).astype(jnp.int32)
    return best_gain, best_feat, best_t, na_left, G, H, W


@partial(jax.jit, static_argnames=("n_bins",))
def _route_rows(binned, node_local, feat, t, na_left, do_split, n_bins: int):
    """Advance rows to next-level node ids; frozen (leaf) rows get -1."""
    active = node_local >= 0
    nl = jnp.where(active, node_local, 0)
    f = feat[nl]
    split = do_split[nl] & active
    b = jnp.take_along_axis(binned, f[:, None], axis=1)[:, 0]
    is_na = b >= n_bins
    left = jnp.where(is_na, na_left[nl], b < t[nl])
    child = nl * 2 + jnp.where(left, 0, 1)
    return jnp.where(split, child, -1)


def predict_binned(binned, trees: list[Tree], n_bins: int) -> jax.Array:
    """Sum of leaf values over stacked trees, traversing binned features."""
    stack = lambda attr: jnp.stack([getattr(t, attr) for t in trees])
    return _predict_binned_impl(binned, stack("feat"), stack("thresh_bin"),
                                stack("na_left"), stack("is_split"), stack("leaf"),
                                n_bins)


@partial(jax.jit, static_argnames=("n_bins",))
def _predict_binned_impl(binned, feat_s, t_s, na_s, sp_s, leaf_s, n_bins: int):
    rows = binned.shape[0]
    depth = int(np.log2(feat_s.shape[1] + 1)) - 1

    def one_tree(acc, tr):
        feat, t, na_l, is_sp, leaf = tr
        idx = jnp.zeros(rows, jnp.int32)
        for _ in range(depth):
            f = jnp.maximum(feat[idx], 0)
            b = jnp.take_along_axis(binned, f[:, None], axis=1)[:, 0]
            left = jnp.where(b >= n_bins, na_l[idx], b < t[idx])
            nxt = idx * 2 + jnp.where(left, 1, 2)
            idx = jnp.where(is_sp[idx], nxt, idx)
        return acc + leaf[idx], None

    acc, _ = lax.scan(one_tree, jnp.zeros(rows, jnp.float32),
                      (feat_s, t_s, na_s, sp_s, leaf_s))
    return acc


@jax.jit
def _predict_raw_impl(X, feat_s, tv_s, na_s, sp_s, leaf_s):
    """Raw-value traversal for scoring new frames (threshold = edge value)."""
    rows = X.shape[0]
    depth = int(np.log2(feat_s.shape[1] + 1)) - 1

    def one_tree(acc, tr):
        feat, tv, na_l, is_sp, leaf = tr
        idx = jnp.zeros(rows, jnp.int32)
        for _ in range(depth):
            f = jnp.maximum(feat[idx], 0)
            x = jnp.take_along_axis(X, f[:, None], axis=1)[:, 0]
            left = jnp.where(jnp.isnan(x), na_l[idx], x < tv[idx])
            nxt = idx * 2 + jnp.where(left, 1, 2)
            idx = jnp.where(is_sp[idx], nxt, idx)
        return acc + leaf[idx], None

    acc, _ = lax.scan(one_tree, jnp.zeros(rows, jnp.float32),
                      (feat_s, tv_s, na_s, sp_s, leaf_s))
    return acc


def predict_raw(X, trees: list[Tree]) -> jax.Array:
    stack = lambda attr: jnp.stack([getattr(t, attr) for t in trees])
    return _predict_raw_impl(X, stack("feat"), stack("thresh_val"),
                             stack("na_left"), stack("is_split"), stack("leaf"))


def grow_tree(binned: jax.Array, edges: jax.Array, g: jax.Array, h: jax.Array,
              w: jax.Array, params: TreeParams, feat_mask: jax.Array,
              col_rate: float = 1.0, key: jax.Array | None = None) -> Tree:
    """Grow one tree level-synchronously. All heavy steps are cached jits;
    only tiny per-level heap slices move to host.

    ``col_rate`` < 1 resamples the feature mask every level — the TPU stand-in
    for the reference's per-split mtries/col_sample_rate (per-node sampling
    would break the single-batched-argmax split search; per-level is the
    standard compromise, cf. LightGBM feature_fraction_bynode granularity)."""
    D = params.max_depth
    B = params.nbins
    Bt = B + 1
    heap = 2 ** (D + 1) - 1
    hf = np.full(heap, -1, np.int32)
    ht = np.zeros(heap, np.int32)
    htv = np.zeros(heap, np.float32)
    hna = np.zeros(heap, bool)
    hsp = np.zeros(heap, bool)
    hlf = np.zeros(heap, np.float32)

    edges_np = np.asarray(jax.device_get(edges))
    node_local = jnp.zeros(binned.shape[0], jnp.int32)

    F = binned.shape[1]
    for d in range(D):
        N = 2 ** d
        off = N - 1
        lmask = feat_mask
        if col_rate < 1.0 and key is not None:
            key, kd, kf = jax.random.split(key, 3)
            sub = jax.random.uniform(kd, (F,)) < col_rate
            sub = sub.at[jax.random.randint(kf, (), 0, F)].set(True)
            lmask = feat_mask & sub
            # the forced index may miss feat_mask; never let the level go empty
            lmask = jnp.where(lmask.any(), lmask, feat_mask)
        hists = _level_histograms(binned, node_local, g, h, w, N, Bt)
        gain, feat, t, na_left, G, H, W = _find_splits(
            hists, B, jnp.float32(params.min_rows), jnp.float32(params.reg_lambda),
            jnp.float32(params.reg_alpha), jnp.float32(params.gamma), lmask)
        gain_h, feat_h, t_h, nal_h, G_h, H_h, W_h = (
            np.asarray(jax.device_get(v)) for v in (gain, feat, t, na_left, G, H, W))
        do = (gain_h > params.min_split_improvement) & np.isfinite(gain_h) & (W_h > 0)
        # record splits and leaves for this level
        idxs = off + np.arange(N)
        hf[idxs] = np.where(do, feat_h, -1)
        ht[idxs] = np.where(do, t_h, 0)
        htv[idxs] = np.where(do, edges_np[feat_h, np.maximum(t_h - 1, 0)], 0.0)
        hna[idxs] = np.where(do, nal_h, False)
        hsp[idxs] = do
        Gt = np.sign(G_h) * np.maximum(np.abs(G_h) - params.reg_alpha, 0.0)
        hlf[idxs] = np.where(do | (W_h <= 0), 0.0,
                             -Gt / np.maximum(H_h + params.reg_lambda, 1e-30))
        if not do.any():
            break
        node_local = _route_rows(binned, node_local, jnp.asarray(feat_h),
                                 jnp.asarray(t_h), jnp.asarray(nal_h),
                                 jnp.asarray(do), B)
    else:
        # final level: all surviving nodes become leaves
        N = 2 ** D
        off = N - 1
        hists = _level_histograms(binned, node_local, g, h, w, N, Bt)
        tot = jnp.asarray(hists)[0].reshape(N, Bt, 3).sum(axis=1)
        tot_h = np.asarray(jax.device_get(tot))
        # NOTE: feature-0 histogram covers all stats; totals are feature-independent
        G_h, H_h, W_h = tot_h[:, 0], tot_h[:, 1], tot_h[:, 2]
        idxs = off + np.arange(N)
        Gt = np.sign(G_h) * np.maximum(np.abs(G_h) - params.reg_alpha, 0.0)
        hlf[idxs] = np.where(W_h > 0, -Gt / np.maximum(H_h + params.reg_lambda, 1e-30), 0.0)

    return Tree(feat=jnp.asarray(hf), thresh_bin=jnp.asarray(ht),
                thresh_val=jnp.asarray(htv), na_left=jnp.asarray(hna),
                is_split=jnp.asarray(hsp), leaf=jnp.asarray(hlf))
