"""Shared histogram-tree grower — the engine under GBM/DRF/IsolationForest.

Reference: ``hex/tree/`` — per level, ``ScoreBuildHistogram2``
(``ScoreBuildHistogram2.java:62,119-236``) accumulates per-bin (w, wY, wYY)
into ``DHistogram._vals`` (``DHistogram.java:48-94``) with a two-stage
node-local pass, histograms reduce across the cloud, and
``DTree.findBestSplitPoint`` (``DTree.java:984``) scans bins for the best
split. The XGBoost extension does the same with (grad, hess) stats and
gain = 0.5*(GL²/(HL+λ)+GR²/(HR+λ)−G²/(H+λ))−γ.

TPU-native redesign (the "hard part #1" of SURVEY.md §7): growth is
**level-synchronous with static shapes**, and — unlike the reference's
per-level driver round-trips — the ENTIRE tree grows inside one compiled XLA
program: the level loop is unrolled at trace time (depth is static), each
level being a feature-scanned ``segment_sum`` histogram build (XLA reduces
per-chip partials over ICI), a vectorized cumsum+argmax split search over
[F, nodes, bins, dir], and a gather re-route of rows. One tree = one device
dispatch; a whole K-class round = one ``vmap``-ed dispatch
(:func:`grow_trees_batched`). This matters doubly on TPU where host↔device
round-trips ride a high-latency link. Trees are stored as dense heaps (arrays
indexed 2i+1/2i+2), so prediction is D gather steps.

Uses (g, h) gradient-pair stats — the XGBoost formulation — for GBM too;
with h = w this reduces exactly to H2O GBM's (w, wY) mean-leaf semantics.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

try:  # jax >= 0.6 exposes shard_map at top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

from h2o3_tpu.utils.costs import accounted_jit


@dataclasses.dataclass
class TreeParams:
    max_depth: int = 5
    nbins: int = 64              # regular bins; bin index nbins = missing
    min_rows: float = 10.0       # min sum of instance weights per child
    reg_lambda: float = 1.0      # L2 on leaf values (XGBoost lambda; H2O GBM ~0)
    reg_alpha: float = 0.0       # L1 on leaf values (XGBoost alpha)
    gamma: float = 0.0           # min split gain (XGBoost gamma)
    min_split_improvement: float = 1e-8


@dataclasses.dataclass
class Tree:
    """Dense heap arrays, length 2^(max_depth+1)-1."""
    feat: jax.Array         # int32, split feature (or -1)
    thresh_bin: jax.Array   # int32, go left if bin < thresh_bin
    thresh_val: jax.Array   # f32, go left if x < thresh_val (raw traversal)
    na_left: jax.Array      # bool, direction for missing values
    is_split: jax.Array     # bool
    leaf: jax.Array         # f32 leaf values (valid where !is_split)
    gain: jax.Array | None = None    # f32 split gain (0 at leaves) — varimp
    cover: jax.Array | None = None   # f32 sum of row weights through the node
    # [heap, B] bool — bins routed LEFT at each node. Present only when the
    # model has categorical features (group splits, reference DHistogram enum
    # subsets); numeric-only trees route by thresh_bin/thresh_val alone.
    left_mask: jax.Array | None = None


def _level_histograms(binned, node_local, g, h, w, n_nodes: int, n_bins_tot: int):
    """All node histograms for one level: [F, n_nodes*n_bins_tot, 3] of (G,H,W).

    The MRTask analog: per-shard masked segment-sums, psum-reduced by XLA.
    """
    active = node_local >= 0
    base = jnp.where(active, node_local * n_bins_tot, 0)
    stats = [jnp.where(active, v, 0.0) for v in (g, h, w)]

    def per_feature(_, binf):
        ids = base + jnp.minimum(binf, n_bins_tot - 1)
        # per 1-D stat (a [rows, 3] stack pads minor dim to 128 lanes in HBM)
        outs = [jax.ops.segment_sum(v, ids, num_segments=n_nodes * n_bins_tot)
                for v in stats]
        return None, jnp.stack(outs, axis=1)

    _, hists = lax.scan(per_feature, None, binned.T)
    return hists


def hist_mesh(arr):
    """The mesh to fuse histogram reductions over, from an input array's
    sharding — or None when fusion buys nothing (single device, no named
    mesh, or rows not divisible by the row axis). Called OUTSIDE jit by the
    dispatch wrappers; the mesh then rides into the compiled program as a
    STATIC argument, so a trace can never reuse a stale mesh after the
    global mesh changes (shard_map bakes its mesh in at trace time)."""
    from h2o3_tpu.parallel.mesh import ROWS
    sharding = getattr(arr, "sharding", None)
    mesh = getattr(sharding, "mesh", None)
    if mesh is None or getattr(mesh, "axis_names", None) is None:
        return None
    if ROWS not in mesh.axis_names or mesh.shape[ROWS] <= 1:
        return None
    if arr.shape[0] % mesh.shape[ROWS] != 0:
        return None
    return mesh


def _level_histograms_fused(binned, node_local, g, h, w, n_nodes: int,
                            n_bins_tot: int, mesh):
    """One-collective level histograms on a multi-device mesh: shard-local
    segment-sums inside ``shard_map``, then ONE ``lax.psum`` of the whole
    stacked ``[F, n_nodes*n_bins_tot, 3]`` payload over the row axis — the
    FireCaffe shape: few, large, tree-reduced collectives. The implicit-SPMD
    path instead lowers one small all-reduce per feature-scan step, which is
    exactly the 4-tiny-collectives-per-level pattern MULTICHIP_r05 flagged."""
    from h2o3_tpu.parallel.mesh import ROWS
    rows = P(ROWS)

    def local(b, nl, gg, hh, ww):
        return lax.psum(
            _level_histograms(b, nl, gg, hh, ww, n_nodes, n_bins_tot), ROWS)

    fused = _shard_map(local, mesh=mesh,
                       in_specs=(P(ROWS, None), rows, rows, rows, rows),
                       out_specs=P())
    return fused(binned, node_local, g, h, w)


def _histograms(binned, binned_T, node_local, g, h, w, n_nodes: int,
                n_bins_tot: int, mesh=None):
    """Dispatch: one fused-collective shard_map reduction on a multi-device
    mesh FIRST — the Pallas kernel is single-device and running it over the
    global array would skip the per-level ``psum`` entirely (each shard's
    partial histogram would be treated as the total) — then the Pallas MXU
    kernel on TPU (≈4× the XLA scatter path inside the fused tree program),
    then segment_sum elsewhere / beyond the kernel's VMEM envelope."""
    from h2o3_tpu.ops.pallas_hist import hist_pallas, pallas_available
    if mesh is not None:
        return _level_histograms_fused(binned, node_local, g, h, w, n_nodes,
                                       n_bins_tot, mesh)
    if pallas_available(n_nodes, binned.shape[1], n_bins_tot):
        return hist_pallas(binned_T, node_local, g, h, w, n_nodes, n_bins_tot)
    return _level_histograms(binned, node_local, g, h, w, n_nodes, n_bins_tot)


def _node_totals(node_local, g, h, w, n_nodes: int):
    """Per-node (G, H, W) sums — the feature-independent stats the final
    level needs (cheaper than a full histogram build). Summed per 1-D stat
    column: a [rows, 3] stack would pad its minor dim to 128 lanes in HBM
    (42x memory at 11M rows)."""
    active = node_local >= 0
    ids = jnp.where(active, node_local, 0)
    outs = [jax.ops.segment_sum(jnp.where(active, v, 0.0), ids,
                                num_segments=n_nodes) for v in (g, h, w)]
    return jnp.stack(outs, axis=1)


def _find_splits(hists, n_bins: int, min_rows, reg_lambda, reg_alpha, gamma,
                 feat_mask, mono=None, allowed=None, cat_feats=None):
    """Vectorized split search (reference: DTree.findBestSplitPoint).

    hists: [F, N*(n_bins+1), 3]. Returns per-node best (gain, feat, t,
    na_left, child values) and node totals (G, H, W). Candidate split t in
    [1, n_bins-1]: bins < t go left; the missing bin (index n_bins) is
    assigned to the better direction.

    ``mono`` [F] in {-1,0,1} rejects splits whose child leaf values violate
    the feature's monotone direction (reference ``hex/tree/Constraints.java``;
    LightGBM "basic" mode — violating candidates get -inf gain; the CALLER
    propagates [lo,hi] bounds down the heap and clamps leaf values).
    ``allowed`` [N,F] masks features an interaction-constrained branch may
    split on (reference ``BranchInteractionConstraints.java``).
    ``cat_feats`` [F] marks categorical features: their candidate splits are
    GROUP splits — bins re-ranked per node by gradient ratio G/H and scanned
    as sorted prefixes (reference ``DHistogram`` enum handling /
    ``DTree.findBestSplitPoint`` Fisher-optimal subset search) — instead of
    ordinal thresholds.
    """
    F = hists.shape[0]
    Bt = n_bins + 1
    N = hists.shape[1] // Bt
    hist4 = hists.reshape(F, N, Bt, 3)
    reg = hist4[:, :, :n_bins, :]                 # [F,N,B,3]
    na = hist4[:, :, n_bins, :]                   # [F,N,3]
    cum = jnp.cumsum(reg, axis=2)                 # [F,N,B,3]
    rank = None
    if cat_feats is not None:
        # rank bins by mean gradient; empty bins sort to the end so prefix
        # candidates enumerate only occupied categories first
        ratio = reg[..., 0] / jnp.maximum(reg[..., 1], 1e-12)
        ratio = jnp.where(reg[..., 2] > 0, ratio, jnp.inf)
        order = jnp.argsort(ratio, axis=2)                      # [F,N,B]
        reg_sorted = jnp.take_along_axis(reg, order[..., None], axis=2)
        cum_sorted = jnp.cumsum(reg_sorted, axis=2)
        rank = jnp.argsort(order, axis=2)                       # bin → rank
        cum = jnp.where(cat_feats[:, None, None, None], cum_sorted, cum)
    tot = cum[:, :, -1, :] + na                   # [F,N,3] (same for all f)
    G, H, W = tot[0, :, 0], tot[0, :, 1], tot[0, :, 2]

    GL = cum[:, :, : n_bins - 1, :]               # split t=b+1 → left = bins<=b
    # direction choice for missing values: [2, F, N, B-1, 3]
    GLd = jnp.stack([GL + na[:, :, None, :], GL], axis=0)
    gl, hl, wl = GLd[..., 0], GLd[..., 1], GLd[..., 2]
    gr = G[None, None, :, None] - gl
    hr = H[None, None, :, None] - hl
    wr = W[None, None, :, None] - wl

    def half(gs, hs):
        # XGBoost leaf objective with L1: soft-threshold G by alpha
        gt = jnp.sign(gs) * jnp.maximum(jnp.abs(gs) - reg_alpha, 0.0)
        return gt * gt / (hs + reg_lambda)

    parent = half(G, H)[None, None, :, None]
    gain = 0.5 * (half(gl, hl) + half(gr, hr) - parent) - gamma
    ok = (wl >= min_rows) & (wr >= min_rows) & feat_mask[None, :, None, None]
    if allowed is not None:
        ok = ok & allowed.T[None, :, :, None]
    vl = _leaf_value(gl, hl, wl, reg_lambda, reg_alpha)
    vr = _leaf_value(gr, hr, wr, reg_lambda, reg_alpha)
    if mono is not None:
        m = mono[None, :, None, None]
        viol = ((m > 0) & (vl > vr)) | ((m < 0) & (vl < vr))
        ok = ok & ~viol
    gain = jnp.where(ok, gain, -jnp.inf)

    flat = gain.transpose(2, 0, 1, 3).reshape(N, -1)   # [N, 2*F*(B-1)]
    best = jnp.argmax(flat, axis=1)
    best_gain = jnp.take_along_axis(flat, best[:, None], axis=1)[:, 0]
    na_left = best < F * (n_bins - 1)
    rem = best % (F * (n_bins - 1))
    best_feat = (rem // (n_bins - 1)).astype(jnp.int32)
    best_t = (rem % (n_bins - 1) + 1).astype(jnp.int32)
    nn = jnp.arange(N)
    dirs = jnp.where(na_left, 0, 1)
    vl_b = vl[dirs, best_feat, nn, best_t - 1]
    vr_b = vr[dirs, best_feat, nn, best_t - 1]
    wl_b = wl[dirs, best_feat, nn, best_t - 1]
    wr_b = wr[dirs, best_feat, nn, best_t - 1]
    # left-membership mask over bins for the chosen split: numeric = bins
    # below the threshold; categorical = bins whose per-node rank is in the
    # sorted prefix (the group going left)
    member = jnp.arange(n_bins)[None, :] < best_t[:, None]       # [N,B]
    if cat_feats is not None:
        rank_best = rank[best_feat, nn, :]                       # [N,B]
        member = jnp.where(cat_feats[best_feat][:, None],
                           rank_best < best_t[:, None], member)
    return (best_gain, best_feat, best_t, na_left, G, H, W, vl_b, vr_b,
            wl_b, wr_b, member)


def _route_rows(binned, node_local, feat, member, na_left, do_split,
                n_bins: int):
    """Advance rows to next-level node ids; frozen (leaf) rows get -1.

    ``member`` [N, B]: left-membership of each bin at each node (covers both
    ordinal thresholds and categorical group splits)."""
    active = node_local >= 0
    nl = jnp.where(active, node_local, 0)
    f = feat[nl]
    split = do_split[nl] & active
    b = jnp.take_along_axis(binned, f[:, None], axis=1)[:, 0]
    is_na = b >= n_bins
    left = jnp.where(is_na, na_left[nl],
                     member[nl, jnp.minimum(b, n_bins - 1)])
    child = nl * 2 + jnp.where(left, 0, 1)
    return jnp.where(split, child, -1)


def _leaf_value(G, H, W, reg_lambda, reg_alpha):
    Gt = jnp.sign(G) * jnp.maximum(jnp.abs(G) - reg_alpha, 0.0)
    return jnp.where(W > 0, -Gt / jnp.maximum(H + reg_lambda, 1e-30), 0.0)


def _grow_tree_device(binned, binned_T, edges, g, h, w, feat_mask, key,
                      depth: int, n_bins: int, min_rows, reg_lambda, reg_alpha,
                      gamma, min_split_improvement, col_rate: float,
                      do_col_sample: bool | None = None,
                      mono=None, reach=None, cat_feats=None, mesh=None):
    """Grow one whole tree on device; the level loop unrolls at trace time.

    Returns heap arrays + per-row training predictions (leaf of each row).

    ``mono`` [F]: monotone directions per feature; child leaf bounds
    propagate down the heap and leaves clamp into them.
    ``reach`` [F, F]: interaction reachability — ``reach[f]`` is the set of
    features allowed below a split on ``f`` (union of the constraint sets
    containing ``f``; unlisted features are singletons, XGBoost semantics).
    """
    B = n_bins
    Bt = B + 1
    F = binned.shape[1]
    node_local = jnp.zeros(binned.shape[0], jnp.int32)

    lv_feat, lv_t, lv_tv, lv_na, lv_sp, lv_leaf = [], [], [], [], [], []
    lv_gain, lv_cover, lv_mask = [], [], []
    row_leaf = jnp.zeros(binned.shape[0], jnp.float32)
    bounds = jnp.array([[-jnp.inf, jnp.inf]], jnp.float32) if mono is not None else None
    allowed = jnp.ones((1, F), bool) if reach is not None else None

    def clamp(v, bnd):
        return jnp.clip(v, bnd[:, 0], bnd[:, 1]) if bnd is not None else v

    if do_col_sample is None:     # static callers pass a concrete col_rate
        do_col_sample = col_rate < 1.0
    # sibling-subtraction state (reference ScoreBuildHistogram2 /
    # gpu_hist "hist subtraction trick"): at level d >= 1 only the SMALLER
    # child of each split parent is histogrammed — the sibling is the
    # parent's histogram minus the computed child's — halving the one-hot
    # contraction's node dimension (its FLOPs are ∝ N) at every level
    prev_hists = prev_do = chosen_left = None
    for d in range(depth):
        N = 2 ** d
        lmask = feat_mask
        if do_col_sample:
            key, kd, kf = jax.random.split(key, 3)
            sub = jax.random.uniform(kd, (F,)) < col_rate
            sub = sub.at[jax.random.randint(kf, (), 0, F)].set(True)
            lmask = feat_mask & sub
            # the forced index may miss feat_mask; never let the level go empty
            lmask = jnp.where(lmask.any(), lmask, feat_mask)
        if d == 0:
            hists = _histograms(binned, binned_T, node_local, g, h, w, N, Bt,
                                mesh=mesh)
        else:
            P = N // 2
            # chosen child id per parent; rows elsewhere mask to -1
            chosen = (jnp.arange(P) * 2
                      + jnp.where(chosen_left, 0, 1).astype(jnp.int32))
            act = node_local >= 0
            par = jnp.where(act, node_local // 2, 0)
            at_chosen = act & (node_local == chosen[par])
            node_slot = jnp.where(at_chosen, par, -1)
            part = _histograms(binned, binned_T, node_slot, g, h, w, P, Bt,
                               mesh=mesh)
            part4 = part.reshape(F, P, Bt, 3)
            prev4 = prev_hists.reshape(F, P, Bt, 3)
            # sibling by subtraction — only where the parent really split
            # (a frozen parent's children hold no rows; its stale parent
            # histogram must not leak into phantom nodes)
            other4 = jnp.where(prev_do[None, :, None, None],
                               prev4 - part4, 0.0)
            cl = chosen_left[None, :, None, None]
            left4 = jnp.where(cl, part4, other4)
            right4 = jnp.where(cl, other4, part4)
            hists = jnp.stack([left4, right4], axis=2).reshape(F, N * Bt, 3)
        (gain, feat, t, na_left, G, H, W, vl_b, vr_b, wl_b, wr_b,
         member) = _find_splits(
            hists, B, min_rows, reg_lambda, reg_alpha, gamma, lmask,
            mono=mono, allowed=allowed, cat_feats=cat_feats)
        prev_hists = hists
        chosen_left = wl_b <= wr_b
        do = (gain > min_split_improvement) & jnp.isfinite(gain) & (W > 0)
        prev_do = do
        leaf = jnp.where(do, 0.0,
                         clamp(_leaf_value(G, H, W, reg_lambda, reg_alpha),
                               bounds))
        lv_feat.append(jnp.where(do, feat, -1))
        lv_t.append(jnp.where(do, t, 0))
        lv_tv.append(jnp.where(do, edges[feat, jnp.maximum(t - 1, 0)], 0.0))
        lv_na.append(do & na_left)
        lv_sp.append(do)
        lv_leaf.append(leaf)
        lv_gain.append(jnp.where(do, gain, 0.0))
        lv_cover.append(W)
        if cat_feats is not None:
            lv_mask.append(member & do[:, None])
        # rows whose node froze at this level take its leaf value
        active = node_local >= 0
        nl = jnp.where(active, node_local, 0)
        row_leaf = jnp.where(active & ~do[nl], leaf[nl], row_leaf)
        node_local = _route_rows(binned, node_local, lv_feat[-1], member,
                                 na_left, do, B)
        if bounds is not None:
            # monotone bound propagation: split midpoint bounds the children
            lo, hi = bounds[:, 0], bounds[:, 1]
            mid = jnp.clip(0.5 * (vl_b + vr_b), lo, hi)
            c = mono[feat] * do          # 0 where unconstrained or no split
            l_lo = jnp.where(c < 0, mid, lo)
            l_hi = jnp.where(c > 0, mid, hi)
            r_lo = jnp.where(c > 0, mid, lo)
            r_hi = jnp.where(c < 0, mid, hi)
            bounds = jnp.stack(
                [jnp.stack([l_lo, l_hi], 1), jnp.stack([r_lo, r_hi], 1)],
                axis=1).reshape(2 * N, 2)
        if allowed is not None:
            child_allowed = jnp.where(do[:, None],
                                      allowed & reach[feat], allowed)
            allowed = jnp.repeat(child_allowed, 2, axis=0)

    # final level: all surviving nodes become leaves; only per-node totals
    # are needed (no split search), so skip the full histogram build
    N = 2 ** depth
    tot = _node_totals(node_local, g, h, w, N)
    leaf = clamp(_leaf_value(tot[:, 0], tot[:, 1], tot[:, 2], reg_lambda,
                             reg_alpha), bounds)
    lv_feat.append(jnp.full(N, -1, jnp.int32))
    lv_t.append(jnp.zeros(N, jnp.int32))
    lv_tv.append(jnp.zeros(N, jnp.float32))
    lv_na.append(jnp.zeros(N, bool))
    lv_sp.append(jnp.zeros(N, bool))
    lv_leaf.append(leaf)
    lv_gain.append(jnp.zeros(N, jnp.float32))
    lv_cover.append(tot[:, 2])
    if cat_feats is not None:
        lv_mask.append(jnp.zeros((N, B), bool))
    active = node_local >= 0
    nl = jnp.where(active, node_local, 0)
    row_leaf = jnp.where(active, leaf[nl], row_leaf)

    out = (jnp.concatenate(lv_feat), jnp.concatenate(lv_t),
           jnp.concatenate(lv_tv), jnp.concatenate(lv_na),
           jnp.concatenate(lv_sp), jnp.concatenate(lv_leaf),
           jnp.concatenate(lv_gain), jnp.concatenate(lv_cover))
    if cat_feats is not None:
        out = out + (jnp.concatenate(lv_mask, axis=0),)
    return out + (row_leaf,)


# the boosting round's host-dispatched program — registered with the
# compute observatory (utils/costs.py) so each (shape, K, depth, mesh)
# signature's compile time and cost_analysis FLOPs are attributable
@accounted_jit("gbm:grow_batched", loop="gbm_chunk",
               static_argnames=("depth", "n_bins", "col_rate", "min_rows",
                                "reg_lambda", "reg_alpha", "gamma",
                                "min_split_improvement", "mesh"))
def _grow_batched(binned, edges, g, h, w, feat_mask, keys,
                  depth: int, n_bins: int, min_rows, reg_lambda, reg_alpha,
                  gamma, min_split_improvement, col_rate: float,
                  mono=None, reach=None, cat_feats=None, mesh=None):
    """K trees in ONE dispatch: vmap over the stats axis (class trees of a
    multinomial round, or K=1). binned/edges are shared (in_axes=None)."""
    binned_T = binned.T   # once per round; the Pallas kernel wants [F, rows]
    fn = lambda gk, hk, wk, mk, kk: _grow_tree_device(
        binned, binned_T, edges, gk, hk, wk, mk, kk, depth, n_bins, min_rows,
        reg_lambda, reg_alpha, gamma, min_split_improvement, col_rate,
        mono=mono, reach=reach, cat_feats=cat_feats, mesh=mesh)
    return jax.vmap(fn)(g, h, w, feat_mask, keys)


def grow_trees_batched(binned, edges, g, h, w, params: TreeParams, feat_mask,
                       col_rate: float = 1.0, key: jax.Array | None = None,
                       mono=None, reach=None, cat_feats=None
                       ) -> tuple[list[Tree], jax.Array]:
    """Grow K trees (leading axis of g/h/w) in one compiled program.

    Returns (trees, preds[K, rows]) where preds are each tree's training-row
    leaf values (what the boosting driver adds to F).

    ``col_rate`` < 1 resamples the feature mask every level — the TPU stand-in
    for the reference's per-split mtries/col_sample_rate (per-node sampling
    would break the single-batched-argmax split search; per-level is the
    standard compromise, cf. LightGBM feature_fraction granularity)."""
    K = g.shape[0]
    if key is None:
        key = jax.random.PRNGKey(0)
    keys = jax.random.split(key, K)
    if feat_mask.ndim == 1:
        feat_mask = jnp.broadcast_to(feat_mask[None, :], (K, feat_mask.shape[0]))
    # hyperparams are STATIC (compiled constants): a traced jnp scalar would
    # cost a host→device upload per call — ~43ms each over a tunneled TPU,
    # dwarfing the 200ms tree-growth compute itself
    out = _grow_batched(
        binned, edges, g, h, w, feat_mask, keys,
        params.max_depth, params.nbins, float(params.min_rows),
        float(params.reg_lambda), float(params.reg_alpha),
        float(params.gamma), float(params.min_split_improvement),
        float(col_rate), mono=mono, reach=reach, cat_feats=cat_feats,
        mesh=hist_mesh(binned))
    hf, ht, htv, hna, hsp, hlf, hg, hc = out[:8]
    hm = out[8] if cat_feats is not None else None
    preds = out[-1]
    trees = [Tree(feat=hf[k], thresh_bin=ht[k], thresh_val=htv[k],
                  na_left=hna[k], is_split=hsp[k], leaf=hlf[k],
                  gain=hg[k], cover=hc[k],
                  left_mask=None if hm is None else hm[k])
             for k in range(K)]
    return trees, preds


def grow_tree(binned: jax.Array, edges: jax.Array, g: jax.Array, h: jax.Array,
              w: jax.Array, params: TreeParams, feat_mask: jax.Array,
              col_rate: float = 1.0, key: jax.Array | None = None) -> Tree:
    """Grow one tree (K=1 batched growth); see :func:`grow_trees_batched`."""
    trees, _ = grow_trees_batched(binned, edges, g[None], h[None], w[None],
                                  params, feat_mask, col_rate, key)
    return trees[0]


def predict_binned(binned, trees: list[Tree], n_bins: int) -> jax.Array:
    """Sum of leaf values over stacked trees, traversing binned features."""
    stack = lambda attr: jnp.stack([getattr(t, attr) for t in trees])
    if trees[0].left_mask is not None:
        return _predict_binned_masked(binned, stack("feat"),
                                      stack("left_mask"), stack("na_left"),
                                      stack("is_split"), stack("leaf"), n_bins)
    return _predict_binned_impl(binned, stack("feat"), stack("thresh_bin"),
                                stack("na_left"), stack("is_split"), stack("leaf"),
                                n_bins)


@partial(jax.jit, static_argnames=("n_bins",))
def _predict_binned_impl(binned, feat_s, t_s, na_s, sp_s, leaf_s, n_bins: int):
    rows = binned.shape[0]
    depth = int(np.log2(feat_s.shape[1] + 1)) - 1

    def one_tree(acc, tr):
        feat, t, na_l, is_sp, leaf = tr
        idx = jnp.zeros(rows, jnp.int32)
        for _ in range(depth):
            f = jnp.maximum(feat[idx], 0)
            b = jnp.take_along_axis(binned, f[:, None], axis=1)[:, 0]
            left = jnp.where(b >= n_bins, na_l[idx], b < t[idx])
            nxt = idx * 2 + jnp.where(left, 1, 2)
            idx = jnp.where(is_sp[idx], nxt, idx)
        return acc + leaf[idx], None

    acc, _ = lax.scan(one_tree, jnp.zeros(rows, jnp.float32),
                      (feat_s, t_s, na_s, sp_s, leaf_s))
    return acc


def fold_binned(binned, trees: "list[Tree]", n_bins: int, lr, F0) -> jax.Array:
    """Margins folded tree-by-tree: ``F = (((F0 + lr*l1) + lr*l2) + ...)``.

    The boosting scan accumulates margins in exactly this float-addition
    order, so a checkpoint resume seeding from here reproduces the
    uninterrupted run's margins — and therefore its remaining trees —
    BIT-IDENTICALLY. ``predict_binned`` (sum-then-scale) differs by ulps,
    which is fine for scoring but breaks exact-resume guarantees
    (docs/RELIABILITY.md)."""
    if not trees:
        # a zero-tree checkpoint (deadline tripped before the first chunk)
        # legally resumes from the bare f0 margins
        return jnp.asarray(F0, jnp.float32)
    stack = lambda attr: jnp.stack([getattr(t, attr) for t in trees])
    lr = jnp.float32(lr)
    if trees[0].left_mask is not None:
        return _fold_binned_masked(binned, stack("feat"), stack("left_mask"),
                                   stack("na_left"), stack("is_split"),
                                   stack("leaf"), lr, F0, n_bins)
    return _fold_binned_impl(binned, stack("feat"), stack("thresh_bin"),
                             stack("na_left"), stack("is_split"),
                             stack("leaf"), lr, F0, n_bins)


@partial(jax.jit, static_argnames=("n_bins",))
def _fold_binned_impl(binned, feat_s, t_s, na_s, sp_s, leaf_s, lr, F0,
                      n_bins: int):
    rows = binned.shape[0]
    depth = int(np.log2(feat_s.shape[1] + 1)) - 1

    def one_tree(acc, tr):
        feat, t, na_l, is_sp, leaf = tr
        idx = jnp.zeros(rows, jnp.int32)
        for _ in range(depth):
            f = jnp.maximum(feat[idx], 0)
            b = jnp.take_along_axis(binned, f[:, None], axis=1)[:, 0]
            left = jnp.where(b >= n_bins, na_l[idx], b < t[idx])
            nxt = idx * 2 + jnp.where(left, 1, 2)
            idx = jnp.where(is_sp[idx], nxt, idx)
        return acc + lr * leaf[idx], None

    acc, _ = lax.scan(one_tree, F0.astype(jnp.float32),
                      (feat_s, t_s, na_s, sp_s, leaf_s))
    return acc


@partial(jax.jit, static_argnames=("n_bins",))
def _fold_binned_masked(binned, feat_s, mask_s, na_s, sp_s, leaf_s, lr, F0,
                        n_bins: int):
    rows = binned.shape[0]
    depth = int(np.log2(feat_s.shape[1] + 1)) - 1

    def one_tree(acc, tr):
        feat, mask, na_l, is_sp, leaf = tr
        idx = jnp.zeros(rows, jnp.int32)
        for _ in range(depth):
            f = jnp.maximum(feat[idx], 0)
            b = jnp.take_along_axis(binned, f[:, None], axis=1)[:, 0]
            left = jnp.where(b >= n_bins, na_l[idx],
                             mask[idx, jnp.minimum(b, n_bins - 1)])
            nxt = idx * 2 + jnp.where(left, 1, 2)
            idx = jnp.where(is_sp[idx], nxt, idx)
        return acc + lr * leaf[idx], None

    acc, _ = lax.scan(one_tree, F0.astype(jnp.float32),
                      (feat_s, mask_s, na_s, sp_s, leaf_s))
    return acc


@partial(jax.jit, static_argnames=("n_bins",))
def _predict_binned_masked(binned, feat_s, mask_s, na_s, sp_s, leaf_s,
                           n_bins: int):
    """Traversal by left-membership masks (group splits)."""
    rows = binned.shape[0]
    depth = int(np.log2(feat_s.shape[1] + 1)) - 1

    def one_tree(acc, tr):
        feat, mask, na_l, is_sp, leaf = tr
        idx = jnp.zeros(rows, jnp.int32)
        for _ in range(depth):
            f = jnp.maximum(feat[idx], 0)
            b = jnp.take_along_axis(binned, f[:, None], axis=1)[:, 0]
            left = jnp.where(b >= n_bins, na_l[idx],
                             mask[idx, jnp.minimum(b, n_bins - 1)])
            nxt = idx * 2 + jnp.where(left, 1, 2)
            idx = jnp.where(is_sp[idx], nxt, idx)
        return acc + leaf[idx], None

    acc, _ = lax.scan(one_tree, jnp.zeros(rows, jnp.float32),
                      (feat_s, mask_s, na_s, sp_s, leaf_s))
    return acc


@jax.jit
def _predict_raw_impl(X, feat_s, tv_s, na_s, sp_s, leaf_s):
    """Raw-value traversal for scoring new frames (threshold = edge value)."""
    rows = X.shape[0]
    depth = int(np.log2(feat_s.shape[1] + 1)) - 1

    def one_tree(acc, tr):
        feat, tv, na_l, is_sp, leaf = tr
        idx = jnp.zeros(rows, jnp.int32)
        for _ in range(depth):
            f = jnp.maximum(feat[idx], 0)
            x = jnp.take_along_axis(X, f[:, None], axis=1)[:, 0]
            left = jnp.where(jnp.isnan(x), na_l[idx], x < tv[idx])
            nxt = idx * 2 + jnp.where(left, 1, 2)
            idx = jnp.where(is_sp[idx], nxt, idx)
        return acc + leaf[idx], None

    acc, _ = lax.scan(one_tree, jnp.zeros(rows, jnp.float32),
                      (feat_s, tv_s, na_s, sp_s, leaf_s))
    return acc


@partial(jax.jit, static_argnames=("n_bins",))
def _predict_raw_masked(X, cat_card, feat_s, tv_s, mask_s, na_s, sp_s, leaf_s,
                        n_bins: int):
    """Raw traversal with group splits: categorical features map raw codes
    to their histogram bin (range-grouped when cardinality > bins) and test
    membership; numeric features compare against the edge threshold."""
    rows = X.shape[0]
    depth = int(np.log2(feat_s.shape[1] + 1)) - 1
    cat_bin = cat_bins_for_codes(X, cat_card, n_bins)   # [rows, F] int32

    def one_tree(acc, tr):
        feat, tv, mask, na_l, is_sp, leaf = tr
        idx = jnp.zeros(rows, jnp.int32)
        for _ in range(depth):
            f = jnp.maximum(feat[idx], 0)
            x = jnp.take_along_axis(X, f[:, None], axis=1)[:, 0]
            is_cat = cat_card[f] > 0
            b = jnp.take_along_axis(cat_bin, f[:, None], axis=1)[:, 0]
            left_cat = mask[idx, jnp.clip(b, 0, n_bins - 1)]
            left = jnp.where(jnp.isnan(x), na_l[idx],
                             jnp.where(is_cat, left_cat, x < tv[idx]))
            nxt = idx * 2 + jnp.where(left, 1, 2)
            idx = jnp.where(is_sp[idx], nxt, idx)
        return acc + leaf[idx], None

    acc, _ = lax.scan(one_tree, jnp.zeros(rows, jnp.float32),
                      (feat_s, tv_s, mask_s, na_s, sp_s, leaf_s))
    return acc


def cat_bins_for_codes(X, cat_card, n_bins: int) -> jax.Array:
    """Map raw categorical codes to histogram bins: identity when the
    cardinality fits, contiguous range-grouping otherwise (reference
    DHistogram nbins_cats grouping)."""
    code = jnp.nan_to_num(X, nan=0.0).astype(jnp.int32)
    card = jnp.maximum(cat_card, 1)[None, :]
    grouped = (code * n_bins) // card
    return jnp.where(cat_card[None, :] > n_bins,
                     jnp.clip(grouped, 0, n_bins - 1),
                     jnp.clip(code, 0, n_bins - 1)).astype(jnp.int32)


def predict_raw(X, trees: list[Tree], cat_card=None, n_bins: int = 0) -> jax.Array:
    stack = lambda attr: jnp.stack([getattr(t, attr) for t in trees])
    if trees[0].left_mask is not None:
        return _predict_raw_masked(X, cat_card, stack("feat"),
                                   stack("thresh_val"), stack("left_mask"),
                                   stack("na_left"), stack("is_split"),
                                   stack("leaf"), n_bins)
    return _predict_raw_impl(X, stack("feat"), stack("thresh_val"),
                             stack("na_left"), stack("is_split"), stack("leaf"))
