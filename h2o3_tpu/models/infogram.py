"""Infogram — admissible-ML feature selection (core + fair variants).

Reference: ``h2o-admissibleml/src/main/java/hex/Infogram/`` —
``Infogram.java`` (driver: builds one surrogate model per predictor,
``buildTrainingFrames`` ``:545-570``), ``EstimateCMI.java`` (conditional
mutual information proxy: mean log2-probability of the actual class over
scored rows), ``InfogramUtils.calculateFinalCMI`` (core:
``cmi_i = max(0, cmi_full - cmi_without_i)``; fair:
``cmi_i = max(0, cmi_protected+i - cmi_protected_only)``; normalize by max).

Semantics:

- **Core infogram** (no ``protected_columns``): relevance = variable
  importance of the full model (scaled to max 1); net information (CMI) of
  ``x_i`` = drop in conditional log-likelihood when ``x_i`` is removed —
  I(y; x_i | x_{-i}) up to estimation. Admissible features clear both
  ``net_information_threshold`` and ``total_information_threshold`` (0.1).
- **Fair infogram** (``protected_columns`` given): relevance from a model on
  all predictors minus protected; safety index of ``x_i`` = information
  about y in ``x_i`` beyond the protected set = cmi(protected ∪ {x_i}) −
  cmi(protected). Admissible = safe AND relevant.

TPU-native: every surrogate is this framework's GBM — each a fully compiled
XLA tree-growth program; the N+1 surrogates share one device-resident frame
and differ only in the feature list (no frame carving as in the reference).
"""

from __future__ import annotations

import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.job import Job
from h2o3_tpu.models.model_base import Model, ModelBuilder, make_model_key


def _mean_cmi(model: Model, frame: Frame, y: str) -> float:
    """EstimateCMI.java: mean log2 p(actual class) over scorable rows."""
    import jax
    import jax.numpy as jnp
    from h2o3_tpu.models.data_info import response_as_float

    raw = model._score_raw(frame)           # [plen, nclass] probabilities
    yy, valid = response_as_float(frame.vec(y))
    mask = frame.row_mask() & valid
    yi = jnp.clip(yy.astype(jnp.int32), 0, raw.shape[1] - 1)
    p = jnp.take_along_axis(raw, yi[:, None], axis=1)[:, 0]
    ok = mask & (p > 0)
    tot = jnp.sum(jnp.where(ok, jnp.log(jnp.maximum(p, 1e-30)), 0.0))
    cnt = jnp.maximum(jnp.sum(ok), 1)
    return float(jax.device_get(tot / cnt)) / float(np.log(2.0))


class InfogramModel(Model):
    algo = "infogram"

    def _score_raw(self, frame: Frame):
        # scoring delegates to the relevance (full) surrogate model
        return self.output["relevance_model"]._score_raw(frame)

    def get_admissible_features(self) -> list[str]:
        return list(self.output["admissible_features"])

    def get_admissible_cmi(self) -> list[float]:
        a = set(self.output["admissible_features"])
        return [c for f, c in zip(self.output["all_predictor_names"],
                                  self.output["cmi"]) if f in a]

    def infogram_data(self):
        """Rows of (column, admissible, relevance, cmi, cmi_raw) — the plot
        data behind h2o-py's ``model.plot()`` infogram."""
        o = self.output
        adm = set(o["admissible_features"])
        return [dict(column=f, admissible=f in adm,
                     relevance=float(r), cmi=float(c), cmi_raw=float(cr))
                for f, r, c, cr in zip(o["all_predictor_names"], o["relevance"],
                                       o["cmi"], o["cmi_raw"])]


class Infogram(ModelBuilder):
    algo = "infogram"
    supports_regression = False   # CMI needs class probabilities (reference ditto)

    @classmethod
    def defaults(cls) -> dict:
        return dict(
            ModelBuilder.defaults(),
            protected_columns=None,
            net_information_threshold=0.1,     # cmi threshold (core)
            total_information_threshold=0.1,   # relevance threshold (core)
            safety_index_threshold=0.1,        # cmi threshold (fair)
            relevance_index_threshold=0.1,     # relevance threshold (fair)
            top_n_features=50,
            algorithm="gbm",
            algorithm_params=None,
        )

    def _surrogate(self, x, y, frame, weights):
        from h2o3_tpu.models.gbm import DRF, GBM
        from h2o3_tpu.models.glm import GLM
        # surrogates must expose varimp() for the relevance axis — restrict
        # to tree models + GLM (reference defaults to GBM too)
        algos = {"gbm": GBM, "glm": GLM, "drf": DRF}
        cls = algos.get(str(self.params.get("algorithm", "gbm")).lower())
        if cls is None:
            raise ValueError(f"unsupported infogram algorithm "
                             f"{self.params['algorithm']!r}; one of {sorted(algos)}")
        extra = dict(self.params.get("algorithm_params") or {})
        if cls in (GBM, DRF):
            extra.setdefault("ntrees", 20)
            extra.setdefault("max_depth", 5)
        seed = int(self.params.get("seed") or -1)
        if seed >= 0:
            extra.setdefault("seed", seed)
        builder = cls(**extra)
        return builder._fit(Job(f"infogram surrogate on {len(x)} cols"),
                            frame, list(x), y, weights)

    def _fit(self, job: Job, frame: Frame, x, y, weights) -> InfogramModel:
        p = self.params
        protected = list(p.get("protected_columns") or [])
        build_core = not protected
        preds = [c for c in x if c not in protected]
        if not preds:
            raise ValueError("no predictors left after removing protected columns")
        top_n = int(p.get("top_n_features") or 50)

        # relevance model: full predictors (core) / all minus protected (fair)
        rel_model = self._surrogate(preds, y, frame, weights)
        vi = {name: rel for name, rel, _, _ in rel_model.varimp()}
        vmax = max(vi.values()) if vi and max(vi.values()) > 0 else 1.0
        relevance = {c: vi.get(c, 0.0) / vmax for c in preds}

        # keep top-K by relevance (reference: extractTopKPredictors)
        preds = sorted(preds, key=lambda c: -relevance[c])[:top_n]

        cmi_raw = {}
        if build_core:
            full_cmi = _mean_cmi(rel_model, frame, y)
            for i, c in enumerate(preds):
                rest = [q for q in preds if q != c]
                if not rest:
                    cmi_raw[c] = max(0.0, full_cmi)
                    continue
                m = self._surrogate(rest, y, frame, weights)
                cmi_raw[c] = max(0.0, full_cmi - _mean_cmi(m, frame, y))
                job.update((i + 1) / (len(preds) + 1), f"CMI {c}")
        else:
            base_model = self._surrogate(protected, y, frame, weights)
            base_cmi = _mean_cmi(base_model, frame, y)
            for i, c in enumerate(preds):
                m = self._surrogate(protected + [c], y, frame, weights)
                cmi_raw[c] = max(0.0, _mean_cmi(m, frame, y) - base_cmi)
                job.update((i + 1) / (len(preds) + 1), f"CMI {c}")

        cmax = max(cmi_raw.values()) if cmi_raw and max(cmi_raw.values()) > 0 else 1.0
        cmi = {c: v / cmax for c, v in cmi_raw.items()}

        cmi_thr = float(p["net_information_threshold"] if build_core
                        else p["safety_index_threshold"])
        rel_thr = float(p["total_information_threshold"] if build_core
                        else p["relevance_index_threshold"])
        admissible = [c for c in preds
                      if cmi[c] >= cmi_thr and relevance[c] >= rel_thr]

        yvec = frame.vec(y)
        return InfogramModel(
            make_model_key(self.algo, self.model_id), self.params,
            rel_model.data_info, y, yvec.domain,
            output=dict(
                all_predictor_names=preds,
                relevance=[relevance[c] for c in preds],
                cmi=[cmi[c] for c in preds],
                cmi_raw=[cmi_raw[c] for c in preds],
                admissible_features=admissible,
                protected_columns=protected,
                build_core=build_core,
                relevance_model=rel_model,
            ))


def fairness_metrics(model, frame: Frame, protected_cols: list[str],
                     reference: list[str] | None = None,
                     favorable_class: str | None = None) -> Frame:
    """Per-protected-group fairness table (reference:
    ``water/rapids/ast/prims/models/AstFairnessMetrics.java`` — tp/fp/tn/fn,
    accuracy/precision/f1/tpr/tnr/fpr/fnr, AUC, logloss, selectedRatio, plus
    the adverse-impact ratio (AIR) and Fisher p-value vs the reference group).
    The reference returns a map of frames (overview + per-group ROC tables);
    here the overview frame carries the full metric set — the per-group ROC
    curves are recoverable via ``model.model_performance`` on a sliced frame.
    """
    import numpy as np

    from h2o3_tpu.frame.types import VecType
    from h2o3_tpu.frame.vec import Vec

    if not model.is_classifier or len(model.response_domain or ()) != 2:
        raise ValueError("fairnessMetrics requires a binomial model")
    dom = list(model.response_domain)
    fav = favorable_class or dom[1]
    if fav not in dom:
        raise ValueError(f"favorable class {fav!r} not in domain {dom}")
    fi = dom.index(fav)

    preds = model.predict(frame)
    p = np.asarray(preds.vec(f"p{fav}").to_numpy(), np.float64)[: frame.nrows]
    yl = frame.vec(model.response_column).labels()
    act = np.array([lbl == fav for lbl in yl], bool)
    thr = getattr(model, "_default_threshold", None)
    thr = 0.5 if thr is None else float(thr)   # 0.0 is a valid threshold
    sel = p >= thr

    glabels = [frame.vec(c).labels() for c in protected_cols]
    keys = list(zip(*glabels))
    groups: dict[tuple, np.ndarray] = {}
    for i, k in enumerate(keys):
        groups.setdefault(k, []).append(i)
    groups = {k: np.asarray(v) for k, v in groups.items()}

    if reference:
        ref_key = tuple(reference)
        if ref_key not in groups:
            raise ValueError(f"reference group {ref_key} not present")
    else:   # reference default: the largest group (reference ditto)
        ref_key = max(groups, key=lambda k: len(groups[k]))

    def rank_auc(pi, ai):
        pos, neg = pi[ai], pi[~ai]
        if not len(pos) or not len(neg):
            return float("nan")
        order = np.argsort(np.concatenate([pos, neg]), kind="mergesort")
        ranks = np.empty(len(order)); ranks[order] = np.arange(1, len(order) + 1)
        return float((ranks[: len(pos)].sum() - len(pos) * (len(pos) + 1) / 2)
                     / (len(pos) * len(neg)))

    def fisher_p(a, b, c, d):
        try:
            from scipy.stats import fisher_exact
            return float(fisher_exact([[a, b], [c, d]])[1])
        except Exception:          # noqa: BLE001 — scipy-free fallback
            return float("nan")

    ref_idx = groups[ref_key]
    ref_sel_ratio = float(sel[ref_idx].mean()) if len(ref_idx) else float("nan")

    rows = []
    # NA protected-attribute values form their own group; None sorts first
    order = sorted(groups, key=lambda k: tuple("" if x is None else str(x)
                                               for x in k))
    for k in order:
        idx = groups[k]
        s, a = sel[idx], act[idx]
        tp = float((s & a).sum()); fp = float((s & ~a).sum())
        fn = float((~s & a).sum()); tn = float((~s & ~a).sum())
        tot = tp + fp + tn + fn
        pc = np.clip(p[idx], 1e-15, 1 - 1e-15)
        ll = float(-(a * np.log(pc) + ~a * np.log1p(-pc)).mean()) if tot else float("nan")
        sel_ratio = (tp + fp) / tot if tot else float("nan")
        rows.append(list(k) + [
            tot, tot / frame.nrows,
            (tp + tn) / tot if tot else np.nan,
            tp / (tp + fp) if tp + fp else np.nan,
            2 * tp / (2 * tp + fp + fn) if 2 * tp + fp + fn else np.nan,
            tp / (tp + fn) if tp + fn else np.nan,
            tn / (tn + fp) if tn + fp else np.nan,
            fp / (fp + tn) if fp + tn else np.nan,
            fn / (fn + tp) if fn + tp else np.nan,
            rank_auc(p[idx], a), ll, sel_ratio,
            sel_ratio / ref_sel_ratio if ref_sel_ratio else np.nan,
            fisher_p(tp + fp, tn + fn,
                     float(sel[ref_idx].sum()),
                     float((~sel[ref_idx]).sum())),
        ])
    names = list(protected_cols) + [
        "total", "relativeSize", "accuracy", "precision", "f1", "tpr", "tnr",
        "fpr", "fnr", "auc", "logloss", "selectedRatio", "air", "p_value"]
    ncat = len(protected_cols)
    vecs = [Vec.from_numpy(np.array([r[j] for r in rows], dtype=object),
                           type=VecType.STR) for j in range(ncat)]
    vecs += [Vec.from_numpy(np.float32([r[j] for r in rows]))
             for j in range(ncat, len(names))]
    return Frame(names, vecs)
