"""Sparse GLM — matrix-free IRLS over a COO design (SURVEY.md §7 hard (c)).

Reference: wide-sparse GLM in H2O runs over CXI sparse chunks
(``hex/glm/GLMTask.java`` sparse row iterators) and still forms the dense
[K,K] Gram. At 10k+ columns the Gram itself is fine ([K,K] fits), but
FORMING it from sparse rows costs nnz·K work; the TPU-native route is
matrix-free: each IRLS step solves the normal equations

    (X'WX + λ·n·I) β = X'Wz

by Jacobi-preconditioned conjugate gradients, where every operator
application is two sparse products (one gather + one ``segment_sum`` each —
:mod:`h2o3_tpu.frame.sparse`). The dense design is never materialized; the
intercept rides as an appended virtual all-ones column.
"""

from __future__ import annotations

import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.sparse import SparseFrame, SparseMatrix
from h2o3_tpu.ops.map_reduce import retrying
from h2o3_tpu.utils import telemetry as _tm
from h2o3_tpu.utils.costs import accounted_jit
from h2o3_tpu.utils.timeline import timed_event


@partial(jax.jit, static_argnames=("family", "cg_iters", "nrows", "ncols"))
def _sparse_irls_step(family: str, data, row, col, nrows: int, ncols: int,
                      y, w, beta, lam, cg_iters: int = 50):
    """One IRLS iteration with a CG inner solve; beta[-1] is the intercept."""
    sm = SparseMatrix(data, row, col, nrows, ncols, 0)

    def link_terms(eta):
        if family == "binomial":
            mu = jax.nn.sigmoid(eta)
            d = jnp.maximum(mu * (1 - mu), 1e-10)
            return mu, d, d          # var == d for logistic
        if family == "poisson":
            mu = jnp.exp(jnp.clip(eta, -30, 30))
            return mu, mu, mu
        return eta, jnp.ones_like(eta), jnp.ones_like(eta)   # gaussian

    eta = sm.matvec(beta[:-1]) + beta[-1]
    mu, d, var = link_terms(eta)
    W = w * d * d / jnp.maximum(var, 1e-12)
    z = eta + (y - mu) / jnp.maximum(d, 1e-12)
    nobs = jnp.maximum(w.sum(), 1.0)
    l2 = lam * nobs

    def A(v):
        xv = sm.matvec(v[:-1]) + v[-1]
        wxv = W * xv
        return jnp.concatenate([sm.rmatvec(wxv) + l2 * v[:-1],
                                wxv.sum()[None]])

    b = jnp.concatenate([sm.rmatvec(W * z), (W * z).sum()[None]])
    diag = jnp.concatenate([sm.col_sq_weighted(W) + l2,
                            jnp.maximum(W.sum(), 1e-12)[None]])
    M = lambda v: v / jnp.maximum(diag, 1e-12)
    beta_new, _ = jax.scipy.sparse.linalg.cg(A, b, x0=beta, M=M,
                                             maxiter=cg_iters, tol=1e-8)
    if family == "binomial":
        p = jnp.clip(mu, 1e-7, 1 - 1e-7)
        dev = -2.0 * (w * (y * jnp.log(p) + (1 - y) * jnp.log1p(-p))).sum()
    elif family == "poisson":
        dev = 2.0 * (w * (mu - y + jnp.where(y > 0, y * (jnp.log(
            jnp.maximum(y, 1e-30)) - jnp.clip(eta, -30, 30)), 0.0))).sum()
    else:
        dev = (w * (y - mu) ** 2).sum()
    return beta_new, dev


@accounted_jit("glm:sparse_irls_megastep", loop="glm_sparse_irls",
               static_argnames=("family", "k", "nrows", "ncols"))
def _sparse_irls_megastep(family: str, data, row, col, nrows: int, ncols: int,
                         y, w, beta, lam, k: int, it0, max_it, beta_eps,
                         dev_prev0):
    """Up to ``k`` CG-IRLS iterations in ONE compiled dispatch with the
    convergence test on device; the host fetches per-step deviances + step
    count once per megastep (stop-computing-on-converge ``while_loop``,
    same contract as the dense
    :func:`h2o3_tpu.models.glm._irls_megastep`)."""
    def cond(state):
        _, _, it, i, done, _, _ = state
        return (~done) & (i < k) & (it < max_it)

    def body(state):
        beta, dev_prev, it, i, done, devs, ran = state
        beta_new, dev = _sparse_irls_step(family, data, row, col, nrows,
                                          ncols, y, w, beta, lam)
        delta = jnp.max(jnp.abs(beta_new - beta))
        stop = delta < beta_eps
        if family == "gaussian":
            stop = stop | (it >= 1)
        stop = stop | (jnp.isfinite(dev_prev)
                       & (jnp.abs(dev_prev - dev)
                          <= 1e-6 * jnp.maximum(jnp.abs(dev_prev), 1.0)))
        return (beta_new, dev, it + 1, i + 1, stop,
                devs.at[i].set(dev), ran.at[i].set(True))

    state = (beta, jnp.asarray(dev_prev0, jnp.float32),
             jnp.asarray(it0, jnp.int32), jnp.asarray(0, jnp.int32),
             jnp.asarray(False), jnp.full(k, jnp.nan, jnp.float32),
             jnp.zeros(k, bool))
    beta, _, _, _, done, devs, ran = jax.lax.while_loop(cond, body, state)
    return beta, devs, ran, done


def fit_sparse_glm(builder, job, sf: SparseFrame, y: str, weights=None):
    """Driver for GLM on a :class:`SparseFrame`; returns a GLMModel."""
    from h2o3_tpu.models.glm import GLMModel
    from h2o3_tpu.models.model_base import (ModelParameters, compute_metrics,
                                            make_model_key, megastep_k,
                                            publish_dispatch_audit)

    p = builder.params
    family = str(p["family"]).lower()
    if family in ("auto",):
        family = "gaussian"
    if family not in ("gaussian", "binomial", "poisson"):
        raise ValueError(f"sparse GLM supports gaussian/binomial/poisson, "
                         f"got {family!r} (densify for other families)")
    mi = int(50 if p.get("max_iterations") is None else p["max_iterations"])
    if mi == -1:
        mi = 50
    elif mi < 1:
        raise ValueError("max_iterations must be >= 1 (or -1 for auto)")
    if float(p.get("alpha") or 0.0) > 0:
        raise ValueError("sparse GLM is L2-only (alpha=0); the reference's "
                         "sparse path likewise solves ridge IRLS")

    X = sf.X
    yv = np.asarray(sf.vec(y).to_numpy(), np.float64)
    if family == "binomial":
        uniq = set(np.unique(yv).tolist())
        if uniq <= {-1.0, 1.0}:          # SVMLight labels
            yv = (yv + 1.0) / 2.0
        elif not uniq <= {0.0, 1.0}:
            raise ValueError("binomial sparse GLM needs 0/1 or ±1 labels")
    yy = jnp.asarray(yv.astype(np.float32))
    w = (jnp.asarray(np.asarray(weights, np.float32))
         if weights is not None else jnp.ones(X.nrows, jnp.float32))

    beta = jnp.zeros(X.ncols + 1, jnp.float32)
    lam = float(p.get("lambda_") or 0.0)
    k = megastep_k()
    beta_eps = float(p.get("beta_epsilon") or 1e-4)
    dev_prev, dev, it_total, done = np.inf, np.inf, 0, False
    megasteps = 0
    while it_total < mi and not done:
        t0 = time.time_ns()

        def _megastep(beta=beta, it_total=it_total, dev_prev=dev_prev):
            b, devs_d, ran_d, done_d = _sparse_irls_megastep(
                family, X.data, X.row, X.col, X.nrows, X.ncols, yy, w, beta,
                lam, k, it_total, mi, beta_eps, dev_prev)
            # ONE blocking transfer per K-step megastep — the per-step
            # deviance series + executed count IS the convergence test
            devs, ran, done = map(  # graftlint: ok(one batched fetch per megastep)
                np.asarray, jax.device_get((devs_d, ran_d, done_d)))
            return b, devs, ran, done

        with timed_event("iteration", "glm_sparse_irls"):
            # transient dispatch failures retry with backoff (functional
            # over beta — a re-run is exact)
            beta, devs, ran, done = retrying("glm_megastep", _megastep)
        megasteps += 1
        n = int(ran.sum())
        steps = [float(d) for d in devs[:n]]
        dev = steps[-1] if steps else dev
        dev_prev = dev
        done = bool(done)
        it_total += n
        dt = (time.time_ns() - t0) / 1e9
        for _ in range(max(n, 1)):
            _tm.ITER_SECONDS.labels(loop="glm_sparse_irls").observe(
                dt / max(n, 1))
        job.update(it_total / mi,
                   f"sparse IRLS iter {it_total - 1} deviance {dev:.4f}")
    it = max(it_total - 1, 0)
    publish_dispatch_audit(builder, "glm_sparse_irls",
                           iterations=max(it_total, 1),
                           host_syncs=megasteps, device_dispatches=megasteps)

    nclasses = 2 if family == "binomial" else 0
    mparams = ModelParameters(p)
    mparams["family"] = family
    model = GLMModel(
        key=make_model_key(builder.algo, builder.model_id),
        params=mparams, data_info=None, response_column=y,
        response_domain=("0", "1") if family == "binomial" else None,
        output=dict(beta=beta, coef=np.asarray(jax.device_get(beta), np.float64),
                    coef_names=[f"C{j}" for j in range(X.ncols)],
                    residual_deviance=float(dev), iterations=it + 1,
                    family=family, lambda_best=lam, regularization_path=None,
                    sparse=True),
    )
    raw = model._score_raw(sf)
    mask = jnp.ones(X.nrows, bool)
    model.training_metrics = compute_metrics(raw, yy, mask, nclasses)
    return model
