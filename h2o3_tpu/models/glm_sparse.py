"""Sparse GLM — matrix-free IRLS over a COO design (SURVEY.md §7 hard (c)).

Reference: wide-sparse GLM in H2O runs over CXI sparse chunks
(``hex/glm/GLMTask.java`` sparse row iterators) and still forms the dense
[K,K] Gram. At 10k+ columns the Gram itself is fine ([K,K] fits), but
FORMING it from sparse rows costs nnz·K work; the TPU-native route is
matrix-free: each IRLS step solves the normal equations

    (X'WX + λ·n·I) β = X'Wz

by Jacobi-preconditioned conjugate gradients, where every operator
application is two sparse products (one gather + one ``segment_sum`` each —
:mod:`h2o3_tpu.frame.sparse`). The dense design is never materialized; the
intercept rides as an appended virtual all-ones column.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.sparse import SparseFrame, SparseMatrix
from h2o3_tpu.utils import telemetry as _tm
from h2o3_tpu.utils.timeline import timed_event


@partial(jax.jit, static_argnames=("family", "cg_iters", "nrows", "ncols"))
def _sparse_irls_step(family: str, data, row, col, nrows: int, ncols: int,
                      y, w, beta, lam, cg_iters: int = 50):
    """One IRLS iteration with a CG inner solve; beta[-1] is the intercept."""
    sm = SparseMatrix(data, row, col, nrows, ncols, 0)

    def link_terms(eta):
        if family == "binomial":
            mu = jax.nn.sigmoid(eta)
            d = jnp.maximum(mu * (1 - mu), 1e-10)
            return mu, d, d          # var == d for logistic
        if family == "poisson":
            mu = jnp.exp(jnp.clip(eta, -30, 30))
            return mu, mu, mu
        return eta, jnp.ones_like(eta), jnp.ones_like(eta)   # gaussian

    eta = sm.matvec(beta[:-1]) + beta[-1]
    mu, d, var = link_terms(eta)
    W = w * d * d / jnp.maximum(var, 1e-12)
    z = eta + (y - mu) / jnp.maximum(d, 1e-12)
    nobs = jnp.maximum(w.sum(), 1.0)
    l2 = lam * nobs

    def A(v):
        xv = sm.matvec(v[:-1]) + v[-1]
        wxv = W * xv
        return jnp.concatenate([sm.rmatvec(wxv) + l2 * v[:-1],
                                wxv.sum()[None]])

    b = jnp.concatenate([sm.rmatvec(W * z), (W * z).sum()[None]])
    diag = jnp.concatenate([sm.col_sq_weighted(W) + l2,
                            jnp.maximum(W.sum(), 1e-12)[None]])
    M = lambda v: v / jnp.maximum(diag, 1e-12)
    beta_new, _ = jax.scipy.sparse.linalg.cg(A, b, x0=beta, M=M,
                                             maxiter=cg_iters, tol=1e-8)
    if family == "binomial":
        p = jnp.clip(mu, 1e-7, 1 - 1e-7)
        dev = -2.0 * (w * (y * jnp.log(p) + (1 - y) * jnp.log1p(-p))).sum()
    elif family == "poisson":
        dev = 2.0 * (w * (mu - y + jnp.where(y > 0, y * (jnp.log(
            jnp.maximum(y, 1e-30)) - jnp.clip(eta, -30, 30)), 0.0))).sum()
    else:
        dev = (w * (y - mu) ** 2).sum()
    return beta_new, dev


def fit_sparse_glm(builder, job, sf: SparseFrame, y: str, weights=None):
    """Driver for GLM on a :class:`SparseFrame`; returns a GLMModel."""
    from h2o3_tpu.models.glm import GLMModel
    from h2o3_tpu.models.model_base import (ModelParameters, compute_metrics,
                                            make_model_key)

    p = builder.params
    family = str(p["family"]).lower()
    if family in ("auto",):
        family = "gaussian"
    if family not in ("gaussian", "binomial", "poisson"):
        raise ValueError(f"sparse GLM supports gaussian/binomial/poisson, "
                         f"got {family!r} (densify for other families)")
    mi = int(50 if p.get("max_iterations") is None else p["max_iterations"])
    if mi == -1:
        mi = 50
    elif mi < 1:
        raise ValueError("max_iterations must be >= 1 (or -1 for auto)")
    if float(p.get("alpha") or 0.0) > 0:
        raise ValueError("sparse GLM is L2-only (alpha=0); the reference's "
                         "sparse path likewise solves ridge IRLS")

    X = sf.X
    yv = np.asarray(sf.vec(y).to_numpy(), np.float64)
    if family == "binomial":
        uniq = set(np.unique(yv).tolist())
        if uniq <= {-1.0, 1.0}:          # SVMLight labels
            yv = (yv + 1.0) / 2.0
        elif not uniq <= {0.0, 1.0}:
            raise ValueError("binomial sparse GLM needs 0/1 or ±1 labels")
    yy = jnp.asarray(yv.astype(np.float32))
    w = (jnp.asarray(np.asarray(weights, np.float32))
         if weights is not None else jnp.ones(X.nrows, jnp.float32))

    beta = jnp.zeros(X.ncols + 1, jnp.float32)
    lam = float(p.get("lambda_") or 0.0)
    dev_prev = np.inf
    it = 0
    for it in range(mi):
        with timed_event("iteration", "glm_sparse_irls",
                         observe=_tm.ITER_SECONDS.labels(
                             loop="glm_sparse_irls")):
            beta_new, dev_d = _sparse_irls_step(
                family, X.data, X.row, X.col, X.nrows, X.ncols, yy, w, beta,
                lam)
            # ONE batched transfer per iteration — deviance + step size
            # (two separate device_gets doubled host round-trips: TRC003)
            dev, delta = map(  # graftlint: ok(batched convergence fetch)
                float, jax.device_get(
                    (dev_d, jnp.max(jnp.abs(beta_new - beta)))))
        beta = beta_new
        job.update((it + 1) / mi,
                   f"sparse IRLS iter {it} deviance {dev:.4f}")
        if family == "gaussian" and it >= 1:
            break
        if delta < float(p.get("beta_epsilon") or 1e-4):
            break
        if np.isfinite(dev_prev) and abs(dev_prev - dev) <= \
                1e-6 * max(abs(dev_prev), 1.0):
            break
        dev_prev = dev

    nclasses = 2 if family == "binomial" else 0
    mparams = ModelParameters(p)
    mparams["family"] = family
    model = GLMModel(
        key=make_model_key(builder.algo, builder.model_id),
        params=mparams, data_info=None, response_column=y,
        response_domain=("0", "1") if family == "binomial" else None,
        output=dict(beta=beta, coef=np.asarray(jax.device_get(beta), np.float64),
                    coef_names=[f"C{j}" for j in range(X.ncols)],
                    residual_deviance=float(dev), iterations=it + 1,
                    family=family, lambda_best=lam, regularization_path=None,
                    sparse=True),
    )
    raw = model._score_raw(sf)
    mask = jnp.ones(X.nrows, bool)
    model.training_metrics = compute_metrics(raw, yy, mask, nclasses)
    return model
