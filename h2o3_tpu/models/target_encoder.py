"""TargetEncoder — CV-aware categorical target encoding with blending.

Reference: ``h2o-extensions/target-encoder/.../TargetEncoder.java`` +
``TargetEncoderModel.java``: per categorical column, replace levels with the
(blended) mean response computed from training statistics; leakage handling
via ``data_leakage_handling`` = None | KFold | LeaveOneOut; blending shrinks
small groups toward the prior with inflection_point/smoothing
(``TargetEncoderHelper.java``).

TPU-native: per-level (sum_y, count) statistics are one ``segment_sum`` over
the categorical codes (the reference runs a group-by MRTask +
``TargetEncoderBroadcastJoin``); encoding a frame is one gather through the
level→value LUT.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.types import VecType
from h2o3_tpu.frame.vec import Vec
from h2o3_tpu.models.data_info import response_as_float
from h2o3_tpu.models.job import Job
from h2o3_tpu.models.model_base import Model, ModelBuilder, make_model_key


def _blend(sum_y, cnt, prior, inflection_point, smoothing):
    """Blended level mean (reference ``TargetEncoderHelper.getBlendedValue``):
    lambda = 1/(1+exp((ip - n)/s)); value = lambda*mean + (1-lambda)*prior."""
    mean = sum_y / jnp.maximum(cnt, 1.0)
    lam = 1.0 / (1.0 + jnp.exp((inflection_point - cnt) / jnp.maximum(smoothing, 1e-6)))
    return jnp.where(cnt > 0, lam * mean + (1 - lam) * prior, prior)


class TargetEncoderModel(Model):
    algo = "targetencoder"

    def is_applied(self, frame) -> bool:
        """True when every encoded column this transformer adds is already
        present (scoring-pipeline idempotence hook)."""
        return all(f"{c}_te" in frame for c in self.output["columns"])

    def transform(self, frame: Frame, as_training: bool = False) -> Frame:
        """Append ``<col>_te`` columns (h2o-py:
        ``H2OTargetEncoderEstimator.transform``). ``as_training`` applies the
        leakage strategy (KFold/LOO) instead of the full-data statistics."""
        out = Frame(list(frame.names), list(frame.vecs))
        o = self.output
        if as_training and o["data_leakage_handling"] != "None" \
                and o.get("train_encoded") is not None:
            for c in o["columns"]:
                out.add(f"{c}_te", o["train_encoded"][c])
            return out
        for c in o["columns"]:
            v = frame.vec(c)
            lut = o["lut"][c]                    # [K+1]: per-level value + NA slot
            if v.domain != o["domains"][c]:
                # map this frame's levels onto the training domain
                tdom = {s: i for i, s in enumerate(o["domains"][c])}
                remap = np.array([tdom.get(s, len(lut) - 1) for s in v.domain]
                                 + [len(lut) - 1], np.int32)
                codes = jnp.asarray(remap)[jnp.clip(v.data, -1, len(v.domain) - 1)]
                codes = jnp.where(v.data < 0, len(lut) - 1, codes)
            else:
                codes = jnp.where(v.data < 0, len(lut) - 1, v.data)
            enc = jnp.asarray(lut)[codes]
            out.add(f"{c}_te", Vec(enc.astype(jnp.float32), VecType.NUM, v.nrows))
        return out

    def _score_raw(self, frame: Frame):
        raise NotImplementedError("TargetEncoder is a transformer; use transform()")

    def model_performance(self, frame: Frame):
        return None


class TargetEncoder(ModelBuilder):
    """h2o-py surface: ``H2OTargetEncoderEstimator``."""

    algo = "targetencoder"

    def _holdout_metrics(self, model, frame, y, w):
        return None   # transformer: no scoring metrics (reference: TE model
        #               metrics are the identity transform's)

    def _cross_validate(self, *a, **kw):
        return None   # nfolds configures the KFold leakage strategy, not CV

    @classmethod
    def defaults(cls) -> dict:
        return dict(
            super().defaults(),
            columns=None,                       # None → all categorical x
            data_leakage_handling="None",       # None | KFold | LeaveOneOut
            blending=False,
            inflection_point=10.0,
            smoothing=20.0,
            noise=0.0,
            fold_column=None,
        )

    def _fit(self, job: Job, frame: Frame, x, y, weights) -> TargetEncoderModel:
        p = self.params
        yvec = frame.vec(y)
        if yvec.is_categorical and yvec.cardinality() != 2:
            raise ValueError("TargetEncoder supports binary or numeric targets")
        yy, valid = response_as_float(yvec)
        w = weights * valid
        cols = p["columns"] or [c for c in x if frame.vec(c).is_categorical]
        if not cols:
            raise ValueError("no categorical columns to encode")

        prior = float(jax.device_get((w * yy).sum() / jnp.maximum(w.sum(), 1e-30)))
        ip, sm = float(p["inflection_point"]), float(p["smoothing"])
        blend = bool(p["blending"])

        lut, domains, train_encoded = {}, {}, {}
        leak = str(p["data_leakage_handling"])
        nfolds = int(p.get("nfolds") or 5)
        if leak == "KFold" and p.get("fold_column"):
            # an explicit fold column overrides nfolds: every distinct
            # value is a fold, else rows in folds >= nfolds would keep the
            # 0.0 initializer below (never encoded)
            nfolds = self._fold_column_cardinality(frame)
        fold = self._fold_ids(frame, nfolds, yvec) if leak == "KFold" \
            else None
        noise = float(p["noise"])
        key = jax.random.PRNGKey(int(p.get("seed") or 0) if int(p.get("seed") or -1) >= 0 else 7)

        for c in cols:
            v = frame.vec(c)
            K = v.cardinality()
            domains[c] = v.domain
            code = jnp.where(v.data < 0, K, jnp.clip(v.data, 0, K - 1))
            sum_y = jax.ops.segment_sum(w * yy, code, K + 1)
            cnt = jax.ops.segment_sum(w, code, K + 1)
            if blend:
                vals = _blend(sum_y, cnt, prior, ip, sm)
            else:
                vals = jnp.where(cnt > 0, sum_y / jnp.maximum(cnt, 1.0), prior)
            # NA slot encodes to the prior (reference: NA treated as own level
            # only when seen in training; default to prior)
            vals = vals.at[K].set(_blend(sum_y[K], cnt[K], prior, ip, sm)
                                  if blend and float(cnt[K]) > 0 else
                                  (float(sum_y[K] / cnt[K]) if float(cnt[K]) > 0
                                   else prior))
            lut[c] = np.asarray(jax.device_get(vals), np.float32)

            if leak == "KFold":
                enc = jnp.zeros(frame.plen, jnp.float32)
                for f in range(nfolds):
                    out_mask = (fold == f)
                    wf = w * (~out_mask)
                    s_f = jax.ops.segment_sum(wf * yy, code, K + 1)
                    c_f = jax.ops.segment_sum(wf, code, K + 1)
                    pf = float(jax.device_get(
                        (wf * yy).sum() / jnp.maximum(wf.sum(), 1e-30)))
                    v_f = _blend(s_f, c_f, pf, ip, sm) if blend else \
                        jnp.where(c_f > 0, s_f / jnp.maximum(c_f, 1.0), pf)
                    enc = jnp.where(out_mask, v_f[code], enc)
                train_encoded[c] = Vec(enc, VecType.NUM, frame.nrows)
            elif leak == "LeaveOneOut":
                s_loo = sum_y[code] - w * yy
                c_loo = cnt[code] - w
                v_loo = _blend(s_loo, c_loo, prior, ip, sm) if blend else \
                    jnp.where(c_loo > 0, s_loo / jnp.maximum(c_loo, 1.0), prior)
                train_encoded[c] = Vec(v_loo.astype(jnp.float32), VecType.NUM,
                                       frame.nrows)
            if noise > 0 and c in train_encoded:
                key, kn = jax.random.split(key)
                tv = train_encoded[c]
                train_encoded[c] = Vec(
                    tv.data + jax.random.uniform(kn, tv.data.shape,
                                                 minval=-noise, maxval=noise),
                    VecType.NUM, tv.nrows)
            job.update(0.9, f"encoded {c}")

        return TargetEncoderModel(
            key=make_model_key(self.algo, self.model_id),
            params=self.params, data_info=None, response_column=y,
            response_domain=None,
            output=dict(columns=cols, lut=lut, domains=domains, prior=prior,
                        data_leakage_handling=leak,
                        train_encoded=train_encoded or None),
        )
