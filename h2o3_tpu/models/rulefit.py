"""RuleFit — rule extraction from a tree ensemble + sparse linear model.

Reference: ``hex/rulefit/RuleFit.java`` (Friedman & Popescu): fit GBM/DRF
ensembles over a ladder of depths, decompose every tree path into a
conjunctive rule, build the 0/1 rule-activation matrix, then fit an
L1-regularized GLM over rules (+ optionally the linear terms); nonzero
coefficients become the interpretable rule list (``Rule.java``,
``RuleFitUtils.java``).

TPU-native: rule activation for ALL heap nodes of a tree is one vectorized
masked descent over the dense heap (no per-rule re-evaluation) — the
activation matrix is assembled on device and fed to the existing GLM IRLS.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.types import VecType
from h2o3_tpu.frame.vec import Vec
from h2o3_tpu.models.gbm import tree_matrix
from h2o3_tpu.models.job import Job
from h2o3_tpu.models.model_base import Model, ModelBuilder, make_model_key


def _node_masks(X, tree):
    """[rows, heap] node-membership for one dense-heap tree: root mask is 1;
    children AND the split condition down the heap (vectorized level sweep)."""
    heap = tree.feat.shape[0]
    rows = X.shape[0]
    masks = jnp.zeros((rows, heap), bool).at[:, 0].set(True)
    n_internal = heap // 2
    for i in range(n_internal):
        f, t = tree.feat[i], tree.thresh_val[i]
        xv = X[:, jnp.maximum(f, 0)]
        nan = jnp.isnan(xv)
        go_left = jnp.where(nan, tree.na_left[i], xv < t)
        m = masks[:, i] & tree.is_split[i]
        masks = masks.at[:, 2 * i + 1].set(m & go_left)
        masks = masks.at[:, 2 * i + 2].set(m & ~go_left)
    return masks


class RuleFitModel(Model):
    algo = "rulefit"

    def _rule_matrix(self, frame: Frame) -> jax.Array:
        o = self.output
        X = tree_matrix(frame, o["x_cols"], o["feat_domains"])
        lin = None
        if o["model_type"] in ("linear", "rules_and_linear"):
            lin = (X - jnp.asarray(o["lin_mean"])[None, :]) / \
                jnp.asarray(o["lin_sd"])[None, :]
            lin = jnp.where(jnp.isnan(lin), 0.0, lin)
            if o["model_type"] == "linear":
                return lin                       # no tree sweep needed
        cols = [_node_masks(X, tr)[:, 1:] for tr in o["trees"]]
        M = jnp.concatenate(cols, axis=1).astype(jnp.float32)
        M = M[:, jnp.asarray(o["rule_keep"])]
        return M if lin is None else jnp.concatenate([M, lin], axis=1)

    def _score_raw(self, frame: Frame):
        M = self._rule_matrix(frame)
        beta = jnp.asarray(self.output["beta"])
        eta = M @ beta[:-1] + beta[-1]
        if self.nclasses == 2:
            p = jax.nn.sigmoid(eta)
            return jnp.stack([1 - p, p], axis=1)
        return eta

    def rule_importance(self) -> list[tuple[str, float]]:
        """Nonzero rules sorted by |coefficient| (reference: significant rules
        table)."""
        o = self.output
        out = [(d, float(c)) for d, c in zip(o["rule_names"], o["beta"][:-1])
               if abs(float(c)) > 1e-8]
        return sorted(out, key=lambda t: -abs(t[1]))


class RuleFit(ModelBuilder):
    """h2o-py surface: ``H2ORuleFitEstimator``."""

    algo = "rulefit"

    @classmethod
    def defaults(cls) -> dict:
        return dict(
            super().defaults(),
            model_type="rules_and_linear",   # rules | linear | rules_and_linear
            min_rule_length=1,
            max_rule_length=3,
            rule_generation_ntrees=10,       # trees per depth (reference: 50)
            lambda_=1e-3,                    # L1 strength for rule selection
            max_num_rules=-1,
        )

    def _fit(self, job: Job, frame: Frame, x, y, weights) -> RuleFitModel:
        p = self.params
        yvec = frame.vec(y)
        binom = yvec.is_categorical
        if binom and yvec.cardinality() != 2:
            raise ValueError("RuleFit supports binary classification or regression")

        # 1) tree ensemble over the depth ladder (reference: one model per depth)
        from h2o3_tpu.models.gbm import GBM
        trees = []
        lo, hi = int(p["min_rule_length"]), int(p["max_rule_length"])
        for d in range(lo, hi + 1):
            # ordinal cat encoding: rule extraction reads threshold splits
            gbm = GBM(ntrees=int(p["rule_generation_ntrees"]), max_depth=d,
                      learn_rate=0.1, seed=int(p.get("seed") or 0) + d,
                      categorical_encoding="ordinal") \
                .train(x=x, y=y, training_frame=frame, weights=weights)
            trees.extend(gbm.output["trees"])
            job.update(0.3 * (d - lo + 1) / (hi - lo + 1), f"depth {d} trees")
        feat_domains = {c: frame.vec(c).domain for c in x
                        if frame.vec(c).is_categorical}

        # 2) rule activation matrix (device), pruning dead/constant rules
        X = tree_matrix(frame, x, feat_domains)
        mask = frame.row_mask()
        blocks = [_node_masks(X, tr)[:, 1:] for tr in trees]
        M = jnp.concatenate(blocks, axis=1).astype(jnp.float32)
        frac = jnp.where(mask[:, None], M, 0.0).sum(0) / mask.sum()
        keep = np.asarray(jax.device_get((frac > 0.005) & (frac < 0.995)))
        max_rules = int(p["max_num_rules"])
        if max_rules > 0 and keep.sum() > max_rules:
            idx = np.nonzero(keep)[0]
            keep[:] = False
            keep[idx[:max_rules]] = True
        M = M[:, jnp.asarray(keep)]

        all_names = []
        for ti, tr in enumerate(trees):
            all_names.extend(_rule_names_for_tree(tr, x, ti))
        rule_names = [n for n, k in zip(all_names, keep) if k]

        lin_mean = np.zeros(len(x), np.float32)
        lin_sd = np.ones(len(x), np.float32)
        if p["model_type"] in ("linear", "rules_and_linear"):
            Xm = jnp.where(mask[:, None], X, jnp.nan)
            lin_mean = np.asarray(jax.device_get(jnp.nanmean(Xm, axis=0)))
            lin_sd = np.maximum(np.asarray(jax.device_get(jnp.nanstd(Xm, axis=0))),
                                1e-6)
            lin = (X - lin_mean[None, :]) / lin_sd[None, :]
            lin = jnp.where(jnp.isnan(lin), 0.0, lin)
            M = lin if p["model_type"] == "linear" else \
                jnp.concatenate([M, lin], axis=1)
            rule_names = (rule_names if p["model_type"] != "linear" else []) + \
                [f"linear.{c}" for c in x]

        # 3) sparse GLM on the rule matrix (reference: GLM alpha=1 lambda search)
        from h2o3_tpu.models.glm import GLM
        lvl1 = Frame([f"r{i}" for i in range(M.shape[1])] + [y],
                     [Vec(M[:, i], VecType.NUM, frame.nrows)
                      for i in range(M.shape[1])] + [yvec])
        glm = GLM(family="binomial" if binom else "gaussian",
                  alpha=1.0, lambda_=float(p["lambda_"]), standardize=False) \
            .train(x=[f"r{i}" for i in range(M.shape[1])], y=y,
                   training_frame=lvl1, weights=weights)
        beta = np.asarray(glm.output["coef"], np.float64)

        if p["model_type"] == "linear":
            trees = []   # linear-only models never traverse (or serialize) trees
        return RuleFitModel(
            key=make_model_key(self.algo, self.model_id),
            params=self.params, data_info=None, response_column=y,
            response_domain=yvec.domain if binom else None,
            output=dict(trees=trees, x_cols=list(x), feat_domains=feat_domains,
                        rule_keep=keep, rule_names=rule_names, beta=beta,
                        model_type=p["model_type"], lin_mean=lin_mean,
                        lin_sd=lin_sd, glm_key=glm.key),
        )


def _rule_names_for_tree(tr, names, ti: int) -> list[str]:
    feat = np.asarray(jax.device_get(tr.feat))
    tv = np.asarray(jax.device_get(tr.thresh_val))
    nal = np.asarray(jax.device_get(tr.na_left))
    isp = np.asarray(jax.device_get(tr.is_split))
    heap = len(feat)
    conds: dict[int, list[str]] = {0: []}
    for i in range(heap // 2):
        if not isp[i]:
            continue
        base = conds.get(i)
        if base is None:
            continue
        f, t = names[feat[i]], tv[i]
        na = " or NA" if nal[i] else ""
        conds[2 * i + 1] = base + [f"({f} < {t:.6g}{na})"]
        conds[2 * i + 2] = base + [f"({f} >= {t:.6g}{'' if nal[i] else ' or NA'})"]
    return [f"M{ti}.N{i}: " + " & ".join(conds[i]) if i in conds and conds[i]
            else f"M{ti}.N{i}" for i in range(1, heap)]
