"""GLM — generalized linear models with IRLSM.

Reference: ``hex/glm/GLM.java:543,880,1335`` — per-IRLS-iteration the cluster
computes the weighted Gram matrix X'WX via ``GLMIterationTask``
(``hex/glm/GLMTask.java:1509``, a chunk-parallel MRTask), the leader solves by
Cholesky (``hex/gram/Gram.java:452-473``), and iterates to convergence
(``beta_epsilon``/``objective_epsilon``). Regularization: elastic net; L2 goes
into the Gram diagonal, L1 via ADMM (``hex/optimization/ADMM.java``).

TPU-native: the Gram contraction is one ``einsum`` over the row-sharded design
matrix — XLA reduces per-chip partials over ICI (exactly the MRTask tree reduce)
and the [K,K] solve happens replicated. The whole IRLS step is a single jitted
program; only the scalar deviance crosses to host for the convergence test.
L1 is handled by ADMM over the cached Cholesky factor, mirroring the reference.
"""

from __future__ import annotations

import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from scipy.special import erfc

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.data_info import DataInfo
from h2o3_tpu.models.distributions import get_family
from h2o3_tpu.models.job import Job
from h2o3_tpu.ops.map_reduce import retrying
from h2o3_tpu.models.model_base import (Model, ModelBuilder, ModelParameters,
                                        make_model_key, megastep_k,
                                        publish_dispatch_audit)
from h2o3_tpu.utils import telemetry as _tm
from h2o3_tpu.utils.costs import accounted_jit
from h2o3_tpu.utils.timeline import timed_event


def _fam(family: str, tweedie_p: float):
    """``tweedie_p`` doubles as the family's auxiliary parameter: variance
    power for tweedie, dispersion theta for negativebinomial (one static
    slot through every jitted solver)."""
    if family == "tweedie":
        return get_family(family, p=tweedie_p)
    if family == "negativebinomial":
        return get_family(family, theta=tweedie_p)
    return get_family(family)


def _weighted_gram(X, W, z, l2, nobs, jitter):
    """Normal equations for weighted LS with an unpenalized intercept column:
    gram = [X,1]'W[X,1] + l2*nobs*diag(1..1,0) + jitter*I, rhs = [X,1]'Wz.
    One contraction over the row-sharded X — XLA reduces per-chip partials over
    ICI (the reference's ``GLMIterationTask`` Gram reduce).

    Contractions run at HIGHEST precision: the TPU MXU's default bf16 inputs
    lose ~1e-2 relative on the Gram, which breaks the Cholesky on
    ill-conditioned designs (the solve is [K,K] — full f32 costs nothing).
    """
    k = X.shape[1]
    hi = jax.lax.Precision.HIGHEST
    Xw = X * W[:, None]
    gram = jnp.empty((k + 1, k + 1), X.dtype)
    gram = gram.at[:k, :k].set(jnp.matmul(Xw.T, X, precision=hi))
    xw_sum = Xw.sum(axis=0)
    gram = gram.at[:k, k].set(xw_sum).at[k, :k].set(xw_sum).at[k, k].set(W.sum())
    rhs = jnp.concatenate([jnp.matmul(Xw.T, z, precision=hi),
                           (W * z).sum()[None]])
    penalty = l2 * nobs * jnp.concatenate([jnp.ones(k), jnp.zeros(1)])
    # ridge jitter relative to the Gram scale: collinear designs (e.g. a
    # RuleFit rule matrix with complementary 0/1 rules) stay factorizable
    j = jitter * (jnp.trace(gram) / (k + 1) + 1.0)
    gram = gram + jnp.diag(penalty) + j * jnp.eye(k + 1)
    return gram, rhs


def _nn_solve(gram, rhs, beta0, tol: float = 1e-7, max_passes: int = 100):
    """Non-negative solve of the penalized normal equations by cyclic projected
    coordinate descent (reference: ADMM.java solves the same bound-constrained
    QP; for a convex quadratic, projected CD converges to the NNLS optimum).
    The intercept (last coordinate) stays unconstrained; sweeps stop once the
    largest coordinate move falls below ``tol``."""
    k = gram.shape[0] - 1

    def coord(j, b):
        r = rhs[j] - gram[j] @ b
        bj = b[j] + r / jnp.maximum(gram[j, j], 1e-12)
        return b.at[j].set(jnp.where(j < k, jnp.maximum(bj, 0.0), bj))

    def body(state):
        i, b, _ = state
        nb = jax.lax.fori_loop(0, k + 1, coord, b)
        return i + 1, nb, jnp.max(jnp.abs(nb - b))

    _, beta, _ = jax.lax.while_loop(
        lambda s: (s[0] < max_passes) & (s[2] > tol), body,
        (0, beta0, jnp.asarray(jnp.inf, beta0.dtype)))
    return beta


@partial(jax.jit, static_argnames=("family", "tweedie_p", "non_negative"))
def _irls_step(family: str, tweedie_p: float, X, y, w, beta, l2,
               non_negative: bool = False, off=0.0):
    """One IRLS iteration: weighted Gram + Cholesky solve (all on device);
    under ``non_negative`` the same system is solved with projected CD.
    ``off`` is the per-row margin offset (reference offset_column: enters
    eta but is excluded from the working response the solve fits).

    Returns ``(new_beta, deviance, step_delta)`` — the convergence scalars
    are computed ON DEVICE so the host loop fetches both in one transfer
    (graftlint TRC003: two separate device_gets per iteration doubled the
    host round-trips on the IRLS hot path)."""
    fam = _fam(family, tweedie_p)
    eta = X @ beta[:-1] + beta[-1] + off
    mu = fam.linkinv(eta)
    d = fam.dmu_deta(eta)
    var = fam.variance(mu)
    W = w * d * d / jnp.maximum(var, 1e-12)
    z = eta + (y - mu) / jnp.maximum(d, 1e-12) - off
    nobs = jnp.maximum(w.sum(), 1.0)
    gram, rhs = _weighted_gram(X, W, z, l2, nobs, 1e-5)
    if non_negative:
        new_beta = _nn_solve(gram, rhs, jnp.maximum(beta, 0.0).at[-1].set(beta[-1]))
    else:
        chol = jax.scipy.linalg.cho_factor(gram, lower=True)
        new_beta = jax.scipy.linalg.cho_solve(chol, rhs)
    dev = (w * fam.deviance(y, mu)).sum()
    return new_beta, dev, jnp.max(jnp.abs(new_beta - beta))


# the host-dispatched IRLS program — registered with the compute
# observatory (utils/costs.py): per-signature compile time + cost_analysis
# FLOPs/bytes land in /3/Compute, and a shape-changed rebuild records a
# recompile event naming the changed dimension
@accounted_jit("glm:irls_megastep", loop="glm_irls",
               static_argnames=("family", "tweedie_p", "non_negative",
                                "k", "has_bounds"))
def _irls_megastep(family: str, tweedie_p: float, X, y, w, beta, l2, k: int,
                   it0, max_it, beta_eps, obj_eps, dev_prev0,
                   non_negative: bool = False, off=0.0, lo=None, hi=None,
                   has_bounds: bool = False):
    """Up to ``k`` IRLS iterations in ONE compiled dispatch, with the
    convergence predicate evaluated ON DEVICE — the host fetches the
    per-step deviances + step count once per megastep instead of blocking
    on (dev, delta) every iteration (the FireCaffe lesson: no host
    round-trip between steps). Semantics are step-for-step identical to the
    per-iteration driver: once the predicate fires (or ``max_it`` global
    iterations are reached) the carry freezes, so iteration counts,
    deviance history, and coefficients match the old loop exactly.

    Returns ``(beta, devs[k], ran[k], done)``: ``ran`` marks which steps
    executed (``devs`` is NaN on unexecuted slots), ``done`` = converged.
    A ``lax.while_loop`` (not a frozen scan) so convergence mid-megastep
    stops COMPUTING, not just updating — the per-iteration cost must drop
    even on CPU, where the Gram dominates and wasted post-convergence
    steps would eat the round-trip savings.
    """
    def cond(state):
        _, _, it, i, done, _, _ = state
        return (~done) & (i < k) & (it < max_it)

    def body(state):
        beta, dev_prev, it, i, done, devs, ran = state
        beta_new, dev, delta = _irls_step(family, tweedie_p, X, y, w, beta,
                                          l2, non_negative=non_negative,
                                          off=off)
        if has_bounds:
            # projected Newton, as in the host driver: clip into the box,
            # re-measure the step against the projected point
            beta_new = jnp.clip(beta_new, lo, hi)
            delta = jnp.max(jnp.abs(beta_new - beta))
        stop = delta < beta_eps
        if family == "gaussian" and not non_negative:
            # weighted LS solves exactly in one step; the second confirms
            stop = stop | (it >= 1)
        stop = stop | (jnp.isfinite(dev_prev)
                       & (jnp.abs(dev_prev - dev)
                          <= obj_eps * jnp.maximum(jnp.abs(dev_prev), 1.0)))
        return (beta_new, dev, it + 1, i + 1, stop,
                devs.at[i].set(dev), ran.at[i].set(True))

    state = (beta, jnp.asarray(dev_prev0, jnp.float32),
             jnp.asarray(it0, jnp.int32), jnp.asarray(0, jnp.int32),
             jnp.asarray(False), jnp.full(k, jnp.nan, jnp.float32),
             jnp.zeros(k, bool))
    beta, _, _, _, done, devs, ran = jax.lax.while_loop(cond, body, state)
    return beta, devs, ran, done


@partial(jax.jit, static_argnames=("family", "tweedie_p"))
def _l1_threshold(family: str, tweedie_p: float, X, y, w, beta, lam1, lam2,
                  off=0.0):
    """Per-coefficient proximal threshold lam1*nobs/(gram_jj + lam2*nobs)."""
    fam = _fam(family, tweedie_p)
    eta = X @ beta[:-1] + beta[-1] + off
    d = fam.dmu_deta(eta)
    W = w * d * d / jnp.maximum(fam.variance(fam.linkinv(eta)), 1e-12)
    nobs = jnp.maximum(w.sum(), 1.0)
    gram_diag = (W[:, None] * X * X).sum(axis=0) + lam2 * nobs
    return lam1 * nobs / jnp.maximum(gram_diag, 1e-12)


def _wald_inference(family: str, tw: float, X, yy, w, beta, dev: float,
                    off=0.0):
    """Wald standard errors / z / p per coefficient (reference: GLM.java
    ``computePValues`` — inverse information matrix at the MLE; dispersion
    estimated for gaussian/gamma/tweedie, fixed 1 for binomial/poisson)."""
    fam = _fam(family, tw)
    eta = X @ beta[:-1] + beta[-1] + off
    d = fam.dmu_deta(eta)
    var = fam.variance(fam.linkinv(eta))
    W = w * d * d / jnp.maximum(var, 1e-12)
    nobs = jnp.maximum(w.sum(), 1.0)
    gram, _ = _weighted_gram(X, W, jnp.zeros_like(yy), 0.0, nobs, 1e-8)
    inv = jnp.linalg.inv(gram)
    n_eff = float(jax.device_get((w > 0).sum()))
    pdim = X.shape[1] + 1
    phi = (dev / max(n_eff - pdim, 1.0)
           if family in ("gaussian", "gamma", "tweedie") else 1.0)
    cov = np.asarray(jax.device_get(inv), np.float64) * phi
    se = np.sqrt(np.clip(np.diag(cov), 0.0, None))
    z = np.asarray(jax.device_get(beta), np.float64) / np.maximum(se, 1e-30)
    p = erfc(np.abs(z) / np.sqrt(2.0))
    return se, z, p, cov


@partial(jax.jit, static_argnames=("family", "tweedie_p"))
def _deviance_at(family: str, tweedie_p: float, X, y, w, beta, off=0.0):
    fam = _fam(family, tweedie_p)
    mu = fam.linkinv(X @ beta[:-1] + beta[-1] + off)
    return (w * fam.deviance(y, mu)).sum()


@partial(jax.jit, static_argnames=("family", "tweedie_p"))
def _null_deviance(family: str, tweedie_p: float, y, w):
    fam = _fam(family, tweedie_p)
    mu0 = jnp.full_like(y, (w * y).sum() / jnp.maximum(w.sum(), 1e-30))
    return (w * fam.deviance(y, mu0)).sum()


@partial(jax.jit, static_argnames=("family", "nclasses", "tweedie_p"))
def _glm_score(family: str, nclasses: int, tweedie_p: float, X, beta,
               off=0.0):
    if family == "multinomial":
        return jax.nn.softmax(X @ beta[:-1, :] + beta[-1, :][None, :], axis=1)
    fam = _fam(family, tweedie_p)
    mu = fam.linkinv(X @ beta[:-1] + beta[-1] + off)
    if nclasses == 2:
        return jnp.stack([1.0 - mu, mu], axis=1)
    return mu


@partial(jax.jit, static_argnames=("nclasses", "non_negative"))
def _multinomial_step(nclasses: int, X, yoh, w, B, l2, l1, non_negative: bool = False):
    """One sweep of per-class quadratic (IRLS) updates for softmax regression.

    Reference: GLM.java multinomial solves class-blocks cyclically with the
    binomial-style working response per class (``GLMTask.GLMMultinomial*``).
    B: [P+1, K] (last row = intercepts). The class loop unrolls in the jit.
    L1 is applied as a per-class proximal soft-threshold with the same
    lam1*nobs/gram_jj units as the binomial ``_admm_l1`` path.
    """
    k_feat = X.shape[1]
    nobs = jnp.maximum(w.sum(), 1.0)
    for c in range(nclasses):
        eta = X @ B[:-1, :] + B[-1, :][None, :]
        p = jax.nn.softmax(eta, axis=1)
        pc = p[:, c]
        W = w * jnp.maximum(pc * (1 - pc), 1e-10)
        z = eta[:, c] + (yoh[:, c] - pc) / jnp.maximum(pc * (1 - pc), 1e-10)
        gram, rhs = _weighted_gram(X, W, z, l2, nobs, 1e-5)
        if non_negative:
            bc = _nn_solve(gram, rhs, jnp.maximum(B[:, c], 0.0).at[-1].set(B[-1, c]))
        else:
            chol = jax.scipy.linalg.cho_factor(gram, lower=True)
            bc = jax.scipy.linalg.cho_solve(chol, rhs)
        thr = l1 * nobs / jnp.maximum(jnp.diag(gram)[:k_feat], 1e-12)
        bc = bc.at[:-1].set(jnp.sign(bc[:-1]) * jnp.maximum(jnp.abs(bc[:-1]) - thr, 0.0))
        B = B.at[:, c].set(bc)
    eta = X @ B[:-1, :] + B[-1, :][None, :]
    logp = jax.nn.log_softmax(eta, axis=1)
    dev = -2.0 * (w * (yoh * logp).sum(axis=1)).sum()
    return B, dev


@accounted_jit("glm:multinomial_megastep", loop="glm_multinomial",
               static_argnames=("nclasses", "non_negative", "k"))
def _multinomial_megastep(nclasses: int, X, yoh, w, B, l2, l1, k: int,
                          it0, max_it, obj_eps, dev_prev0,
                          non_negative: bool = False):
    """Up to ``k`` cyclic per-class IRLS sweeps in ONE compiled dispatch;
    the deviance-plateau stopping test runs on device and the host fetches
    the per-step deviances once per megastep (same stop-computing-on-
    converge ``while_loop`` contract as :func:`_irls_megastep`)."""
    def cond(state):
        _, _, it, i, done, _, _ = state
        return (~done) & (i < k) & (it < max_it)

    def body(state):
        B, dev_prev, it, i, done, devs, ran = state
        B_new, dev = _multinomial_step(nclasses, X, yoh, w, B, l2, l1,
                                       non_negative)
        stop = (jnp.isfinite(dev_prev)
                & (jnp.abs(dev_prev - dev)
                   <= obj_eps * jnp.maximum(jnp.abs(dev_prev), 1.0)))
        return (B_new, dev, it + 1, i + 1, stop,
                devs.at[i].set(dev), ran.at[i].set(True))

    state = (B, jnp.asarray(dev_prev0, jnp.float32),
             jnp.asarray(it0, jnp.int32), jnp.asarray(0, jnp.int32),
             jnp.asarray(False), jnp.full(k, jnp.nan, jnp.float32),
             jnp.zeros(k, bool))
    B, _, _, _, done, devs, ran = jax.lax.while_loop(cond, body, state)
    return B, devs, ran, done


class GLMModel(Model):
    algo = "glm"

    def _score_raw(self, frame) -> jax.Array:
        if self.output.get("sparse"):
            from h2o3_tpu.frame.sparse import SparseFrame
            if not isinstance(frame, SparseFrame):
                raise ValueError("this GLM was trained on a SparseFrame; "
                                 "score SparseFrame inputs")
            beta = self.output["beta"]
            eta = frame.X.matvec(beta[:-1]) + beta[-1]
            fam = self.params["family"]
            if fam == "binomial":
                mu = jax.nn.sigmoid(eta)
                return jnp.stack([1.0 - mu, mu], axis=1)
            if fam == "poisson":
                return jnp.exp(jnp.clip(eta, -30, 30))
            return eta
        if self.params["family"] == "ordinal":
            X = self.data_info.expand(frame)
            eta = X @ self.output["beta"]
            theta = self.output["ordinal_theta"]
            cum = jax.nn.sigmoid(theta[None, :] - eta[:, None])
            cdf = jnp.concatenate(
                [jnp.zeros((X.shape[0], 1)), cum,
                 jnp.ones((X.shape[0], 1))], axis=1)
            return jnp.diff(cdf, axis=1)        # [n, J] class probabilities
        oc = self.params.get("offset_column")
        off = 0.0
        if oc:
            if oc not in frame:
                raise ValueError(f"scoring frame lacks offset column {oc!r}")
            import jax.numpy as _jnp
            off = _jnp.nan_to_num(frame.vec(oc).as_float(), nan=0.0)
        if self.params.get("interactions"):
            from h2o3_tpu.models.data_info import expand_interactions
            frame = expand_interactions(
                frame, self.params["interactions"],
                self.output.get("interaction_domains"))
        X = self.data_info.expand(frame)
        return _glm_score(self.params["family"], self.nclasses or 0,
                          float(self.params.get("theta", 1.0))
                          if self.params["family"] == "negativebinomial"
                          else float(self.params["tweedie_variance_power"]),
                          X, self.output["beta"], off)

    def coef(self):
        """Coefficients on the original scale (reference: GLMModel.coefficients()).
        Multinomial models return a per-class nested dict keyed
        ``coefs_class_K`` (the h2o-py multinomial ``coef()`` shape)."""
        return self._coef_dict(np.asarray(self.output["coef"]))

    def coef_norm(self):
        """Standardized coefficients (same multinomial nesting as ``coef``)."""
        return self._coef_dict(np.asarray(jax.device_get(self.output["beta"])))

    def _coef_dict(self, mat: np.ndarray):
        names = self.output["coef_names"] + ["Intercept"]
        if mat.ndim == 1:
            return dict(zip(names, mat))
        return {f"coefs_class_{k}": dict(zip(names, mat[:, k]))
            for k in range(mat.shape[1])}

    def coef_table(self):
        """Rows (name, coefficient, std_error, z_value, p_value) — the
        reference's coefficients table with Wald inference (needs
        ``compute_p_values=True``)."""
        if "p_values" not in self.output:
            raise ValueError("train with compute_p_values=True")
        names = self.output["coef_names"] + ["Intercept"]
        return [dict(name=n, coefficient=float(c), std_error=float(s),
                     z_value=float(z), p_value=float(p))
                for n, c, s, z, p in zip(
                    names, np.asarray(self.output["coef"]),
                    self.output["std_errs"], self.output["z_values"],
                    self.output["p_values"])]

    def get_regularization_path(self):
        """Lambda-search path (h2o-py ``getGLMRegularizationPath``): dicts of
        (lambda_, deviance, dev_explained, nonzero, beta)."""
        path = self.output.get("regularization_path")
        if path is None:
            raise ValueError("train with lambda_search=True")
        return path

    def varimp(self, use_pandas: bool = False):
        """Standardized-coefficient magnitudes per SOURCE column (reference:
        GLM variable importances = abs standardized coefs; one-hot levels of a
        categorical aggregate to the parent column)."""
        beta = np.abs(np.asarray(jax.device_get(self.output["beta"])))
        if beta.ndim == 2:                       # multinomial: sum over classes
            beta = beta.sum(axis=1)
        names = self.output["coef_names"]        # excludes Intercept (last)
        di = self.data_info
        rel: dict[str, float] = {c: 0.0 for c in di.cat_cols + di.num_cols}
        for name, b in zip(names, beta[:len(names)]):
            src = name.split(".", 1)[0] if name.split(".", 1)[0] in rel else name
            rel[src] = rel.get(src, 0.0) + float(b)
        mx = max(rel.values()) if rel and max(rel.values()) > 0 else 1.0
        tot = sum(rel.values()) or 1.0
        rows = sorted(((c, v, v / mx, v / tot) for c, v in rel.items()),
                      key=lambda r: -r[1])
        if use_pandas:
            import pandas as pd
            return pd.DataFrame(rows, columns=["variable", "relative_importance",
                                               "scaled_importance", "percentage"])
        return rows


class GLM(ModelBuilder):
    """h2o-py surface: ``H2OGeneralizedLinearEstimator``."""

    algo = "glm"

    def train(self, x=None, y=None, training_frame=None,
              validation_frame=None, weights=None):
        from h2o3_tpu.frame.sparse import SparseFrame
        if isinstance(training_frame, SparseFrame):
            # wide-sparse path: matrix-free IRLS-CG, no dense design
            from h2o3_tpu.models.glm_sparse import fit_sparse_glm
            from h2o3_tpu.utils.registry import DKV
            if x is not None:
                raise ValueError("column selection (x) is not supported on "
                                 "SparseFrame inputs — slice the COO instead")
            self.job = Job(f"glm-sparse on {training_frame.key or 'frame'}")

            def driver(j):
                model = fit_sparse_glm(self, j, training_frame,
                                       y or "C0", weights)
                if validation_frame is not None:
                    model.validation_metrics = model.model_performance(
                        validation_frame)
                DKV.put(model.key, model)
                return model

            self.job.run(driver)
            if self.job.status == Job.FAILED:
                raise self.job.exception
            self.model = self.job.result
            return self.model
        return super().train(x=x, y=y, training_frame=training_frame,
                             validation_frame=validation_frame,
                             weights=weights)

    def _scoring_history(self, model):
        """Per-IRLS-iteration rows (reference: ``GLM.java``
        ``ScoringHistory`` — iterations / negative_log_likelihood /
        objective; h2o-py's ``model.negative_log_likelihood()`` reads these
        column names)."""
        devs = getattr(self, "_iter_devs", None)
        if not devs:
            return None
        nobs = float(model.training_metrics.nobs) if getattr(
            model.training_metrics, "nobs", 0) else 1.0
        return self._history_table(
            model,
            [("iterations", "long", "%d"),
             ("negative_log_likelihood", "double", "%.5f"),
             ("objective", "double", "%.5f")],
            [[i + 1, d / 2.0, d / (2.0 * nobs)]
             for i, d in enumerate(devs)])

    @classmethod
    def defaults(cls) -> dict:
        return dict(
            super().defaults(),
            family="gaussian",        # AUTO resolved in _validate
            solver="IRLSM",
            alpha=0.0,                # elastic-net mix (L1 fraction)
            lambda_=0.0,              # regularization strength
            tweedie_variance_power=1.5,
            theta=1.0,                # negativebinomial dispersion
            standardize=True,
            use_all_factor_levels=False,
            intercept=True,
            non_negative=False,
            max_iterations=50,
            beta_epsilon=1e-4,
            objective_epsilon=1e-6,
            compute_p_values=False,
            lambda_search=False,
            nlambdas=30,
            lambda_min_ratio=1e-4,
            beta_constraints=None,    # {name: (lower, upper)} or h2o-frame
            #                           style [{"names","lower_bounds",...}]
            offset_column=None,       # per-row margin offset
            interactions=None,        # columns to cross (DataInfo interactions)
            # MeanImputation (default) | Skip | PlugValues (reference
            # GLMParameters.MissingValuesHandling)
            missing_values_handling="MeanImputation",
            # with PlugValues: {numeric_col: value} or a 1-row-frame DKV
            # key (reference _plug_values); categorical plugs not yet
            plug_values=None,
        )

    def _fit_ordinal(self, job: Job, frame, x, y, weights, yvec) -> "GLMModel":
        """Proportional-odds cumulative-logit fit (reference: GLM.java
        ordinal family, ``GLMModel.GLMParameters.Family.ordinal`` — the
        reference solves it by gradient descent too).

        P(y <= j) = sigmoid(theta_j - x·beta) with ordered thresholds
        theta_1 < ... < theta_{J-1} (parameterized theta_j = a + Σ
        softplus(d_i) so ordering is free); full-batch Adam inside one
        ``lax.scan``."""
        params = self.params
        if params.get("interactions") or params.get("offset_column"):
            raise ValueError("interactions/offset_column are not supported "
                             "for the ordinal family")
        di = self._make_data_info(frame, x)
        X = di.expand(frame)
        codes = yvec.data.astype(jnp.int32)
        valid = codes >= 0
        w = weights * valid
        yc = jnp.where(valid, codes, 0)
        J = yvec.cardinality()
        K = X.shape[1]
        lam = float(params["lambda_"])

        def unpack(p):
            beta, a, d = p[:K], p[K], p[K + 1:]
            theta = a + jnp.concatenate(
                [jnp.zeros(1), jnp.cumsum(jax.nn.softplus(d))])
            return beta, theta

        def nll(p):
            beta, theta = unpack(p)
            eta = X @ beta
            cum = jax.nn.sigmoid(theta[None, :] - eta[:, None])   # [n, J-1]
            cdf = jnp.concatenate(
                [jnp.zeros((X.shape[0], 1)), cum,
                 jnp.ones((X.shape[0], 1))], axis=1)
            pj = jnp.take_along_axis(cdf, yc[:, None] + 1, 1)[:, 0] \
                - jnp.take_along_axis(cdf, yc[:, None], 1)[:, 0]
            nobs = jnp.maximum(w.sum(), 1.0)
            return (-(w * jnp.log(jnp.maximum(pj, 1e-12))).sum()
                    + lam * nobs * (beta * beta).sum()) / nobs

        p0 = jnp.zeros(K + J - 1, jnp.float32)
        iters = max(int(params["max_iterations"]), 1) * 20
        lr = 0.5

        @jax.jit
        def run(p0):
            grad = jax.grad(nll)

            def body(carry, _):
                p, m, v, t = carry
                g = grad(p)
                m = 0.9 * m + 0.1 * g
                v = 0.999 * v + 0.001 * g * g
                t = t + 1
                mh = m / (1 - 0.9 ** t)
                vh = v / (1 - 0.999 ** t)
                p = p - lr * mh / (jnp.sqrt(vh) + 1e-8)
                return (p, m, v, t), None

            (p, _, _, _), _ = jax.lax.scan(
                body, (p0, jnp.zeros_like(p0), jnp.zeros_like(p0), 0.0),
                None, length=iters)
            return p, nll(p)

        p, final = run(p0)
        job.update(0.9, f"ordinal nll {float(jax.device_get(final)):.5f}")
        beta, theta = unpack(p)
        # destandardize like the main path: coef_orig = beta_std * mul;
        # centering shifts the thresholds (theta absorbs x·sub terms)
        b = np.asarray(jax.device_get(beta), np.float64)
        coef = b.copy()
        th = np.asarray(jax.device_get(theta), np.float64)
        if params["standardize"] and di.num_cols:
            s0, nnum = di.ncats_expanded, len(di.num_cols)
            mul = di.num_mul.astype(np.float64)
            sub = di.num_sub.astype(np.float64)
            coef[s0:s0 + nnum] = b[s0:s0 + nnum] * mul
            th = th + float((b[s0:s0 + nnum] * mul * sub).sum())

        from h2o3_tpu.models.model_base import ModelParameters
        mparams = ModelParameters(params)
        mparams["family"] = "ordinal"
        model = GLMModel(
            key=make_model_key(self.algo, self.model_id),
            params=mparams, data_info=di, response_column=y,
            response_domain=yvec.domain,
            output=dict(beta=beta, coef=coef,
                        coef_names=di.coef_names,
                        ordinal_theta=theta, ordinal_theta_orig=th,
                        residual_deviance=2.0 * float(jax.device_get(final)),
                        iterations=iters, family="ordinal",
                        lambda_best=lam, regularization_path=None),
        )
        return model

    def _build_beta_bounds(self, di, params, family: str):
        """[lo, hi] per coefficient (+intercept) from ``beta_constraints``
        (reference: GLM BetaConstraints frame — names/lower_bounds/
        upper_bounds). Bounds are given on the ORIGINAL coefficient scale;
        with standardization they transform to the fitted scale
        (beta_std = beta_orig / num_mul)."""
        bc = params.get("beta_constraints")
        if not bc:
            return None
        if family == "multinomial":
            raise ValueError("beta_constraints are not supported for "
                             "multinomial (reference: GLM.java)")
        names = list(di.coef_names)
        items: dict[str, tuple] = {}
        if isinstance(bc, dict):
            for k, v in bc.items():
                items[k] = (v[0], v[1]) if isinstance(v, (tuple, list)) else (v, None)
        else:
            for row in bc:
                items[row["names"]] = (row.get("lower_bounds"),
                                       row.get("upper_bounds"))
        unknown = set(items) - set(names) - {"Intercept"}
        if unknown:
            raise ValueError(f"beta_constraints name unknown coefficients: "
                             f"{sorted(unknown)}")
        K = len(names)
        lo = np.full(K + 1, -np.inf, np.float64)
        hi = np.full(K + 1, np.inf, np.float64)
        for i, n in enumerate(names + ["Intercept"]):
            if n in items:
                l, u = items[n]
                lo[i] = -np.inf if l is None else float(l)
                hi[i] = np.inf if u is None else float(u)
        if params["standardize"] and di.num_cols:
            if "Intercept" in items and np.any(di.num_sub != 0):
                # original intercept = b_int - Σ b_j·mul_j·sub_j: a box on it
                # is not a box on the standardized intercept
                raise ValueError(
                    "an Intercept beta_constraint cannot be honored with "
                    "standardize=True over centered numeric columns; set "
                    "standardize=False")
            s0, nnum = di.ncats_expanded, len(di.num_cols)
            mul = di.num_mul.astype(np.float64)       # 1/sd, > 0
            lo[s0:s0 + nnum] = lo[s0:s0 + nnum] / mul
            hi[s0:s0 + nnum] = hi[s0:s0 + nnum] / mul
        return (jnp.asarray(lo, jnp.float32), jnp.asarray(hi, jnp.float32))

    def _irls_fit(self, job: Job, family, tw, X, yy, w, beta, lambda_: float,
                  params) -> tuple[jax.Array, float, int]:
        """IRLS to convergence at ONE lambda (reference: GLM.java IRLSM
        iteration loop); elastic-net L1 handled by the ADMM pass.

        The loop runs in K-step MEGASTEPS (``H2O3TPU_MEGASTEP_K``): one
        compiled dispatch carries up to K iterations with the convergence
        test on device, and the host blocks exactly ONCE per megastep to
        fetch the per-step deviances + how many steps actually ran — the
        fetch reconciles exact iteration counts for scoring history."""
        lam = lambda_ * (1.0 - float(params["alpha"]))
        k = megastep_k()
        nn = bool(params.get("non_negative"))
        bounds = getattr(self, "_beta_bounds", None)
        off = getattr(self, "_offset", 0.0)
        lo, hi = bounds if bounds is not None else (None, None)
        max_it = int(params["max_iterations"])
        beta_eps = float(params["beta_epsilon"])
        obj_eps = float(params["objective_epsilon"])
        dev_prev, dev, it_total, done = np.inf, np.inf, 0, False
        megasteps = 0
        while it_total < max_it and not done:
            t0 = time.time_ns()

            def _megastep(beta=beta, it_total=it_total, dev_prev=dev_prev):
                b, devs_d, ran_d, done_d = _irls_megastep(
                    family, tw, X, yy, w, beta, lam, k, it_total, max_it,
                    beta_eps, obj_eps, dev_prev, non_negative=nn, off=off,
                    lo=lo, hi=hi, has_bounds=bounds is not None)
                # the ONE blocking transfer per megastep — per-step deviances,
                # executed-step mask, converged flag together; this fetch IS
                # the convergence test
                devs, ran, done = map(  # graftlint: ok(one batched fetch per megastep)
                    np.asarray, jax.device_get((devs_d, ran_d, done_d)))
                return b, devs, ran, done

            with timed_event("iteration", "glm_irls"):
                # transient dispatch failures retry with backoff (the
                # megastep is functional over beta — a re-run is exact)
                beta, devs, ran, done = retrying("glm_megastep", _megastep)
            megasteps += 1
            n = int(ran.sum())
            steps = [float(d) for d in devs[:n]]
            dev = steps[-1] if steps else dev
            dev_prev = dev
            done = bool(done)
            it_total += n
            if hasattr(self, "_iter_devs"):
                self._iter_devs.extend(steps)
            # per-ITERATION latency: the megastep's wall time amortized over
            # the steps it carried (histogram count keeps matching iterations)
            dt = (time.time_ns() - t0) / 1e9
            for _ in range(max(n, 1)):
                _tm.ITER_SECONDS.labels(loop="glm_irls").observe(
                    dt / max(n, 1))
            job.update(it_total / max_it,
                       f"iter {it_total - 1} deviance {dev:.4f}")
        it = max(it_total - 1, 0)
        publish_dispatch_audit(self, "glm_irls", iterations=max(it_total, 1),
                               host_syncs=megasteps,
                               device_dispatches=megasteps)
        if float(params["alpha"]) > 0 and lambda_ > 0:
            local = ModelParameters(params)
            local["lambda_"] = lambda_
            beta = self._admm_l1(family, tw, X, yy, w, beta, local)
            if bounds is not None:
                beta = jnp.clip(beta, bounds[0], bounds[1])
            dev = float(jax.device_get(_deviance_at(family, tw, X, yy, w,
                                                    beta, off)))
        return beta, dev, it

    def _lambda_search(self, job: Job, family, tw, X, yy, w, beta, params):
        """Regularization path with warm starts (reference: GLM.java lambda
        search / glmnet: geometric grid from lambda_max down; stop when the
        deviance-explained gain plateaus; ``getGLMRegularizationPath``)."""
        alpha = max(float(params["alpha"]), 1e-3)   # glmnet λmax convention
        mu_bar = (w * yy).sum() / jnp.maximum(w.sum(), 1e-30)
        lam_max = float(jax.device_get(
            jnp.max(jnp.abs(X.T @ (w * (yy - mu_bar))))
            / jnp.maximum(w.sum(), 1e-30))) / alpha
        lam_max = max(lam_max, 1e-6)
        nlam = int(params["nlambdas"])
        ratio = float(params["lambda_min_ratio"])
        lambdas = lam_max * np.power(ratio, np.linspace(0, 1, nlam))
        null_dev = float(jax.device_get(_null_deviance(family, tw, yy, w)))
        path = []
        dev_prev, flat_steps = null_dev, 0
        for i, lam in enumerate(lambdas):
            beta, dev, it = self._irls_fit(job, family, tw, X, yy, w, beta,
                                           float(lam), params)
            # one batched fetch per lambda: nonzero count + coefficients
            nz, beta_h = jax.device_get(  # graftlint: ok(batched path fetch)
                ((jnp.abs(beta[:-1]) > 1e-8).sum(), beta))
            path.append(dict(lambda_=float(lam), deviance=dev,
                             dev_explained=1.0 - dev / max(null_dev, 1e-30),
                             nonzero=int(nz),
                             beta=np.asarray(beta_h)))
            # stop once extra shrinkage relief stops paying — but only after
            # SUSTAINED flatness: near lambda_max every step is flat because
            # beta is still ~0 (reference stops on devExplained plateau)
            if (dev_prev - dev) < 1e-4 * max(null_dev, 1e-30):
                flat_steps += 1
                if flat_steps >= 3 and path[i]["dev_explained"] > 0:
                    break
            else:
                flat_steps = 0
            dev_prev = dev
        best = min(path, key=lambda e: e["deviance"])
        beta = jnp.asarray(best["beta"])
        return beta, best["deviance"], 0, best["lambda_"], path

    def _make_data_info(self, frame: Frame, x) -> DataInfo:
        """DataInfo with the configured missing-value mode baked into the
        imputation vector: PlugValues overrides the per-column means the
        expander substitutes for NaN — at training AND scoring (reference
        GLM.java imputes with _plug_values wherever MeanImputation would
        use means)."""
        params = self.params
        di = DataInfo.make(frame, x, standardize=params["standardize"],
                           use_all_factor_levels=params["use_all_factor_levels"])
        if self._mvh_mode() != "plugvalues":
            if params.get("plug_values") is not None:
                # reference GLM.java errors on this mismatch — silent
                # mean-imputation would not be what the user configured
                raise ValueError("plug_values requires "
                                 "missing_values_handling='PlugValues'")
            return di
        plugs = params.get("plug_values")
        if isinstance(plugs, str):
            from h2o3_tpu.utils.registry import DKV
            pf = DKV[plugs]
            if pf.nrows != 1:
                raise ValueError(f"plug_values frame {plugs!r} must have "
                                 f"exactly 1 row, got {pf.nrows}")
            plugs = {c: pf.vec(c).to_numpy()[0] for c in pf.names}
        if not isinstance(plugs, dict) or not plugs:
            raise ValueError("missing_values_handling='PlugValues' needs "
                             "plug_values ({column: value} or a 1-row "
                             "frame key)")
        bad = [c for c in plugs if c in di.cat_cols]
        if bad:
            raise ValueError(f"categorical plug values not supported yet: "
                             f"{bad}")
        unknown = [c for c in plugs if c not in di.num_cols]
        if unknown:
            raise ValueError(f"plug_values name unknown numeric columns: "
                             f"{unknown}")
        def _coerce(v) -> float:
            # None / strings / non-numerics all fail the SAME way: as a
            # non-finite plug, caught below with a curated message
            try:
                return float(v)
            except (TypeError, ValueError):
                return float("nan")
        plugs = {c: _coerce(v) for c, v in plugs.items()}
        bad_vals = [c for c, v in plugs.items() if not np.isfinite(v)]
        if bad_vals:
            raise ValueError(f"plug_values must be finite numbers; got "
                             f"non-finite for {bad_vals}")
        means = np.array(di.num_means, np.float32).copy()
        for c, v in plugs.items():
            means[di.num_cols.index(c)] = float(v)
        di.num_means = means
        return di

    def _mvh_mode(self) -> str:
        """Canonical missing_values_handling (h2o-py sends lowercase enum
        forms like mean_imputation) — the ONE normalization site."""
        return str(self.params.get("missing_values_handling")
                   or "MeanImputation").replace("_", "").lower()

    def _fit(self, job: Job, frame: Frame, x, y, weights) -> GLMModel:
        params = self.params
        self._iter_devs = []    # per-IRLS-iteration deviances → scoring_history
        mvh = self._mvh_mode()
        self._metrics_weights = None
        if mvh == "skip":
            # rows with any NA among the used predictors drop out of the
            # fit (weight 0) — reference MissingValuesHandling.Skip; the
            # default path mean-imputes inside DataInfo.expand
            from h2o3_tpu.frame.types import VecType
            na = jnp.zeros(frame.plen, bool)
            for c in x:
                v = frame.vec(c)
                na = na | ((v.data < 0) if v.type is VecType.CAT
                           else jnp.isnan(v.data))
            had_weight = float(jnp.sum(weights)) > 0.0
            weights = weights * (~na)
            if float(jnp.sum(weights)) == 0.0:
                raise ValueError(
                    "missing_values_handling='Skip' removed every row "
                    "(all rows have at least one NA predictor)"
                    if had_weight else
                    "no rows carry training weight (check weights_column)")
            # metrics + CV must see the same reduced row set (model_base
            # reads this after _fit)
            self._metrics_weights = weights
        elif mvh not in ("meanimputation", "plugvalues"):
            raise ValueError(
                f"missing_values_handling {mvh!r} unsupported "
                "(MeanImputation | Skip | PlugValues)")
        if int(params["max_iterations"]) == -1:
            # reference: -1 means solver-chosen default (GLM.java auto)
            params["max_iterations"] = 50
        elif int(params["max_iterations"]) < 1:
            raise ValueError("max_iterations must be >= 1 (or -1 for auto)")
        yvec = frame.vec(y)
        family = params["family"]
        if yvec.is_categorical:
            if family == "ordinal":
                if yvec.cardinality() < 3:
                    raise ValueError("ordinal family needs >= 3 ordered levels")
                return self._fit_ordinal(job, frame, x, y, weights, yvec)
            # multinomial family is honored even for 2-level responses
            # (reference: GLM.java accepts multinomial on a binary y)
            if family == "multinomial" or yvec.cardinality() != 2:
                if family not in ("AUTO", "gaussian", "multinomial"):
                    raise ValueError(f"family {family!r} requires a binary or "
                                     "numeric response")
                return self._fit_multinomial_glm(job, frame, x, y, weights, yvec)
            family = "binomial" if family in ("gaussian", "AUTO") else family
        else:
            if family == "AUTO":
                family = "gaussian"
            if family in ("binomial", "bernoulli"):
                raise ValueError("binomial family requires a categorical (2-level) response")
            if family == "multinomial":
                raise ValueError("multinomial family requires a categorical response")
        tw = (float(params.get("theta", 1.0)) if family == "negativebinomial"
              else float(params["tweedie_variance_power"]))

        if params.get("interactions"):
            from h2o3_tpu.models.data_info import expand_interactions
            inter = list(params["interactions"])
            bad = set(inter) - set(frame.names)
            if bad:
                raise ValueError(f"interactions name unknown columns: "
                                 f"{sorted(bad)}")
            self._interaction_domains = {
                c: frame.vec(c).domain for c in inter
                if frame.vec(c).is_categorical}
            before = set(frame.names)
            frame = expand_interactions(frame, inter,
                                        self._interaction_domains)
            x = list(x) + [c for c in frame.names if c not in before]

        di = self._make_data_info(frame, x)
        X = di.expand(frame)
        from h2o3_tpu.models.data_info import response_as_float
        yy, valid = response_as_float(yvec)
        w = weights * valid
        yy = jnp.where(w > 0, yy, 0.0)

        fam = _fam(family, tw)
        mu0 = fam.initialize_mu(yy)
        k = X.shape[1]
        beta = jnp.zeros(k + 1, jnp.float32)
        beta = beta.at[-1].set(float(jax.device_get(
            fam.link((w * mu0).sum() / jnp.maximum(w.sum(), 1e-30)))))

        self._beta_bounds = self._build_beta_bounds(di, params, family)
        oc = params.get("offset_column")
        if oc:
            if family == "multinomial":
                raise ValueError("offset_column is not supported for "
                                 "multinomial")
            self._offset = jnp.nan_to_num(frame.vec(oc).as_float(), nan=0.0)
        else:
            self._offset = 0.0

        if bool(params.get("lambda_search")):
            beta, dev, it, lambda_best, reg_path = self._lambda_search(
                job, family, tw, X, yy, w, beta, params)
        else:
            beta, dev, it = self._irls_fit(job, family, tw, X, yy, w, beta,
                                           float(params["lambda_"]), params)
            lambda_best, reg_path = float(params["lambda_"]), None

        # destandardize for reporting: X_std = (x - sub) * mul
        b = np.asarray(jax.device_get(beta), np.float64)
        coef = b.copy()
        if params["standardize"] and di.num_cols:
            nnum = len(di.num_cols)
            mul, sub = di.num_mul.astype(np.float64), di.num_sub.astype(np.float64)
            coef[di.ncats_expanded:-1] = b[di.ncats_expanded:-1] * mul
            coef[-1] = b[-1] - float((b[di.ncats_expanded:di.ncats_expanded + nnum] * mul * sub).sum())

        null_dev = float(jax.device_get(_null_deviance(family, tw, yy, w)))
        from h2o3_tpu.models.model_base import ModelParameters
        mparams = ModelParameters(self.params)   # snapshot: builder stays reusable
        mparams["family"] = family
        output = dict(beta=beta, coef=coef, coef_names=di.coef_names,
                      residual_deviance=dev, null_deviance=null_dev,
                      iterations=it + 1, family=family,
                      lambda_best=lambda_best, regularization_path=reg_path,
                      interaction_domains=getattr(
                          self, "_interaction_domains", None))
        if bool(params.get("compute_p_values")):
            if float(params["lambda_"]) > 0 or bool(params.get("lambda_search")):
                raise ValueError("compute_p_values requires no regularization "
                                 "(reference: GLM.java p-values need lambda=0)")
            se, zv, pv, cov = _wald_inference(family, tw, X, yy, w, beta,
                                              dev, self._offset)
            if params["standardize"] and di.num_cols:
                # SEs must be on the same (de-standardized) scale as `coef`:
                # se_orig[num] = se_std[num] * mul; intercept via the delta
                # method on b_int - sum_j b_j*mul_j*sub_j using the full cov.
                s0, nnum = di.ncats_expanded, len(di.num_cols)
                se = se.copy()
                se[s0:s0 + nnum] *= mul
                a = np.zeros(len(b))
                a[-1] = 1.0
                a[s0:s0 + nnum] = -(mul * sub)
                se[-1] = float(np.sqrt(max(a @ cov @ a, 0.0)))
                zv = coef / np.maximum(se, 1e-30)
                pv = erfc(np.abs(zv) / np.sqrt(2.0))
            output.update(std_errs=se, z_values=zv, p_values=pv)
        model = GLMModel(
            key=make_model_key(self.algo, self.model_id),
            params=mparams,
            data_info=di,
            response_column=y,
            response_domain=yvec.domain if yvec.is_categorical else None,
            output=output,
        )
        return model

    def _fit_multinomial_glm(self, job: Job, frame: Frame, x, y, weights, yvec
                             ) -> GLMModel:
        """Softmax regression via cyclic per-class IRLS blocks (reference:
        GLM.java multinomial path)."""
        params = self.params
        if params.get("interactions") or params.get("offset_column"):
            raise ValueError("interactions/offset_column are not supported "
                             "for multinomial")
        di = self._make_data_info(frame, x)
        X = di.expand(frame)
        from h2o3_tpu.models.data_info import response_as_float
        yy, valid = response_as_float(yvec)
        w = weights * valid
        K = yvec.cardinality()
        yoh = jax.nn.one_hot(jnp.where(w > 0, yy, 0.0).astype(jnp.int32), K)
        yoh = yoh * (w > 0)[:, None]

        P = X.shape[1]
        B = jnp.zeros((P + 1, K), jnp.float32)
        lam = float(params["lambda_"]) * (1.0 - float(params["alpha"]))
        lam1 = float(params["lambda_"]) * float(params["alpha"])
        nn = bool(params.get("non_negative"))
        k = megastep_k()
        max_it = int(params["max_iterations"])
        obj_eps = float(params["objective_epsilon"])
        dev_prev, dev, it_total, done = np.inf, np.inf, 0, False
        megasteps = 0
        while it_total < max_it and not done:
            t0 = time.time_ns()

            def _megastep(B=B, it_total=it_total, dev_prev=dev_prev):
                B2, devs_d, ran_d, done_d = _multinomial_megastep(
                    K, X, yoh, w, B, jnp.float32(lam), jnp.float32(lam1), k,
                    it_total, max_it, obj_eps, dev_prev, non_negative=nn)
                # ONE blocking fetch per K-step megastep — the per-step
                # deviance series IS the stopping test
                devs, ran, done = map(  # graftlint: ok(one batched fetch per megastep)
                    np.asarray, jax.device_get((devs_d, ran_d, done_d)))
                return B2, devs, ran, done

            with timed_event("iteration", "glm_multinomial"):
                B, devs, ran, done = retrying("glm_megastep", _megastep)
            megasteps += 1
            n = int(ran.sum())
            steps = [float(d) for d in devs[:n]]
            dev = steps[-1] if steps else dev
            dev_prev = dev
            done = bool(done)
            it_total += n
            dt = (time.time_ns() - t0) / 1e9
            for _ in range(max(n, 1)):
                _tm.ITER_SECONDS.labels(loop="glm_multinomial").observe(
                    dt / max(n, 1))
            job.update(it_total / max_it,
                       f"iter {it_total - 1} deviance {dev:.4f}")
        it = max(it_total - 1, 0)
        publish_dispatch_audit(self, "glm_multinomial",
                               iterations=max(it_total, 1),
                               host_syncs=megasteps,
                               device_dispatches=megasteps)

        # destandardized per-class coefficients
        b = np.asarray(jax.device_get(B), np.float64)
        coef = b.copy()
        if params["standardize"] and di.num_cols:
            nnum = len(di.num_cols)
            s = di.ncats_expanded
            mul, sub = di.num_mul.astype(np.float64), di.num_sub.astype(np.float64)
            coef[s:s + nnum, :] = b[s:s + nnum, :] * mul[:, None]
            coef[-1, :] = b[-1, :] - (b[s:s + nnum, :] * (mul * sub)[:, None]).sum(axis=0)

        from h2o3_tpu.models.model_base import ModelParameters
        mparams = ModelParameters(self.params)
        mparams["family"] = "multinomial"
        return GLMModel(
            key=make_model_key(self.algo, self.model_id),
            params=mparams, data_info=di, response_column=y,
            response_domain=yvec.domain,
            output=dict(beta=B, coef=coef, coef_names=di.coef_names,
                        residual_deviance=dev, null_deviance=float("nan"),
                        iterations=it + 1, family="multinomial"),
        )

    def _admm_l1(self, family, tw, X, yy, w, beta, params):
        """L1 via proximal IRLS (simplified ADMM, reference hex/optimization/ADMM.java):
        iterate IRLS steps then soft-threshold non-intercept coefficients.

        Units: the IRLS normal equations carry an L2 term scaled by nobs
        (matching the per-observation lambda convention), so the proximal
        threshold for coefficient j is lam1 * nobs / gram_jj — dividing by the
        curvature keeps L1 and L2 in the same per-observation units."""
        lam1 = float(params["lambda_"]) * float(params["alpha"])
        lam2 = float(params["lambda_"]) * (1.0 - float(params["alpha"]))
        nn = bool(params.get("non_negative"))
        off = getattr(self, "_offset", 0.0)
        for _ in range(10):
            beta, _dev, _delta = _irls_step(family, tw, X, yy, w, beta, lam2,
                                            non_negative=nn, off=off)
            thr = _l1_threshold(family, tw, X, yy, w, beta, lam1, lam2, off)
            mag = jnp.abs(beta[:-1])
            beta = beta.at[:-1].set(jnp.sign(beta[:-1]) * jnp.maximum(mag - thr, 0.0))
        return beta
