"""GLM — generalized linear models with IRLSM.

Reference: ``hex/glm/GLM.java:543,880,1335`` — per-IRLS-iteration the cluster
computes the weighted Gram matrix X'WX via ``GLMIterationTask``
(``hex/glm/GLMTask.java:1509``, a chunk-parallel MRTask), the leader solves by
Cholesky (``hex/gram/Gram.java:452-473``), and iterates to convergence
(``beta_epsilon``/``objective_epsilon``). Regularization: elastic net; L2 goes
into the Gram diagonal, L1 via ADMM (``hex/optimization/ADMM.java``).

TPU-native: the Gram contraction is one ``einsum`` over the row-sharded design
matrix — XLA reduces per-chip partials over ICI (exactly the MRTask tree reduce)
and the [K,K] solve happens replicated. The whole IRLS step is a single jitted
program; only the scalar deviance crosses to host for the convergence test.
L1 is handled by ADMM over the cached Cholesky factor, mirroring the reference.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.data_info import DataInfo
from h2o3_tpu.models.distributions import get_family
from h2o3_tpu.models.job import Job
from h2o3_tpu.models.model_base import Model, ModelBuilder, make_model_key


def _fam(family: str, tweedie_p: float):
    return get_family(family, p=tweedie_p) if family == "tweedie" else get_family(family)


@partial(jax.jit, static_argnames=("family", "tweedie_p"))
def _irls_step(family: str, tweedie_p: float, X, y, w, beta, l2):
    """One IRLS iteration: weighted Gram + Cholesky solve (all on device)."""
    fam = _fam(family, tweedie_p)
    eta = X @ beta[:-1] + beta[-1]
    mu = fam.linkinv(eta)
    d = fam.dmu_deta(eta)
    var = fam.variance(mu)
    W = w * d * d / jnp.maximum(var, 1e-12)
    z = eta + (y - mu) / jnp.maximum(d, 1e-12)

    Xw = X * W[:, None]
    k = X.shape[1]
    gram = jnp.empty((k + 1, k + 1), X.dtype)
    gram = gram.at[:k, :k].set(Xw.T @ X)
    xw_sum = Xw.sum(axis=0)
    gram = gram.at[:k, k].set(xw_sum).at[k, :k].set(xw_sum).at[k, k].set(W.sum())
    rhs = jnp.concatenate([Xw.T @ z, (W * z).sum()[None]])

    nobs = jnp.maximum(w.sum(), 1.0)
    penalty = l2 * nobs * jnp.concatenate([jnp.ones(k), jnp.zeros(1)])  # no intercept penalty
    gram = gram + jnp.diag(penalty) + 1e-8 * jnp.eye(k + 1)
    chol = jax.scipy.linalg.cho_factor(gram, lower=True)
    new_beta = jax.scipy.linalg.cho_solve(chol, rhs)
    dev = (w * fam.deviance(y, mu)).sum()
    return new_beta, dev


@partial(jax.jit, static_argnames=("family", "tweedie_p"))
def _l1_threshold(family: str, tweedie_p: float, X, y, w, beta, lam1, lam2):
    """Per-coefficient proximal threshold lam1*nobs/(gram_jj + lam2*nobs)."""
    fam = _fam(family, tweedie_p)
    eta = X @ beta[:-1] + beta[-1]
    d = fam.dmu_deta(eta)
    W = w * d * d / jnp.maximum(fam.variance(fam.linkinv(eta)), 1e-12)
    nobs = jnp.maximum(w.sum(), 1.0)
    gram_diag = (W[:, None] * X * X).sum(axis=0) + lam2 * nobs
    return lam1 * nobs / jnp.maximum(gram_diag, 1e-12)


@partial(jax.jit, static_argnames=("family", "tweedie_p"))
def _null_deviance(family: str, tweedie_p: float, y, w):
    fam = _fam(family, tweedie_p)
    mu0 = jnp.full_like(y, (w * y).sum() / jnp.maximum(w.sum(), 1e-30))
    return (w * fam.deviance(y, mu0)).sum()


@partial(jax.jit, static_argnames=("family", "nclasses", "tweedie_p"))
def _glm_score(family: str, nclasses: int, tweedie_p: float, X, beta):
    fam = _fam(family, tweedie_p)
    mu = fam.linkinv(X @ beta[:-1] + beta[-1])
    if nclasses == 2:
        return jnp.stack([1.0 - mu, mu], axis=1)
    return mu


class GLMModel(Model):
    algo = "glm"

    def _score_raw(self, frame: Frame) -> jax.Array:
        X = self.data_info.expand(frame)
        return _glm_score(self.params["family"], self.nclasses or 0,
                          float(self.params["tweedie_variance_power"]), X, self.output["beta"])

    def coef(self) -> dict[str, float]:
        """Coefficients on the original scale (reference: GLMModel.coefficients())."""
        return dict(zip(self.output["coef_names"] + ["Intercept"], self.output["coef"]))

    def coef_norm(self) -> dict[str, float]:
        """Standardized coefficients."""
        beta = np.asarray(jax.device_get(self.output["beta"]))
        return dict(zip(self.output["coef_names"] + ["Intercept"], beta))


class GLM(ModelBuilder):
    """h2o-py surface: ``H2OGeneralizedLinearEstimator``."""

    algo = "glm"

    @classmethod
    def defaults(cls) -> dict:
        return dict(
            super().defaults(),
            family="gaussian",        # AUTO resolved in _validate
            solver="IRLSM",
            alpha=0.0,                # elastic-net mix (L1 fraction)
            lambda_=0.0,              # regularization strength
            tweedie_variance_power=1.5,
            standardize=True,
            use_all_factor_levels=False,
            intercept=True,
            max_iterations=50,
            beta_epsilon=1e-4,
            objective_epsilon=1e-6,
            compute_p_values=False,
        )

    def _fit(self, job: Job, frame: Frame, x, y, weights) -> GLMModel:
        params = self.params
        if int(params["max_iterations"]) < 1:
            raise ValueError("max_iterations must be >= 1")
        yvec = frame.vec(y)
        family = params["family"]
        if yvec.is_categorical:
            if yvec.cardinality() != 2:
                raise ValueError("multinomial GLM not yet supported; response must be binary")
            family = "binomial" if family in ("gaussian", "AUTO") else family
        else:
            if family == "AUTO":
                family = "gaussian"
            if family in ("binomial", "bernoulli"):
                raise ValueError("binomial family requires a categorical (2-level) response")
        tw = float(params["tweedie_variance_power"])

        di = DataInfo.make(frame, x, standardize=params["standardize"],
                           use_all_factor_levels=params["use_all_factor_levels"])
        X = di.expand(frame)
        from h2o3_tpu.models.data_info import response_as_float
        yy, valid = response_as_float(yvec)
        w = weights * valid
        yy = jnp.where(w > 0, yy, 0.0)

        fam = _fam(family, tw)
        mu0 = fam.initialize_mu(yy)
        k = X.shape[1]
        beta = jnp.zeros(k + 1, jnp.float32)
        beta = beta.at[-1].set(float(jax.device_get(
            fam.link((w * mu0).sum() / jnp.maximum(w.sum(), 1e-30)))))

        lam = float(params["lambda_"]) * (1.0 - float(params["alpha"]))
        dev_prev = np.inf
        for it in range(int(params["max_iterations"])):
            beta_new, dev = _irls_step(family, tw, X, yy, w, beta, lam)
            dev = float(jax.device_get(dev))
            delta = float(jax.device_get(jnp.max(jnp.abs(beta_new - beta))))
            beta = beta_new
            job.update((it + 1) / int(params["max_iterations"]), f"iter {it} deviance {dev:.4f}")
            if family == "gaussian" and it >= 1:
                break
            if delta < float(params["beta_epsilon"]):
                break
            if np.isfinite(dev_prev) and abs(dev_prev - dev) <= \
                    float(params["objective_epsilon"]) * max(abs(dev_prev), 1.0):
                break
            dev_prev = dev

        if float(params["alpha"]) > 0 and float(params["lambda_"]) > 0:
            beta = self._admm_l1(family, tw, X, yy, w, beta, params)

        # destandardize for reporting: X_std = (x - sub) * mul
        b = np.asarray(jax.device_get(beta), np.float64)
        coef = b.copy()
        if params["standardize"] and di.num_cols:
            nnum = len(di.num_cols)
            mul, sub = di.num_mul.astype(np.float64), di.num_sub.astype(np.float64)
            coef[di.ncats_expanded:-1] = b[di.ncats_expanded:-1] * mul
            coef[-1] = b[-1] - float((b[di.ncats_expanded:di.ncats_expanded + nnum] * mul * sub).sum())

        null_dev = float(jax.device_get(_null_deviance(family, tw, yy, w)))
        from h2o3_tpu.models.model_base import ModelParameters
        mparams = ModelParameters(self.params)   # snapshot: builder stays reusable
        mparams["family"] = family
        model = GLMModel(
            key=make_model_key(self.algo, self.model_id),
            params=mparams,
            data_info=di,
            response_column=y,
            response_domain=yvec.domain if yvec.is_categorical else None,
            output=dict(beta=beta, coef=coef, coef_names=di.coef_names,
                        residual_deviance=dev, null_deviance=null_dev,
                        iterations=it + 1, family=family),
        )
        return model

    def _admm_l1(self, family, tw, X, yy, w, beta, params):
        """L1 via proximal IRLS (simplified ADMM, reference hex/optimization/ADMM.java):
        iterate IRLS steps then soft-threshold non-intercept coefficients.

        Units: the IRLS normal equations carry an L2 term scaled by nobs
        (matching the per-observation lambda convention), so the proximal
        threshold for coefficient j is lam1 * nobs / gram_jj — dividing by the
        curvature keeps L1 and L2 in the same per-observation units."""
        lam1 = float(params["lambda_"]) * float(params["alpha"])
        lam2 = float(params["lambda_"]) * (1.0 - float(params["alpha"]))
        for _ in range(10):
            beta, _ = _irls_step(family, tw, X, yy, w, beta, lam2)
            thr = _l1_threshold(family, tw, X, yy, w, beta, lam1, lam2)
            mag = jnp.abs(beta[:-1])
            beta = beta.at[:-1].set(jnp.sign(beta[:-1]) * jnp.maximum(mag - thr, 0.0))
        return beta
