"""HGLM — hierarchical (mixed-effects) GLM with random effects per group.

Reference: ``hex/glm/GLMModel.java:271,379-398`` — ``HGLM=True`` with
``random_columns`` fits y = X·β + Z·u + ε where u ~ N(0, σ²_u I) are
random effects keyed by a grouping column (the reference's
gaussian/gaussian HGLM; its h-likelihood solver interleaves fixed-effect,
random-effect, and dispersion updates).

TPU-native: the gaussian random-intercept/random-slope model has
closed-form EM updates whose per-group sufficient statistics are
``segment_sum`` reductions over the row-sharded frame — the same monoid
contract as every other solver here:

    E-step:  u_g | y  ~  N(m_g, V_g)   per group (tiny per-group solves)
    M-step:  β  ← WLS on (y - Z·E[u]);  σ²_u, σ²_e ← moment updates

Every iteration is a handful of fused device ops; groups stay on device as
integer codes (no per-group python loops).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.data_info import DataInfo
from h2o3_tpu.models.job import Job
from h2o3_tpu.models.model_base import (Model, ModelBuilder, ModelParameters,
                                        make_model_key)


@partial(jax.jit, static_argnames=("n_groups", "q"))
def _em_step(X, Zr, gid, y, w, beta, sig_u, sig_e, n_groups: int, q: int):
    """One EM iteration. Zr [rows, q]: per-row random-effect design (column 0
    is the intercept 1s, further columns are random slopes); gid [rows]."""
    # E-step: per-group posterior of u_g given current (beta, sigmas).
    # V_g = (Z_g'Z_g/sig_e + I/sig_u)^-1 ; m_g = V_g Z_g'(y-Xb)/sig_e
    resid = y - (X @ beta[:-1] + beta[-1])
    wZ = Zr * w[:, None]
    # per-group ZtZ [G, q, q] and Zt r [G, q] via segment sums
    ZtZ = jax.ops.segment_sum(
        (wZ[:, :, None] * Zr[:, None, :]).reshape(-1, q * q),
        gid, num_segments=n_groups).reshape(n_groups, q, q)
    Ztr = jax.ops.segment_sum(wZ * resid[:, None], gid,
                              num_segments=n_groups)
    prec = ZtZ / jnp.maximum(sig_e, 1e-10) \
        + jnp.eye(q)[None] / jnp.maximum(sig_u, 1e-10)
    V = jnp.linalg.inv(prec)
    m = jnp.einsum("gab,gb->ga", V, Ztr) / jnp.maximum(sig_e, 1e-10)

    # M-step for beta: WLS on y - Z·E[u]
    zu = (Zr * m[gid]).sum(axis=1)
    yt = y - zu
    hi = jax.lax.Precision.HIGHEST
    k = X.shape[1]
    Xw = X * w[:, None]
    gram = jnp.empty((k + 1, k + 1), X.dtype)
    gram = gram.at[:k, :k].set(jnp.matmul(Xw.T, X, precision=hi))
    xs = Xw.sum(axis=0)
    gram = gram.at[:k, k].set(xs).at[k, :k].set(xs).at[k, k].set(w.sum())
    gram = gram + 1e-6 * jnp.eye(k + 1)
    rhs = jnp.concatenate([jnp.matmul(Xw.T, yt, precision=hi),
                           (w * yt).sum()[None]])
    beta_new = jnp.linalg.solve(gram, rhs)

    # M-step for variances (EM moment updates)
    nobs = jnp.maximum(w.sum(), 1.0)
    e = y - (X @ beta_new[:-1] + beta_new[-1]) - zu
    # E[e'e] adds the posterior variance of Z u
    trZVZ = (jnp.einsum("gab,gab->g", ZtZ, V)).sum()
    sig_e_new = ((w * e * e).sum() + trZVZ) / nobs
    sig_u_new = (m * m + jnp.einsum("gaa->ga", V)).sum() / (n_groups * q)
    return beta_new, m, V, sig_u_new, sig_e_new


def _z_design(frame: Frame, random_columns) -> jax.Array:
    """[rows, q] random-effect design: intercept 1s + random-slope cols
    (ONE definition shared by fit and score so BLUPs and predictions cannot
    drift)."""
    cols = [jnp.ones(frame.plen, jnp.float32)]
    for c in random_columns:
        cols.append(jnp.nan_to_num(frame.vec(c).as_float(), nan=0.0))
    return jnp.stack(cols, axis=1)


class HGLMModel(Model):
    algo = "hglm"

    def _score_raw(self, frame: Frame) -> jax.Array:
        o = self.output
        X = self.data_info.expand(frame)
        eta = X @ o["beta"][:-1] + o["beta"][-1]
        gcol = self.params["group_column"]
        if gcol in frame:
            v = frame.vec(gcol)
            if not v.is_categorical:
                raise TypeError(f"group column {gcol!r} must be categorical "
                                "at scoring time")
            codes = v.data
            if v.domain != o["group_domain"]:
                from h2o3_tpu.models.data_info import _remap_codes
                codes = _remap_codes(codes, v.domain or (), o["group_domain"])
            known = codes >= 0
            safe = jnp.where(known, codes, 0)
            Zr = self._zrows(frame)
            zu = (Zr * o["u"][safe]).sum(axis=1)
            eta = eta + jnp.where(known, zu, 0.0)   # unseen group → fixed only
        return eta

    def _zrows(self, frame: Frame) -> jax.Array:
        return _z_design(frame, self.params.get("random_columns") or [])

    def ranef(self) -> dict:
        """Per-group random effects (h2o-py HGLM: model.coefs_random)."""
        u = np.asarray(jax.device_get(self.output["u"]))
        names = ["intercept"] + list(self.params.get("random_columns") or [])
        return {lvl: dict(zip(names, u[i]))
                for i, lvl in enumerate(self.output["group_domain"])}


class HGLM(ModelBuilder):
    """h2o-py surface: ``H2OGeneralizedLinearEstimator(HGLM=True,
    random_columns=[...])`` — exposed here as a first-class builder.

    ``group_column``: the grouping factor (random intercept per level);
    ``random_columns``: numeric columns that ALSO get a random slope per
    group. Gaussian family (the reference HGLM default)."""

    algo = "hglm"

    @classmethod
    def defaults(cls) -> dict:
        return dict(
            super().defaults(),
            group_column=None,       # required: categorical grouping factor
            random_columns=None,     # numeric cols with per-group slopes
            max_iterations=50,
            em_epsilon=1e-5,
        )

    def _fit(self, job: Job, frame: Frame, x, y, weights) -> HGLMModel:
        p = self.params
        if int(p["max_iterations"]) == -1:
            p["max_iterations"] = 50    # h2o-py auto sentinel (GLM.java)
        elif int(p["max_iterations"]) < 1:
            raise ValueError("max_iterations must be >= 1 (or -1 for auto)")
        gcol = p.get("group_column")
        if not gcol:
            raise ValueError("group_column is required for HGLM")
        gvec = frame.vec(gcol)
        if not gvec.is_categorical:
            raise ValueError(f"group_column {gcol!r} must be categorical")
        yvec = frame.vec(y)
        if yvec.is_categorical:
            raise ValueError("HGLM here is gaussian-family (numeric response) "
                             "— the reference HGLM default")
        rand_cols = list(p.get("random_columns") or [])
        for c in rand_cols:
            if frame.vec(c).is_categorical:
                raise ValueError(f"random column {c!r} must be numeric")

        x = [c for c in x if c != gcol]
        di = DataInfo.make(frame, x, standardize=False,
                           use_all_factor_levels=False)
        X = di.expand(frame)
        from h2o3_tpu.models.data_info import response_as_float
        yy, valid = response_as_float(yvec)
        gvalid = gvec.data >= 0
        w = weights * valid * gvalid
        yc = jnp.where(w > 0, yy, 0.0)
        gid = jnp.where(gvalid, gvec.data, 0)
        G = gvec.cardinality()
        q = 1 + len(rand_cols)

        Zr = _z_design(frame, rand_cols)

        k = X.shape[1]
        beta = jnp.zeros(k + 1, jnp.float32)
        ybar = float(jax.device_get((w * yc).sum() /
                                    jnp.maximum(w.sum(), 1e-30)))
        beta = beta.at[-1].set(ybar)
        var0 = float(jax.device_get(
            (w * (yc - ybar) ** 2).sum() / jnp.maximum(w.sum(), 1.0)))
        sig_u = jnp.float32(max(var0 / 2, 1e-4))
        sig_e = jnp.float32(max(var0 / 2, 1e-4))

        prev = np.inf
        it = 0
        u = V = None
        for it in range(int(p["max_iterations"])):
            beta, u, V, sig_u, sig_e = _em_step(
                X, Zr, gid, yc, w, beta, sig_u, sig_e, G, q)
            se = float(jax.device_get(sig_e))
            job.update((it + 1) / int(p["max_iterations"]),
                       f"EM iter {it}: sig_u {float(jax.device_get(sig_u)):.4f}"
                       f" sig_e {se:.4f}")
            if np.isfinite(prev) and abs(prev - se) <= \
                    float(p["em_epsilon"]) * max(prev, 1e-12):
                break
            prev = se

        return HGLMModel(
            key=make_model_key(self.algo, self.model_id),
            params=ModelParameters(p), data_info=di, response_column=y,
            response_domain=None,
            output=dict(beta=beta, u=u, u_var=V,
                        sig_u=float(jax.device_get(sig_u)),
                        sig_e=float(jax.device_get(sig_e)),
                        coef=np.asarray(jax.device_get(beta)),
                        coef_names=di.coef_names,
                        group_domain=gvec.domain, iterations=it + 1),
        )
