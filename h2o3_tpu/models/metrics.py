"""ModelMetrics — per-problem-type metrics computed device-side in one pass.

Reference: the ``hex/ModelMetrics*.java`` hierarchy computed chunk-parallel via
``MetricBuilder`` reduces; binomial AUC uses a 400-bin streaming histogram of
scores (``hex/AUC2.java:24-36,347-362``) from which ROC, PR, max-F1/F2/MCC
criteria and the confusion matrix are derived; regression metrics in
``ModelMetricsRegression.java``; multinomial in ``ModelMetricsMultinomial.java``.

Here each builder is one jitted reduction over the sharded prediction/response
columns; the 400-bin AUC histogram is kept (it is exactly the right algorithm
for a data-parallel machine — fixed-shape partials, psum-reducible).
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

NBINS = 400  # reference: AUC2.NBINS=400


# -- containers --------------------------------------------------------------


@dataclasses.dataclass
class MetricsBase:
    nobs: int
    mse: float

    @property
    def rmse(self) -> float:
        return float(np.sqrt(self.mse))


@dataclasses.dataclass
class ModelMetricsRegression(MetricsBase):
    mae: float
    rmsle: float
    mean_residual_deviance: float
    r2: float

    def __repr__(self):
        return (f"ModelMetricsRegression(rmse={self.rmse:.6g}, mse={self.mse:.6g}, "
                f"mae={self.mae:.6g}, deviance={self.mean_residual_deviance:.6g}, r2={self.r2:.4f})")


@dataclasses.dataclass
class ModelMetricsBinomial(MetricsBase):
    auc: float
    pr_auc: float
    logloss: float
    mean_per_class_error: float
    max_f1_threshold: float
    confusion_matrix: np.ndarray  # 2x2 at max-F1 threshold, rows=actual
    ks: float = 0.0               # Kolmogorov-Smirnov (max TPR-FPR)
    gini: float = dataclasses.field(init=False)
    # score histograms retained for gains/lift (not shown in repr)
    _tp_h: np.ndarray | None = dataclasses.field(default=None, repr=False)
    _fp_h: np.ndarray | None = dataclasses.field(default=None, repr=False)
    _s_h: np.ndarray | None = dataclasses.field(default=None, repr=False)

    def gains_lift(self, groups: int = 16):
        """Gains/Lift table rows (reference: ``hex/GainsLift.java`` — the
        TwoDimTable columns ``GainsLift.java:150``). Groups are quantile bins
        of the predicted score, resolved on the 400-bin AUC histogram (the
        reference runs a separate Quantile model; same table up to bin
        granularity)."""
        if self._tp_h is None:
            return []
        tp_h = np.asarray(self._tp_h, np.float64)[::-1]   # descending score
        fp_h = np.asarray(self._fp_h, np.float64)[::-1]
        s_h = np.asarray(self._s_h, np.float64)[::-1]
        n_h = tp_h + fp_h
        N = n_h.sum()
        E = tp_h.sum()
        if N <= 0:
            return []
        P = E / N
        cum_n = np.cumsum(n_h)
        cum_e = np.cumsum(tp_h)
        cum_s = np.cumsum(s_h)
        nb = len(n_h)
        rows = []
        prev_idx = -1
        prev = np.zeros(3)
        for g in range(groups):
            target = N * (g + 1) / groups
            idx = int(np.searchsorted(cum_n, target - 1e-9))
            idx = min(idx, nb - 1)
            if idx <= prev_idx and g < groups - 1:
                continue                      # empty group (coarse histogram)
            idx = nb - 1 if g == groups - 1 else idx
            e_i = cum_e[idx] - prev[0]
            n_i = cum_n[idx] - prev[1]
            s_i = cum_s[idx] - prev[2]
            if n_i <= 0:
                continue
            p_i = e_i / n_i
            lift = p_i / P if P > 0 else np.nan
            cum_lift = cum_e[idx] / cum_n[idx] / P if P > 0 else np.nan
            cum_event = cum_e[idx] / max(E, 1e-30)
            tot_ne = N - E
            cum_non_event = 0.0 if tot_ne == 0 else \
                (cum_n[idx] - cum_e[idx]) / tot_ne
            rows.append(dict(
                group=len(rows) + 1,
                cumulative_data_fraction=cum_n[idx] / N,
                lower_threshold=(nb - 1 - idx) / nb,
                lift=lift,
                cumulative_lift=cum_lift,
                response_rate=p_i,
                score=s_i / n_i,
                cumulative_response_rate=cum_e[idx] / cum_n[idx],
                cumulative_score=cum_s[idx] / cum_n[idx],
                capture_rate=e_i / max(E, 1e-30),
                cumulative_capture_rate=cum_event,
                gain=100 * (lift - 1) if np.isfinite(lift) else np.nan,
                cumulative_gain=100 * (cum_lift - 1) if np.isfinite(cum_lift) else np.nan,
                kolmogorov_smirnov=cum_event - cum_non_event,
            ))
            prev_idx = idx
            prev = np.array([cum_e[idx], cum_n[idx], cum_s[idx]])
        return rows

    def __post_init__(self):
        self.gini = 2.0 * self.auc - 1.0

    #: criteria maximized over thresholds (reference: ``hex/AUC2.java:24-36``
    #: ThresholdCriterion enum; the tns/fns/fps/tps count rows each maximize
    #: the count itself, appended in max_criteria_and_metric_scores)
    MAX_CRITERIA = ("f1", "f2", "f0point5", "accuracy", "precision",
                    "recall", "specificity", "absolute_mcc",
                    "min_per_class_accuracy", "mean_per_class_accuracy")

    def threshold_table(self):
        """Per-threshold criterion values over the 400-bin score histogram
        (reference: ``hex/AUC2.java`` — the ``thresholds_and_metric_scores``
        table h2o-py's ``perf.F1()``/``perf.mcc()`` read). Returns
        (columns, rows) with thresholds descending."""
        if self._tp_h is None:
            return [], []
        tp_h = np.asarray(self._tp_h, np.float64)[::-1]   # descending score
        fp_h = np.asarray(self._fp_h, np.float64)[::-1]
        P, N = tp_h.sum(), fp_h.sum()
        tps = np.cumsum(tp_h)          # predicted-positive counts at ≥ thr
        fps = np.cumsum(fp_h)
        fns, tns = P - tps, N - fps
        nb = len(tp_h)
        thr = (nb - 1 - np.arange(nb)) / nb
        eps = 1e-30
        precision = tps / np.maximum(tps + fps, eps)
        recall = tps / max(P, eps)                      # = tpr
        specificity = tns / max(N, eps)                 # = tnr
        accuracy = (tps + tns) / max(P + N, eps)
        f1 = 2 * precision * recall / np.maximum(precision + recall, eps)
        f2 = 5 * precision * recall / np.maximum(4 * precision + recall, eps)
        f05 = 1.25 * precision * recall / np.maximum(
            0.25 * precision + recall, eps)
        mcc_den = np.sqrt(np.maximum(
            (tps + fps) * (tps + fns) * (tns + fps) * (tns + fns), eps))
        mcc = np.abs((tps * tns - fps * fns) / mcc_den)
        minpca = np.minimum(recall, specificity)
        meanpca = 0.5 * (recall + specificity)
        cols = ["threshold", "f1", "f2", "f0point5", "accuracy", "precision",
                "recall", "specificity", "absolute_mcc",
                "min_per_class_accuracy", "mean_per_class_accuracy",
                "tns", "fns", "fps", "tps", "tnr", "fnr", "fpr", "tpr", "idx"]
        rows = [[float(thr[i]), float(f1[i]), float(f2[i]), float(f05[i]),
                 float(accuracy[i]), float(precision[i]), float(recall[i]),
                 float(specificity[i]), float(mcc[i]), float(minpca[i]),
                 float(meanpca[i]), float(tns[i]), float(fns[i]),
                 float(fps[i]), float(tps[i]), float(specificity[i]),
                 float(fns[i] / max(P, eps)), float(fps[i] / max(N, eps)),
                 float(recall[i]), i]
                for i in range(nb)]
        return cols, rows

    def max_criteria_and_metric_scores(self, table=None):
        """The AUC2 max-criteria table (reference: ``hex/AUC2.java:24-36``;
        h2o-py ``find_threshold_by_max_metric``). Rows:
        (metric, threshold, value, idx). Pass an already-computed
        ``threshold_table()`` result to avoid rebuilding the 400-row sweep."""
        cols, rows = table if table is not None else self.threshold_table()
        if not rows:
            return [], []
        arr = np.asarray([r[:11] for r in rows], np.float64)
        out = []
        for j, name in enumerate(self.MAX_CRITERIA, start=1):
            i = int(np.argmax(arr[:, j]))
            out.append([f"max {name}", float(arr[i, 0]), float(arr[i, j]), i])
        # count criteria report the count at ITS OWN max (reference: tns..tps
        # maximize the count itself)
        for name, col in (("tns", 11), ("fns", 12), ("fps", 13), ("tps", 14)):
            vals = np.asarray([r[col] for r in rows], np.float64)
            i = int(np.argmax(vals))
            out.append([f"max {name}", float(rows[i][0]), float(vals[i]), i])
        return ["metric", "threshold", "value", "idx"], out

    def __repr__(self):
        return (f"ModelMetricsBinomial(auc={self.auc:.5f}, pr_auc={self.pr_auc:.5f}, "
                f"logloss={self.logloss:.5f}, rmse={self.rmse:.5f}, "
                f"mean_per_class_error={self.mean_per_class_error:.5f})")


@dataclasses.dataclass
class ModelMetricsMultinomial(MetricsBase):
    logloss: float
    mean_per_class_error: float
    confusion_matrix: np.ndarray

    @property
    def accuracy(self) -> float:
        cm = self.confusion_matrix
        return float(np.trace(cm) / max(cm.sum(), 1))

    def __repr__(self):
        return (f"ModelMetricsMultinomial(logloss={self.logloss:.5f}, "
                f"mean_per_class_error={self.mean_per_class_error:.5f}, "
                f"accuracy={self.accuracy:.4f})")


# -- regression ---------------------------------------------------------------


@jax.jit
def _regression_pass(pred, y, mask, dev):
    w = mask.astype(jnp.float32)
    n = w.sum()
    err = jnp.where(mask, pred - y, 0.0)
    mse = (err * err).sum() / n
    mae = jnp.abs(err).sum() / n
    both_pos = mask & (pred > -1) & (y > -1)
    le = jnp.where(both_pos, jnp.log1p(jnp.maximum(pred, -1 + 1e-10)) - jnp.log1p(y), 0.0)
    rmsle = jnp.sqrt((le * le).sum() / n)
    ymean = jnp.where(mask, y, 0.0).sum() / n
    ss_tot = jnp.where(mask, (y - ymean) ** 2, 0.0).sum()
    r2 = 1.0 - (err * err).sum() / jnp.maximum(ss_tot, 1e-30)
    mrd = jnp.where(mask, dev, 0.0).sum() / n
    return dict(n=n, mse=mse, mae=mae, rmsle=rmsle, r2=r2, mrd=mrd)


def regression_metrics(pred: jax.Array, y: jax.Array, mask: jax.Array,
                       family=None) -> ModelMetricsRegression:
    from h2o3_tpu.models.distributions import get_family
    fam = family or get_family("gaussian")
    dev = fam.deviance(y, jnp.maximum(pred, 1e-10) if fam.name != "gaussian" else pred)
    r = jax.device_get(_regression_pass(pred, y, mask, dev))
    return ModelMetricsRegression(
        nobs=int(r["n"]), mse=float(r["mse"]), mae=float(r["mae"]),
        rmsle=float(r["rmsle"]), mean_residual_deviance=float(r["mrd"]), r2=float(r["r2"]))


# -- binomial -----------------------------------------------------------------


@partial(jax.jit, static_argnames=("nbins",))
def _binomial_pass(p, y, mask, nbins=NBINS):
    """One fused pass: 400-bin score histogram (AUC2 semantics) + logloss + MSE."""
    w = mask.astype(jnp.float32)
    n = w.sum()
    pc = jnp.clip(p, 1e-7, 1 - 1e-7)
    logloss = -(w * (y * jnp.log(pc) + (1 - y) * jnp.log1p(-pc))).sum() / n
    err = jnp.where(mask, p - y, 0.0)
    mse = (err * err).sum() / n

    bins = jnp.clip((p * nbins).astype(jnp.int32), 0, nbins - 1)
    bins = jnp.where(mask, bins, 0)
    tp_h = jax.ops.segment_sum(w * y, bins, num_segments=nbins)
    fp_h = jax.ops.segment_sum(w * (1.0 - y), bins, num_segments=nbins)
    s_h = jax.ops.segment_sum(w * p, bins, num_segments=nbins)
    return dict(n=n, logloss=logloss, mse=mse, tp_h=tp_h, fp_h=fp_h, s_h=s_h)


def binomial_metrics(p: jax.Array, y: jax.Array, mask: jax.Array) -> ModelMetricsBinomial:
    r = jax.device_get(_binomial_pass(p, y, mask))
    tp_h, fp_h = np.asarray(r["tp_h"], np.float64), np.asarray(r["fp_h"], np.float64)
    P, N = tp_h.sum(), fp_h.sum()
    # descending threshold sweep: cumulative TP/FP from the top bin down
    tps = np.cumsum(tp_h[::-1])[::-1]   # tps[b] = positives with score >= bin b
    fps = np.cumsum(fp_h[::-1])[::-1]
    # tps/fps are monotone non-increasing in b, so the descending-b sweep IS
    # the ROC polyline (both coordinates non-decreasing) — no re-sorting.
    # Sorting by fpr alone is wrong: stable ties put high-tpr points first,
    # ending each vertical ROC segment at its BOTTOM (a two-valued score
    # distribution then reads as auc=0.5 despite perfect separation).
    tpr_pts = np.concatenate([[0.0], (tps / max(P, 1e-30))[::-1], [1.0]])
    fpr_pts = np.concatenate([[0.0], (fps / max(N, 1e-30))[::-1], [1.0]])
    auc = float(np.trapezoid(tpr_pts, fpr_pts))
    # PR curve — same descending-b traversal (recall non-decreasing)
    prec = tps / np.maximum(tps + fps, 1e-30)
    rec = tps / max(P, 1e-30)
    pr_auc = float(np.trapezoid(prec[::-1], rec[::-1]))
    # max-F1 threshold + confusion matrix (reference AUC2.ThresholdCriterion.f1)
    f1 = 2 * prec * rec / np.maximum(prec + rec, 1e-30)
    b = int(np.argmax(f1))
    thr = b / NBINS
    tp, fp = tps[b], fps[b]
    fn, tn = P - tp, N - fp
    cm = np.array([[tn, fp], [fn, tp]])
    mpce = 0.5 * (fp / max(N, 1e-30) + fn / max(P, 1e-30))
    ks = float(np.max(tps / max(P, 1e-30) - fps / max(N, 1e-30)))
    return ModelMetricsBinomial(
        nobs=int(r["n"]), mse=float(r["mse"]), auc=auc, pr_auc=pr_auc,
        logloss=float(r["logloss"]), mean_per_class_error=float(mpce),
        max_f1_threshold=float(thr), confusion_matrix=cm, ks=ks,
        _tp_h=tp_h, _fp_h=fp_h, _s_h=np.asarray(r["s_h"], np.float64))


# -- multinomial --------------------------------------------------------------


@partial(jax.jit, static_argnames=("nclass",))
def _multinomial_pass(probs, y, mask, nclass):
    w = mask.astype(jnp.float32)
    n = w.sum()
    yi = jnp.where(mask, y.astype(jnp.int32), 0)
    p_true = jnp.clip(jnp.take_along_axis(probs, yi[:, None], axis=1)[:, 0], 1e-15, 1.0)
    logloss = -(w * jnp.log(p_true)).sum() / n
    mse = (w * (1.0 - p_true) ** 2).sum() / n
    pred = jnp.argmax(probs, axis=1)
    idx = jnp.where(mask, yi * nclass + pred, 0)
    cm = jax.ops.segment_sum(w, idx, num_segments=nclass * nclass).reshape(nclass, nclass)
    return dict(n=n, logloss=logloss, mse=mse, cm=cm)


def multinomial_metrics(probs: jax.Array, y: jax.Array, mask: jax.Array,
                        nclass: int) -> ModelMetricsMultinomial:
    r = jax.device_get(_multinomial_pass(probs, y, mask, nclass))
    cm = np.asarray(r["cm"], np.float64)
    row = cm.sum(axis=1)
    per_class_err = 1.0 - np.diag(cm) / np.maximum(row, 1e-30)
    mpce = float(per_class_err[row > 0].mean()) if (row > 0).any() else 0.0
    return ModelMetricsMultinomial(
        nobs=int(r["n"]), mse=float(r["mse"]), logloss=float(r["logloss"]),
        mean_per_class_error=mpce, confusion_matrix=cm)
