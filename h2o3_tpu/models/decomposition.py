"""PCA / SVD / GLRM — matrix decompositions.

Reference:
- ``hex/pca/PCA.java`` (479 LoC): method GramSVD (default) forms the Gram
  matrix distributed (``hex/util/LinearAlgebraUtils.java``) and eigendecomposes
  on the leader; transforms NONE/DEMEAN/DESCALE/STANDARDIZE/NORMALIZE.
- ``hex/svd/SVD.java``: distributed power iteration / randomized SVD over the
  same Gram machinery.
- ``hex/glrm/GLRM.java`` (2,603 LoC): generalized low-rank model X ≈ A·Y via
  alternating minimization with per-column losses and regularizers on A and Y.

TPU-native: the Gram contraction ``XᵀX`` is a single einsum over the
row-sharded design matrix (XLA all-reduces per-chip partials over ICI — the
MRTask tree reduce of the reference), and the small [K,K] eig/Cholesky runs
replicated. GLRM's alternating updates are closed-form ridge solves, each a
pair of MXU matmuls + a [k,k] Cholesky, jitted as one program per sweep.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.types import VecType
from h2o3_tpu.frame.vec import Vec
from h2o3_tpu.models.data_info import DataInfo
from h2o3_tpu.models.job import Job
from h2o3_tpu.models.model_base import Model, ModelBuilder, make_model_key


def _make_data_info(frame: Frame, x, transform: str,
                    use_all_factor_levels: bool = False) -> DataInfo:
    """Map the reference transform enum onto DataInfo's sub/mul machinery."""
    t = str(transform).upper()
    di = DataInfo.make(frame, x, standardize=(t == "STANDARDIZE"),
                       use_all_factor_levels=use_all_factor_levels)
    if t == "DEMEAN":
        di.num_sub = di.num_means.copy()
        di.num_mul = np.ones_like(di.num_mul)
    elif t == "DESCALE":
        di.num_sub = np.zeros_like(di.num_sub)
        sigmas = np.array([frame.vec(c).sigma() for c in di.num_cols], np.float32)
        di.num_mul = np.where((sigmas > 0) & np.isfinite(sigmas),
                              1.0 / np.maximum(sigmas, 1e-30), 1.0).astype(np.float32)
    elif t == "NORMALIZE":
        # (x - mean) / (max - min), per DataInfo.java TransformType.NORMALIZE
        rng = np.array([frame.vec(c).max() - frame.vec(c).min()
                        for c in di.num_cols], np.float32)
        di.num_sub = di.num_means.copy()
        di.num_mul = np.where((rng > 0) & np.isfinite(rng),
                              1.0 / np.maximum(rng, 1e-30), 1.0).astype(np.float32)
    elif t == "NONE":
        di.num_sub = np.zeros_like(di.num_sub)
        di.num_mul = np.ones_like(di.num_mul)
    return di


@jax.jit
def _gram(X, w):
    """Weighted Gram XᵀWX and weighted column means (one pass, psum-reduced)."""
    Xw = X * w[:, None]
    return X.T @ Xw, Xw.sum(axis=0), w.sum()


# ---------------------------------------------------------------------------
# PCA
# ---------------------------------------------------------------------------

class PCAModel(Model):
    algo = "pca"

    def _score_raw(self, frame: Frame) -> jax.Array:
        # scores are centered projections: the eigendecomposition is of the
        # covariance, so the train-time column means must come off here too
        X = self.data_info.expand(frame)
        mu = jnp.asarray(self.output["mu"], jnp.float32)
        return (X - mu[None, :]) @ self.output["eigenvectors"]

    def predict(self, frame: Frame) -> Frame:
        S = self._score_raw(frame)
        k = S.shape[1]
        return Frame([f"PC{i+1}" for i in range(k)],
                     [Vec.from_device(S[:, i], frame.nrows, VecType.NUM)
                      for i in range(k)])

    def rotation(self) -> np.ndarray:
        return np.asarray(self.output["eigenvectors"])


class PCA(ModelBuilder):
    """h2o-py surface: ``H2OPrincipalComponentAnalysisEstimator``."""

    algo = "pca"
    unsupervised = True

    @classmethod
    def defaults(cls) -> dict:
        return dict(
            super().defaults(),
            k=1,
            transform="DEMEAN",        # reference PCA default
            pca_method="GramSVD",
            use_all_factor_levels=False,
            compute_metrics=True,
            max_iterations=1000,
        )

    def _fit(self, job: Job, frame: Frame, x, y, weights) -> PCAModel:
        p = self.params
        if str(p["pca_method"]) != "GramSVD":
            raise NotImplementedError(
                f"pca_method={p['pca_method']!r} not implemented (have GramSVD)")
        k = int(p["k"])
        di = _make_data_info(frame, x, p["transform"],
                             bool(p.get("use_all_factor_levels", False)))
        X = di.expand(frame)
        K = X.shape[1]
        if not (1 <= k <= K):
            raise ValueError(f"k must be in [1, {K}]")
        w = weights
        G, colsum, wsum = _gram(X, w)
        G = jax.device_get(G).astype(np.float64)
        mu = jax.device_get(colsum).astype(np.float64) / max(float(jax.device_get(wsum)), 1e-12)
        n = max(float(jax.device_get(wsum)), 2.0)
        # covariance of the (already transformed) design matrix; PCA always
        # centers internally (reference GramSVD centers via transform)
        cov = (G / (n - 1.0)) - np.outer(mu, mu) * (n / (n - 1.0))
        evals, evecs = np.linalg.eigh(cov)
        order = np.argsort(evals)[::-1][:k]
        evals = np.maximum(evals[order], 0.0)
        evecs = evecs[:, order]
        # sign convention: largest-|.| component positive (deterministic)
        signs = np.sign(evecs[np.abs(evecs).argmax(axis=0), np.arange(k)])
        evecs = evecs * np.where(signs == 0, 1.0, signs)[None, :]

        sdev = np.sqrt(evals)
        tot_var = float(np.trace(cov))
        prop = evals / tot_var if tot_var > 0 else np.zeros_like(evals)
        from h2o3_tpu.models.model_base import ModelParameters
        return PCAModel(
            key=make_model_key(self.algo, self.model_id),
            params=ModelParameters(p),
            data_info=di,
            response_column=None, response_domain=None,
            output=dict(eigenvectors=jnp.asarray(evecs, jnp.float32),
                        mu=mu.astype(np.float32),
                        std_deviation=sdev,
                        eigenvalues=evals,
                        prop_var=prop, cum_var=np.cumsum(prop),
                        coef_names=di.coef_names, total_variance=tot_var),
        )


# ---------------------------------------------------------------------------
# SVD
# ---------------------------------------------------------------------------

class SVDModel(Model):
    algo = "svd"

    def _score_raw(self, frame: Frame) -> jax.Array:
        X = self.data_info.expand(frame)
        # U = X V D^-1
        V = self.output["v"]
        d = jnp.asarray(self.output["d"], jnp.float32)
        return (X @ V) / jnp.maximum(d[None, :], 1e-30)

    def predict(self, frame: Frame) -> Frame:
        U = self._score_raw(frame)
        k = U.shape[1]
        return Frame([f"u{i+1}" for i in range(k)],
                     [Vec.from_device(U[:, i], frame.nrows, VecType.NUM)
                      for i in range(k)])


class SVD(ModelBuilder):
    """h2o-py surface: ``H2OSingularValueDecompositionEstimator``
    (method GramSVD: eig of XᵀX, reference ``hex/svd/SVD.java``)."""

    algo = "svd"
    unsupervised = True

    @classmethod
    def defaults(cls) -> dict:
        return dict(
            super().defaults(),
            nv=1,
            transform="NONE",
            svd_method="GramSVD",
            use_all_factor_levels=True,
        )

    def _fit(self, job: Job, frame: Frame, x, y, weights) -> SVDModel:
        p = self.params
        if str(p["svd_method"]) != "GramSVD":
            raise NotImplementedError(
                f"svd_method={p['svd_method']!r} not implemented (have GramSVD)")
        di = _make_data_info(frame, x, p["transform"],
                             bool(p.get("use_all_factor_levels", False)))
        X = di.expand(frame)
        K = X.shape[1]
        nv = int(p["nv"])
        if not (1 <= nv <= K):
            raise ValueError(f"nv must be in [1, {K}]")
        G, _, _ = _gram(X, weights)
        G = jax.device_get(G).astype(np.float64)
        evals, evecs = np.linalg.eigh(G)
        order = np.argsort(evals)[::-1][:nv]
        d = np.sqrt(np.maximum(evals[order], 0.0))
        V = evecs[:, order]
        signs = np.sign(V[np.abs(V).argmax(axis=0), np.arange(nv)])
        V = V * np.where(signs == 0, 1.0, signs)[None, :]
        from h2o3_tpu.models.model_base import ModelParameters
        return SVDModel(
            key=make_model_key(self.algo, self.model_id),
            params=ModelParameters(p),
            data_info=di,
            response_column=None, response_domain=None,
            output=dict(v=jnp.asarray(V, jnp.float32), d=d,
                        coef_names=di.coef_names),
        )


# ---------------------------------------------------------------------------
# GLRM
# ---------------------------------------------------------------------------

@jax.jit
def _glrm_update_A(X, M, Y, gamma_x):
    """Exact masked ridge solve per row: (Y·diag(mᵢ)·Yᵀ + γI)aᵢ = Y·diag(mᵢ)·xᵢ.

    The [rows, k, k] Gram batch is one einsum (MXU) followed by a batched
    [k,k] solve — rows stay sharded, each chip solves its own rows."""
    k = Y.shape[0]
    G = jnp.einsum("ak,nk,bk->nab", Y, M, Y) \
        + (gamma_x + 1e-6) * jnp.eye(k, dtype=X.dtype)[None]
    r = jnp.einsum("ak,nk->na", Y, X * M)
    return jnp.linalg.solve(G, r[..., None])[..., 0]


@jax.jit
def _glrm_update_Y(X, M, A, gamma_y):
    """Exact masked ridge solve per column (same shape trick, [cols, k, k])."""
    k = A.shape[1]
    G = jnp.einsum("na,nj,nb->jab", A, M, A) \
        + (gamma_y + 1e-6) * jnp.eye(k, dtype=X.dtype)[None]
    r = jnp.einsum("na,nj->ja", A, X * M)
    return jnp.linalg.solve(G, r[..., None])[..., 0].T


@jax.jit
def _glrm_objective(X, M, A, Y, gamma_x, gamma_y):
    R = (X - A @ Y) * M
    return (R * R).sum() + gamma_x * (A * A).sum() + gamma_y * (Y * Y).sum()


def _apply_reg(Z, kind: str):
    if kind == "NonNegative":
        return jnp.maximum(Z, 0.0)
    return Z


def _expand_masked(di: DataInfo, frame: Frame, row_ok) -> tuple[jax.Array, jax.Array]:
    """Expanded design + observation mask M (1=observed cell). ``expand()``
    mean-imputes NAs, so the NA positions must be read off the raw columns
    (a cat NA zeroes its whole one-hot block)."""
    X = di.expand(frame)
    plen, K = X.shape
    M = jnp.broadcast_to(jnp.asarray(row_ok)[:, None], (plen, K)).astype(jnp.float32)
    col = 0
    for ci, c in enumerate(di.cat_cols):
        width = len(di.cat_domains[ci]) - (0 if di.use_all_factor_levels else 1)
        if width > 0:
            v = frame.vec(c)
            codes = v.data
            if v.domain != di.cat_domains[ci]:
                from h2o3_tpu.models.data_info import _remap_codes
                codes = _remap_codes(codes, v.domain or (), di.cat_domains[ci])
            ok = codes >= 0
            M = M.at[:, col:col + width].set(M[:, col:col + width] * ok[:, None])
            col += width
    for ni, c in enumerate(di.num_cols):
        ok = ~jnp.isnan(frame.vec(c).data)
        M = M.at[:, col + ni].set(M[:, col + ni] * ok)
    return X * M, M


class GLRMModel(Model):
    algo = "glrm"

    def _score_raw(self, frame: Frame) -> jax.Array:
        # project new rows onto the archetypes Y: A_new = masked ridge solve
        Xc, M = _expand_masked(self.data_info, frame, frame.row_mask())
        A = _glrm_update_A(Xc, M, self.output["archetypes"],
                           jnp.float32(self.output["gamma_x"]))
        return A @ self.output["archetypes"]

    def transform_frame(self, frame: Frame) -> Frame:
        """Low-rank representation A of new rows (reference: GLRM x-factor)."""
        Xc, M = _expand_masked(self.data_info, frame, frame.row_mask())
        A = _glrm_update_A(Xc, M, self.output["archetypes"],
                           jnp.float32(self.output["gamma_x"]))
        k = A.shape[1]
        return Frame([f"Arch{i+1}" for i in range(k)],
                     [Vec.from_device(A[:, i], frame.nrows, VecType.NUM)
                      for i in range(k)])

    def predict(self, frame: Frame) -> Frame:
        R = self._score_raw(frame)
        names = [f"reconstr_{n}" for n in self.data_info.coef_names]
        return Frame(names, [Vec.from_device(R[:, i], frame.nrows, VecType.NUM)
                             for i in range(R.shape[1])])

    def archetypes(self) -> np.ndarray:
        return np.asarray(self.output["archetypes"])


class GLRM(ModelBuilder):
    """h2o-py surface: ``H2OGeneralizedLowRankEstimator`` (quadratic loss,
    L2/NonNegative regularizers; alternating ridge solves)."""

    algo = "glrm"
    unsupervised = True

    @classmethod
    def defaults(cls) -> dict:
        return dict(
            super().defaults(),
            k=1,
            transform="NONE",
            loss="Quadratic",
            regularization_x="None",     # None | Quadratic | NonNegative
            regularization_y="None",
            gamma_x=0.0,
            gamma_y=0.0,
            max_iterations=100,
            init="SVD",                  # SVD | Random
        )

    def _fit(self, job: Job, frame: Frame, x, y, weights) -> GLRMModel:
        p = self.params
        k = int(p["k"])
        if str(p["loss"]).lower() != "quadratic":
            raise ValueError("only Quadratic loss implemented")
        di = _make_data_info(frame, x, p["transform"],
                             bool(p.get("use_all_factor_levels", False)))
        Xc, M = _expand_masked(di, frame, weights > 0)
        plen, K = Xc.shape
        if not (1 <= k <= min(plen, K)):
            raise ValueError(f"k must be in [1, {min(plen, K)}]")

        seed = int(p.get("seed") or -1)
        key = jax.random.PRNGKey(seed if seed >= 0 else 271828)
        if str(p["init"]).upper() == "SVD":
            G = jax.device_get(Xc.T @ Xc).astype(np.float64)
            evals, evecs = np.linalg.eigh(G)
            Y = jnp.asarray(evecs[:, np.argsort(evals)[::-1][:k]].T, jnp.float32)
        else:
            Y = 0.1 * jax.random.normal(key, (k, K), jnp.float32)
        gx, gy = jnp.float32(p["gamma_x"]), jnp.float32(p["gamma_y"])

        obj_prev = np.inf
        for it in range(max(int(p["max_iterations"]), 1)):
            A = _apply_reg(_glrm_update_A(Xc, M, Y, gx), p["regularization_x"])
            Y = _apply_reg(_glrm_update_Y(Xc, M, A, gy), p["regularization_y"])
            obj = float(jax.device_get(_glrm_objective(Xc, M, A, Y, gx, gy)))
            job.update((it + 1) / max(int(p["max_iterations"]), 1),
                       f"iter {it+1} objective {obj:.5f}")
            if np.isfinite(obj_prev) and abs(obj_prev - obj) <= 1e-6 * max(obj_prev, 1.0):
                break
            obj_prev = obj
        # re-solve A against the final Y so x_factor matches archetypes
        A = _apply_reg(_glrm_update_A(Xc, M, Y, gx), p["regularization_x"])
        obj = float(jax.device_get(_glrm_objective(Xc, M, A, Y, gx, gy)))

        from h2o3_tpu.models.model_base import ModelParameters
        return GLRMModel(
            key=make_model_key(self.algo, self.model_id),
            params=ModelParameters(p),
            data_info=di,
            response_column=None, response_domain=None,
            output=dict(archetypes=Y, x_factor=A, objective=obj,
                        gamma_x=float(p["gamma_x"]), gamma_y=float(p["gamma_y"]),
                        iterations=it + 1, coef_names=di.coef_names),
        )
