"""PCA / SVD / GLRM — matrix decompositions.

Reference:
- ``hex/pca/PCA.java`` (479 LoC): method GramSVD (default) forms the Gram
  matrix distributed (``hex/util/LinearAlgebraUtils.java``) and eigendecomposes
  on the leader; transforms NONE/DEMEAN/DESCALE/STANDARDIZE/NORMALIZE.
- ``hex/svd/SVD.java``: distributed power iteration / randomized SVD over the
  same Gram machinery.
- ``hex/glrm/GLRM.java`` (2,603 LoC): generalized low-rank model X ≈ A·Y via
  alternating minimization with per-column losses and regularizers on A and Y.

TPU-native: the Gram contraction ``XᵀX`` is a single einsum over the
row-sharded design matrix (XLA all-reduces per-chip partials over ICI — the
MRTask tree reduce of the reference), and the small [K,K] eig/Cholesky runs
replicated. GLRM's alternating updates are closed-form ridge solves, each a
pair of MXU matmuls + a [k,k] Cholesky, jitted as one program per sweep.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.types import VecType
from h2o3_tpu.frame.vec import Vec
from h2o3_tpu.models.data_info import DataInfo
from h2o3_tpu.models.job import Job
from h2o3_tpu.models.model_base import Model, ModelBuilder, make_model_key


def _make_data_info(frame: Frame, x, transform: str,
                    use_all_factor_levels: bool = False) -> DataInfo:
    """Map the reference transform enum onto DataInfo's sub/mul machinery."""
    t = str(transform).upper()
    di = DataInfo.make(frame, x, standardize=(t == "STANDARDIZE"),
                       use_all_factor_levels=use_all_factor_levels)
    if t == "DEMEAN":
        di.num_sub = di.num_means.copy()
        di.num_mul = np.ones_like(di.num_mul)
    elif t == "DESCALE":
        di.num_sub = np.zeros_like(di.num_sub)
        sigmas = np.array([frame.vec(c).sigma() for c in di.num_cols], np.float32)
        di.num_mul = np.where((sigmas > 0) & np.isfinite(sigmas),
                              1.0 / np.maximum(sigmas, 1e-30), 1.0).astype(np.float32)
    elif t == "NORMALIZE":
        # (x - mean) / (max - min), per DataInfo.java TransformType.NORMALIZE
        rng = np.array([frame.vec(c).max() - frame.vec(c).min()
                        for c in di.num_cols], np.float32)
        di.num_sub = di.num_means.copy()
        di.num_mul = np.where((rng > 0) & np.isfinite(rng),
                              1.0 / np.maximum(rng, 1e-30), 1.0).astype(np.float32)
    elif t == "NONE":
        di.num_sub = np.zeros_like(di.num_sub)
        di.num_mul = np.ones_like(di.num_mul)
    return di


@jax.jit
def _gram(X, w):
    """Weighted Gram XᵀWX and weighted column means (one pass, psum-reduced)."""
    Xw = X * w[:, None]
    return X.T @ Xw, Xw.sum(axis=0), w.sum()


# ---------------------------------------------------------------------------
# PCA
# ---------------------------------------------------------------------------

class PCAModel(Model):
    algo = "pca"

    def _score_raw(self, frame: Frame) -> jax.Array:
        # scores are centered projections: the eigendecomposition is of the
        # covariance, so the train-time column means must come off here too
        X = self.data_info.expand(frame)
        mu = jnp.asarray(self.output["mu"], jnp.float32)
        return (X - mu[None, :]) @ self.output["eigenvectors"]

    def predict(self, frame: Frame) -> Frame:
        S = self._score_raw(frame)
        k = S.shape[1]
        return Frame([f"PC{i+1}" for i in range(k)],
                     [Vec.from_device(S[:, i], frame.nrows, VecType.NUM)
                      for i in range(k)])

    def rotation(self) -> np.ndarray:
        return np.asarray(self.output["eigenvectors"])


class PCA(ModelBuilder):
    """h2o-py surface: ``H2OPrincipalComponentAnalysisEstimator``."""

    algo = "pca"
    unsupervised = True

    @classmethod
    def defaults(cls) -> dict:
        return dict(
            super().defaults(),
            k=1,
            transform="DEMEAN",        # reference PCA default
            pca_method="GramSVD",
            use_all_factor_levels=False,
            compute_metrics=True,
            max_iterations=1000,
        )

    def _fit(self, job: Job, frame: Frame, x, y, weights) -> PCAModel:
        p = self.params
        if str(p["pca_method"]) != "GramSVD":
            raise NotImplementedError(
                f"pca_method={p['pca_method']!r} not implemented (have GramSVD)")
        k = int(p["k"])
        di = _make_data_info(frame, x, p["transform"],
                             bool(p.get("use_all_factor_levels", False)))
        X = di.expand(frame)
        K = X.shape[1]
        if not (1 <= k <= K):
            raise ValueError(f"k must be in [1, {K}]")
        w = weights
        G, colsum, wsum = _gram(X, w)
        G = jax.device_get(G).astype(np.float64)
        mu = jax.device_get(colsum).astype(np.float64) / max(float(jax.device_get(wsum)), 1e-12)
        n = max(float(jax.device_get(wsum)), 2.0)
        # covariance of the (already transformed) design matrix; PCA always
        # centers internally (reference GramSVD centers via transform)
        cov = (G / (n - 1.0)) - np.outer(mu, mu) * (n / (n - 1.0))
        evals, evecs = np.linalg.eigh(cov)
        order = np.argsort(evals)[::-1][:k]
        evals = np.maximum(evals[order], 0.0)
        evecs = evecs[:, order]
        # sign convention: largest-|.| component positive (deterministic)
        signs = np.sign(evecs[np.abs(evecs).argmax(axis=0), np.arange(k)])
        evecs = evecs * np.where(signs == 0, 1.0, signs)[None, :]

        sdev = np.sqrt(evals)
        tot_var = float(np.trace(cov))
        prop = evals / tot_var if tot_var > 0 else np.zeros_like(evals)
        from h2o3_tpu.models.model_base import ModelParameters
        return PCAModel(
            key=make_model_key(self.algo, self.model_id),
            params=ModelParameters(p),
            data_info=di,
            response_column=None, response_domain=None,
            output=dict(eigenvectors=jnp.asarray(evecs, jnp.float32),
                        mu=mu.astype(np.float32),
                        std_deviation=sdev,
                        eigenvalues=evals,
                        prop_var=prop, cum_var=np.cumsum(prop),
                        coef_names=di.coef_names, total_variance=tot_var),
        )


# ---------------------------------------------------------------------------
# SVD
# ---------------------------------------------------------------------------

class SVDModel(Model):
    algo = "svd"

    def _score_raw(self, frame: Frame) -> jax.Array:
        X = self.data_info.expand(frame)
        # U = X V D^-1
        V = self.output["v"]
        d = jnp.asarray(self.output["d"], jnp.float32)
        return (X @ V) / jnp.maximum(d[None, :], 1e-30)

    def predict(self, frame: Frame) -> Frame:
        U = self._score_raw(frame)
        k = U.shape[1]
        return Frame([f"u{i+1}" for i in range(k)],
                     [Vec.from_device(U[:, i], frame.nrows, VecType.NUM)
                      for i in range(k)])


class SVD(ModelBuilder):
    """h2o-py surface: ``H2OSingularValueDecompositionEstimator``
    (method GramSVD: eig of XᵀX, reference ``hex/svd/SVD.java``)."""

    algo = "svd"
    unsupervised = True

    @classmethod
    def defaults(cls) -> dict:
        return dict(
            super().defaults(),
            nv=1,
            transform="NONE",
            svd_method="GramSVD",
            use_all_factor_levels=True,
        )

    def _fit(self, job: Job, frame: Frame, x, y, weights) -> SVDModel:
        p = self.params
        if str(p["svd_method"]) != "GramSVD":
            raise NotImplementedError(
                f"svd_method={p['svd_method']!r} not implemented (have GramSVD)")
        di = _make_data_info(frame, x, p["transform"],
                             bool(p.get("use_all_factor_levels", False)))
        X = di.expand(frame)
        K = X.shape[1]
        nv = int(p["nv"])
        if not (1 <= nv <= K):
            raise ValueError(f"nv must be in [1, {K}]")
        G, _, _ = _gram(X, weights)
        G = jax.device_get(G).astype(np.float64)
        evals, evecs = np.linalg.eigh(G)
        order = np.argsort(evals)[::-1][:nv]
        d = np.sqrt(np.maximum(evals[order], 0.0))
        V = evecs[:, order]
        signs = np.sign(V[np.abs(V).argmax(axis=0), np.arange(nv)])
        V = V * np.where(signs == 0, 1.0, signs)[None, :]
        from h2o3_tpu.models.model_base import ModelParameters
        return SVDModel(
            key=make_model_key(self.algo, self.model_id),
            params=ModelParameters(p),
            data_info=di,
            response_column=None, response_domain=None,
            output=dict(v=jnp.asarray(V, jnp.float32), d=d,
                        coef_names=di.coef_names),
        )


# ---------------------------------------------------------------------------
# GLRM
# ---------------------------------------------------------------------------

@jax.jit
def _glrm_update_A(X, M, Y, gamma_x):
    """Exact masked ridge solve per row: (Y·diag(mᵢ)·Yᵀ + γI)aᵢ = Y·diag(mᵢ)·xᵢ.

    The [rows, k, k] Gram batch is one einsum (MXU) followed by a batched
    [k,k] solve — rows stay sharded, each chip solves its own rows."""
    k = Y.shape[0]
    G = jnp.einsum("ak,nk,bk->nab", Y, M, Y) \
        + (gamma_x + 1e-6) * jnp.eye(k, dtype=X.dtype)[None]
    r = jnp.einsum("ak,nk->na", Y, X * M)
    return jnp.linalg.solve(G, r[..., None])[..., 0]


@jax.jit
def _glrm_update_Y(X, M, A, gamma_y):
    """Exact masked ridge solve per column (same shape trick, [cols, k, k])."""
    k = A.shape[1]
    G = jnp.einsum("na,nj,nb->jab", A, M, A) \
        + (gamma_y + 1e-6) * jnp.eye(k, dtype=X.dtype)[None]
    r = jnp.einsum("na,nj->ja", A, X * M)
    return jnp.linalg.solve(G, r[..., None])[..., 0].T


@jax.jit
def _glrm_objective(X, M, A, Y, gamma_x, gamma_y):
    R = (X - A @ Y) * M
    return (R * R).sum() + gamma_x * (A * A).sum() + gamma_y * (Y * Y).sum()


def _apply_reg(Z, kind: str):
    if kind == "NonNegative":
        return jnp.maximum(Z, 0.0)
    return Z


# ---------------------------------------------------------------------------
# GLRM generalized losses (reference: hex/genmodel/algos/glrm/GlrmLoss.java —
# loss/lgrad per enum member are reproduced exactly) and regularizer
# proximal operators (GlrmRegularizer.java)
# ---------------------------------------------------------------------------

_LOSS_IDS = {"quadratic": 0, "absolute": 1, "huber": 2, "poisson": 3,
             "hinge": 4, "logistic": 5, "periodic": 6,
             "categorical": 7, "ordinal": 8}


@jax.jit
def _glrm_loss_and_grad(U, T, M, lid, period, blk_start, blk_last):
    """Elementwise loss + dL/dU for the mixed per-column losses.

    U = A@Y [n,K]; T = target matrix (numeric value, 0/1 for binary and
    one-hot blocks); M observation mask; lid [K] loss id per expanded
    column; blk_start[j] = first column of j's categorical block (j for
    non-cat); blk_last[j] marks the final column of an ordinal block
    (excluded from the ordinal sum, GlrmLoss.Ordinal).
    """
    x = U - T
    s = 1.0 - 2.0 * T                      # binary sign (GlrmLoss Hinge/Logistic)
    f = 2.0 * jnp.pi / period

    quad_l, quad_g = x * x, 2.0 * x
    abs_l, abs_g = jnp.abs(x), jnp.sign(x)
    hub_l = jnp.where(x > 1, x - 0.5, jnp.where(x < -1, -x - 0.5, 0.5 * x * x))
    hub_g = jnp.clip(x, -1.0, 1.0)
    eu = jnp.exp(jnp.clip(U, -30, 30))
    Tpos = jnp.maximum(T, 1e-30)
    poi_l = eu - T * U + jnp.where(T > 0, T * jnp.log(Tpos) - T, 0.0)
    poi_g = eu - T
    hin_l = jnp.maximum(1.0 + s * U, 0.0)
    hin_g = jnp.where(1.0 + s * U > 0, s, 0.0)
    log_l = jnp.log1p(jnp.exp(jnp.clip(s * U, -30, 30)))
    log_g = s * jax.nn.sigmoid(s * U)
    per_l = 1.0 - jnp.cos((T - U) * f)
    per_g = -f * jnp.sin((T - U) * f)
    # Categorical block (one-hot T): sum_j≠a max(1+u_j,0) + max(1-u_a,0)
    cat_l = jnp.where(T > 0, jnp.maximum(1.0 - U, 0.0),
                      jnp.maximum(1.0 + U, 0.0))
    cat_g = jnp.where(T > 0, -(1.0 - U > 0).astype(U.dtype),
                      (1.0 + U > 0).astype(U.dtype))
    # Ordinal block: for threshold col i (< d-1): a > i → max(1-u_i,0) else 1.
    # a > i ⟺ the block's inclusive one-hot cumsum at i is 0.
    cum = jnp.cumsum(T, axis=1)
    base = jnp.take_along_axis(
        jnp.pad(cum, ((0, 0), (1, 0))), blk_start[None, :], axis=1)
    a_gt_i = (cum - base) == 0
    ord_l = jnp.where(blk_last[None, :], 0.0,
                      jnp.where(a_gt_i, jnp.maximum(1.0 - U, 0.0), 1.0))
    ord_g = jnp.where(blk_last[None, :] | ~a_gt_i, 0.0,
                      jnp.where(1.0 - U > 0, -1.0, 0.0))

    # accumulate by per-column select: a stacked [9, n, K] gather would hold
    # ~18 full matrices in HBM; this keeps two [n, K] buffers
    L = jnp.zeros_like(U)
    G = jnp.zeros_like(U)
    for fid, (lf, gf) in enumerate([
            (quad_l, quad_g), (abs_l, abs_g), (hub_l, hub_g),
            (poi_l, poi_g), (hin_l, hin_g), (log_l, log_g),
            (per_l, per_g), (cat_l, cat_g), (ord_l, ord_g)]):
        sel = (lid == fid)[None, :]
        L = jnp.where(sel, lf, L)
        G = jnp.where(sel, gf, G)
    return (L * M).sum(), G * M


def _prox(Z, kind: str, step):
    """Proximal operator of step * regularizer (GlrmRegularizer.rproxgrad)."""
    if kind in (None, "None"):
        return Z
    if kind == "Quadratic":
        return Z / (1.0 + 2.0 * step)
    if kind == "L2":                      # group (row-wise) shrinkage
        nrm = jnp.linalg.norm(Z, axis=-1, keepdims=True)
        return Z * jnp.maximum(1.0 - step / jnp.maximum(nrm, 1e-30), 0.0)
    if kind == "L1":
        return jnp.sign(Z) * jnp.maximum(jnp.abs(Z) - step, 0.0)
    if kind == "NonNegative":
        return jnp.maximum(Z, 0.0)
    if kind == "OneSparse":               # largest nonneg coordinate only
        Zp = jnp.maximum(Z, 0.0)
        best = jnp.argmax(Zp, axis=-1, keepdims=True)
        oh = jnp.arange(Z.shape[-1])[None, :] == best
        return jnp.where(oh, Zp, 0.0)
    if kind == "UnitOneSparse":           # indicator vector
        best = jnp.argmax(Z, axis=-1, keepdims=True)
        return (jnp.arange(Z.shape[-1])[None, :] == best).astype(Z.dtype)
    if kind == "Simplex":                 # Euclidean projection onto simplex
        srt = jnp.sort(Z, axis=-1)[:, ::-1]
        css = jnp.cumsum(srt, axis=-1) - 1.0
        j = jnp.arange(1, Z.shape[-1] + 1)
        cond = srt - css / j > 0
        rho = jnp.sum(cond, axis=-1, keepdims=True)
        theta = jnp.take_along_axis(css, rho - 1, axis=-1) / rho
        return jnp.maximum(Z - theta, 0.0)
    raise ValueError(f"unknown regularization {kind!r}")


def _reg_value(Z, kind: str, gamma):
    if kind in (None, "None", "NonNegative", "OneSparse", "UnitOneSparse",
                "Simplex"):
        return 0.0
    if kind == "Quadratic":
        return gamma * float(jax.device_get((Z * Z).sum()))
    if kind == "L2":
        return gamma * float(jax.device_get(
            jnp.linalg.norm(Z, axis=-1).sum()))
    if kind == "L1":
        return gamma * float(jax.device_get(jnp.abs(Z).sum()))
    return 0.0


@jax.jit
def _glrm_grad_A(Xt, M, A, Y, lid, period, blk_start, blk_last):
    L, G = _glrm_loss_and_grad(A @ Y, Xt, M, lid, period, blk_start, blk_last)
    return L, G @ Y.T


@jax.jit
def _glrm_grad_Y(Xt, M, A, Y, lid, period, blk_start, blk_last):
    L, G = _glrm_loss_and_grad(A @ Y, Xt, M, lid, period, blk_start, blk_last)
    return L, A.T @ G


def _expand_masked(di: DataInfo, frame: Frame, row_ok) -> tuple[jax.Array, jax.Array]:
    """Expanded design + observation mask M (1=observed cell). ``expand()``
    mean-imputes NAs, so the NA positions must be read off the raw columns
    (a cat NA zeroes its whole one-hot block)."""
    X = di.expand(frame)
    plen, K = X.shape
    M = jnp.broadcast_to(jnp.asarray(row_ok)[:, None], (plen, K)).astype(jnp.float32)
    col = 0
    for ci, c in enumerate(di.cat_cols):
        width = len(di.cat_domains[ci]) - (0 if di.use_all_factor_levels else 1)
        if width > 0:
            v = frame.vec(c)
            codes = v.data
            if v.domain != di.cat_domains[ci]:
                from h2o3_tpu.models.data_info import _remap_codes
                codes = _remap_codes(codes, v.domain or (), di.cat_domains[ci])
            ok = codes >= 0
            M = M.at[:, col:col + width].set(M[:, col:col + width] * ok[:, None])
            col += width
    for ni, c in enumerate(di.num_cols):
        ok = ~jnp.isnan(frame.vec(c).data)
        M = M.at[:, col + ni].set(M[:, col + ni] * ok)
    return X * M, M


class GLRMModel(Model):
    algo = "glrm"

    def _score_raw(self, frame: Frame) -> jax.Array:
        # project new rows onto the archetypes Y: A_new = masked ridge solve
        Xc, M = _expand_masked(self.data_info, frame, frame.row_mask())
        A = _glrm_update_A(Xc, M, self.output["archetypes"],
                           jnp.float32(self.output["gamma_x"]))
        return A @ self.output["archetypes"]

    def transform_frame(self, frame: Frame) -> Frame:
        """Low-rank representation A of new rows (reference: GLRM x-factor)."""
        Xc, M = _expand_masked(self.data_info, frame, frame.row_mask())
        A = _glrm_update_A(Xc, M, self.output["archetypes"],
                           jnp.float32(self.output["gamma_x"]))
        k = A.shape[1]
        return Frame([f"Arch{i+1}" for i in range(k)],
                     [Vec.from_device(A[:, i], frame.nrows, VecType.NUM)
                      for i in range(k)])

    def predict(self, frame: Frame) -> Frame:
        R = self._score_raw(frame)
        names = [f"reconstr_{n}" for n in self.data_info.coef_names]
        return Frame(names, [Vec.from_device(R[:, i], frame.nrows, VecType.NUM)
                             for i in range(R.shape[1])])

    def archetypes(self) -> np.ndarray:
        return np.asarray(self.output["archetypes"])


class GLRM(ModelBuilder):
    """h2o-py surface: ``H2OGeneralizedLowRankEstimator``.

    Quadratic-loss models with closed-form-friendly regularizers use exact
    alternating ridge solves (MXU matmuls + batched [k,k] Cholesky). Any
    other loss (Absolute/Huber/Poisson/Hinge/Logistic/Periodic per numeric
    column, Categorical/Ordinal per enum block — reference ``GlrmLoss``) or
    regularizer (L1/L2/OneSparse/UnitOneSparse/Simplex — ``GlrmRegularizer``)
    runs the reference's alternating PROXIMAL gradient scheme
    (``hex/glrm/GLRM.java`` update loop: gradient step on A, prox, gradient
    step on Y, prox, adaptive step size — halve on objective increase, grow
    5% on success)."""

    algo = "glrm"
    unsupervised = True

    #: regularizers the exact quadratic ALS path can honor
    _EXACT_REGS = (None, "None", "Quadratic", "NonNegative")

    @classmethod
    def defaults(cls) -> dict:
        return dict(
            super().defaults(),
            k=1,
            transform="NONE",
            loss="Quadratic",            # numeric default (GlrmLoss)
            multi_loss="Categorical",    # categorical default (Categorical|Ordinal)
            loss_by_col=None,            # per-source-column overrides
            loss_by_col_idx=None,
            period=1.0,                  # Periodic loss period
            regularization_x="None",     # None|Quadratic|L2|L1|NonNegative|
            regularization_y="None",     # OneSparse|UnitOneSparse|Simplex
            gamma_x=0.0,
            gamma_y=0.0,
            max_iterations=100,
            init="SVD",                  # SVD | Random
        )

    def _loss_ids(self, di: DataInfo, x: list[str]) -> np.ndarray:
        """Per-expanded-column loss ids from loss/multi_loss/loss_by_col."""
        p = self.params
        per_col: dict[str, str] = {}
        if p.get("loss_by_col"):
            names = list(p["loss_by_col"])
            idxs = list(p.get("loss_by_col_idx") or range(len(names)))
            if len(idxs) != len(names):
                raise ValueError("loss_by_col and loss_by_col_idx lengths "
                                 "differ")
            for i, nm in zip(idxs, names):
                per_col[x[int(i)]] = str(nm)
        K = len(di.coef_names)
        lid = np.zeros(K, np.int32)
        col = 0
        for ci, c in enumerate(di.cat_domains):
            width = len(c) - (0 if di.use_all_factor_levels else 1)
            name = di.cat_cols[ci]
            loss = per_col.get(name, str(p["multi_loss"])).lower()
            if loss not in ("categorical", "ordinal"):
                raise ValueError(f"categorical column {name!r} needs "
                                 "Categorical or Ordinal loss")
            lid[col:col + width] = _LOSS_IDS[loss]
            col += width
        for ni, c in enumerate(di.num_cols):
            loss = per_col.get(c, str(p["loss"])).lower()
            if loss in ("categorical", "ordinal"):
                raise ValueError(f"numeric column {c!r} cannot use {loss}")
            if loss not in _LOSS_IDS:
                raise ValueError(f"unknown loss {loss!r}; have "
                                 f"{sorted(_LOSS_IDS)}")
            lid[col + ni] = _LOSS_IDS[loss]
        return lid

    def _block_layout(self, di: DataInfo) -> tuple[np.ndarray, np.ndarray]:
        """(blk_start[K], blk_last[K]) for the categorical-block losses."""
        K = len(di.coef_names)
        start = np.arange(K, dtype=np.int32)
        last = np.zeros(K, bool)
        col = 0
        for dom in di.cat_domains:
            width = len(dom) - (0 if di.use_all_factor_levels else 1)
            start[col:col + width] = col
            if width > 0:
                last[col + width - 1] = True
            col += width
        return start, last

    def _fit(self, job: Job, frame: Frame, x, y, weights) -> GLRMModel:
        p = self.params
        k = int(p["k"])
        lb = [str(v).lower() for v in (p.get("loss_by_col") or [])]
        has_cat = any(frame.vec(c).is_categorical for c in x)
        nonquad = (str(p["loss"]).lower() != "quadratic" or has_cat
                   or any(v != "quadratic" for v in lb))
        exact_ok = (not nonquad
                    and p["regularization_x"] in self._EXACT_REGS
                    and p["regularization_y"] in self._EXACT_REGS)

        # generalized losses need the FULL one-hot block per enum column
        di = _make_data_info(frame, x, p["transform"],
                             use_all_factor_levels=has_cat or
                             bool(p.get("use_all_factor_levels", False)))
        Xc, M = _expand_masked(di, frame, weights > 0)
        plen, K = Xc.shape
        if not (1 <= k <= min(plen, K)):
            raise ValueError(f"k must be in [1, {min(plen, K)}]")

        seed = int(p.get("seed") or -1)
        key = jax.random.PRNGKey(seed if seed >= 0 else 271828)
        if str(p["init"]).upper() == "SVD":
            G = jax.device_get(Xc.T @ Xc).astype(np.float64)
            evals, evecs = np.linalg.eigh(G)
            Y = jnp.asarray(evecs[:, np.argsort(evals)[::-1][:k]].T, jnp.float32)
        else:
            Y = 0.1 * jax.random.normal(key, (k, K), jnp.float32)
        gx, gy = jnp.float32(p["gamma_x"]), jnp.float32(p["gamma_y"])
        iters = max(int(p["max_iterations"]), 1)

        if exact_ok:
            obj_prev = np.inf
            for it in range(iters):
                A = _apply_reg(_glrm_update_A(Xc, M, Y, gx), p["regularization_x"])
                Y = _apply_reg(_glrm_update_Y(Xc, M, A, gy), p["regularization_y"])
                obj = float(jax.device_get(_glrm_objective(Xc, M, A, Y, gx, gy)))
                job.update((it + 1) / iters, f"iter {it+1} objective {obj:.5f}")
                if np.isfinite(obj_prev) and abs(obj_prev - obj) <= 1e-6 * max(obj_prev, 1.0):
                    break
                obj_prev = obj
            A = _apply_reg(_glrm_update_A(Xc, M, Y, gx), p["regularization_x"])
            obj = float(jax.device_get(_glrm_objective(Xc, M, A, Y, gx, gy)))
        else:
            A, Y, obj, it = self._fit_proximal(job, di, Xc, M, Y, k, iters)

        from h2o3_tpu.models.model_base import ModelParameters
        return GLRMModel(
            key=make_model_key(self.algo, self.model_id),
            params=ModelParameters(p),
            data_info=di,
            response_column=None, response_domain=None,
            output=dict(archetypes=Y, x_factor=A, objective=obj,
                        gamma_x=float(p["gamma_x"]), gamma_y=float(p["gamma_y"]),
                        iterations=it + 1, coef_names=di.coef_names),
        )

    def _fit_proximal(self, job: Job, di, Xc, M, Y, k: int, iters: int):
        """Alternating proximal gradient (GLRM.java non-quadratic path)."""
        p = self.params
        lid = jnp.asarray(self._loss_ids(di, self._x_cols))
        blk_start, blk_last = self._block_layout(di)
        blk_start = jnp.asarray(blk_start)
        blk_last = jnp.asarray(blk_last)
        period = jnp.float32(p.get("period") or 1.0)
        gx, gy = float(p["gamma_x"]), float(p["gamma_y"])
        rx, ry = p["regularization_x"], p["regularization_y"]
        n = max(float(jax.device_get(M.sum())), 1.0)

        A = jnp.zeros((Xc.shape[0], k), jnp.float32)
        alpha = 1.0 / n                  # ~1/Lipschitz of the summed loss
        L_prev, _ = _glrm_grad_A(Xc, M, A, Y, lid, period, blk_start, blk_last)
        obj_prev = float(jax.device_get(L_prev)) + _reg_value(A, rx, gx) \
            + _reg_value(Y.T, ry, gy)
        it = 0
        for it in range(iters):
            _, GA = _glrm_grad_A(Xc, M, A, Y, lid, period, blk_start, blk_last)
            A1 = _prox(A - alpha * GA, rx, alpha * gx)
            _, GY = _glrm_grad_Y(Xc, M, A1, Y, lid, period, blk_start, blk_last)
            Y1 = _prox((Y - alpha * GY).T, ry, alpha * gy).T
            L, _ = _glrm_grad_A(Xc, M, A1, Y1, lid, period, blk_start, blk_last)
            obj = float(jax.device_get(L)) + _reg_value(A1, rx, gx) \
                + _reg_value(Y1.T, ry, gy)
            if np.isfinite(obj) and obj <= obj_prev:
                A, Y = A1, Y1
                converged = abs(obj_prev - obj) <= 1e-7 * max(obj_prev, 1.0)
                obj_prev = obj
                alpha *= 1.05          # reference: grow on success
                if converged:
                    break
            else:
                alpha *= 0.5           # reference: halve on failure
                if alpha < 1e-12:
                    break
            job.update((it + 1) / iters, f"iter {it+1} objective {obj_prev:.5f}")
        return A, Y, obj_prev, it
