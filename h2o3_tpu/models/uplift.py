"""Uplift DRF — treatment-effect forests + AUUC metrics.

Reference: ``hex/tree/uplift/UpliftDRF.java`` (725 LoC) grows forests whose
splits maximize treatment/control divergence (KL, Euclidean, ChiSquared), and
``hex/AUUC.java`` ranks rows by predicted uplift and accumulates the uplift
curve (qini / lift / gain) over ``auuc_nbins`` thresholds.

TPU-native: trees grow on the shared level-synchronous histogram engine via
the transformed-outcome target Z = Y·T/p − Y·(1−T)/(1−p) (Athey–Imbens), whose
per-leaf mean is an unbiased uplift estimate — this keeps the (G,H,W)
3-channel histogram layout intact, where the reference's divergence gains
require 4 channels. The AUUC computation follows the reference exactly
(threshold bins over ranked uplift, qini default).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.data_info import response_as_float
from h2o3_tpu.models.gbm import SharedTreeBuilder, SharedTreeModel, tree_matrix
from h2o3_tpu.models.job import Job
from h2o3_tpu.models.model_base import make_model_key
from h2o3_tpu.models.tree import TreeParams, grow_trees_batched


class ModelMetricsBinomialUplift:
    """AUUC family (reference: ``hex/ModelMetricsBinomialUplift.java``)."""

    def __init__(self, auuc, qini, auuc_normalized, nbins):
        self.auuc = auuc
        self.qini = qini
        self.auuc_normalized = auuc_normalized
        self.nbins = nbins

    def __repr__(self):
        return (f"ModelMetricsBinomialUplift(auuc={self.auuc:.5f}, "
                f"qini={self.qini:.5f}, norm={self.auuc_normalized:.5f})")


def compute_auuc(uplift_pred, y, treat, mask, nbins: int = 1000):
    """AUUC by ranked-threshold bins (reference ``AUUC.java``: rows sorted by
    predicted uplift, per-bin (n_t, n_c, y_t, y_c) accumulated, qini value
    qini(i) = y_t(i) − y_c(i)·n_t(i)/n_c(i) summed over bins)."""
    u = jnp.where(mask, uplift_pred, -jnp.inf)
    order = jnp.argsort(-u)   # descending predicted uplift
    ys = y[order]
    ts = treat[order]
    ms = mask[order].astype(jnp.float32)
    n = jnp.maximum(ms.sum(), 1.0)

    cum_t = jnp.cumsum(ms * ts)
    cum_c = jnp.cumsum(ms * (1 - ts))
    cum_yt = jnp.cumsum(ms * ts * ys)
    cum_yc = jnp.cumsum(ms * (1 - ts) * ys)

    # qini curve at nbins thresholds
    plen = ys.shape[0]
    idx = jnp.clip((jnp.arange(1, nbins + 1) * n / nbins).astype(jnp.int32) - 1,
                   0, plen - 1)
    nt, nc = cum_t[idx], cum_c[idx]
    yt, yc = cum_yt[idx], cum_yc[idx]
    qini_curve = yt - yc * nt / jnp.maximum(nc, 1.0)
    auuc = qini_curve.sum() / nbins

    # random-targeting baseline: straight line to the final qini value
    final = qini_curve[-1]
    random_auuc = final / 2.0
    qini = auuc - random_auuc
    norm = jnp.where(jnp.abs(final) > 1e-12, auuc / jnp.abs(final), 0.0)
    return (float(jax.device_get(auuc)), float(jax.device_get(qini)),
            float(jax.device_get(norm)))


class UpliftDRFModel(SharedTreeModel):
    algo = "upliftdrf"

    def _contrib_scale_bias(self):
        return 1.0 / max(len(self.output["trees"]), 1), 0.0

    def _score_raw(self, frame: Frame):
        raw = self._tree_raw_sum(frame) / max(len(self.output["trees"]), 1)
        return raw   # predicted uplift per row

    def predict(self, frame: Frame) -> Frame:
        from h2o3_tpu.frame.types import VecType
        from h2o3_tpu.frame.vec import Vec
        u = self._score_raw(frame)
        return Frame(["uplift_predict"],
                     [Vec(u.astype(jnp.float32), VecType.NUM, frame.nrows)])

    def model_performance(self, frame: Frame):
        y, valid = response_as_float(frame.vec(self.response_column))
        t = frame.vec(self.output["treatment_column"]).as_float()
        mask = frame.row_mask() & valid & ~jnp.isnan(t)
        u = self._score_raw(frame)
        nbins = int(self.params.get("auuc_nbins") or -1)
        if nbins <= 0:
            nbins = 1000   # reference AUUC default bin count
        return ModelMetricsBinomialUplift(
            *compute_auuc(u, y, jnp.where(mask, t, 0.0), mask, nbins),
            nbins=nbins)


class UpliftDRF(SharedTreeBuilder):
    """h2o-py surface: ``H2OUpliftRandomForestEstimator``."""

    algo = "upliftdrf"

    @classmethod
    def defaults(cls) -> dict:
        d = super().defaults()
        d.update(treatment_column=None, uplift_metric="KL",
                 auuc_type="qini", auuc_nbins=-1, ntrees=50,
                 mtries=-1, sample_rate=0.632)
        return d

    def _validate(self, frame: Frame, x, y):
        super()._validate(frame, x, y)
        tc = self.params.get("treatment_column")
        if not tc:
            raise ValueError("treatment_column is required")
        tv = frame.vec(tc)
        if not tv.is_categorical or tv.cardinality() != 2:
            raise ValueError("treatment_column must be a 2-level categorical "
                             "(control first level, treatment second)")

    def _fit(self, job: Job, frame: Frame, x, y, weights) -> UpliftDRFModel:
        p = self.params
        tc = p["treatment_column"]
        x = [c for c in x if c != tc]
        yvec = frame.vec(y)
        if not yvec.is_categorical or yvec.cardinality() != 2:
            raise ValueError("uplift response must be a 2-level categorical")
        X, edges, binned, yy, valid, yvec, domains = self._prepare(frame, x, y, weights)
        t = frame.vec(tc).as_float()           # codes 0 (control) / 1 (treatment)
        w = weights * valid * ~jnp.isnan(t)
        t = jnp.where(w > 0, t, 0.0)
        yy = jnp.where(w > 0, yy, 0.0)

        # transformed outcome: E[Z|x] = uplift(x) (propensity from the data)
        pt = float(jax.device_get((w * t).sum() / jnp.maximum(w.sum(), 1e-30)))
        pt = min(max(pt, 1e-6), 1 - 1e-6)
        z = yy * t / pt - yy * (1 - t) / (1 - pt)

        tp = TreeParams(max_depth=int(p["max_depth"]), nbins=int(p["nbins"]),
                        min_rows=float(p["min_rows"]), reg_lambda=0.0,
                        min_split_improvement=float(p["min_split_improvement"]))
        ntrees = int(p["ntrees"])
        seed = int(p.get("seed") or 0) or 23
        key = jax.random.PRNGKey(seed)
        col_rate = 1.0
        if int(p.get("mtries") or -1) > 0:
            col_rate = min(1.0, int(p["mtries"]) / max(len(x), 1))
        trees = []
        batch = 8
        for s in range(0, ntrees, batch):
            k = min(batch, ntrees - s)
            keys = jax.random.split(jax.random.fold_in(key, s), k + 1)
            gs, hs, ws = [], [], []
            for i in range(k):
                wk = self._row_weights(keys[i], w, float(p["sample_rate"]), True)
                gs.append(-wk * z)
                hs.append(wk)
                ws.append(wk)
            grown, _ = grow_trees_batched(
                binned, edges, jnp.stack(gs), jnp.stack(hs), jnp.stack(ws),
                tp, jnp.ones(binned.shape[1], bool), col_rate, keys[-1],
                cat_feats=self._cat_feats)
            trees.extend(grown)
            job.update((s + k) / ntrees, f"{s + k}/{ntrees} trees")

        model = UpliftDRFModel(
            key=make_model_key(self.algo, self.model_id),
            params=self.params, data_info=None, response_column=y,
            response_domain=yvec.domain,
            output=dict(trees=trees, x_cols=list(x), feat_domains=domains,
                        treatment_column=tc, propensity=pt,
                        **self._cat_output()),
        )
        return model

    def _holdout_metrics(self, model, frame, y, w):
        return model.model_performance(frame)