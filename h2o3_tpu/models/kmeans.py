"""KMeans — Lloyd iterations with Random / PlusPlus / Furthest init.

Reference: ``hex/kmeans/KMeans.java`` (1,196 LoC): distributed Lloyd where each
MRTask pass assigns rows to the nearest center and accumulates per-cluster
sums/counts, reduced across nodes; init supports Random, PlusPlus, Furthest
(``KMeans.java`` ``Initialization`` enum); standardization optional; metrics
are within/between/total sum-of-squares (``hex/ModelMetricsClustering.java``).

TPU-native: one Lloyd step is two MXU matmuls — the [rows, k] distance matrix
via ``|x|² - 2 X·Cᵀ + |c|²`` and the per-cluster sums via ``onehot(assign)ᵀ·X``
— jitted over the row-sharded design matrix, so XLA reduces the per-shard
cluster sums/counts over ICI exactly like the reference's MRTask reduce.
Only the scalar convergence test crosses to host per iteration.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.types import VecType
from h2o3_tpu.frame.vec import Vec
from h2o3_tpu.models.data_info import DataInfo
from h2o3_tpu.models.job import Job
from h2o3_tpu.models.model_base import Model, ModelBuilder, make_model_key


@jax.jit
def _sq_dists(X, C):
    """[rows, k] squared distances (rows with w=0 still computed, masked later)."""
    x2 = (X * X).sum(axis=1, keepdims=True)
    c2 = (C * C).sum(axis=1)[None, :]
    return jnp.maximum(x2 - 2.0 * (X @ C.T) + c2, 0.0)


@jax.jit
def _lloyd_step(X, w, C):
    """One Lloyd iteration → (new centers, within-SS, assignment counts)."""
    d2 = _sq_dists(X, C)
    assign = jnp.argmin(d2, axis=1)
    wss = (w * jnp.min(d2, axis=1)).sum()
    onehot = (assign[:, None] == jnp.arange(C.shape[0])[None, :]).astype(X.dtype) \
        * w[:, None]
    sums = onehot.T @ X                       # [k, K] cluster sums (MXU)
    counts = onehot.sum(axis=0)               # [k]
    # empty cluster keeps its previous center (reference re-seeds from the
    # worst row; stationary center is the deterministic-shape equivalent)
    newC = jnp.where(counts[:, None] > 0, sums / jnp.maximum(counts[:, None], 1e-12), C)
    return newC, wss, counts


@jax.jit
def _assign(X, C):
    d2 = _sq_dists(X, C)
    return jnp.argmin(d2, axis=1), jnp.min(d2, axis=1)


@jax.jit
def _weighted_row_choice(key, p, w):
    """Sample a row index proportionally to p*w (device-side categorical)."""
    logits = jnp.log(jnp.maximum(p * w, 1e-30))
    return jax.random.categorical(key, logits)


class KMeansModel(Model):
    algo = "kmeans"

    def _score_raw(self, frame: Frame) -> jax.Array:
        X = self.data_info.expand(frame)
        assign, _ = _assign(X, self.output["centers_std"])
        return assign.astype(jnp.float32)

    def predict(self, frame: Frame) -> Frame:
        assign = self._score_raw(frame).astype(jnp.int32)
        # estimate_k may settle on fewer clusters than params["k"]
        dom = tuple(str(i) for i in range(self.output["centers_std"].shape[0]))
        return Frame(["predict"],
                     [Vec.from_device(assign, frame.nrows, VecType.CAT, domain=dom)])

    def centers(self) -> np.ndarray:
        """De-standardized centers (reference: KMeansModel._output._centers_raw)."""
        return np.asarray(self.output["centers"])

    def tot_withinss(self) -> float:
        return float(self.output["tot_withinss"])

    def betweenss(self) -> float:
        return float(self.output["betweenss"])

    def totss(self) -> float:
        return float(self.output["totss"])


class KMeans(ModelBuilder):
    """h2o-py surface: ``H2OKMeansEstimator``."""

    algo = "kmeans"
    unsupervised = True

    @classmethod
    def defaults(cls) -> dict:
        return dict(
            super().defaults(),
            k=1,
            max_iterations=10,
            init="Furthest",          # Random | PlusPlus | Furthest | User
            user_points=None,
            standardize=True,
            estimate_k=False,
        )

    def _init_centers(self, key, X, w, k: int, mode: str) -> jax.Array:
        plen, K = X.shape
        if mode == "user":
            pts = np.asarray(self.params["user_points"], np.float32)
            if pts.shape != (k, K):
                raise ValueError(f"user_points must be [{k}, {K}] in the expanded "
                                 f"column layout, got {pts.shape}")
            # user points arrive on the raw scale; move the numeric block into
            # the standardized space the data lives in (reference KMeans.java
            # standardizes user points alongside the data)
            di = self._di
            nnum = len(di.num_cols)
            if nnum:
                s = di.ncats_expanded
                pts = pts.copy()
                pts[:, s:s + nnum] = (pts[:, s:s + nnum] - di.num_sub) * di.num_mul
            return jnp.asarray(pts)
        if mode == "random":
            idx = jax.random.choice(key, plen, (k,), replace=False,
                                    p=np.asarray(jax.device_get(w / w.sum())))
            return X[idx]
        # PlusPlus / Furthest: greedy seeding; k host steps, each a jitted pass
        # (reference: KMeans.java Initialization.PlusPlus / Furthest loops)
        key, sub = jax.random.split(key)
        first = _weighted_row_choice(sub, jnp.ones(plen), w)
        centers = [X[first]]
        for _ in range(1, k):
            C = jnp.stack(centers)
            d2 = _sq_dists(X, C).min(axis=1)
            if mode == "furthest":
                nxt = jnp.argmax(jnp.where(w > 0, d2, -jnp.inf))
            else:  # plusplus: sample ∝ D²
                key, sub = jax.random.split(key)
                nxt = _weighted_row_choice(sub, d2, w)
            centers.append(X[nxt])
        return jnp.stack(centers)

    def _run_lloyd(self, job: Job, X, w, C) -> tuple[jax.Array, float, int]:
        """Lloyd to convergence; returns (centers, tot_withinss, iters)."""
        wss_v, wss_prev, iters = np.inf, np.inf, 0
        self._wss_series = []
        for it in range(max(int(self.params["max_iterations"]), 1)):
            C, wss, _ = _lloyd_step(X, w, C)
            wss_v = float(jax.device_get(wss))
            self._wss_series.append(wss_v)
            iters = it + 1
            job.update(iters / max(int(self.params["max_iterations"]), 1),
                       f"k={C.shape[0]} iter {iters} within-SS {wss_v:.4f}")
            if np.isfinite(wss_prev) and abs(wss_prev - wss_v) <= 1e-7 * max(wss_prev, 1.0):
                break
            wss_prev = wss_v
        return C, wss_v, iters

    def _scoring_history(self, model):
        """Per-Lloyd-iteration rows (reference: ``KMeans.java``
        scoring-history table — iteration / within_cluster_sum_of_squares)."""
        ser = getattr(self, "_wss_series", None)
        if not ser:
            return None
        return self._history_table(
            model,
            [("iterations", "long", "%d"),
             ("within_cluster_sum_of_squares", "double", "%.5f")],
            [[i + 1, v] for i, v in enumerate(ser)])

    def _fit(self, job: Job, frame: Frame, x, y, weights) -> KMeansModel:
        p = self.params
        k = int(p["k"])
        if k < 1:
            raise ValueError("k must be >= 1")
        di = DataInfo.make(frame, x, standardize=p["standardize"],
                           use_all_factor_levels=True)
        self._di = di
        X = di.expand(frame)
        w = weights
        seed = int(p.get("seed") or -1)
        key = jax.random.PRNGKey(seed if seed >= 0 else 1234)

        if bool(p["estimate_k"]):
            if p["user_points"] is not None:
                raise ValueError("Cannot estimate k if user_points are provided.")
            # reference KMeans.java:284-420: deterministic growth from k=1,
            # accept each added centroid while the relative within-SS
            # improvement beats min(0.02 + 10/nrows + 2.5/nfeatures^2, 0.8)
            nrows = max(float(jax.device_get(w.sum())), 1.0)
            cutoff = min(0.02 + 10.0 / nrows + 2.5 / max(X.shape[1], 1) ** 2, 0.8)
            C = ((w[:, None] * X).sum(axis=0) / jnp.maximum(w.sum(), 1e-12))[None, :]
            C, wss_best, iters = self._run_lloyd(job, X, w, C)
            accepted_series = list(self._wss_series)
            for k_try in range(2, k + 1):
                d2 = _sq_dists(X, C).min(axis=1)
                nxt = jnp.argmax(jnp.where(w > 0, d2, -jnp.inf))
                Cand = jnp.concatenate([C, X[nxt][None, :]], axis=0)
                Cand, wss_now, it2 = self._run_lloyd(job, X, w, Cand)
                rel = (wss_best - wss_now) / max(wss_best, 1e-30)
                if rel < cutoff:
                    break
                C, wss_best, iters = Cand, wss_now, it2
                accepted_series = list(self._wss_series)
            # scoring history must describe the ACCEPTED run, not the
            # rejected final candidate that broke the loop
            self._wss_series = accepted_series
            k = C.shape[0]
        else:
            mode = str(p["init"]).lower()
            C = self._init_centers(key, X, w, k, mode)
            C, _, iters = self._run_lloyd(job, X, w, C)

        # final stats on converged centers
        assign, d2 = _assign(X, C)
        tot_within = float(jax.device_get((w * d2).sum()))
        gm = (w[:, None] * X).sum(axis=0) / jnp.maximum(w.sum(), 1e-12)
        totss = float(jax.device_get((w * ((X - gm[None, :]) ** 2).sum(axis=1)).sum()))
        counts_f = jax.device_get(
            ((assign[:, None] == jnp.arange(k)[None, :]) * w[:, None]).sum(axis=0))

        # de-standardize centers back to original numeric scale
        C_host = np.asarray(jax.device_get(C), np.float64)
        centers_raw = C_host.copy()
        nnum = len(di.num_cols)
        if nnum:
            s = di.ncats_expanded
            centers_raw[:, s:s + nnum] = C_host[:, s:s + nnum] / di.num_mul + di.num_sub

        from h2o3_tpu.models.model_base import ModelParameters
        return KMeansModel(
            key=make_model_key(self.algo, self.model_id),
            params=ModelParameters(p),
            data_info=di,
            response_column=None,
            response_domain=None,
            output=dict(centers_std=C, centers=centers_raw,
                        tot_withinss=tot_within, totss=totss,
                        betweenss=totss - tot_within,
                        size=np.asarray(counts_f), iterations=iters,
                        coef_names=di.coef_names),
        )
