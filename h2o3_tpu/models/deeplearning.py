"""DeepLearning — feed-forward MLP (classification / regression / autoencoder).

Reference: ``hex/deeplearning/`` (5.8 kLoC). The reference trains with
**Hogwild! lock-free intra-node SGD + per-iteration cross-node model
averaging** (``hex/deeplearning/DeepLearningTask.java:17-90``,
``DeepLearning.java:379-478``): threads race on shared per-node weights,
then nodes average. Forward/backward math, ADADELTA, momentum ramp, dropout
and maxout live in ``hex/deeplearning/Neurons.java`` (``bpropMiniBatch:135``).

TPU-first redesign (SURVEY.md §7 step 7): Hogwild is a CPU-cache trick with
no accelerator analog — the same statistical contract (stochastic minibatch
updates whose gradient is averaged across the cluster each step) is expressed
as **synchronous data-parallel minibatch SGD**: the design matrix is
row-sharded across the mesh, each step consumes one shuffled minibatch, XLA
inserts the gradient all-reduce over ICI (replacing per-iteration model
averaging with per-step exact averaging — strictly less stale). The whole
epoch is one jitted ``lax.scan`` over minibatches: zero host round-trips in
the hot loop, weights live in HBM, matmuls hit the MXU in bf16-friendly f32.

Supported reference options: activations Tanh/Rectifier/Maxout (+WithDropout),
``adaptive_rate`` ADADELTA(rho, epsilon) or annealed-rate momentum SGD with
Nesterov, ``input_dropout_ratio``/``hidden_dropout_ratios`` (inverted dropout),
``l1``/``l2``, ``max_w2`` per-unit norm constraint, loss CrossEntropy/
Quadratic/Absolute/Huber, ``initial_weight_distribution`` UniformAdaptive/
Uniform/Normal, ``autoencoder`` with reconstruction-error anomaly scoring
(reference ``DlInput``/``Neurons`` semantics).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.types import VecType
from h2o3_tpu.frame.vec import Vec
from h2o3_tpu.models.data_info import DataInfo, response_as_float
from h2o3_tpu.models.job import Job
from h2o3_tpu.models.model_base import (Model, ModelBuilder, ModelParameters,
                                        make_model_key, megastep_k,
                                        publish_dispatch_audit)
from h2o3_tpu.utils import telemetry as _tm
from h2o3_tpu.utils.costs import accounted_jit
from h2o3_tpu.utils.timeline import timed_event


# ---------------------------------------------------------------------------
# forward / backward
# ---------------------------------------------------------------------------

def _act_kind(activation: str) -> tuple[str, bool]:
    """Map reference activation enum → (base activation, hidden dropout on)."""
    a = activation.lower()
    drop = a.endswith("withdropout")
    base = a.replace("withdropout", "")
    if base not in ("tanh", "rectifier", "maxout"):
        raise ValueError(f"unknown activation {activation!r}")
    return base, drop


def _forward(params, X, act: str, train: bool, key, in_drop: float,
             hid_drops: tuple[float, ...]):
    """MLP forward pass. Maxout layers hold W of width 2*units and take the
    pairwise max (reference: 2-channel Maxout, ``Neurons.java``). Dropout is
    inverted (scale at train time) so scoring needs no rescale."""
    h = X
    if train and in_drop > 0:
        key, sub = jax.random.split(key)
        keep = jax.random.bernoulli(sub, 1.0 - in_drop, h.shape)
        h = jnp.where(keep, h / (1.0 - in_drop), 0.0)
    n_hidden = len(params["W"]) - 1
    for i in range(n_hidden):
        z = h @ params["W"][i] + params["b"][i]
        if act == "tanh":
            h = jnp.tanh(z)
        elif act == "rectifier":
            h = jnp.maximum(z, 0.0)
        else:  # maxout: [B, 2u] → max over channel pairs → [B, u]
            u = z.shape[-1] // 2
            h = jnp.maximum(z[..., :u], z[..., u:])
        p = hid_drops[i] if i < len(hid_drops) else 0.0
        if train and p > 0:
            key, sub = jax.random.split(key)
            keep = jax.random.bernoulli(sub, 1.0 - p, h.shape)
            h = jnp.where(keep, h / (1.0 - p), 0.0)
    return h @ params["W"][-1] + params["b"][-1]   # linear output (logits / preds)


def _row_loss(out, y, w, loss: str, nclasses: int, huber_delta: float):
    """Weighted per-row loss summed over the batch (reference loss enum)."""
    if nclasses >= 2:
        logp = jax.nn.log_softmax(out, axis=-1)
        yi = y.astype(jnp.int32)
        nll = -jnp.take_along_axis(logp, yi[:, None], axis=1)[:, 0]
        return (w * nll).sum()
    err = out - (y if out.ndim == 1 else y.reshape(out.shape))
    if loss == "absolute":
        e = jnp.abs(err)
    elif loss == "huber":
        a = jnp.abs(err)
        e = jnp.where(a <= huber_delta, 0.5 * a * a,
                      huber_delta * (a - 0.5 * huber_delta))
    else:  # quadratic
        e = 0.5 * err * err
    if e.ndim == 2:            # autoencoder / multi-output: sum over outputs
        e = e.sum(axis=1)
    return (w * e).sum()


# ---------------------------------------------------------------------------
# one jitted training "iteration": scan over minibatches
# ---------------------------------------------------------------------------

def _epoch_steps(params, opt, Xb, yb, wb, key, samples0,
                 act: str, loss: str, nclasses: int, cfg: tuple):
    """Scan all minibatches of one (shuffled) epoch — the traceable body
    the K-epoch megastep scan runs per epoch.

    Xb: [nb, B, K] minibatched design matrix, yb: [nb, B], wb: [nb, B].
    cfg is a hashable tuple of hyperparameters (see _fit for layout).
    """
    (adaptive, rho, eps, rate, rate_annealing, rate_decay,
     mom_start, mom_ramp, mom_stable, nesterov,
     l1, l2, max_w2, in_drop, hid_drops, huber_delta) = cfg

    def grad_fn(p, X, y, w, k):
        out = _forward(p, X, act, True, k, in_drop, hid_drops)
        if nclasses == 0 and out.shape[-1] == 1 and y.ndim == 1:
            out = out[:, 0]
        lsum = _row_loss(out, y, w, loss, nclasses, huber_delta)
        return lsum / jnp.maximum(w.sum(), 1e-8)

    def apply_l1l2(g, p):
        return jax.tree.map(lambda gi, pi: gi + l2 * pi + l1 * jnp.sign(pi), g, p)

    def constrain(p):
        # reference default max_w2 = Float.MAX_VALUE means "disabled"; values
        # that big also overflow bf16/f32 intermediates on TPU, so gate here
        if max_w2 <= 0 or not np.isfinite(max_w2) or max_w2 >= 1e30:
            return p
        # per-unit incoming squared-norm cap (reference Neurons max_w2)
        def cap(W):
            if W.ndim != 2:
                return W
            ss = (W * W).sum(axis=0, keepdims=True)
            return W * jnp.sqrt(max_w2 / jnp.maximum(ss, max_w2))
        return {"W": [cap(W) for W in p["W"]], "b": p["b"]}

    def step(carry, xs):
        p, o, k, samples = carry
        X, y, w = xs
        k, sub = jax.random.split(k)
        lossv, g = jax.value_and_grad(grad_fn)(p, X, y, w, sub)
        g = apply_l1l2(g, p)
        if adaptive:
            # ADADELTA (reference Neurons.java adaDelta branch)
            Eg = jax.tree.map(lambda e, gi: rho * e + (1 - rho) * gi * gi, o["Eg"], g)
            dx = jax.tree.map(
                lambda ed, eg, gi: -jnp.sqrt(ed + eps) / jnp.sqrt(eg + eps) * gi,
                o["Edx"], Eg, g)
            Edx = jax.tree.map(lambda e, d: rho * e + (1 - rho) * d * d, o["Edx"], dx)
            p = jax.tree.map(jnp.add, p, dx)
            o = {"Eg": Eg, "Edx": Edx, "v": o["v"]}
        else:
            lr0 = rate / (1.0 + rate_annealing * samples)
            # per-layer rate decay (reference DeepLearningParameters.rate_decay:
            # layer i trains at rate * rate_decay^i)
            lrs = [lr0 * (rate_decay ** i) for i in range(len(p["W"]))]
            mom = jnp.where(
                mom_ramp > 0,
                jnp.minimum(mom_stable,
                            mom_start + samples * (mom_stable - mom_start)
                            / jnp.maximum(mom_ramp, 1.0)),
                mom_stable)
            v = {kk: [mom * vi - lrs[i] * gi
                      for i, (vi, gi) in enumerate(zip(o["v"][kk], g[kk]))]
                 for kk in ("W", "b")}
            if nesterov:
                p = {kk: [pi + mom * vi - lrs[i] * gi
                          for i, (pi, vi, gi) in enumerate(zip(p[kk], v[kk], g[kk]))]
                     for kk in ("W", "b")}
            else:
                p = jax.tree.map(jnp.add, p, v)
            o = {"Eg": o["Eg"], "Edx": o["Edx"], "v": v}
        p = constrain(p)
        samples = samples + w.sum()
        return (p, o, k, samples), lossv

    (params, opt, key, samples), losses = jax.lax.scan(
        step, (params, opt, key, samples0), (Xb, yb, wb))
    return params, opt, key, samples, losses.mean()


@accounted_jit("dl:train_epochs", loop="dl_epoch",
               static_argnames=("act", "loss", "nclasses", "cfg", "k",
                                "nb", "B", "autoenc"))
def _train_epochs(params, opt, X, yy, w, key, samples0,
                  act: str, loss: str, nclasses: int, cfg: tuple, k: int,
                  nb: int, B: int, autoenc: bool):
    """K whole epochs in ONE compiled dispatch: shuffle → minibatch →
    step-scan all run on device, so consecutive epochs pipeline with zero
    host dispatches between them (the K-step megastep of the DL loop).

    The PRNG stream is split in exactly the order the per-epoch host loop
    used (``key → pk`` for the permutation, then ``key → ek`` for the
    in-epoch dropout/minibatch stream), so K-epoch training is
    reproducibility-identical to K single-epoch dispatches."""
    used = nb * B
    K = X.shape[1]

    def epoch(carry, _):
        params, opt, key, samples = carry
        key, pk = jax.random.split(key)
        perm = jax.random.permutation(pk, X.shape[0])[:used]
        Xb = jnp.take(X, perm, axis=0).reshape(nb, B, K)
        wb = jnp.take(w, perm, axis=0).reshape(nb, B)
        ybt = Xb if autoenc else jnp.take(yy, perm, axis=0).reshape(nb, B)
        key, ek = jax.random.split(key)
        params, opt, _, samples, mloss = _epoch_steps(
            params, opt, Xb, ybt, wb, ek, samples, act, loss, nclasses, cfg)
        return (params, opt, key, samples), mloss

    (params, opt, key, samples), losses = jax.lax.scan(
        epoch, (params, opt, key, samples0), None, length=k)
    return params, opt, key, samples, losses


@partial(jax.jit, static_argnames=("act",))
def _dl_forward_score(params, X, act: str):
    return _forward(params, X, act, False, jax.random.PRNGKey(0), 0.0, ())


@partial(jax.jit, static_argnames=("act",))
def _dl_reconstruction_mse(params, X, act: str):
    out = _forward(params, X, act, False, jax.random.PRNGKey(0), 0.0, ())
    return ((out - X) ** 2).mean(axis=1)


# ---------------------------------------------------------------------------
# Model / Builder
# ---------------------------------------------------------------------------

class DeepLearningModel(Model):
    algo = "deeplearning"

    def _score_raw(self, frame: Frame) -> jax.Array:
        X = self.data_info.expand(frame)
        out = _dl_forward_score(self.output["params"], X, self.output["act"])
        if self.is_classifier:
            return jax.nn.softmax(out, axis=-1)
        if self.params.get("autoencoder"):
            return out
        return out[:, 0]

    def anomaly(self, frame: Frame) -> Frame:
        """Per-row reconstruction MSE (reference: ``DeepLearningModel
        .scoreAutoEncoder``, anomaly detection use of autoencoders)."""
        if not self.params.get("autoencoder"):
            raise ValueError("anomaly() requires autoencoder=True")
        X = self.data_info.expand(frame)
        mse = _dl_reconstruction_mse(self.output["params"], X, self.output["act"])
        return Frame(["Reconstruction.MSE"],
                     [Vec.from_device(mse, frame.nrows, VecType.NUM)])

    def predict(self, frame: Frame) -> Frame:
        if self.params.get("autoencoder"):
            # reconstruction in the expanded space, named after coefficients
            out = self._score_raw(frame)
            names = [f"reconstr_{n}" for n in self.data_info.coef_names]
            vecs = [Vec.from_device(out[:, i], frame.nrows, VecType.NUM)
                    for i in range(out.shape[1])]
            return Frame(names, vecs)
        return super().predict(frame)


class DeepLearning(ModelBuilder):
    """h2o-py surface: ``H2ODeepLearningEstimator``."""

    algo = "deeplearning"

    @classmethod
    def defaults(cls) -> dict:
        return dict(
            super().defaults(),
            hidden=[200, 200],
            epochs=10.0,
            activation="Rectifier",
            adaptive_rate=True,
            rho=0.99,
            epsilon=1e-8,
            rate=0.005,
            rate_annealing=1e-6,
            rate_decay=1.0,
            momentum_start=0.0,
            momentum_ramp=1e6,
            momentum_stable=0.0,
            nesterov_accelerated_gradient=True,
            input_dropout_ratio=0.0,
            hidden_dropout_ratios=None,     # default 0.5 when *WithDropout
            l1=0.0,
            l2=0.0,
            max_w2=3.4028235e38,
            loss="Automatic",               # CrossEntropy|Quadratic|Absolute|Huber
            huber_alpha=0.9,                # kept for API parity (delta fixed = 1)
            mini_batch_size=32,             # reference default 1 (Hogwild row-at-
                                            # a-time); vectorized minibatch here
            standardize=True,
            use_all_factor_levels=True,
            initial_weight_distribution="UniformAdaptive",
            initial_weight_scale=1.0,
            autoencoder=False,
            score_each_iteration=False,
            # elastic local-SGD (docs/RELIABILITY.md "Elastic training"):
            # elastic = number of requested workers (0 = off; clamped to
            # the mesh-slice layout), local_steps = local epochs each
            # worker runs between parameter-averaging rounds (0 coerces
            # to 1 — average every epoch)
            elastic=0,
            local_steps=1,
        )

    unsupervised = False

    def train(self, x=None, y=None, training_frame=None, validation_frame=None,
              weights=None):
        self.unsupervised = bool(self.params.get("autoencoder"))
        return super().train(x=x, y=y, training_frame=training_frame,
                             validation_frame=validation_frame, weights=weights)

    def _init_params(self, key, sizes: list[int], act: str):
        dist = str(self.params["initial_weight_distribution"]).lower()
        scale = float(self.params["initial_weight_scale"])
        Ws, bs = [], []
        n_layers = len(sizes) - 1
        for i in range(n_layers):
            fan_in, fan_out = sizes[i], sizes[i + 1]
            width = fan_out
            if act == "maxout" and i < n_layers - 1:
                width = 2 * fan_out
            key, sub = jax.random.split(key)
            if dist == "uniformadaptive":
                lim = np.sqrt(6.0 / (fan_in + fan_out))
                W = jax.random.uniform(sub, (fan_in, width), jnp.float32, -lim, lim)
            elif dist == "uniform":
                W = jax.random.uniform(sub, (fan_in, width), jnp.float32, -scale, scale)
            else:  # normal
                W = scale * jax.random.normal(sub, (fan_in, width), jnp.float32)
            Ws.append(W)
            bs.append(jnp.zeros(width, jnp.float32))
        return {"W": Ws, "b": bs}

    def supports_auto_recovery(self) -> bool:
        # elastic builds survive faults through MEMBERSHIP (ejection +
        # shard reassignment), not snapshots — advertising auto_recovery
        # there would promise a resume path the round engine doesn't write
        return not int(self.params.get("elastic") or 0)

    def validate_request(self) -> None:
        super().validate_request()
        el = self.params.get("elastic")
        if el is not None and int(el) < 0:
            raise ValueError("elastic must be >= 0 (worker count; 0 = off)")
        ls = self.params.get("local_steps")
        if ls is not None and int(ls) < 0:
            raise ValueError("local_steps must be >= 0")

    def _fit(self, job: Job, frame: Frame, x, y, weights) -> DeepLearningModel:
        p = self.params
        act, act_dropout = _act_kind(p["activation"])
        autoenc = bool(p["autoencoder"])

        di = DataInfo.make(frame, x, standardize=p["standardize"],
                           use_all_factor_levels=p["use_all_factor_levels"])
        X = di.expand(frame)
        K = X.shape[1]

        if autoenc:
            yy, w = jnp.zeros(X.shape[0], jnp.float32), weights
            nclasses, loss = 0, "quadratic"
            domain = None
        else:
            yvec = frame.vec(y)
            yy, valid = response_as_float(yvec)
            w = weights * valid
            nclasses = yvec.cardinality() if yvec.is_categorical else 0
            domain = yvec.domain if yvec.is_categorical else None
            loss = str(p["loss"]).lower()
            if loss == "automatic":
                loss = "crossentropy" if nclasses else "quadratic"
            if nclasses and loss != "crossentropy":
                raise ValueError("classification requires CrossEntropy loss")
            if not nclasses and loss == "crossentropy":
                raise ValueError("CrossEntropy loss requires a categorical "
                                 "response (reference: DeepLearningParameters "
                                 "validation)")
        yy = jnp.where(w > 0, yy, 0.0)

        hidden = [int(h) for h in p["hidden"]]
        out_dim = K if autoenc else (nclasses if nclasses >= 2 else 1)
        sizes = [K] + hidden + [out_dim]
        seed = int(p.get("seed") or -1)
        key = jax.random.PRNGKey(seed if seed >= 0 else 5318008)
        key, init_key = jax.random.split(key)
        cp = self._resolve_checkpoint()
        done_ep = 0
        samples0 = 0.0
        if cp is not None:
            # resume from the prior model's weights (reference:
            # DeepLearning.java:348 checkpoint path: continue training the
            # same topology on more epochs). An auto-recovery snapshot
            # additionally carries epochs_done, so a crashed build resumes
            # with only the REMAINING epochs instead of the full budget.
            if cp.output["sizes"] != sizes or cp.output["act"] != act:
                raise ValueError("checkpoint topology/activation differs; "
                                 "hidden/activation are immutable across resume")
            params = cp.output["params"]
            key = jax.random.fold_in(key, 1 + int(cp.output["samples_trained"]))
            done_ep = int(cp.output.get("epochs_done") or 0)
            samples0 = float(cp.output.get("samples_trained") or 0.0)
        else:
            params = self._init_params(init_key, sizes, act)

        zeros = jax.tree.map(jnp.zeros_like, params)
        opt = {"Eg": zeros, "Edx": jax.tree.map(jnp.zeros_like, params),
               "v": jax.tree.map(jnp.zeros_like, params)}

        hid_drops = p["hidden_dropout_ratios"]
        if hid_drops is not None and not act_dropout:
            raise ValueError("hidden_dropout_ratios require a *WithDropout "
                             "activation (reference: DeepLearningParameters "
                             "validation)")
        if hid_drops is None:
            hid_drops = [0.5] * len(hidden) if act_dropout else [0.0] * len(hidden)
        if len(hid_drops) != len(hidden):
            raise ValueError("hidden_dropout_ratios must match hidden length")
        cfg = (bool(p["adaptive_rate"]), float(p["rho"]), float(p["epsilon"]),
               float(p["rate"]), float(p["rate_annealing"]), float(p["rate_decay"]),
               float(p["momentum_start"]), float(p["momentum_ramp"]),
               float(p["momentum_stable"]), bool(p["nesterov_accelerated_gradient"]),
               float(p["l1"]), float(p["l2"]), float(p["max_w2"]),
               float(p["input_dropout_ratio"]), tuple(float(d) for d in hid_drops),
               1.0)

        if int(p.get("elastic") or 0):
            # elastic local-SGD: k slice-leased workers train K local
            # epochs per round on their own shard and average parameters
            # at round boundaries, under elastic membership
            # (parallel/elastic.py; docs/RELIABILITY.md)
            return self._fit_elastic(job, frame, y, di, X, yy, w, act,
                                     loss, nclasses, domain, cfg, autoenc,
                                     params, key, sizes, done_ep, samples0)

        plen = X.shape[0]
        B = min(max(int(p["mini_batch_size"]), 1), plen)
        nb = plen // B
        used = nb * B
        epochs = float(p["epochs"])
        n_epochs = max(int(np.ceil(epochs)), 1)

        samples = jnp.float32(samples0)
        k_mega = megastep_k()
        epoch_losses = []        # [k] device arrays; fetched once post-loop
        ep = 0
        n_epochs = max(n_epochs - done_ep, 0)   # remaining after auto-resume
        dispatches = 0
        import time as _time
        from h2o3_tpu.models.job import JobCancelled
        from h2o3_tpu.ops.map_reduce import retrying
        from h2o3_tpu.persist.recovery import checkpoint_every
        recovery = getattr(self, "_build_recovery", None)
        ckpt_every = checkpoint_every()
        last_snap = 0

        def _snapshot(epochs_now: int) -> None:
            pm = DeepLearningModel(
                key=f"{self.model_id or self.algo}_autockpt",
                params=ModelParameters(p), data_info=di,
                response_column=None if autoenc else y,
                response_domain=domain,
                output=dict(params=params, act=act, sizes=sizes,
                            score_history=[],
                            samples_trained=float(jax.device_get(samples)),
                            epochs_done=done_ep + epochs_now))
            recovery.snapshot(pm, progress=done_ep + epochs_now,
                              target=done_ep + n_epochs)

        while ep < n_epochs:
            if job.should_stop:
                # cooperative deadline/cancel between megasteps: trained
                # epochs are kept (partial model, job CANCELLED)
                job.keep_partial()
                break
            # K epochs per compiled dispatch (trailing chunk compiles its own
            # smaller K once); shuffle + minibatching run inside the program,
            # so the host dispatches WORK, not steps
            kk = min(k_mega, n_epochs - ep)
            t0 = _time.time_ns()
            _in = (params, opt, key, samples)
            with timed_event("iteration", "dl_epoch"):
                # retried on transient dispatch failure: the megastep is
                # functional over its inputs, so a re-run is exact
                params, opt, key, samples, losses_k = retrying(
                    "dl_epochs", lambda: _train_epochs(
                        *_in[:2], X, yy, w, *_in[2:],
                        act, loss, nclasses, cfg, kk, nb, B, autoenc))
            # NO per-epoch fetch: the loss series stays on device and is
            # fetched in one batched transfer below, so megasteps pipeline
            epoch_losses.append(losses_k)
            dispatches += 1
            ep += kk
            # per-EPOCH latency: megastep wall amortized over its epochs, so
            # the histogram count keeps matching epochs (same contract as
            # the GLM loops; like the old per-epoch path this is dispatch
            # enqueue time — the loss fetch below pays the real wait)
            dt = (_time.time_ns() - t0) / 1e9
            for _ in range(kk):
                _tm.ITER_SECONDS.labels(loop="dl_epoch").observe(dt / kk)
            if recovery is not None and ep - last_snap >= ckpt_every:
                _snapshot(ep)
                last_snap = ep
            try:
                job.update(ep / max(n_epochs, 1), f"epoch {ep}/{n_epochs}")
            except JobCancelled:
                job.keep_partial()
                break           # partial-result algorithm: keep the epochs
            if job.cancelled:
                break
        if recovery is not None and job.should_stop and ep > last_snap:
            _snapshot(ep)       # CANCELLED builds stay resumable
        publish_dispatch_audit(self, "dl_epoch", iterations=max(ep, 1),
                               host_syncs=1, device_dispatches=dispatches)
        score_history = [
            {"epoch": i + 1, "train_loss": float(v)}
            for i, v in enumerate(np.concatenate(
                [np.atleast_1d(np.asarray(a))
                 for a in jax.device_get(epoch_losses)])
                if epoch_losses else [])]

        model = DeepLearningModel(
            key=make_model_key(self.algo, self.model_id),
            params=ModelParameters(p),
            data_info=di,
            response_column=None if autoenc else y,
            response_domain=domain,
            output=dict(params=params, act=act, sizes=sizes,
                        score_history=score_history,
                        samples_trained=float(jax.device_get(samples))),
        )
        return model

    # -- elastic local-SGD (docs/RELIABILITY.md "Elastic training") ----------

    def _fit_elastic(self, job: Job, frame: Frame, y, di, X, yy, w,
                     act: str, loss: str, nclasses: int, domain, cfg: tuple,
                     autoenc: bool, params0, key, sizes, done_ep: int,
                     samples0: float) -> DeepLearningModel:
        """Local-SGD rounds over an elastic worker group.

        Workers are mesh slices leased for the group's lifetime; each runs
        ``local_steps`` whole epochs (the ``_train_epochs`` megastep) on its
        own contiguous data shard per round, then live workers' parameters
        are weighted-averaged (weights = shard weight-sums, renormalized
        over whoever reported) and re-broadcast. A worker that faults,
        exhausts its dispatch-retry budget, blows the round deadline, or
        stops heartbeating is EJECTED: its shards are reassigned to
        survivors at the next boundary; a (re)joining worker catches up by
        cloning the latest average (every round thunk starts from the
        broadcast). Below the quorum the build cancels with partial results
        (``Job.keep_partial``). Fixed membership + fixed seeds is
        reproducibility-identical across reruns: shard assignment, worker
        PRNG streams (``fold_in(key, wid)``), and the wid-ordered host-side
        float64 average are all deterministic — ejection changes the
        averaging sequence, so parity holds only at fixed membership."""
        from h2o3_tpu.models.job import JobCancelled
        from h2o3_tpu.ops.map_reduce import retrying
        from h2o3_tpu.orchestration.scheduler import MeshScheduler
        from h2o3_tpu.parallel.elastic import (ElasticGroup,
                                               min_workers_from_env)
        from h2o3_tpu.parallel.mesh import (ROWS, num_devices,
                                            replicated_sharding,
                                            row_sharding)

        p = self.params
        k_req = max(int(p["elastic"]), 1)
        local_k = max(int(p.get("local_steps") or 0), 1)
        scheduler = MeshScheduler(slices=k_req)
        if scheduler.n > 1:
            k = scheduler.n
        else:
            # degenerate layout: on a single-device mesh threads overlap
            # safely (no collectives to rendezvous); on a multi-device mesh
            # one slice means ONE worker — overlapped same-mesh collectives
            # are the documented XLA wedge the slice layout exists to avoid
            k = k_req if num_devices() <= 1 else 1
        slice_ndev = scheduler.meshes[0].shape[ROWS]

        # host-side data: shard the REAL rows contiguously into k*spw equal
        # SUB-shards (several per worker), each padded (zero-weight rows)
        # to a multiple of the slice device count. Identical shapes mean a
        # reassigned shard reuses the survivor's compiled program, and
        # finer granularity means an ejected worker's load spreads ~evenly
        # over the k-1 survivors (one whole-worker shard handed to one
        # survivor would DOUBLE its round wall — the post-ejection
        # throughput floor is k/(k-1), reachable only with sub-shards);
        # workers also heartbeat between sub-shards, so slow and dead
        # separate faster. spw shrinks until each sub-shard still holds a
        # full minibatch.
        n = frame.nrows
        Xh = np.asarray(jax.device_get(X))[:n]
        yh = np.asarray(jax.device_get(yy))[:n]
        wh = np.asarray(jax.device_get(w))[:n]
        B_req = max(int(p["mini_batch_size"]), 1)
        spw = 6 if k > 1 else 1
        while spw > 1 and n // (k * spw) < B_req:
            spw -= 1
        n_shards = k * spw
        base = -(-n // n_shards)                # ceil
        shard_n = -(-base // slice_ndev) * slice_ndev
        host_shards = []
        for i in range(n_shards):
            lo, hi = i * base, min(n, (i + 1) * base)
            m = max(hi - lo, 0)
            Xs = np.zeros((shard_n, Xh.shape[1]), np.float32)
            ys = np.zeros(shard_n, np.float32)
            ws = np.zeros(shard_n, np.float32)
            if m:
                Xs[:m], ys[:m], ws[:m] = Xh[lo:hi], yh[lo:hi], wh[lo:hi]
            host_shards.append({"X": Xs, "y": ys, "w": ws,
                                "wsum": float(ws.sum()), "rows": m})
        B = min(B_req, shard_n)
        nb = shard_n // B
        n_epochs = max(max(int(np.ceil(float(p["epochs"]))), 1) - done_ep, 0)

        key_h = np.asarray(jax.device_get(key))
        avg_h = jax.device_get(params0)         # host pytree of np arrays
        wstate = {wid: {"opt": None, "key": None, "data": {},
                        "samples": float(samples0)}
                  for wid in range(k)}

        group = ElasticGroup(k, scheduler=scheduler, job=job,
                             group_id=job.key,
                             shards={wid: list(range(wid * spw,
                                                     (wid + 1) * spw))
                                     for wid in range(k)})
        group.start()

        def make_step(wid: int, owned: list, kk: int, avg):
            def step():
                st = wstate[wid]
                for sid in [s for s in st["data"] if s not in owned]:
                    st["data"].pop(sid)
                for sid in owned:
                    if sid not in st["data"]:
                        hs = host_shards[sid]
                        st["data"][sid] = (
                            jax.device_put(hs["X"], row_sharding(2)),
                            jax.device_put(hs["y"], row_sharding(1)),
                            jax.device_put(hs["w"], row_sharding(1)))
                rs = replicated_sharding()
                pd = jax.device_put(avg, rs)
                if st["opt"] is None:
                    zeros = jax.tree.map(jnp.zeros_like, pd)
                    st["opt"] = {
                        "Eg": zeros,
                        "Edx": jax.tree.map(jnp.zeros_like, pd),
                        "v": jax.tree.map(jnp.zeros_like, pd)}
                    st["key"] = jax.device_put(
                        jax.random.fold_in(jnp.asarray(key_h), wid), rs)
                opt, kw = st["opt"], st["key"]
                samples_d = jnp.float32(st["samples"])
                shard_losses = []
                for sid in owned:
                    Xd, yd, wd = st["data"][sid]
                    _in = (pd, opt, kw, samples_d)
                    with timed_event("iteration", "dl_epoch"):
                        pd, opt, kw, samples_d, losses_k = retrying(
                            "dl_epochs", lambda: _train_epochs(
                                _in[0], _in[1], Xd, yd, wd, _in[2], _in[3],
                                act, loss, nclasses, cfg, kk, nb, B,
                                autoenc))
                    group.heartbeat(wid)
                    shard_losses.append(losses_k)
                # ONE batched fetch per worker-round (the megastep fetch
                # contract): params + the loss series + the sample counter
                ph, lh, sh = jax.device_get((pd, shard_losses, samples_d))
                st["opt"], st["key"] = opt, kw
                st["samples"] = float(sh)
                wsum = sum(host_shards[sid]["wsum"] for sid in owned)
                la = np.zeros(kk)
                for sid, lk in zip(owned, lh):
                    la += (np.atleast_1d(np.asarray(lk))
                           * (host_shards[sid]["wsum"] / max(wsum, 1e-8)))
                return {"params": ph, "losses": la, "wsum": wsum}
            return step

        quorum = min_workers_from_env()
        epoch_losses: list[float] = []
        ep_done = 0
        rnd = 0
        try:
            while ep_done < n_epochs:
                if job.should_stop:
                    job.keep_partial()
                    break
                live = group.live_workers()
                if len(live) < quorum:
                    # quorum lost: cancel with partial results — the last
                    # average IS the partial model (PR 8 contract)
                    job.cancel()
                    job.keep_partial()
                    break
                kk = min(local_k, n_epochs - ep_done)
                rnd += 1
                thunks = {wid: make_step(wid, owned, kk, avg_h)
                          for wid in live
                          if (owned := group.owned_shards(wid))}
                if not thunks:
                    break
                reports = group.run_round(rnd, thunks)
                if not reports:
                    # everyone missed the boundary — membership was swept;
                    # the quorum check above decides whether to go on
                    continue
                # every host-side reduction iterates wid-SORTED reports:
                # dict order is thread-arrival order, and float sums in
                # arrival order would break rerun bit-reproducibility
                ordered = [reports[w] for w in sorted(reports)]
                tot = sum(r["wsum"] for r in ordered)
                if tot > 0:
                    # wid-ordered float64 weighted average — deterministic,
                    # and renormalized over exactly the reporting workers
                    avg_h = jax.tree.map(
                        lambda *leaves: sum(
                            (r["wsum"] / tot) * np.asarray(lv, np.float64)
                            for r, lv in zip(ordered, leaves))
                        .astype(np.float32),
                        *[r["params"] for r in ordered])
                    for e in range(kk):
                        epoch_losses.append(float(sum(
                            (r["wsum"] / tot) * r["losses"][e]
                            for r in ordered)))
                ep_done += kk
                try:
                    job.update(ep_done / max(n_epochs, 1),
                               f"round {rnd}: epoch {ep_done}/{n_epochs} "
                               f"({len(reports)}/{k} workers)")
                except JobCancelled:
                    job.keep_partial()
                    break
                if job.cancelled:
                    break
        finally:
            group.shutdown()

        publish_dispatch_audit(self, "dl_elastic",
                               iterations=max(ep_done, 1),
                               host_syncs=max(rnd, 1),
                               device_dispatches=max(rnd, 1))
        score_history = [{"epoch": i + 1, "train_loss": v}
                         for i, v in enumerate(epoch_losses)]
        params_final = jax.device_put(avg_h, replicated_sharding())
        # every worker starts its schedule counter at samples0 (checkpoint
        # resume position); the TRAINED total is the sum of deltas
        samples_trained = float(samples0 + sum(
            st["samples"] - samples0 for st in wstate.values()))
        model = DeepLearningModel(
            key=make_model_key(self.algo, self.model_id),
            params=ModelParameters(p),
            data_info=di,
            response_column=None if autoenc else y,
            response_domain=domain,
            output=dict(params=params_final, act=act, sizes=sizes,
                        score_history=score_history,
                        samples_trained=samples_trained,
                        elastic={**group.summary(),
                                 "shards_per_worker": spw}),
        )
        return model

    def _validate(self, frame, x, y):
        if not self.params.get("autoencoder"):
            super()._validate(frame, x, y)

    def _scoring_history(self, model):
        """Per-epoch rows (reference: ``DeepLearningScoringInfo`` →
        ``createScoringHistoryTable``)."""
        hist = model.output.get("score_history") or []
        if not hist:
            return None
        return self._history_table(
            model,
            [("epochs", "double", "%.1f"),
             ("training_loss", "double", "%.5f")],
            [[float(h["epoch"]), float(h["train_loss"])] for h in hist])


class AutoEncoder(DeepLearning):
    """Convenience alias (h2o-py: H2OAutoEncoderEstimator)."""

    @classmethod
    def defaults(cls) -> dict:
        d = super().defaults()
        d["autoencoder"] = True
        d["hidden"] = [20]
        return d
