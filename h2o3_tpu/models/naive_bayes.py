"""NaiveBayes — count/Gaussian conditional probability classifier.

Reference: ``hex/naivebayes/NaiveBayes.java`` (538 LoC): one MRTask pass
(``NBTask``) accumulates per-class counts, per-(class, level) counts for
categoricals and per-(class, col) sum/sumsq for numerics, reduced across
nodes; prediction multiplies log conditionals with Laplace smoothing and
``min_sdev``/``eps_sdev`` floors for numeric Gaussians.

TPU-native: all sufficient statistics are one-hot matmuls on the row-sharded
design — ``onehot(y)ᵀ · onehot(x)`` for categorical count tables and
``onehot(y)ᵀ · [x, x²]`` for Gaussian moments — so the whole training pass is
a single jitted program whose per-shard partial tables XLA all-reduces over
ICI (the MRTask reduce).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.data_info import response_as_float
from h2o3_tpu.models.job import Job
from h2o3_tpu.models.model_base import Model, ModelBuilder, make_model_key


@partial(jax.jit, static_argnames=("nclass", "cards"))
def _nb_train(y, w, cat_stack, num_stack, nclass: int, cards: tuple[int, ...]):
    """Sufficient statistics in one pass."""
    yi = y.astype(jnp.int32)
    Yoh = (yi[:, None] == jnp.arange(nclass)[None, :]).astype(jnp.float32) * w[:, None]
    class_counts = Yoh.sum(axis=0)                       # [C]
    cat_tables = []
    for j, card in enumerate(cards):
        c = cat_stack[:, j]
        ok = (c >= 0).astype(jnp.float32)
        Xoh = (c[:, None] == jnp.arange(card)[None, :]).astype(jnp.float32)
        cat_tables.append((Yoh * ok[:, None]).T @ Xoh)   # [C, card]
    if num_stack.shape[1]:
        ok = (~jnp.isnan(num_stack)).astype(jnp.float32)
        xs = jnp.nan_to_num(num_stack)
        cnt = Yoh.T @ ok                                  # [C, P]
        s1 = Yoh.T @ (xs * ok)
        s2 = Yoh.T @ (xs * xs * ok)
    else:
        cnt = s1 = s2 = jnp.zeros((nclass, 0), jnp.float32)
    return class_counts, cat_tables, cnt, s1, s2


@partial(jax.jit, static_argnames=("nclass", "cards"))
def _nb_score(cat_stack, num_stack, log_prior, cat_logp, mu, sd,
              nclass: int, cards: tuple[int, ...]):
    plen = cat_stack.shape[0] if cards else num_stack.shape[0]
    ll = jnp.broadcast_to(log_prior[None, :], (plen, nclass))
    for j, card in enumerate(cards):
        c = cat_stack[:, j]
        tbl = cat_logp[j]                                  # [C, card]
        safe = jnp.clip(c, 0, card - 1)
        contrib = tbl.T[safe]                              # [plen, C]
        ll = ll + jnp.where((c >= 0)[:, None], contrib, 0.0)
    if num_stack.shape[1]:
        x = num_stack[:, :, None]                          # [plen, P, 1]
        m = mu.T[None, :, :]                               # [1, P, C]
        s = sd.T[None, :, :]
        logpdf = -0.5 * jnp.log(2 * jnp.pi * s * s) - 0.5 * ((x - m) / s) ** 2
        logpdf = jnp.where(jnp.isnan(x), 0.0, logpdf)
        ll = ll + logpdf.sum(axis=1)
    return jax.nn.softmax(ll, axis=1)


class NaiveBayesModel(Model):
    algo = "naivebayes"

    def _score_raw(self, frame: Frame) -> jax.Array:
        o = self.output
        cats, nums = _stack_features(frame, o["cat_cols"], o["num_cols"],
                                     o["cat_domains"])
        return _nb_score(cats, nums, o["log_prior"], tuple(o["cat_logp"]),
                         o["mu"], o["sd"], self.nclasses, o["cards"])


def _stack_features(frame: Frame, cat_cols, num_cols, train_domains):
    from h2o3_tpu.models.data_info import _remap_codes
    cats = []
    for col, dom in zip(cat_cols, train_domains):
        v = frame.vec(col)
        codes = v.data
        if v.domain != dom:
            codes = _remap_codes(codes, v.domain, dom)
        cats.append(codes)
    nums = [frame.vec(c).data for c in num_cols]
    cat_stack = jnp.stack(cats, axis=1) if cats else jnp.zeros((frame.plen, 0), jnp.int32)
    num_stack = jnp.stack(nums, axis=1) if nums else jnp.zeros((frame.plen, 0), jnp.float32)
    return cat_stack, num_stack


class NaiveBayes(ModelBuilder):
    """h2o-py surface: ``H2ONaiveBayesEstimator``."""

    algo = "naivebayes"
    supports_regression = False

    @classmethod
    def defaults(cls) -> dict:
        return dict(
            super().defaults(),
            laplace=0.0,
            min_sdev=0.001,
            eps_sdev=0.0,
            min_prob=0.001,
            eps_prob=0.0,
        )

    def _fit(self, job: Job, frame: Frame, x, y, weights) -> NaiveBayesModel:
        p = self.params
        yvec = frame.vec(y)
        if not yvec.is_categorical:
            raise ValueError("NaiveBayes requires a categorical response")
        nclass = yvec.cardinality()
        yy, valid = response_as_float(yvec)
        w = weights * valid
        yy = jnp.where(w > 0, yy, 0.0)

        cat_cols = [c for c in x if frame.vec(c).is_categorical]
        num_cols = [c for c in x if not frame.vec(c).is_categorical]
        cat_domains = [frame.vec(c).domain for c in cat_cols]
        cards = tuple(len(d) for d in cat_domains)
        cats, nums = _stack_features(frame, cat_cols, num_cols, cat_domains)

        class_counts, cat_tables, cnt, s1, s2 = _nb_train(yy, w, cats, nums,
                                                          nclass, cards)
        lap = float(p["laplace"])
        total = jnp.maximum(class_counts.sum(), 1e-12)
        log_prior = jnp.log(jnp.maximum(class_counts / total, 1e-30))
        cat_logp = []
        min_prob, eps_prob = float(p["min_prob"]), float(p["eps_prob"])
        for j, card in enumerate(cards):
            tbl = cat_tables[j] + lap
            probs = tbl / jnp.maximum(tbl.sum(axis=1, keepdims=True), 1e-30)
            # reference: min_prob substitutes ONLY when prob <= eps_prob
            # (NaiveBayesModel.java:94); legitimately small probs are kept
            probs = jnp.where(probs <= eps_prob, min_prob, jnp.maximum(probs, 1e-30))
            cat_logp.append(jnp.log(probs))
        if num_cols:
            n = jnp.maximum(cnt, 1e-12)
            mu = s1 / n
            var = jnp.maximum(s2 / n - mu * mu, 0.0) * n / jnp.maximum(n - 1.0, 1.0)
            min_sdev, eps_sdev = float(p["min_sdev"]), float(p["eps_sdev"])
            # reference: min_sdev substitutes ONLY when sd <= eps_sdev
            # (NaiveBayesModel.java:103)
            sd = jnp.sqrt(var)
            sd = jnp.where(sd <= eps_sdev, min_sdev, sd)
        else:
            mu = sd = jnp.zeros((nclass, 0), jnp.float32)

        from h2o3_tpu.models.model_base import ModelParameters
        return NaiveBayesModel(
            key=make_model_key(self.algo, self.model_id),
            params=ModelParameters(p),
            data_info=None,
            response_column=y,
            response_domain=yvec.domain,
            output=dict(log_prior=log_prior, cat_logp=cat_logp, mu=mu, sd=sd,
                        cat_cols=cat_cols, num_cols=num_cols,
                        cat_domains=cat_domains, cards=cards,
                        class_counts=np.asarray(jax.device_get(class_counts))),
        )
