"""Grep — regex search over a text column as a model builder.

Reference: ``hex/grep/Grep.java`` (174 LoC demo algo): MRTask over byte
chunks running a regex, output = matches + offsets. Text columns are
host-resident here (see ``Vec``), so the scan is one vectorized host pass —
the value is API parity for the reference's demo, not device compute.
"""

from __future__ import annotations

import re

import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.types import VecType
from h2o3_tpu.frame.vec import Vec
from h2o3_tpu.models.job import Job
from h2o3_tpu.models.model_base import Model, ModelBuilder, make_model_key


class GrepModel(Model):
    algo = "grep"

    def model_performance(self, frame: Frame):
        return None

    @property
    def matches(self) -> Frame:
        return self.output["matches"]


class Grep(ModelBuilder):
    algo = "grep"
    unsupervised = True

    @classmethod
    def defaults(cls) -> dict:
        return dict(super().defaults(), regex=".*")

    def train(self, x=None, y=None, training_frame: Frame | None = None, **kw):
        # bypass the base feature filter: STR columns are host-resident and
        # would be dropped as "not on device"
        if training_frame is None:
            raise ValueError("training_frame is required")
        col = (x[0] if isinstance(x, (list, tuple)) else x) or training_frame.names[0]
        self.job = Job(f"grep on {col}")
        self.job.run(lambda j: self._fit(j, training_frame, [col], None, None))
        if self.job.status == Job.FAILED:
            raise self.job.exception
        self.model = self.job.result
        from h2o3_tpu.utils.registry import DKV
        DKV.put(self.model.key, self.model)
        return self.model

    def _fit(self, job: Job, frame: Frame, x, y, weights) -> GrepModel:
        rx = re.compile(str(self.params["regex"]))
        col = x[0]
        v = frame.vec(col)
        if v.is_categorical:
            vals = [None if c < 0 else v.domain[c] for c in v.to_numpy()]
        elif v.type is VecType.STR:
            vals = list(v.host_values)
        else:
            raise ValueError("grep requires a string or categorical column")
        rows, matches, offsets = [], [], []
        for i, s in enumerate(vals):
            if s is None:
                continue
            for m in rx.finditer(s):
                rows.append(float(i))
                matches.append(m.group(0))
                offsets.append(float(m.start()))
        out = Frame(["row", "match", "offset"],
                    [Vec.from_numpy(np.asarray(rows, np.float32)),
                     Vec(None, VecType.STR, len(matches),
                         host_values=np.array(matches, dtype=object)),
                     Vec.from_numpy(np.asarray(offsets, np.float32))])
        job.update(1.0, f"{len(matches)} matches")
        return GrepModel(
            key=make_model_key(self.algo, self.model_id),
            params=self.params, data_info=None, response_column=None,
            response_domain=None, output=dict(matches=out))
