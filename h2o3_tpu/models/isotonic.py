"""Isotonic regression — pool-adjacent-violators.

Reference: ``hex/isotonic/IsotonicRegression.java`` (489 LoC): aggregates
(x, y, w) by unique x with a distributed task, runs PAVA on the leader, stores
the breakpoint thresholds; scoring interpolates linearly between thresholds
with ``out_of_bounds`` NA/clip handling.

TPU-native: aggregation of (sum_wy, sum_w) per unique x is a device
``segment_sum`` over the sorted column (the MRTask reduce); the PAV merge
itself is inherently sequential and runs on host over the (already tiny)
unique-x table; scoring is a vectorized ``searchsorted`` + lerp on device.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.types import VecType
from h2o3_tpu.frame.vec import Vec
from h2o3_tpu.models.data_info import response_as_float
from h2o3_tpu.models.job import Job
from h2o3_tpu.models.model_base import Model, ModelBuilder, make_model_key


def _pav(ys: np.ndarray, ws: np.ndarray) -> np.ndarray:
    """Weighted PAVA over block means (classic stack algorithm, O(n))."""
    n = len(ys)
    mean = np.empty(n)
    weight = np.empty(n)
    size = np.empty(n, np.int64)
    top = 0
    for i in range(n):
        mean[top], weight[top], size[top] = ys[i], ws[i], 1
        while top > 0 and mean[top - 1] >= mean[top]:
            wsum = weight[top - 1] + weight[top]
            mean[top - 1] = (mean[top - 1] * weight[top - 1]
                             + mean[top] * weight[top]) / max(wsum, 1e-300)
            weight[top - 1] = wsum
            size[top - 1] += size[top]
            top -= 1
        top += 1
    out = np.empty(n)
    pos = 0
    for b in range(top):
        out[pos:pos + size[b]] = mean[b]
        pos += size[b]
    return out


@jax.jit
def _interp(x, tx, ty):
    """Piecewise-linear interpolation through thresholds, clipped at the ends."""
    idx = jnp.clip(jnp.searchsorted(tx, x, side="right") - 1, 0, tx.shape[0] - 2)
    x0, x1 = tx[idx], tx[idx + 1]
    y0, y1 = ty[idx], ty[idx + 1]
    t = jnp.where(x1 > x0, (x - x0) / jnp.maximum(x1 - x0, 1e-30), 0.0)
    return y0 + jnp.clip(t, 0.0, 1.0) * (y1 - y0)


class IsotonicRegressionModel(Model):
    algo = "isotonicregression"

    def _score_raw(self, frame: Frame) -> jax.Array:
        x = frame.vec(self.output["x_col"]).as_float()
        tx = self.output["thresholds_x"]
        ty = self.output["thresholds_y"]
        pred = _interp(jnp.clip(x, self.output["min_x"], self.output["max_x"]), tx, ty)
        if str(self.params.get("out_of_bounds", "NA")).upper() == "NA":
            oob = (x < self.output["min_x"]) | (x > self.output["max_x"])
            pred = jnp.where(oob, jnp.nan, pred)
        return jnp.where(jnp.isnan(x), jnp.nan, pred)


class IsotonicRegression(ModelBuilder):
    """h2o-py surface: ``H2OIsotonicRegressionEstimator`` (single feature)."""

    algo = "isotonicregression"
    supports_classification = False

    @classmethod
    def defaults(cls) -> dict:
        return dict(super().defaults(), out_of_bounds="NA")

    def _fit(self, job: Job, frame: Frame, x, y, weights) -> IsotonicRegressionModel:
        if len(x) != 1:
            raise ValueError("IsotonicRegression requires exactly one feature column")
        xv = frame.vec(x[0]).as_float()
        yy, valid = response_as_float(frame.vec(y))
        w = weights * valid * ~jnp.isnan(xv)

        # device aggregation by unique x (segment-sum the (wy, w) pairs)
        xs = np.asarray(jax.device_get(xv))
        wh = np.asarray(jax.device_get(w))
        yh = np.asarray(jax.device_get(jnp.where(w > 0, yy, 0.0)))
        keep = wh > 0
        xs, yh, wh = xs[keep], yh[keep], wh[keep]
        if xs.size == 0:
            raise ValueError("no usable rows")
        ux, inv = np.unique(xs, return_inverse=True)
        sw = np.bincount(inv, weights=wh, minlength=len(ux))
        swy = np.bincount(inv, weights=wh * yh, minlength=len(ux))
        ymean = swy / np.maximum(sw, 1e-300)

        fitted = _pav(ymean, sw)
        # thresholds: keep only breakpoints (first/last of each constant block)
        change = np.ones(len(ux), bool)
        if len(ux) > 2:
            interior_same = (fitted[1:-1] == fitted[:-2]) & (fitted[1:-1] == fitted[2:])
            change[1:-1] = ~interior_same
        tx, ty = ux[change], fitted[change]
        if len(tx) == 1:
            tx = np.array([tx[0], tx[0] + 1.0])
            ty = np.array([ty[0], ty[0]])

        job.update(1.0, f"{len(tx)} thresholds")
        return IsotonicRegressionModel(
            key=make_model_key(self.algo, self.model_id),
            params=self.params, data_info=None, response_column=y,
            response_domain=None,
            output=dict(thresholds_x=jnp.asarray(tx, jnp.float32),
                        thresholds_y=jnp.asarray(ty, jnp.float32),
                        min_x=float(ux[0]), max_x=float(ux[-1]),
                        x_col=x[0], nobs=int(keep.sum())),
        )
