"""XGBoost-parity booster.

Reference: ``h2o-extensions/xgboost`` wraps libxgboost (CUDA ``gpu_hist``,
``XGBoostModel.java:396-398``) over a rabit allreduce ring
(``RabitTrackerH2O.java:14``). The TPU replacement (SURVEY.md §2.9) is the same
histogram tree algorithm implemented natively: global-quantile binning,
(g, h) gradient-pair histograms all-reduced over ICI by XLA, exact XGBoost gain
``0.5*(GL²/(HL+λ)+GR²/(HR+λ)−G²/(H+λ))−γ`` with learned default direction for
missing values — which is precisely what :mod:`h2o3_tpu.models.tree` computes.
So "XGBoost" here is the shared tree engine with XGBoost's parameterization
rather than a second engine; rabit's ring allreduce has no user-visible
equivalent to port — XLA emits the collective.

Beyond the shared engine, this builder carries XGBoost's distinguishing
features (reference ``XGBoostModel.XGBoostParameters``):

- ``booster="dart"`` — DART (Rashmi & Gilad-Bachrach 2015): per round a
  random subset of prior trees is DROPPED, the new tree fits the gradients
  of the reduced ensemble, and the dropped + new trees are renormalized
  (``normalize_type`` tree/forest, ``rate_drop``, ``skip_drop``,
  ``one_drop``). Tree weights are baked into leaf values at the end so
  every scoring artifact (raw/MOJO/POJO/SHAP) works unchanged.
- ``col_sample_by_level`` / ``col_sample_by_node`` — the by-node rate
  folds into the per-level rate (per-node sampling would break the
  single-batched-argmax split search; the compromise mirrors LightGBM's
  feature_fraction granularity and is noted in PARITY.md).
- ``offset_column``, ``monotone_constraints``, ``interaction_constraints``,
  categorical ``enum`` group splits — inherited from the shared engine.
- XGBoost-native aliases: eta, max_bin, subsample, colsample_bytree/
  bylevel/bynode, min_child_weight, min_split_loss, reg_lambda/reg_alpha.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from h2o3_tpu.models.gbm import GBM, GBMModel, _grad_hess
from h2o3_tpu.models.job import Job, JobCancelled
from h2o3_tpu.models.model_base import make_model_key
from h2o3_tpu.models.tree import TreeParams, grow_trees_batched


class XGBoostModel(GBMModel):
    algo = "xgboost"


#: h2o-py H2OXGBoostEstimator parameter names → shared-engine names
_ALIASES = {
    "eta": "learn_rate",
    "max_bin": "nbins",
    "subsample": "sample_rate",
    "colsample_bytree": "col_sample_rate_per_tree",
    "colsample_bylevel": "col_sample_rate",
    "colsample_bynode": "col_sample_by_node",
    "min_child_weight": "min_rows",
    "min_split_loss": "gamma",
    "max_delta_step": None,          # accepted, inert (rarely used)
    "grow_policy": None,             # depthwise only (level-synchronous)
    "tree_method": None,             # always hist
    "backend": None,
    "gpu_id": None,
    "dmatrix_type": None,
}


class XGBoost(GBM):
    """h2o-py surface: ``H2OXGBoostEstimator`` (tree_method=hist semantics)."""

    algo = "xgboost"

    @classmethod
    def defaults(cls) -> dict:
        d = super().defaults()
        d.update(
            ntrees=50,
            max_depth=6,
            learn_rate=0.3,        # eta
            reg_lambda=1.0,        # lambda
            reg_alpha=0.0,         # alpha (leaf L1; applied as soft threshold)
            gamma=0.0,             # min_split_loss
            min_rows=1.0,          # min_child_weight
            nbins=256,             # max_bin
            sample_rate=1.0,       # subsample
            col_sample_rate=1.0,   # colsample_bylevel
            col_sample_rate_per_tree=1.0,  # colsample_bytree
            col_sample_by_node=1.0,        # colsample_bynode (folds into level)
            booster="gbtree",      # gbtree | dart | (gblinear → use GLM)
            rate_drop=0.0,         # DART: P(tree is dropped) per round
            skip_drop=0.0,         # DART: P(round skips dropping entirely)
            one_drop=False,        # DART: always drop >= 1 tree
            normalize_type="tree",  # DART: tree | forest
        )
        return d

    def __init__(self, **params):
        for alias, target in _ALIASES.items():
            if alias in params:
                v = params.pop(alias)
                if target is not None:
                    params.setdefault(target, v)
        super().__init__(**params)

    def _effective_col_rate(self) -> float:
        # by-node sampling folds into the per-level rate (see module docs);
        # derived here so stored params keep the user's values
        return (float(self.params["col_sample_rate"])
                * float(self.params.get("col_sample_by_node") or 1.0))

    def supports_auto_recovery(self) -> bool:
        # DART neither checkpoints nor resumes (renormalized prior-tree
        # weights); gbtree shares GBM's chunk snapshots
        return str(self.params.get("booster") or "gbtree").lower() != "dart"

    def validate_request(self) -> None:
        """REST fail-fast: DART cannot resume a checkpoint (per-round
        renormalization rescales prior tree weights, so the ensemble the
        checkpoint froze no longer exists) — the server turns this into a
        structured 400 instead of a background FAILED job."""
        super().validate_request()
        if str(self.params.get("booster") or "").lower() == "dart" \
                and self.params.get("checkpoint"):
            raise ValueError("checkpoint resume is not supported with "
                             "booster='dart' (prior-tree weights would have "
                             "been renormalized away)")

    def _fit(self, job, frame, x, y, weights):
        booster = str(self.params.get("booster") or "gbtree").lower()
        if booster == "gblinear":
            raise ValueError("booster='gblinear' is a linear model — use GLM "
                             "(the reference routes it to a linear booster)")
        if booster not in ("gbtree", "dart"):
            raise ValueError(f"unknown booster {booster!r}")
        if booster == "dart":
            model = self._fit_dart(job, frame, x, y, weights)
        else:
            model = super()._fit(job, frame, x, y, weights)
        model.__class__ = XGBoostModel
        return model

    # -- DART ---------------------------------------------------------------

    def _fit_dart(self, job: Job, frame, x, y, weights):
        """DART boosting: per-round tree dropout + renormalization.

        Rounds run as a host loop (each round re-weights PRIOR trees, which
        a fused scan cannot express); per-round compute (gradient refresh,
        dropped-ensemble margin, one tree growth) stays on device.
        """
        p = self.params
        if p.get("checkpoint"):
            raise ValueError("checkpoint resume is not supported with "
                             "booster='dart' (prior-tree weights would have "
                             "been renormalized away)")
        X, edges, binned, yy, valid, yvec, domains = self._prepare(frame, x, y, weights)
        dist = str(p["distribution"])
        if dist.lower() == "auto":
            dist = "AUTO"
        if yvec.is_categorical:
            if yvec.cardinality() != 2:
                raise ValueError("booster='dart' supports binomial and "
                                 "regression responses here")
            dist = "bernoulli"
        elif dist == "bernoulli":
            raise ValueError("bernoulli distribution requires a categorical "
                             "(2-level) response")
        elif dist == "AUTO":
            dist = "gaussian"
        w = weights * valid
        yc = jnp.where(w > 0, yy, 0.0)

        ybar = float(jax.device_get((w * yc).sum() /
                                    jnp.maximum(w.sum(), 1e-30)))
        if dist == "bernoulli":
            ybar = min(max(ybar, 1e-6), 1 - 1e-6)
            f0 = float(np.log(ybar / (1 - ybar)))
        else:
            f0 = ybar

        lr = float(p["learn_rate"])
        ntrees = int(p["ntrees"])
        nbins = int(p["nbins"])
        seed = int(p["seed"]) if int(p["seed"]) >= 0 else 42
        rng = np.random.default_rng(seed)
        key = jax.random.PRNGKey(seed)
        tp = TreeParams(
            max_depth=int(p["max_depth"]), nbins=nbins,
            min_rows=float(p["min_rows"]), reg_lambda=float(p["reg_lambda"]),
            reg_alpha=float(p["reg_alpha"]), gamma=float(p["gamma"]),
            min_split_improvement=float(p["min_split_improvement"]))
        mono, reach = self._constraint_arrays(x, frame)
        fmask = jnp.ones(binned.shape[1], bool)

        rate_drop = float(p.get("rate_drop") or 0.0)
        skip_drop = float(p.get("skip_drop") or 0.0)
        one_drop = bool(p.get("one_drop"))
        norm_forest = str(p.get("normalize_type") or "tree") == "forest"
        sample_rate = float(p["sample_rate"])
        col_tree_rate = float(p["col_sample_rate_per_tree"])
        sr = int(p.get("stopping_rounds") or 0)
        metric = str(p.get("stopping_metric") or "AUTO")
        metric = {m.lower(): m for m in self.STOPPING_METRICS}.get(
            metric.lower(), metric)
        tol = float(p.get("stopping_tolerance") or 1e-3)
        best, since = np.inf, 0

        trees, wts, preds = [], [], []   # preds: per-tree [rows] leaf sums
        Fcur = jnp.full(binned.shape[0], f0, jnp.float32)
        oc = p.get("offset_column")
        if oc:
            Fcur = Fcur + jnp.nan_to_num(frame.vec(oc).as_float(), nan=0.0)

        for m in range(ntrees):
            drop = np.zeros(len(trees), bool)
            if trees and rng.random() >= skip_drop:
                drop = rng.random(len(trees)) < rate_drop
                if one_drop and not drop.any():
                    drop[rng.integers(0, len(trees))] = True
            k = int(drop.sum())
            F_drop = 0.0
            if k:
                F_drop = sum(wts[i] * preds[i]
                             for i in range(len(trees)) if drop[i])
            F_eff = Fcur - F_drop
            key, ks, kf, kt = jax.random.split(key, 4)
            wt = w
            if sample_rate < 1.0:       # subsample (per-round row thinning)
                wt = w * (jax.random.uniform(ks, w.shape) < sample_rate)
            tmask = fmask
            if col_tree_rate < 1.0:     # colsample_bytree
                sub = jax.random.uniform(kf, fmask.shape) < col_tree_rate
                sub = sub.at[jax.random.randint(
                    jax.random.fold_in(kf, 1), (), 0, fmask.shape[0])].set(True)
                tmask = jnp.where((fmask & sub).any(), fmask & sub, fmask)
            g, h = _grad_hess(dist, F_eff, yc, wt,
                              float(p["quantile_alpha"]),
                              float(p["huber_alpha"]),
                              float(p["tweedie_power"]))
            new, pred = grow_trees_batched(
                binned, edges, g[None], h[None], wt[None], tp, tmask,
                col_rate=self._effective_col_rate(), key=kt,
                mono=mono, reach=reach, cat_feats=self._cat_feats)
            pred = pred[0]
            if k:
                # renormalize (XGBoost DART): tree: new w = lr/(k+lr),
                # dropped *= k/(k+lr); forest: lr/(1+lr) and 1/(1+lr)
                if norm_forest:
                    w_new, scale = lr / (1.0 + lr), 1.0 / (1.0 + lr)
                else:
                    w_new, scale = lr / (k + lr), k / (k + lr)
                for i in range(len(trees)):
                    if drop[i]:
                        wts[i] *= scale
                Fcur = F_eff + scale * F_drop + w_new * pred
            else:
                w_new = lr
                Fcur = Fcur + w_new * pred
            trees.append(new[0])
            wts.append(w_new)
            preds.append(pred)
            try:
                job.update(0.1 + 0.8 * (m + 1) / ntrees,
                           f"DART tree {m + 1}/{ntrees} (dropped {k})")
            except JobCancelled:
                # deadline/cancel between rounds: DART keeps its grown
                # trees like the other tree builders (partial model, job
                # terminates CANCELLED)
                job.keep_partial()
                break
            if sr > 0:                  # ScoreKeeper early stopping
                dev = self._stop_score(metric, dist, Fcur, yc, w, 0)
                if dev < best - tol * abs(best) or not np.isfinite(best):
                    best, since = dev, 0
                else:
                    since += 1
                    if since >= sr:
                        break

        # bake weights into leaves: every downstream scorer (raw/binned/
        # MOJO/POJO/SHAP) then treats the ensemble uniformly with lr=1
        baked = [dataclasses.replace(t, leaf=t.leaf * wt)
                 for t, wt in zip(trees, wts)]

        if dist == "bernoulli":
            pe = jax.nn.sigmoid(Fcur)
            self._last_train_raw = jnp.stack([1 - pe, pe], axis=1)
        else:
            self._last_train_raw = Fcur

        model = XGBoostModel(
            key=make_model_key(self.algo, self.model_id),
            params=self.params, data_info=None, response_column=y,
            response_domain=yvec.domain if yvec.is_categorical else None,
            output=dict(trees=baked, edges=edges, f0=f0, learn_rate=1.0,
                        distribution=dist, x_cols=list(x),
                        feat_domains=domains, ntrees=len(baked),
                        dart_weights=[float(v) for v in wts],
                        **self._cat_output()),
        )
        self._maybe_calibrate(model)
        return model
