"""XGBoost-parity booster.

Reference: ``h2o-extensions/xgboost`` wraps libxgboost (CUDA ``gpu_hist``,
``XGBoostModel.java:396-398``) over a rabit allreduce ring
(``RabitTrackerH2O.java:14``). The TPU replacement (SURVEY.md §2.9) is the same
histogram tree algorithm implemented natively: global-quantile binning,
(g, h) gradient-pair histograms all-reduced over ICI by XLA, exact XGBoost gain
``0.5*(GL²/(HL+λ)+GR²/(HR+λ)−G²/(H+λ))−γ`` with learned default direction for
missing values — which is precisely what :mod:`h2o3_tpu.models.tree` computes.
So "XGBoost" here is the shared tree engine with XGBoost's parameterization
(eta/lambda/gamma/alpha naming, 256 bins, depth 6) rather than a second engine;
rabit's ring allreduce has no user-visible equivalent to port — XLA emits the
collective.
"""

from __future__ import annotations

from h2o3_tpu.models.gbm import GBM, GBMModel


class XGBoostModel(GBMModel):
    algo = "xgboost"


class XGBoost(GBM):
    """h2o-py surface: ``H2OXGBoostEstimator`` (tree_method=hist semantics)."""

    algo = "xgboost"

    @classmethod
    def defaults(cls) -> dict:
        d = super().defaults()
        d.update(
            ntrees=50,
            max_depth=6,
            learn_rate=0.3,        # eta
            reg_lambda=1.0,        # lambda
            reg_alpha=0.0,         # alpha (leaf L1; applied as soft threshold)
            gamma=0.0,             # min_split_loss
            min_rows=1.0,          # min_child_weight
            nbins=256,             # max_bin
            sample_rate=1.0,       # subsample
            col_sample_rate=1.0,   # colsample_bylevel
            col_sample_rate_per_tree=1.0,  # colsample_bytree
        )
        return d

    def _fit(self, job, frame, x, y, weights):
        model = super()._fit(job, frame, x, y, weights)
        model.__class__ = XGBoostModel
        return model
