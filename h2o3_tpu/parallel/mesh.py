"""Global device mesh — the TPU-native equivalent of H2O's "cloud".

In the reference, every node gossips heartbeats until all agree on the member
list (``water/Paxos.java:27-124``) and the cloud is then locked — membership is
static for the lifetime of a job. A TPU slice has exactly that property out of
the box: the set of chips is fixed, so "cloud formation" reduces to constructing
a ``jax.sharding.Mesh`` over ``jax.devices()``.

The default mesh is 1-D over all addressable devices with axis name ``"rows"``:
frames are row-partitioned across it the way H2O chunks rows across nodes
(ESPC layout, ``water/fvec/Vec.java:152``). Multi-dim meshes (e.g. rows × model
for sharded Gram linear algebra) can be installed with :func:`set_mesh`.
"""

from __future__ import annotations

import contextlib
import threading

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Name of the data-parallel (row) mesh axis. Every Frame column is sharded
# along this axis; reductions over it ride ICI (lax.psum / XLA SPMD).
ROWS = "rows"

_lock = threading.Lock()
_mesh: Mesh | None = None


def _default_mesh() -> Mesh:
    devices = np.array(jax.devices())
    return Mesh(devices, axis_names=(ROWS,))


def get_mesh() -> Mesh:
    """Return the process-global mesh, creating the default 1-D mesh lazily."""
    global _mesh
    with _lock:
        if _mesh is None:
            _mesh = _default_mesh()
        return _mesh


def set_mesh(mesh: Mesh | None) -> None:
    """Install a mesh globally (``None`` resets to the lazy default).

    The mesh must have a ``"rows"`` axis; extra axes are allowed and are used by
    model-parallel code paths (e.g. sharded Cholesky for wide GLM Gram matrices).
    """
    global _mesh
    if mesh is not None and ROWS not in mesh.axis_names:
        raise ValueError(f"mesh must have a {ROWS!r} axis, got {mesh.axis_names}")
    with _lock:
        _mesh = mesh


@contextlib.contextmanager
def mesh_context(mesh: Mesh):
    """Temporarily install ``mesh`` as the global mesh."""
    prev = _mesh
    set_mesh(mesh)
    try:
        yield mesh
    finally:
        set_mesh(prev)


def num_devices() -> int:
    """Number of devices along the row axis (H2O: ``H2O.CLOUD.size()``)."""
    mesh = get_mesh()
    return mesh.shape[ROWS]


def row_sharding(ndim: int = 1) -> NamedSharding:
    """Sharding that partitions axis 0 (rows) and replicates the rest."""
    spec = P(ROWS, *([None] * (ndim - 1)))
    return NamedSharding(get_mesh(), spec)


def replicated_sharding() -> NamedSharding:
    """Fully-replicated sharding on the global mesh."""
    return NamedSharding(get_mesh(), P())
